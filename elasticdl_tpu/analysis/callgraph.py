"""edlint v2 engine: whole-program call graph with concurrency summaries.

Builds, from ALL parsed units at once, a repo-wide call graph over
``elasticdl_tpu/``:

- **functions**: module-level defs, methods (attributed to their class),
  nested defs — keyed ``"<module>:<qualname>"``;
- **call edges**: ``self.method()``, ``self._attr.method()`` (attribute
  types inferred from ``self._attr = ClassName(...)`` in ``__init__``),
  module-qualified calls through import aliases, local-variable method
  calls when the variable's type is inferable (``x = self._store``);
- **thread entry points**: ``threading.Thread(target=...)``, executor
  ``submit``/``map``, gRPC handler methods (public methods of
  ``*Servicer`` classes), ``signal.signal`` handlers (reentrant);
- **per-function summaries**: locks acquired (``with self._x_lock:`` /
  ``.acquire()``), locks held at each call site, and blocking effects
  (gRPC stub calls, socket/file I/O, ``np.savez``/``np.load``,
  ``subprocess``, ``sleep``, queue ops without a timeout,
  ``.result()``/``.join()``/``.wait()``).

Lock identity is the class-qualified attribute name
(``PserverServicer._push_lock``) — instances of the same class share an
identity, so self-edges (A -> A) are skipped in the order graph rather
than reported as reentrancy.

The lattice is deliberately modest and the degradations explicit
(docs/STATIC_ANALYSIS.md "edlint v2 engine"): dynamic dispatch through
stored callbacks, locals whose type can't be traced to a constructor or
``self`` attribute, and ``getattr`` all degrade to **unknown callee**,
which is counted and surfaced once per run (``unknown_summary()``, the
CLI note, ``--graph`` JSON) — never silently ignored.

Thread-context contracts are declared either with
``@thread_context("name")`` (``elasticdl_tpu.common.annotations``) or a
``# edlint: thread=<name>`` comment on/above the ``def`` line.
"""

import ast
import re
from dataclasses import dataclass, field

from elasticdl_tpu.analysis.core import attr_chain

_THREAD_COMMENT_RE = re.compile(r"edlint:\s*thread=([\w\-]+)")

_LOCK_FACTORIES = {"Lock", "RLock", "Condition"}

_IO_CALLS = {
    "open", "io.open", "gzip.open",
    "np.savez", "np.savez_compressed", "np.save", "np.load",
    "numpy.savez", "numpy.savez_compressed", "numpy.save", "numpy.load",
    "os.replace", "os.rename", "os.makedirs", "os.fsync", "os.remove",
    "shutil.rmtree", "shutil.copy", "shutil.copytree", "shutil.move",
    "urllib.request.urlopen",
}

_SOCKET_TAILS = {"recv", "recv_into", "send", "sendall", "connect", "accept"}

# universal builtin-object method names: a failed resolution whose tail
# is one of these is a str/dict/list/set/file receiver, not package code
_COMMON_OBJ_METHODS = frozenset({
    "add", "append", "appendleft", "clear", "close", "copy", "count",
    "decode", "discard", "encode", "endswith", "extend", "format", "get",
    "index", "insert", "items", "join", "keys", "lower", "pop", "popleft",
    "read", "remove", "replace", "setdefault", "sort", "split",
    "startswith", "strip", "update", "upper", "values", "write",
})


def _looks_lock(name):
    low = name.lower()
    return "lock" in low or "cond" in low or low in ("cv", "mutex")


def _const_str(node):
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    return None


def _kwarg(call, name):
    for kw in call.keywords:
        if kw.arg == name:
            return kw.value
    return None


@dataclass
class LockAcquire:
    lock: str
    line: int
    held: tuple        # locks already held at this acquisition


@dataclass
class BlockEffect:
    category: str      # io | grpc | sleep | wait | queue | subprocess | socket
    code: str          # display code ("np.savez", "self._stub.pull", ...)
    line: int
    held: tuple


@dataclass
class CallSite:
    display: str       # source-level callee text ("self._store.export")
    line: int
    held: tuple
    callees: tuple     # resolved FunctionInfo keys (possibly several: MRO)
    unresolved: bool   # True when this could be package code we can't see


@dataclass
class Entry:
    key: str           # function key
    context: str       # "grpc", "signal", "thread:<n>", "executor:<pool>"
    reentrant: bool
    reason: str        # human-readable provenance for --graph / messages
    path: str
    line: int


class FunctionInfo:
    def __init__(self, unit, node, qualname, class_info):
        self.unit = unit
        self.node = node
        self.module = unit.module
        self.qualname = qualname            # in-file qualname (Finding.symbol)
        self.key = "%s:%s" % (unit.module, qualname)
        self.class_info = class_info        # enclosing class (or None)
        self.is_method = False              # directly in the class body
        self.name = node.name
        self.thread_context = None          # declared context name or None
        self.reentrant = False
        self.locks = []                     # [LockAcquire]
        self.blocking = []                  # [BlockEffect]
        self.calls = []                     # [CallSite]
        self.local_defs = {}                # nested def name -> key

    @property
    def short(self):
        return "%s.%s" % (self.module.rsplit(".", 1)[-1], self.qualname)


class ClassInfo:
    def __init__(self, unit, node, qualname):
        self.unit = unit
        self.node = node
        self.module = unit.module
        self.name = node.name
        self.qualname = qualname
        self.key = "%s:%s" % (unit.module, qualname)
        self.base_exprs = [attr_chain(b) for b in node.bases]
        self.bases = []                     # resolved ClassInfo, pass 2
        self.methods = {}                   # name -> FunctionInfo key
        self.lock_attrs = set()             # attrs assigned a lock factory
        self.attr_types = {}                # attr -> ClassInfo

    def mro(self):
        """self + package-resolved bases, depth-first, cycle-safe."""
        seen, order, work = set(), [], [self]
        while work:
            cls = work.pop(0)
            if cls.key in seen:
                continue
            seen.add(cls.key)
            order.append(cls)
            work.extend(cls.bases)
        return order


class _ModuleTable:
    """Per-module symbol table: import aliases, module-level locks,
    module-level str constants, thread-context comment lines."""

    def __init__(self, unit):
        self.unit = unit
        self.module = unit.module
        self.modtail = unit.module.rsplit(".", 1)[-1]
        self.aliases = {}       # local name -> dotted target
        self.consts = {}        # module-level NAME -> str constant
        self.locks = {}         # module-level name -> lock id
        self.thread_lines = {}  # line -> declared context name
        self._scan()

    def _scan(self):
        for lineno, text in enumerate(self.unit.source.splitlines(), 1):
            m = _THREAD_COMMENT_RE.search(text)
            if m and "#" in text.split(m.group(0))[0][-200:]:
                self.thread_lines[lineno] = m.group(1)
        for node in ast.walk(self.unit.tree):
            if isinstance(node, ast.Import):
                for alias in node.names:
                    name = alias.asname or alias.name.split(".")[0]
                    self.aliases[name] = alias.asname and alias.name or (
                        alias.name.split(".")[0]
                    )
                    if alias.asname:
                        self.aliases[alias.asname] = alias.name
            elif isinstance(node, ast.ImportFrom):
                base = node.module or ""
                if node.level:
                    pkg = self.module.split(".")
                    pkg = pkg[: len(pkg) - node.level]
                    base = ".".join(pkg + ([node.module] if node.module else []))
                for alias in node.names:
                    if alias.name == "*":
                        continue
                    self.aliases[alias.asname or alias.name] = (
                        "%s.%s" % (base, alias.name) if base else alias.name
                    )
        for stmt in self.unit.tree.body:
            if not isinstance(stmt, ast.Assign) or len(stmt.targets) != 1:
                continue
            target = stmt.targets[0]
            if not isinstance(target, ast.Name):
                continue
            value = _const_str(stmt.value)
            if value is not None:
                self.consts[target.id] = value
            elif isinstance(stmt.value, ast.Call):
                chain = attr_chain(stmt.value.func)
                if chain and chain.split(".")[-1] in _LOCK_FACTORIES:
                    self.locks[target.id] = "%s.%s" % (self.modtail, target.id)

    def declared_context(self, node):
        """Context from a # edlint: thread=<name> comment on/above a def."""
        first = min([node.lineno] + [d.lineno for d in node.decorator_list])
        for line in range(first - 1, node.lineno + 1):
            if line in self.thread_lines:
                return self.thread_lines[line]
        return None


class CallGraph:
    """Whole-program index. Build with :meth:`build`; everything below
    is derived data for the conc-* rules and ``--graph``."""

    def __init__(self):
        self.functions = {}       # key -> FunctionInfo
        self.classes = {}         # key -> ClassInfo
        self.tables = {}          # module -> _ModuleTable
        self.module_funcs = {}    # module -> {name: key}
        self.module_classes = {}  # module -> {name: ClassInfo}
        self.modules = set()
        self.entries = []         # [Entry]
        self.unknown_calls = []   # [(path, line, display)]
        self.defined_names = set()  # every def name in the package
        self._contexts = None
        self._acq_memo = {}
        self._block_memo = {}

    # ------------------------------------------------------------- build

    @classmethod
    def build(cls, units):
        graph = cls()
        for unit in units:
            graph.modules.add(unit.module)
            graph.tables[unit.module] = _ModuleTable(unit)
            graph.module_funcs.setdefault(unit.module, {})
            graph.module_classes.setdefault(unit.module, {})
        for unit in units:
            graph._collect(unit)
        graph._resolve_classes()
        graph.defined_names = {f.name for f in graph.functions.values()}
        for info in graph.functions.values():
            for child in info.node.body:
                if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    info.local_defs[child.name] = "%s.%s" % (
                        info.key, child.name
                    )
        for info in graph.functions.values():
            _FuncScanner(graph, info).scan()
        graph._collect_grpc_entries()
        graph.entries.sort(key=lambda e: (e.path, e.line, e.context, e.key))
        graph.unknown_calls.sort()
        return graph

    def _collect(self, unit):
        table = self.tables[unit.module]

        def rec(node, scope, class_info, parent_is_class):
            for child in ast.iter_child_nodes(node):
                if isinstance(child, ast.ClassDef):
                    qual = ".".join(scope + [child.name])
                    cinfo = ClassInfo(unit, child, qual)
                    self.classes[cinfo.key] = cinfo
                    if not scope:
                        self.module_classes[unit.module][child.name] = cinfo
                    rec(child, scope + [child.name], cinfo, True)
                elif isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    qual = ".".join(scope + [child.name])
                    # class_info is the ENCLOSING class even for closures
                    # nested in methods: their ``self`` is the method's
                    finfo = FunctionInfo(unit, child, qual, class_info)
                    finfo.is_method = parent_is_class
                    finfo.thread_context = table.declared_context(child)
                    self._decorator_context(finfo, table)
                    self.functions[finfo.key] = finfo
                    if not scope:
                        self.module_funcs[unit.module][child.name] = finfo.key
                    if parent_is_class and child.name not in class_info.methods:
                        class_info.methods[child.name] = finfo.key
                    rec(child, scope + [child.name], class_info, False)
                else:
                    rec(child, scope, class_info, parent_is_class)

        rec(unit.tree, [], None, False)

    def _decorator_context(self, finfo, table):
        for dec in finfo.node.decorator_list:
            if not isinstance(dec, ast.Call):
                continue
            chain = attr_chain(dec.func)
            if not chain or chain.split(".")[-1] != "thread_context":
                continue
            name = _const_str(dec.args[0]) if dec.args else None
            if name:
                finfo.thread_context = name
            reentrant = _kwarg(dec, "reentrant")
            if isinstance(reentrant, ast.Constant) and reentrant.value is True:
                finfo.reentrant = True

    def _resolve_classes(self):
        for cinfo in self.classes.values():
            for base in cinfo.base_exprs:
                if base is None:
                    continue
                resolved = self.resolve_symbol(cinfo.module, base)
                if resolved and resolved[0] == "class":
                    cinfo.bases.append(resolved[1])
        # lock attrs + attribute types, from every method body
        for cinfo in self.classes.values():
            for mkey in cinfo.methods.values():
                minfo = self.functions[mkey]
                for node in ast.walk(minfo.node):
                    if not isinstance(node, ast.Assign):
                        continue
                    for target in node.targets:
                        if not (
                            isinstance(target, ast.Attribute)
                            and isinstance(target.value, ast.Name)
                            and target.value.id == "self"
                        ):
                            continue
                        if isinstance(node.value, ast.Call):
                            chain = attr_chain(node.value.func)
                            if not chain:
                                continue
                            if chain.split(".")[-1] in _LOCK_FACTORIES:
                                cinfo.lock_attrs.add(target.attr)
                                continue
                            resolved = self.resolve_symbol(cinfo.module, chain)
                            if resolved and resolved[0] == "class":
                                cinfo.attr_types.setdefault(
                                    target.attr, resolved[1]
                                )

    # -------------------------------------------------------- resolution

    def resolve_symbol(self, module, dotted):
        """Resolve a dotted name seen in ``module`` to
        ("class", ClassInfo) | ("func", key) | ("module", name) | None."""
        parts = dotted.split(".")
        table = self.tables.get(module)
        if table is None:
            return None
        head = parts[0]
        if len(parts) == 1:
            classes = self.module_classes.get(module, {})
            if head in classes:
                return ("class", classes[head])
            funcs = self.module_funcs.get(module, {})
            if head in funcs:
                return ("func", funcs[head])
        if head in table.aliases:
            parts = table.aliases[head].split(".") + parts[1:]
        # longest module prefix
        for cut in range(len(parts), 0, -1):
            prefix = ".".join(parts[:cut])
            if prefix in self.modules:
                rest = parts[cut:]
                if not rest:
                    return ("module", prefix)
                classes = self.module_classes.get(prefix, {})
                funcs = self.module_funcs.get(prefix, {})
                if rest[0] in classes:
                    cinfo = classes[rest[0]]
                    if len(rest) == 1:
                        return ("class", cinfo)
                    if len(rest) == 2:
                        mkey = self._method(cinfo, rest[1])
                        if mkey:
                            return ("func", mkey)
                    return None
                if len(rest) == 1 and rest[0] in funcs:
                    return ("func", funcs[rest[0]])
                return None
        if len(parts) == 1:
            return None
        # Class.method within the same module
        classes = self.module_classes.get(module, {})
        if parts[0] in classes and len(parts) == 2:
            mkey = self._method(classes[parts[0]], parts[1])
            if mkey:
                return ("func", mkey)
        return None

    def _method(self, cinfo, name):
        for cls in cinfo.mro():
            if name in cls.methods:
                return cls.methods[name]
        return None

    def lock_owner(self, cinfo, attr):
        for cls in cinfo.mro():
            if attr in cls.lock_attrs:
                return cls.name
        return None

    def ctor_key(self, cinfo):
        return self._method(cinfo, "__init__")

    # ----------------------------------------------------------- entries

    def _collect_grpc_entries(self):
        """Public methods of ``*Servicer`` classes are gRPC handler
        entry points unless they carry an explicit thread contract."""
        for cinfo in self.classes.values():
            if not cinfo.name.endswith("Servicer") or cinfo.name.startswith("_"):
                continue
            for name, key in sorted(cinfo.methods.items()):
                if name.startswith("_"):
                    continue
                finfo = self.functions[key]
                if finfo.thread_context is not None:
                    continue
                self.entries.append(Entry(
                    key=key, context="grpc", reentrant=False,
                    reason="public method of %s" % cinfo.name,
                    path=finfo.unit.path, line=finfo.node.lineno,
                ))

    def add_entry(self, key, context, reentrant, reason, path, line):
        self.entries.append(Entry(key, context, reentrant, reason, path, line))

    # ------------------------------------------------- derived summaries

    def callers(self):
        """key -> [(caller FunctionInfo, CallSite)]"""
        out = {}
        for finfo in self.functions.values():
            for site in finfo.calls:
                for callee in site.callees:
                    out.setdefault(callee, []).append((finfo, site))
        return out

    def transitive_acquires(self, key, _stack=None):
        """lock id -> call path (tuple of function keys, callee-first)
        for every lock acquired by ``key`` or any resolved callee."""
        if key in self._acq_memo:
            return self._acq_memo[key]
        stack = _stack if _stack is not None else set()
        if key in stack:
            return {}
        stack.add(key)
        finfo = self.functions.get(key)
        out = {}
        if finfo is not None:
            for acq in finfo.locks:
                out.setdefault(acq.lock, (key,))
            for site in finfo.calls:
                for callee in site.callees:
                    for lock, path in self.transitive_acquires(
                        callee, stack
                    ).items():
                        out.setdefault(lock, (key,) + path)
        stack.discard(key)
        if _stack is None or not stack:
            self._acq_memo[key] = out
        return out

    def transitive_blocking(self, key, _stack=None):
        """(category, code) -> call path for every blocking effect
        reachable from ``key`` through resolved call edges."""
        if key in self._block_memo:
            return self._block_memo[key]
        stack = _stack if _stack is not None else set()
        if key in stack:
            return {}
        stack.add(key)
        finfo = self.functions.get(key)
        out = {}
        if finfo is not None:
            for eff in finfo.blocking:
                out.setdefault((eff.category, eff.code), (key,))
            for site in finfo.calls:
                for callee in site.callees:
                    for item, path in self.transitive_blocking(
                        callee, stack
                    ).items():
                        out.setdefault(item, (key,) + path)
        stack.discard(key)
        if _stack is None or not stack:
            self._block_memo[key] = out
        return out

    def lock_order_edges(self):
        """(held, acquired) -> [provenance dict]. Self-edges skipped:
        lock identity is class-qualified, so A -> A usually means two
        instances of the same class, not reentrancy."""
        edges = {}
        for key in sorted(self.functions):
            finfo = self.functions[key]
            for acq in finfo.locks:
                for held in acq.held:
                    if held == acq.lock:
                        continue
                    edges.setdefault((held, acq.lock), []).append({
                        "path": finfo.unit.path, "line": acq.line,
                        "symbol": finfo.qualname, "via": "acquires directly",
                    })
            for site in finfo.calls:
                if not site.held:
                    continue
                for callee in site.callees:
                    for lock, cpath in self.transitive_acquires(callee).items():
                        for held in site.held:
                            if held == lock:
                                continue
                            via = " -> ".join(
                                self.functions[k].short for k in cpath
                            )
                            edges.setdefault((held, lock), []).append({
                                "path": finfo.unit.path, "line": site.line,
                                "symbol": finfo.qualname, "via": via,
                            })
        return edges

    def lock_cycles(self):
        """Strongly-connected components (size >= 2) of the lock-order
        graph — each is a potential ABBA deadlock. Returns a sorted list
        of {locks, edges} dicts."""
        edges = self.lock_order_edges()
        adj = {}
        for (a, b) in edges:
            adj.setdefault(a, set()).add(b)
            adj.setdefault(b, set())
        index, low, on_stack, stack = {}, {}, set(), []
        sccs, counter = [], [0]

        def strongconnect(v):
            index[v] = low[v] = counter[0]
            counter[0] += 1
            stack.append(v)
            on_stack.add(v)
            for w in sorted(adj.get(v, ())):
                if w not in index:
                    strongconnect(w)
                    low[v] = min(low[v], low[w])
                elif w in on_stack:
                    low[v] = min(low[v], index[w])
            if low[v] == index[v]:
                comp = []
                while True:
                    w = stack.pop()
                    on_stack.discard(w)
                    comp.append(w)
                    if w == v:
                        break
                if len(comp) >= 2:
                    sccs.append(sorted(comp))

        for v in sorted(adj):
            if v not in index:
                strongconnect(v)
        out = []
        for comp in sorted(sccs):
            members = set(comp)
            cyc_edges = {
                pair: provs for pair, provs in sorted(edges.items())
                if pair[0] in members and pair[1] in members
            }
            out.append({"locks": comp, "edges": cyc_edges})
        return out

    def contexts(self):
        """key -> frozenset of context names the function may run on.

        Seeds: entry points and declared contracts. Propagation is a
        fixpoint over call edges; functions with a DECLARED context
        propagate only their contract (the violation at the crossing
        edge is reported once, not re-propagated downstream)."""
        if self._contexts is not None:
            return self._contexts
        ctx = {key: set() for key in self.functions}
        for entry in self.entries:
            if entry.key in ctx:
                ctx[entry.key].add(entry.context)
        for key, finfo in self.functions.items():
            if finfo.thread_context:
                ctx[key].add(finfo.thread_context)
        changed = True
        while changed:
            changed = False
            for key in sorted(self.functions):
                finfo = self.functions[key]
                out = (
                    {finfo.thread_context} if finfo.thread_context
                    else ctx[key]
                )
                if not out:
                    continue
                for site in finfo.calls:
                    for callee in site.callees:
                        if callee not in ctx:
                            continue
                        target = self.functions[callee]
                        if target.thread_context:
                            continue  # contract: checked at the edge
                        if not out <= ctx[callee]:
                            ctx[callee] |= out
                            changed = True
        self._contexts = {k: frozenset(v) for k, v in ctx.items()}
        return self._contexts

    def unknown_summary(self):
        """(count, sample list) of unresolved possibly-package callees —
        the documented lattice degradation, reported once per run."""
        sample = [
            "%s:%d %s" % (path, line, display)
            for path, line, display in self.unknown_calls[:8]
        ]
        return len(self.unknown_calls), sample

    def to_json(self):
        """JSON-serializable dump for ``edlint --graph``."""
        contexts = self.contexts()
        funcs = {}
        for key in sorted(self.functions):
            finfo = self.functions[key]
            acquires = self.transitive_acquires(key)
            blocking = self.transitive_blocking(key)
            funcs[key] = {
                "path": finfo.unit.path,
                "line": finfo.node.lineno,
                "class": finfo.class_info.name if finfo.class_info else None,
                "declared_thread": finfo.thread_context,
                "reentrant": finfo.reentrant,
                "contexts": sorted(contexts.get(key, ())),
                "locks": [
                    {"lock": a.lock, "line": a.line, "held": list(a.held)}
                    for a in finfo.locks
                ],
                "blocking": [
                    {"category": e.category, "code": e.code,
                     "line": e.line, "held": list(e.held)}
                    for e in finfo.blocking
                ],
                "calls": [
                    {"display": s.display, "line": s.line,
                     "held": list(s.held), "callees": list(s.callees),
                     "unresolved": s.unresolved}
                    for s in finfo.calls
                ],
                "transitive_locks": sorted(acquires),
                "transitive_blocking": sorted(
                    "%s:%s" % item for item in blocking
                ),
            }
        unknown_count, unknown_sample = self.unknown_summary()
        return {
            "functions": funcs,
            "entries": [
                {"key": e.key, "context": e.context,
                 "reentrant": e.reentrant, "reason": e.reason,
                 "path": e.path, "line": e.line}
                for e in self.entries
            ],
            "lock_order": [
                {"held": a, "acquired": b, "sites": provs}
                for (a, b), provs in sorted(self.lock_order_edges().items())
            ],
            "lock_cycles": [
                {"locks": c["locks"],
                 "edges": [
                     {"held": a, "acquired": b, "sites": provs}
                     for (a, b), provs in c["edges"].items()
                 ]}
                for c in self.lock_cycles()
            ],
            "unknown_callees": {
                "count": unknown_count, "sample": unknown_sample,
            },
        }


_EXTERNAL_ROOTS = frozenset({
    "abc", "argparse", "ast", "asyncio", "atexit", "base64", "bisect",
    "collections", "concurrent", "contextlib", "copy", "csv", "ctypes",
    "dataclasses", "datetime", "enum", "errno", "fcntl", "fnmatch",
    "functools", "gc", "glob", "grpc", "gzip", "hashlib", "heapq", "http",
    "importlib", "inspect", "io", "itertools", "jax", "jnp", "json",
    "logging", "math", "multiprocessing", "np", "numpy", "os", "pickle",
    "platform", "pytest", "queue", "random", "re", "resource", "select",
    "shutil", "signal", "socket", "stat", "string", "struct", "subprocess",
    "sys", "tempfile", "textwrap", "threading", "time", "tokenize",
    "traceback", "types", "typing", "unittest", "urllib", "uuid",
    "warnings", "weakref", "zlib",
})

_BUILTINS = frozenset(dir(__builtins__)) | frozenset(dir(__import__("builtins")))


class _FuncScanner:
    """Walks ONE function body (nested defs excluded — they are their
    own FunctionInfo) tracking the lexically-held lock set, recording
    acquisitions, blocking effects, resolved call edges, and thread
    entry registrations."""

    def __init__(self, graph, info):
        self.graph = graph
        self.info = info
        self.table = graph.tables[info.module]
        self.cls = info.class_info
        self.var_types = {}

    # ------------------------------------------------------------ setup

    def scan(self):
        node = self.info.node
        self._infer_var_types(node)
        for stmt in node.body:
            self._visit(stmt, ())

    def _enclosing_sibling(self, name):
        """Resolve a bare name to a def nested in the ENCLOSING
        function (closures see their siblings)."""
        if "." not in self.info.qualname:
            return None
        parent_qual = self.info.qualname.rsplit(".", 1)[0]
        parent = self.graph.functions.get(
            "%s:%s" % (self.info.module, parent_qual)
        )
        if parent is not None:
            return parent.local_defs.get(name)
        return None

    def _attr_type(self, attr):
        if self.cls is None:
            return None
        for cls in self.cls.mro():
            if attr in cls.attr_types:
                return cls.attr_types[attr]
        return None

    def _infer_var_types(self, func_node):
        """Flow-insensitive local type env: x = ClassName(...) /
        x = self._attr (typed attr) / x = other_typed_local."""
        def rec(node):
            for child in ast.iter_child_nodes(node):
                if isinstance(child, (
                    ast.FunctionDef, ast.AsyncFunctionDef,
                    ast.ClassDef, ast.Lambda,
                )):
                    continue
                if isinstance(child, ast.Assign) and len(child.targets) == 1:
                    target = child.targets[0]
                    if isinstance(target, ast.Name):
                        ctype = self._expr_type(child.value)
                        if ctype is not None:
                            self.var_types.setdefault(target.id, ctype)
                rec(child)
        rec(func_node)

    def _expr_type(self, expr):
        if isinstance(expr, ast.Call):
            chain = attr_chain(expr.func)
            if chain:
                resolved = self.graph.resolve_symbol(self.info.module, chain)
                if resolved and resolved[0] == "class":
                    return resolved[1]
            return None
        chain = attr_chain(expr)
        if chain is None:
            return None
        parts = chain.split(".")
        if parts[0] == "self" and len(parts) == 2:
            return self._attr_type(parts[1])
        if len(parts) == 1:
            return self.var_types.get(parts[0])
        return None

    # ------------------------------------------------------------ locks

    def _lock_id(self, expr):
        chain = attr_chain(expr)
        if chain is None:
            return None
        parts = chain.split(".")
        if parts[0] == "self" and self.cls is not None and len(parts) == 2:
            owner = self.graph.lock_owner(self.cls, parts[1])
            if owner:
                return "%s.%s" % (owner, parts[1])
            if _looks_lock(parts[1]):
                return "%s.%s" % (self.cls.name, parts[1])
            return None
        if len(parts) == 1:
            if parts[0] in self.table.locks:
                return self.table.locks[parts[0]]
            if _looks_lock(parts[0]):
                return "%s.%s" % (self.table.modtail, parts[0])
            return None
        head_alias = self.table.aliases.get(parts[0])
        if head_alias:
            full = head_alias.split(".") + parts[1:]
            for cut in range(len(full) - 1, 0, -1):
                prefix = ".".join(full[:cut])
                if prefix in self.graph.modules:
                    rest = full[cut:]
                    mtable = self.graph.tables[prefix]
                    if len(rest) == 1 and rest[0] in mtable.locks:
                        return mtable.locks[rest[0]]
                    break
        if parts[0] == "self" and len(parts) >= 3:
            attr_cls = self._attr_type(parts[1])
            if attr_cls is not None:
                owner = self.graph.lock_owner(attr_cls, parts[-1])
                if owner:
                    return "%s.%s" % (owner, parts[-1])
            if _looks_lock(parts[-1]):
                return "%s.%s" % (parts[-2], parts[-1])
            return None
        if _looks_lock(parts[-1]) and len(parts) >= 2:
            return "%s.%s" % (parts[-2], parts[-1])
        return None

    # ------------------------------------------------------------- walk

    def _visit(self, node, held):
        if isinstance(node, (
            ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef, ast.Lambda,
        )):
            return
        if isinstance(node, (ast.With, ast.AsyncWith)):
            new_held = list(held)
            for item in node.items:
                self._visit(item.context_expr, tuple(new_held))
                lock = self._lock_id(item.context_expr)
                if lock:
                    self.info.locks.append(LockAcquire(
                        lock, item.context_expr.lineno, tuple(new_held)
                    ))
                    new_held.append(lock)
            for stmt in node.body:
                self._visit(stmt, tuple(new_held))
            return
        if isinstance(node, ast.Call):
            self._handle_call(node, held)
        for child in ast.iter_child_nodes(node):
            self._visit(child, held)

    # ------------------------------------------------------------ calls

    def _handle_call(self, call, held):
        chain = attr_chain(call.func)
        if chain is None:
            return
        parts = chain.split(".")
        tail = parts[-1]
        if self._handle_registration(call, chain, parts, tail):
            return
        if tail in ("acquire", "release") and len(parts) >= 2:
            lock = self._lock_id(call.func.value)
            if lock:
                if tail == "acquire":
                    self.info.locks.append(
                        LockAcquire(lock, call.lineno, held)
                    )
                return
        effect = self._blocking(call, chain, parts, tail, held)
        if effect is not None:
            self.info.blocking.append(BlockEffect(
                effect[0], effect[1], call.lineno, held
            ))
            return
        self._resolve_call_edge(call, chain, parts, held)

    def _handle_registration(self, call, chain, parts, tail):
        """Thread/executor/signal registrations: the target function is
        handed off to a new execution context — an entry point, NOT a
        call edge."""
        if tail == "Thread" and (len(parts) == 1 or parts[-2] == "threading"):
            target = _kwarg(call, "target")
            if target is not None:
                ref = self._resolve_ref(target, call.lineno)
                if ref is not None:
                    name_kw = _kwarg(call, "name")
                    label = _const_str(name_kw) if name_kw is not None else None
                    self._add_entry(
                        ref, "thread:%s" % (
                            label or self.graph.functions[ref].name
                        ),
                        False, "Thread(target=...) at %s" % self.info.short,
                        call.lineno,
                    )
            return True
        if tail in ("submit", "map") and len(parts) >= 2 and call.args:
            pool = parts[-2]
            ref = self._resolve_ref(call.args[0], call.lineno)
            if ref is not None:
                self._add_entry(
                    ref, "executor:%s" % pool, False,
                    "%s.%s() at %s" % (pool, tail, self.info.short),
                    call.lineno,
                )
            return True
        if chain == "signal.signal" and len(call.args) >= 2:
            ref = self._resolve_ref(call.args[1], call.lineno)
            if ref is not None:
                self.graph.add_entry(
                    ref, "signal", True,
                    "signal.signal(...) at %s" % self.info.short,
                    self.info.unit.path, call.lineno,
                )
            return True
        return False

    def _add_entry(self, ref, context, reentrant, reason, line):
        finfo = self.graph.functions[ref]
        if finfo.thread_context is not None:
            # the registration IS the declared handoff: the target's
            # contract names the context this entry creates
            return
        self.graph.add_entry(
            ref, context, reentrant, reason, self.info.unit.path, line
        )

    def _resolve_ref(self, expr, line):
        """Resolve a function REFERENCE (Thread target, submit arg,
        signal handler) to a key; unknown references are counted."""
        if isinstance(expr, ast.Call):
            chain = attr_chain(expr.func)
            if chain and chain.split(".")[-1] == "partial" and expr.args:
                return self._resolve_ref(expr.args[0], line)
            return None
        if isinstance(expr, ast.Lambda):
            return None
        chain = attr_chain(expr)
        if chain is None:
            return None
        parts = chain.split(".")
        if parts[0] == "self" and self.cls is not None:
            if len(parts) == 2:
                key = self.graph._method(self.cls, parts[1])
                if key:
                    return key
            elif len(parts) == 3:
                attr_cls = self._attr_type(parts[1])
                if attr_cls is not None:
                    key = self.graph._method(attr_cls, parts[2])
                    if key:
                        return key
            if (
                parts[-1] in self.graph.defined_names
                and parts[-1] not in _COMMON_OBJ_METHODS
            ):
                self.graph.unknown_calls.append(
                    (self.info.unit.path, line, "target:" + chain)
                )
            return None
        if len(parts) == 1:
            if parts[0] in self.info.local_defs:
                return self.info.local_defs[parts[0]]
            if parts[0] == self.info.name:
                return self.info.key
            sibling = self._enclosing_sibling(parts[0])
            if sibling:
                return sibling
        if parts[0] in self.var_types and len(parts) == 2:
            key = self.graph._method(self.var_types[parts[0]], parts[1])
            if key:
                return key
        resolved = self.graph.resolve_symbol(self.info.module, chain)
        if resolved and resolved[0] == "func":
            return resolved[1]
        if (
            parts[0] not in _EXTERNAL_ROOTS
            and parts[0] not in _BUILTINS
            and parts[-1] in self.graph.defined_names
        ):
            self.graph.unknown_calls.append(
                (self.info.unit.path, line, "target:" + chain)
            )
        return None

    def _resolve_call_edge(self, call, chain, parts, held):
        keys, unresolved = (), False
        if parts[0] == "self" and self.cls is not None:
            if len(parts) == 2:
                key = self.graph._method(self.cls, parts[1])
                if key:
                    keys = (key,)
                else:
                    unresolved = True
            elif len(parts) == 3:
                attr_cls = self._attr_type(parts[1])
                if attr_cls is not None:
                    key = self.graph._method(attr_cls, parts[2])
                    keys = (key,) if key else ()
                    unresolved = not keys
                else:
                    unresolved = True
            else:
                unresolved = True
        elif parts[0] == "self":
            unresolved = True
        elif parts[0] in self.var_types and len(parts) == 2:
            key = self.graph._method(self.var_types[parts[0]], parts[1])
            if key:
                keys = (key,)
            else:
                unresolved = True
        elif len(parts) == 1 and parts[0] in self.info.local_defs:
            keys = (self.info.local_defs[parts[0]],)
        elif len(parts) == 1 and parts[0] == self.info.name:
            keys = (self.info.key,)  # self-recursion
        elif len(parts) == 1 and self._enclosing_sibling(parts[0]):
            keys = (self._enclosing_sibling(parts[0]),)
        else:
            resolved = self.graph.resolve_symbol(self.info.module, chain)
            if resolved is None:
                # untyped local receivers (parser.add_argument, f.write)
                # are treated as external — only bare names that could be
                # package functions degrade to unknown (documented lattice)
                unresolved = (
                    len(parts) == 1
                    and parts[0] not in _EXTERNAL_ROOTS
                    and parts[0] not in _BUILTINS
                )
            elif resolved[0] == "func":
                keys = (resolved[1],)
            elif resolved[0] == "class":
                ctor = self.graph.ctor_key(resolved[1])
                keys = (ctor,) if ctor else ()
        if unresolved and (
            parts[-1] not in self.graph.defined_names
            or parts[-1] in _COMMON_OBJ_METHODS
        ):
            # the method name exists nowhere in the package: an external
            # object (list.append, argparse, ...), not a failed resolution
            unresolved = False
        if unresolved:
            self.graph.unknown_calls.append(
                (self.info.unit.path, call.lineno, chain)
            )
        if keys or unresolved:
            self.info.calls.append(CallSite(
                chain, call.lineno, held, keys, unresolved
            ))

    # --------------------------------------------------------- blocking

    def _blocking(self, call, chain, parts, tail, held):
        receiver = ".".join(parts[:-1])
        if chain in _IO_CALLS:
            return ("io", chain)
        if tail in ("savez", "savez_compressed"):
            return ("io", chain)
        if tail == "sleep":
            return ("sleep", chain)
        if parts[0] == "subprocess":
            return ("subprocess", chain)
        if parts[0] == "socket" and tail in _SOCKET_TAILS:
            return ("socket", chain)
        if "sock" in receiver.lower() and tail in _SOCKET_TAILS:
            return ("socket", chain)
        if "stub" in receiver.lower() and len(parts) >= 2:
            return ("grpc", chain)
        if tail == "result" and len(parts) >= 2:
            return ("wait", chain)
        if tail == "join" and len(parts) >= 2 and not call.args:
            return ("wait", chain)
        if tail == "wait_for_termination":
            return ("wait", chain)
        if tail == "wait" and len(parts) >= 2:
            timeout = call.args[0] if call.args else _kwarg(call, "timeout")
            if timeout is not None and not (
                isinstance(timeout, ast.Constant) and timeout.value is None
            ):
                return None
            recv_lock = self._lock_id(call.func.value)
            if recv_lock is not None and recv_lock in held:
                return None  # cv-wait releases the lock it waits on
            return ("wait", chain)
        if tail in ("get", "put") and len(parts) >= 2:
            low = receiver.lower()
            if "queue" in low or low.endswith("_q"):
                block_kw = _kwarg(call, "block")
                if isinstance(block_kw, ast.Constant) and not block_kw.value:
                    return None
                timeout = _kwarg(call, "timeout")
                if timeout is not None and not (
                    isinstance(timeout, ast.Constant)
                    and timeout.value is None
                ):
                    return None
                if tail == "get" and call.args:
                    return None  # dict.get(key, default) shape
                return ("queue", chain)
        return None


_GRAPH_CACHE = []


def build_graph(units):
    """Build (or reuse) the CallGraph for this exact list of units.
    Cached so the three conc-* rules share one build per run."""
    key = tuple(id(u) for u in units)
    for cached_key, graph in _GRAPH_CACHE:
        if cached_key == key:
            return graph
    graph = CallGraph.build(units)
    _GRAPH_CACHE.append((key, graph))
    del _GRAPH_CACHE[:-4]
    return graph
