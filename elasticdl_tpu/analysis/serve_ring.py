"""serve-affinity-unbounded-ring: per-replica/per-key state with no
cleanup entry point in the serving tier.

The failure class behind ISSUE 17's router review: the routing tier
keeps per-replica and per-affinity-key books — ring placements,
in-flight counters, canary tallies, subprocess tables — that grow one
entry per replica id (or hashed key) the fleet has EVER seen. Replicas
churn: the autoscaler spawns and drains them, SIGKILLed pods re-register
under fresh ids, and a router that never deletes the dead id's entries
leaks memory at exactly the rate elasticity works. Same shape as
``ft-unbounded-vocab`` (id-keyed growth with no way to shrink), scoped
to the serving fleet's persistent state.

What fires, in files under a ``serve/`` package directory only:

- a statement that GROWS persistent (attribute-rooted, ``self.X``)
  state keyed by a replica/affinity identity: ``self.d[rid] = ...``
  subscript assignment, ``self.d.setdefault(replica_id, ...)``, or
  ``self.s.add(key_hash)`` — where the key expression reads as an
  identity (``replica_id``, ``rid``, ``affinity_key``, ``key_hash``,
  ``pid``);
- UNLESS the enclosing class (or module, for top-level code) defines a
  cleanup entry point — any of ``deregister``/``deregister_replica``,
  ``forget``/``forget_replica``, ``remove``/``remove_replica``,
  ``expire``, ``evict``, ``prune``, ``reap``, ``release``, or
  ``clear`` — a class that CAN delete a departed replica's entries is
  allowed to insert them.

Locals are out of scope by construction (a per-call dict dies with the
call); only attribute-rooted containers persist across requests. False
positives are one ``# edlint: disable=serve-affinity-unbounded-ring``
away, with the justification the suppression comment forces.
"""

import ast
import os

from elasticdl_tpu.analysis.core import Finding, attr_chain, self_attr_target

RULE = "serve-affinity-unbounded-ring"

_SCOPED_DIRS = {"serve"}

# key spellings that mean "a replica or affinity identity flows here"
_ID_NAMES = {"replica_id", "rid", "affinity_key", "key_hash", "pid"}

# an enclosing class/module with any of these defines a way to drop a
# departed replica's entries: growth is then lifecycle-managed
_CLEANUP_METHODS = {
    "deregister", "deregister_replica", "forget", "forget_replica",
    "remove", "remove_replica", "expire", "evict", "prune", "reap",
    "release", "clear",
}


def _in_scope(path):
    parts = path.replace(os.sep, "/").split("/")
    return bool(_SCOPED_DIRS & set(parts))


def _is_identity_key(node):
    """The key expression derives from a replica/affinity identity:
    a name or attribute tail in the identity vocabulary, directly or
    through int()/str()-style conversion calls."""
    for sub in ast.walk(node):
        if isinstance(sub, ast.Name) and sub.id.lower() in _ID_NAMES:
            return True
        if isinstance(sub, ast.Attribute) and sub.attr.lower() in _ID_NAMES:
            return True
    return False


def _growth_statements(tree):
    """Yield (lineno, code) for identity-keyed growth of persistent
    (``self.X``) containers."""
    for node in ast.walk(tree):
        if isinstance(node, ast.Assign):
            for target in node.targets:
                if not isinstance(target, ast.Subscript):
                    continue
                attr = self_attr_target(target)
                if attr is None:
                    continue  # locals die with the call
                if _is_identity_key(target.slice):
                    yield node.lineno, "self.%s[...] =" % attr
        elif isinstance(node, ast.Call):
            func = node.func
            if not (
                isinstance(func, ast.Attribute)
                and func.attr in ("setdefault", "add")
                and node.args
            ):
                continue
            chain = attr_chain(func.value)
            if chain is None or not chain.startswith("self."):
                continue
            if _is_identity_key(node.args[0]):
                yield node.lineno, "%s.%s()" % (chain, func.attr)


def _scope_methods(unit):
    """{class name or '<module>': defined method/function names} —
    the cleanup-entry-point lookup."""
    scopes = {"<module>": set()}
    for node in ast.walk(unit.tree):
        if isinstance(node, ast.ClassDef):
            scopes[node.name] = {
                child.name
                for child in node.body
                if isinstance(child, (ast.FunctionDef,
                                      ast.AsyncFunctionDef))
            }
        elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            scopes["<module>"].add(node.name)
    return scopes


def run(units):
    from elasticdl_tpu.analysis.core import walk_with_scope

    findings = []
    for unit in units:
        if not _in_scope(unit.path):
            continue
        scopes = _scope_methods(unit)
        # line -> enclosing qualname, to label findings
        growth = dict(_growth_statements(unit.tree))
        if not growth:
            continue
        line_scope = {}
        for node, scope in walk_with_scope(unit.tree):
            if hasattr(node, "lineno") and node.lineno in growth:
                line_scope.setdefault(node.lineno, scope)
        for lineno, code in sorted(growth.items()):
            scope = line_scope.get(lineno, "<module>")
            owner = scope.split(".", 1)[0]
            defined = scopes.get(owner, scopes["<module>"])
            if defined & _CLEANUP_METHODS:
                continue
            findings.append(Finding(
                rule=RULE,
                path=unit.path,
                line=lineno,
                symbol=scope,
                code=code,
                message=(
                    "per-replica/per-key state grows one entry per "
                    "identity with no cleanup entry point (no "
                    "deregister/forget/remove/expire/reap/clear on "
                    "%r) — replica churn leaks this container; drop "
                    "entries when the replica leaves the fleet" % owner
                ),
            ))
    return findings
