"""perf-gil-held-apply: proto parsing and store apply under one lock.

The idiom this rule keeps out of the PS servicer (the pre-ISSUE-11
sync-path shape, hoisted in PR 5 and load-bearing ever since):

    with self._push_lock:
        values, ids = _deserialize_gradients(slices)   # pure CPU work
        self._store.push_gradients(name, ids, values)  # the apply

Deserialization is per-request CPU work that needs no shared state;
doing it inside the push lock serializes every peer's push of a sync
round behind one worker's decode — and with the native store's
GIL-released applies (ISSUE 11) the lock becomes the ONLY remaining
serialization point, so work smuggled under it is pure lost
parallelism. The fix is mechanical: parse outside, take the lock for
the apply alone.

Scope: PS servicer modules only (path contains ``ps/`` or a
``servicer`` basename). Elsewhere a lock around parse+apply can be a
deliberate atomicity choice; on the PS push path it never is — the
buffered-round design already separates the two.

What fires: a ``with`` statement whose context expression mentions a
lock (name/attribute containing ``lock``) and whose body contains BOTH
a parse-ish call (``deserialize``/``unpack_ids``/``blob_to_ndarray``/
``ParseFromString``/``FromString``/``frombuffer``) and a store apply
(``push_gradients``/``push_gradients_blob``/``import_table``/
``import_blob``/``import_table_full``) at any nesting depth inside
that block.
"""

import ast
import os

from elasticdl_tpu.analysis.core import Finding, walk_with_scope

RULE = "perf-gil-held-apply"

_PARSE_NAMES = {
    "deserialize_indexed_slices",
    "_deserialize_gradients",
    "unpack_ids",
    "blob_to_ndarray",
    "ParseFromString",
    "FromString",
    "frombuffer",
}

_APPLY_NAMES = {
    "push_gradients",
    "push_gradients_blob",
    "import_table",
    "import_table_full",
    "import_blob",
}


def _call_name(node):
    func = node.func
    if isinstance(func, ast.Attribute):
        return func.attr
    if isinstance(func, ast.Name):
        return func.id
    return None


def _mentions_lock(expr):
    for node in ast.walk(expr):
        if isinstance(node, ast.Attribute) and "lock" in node.attr.lower():
            return True
        if isinstance(node, ast.Name) and "lock" in node.id.lower():
            return True
    return False


def _servicer_module(path):
    normalized = path.replace(os.sep, "/")
    return (
        "/ps/" in normalized
        or "servicer" in os.path.basename(normalized)
    )


def run(units):
    findings = []
    for unit in units:
        if not _servicer_module(unit.path):
            continue
        for node, scope in walk_with_scope(unit.tree):
            if not isinstance(node, ast.With):
                continue
            if not any(
                _mentions_lock(item.context_expr) for item in node.items
            ):
                continue
            parses, applies = set(), set()
            for stmt in node.body:
                for sub in ast.walk(stmt):
                    if not isinstance(sub, ast.Call):
                        continue
                    name = _call_name(sub)
                    if name in _PARSE_NAMES:
                        parses.add(name)
                    elif name in _APPLY_NAMES:
                        applies.add(name)
            if parses and applies:
                findings.append(
                    Finding(
                        rule=RULE,
                        path=unit.path,
                        line=node.lineno,
                        symbol=scope,
                        code="with lock: %s + %s" % (
                            sorted(parses)[0], sorted(applies)[0]
                        ),
                        message=(
                            "proto parsing (%s) and store apply (%s) "
                            "under one lock: the decode is per-request "
                            "CPU work that serializes every concurrent "
                            "push behind this lock — parse outside, "
                            "lock only the apply"
                            % (", ".join(sorted(parses)),
                               ", ".join(sorted(applies)))
                        ),
                    )
                )
    return findings
