"""perf-io-under-lock: file IO inside a lock-guarded block in ps/.

The idiom this rule keeps out of the PS (the pre-ISSUE-13 shape the
incremental-checkpoint work removed):

    with self._push_lock:
        ...
        self._checkpoint_saver.save(version, self._store)  # np.savez!

A checkpoint save is O(rows) serialization plus file IO; under a lock
the push path contends on, it stalls every worker's push for the
duration of the save — exactly the inline-save stall ISSUE 13's
off-RPC checkpoint thread exists to remove. The same goes for any
``np.savez``/``np.load``/``open``/rename under a store or push lock:
snapshot under the lock (the store's ``export_table_dirty`` is built
for this — one brief gather), serialize and write outside it.

Scope: PS modules only (path contains ``ps/`` or a ``servicer``/
``checkpoint`` basename). Elsewhere a file write under a lock can be a
deliberate write-through-journal choice (events.py holds its lock
across NDJSON appends on purpose); on the PS data path it never is.

What fires: a ``with`` statement whose context expression mentions a
lock (name/attribute containing ``lock``) and whose body contains a
file-IO call at any nesting depth inside that block:

- ``open(...)`` (builtin),
- ``np.savez`` / ``np.savez_compressed`` / ``np.save`` / ``np.load``
  (any receiver: ``savez`` has no other meaning),
- ``os.replace`` / ``os.rename`` / ``os.makedirs`` /
  ``shutil.rmtree``,
- ``.save(...)`` / ``.restore(...)`` on a receiver whose dotted chain
  mentions ``saver`` or ``checkpoint`` (the checkpoint-saver calls —
  each one is a full serialize-and-write).
"""

import ast
import os

from elasticdl_tpu.analysis.core import Finding, attr_chain, walk_with_scope

RULE = "perf-io-under-lock"

# method names that are IO wherever they appear
_IO_METHOD_NAMES = {"savez", "savez_compressed"}

# full dotted chains that are IO
_IO_CHAINS = {
    "np.save", "np.load", "numpy.save", "numpy.load",
    "os.replace", "os.rename", "os.makedirs", "shutil.rmtree",
}

# method names that are IO when the receiver chain names the
# checkpoint saver
_SAVER_METHOD_NAMES = {"save", "restore"}


def _ps_module(path):
    normalized = path.replace(os.sep, "/")
    base = os.path.basename(normalized)
    return (
        "/ps/" in normalized
        or "servicer" in base
        or "checkpoint" in base
    )


def _mentions_lock(expr):
    for node in ast.walk(expr):
        if isinstance(node, ast.Attribute) and "lock" in node.attr.lower():
            return True
        if isinstance(node, ast.Name) and "lock" in node.id.lower():
            return True
    return False


def _io_call_name(node):
    """The display name when ``node`` is a file-IO call, else None."""
    func = node.func
    if isinstance(func, ast.Name) and func.id == "open":
        return "open"
    chain = attr_chain(func)
    if chain in _IO_CHAINS:
        return chain
    if isinstance(func, ast.Attribute):
        if func.attr in _IO_METHOD_NAMES:
            return func.attr
        if func.attr in _SAVER_METHOD_NAMES:
            receiver = attr_chain(func.value) or ""
            lowered = receiver.lower()
            if "saver" in lowered or "checkpoint" in lowered:
                return "%s.%s" % (receiver, func.attr)
    return None


def run(units):
    findings = []
    for unit in units:
        if not _ps_module(unit.path):
            continue
        for node, scope in walk_with_scope(unit.tree):
            if not isinstance(node, ast.With):
                continue
            if not any(
                _mentions_lock(item.context_expr) for item in node.items
            ):
                continue
            io_calls = []
            for stmt in node.body:
                for sub in ast.walk(stmt):
                    if isinstance(sub, ast.Call):
                        name = _io_call_name(sub)
                        if name:
                            io_calls.append(name)
            if io_calls:
                findings.append(
                    Finding(
                        rule=RULE,
                        path=unit.path,
                        line=node.lineno,
                        symbol=scope,
                        code="with lock: %s" % sorted(io_calls)[0],
                        message=(
                            "file IO (%s) inside a lock-guarded block: "
                            "a serialize-and-write under a lock the "
                            "push path contends on stalls every "
                            "worker's push for the save's duration — "
                            "snapshot under the lock (export_table_"
                            "dirty), write outside it"
                            % ", ".join(sorted(set(io_calls)))
                        ),
                    )
                )
    return findings
