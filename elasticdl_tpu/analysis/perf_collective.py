"""perf-bare-collective: a raw ``jax.lax`` collective outside the
``parallel/`` / ``ops/`` scopes that own cross-device communication.

``parallel/collectives.py`` is the one sanctioned spelling of an
explicit in-body collective everywhere else in the tree, for two
load-bearing reasons:

1. **AD correctness on the pinned runtime.** jax 0.4.x ships the
   pmap-era ``transpose(psum) = psum`` rule, which silently scales
   gradients by the axis size when the collective is differentiated
   INSIDE a shard_map body — exactly what the 1f1b pipeline schedule
   does to every stage function. ``mesh_psum`` pins the modern
   transpose (identity) via a custom_vjp; a bare ``lax.psum`` in a
   model or training scope is a latent 2x-gradient bug that no test
   catches until someone runs that model on tp>1.

2. **Byte accounting.** The dense-plane telemetry
   (``collective_bytes_per_step``) is summed from the helpers' ring-
   cost recorder at trace time. A bare collective moves bytes the
   telemetry never sees, so /statusz under-reports ICI traffic.

What fires: any call whose callee resolves to a ``jax.lax`` /
``lax``-prefixed (or bare-imported) collective —
``psum``, ``pmean``, ``psum_scatter``, ``all_gather``, ``all_to_all``,
``all_reduce`` — in a module outside ``elasticdl_tpu.parallel.`` and
``elasticdl_tpu.ops.``. Those two scopes implement the helpers and the
hand-scheduled kernels; everywhere else routes through
``parallel.collectives.mesh_*``.

Legitimate exceptions (the AD-repair substrate in
``common/jax_compat.py``, which the helpers are themselves built on)
carry ``# edlint: disable=perf-bare-collective`` with the reason on
the suppression line.
"""

import ast

from elasticdl_tpu.analysis.core import (
    Finding,
    attr_chain,
    walk_with_scope,
)

RULE = "perf-bare-collective"

_COLLECTIVE_LEAVES = {
    "psum",
    "pmean",
    "psum_scatter",
    "all_gather",
    "all_to_all",
    "all_reduce",
}

# scopes that OWN communication: the helper module itself, the manual
# pipeline/tensor-parallel schedules, and the hand-written kernels
_ALLOWED_PREFIXES = (
    "elasticdl_tpu.parallel.",
    "elasticdl_tpu.ops.",
)


def _in_scope(module):
    if not module.startswith("elasticdl_tpu."):
        return False
    return not any(module.startswith(p) for p in _ALLOWED_PREFIXES)


def _collective_leaf(func):
    """The collective's name when ``func`` is a raw lax collective
    (``jax.lax.psum``, ``lax.psum``, or a bare ``psum`` from
    ``from jax.lax import psum``), else None. The ``mesh_*`` helpers
    have different leaf names and never match."""
    if isinstance(func, ast.Name):
        return func.id if func.id in _COLLECTIVE_LEAVES else None
    chain = attr_chain(func)
    if chain is None:
        return None
    parts = chain.split(".")
    leaf = parts[-1]
    if leaf not in _COLLECTIVE_LEAVES:
        return None
    # attribute calls must come off a lax module; `store.all_gather`
    # or `self.psum` style methods are not collectives
    return leaf if parts[-2] == "lax" else None


def run(units):
    findings = []
    for unit in units:
        if not _in_scope(unit.module):
            continue
        for node, scope in walk_with_scope(unit.tree):
            if not isinstance(node, ast.Call):
                continue
            leaf = _collective_leaf(node.func)
            if leaf is None:
                continue
            findings.append(
                Finding(
                    rule=RULE,
                    path=unit.path,
                    line=node.lineno,
                    symbol=scope,
                    code="lax.%s()" % leaf,
                    message=(
                        "bare lax.%s outside parallel/+ops/: use "
                        "parallel.collectives.mesh_%s — the helper "
                        "pins the correct psum transpose for vjp "
                        "inside shard_map on the pinned jax (bare "
                        "spelling silently scales grads by the axis "
                        "size) and records the bytes the dense-plane "
                        "telemetry reports"
                        % (leaf, "psum" if leaf == "all_reduce" else leaf)
                    ),
                )
            )
    return findings
