"""lock-discipline: off-lock mutation of lock-protected attributes.

For every class that creates a ``threading.Lock``/``RLock``/``Condition``
in ``__init__``, infer the set of attributes that class mutates while
holding each lock, then flag any method that mutates one of those
attributes without holding it.

Lock-held regions are:

- the body of ``with self.<lock>:`` (any of the with's items);
- the body of a method whose name ends in ``_locked`` — this repo's
  caller-holds-the-lock convention (task_dispatcher, ps/servicer).
  With several locks in a class, a ``_locked`` method counts as
  holding ALL of them for checking and contributes to inference only
  when the class has exactly one lock (otherwise the association is
  ambiguous).

A nested ``def`` inside a lock-held region is NOT lock-held: closures
outlive the with-block (deferred callbacks are exactly how the
reference leaked unlocked mutations). Suppress deliberate ones with
``# edlint: disable=lock-discipline`` on the inner ``def`` line.

Known blind spots (documented, not worth the alias analysis): local
aliases (``queue = self._todo; queue.pop()``) and mutations through
``self.<attr>`` element objects.
"""

import ast

from elasticdl_tpu.analysis.core import Finding, self_attr_target

RULE = "lock-discipline"

_LOCK_FACTORIES = {"Lock", "RLock", "Condition"}

# method names on self.<attr> that mutate the container in place
_MUTATORS = {
    "append", "appendleft", "extend", "extendleft", "insert",
    "remove", "pop", "popleft", "popitem", "clear",
    "add", "discard", "update", "setdefault", "sort", "reverse",
}


def _lock_attrs(class_node):
    """Lock attribute names assigned in __init__ (``self._lock =
    threading.Lock()`` or bare ``Lock()``)."""
    locks = set()
    for item in class_node.body:
        if not (
            isinstance(item, ast.FunctionDef) and item.name == "__init__"
        ):
            continue
        for node in ast.walk(item):
            if not isinstance(node, ast.Assign):
                continue
            value = node.value
            if not isinstance(value, ast.Call):
                continue
            func = value.func
            name = (
                func.attr if isinstance(func, ast.Attribute)
                else func.id if isinstance(func, ast.Name)
                else None
            )
            if name not in _LOCK_FACTORIES:
                continue
            for target in node.targets:
                attr = self_attr_target(target)
                if attr is not None:
                    locks.add(attr)
    return locks


def _is_lock_with(node, locks):
    """Lock names this ``with`` statement acquires (subset of locks)."""
    held = set()
    for item in node.items:
        expr = item.context_expr
        # ``with self._lock:`` — also accept ``self._lock.acquire()``-less
        # Condition use: ``with self._cv:``
        if (
            isinstance(expr, ast.Attribute)
            and isinstance(expr.value, ast.Name)
            and expr.value.id == "self"
            and expr.attr in locks
        ):
            held.add(expr.attr)
    return held


def _mutated_attrs(node):
    """Yield (attr, line) for each ``self.<attr>`` mutation directly in
    ``node`` (single statement or expression)."""
    if isinstance(node, (ast.Assign, ast.AnnAssign, ast.AugAssign)):
        targets = (
            node.targets if isinstance(node, ast.Assign) else [node.target]
        )
        for target in targets:
            # unpack tuple/list targets: ``a, self._x = ...``
            elts = (
                target.elts
                if isinstance(target, (ast.Tuple, ast.List))
                else [target]
            )
            for elt in elts:
                attr = self_attr_target(elt)
                if attr is not None:
                    yield attr, node.lineno
    elif isinstance(node, ast.Delete):
        for target in node.targets:
            attr = self_attr_target(target)
            if attr is not None:
                yield attr, node.lineno
    elif isinstance(node, ast.Call):
        func = node.func
        if isinstance(func, ast.Attribute) and func.attr in _MUTATORS:
            attr = self_attr_target(func.value)
            if attr is not None:
                yield attr, node.lineno


class _MethodScanner:
    """Walks one method body tracking which locks are held lexically.
    Every node is visited exactly once with the correct held-set."""

    def __init__(self, locks, holds_all):
        self.locks = locks
        self.holds_all = holds_all
        # list of (attr, line, frozenset(held_locks), in_nested_def)
        self.mutations = []

    def scan(self, method):
        initial = frozenset(self.locks) if self.holds_all else frozenset()
        for stmt in method.body:
            self._visit(stmt, held=initial, nested=False)

    def _visit(self, node, held, nested):
        if isinstance(
            node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)
        ):
            # closures/lambdas escape the lock scope: deferred execution
            # does not inherit the with-block's lock
            body = node.body if isinstance(node.body, list) else [node.body]
            for child in body:
                self._visit(child, held=frozenset(), nested=True)
            return
        if isinstance(node, ast.With):
            newly = _is_lock_with(node, self.locks)
            for item in node.items:
                self._visit(item.context_expr, held, nested)
            for child in node.body:
                self._visit(child, held | newly, nested)
            return
        for attr, line in _mutated_attrs(node):
            self.mutations.append((attr, line, held, nested))
        for child in ast.iter_child_nodes(node):
            self._visit(child, held, nested)


def _scan_class(unit, class_node, findings):
    locks = _lock_attrs(class_node)
    if not locks:
        return
    single_lock = len(locks) == 1
    methods = [
        item for item in class_node.body
        if isinstance(item, (ast.FunctionDef, ast.AsyncFunctionDef))
    ]
    # pass 1: infer protected attrs per lock
    protected = {lock: set() for lock in locks}
    scans = {}
    for method in methods:
        if method.name == "__init__":
            continue
        holds_all = method.name.endswith("_locked")
        scanner = _MethodScanner(locks, holds_all)
        scanner.scan(method)
        # keyed by node, not name: a property getter/setter pair shares
        # a name, and a name key would both skip the getter in pass 2
        # and double-report the setter
        scans[id(method)] = scanner
        for attr, _line, held, nested in scanner.mutations:
            if nested:
                continue  # closures don't prove protection
            if holds_all:
                if single_lock:
                    protected[next(iter(locks))].add(attr)
                continue
            for lock in held:
                protected[lock].add(attr)
    # the lock attributes themselves are infrastructure, not state
    for lock in locks:
        for attrs in protected.values():
            attrs.discard(lock)
    # pass 2: flag mutations of protected attrs made without the lock
    for method in methods:
        if method.name == "__init__":
            continue  # construction happens-before publication
        scanner = scans[id(method)]
        if scanner.holds_all:
            continue
        for attr, line, held, _nested in scanner.mutations:
            owners = [
                lock for lock, attrs in protected.items() if attr in attrs
            ]
            if not owners:
                continue
            if any(lock in held for lock in owners):
                continue
            findings.append(
                Finding(
                    rule=RULE,
                    path=unit.path,
                    line=line,
                    symbol="%s.%s" % (class_node.name, method.name),
                    code="unlocked: %s" % attr,
                    message=(
                        "self.%s is mutated under self.%s elsewhere in "
                        "%s but mutated here without holding it"
                        % (attr, "/self.".join(sorted(owners)),
                           class_node.name)
                    ),
                )
            )


def run(units):
    findings = []
    for unit in units:
        for node in ast.walk(unit.tree):
            if isinstance(node, ast.ClassDef):
                _scan_class(unit, node, findings)
    return findings
