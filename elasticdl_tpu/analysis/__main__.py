"""edlint CLI: ``python -m elasticdl_tpu.analysis [paths...]``.

Exit codes: 0 clean (or everything baselined/suppressed), 1 findings,
2 usage/parse error.
"""

import argparse
import json
import os
import sys

from elasticdl_tpu.analysis.callgraph import build_graph
from elasticdl_tpu.analysis.core import (
    RULE_NAMES,
    _load_units,
    analyze_units,
    baseline_dict,
    load_baseline,
    split_baselined,
)

DEFAULT_BASELINE = ".edlint-baseline.json"


def _discover_baseline(paths):
    """cwd first, then upward from the first scanned path — so the gate
    works both from the repo root and from a subdir."""
    candidates = [os.path.join(os.getcwd(), DEFAULT_BASELINE)]
    if paths:
        probe = os.path.abspath(paths[0])
        for _ in range(6):
            probe = os.path.dirname(probe)
            if not probe or probe == os.path.dirname(probe):
                break
            candidates.append(os.path.join(probe, DEFAULT_BASELINE))
    for candidate in candidates:
        if os.path.isfile(candidate):
            return candidate
    return None


def main(argv=None):
    parser = argparse.ArgumentParser(
        prog="python -m elasticdl_tpu.analysis",
        description="edlint: framework-aware static analysis "
                    "(lock discipline, JAX hot-path, fault-tolerance "
                    "hygiene, cross-host determinism)",
    )
    parser.add_argument(
        "paths", nargs="*", default=["elasticdl_tpu"],
        help="files or directories to analyze (default: elasticdl_tpu)",
    )
    parser.add_argument(
        "--rules", default=None,
        help="comma-separated subset of rules (default: all)",
    )
    parser.add_argument(
        "--baseline", default=None,
        help="baseline JSON (default: auto-discover %s)" % DEFAULT_BASELINE,
    )
    parser.add_argument(
        "--no-baseline", action="store_true",
        help="ignore any baseline file (report everything)",
    )
    parser.add_argument(
        "--write-baseline", metavar="PATH", default=None,
        help="write current findings as a baseline to PATH and exit 0 "
             "(justifications start as TODO — fill them in)",
    )
    parser.add_argument(
        "--list-rules", action="store_true", help="list rules and exit"
    )
    parser.add_argument(
        "--graph", action="store_true",
        help="dump the whole-program call graph the conc-* rules run on "
             "(functions, entries, lock order, cycles, unresolved "
             "callees) as JSON and exit — debug aid for triaging a "
             "concurrency finding",
    )
    args = parser.parse_args(argv)

    if args.list_rules:
        for name in RULE_NAMES:
            print(name)
        return 0

    rules = None
    if args.rules:
        rules = [r.strip() for r in args.rules.split(",") if r.strip()]
    try:
        units, errors = _load_units(args.paths)
    except FileNotFoundError as e:
        print("edlint: error: %s" % e, file=sys.stderr)
        return 2
    for path, message in errors:
        print("edlint: parse error in %s: %s" % (path, message),
              file=sys.stderr)

    if args.graph:
        json.dump(build_graph(units).to_json(), sys.stdout, indent=2,
                  sort_keys=True)
        sys.stdout.write("\n")
        return 2 if errors else 0

    try:
        findings = analyze_units(units, rules=rules)
    except ValueError as e:
        print("edlint: error: %s" % e, file=sys.stderr)
        return 2

    # The conc-* rules degrade soundly on unresolvable callees; the
    # contract (callgraph.py) is that degradation is surfaced, never
    # silent. Report the count once per run.
    if rules is None or any(r.startswith("conc-") for r in rules):
        unknown_count, unknown_sample = build_graph(units).unknown_summary()
        if unknown_count:
            print(
                "edlint: note: %d call site(s) with unresolved "
                "possibly-package callees degraded conc-* analysis "
                "(e.g. %s) — run --graph to inspect"
                % (unknown_count, ", ".join(unknown_sample[:3])),
                file=sys.stderr,
            )

    if args.write_baseline:
        with open(args.write_baseline, "w", encoding="utf-8") as f:
            json.dump(baseline_dict(findings), f, indent=2, sort_keys=True)
            f.write("\n")
        print(
            "edlint: wrote %d baseline entr%s to %s"
            % (len(findings), "y" if len(findings) == 1 else "ies",
               args.write_baseline)
        )
        return 0

    baseline = None
    if not args.no_baseline:
        baseline_path = args.baseline or _discover_baseline(args.paths)
        if baseline_path:
            try:
                baseline = load_baseline(baseline_path)
            except (OSError, ValueError, json.JSONDecodeError) as e:
                print(
                    "edlint: bad baseline %s: %s" % (baseline_path, e),
                    file=sys.stderr,
                )
                return 2

    new, baselined, unused = split_baselined(findings, baseline)
    for finding in new:
        print(finding.render())
    for entry in unused:
        print(
            "edlint: note: unused baseline entry %s:%s (%s) — remove it"
            % (entry.get("path"), entry.get("symbol"), entry.get("rule")),
            file=sys.stderr,
        )
    print(
        "edlint: %d finding(s), %d baselined"
        % (len(new), len(baselined))
    )
    if errors:
        return 2
    return 1 if new else 0


if __name__ == "__main__":
    sys.exit(main())
