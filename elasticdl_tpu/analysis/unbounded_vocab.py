"""ft-unbounded-vocab: id-keyed container growth with no eviction bound.

The failure class behind ISSUE 12: production CTR streams carry
unbounded vocabularies, and any table/dict/set that grows one entry per
raw stream id — with no admission gate and no eviction entry point —
is a slow memory leak by design. The embedding stores paid exactly this
(every novel id materialized a row forever) until the lifecycle manager
landed; this rule keeps the class from creeping back into the hot
store/stream/cache layers.

What fires, in files under a ``ps/``, ``stream/``, or ``embedding/``
package directory only:

- a ``for`` loop whose iterable's dotted name ends in an id-stream
  spelling (``ids``, ``id_list``, ``unique_ids``, ...), containing a
  statement that GROWS a container keyed by the loop variable:
  ``d[i] = ...`` / ``d[int(i)] = ...`` subscript assignment,
  ``d.setdefault(i, ...)``, or ``s.add(i)``;
- UNLESS the growth is bounded by construction: the enclosing class
  (or module, for top-level code) defines an eviction/admission entry
  point — any of ``drop_rows``, ``drop_table``, ``sweep``, ``evict``,
  or ``clear`` with a capacity bound is out of scope (caches with
  ``capacity``/``maxlen`` discipline define ``clear``).

A store that CAN delete rows is allowed to insert them — the rule pins
"grows forever with no way to shrink", not "inserts". False positives
are one ``# edlint: disable=ft-unbounded-vocab`` away, with the
justification the suppression comment forces.
"""

import ast
import os

from elasticdl_tpu.analysis.core import Finding, attr_chain

RULE = "ft-unbounded-vocab"

_SCOPED_DIRS = {"ps", "stream", "embedding"}

# iterable spellings that mean "raw stream ids flow here"
_ID_TAILS = ("ids", "id_list", "id_set")

# an enclosing class/module with any of these defines a way to shrink:
# growth is then lifecycle-managed, not unbounded
_EVICTION_METHODS = {
    "drop_rows", "drop_table", "sweep", "evict", "evict_rows", "clear",
}


def _in_scope(path):
    parts = path.replace(os.sep, "/").split("/")
    return bool(_SCOPED_DIRS & set(parts))


def _is_id_stream(iter_node):
    """True when the for-loop iterable reads as an id stream: a dotted
    name whose last component ends in an id spelling, or such a name
    through zip()/enumerate()/np.asarray()-style wrappers."""
    if isinstance(iter_node, ast.Call):
        return any(
            _is_id_stream(arg) for arg in iter_node.args
        )
    chain = attr_chain(iter_node)
    if not chain:
        return False
    tail = chain.rsplit(".", 1)[-1].lower()
    return tail.endswith(_ID_TAILS)


def _loop_target_names(target):
    if isinstance(target, ast.Name):
        return {target.id}
    if isinstance(target, (ast.Tuple, ast.List)):
        names = set()
        for element in target.elts:
            names |= _loop_target_names(element)
        return names
    return set()


def _key_uses(node, names):
    """The subscript/argument key derives from a loop variable —
    directly, or through int()/str()-style conversion calls."""
    for sub in ast.walk(node):
        if isinstance(sub, ast.Name) and sub.id in names:
            return True
    return False


def _growth_statements(loop, names):
    """Yield (lineno, code) for container growth keyed by ``names``
    inside the loop body (nested loops included — the loop var is
    still in scope)."""
    for node in ast.walk(loop):
        if isinstance(node, ast.Assign):
            for target in node.targets:
                if (
                    isinstance(target, ast.Subscript)
                    and _key_uses(target.slice, names)
                ):
                    chain = attr_chain(target.value) or "<container>"
                    yield node.lineno, "%s[...] =" % chain
        elif isinstance(node, ast.Call):
            func = node.func
            if (
                isinstance(func, ast.Attribute)
                and func.attr in ("setdefault", "add")
                and node.args
                and _key_uses(node.args[0], names)
            ):
                chain = attr_chain(func.value) or "<container>"
                yield node.lineno, "%s.%s()" % (chain, func.attr)


def _scope_methods(unit):
    """{qualname prefix: defined method/function names} for every class
    and the module: the eviction-entry-point lookup."""
    scopes = {"<module>": set()}
    for node in ast.walk(unit.tree):
        if isinstance(node, ast.ClassDef):
            scopes[node.name] = {
                child.name
                for child in node.body
                if isinstance(child, (ast.FunctionDef,
                                      ast.AsyncFunctionDef))
            }
        elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            scopes["<module>"].add(node.name)
    return scopes


def run(units):
    findings = []
    for unit in units:
        if not _in_scope(unit.path):
            continue
        scopes = _scope_methods(unit)
        from elasticdl_tpu.analysis.core import walk_with_scope

        for node, scope in walk_with_scope(unit.tree):
            if not isinstance(node, (ast.For, ast.AsyncFor)):
                continue
            if not _is_id_stream(node.iter):
                continue
            names = _loop_target_names(node.target)
            if not names:
                continue
            # the eviction lookup keys on the enclosing class (first
            # scope component) or the module for top-level loops
            owner = scope.split(".", 1)[0]
            defined = scopes.get(owner, scopes["<module>"])
            if defined & _EVICTION_METHODS:
                continue
            for lineno, code in _growth_statements(node, names):
                findings.append(Finding(
                    rule=RULE,
                    path=unit.path,
                    line=lineno,
                    symbol=scope,
                    code=code,
                    message=(
                        "container grows one entry per raw stream id "
                        "with no admission/eviction bound (no "
                        "drop_rows/sweep/evict/clear on %r) — an "
                        "unbounded-vocab stream leaks memory here; "
                        "bound it or route through the embedding "
                        "lifecycle" % owner
                    ),
                ))
    return findings
