"""edlint: framework-aware static analysis for elasticdl_tpu.

Five rule packs, each encoding a failure class this codebase has paid
for (or refuses to pay for):

- ``lock-discipline``     — attributes mutated under a class's
  ``threading.Lock``/``Condition`` must never be mutated off-lock
  (the sync-PS pairing race class).
- ``jax-hot-path``        — no silent host-device syncs
  (``device_get``/``.item()``/``float``/``np.asarray``), host RNG, or
  wall-clock reads inside jit/pjit-compiled or ``@hot_path`` functions.
- ``obs-hot-path``        — no logging calls or metrics-instrument
  construction (Counter/Gauge/Histogram lookup) inside hot functions;
  instruments are hoisted to module/init scope, only
  inc/set/observe on the step path.
- ``obs-span-no-context`` — no gRPC stub calls inside ``span(...)``
  blocks in modules that bypass ``build_channel``: the trace context
  propagates only through the channel interceptor, so a raw-channel
  stub call orphans the remote half of the trace.
- ``obs-bare-jit``        — no bare ``jax.jit``/``pjit`` in the
  train/ops/serve/worker scopes: compiled steps go through
  ``observability.device.instrumented_jit`` (identical when
  ``EDL_DEVICE_OBS=0``) so every recompile is counted,
  shape-attributed, and visible to the ``recompile_storm`` detector.
- ``num-silent-nonfinite`` — no ``np.nan*`` aggregations or
  ``nan_to_num`` in train/ps/worker scopes: silently masking
  nonfinite values is exactly what the ISSUE-15 health sentinels
  exist to prevent — let the NaN surface and be detected, skipped,
  or halted on.
- ``obs-deterministic-tracer`` — no ``sys.settrace`` /
  ``sys.setprofile`` / ``threading.settrace``/``setprofile`` outside
  ``observability/profiler.py`` and tests: a deterministic tracer in a
  role costs orders of magnitude more than the 29 Hz sampling
  profiler, and does it silently.
- ``ft-swallowed-except`` / ``ft-grpc-timeout`` — fault-tolerance
  hygiene: no broad except that swallows without logging/re-raising,
  no gRPC stub call without a deadline.
- ``ft-deadline-no-propagation`` — no nested stub call on a request
  path (``*Servicer`` method / ``@thread_context`` def) restarting the
  deadline clock with a fresh literal or module-default ``timeout=``;
  wrap the default in ``common.overload.rpc_timeout()`` so the caller's
  remaining budget caps the fan-out.
- ``perf-varint-ids``     — no per-element Python-loop serialization
  into repeated proto fields (``.extend(int(i) for i in ids)``); use
  the packed ``ids_blob`` wire field or ``astype().tolist()``.
- ``perf-host-gather``    — no per-id Python loops gathering embedding
  rows (``for i in ids: table[i]``) inside hot functions; use a
  vectorized gather (``table[ids]``/``np.take``) or the fused
  device-tier kernels (``ops/embedding_tier.py``).
- ``perf-io-under-lock``  — no file IO (``open``/``np.savez``/
  checkpoint-saver calls) inside a lock-guarded block in ps/ modules:
  a serialize-and-write under a push-path lock stalls every worker's
  push for the save's duration — snapshot under the lock, write
  outside it (the ISSUE-13 off-RPC checkpoint contract).
- ``serve-unbounded-queue`` — no unbounded ``queue.Queue()`` /
  ``deque()`` constructors in the serving package: the serving tier's
  contract is admission control, so every queue carries a bound
  (maxsize/maxlen) and overload sheds instead of buffering.
- ``serve-affinity-unbounded-ring`` — no per-replica/per-affinity-key
  ``self.X`` container growth in the serving package without a cleanup
  entry point (deregister/forget/remove/expire/reap/clear) on the
  owning class: replicas churn under the autoscaler, and router-side
  books keyed by replica id leak at exactly the churn rate unless a
  departure deletes them.
- ``xhost-determinism``   — no set-ordered or filesystem-ordered
  iteration in checkpoint/export/gradient-aggregation paths, where
  ordering must match across hosts.

Run ``python -m elasticdl_tpu.analysis elasticdl_tpu/``. See
docs/STATIC_ANALYSIS.md for suppressions (``# edlint: disable=<rule>``)
and the baseline workflow.
"""

from elasticdl_tpu.common.annotations import thread_context  # noqa: F401

from elasticdl_tpu.analysis.core import (  # noqa: F401
    Finding,
    RULE_NAMES,
    analyze_paths,
    analyze_sources,
    load_baseline,
    split_baselined,
)
