"""knob-registry: every EDL_* env knob goes through env_utils and is
documented.

Two finding shapes:

- ``raw-env: <KNOB>`` — an ``EDL_*`` environment read that bypasses
  ``common/env_utils`` (``os.environ[...]`` / ``os.environ.get`` /
  ``os.getenv``) anywhere outside env_utils itself. Ad-hoc parsing is
  how knobs drift: three modules grow three different int-parse
  fallbacks for the same variable.

- ``undocumented: <KNOB>`` — a knob name read anywhere in
  ``elasticdl_tpu/`` that appears in no ``docs/*.md`` knob table.
  Reported once per knob, anchored at the first read site. The docs
  corpus is discovered by walking up from the scanned files to the
  repo root (the directory holding ``docs/``); when no docs directory
  exists — synthetic unit-test sources — the documentation check is
  skipped and only raw-read findings are produced.

Knob names resolve through module-level string constants
(``_FLUSH_ENV = "EDL_X"; env_int(_FLUSH_ENV, 4)``). Dynamic names
(f-strings, templates) are skipped: the repo's dynamic reads are the
preprocessing analyzer's per-feature handoff protocol, not knobs, and
an unresolvable name can't be matched against the docs anyway.
"""

import ast
import os
import re

from elasticdl_tpu.analysis.core import Finding, attr_chain, walk_with_scope

RULE = "knob-registry"

_ENV_HELPERS = {"env_int", "env_float", "env_str", "env_bool"}
_KNOB_RE = re.compile(r"^EDL_[A-Z0-9_]+$")


def _module_consts(tree):
    consts = {}
    for stmt in tree.body:
        if (
            isinstance(stmt, ast.Assign)
            and len(stmt.targets) == 1
            and isinstance(stmt.targets[0], ast.Name)
            and isinstance(stmt.value, ast.Constant)
            and isinstance(stmt.value.value, str)
        ):
            consts[stmt.targets[0].id] = stmt.value.value
    return consts


def _knob_name(node, consts):
    """Resolve a knob-name expression to a string, or None."""
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    if isinstance(node, ast.Name):
        return consts.get(node.id)
    return None


def _docs_corpus(units):
    """Concatenated docs/*.md text, discovered by walking up from the
    scanned files; None when no docs directory is reachable."""
    for unit in units:
        probe = os.path.dirname(os.path.abspath(unit.path))
        for _ in range(8):
            docs = os.path.join(probe, "docs")
            if os.path.isdir(docs):
                chunks = []
                for name in sorted(os.listdir(docs)):
                    if name.endswith(".md"):
                        try:
                            with open(
                                os.path.join(docs, name),
                                "r", encoding="utf-8",
                            ) as f:
                                chunks.append(f.read())
                        except OSError:
                            continue
                if chunks:
                    return "\n".join(chunks)
            parent = os.path.dirname(probe)
            if parent == probe:
                break
            probe = parent
    return None


def run(units):
    findings = []
    # (knob, unit, line, symbol) of every read, in scan order
    reads = []
    for unit in units:
        if unit.module.endswith("common.env_utils"):
            continue
        consts = _module_consts(unit.tree)
        for node, scope in walk_with_scope(unit.tree):
            # raw subscript read: os.environ["EDL_X"]
            if isinstance(node, ast.Subscript) and isinstance(
                node.ctx, ast.Load
            ):
                chain = attr_chain(node.value)
                if chain in ("os.environ", "environ"):
                    knob = _knob_name(node.slice, consts)
                    if knob is not None and _KNOB_RE.match(knob):
                        findings.append(Finding(
                            RULE, unit.path, node.lineno, scope,
                            "raw-env: %s" % (knob or "<dynamic>"),
                            "EDL knob read bypasses common/env_utils — "
                            "use env_int/env_float/env_str/env_bool so "
                            "parsing and fallbacks stay uniform",
                        ))
                        reads.append((knob, unit, node.lineno, scope))
                continue
            if not isinstance(node, ast.Call):
                continue
            chain = attr_chain(node.func)
            if chain is None or not node.args:
                continue
            tail = chain.split(".")[-1]
            if chain in ("os.environ.get", "environ.get", "os.getenv",
                         "getenv"):
                knob = _knob_name(node.args[0], consts)
                if knob is None or not _KNOB_RE.match(knob):
                    # non-EDL env var, or a dynamic name (the analyzer
                    # handoff protocol): not a knob — not auditable
                    continue
                findings.append(Finding(
                    RULE, unit.path, node.lineno, scope,
                    "raw-env: %s" % knob,
                    "EDL knob read bypasses common/env_utils — use "
                    "env_int/env_float/env_str/env_bool so parsing and "
                    "fallbacks stay uniform",
                ))
                reads.append((knob, unit, node.lineno, scope))
            elif tail in _ENV_HELPERS:
                knob = _knob_name(node.args[0], consts)
                if knob:
                    reads.append((knob, unit, node.lineno, scope))

    corpus = _docs_corpus(units)
    if corpus is not None:
        reported = set()
        for knob, unit, line, scope in reads:
            if knob in reported:
                continue
            reported.add(knob)
            if knob not in corpus:
                findings.append(Finding(
                    RULE, unit.path, line, scope,
                    "undocumented: %s" % knob,
                    "knob %s is read here but appears in no docs/*.md "
                    "knob table — document the default, the unit, and "
                    "which role consumes it" % knob,
                ))
    return findings
