"""conc-* rules: whole-program concurrency checks on the call graph.

- ``conc-lock-order``: build the global lock-acquisition-order graph
  (edge L1 -> L2 when L2 is acquired — directly or through any resolved
  call chain — while L1 is held) and flag every cycle: a potential ABBA
  deadlock across modules. Lock identity is the class-qualified
  attribute name, so two instances of one class conflate; self-edges
  are therefore skipped, not reported.

- ``conc-blocking-under-lock``: generalizes the per-module
  ``perf-io-under-lock`` by propagating blocking effects (gRPC, file
  I/O, sleep, unbounded queue/future/join waits, subprocess) through
  the call graph: a helper that does gRPC I/O is flagged when reachable
  with a lock held, even several calls deep. A ``Condition.wait()`` on
  the lock it releases is the cv pattern and exempt.

- ``conc-thread-context``: checks declared execution-context contracts
  (``# edlint: thread=<name>`` / ``@thread_context("<name>")``). A call
  edge into a declared function from code whose inferred context set
  contains anything else is flagged — passing the function as a VALUE
  (Thread target, executor submit, queue, callback) is a handoff and
  never flagged. Signal handlers (``signal.signal`` registrations) are
  reentrant contexts: transitively acquiring any lock or blocking is
  flagged once per (handler, lock) / (handler, effect-category).

All three degrade explicitly: unresolved callees are counted once per
run (``CallGraph.unknown_summary``) and surfaced by the CLI, never
treated as safe silently — see docs/STATIC_ANALYSIS.md.
"""

from elasticdl_tpu.analysis.callgraph import build_graph
from elasticdl_tpu.analysis.core import Finding

LOCK_ORDER_RULE = "conc-lock-order"
BLOCKING_RULE = "conc-blocking-under-lock"
CONTEXT_RULE = "conc-thread-context"


def run_lock_order(units):
    graph = build_graph(units)
    findings = []
    for cycle in graph.lock_cycles():
        locks = cycle["locks"]
        code = "cycle: " + " -> ".join(locks + [locks[0]])
        edge_bits = []
        for (held, acquired), provs in list(cycle["edges"].items())[:4]:
            prov = provs[0]
            edge_bits.append("%s->%s at %s:%d" % (
                held, acquired, prov["path"], prov["line"]
            ))
        (_, _), provs = next(iter(cycle["edges"].items()))
        anchor = provs[0]
        findings.append(Finding(
            LOCK_ORDER_RULE, anchor["path"], anchor["line"],
            anchor["symbol"], code,
            "lock-order cycle (potential ABBA deadlock): %s" % (
                "; ".join(edge_bits)
            ),
        ))
    return findings


def run_blocking_under_lock(units):
    graph = build_graph(units)
    findings = []
    seen = set()

    def emit(finfo, line, lock, code, message):
        fp = (finfo.key, lock, code)
        if fp in seen:
            return
        seen.add(fp)
        findings.append(Finding(
            BLOCKING_RULE, finfo.unit.path, line, finfo.qualname,
            "%s under %s" % (code, lock), message,
        ))

    for key in sorted(graph.functions):
        finfo = graph.functions[key]
        for eff in finfo.blocking:
            for lock in eff.held:
                emit(
                    finfo, eff.line, lock, eff.code,
                    "blocking %s call %s while holding %s — every thread "
                    "contending on the lock stalls for the call's duration"
                    % (eff.category, eff.code, lock),
                )
        for site in finfo.calls:
            if not site.held:
                continue
            for callee in site.callees:
                blocking = graph.transitive_blocking(callee)
                if not blocking:
                    continue
                (cat, code), path = sorted(blocking.items())[0]
                chain = " -> ".join(
                    graph.functions[k].short for k in path
                )
                for lock in site.held:
                    emit(
                        finfo, site.line, lock,
                        "%s via %s" % (code, graph.functions[callee].name),
                        "call %s while holding %s reaches blocking %s "
                        "call %s (%d hop%s: %s)" % (
                            site.display, lock, cat, code, len(path),
                            "s" if len(path) != 1 else "", chain,
                        ),
                    )
    return findings


def run_thread_context(units):
    graph = build_graph(units)
    contexts = graph.contexts()
    findings = []
    seen = set()

    # 1) call edges that cross into a declared context
    for key in sorted(graph.functions):
        finfo = graph.functions[key]
        for site in finfo.calls:
            for callee in site.callees:
                target = graph.functions[callee]
                contract = target.thread_context
                if not contract:
                    continue
                if finfo.thread_context:
                    bad = {finfo.thread_context} - {contract}
                else:
                    bad = set(contexts.get(key, ())) - {contract}
                if not bad:
                    continue
                fp = (key, callee, tuple(sorted(bad)))
                if fp in seen:
                    continue
                seen.add(fp)
                findings.append(Finding(
                    CONTEXT_RULE, finfo.unit.path, site.line,
                    finfo.qualname,
                    "%s[%s] from %s" % (
                        target.name, contract, ",".join(sorted(bad))
                    ),
                    "%s is declared thread=%s but this call edge runs in "
                    "context(s) %s — hand off through a queue/executor/"
                    "flag instead of calling across threads" % (
                        target.short, contract, ", ".join(sorted(bad))
                    ),
                ))

    # 2) reentrant (signal) entries must take no locks and never block
    handled = set()
    for entry in graph.entries:
        if not entry.reentrant or entry.key in handled:
            continue
        handled.add(entry.key)
        finfo = graph.functions.get(entry.key)
        if finfo is None:
            continue
        for lock, path in sorted(graph.transitive_acquires(entry.key).items()):
            chain = " -> ".join(graph.functions[k].short for k in path)
            findings.append(Finding(
                CONTEXT_RULE, finfo.unit.path, finfo.node.lineno,
                finfo.qualname, "signal-lock: %s" % lock,
                "signal handler %s acquires %s (%s) — a handler may "
                "interrupt the very code holding that lock; handlers "
                "must be reentrant-safe (set a flag, do the work on a "
                "normal thread)" % (finfo.name, lock, chain),
            ))
        by_category = {}
        for (cat, code), path in sorted(graph.transitive_blocking(entry.key).items()):
            by_category.setdefault(cat, (code, path))
        for cat, (code, path) in sorted(by_category.items()):
            chain = " -> ".join(graph.functions[k].short for k in path)
            findings.append(Finding(
                CONTEXT_RULE, finfo.unit.path, finfo.node.lineno,
                finfo.qualname, "signal-blocking: %s" % cat,
                "signal handler %s reaches blocking %s call %s (%s) — "
                "handlers must not block; defer to a flag polled off "
                "the signal path" % (finfo.name, cat, code, chain),
            ))
    return findings
