"""num-silent-nonfinite: no NaN-swallowing aggregations in hot scopes.

The training-health sentinels (ISSUE 15, ``train/health.py``) exist
because a NaN batch must be LOUD: detected in-graph, journaled, and
either alerted, skipped, or halted — never silently absorbed. numpy's
``nan*`` family (``nanmean``/``nansum``/``nanmax``/...) and
``nan_to_num`` do exactly the opposite: they make nonfinite values
disappear inside an aggregation, so a corrupted gradient or loss
averages into a plausible number and trains on. A ``nan_to_num`` on a
pushed gradient is the canonical anti-pattern — it converts "the
sentinel would have fired" into "row 12345 silently got a zero
update".

What fires, in files under a ``train/``, ``ps/``, or ``worker/``
package directory only: any call whose target is a ``nan*``
aggregation or ``nan_to_num`` — attribute-style through any module
alias (``np.nanmean``, ``numpy.nansum``, ``jnp.nan_to_num``) or a bare
name bound by ``from numpy import nanmean``.

Legitimate uses (e.g. summarizing a metrics array that encodes
"absent" as NaN by design) are one
``# edlint: disable=num-silent-nonfinite`` away, with the
justification the suppression comment forces. Scripts, tests, and the
analysis package itself are out of scope — the rule pins the training
data path, not reporting tools.
"""

import ast
import os

from elasticdl_tpu.analysis.core import (
    Finding,
    attr_chain,
    walk_with_scope,
)

RULE = "num-silent-nonfinite"

_SCOPED_DIRS = {"train", "ps", "worker"}

_NAN_FUNCS = frozenset({
    "nanmean", "nansum", "nanmax", "nanmin", "nanstd", "nanvar",
    "nanprod", "nanmedian", "nanpercentile", "nanquantile",
    "nanargmax", "nanargmin", "nancumsum", "nancumprod",
    "nan_to_num",
})

# modules whose nan* members count: numpy/jax.numpy under any alias is
# caught by the member NAME (the chains below are only used to catch
# `from numpy import nanmean` rebinding)
_NAN_MODULES = ("numpy", "jax.numpy")


def _in_scope(path):
    parts = path.replace(os.sep, "/").split("/")
    return bool(_SCOPED_DIRS & set(parts))


def _nan_imports(tree):
    """Bare names bound to a nan* aggregation by ``from numpy import
    nanmean``-style imports (aliases included)."""
    bound = set()
    for node in ast.walk(tree):
        if (
            isinstance(node, ast.ImportFrom)
            and node.module in _NAN_MODULES
        ):
            for alias in node.names:
                if alias.name in _NAN_FUNCS:
                    bound.add(alias.asname or alias.name)
    return bound


def run(units):
    findings = []
    for unit in units:
        if not _in_scope(unit.path):
            continue
        bare_names = _nan_imports(unit.tree)
        for node, scope in walk_with_scope(unit.tree):
            if not isinstance(node, ast.Call):
                continue
            func = node.func
            code = None
            if isinstance(func, ast.Attribute) and func.attr in _NAN_FUNCS:
                chain = attr_chain(func)
                code = chain or func.attr
            elif isinstance(func, ast.Name) and func.id in bare_names:
                code = func.id
            if code is None:
                continue
            findings.append(
                Finding(
                    rule=RULE,
                    path=unit.path,
                    line=node.lineno,
                    symbol=scope,
                    code=code,
                    message=(
                        "%s silently masks nonfinite values — exactly "
                        "what the health sentinels exist to catch. "
                        "Let the NaN surface (EDL_HEALTH detects it "
                        "in-graph) or mask explicitly with a boolean "
                        "mask whose coverage is asserted" % code
                    ),
                )
            )
    return findings
