"""obs-bare-jit: a ``jax.jit``/``pjit`` call outside the ISSUE-18
recompile sentinel, on a module the sentinel is contractually wired
through.

``observability/device.instrumented_jit`` is the ONLY sanctioned way
to build a compiled step in the training / serving / worker scopes:
it is byte-identical to ``jax.jit`` when ``EDL_DEVICE_OBS=0``, and
with it on it is what makes a steady-state recompile *observable* —
counted, shape-attributed, journaled, and visible to the master's
``recompile_storm`` detector. A bare ``jax.jit`` in these scopes is a
blind spot: its recompiles happen, stall steps, and never show up
anywhere. The CI gate "zero unexpected recompiles after warmup" is
only as strong as this rule's zero-findings gate.

What fires: any call whose callee's leaf name is ``jit`` or ``pjit``
(``jax.jit(...)``, ``jit(...)``, ``jax.experimental.pjit.pjit(...)``)
— including inside ``partial(jax.jit, ...)`` and as a decorator — in
a module whose dotted name starts with ``elasticdl_tpu.train.``,
``elasticdl_tpu.ops.``, ``elasticdl_tpu.serve.`` or
``elasticdl_tpu.worker.``. The ``parallel/`` research trainers are
deliberately out of scope (not on the elastic worker's step path),
and ``observability/device.py`` itself is where the one legitimate
``jax.jit`` call lives.

Legitimate exceptions exist — ``train_state.create_train_state``'s
init jit must inline inside outer traces, where the sentinel's host
bookkeeping cannot run — and are one
``# edlint: disable=obs-bare-jit`` away, with the reason on the same
lines the suppression covers.
"""

import ast

from elasticdl_tpu.analysis.core import (
    Finding,
    attr_chain,
    walk_with_scope,
)

RULE = "obs-bare-jit"

_JIT_LEAVES = {"jit", "pjit"}

_SCOPE_PREFIXES = (
    "elasticdl_tpu.train.",
    "elasticdl_tpu.ops.",
    "elasticdl_tpu.serve.",
    "elasticdl_tpu.worker.",
)


def _in_scope(module):
    return any(module.startswith(p) for p in _SCOPE_PREFIXES)


def _jit_leaf(func):
    """'jit'/'pjit' when ``func`` resolves to a bare jit factory,
    else None. ``instrumented_jit`` has a different leaf name and
    never matches."""
    if isinstance(func, ast.Name):
        return func.id if func.id in _JIT_LEAVES else None
    chain = attr_chain(func)
    if chain is None:
        return None
    leaf = chain.split(".")[-1]
    return leaf if leaf in _JIT_LEAVES else None


def run(units):
    findings = []
    for unit in units:
        if not _in_scope(unit.module):
            continue
        for node, scope in walk_with_scope(unit.tree):
            targets = []
            if isinstance(node, ast.Call):
                leaf = _jit_leaf(node.func)
                if leaf:
                    targets.append((node, leaf))
                else:
                    # partial(jax.jit, ...) builds a bare jit factory
                    chain = attr_chain(node.func)
                    if (
                        chain
                        and chain.split(".")[-1] == "partial"
                        and node.args
                    ):
                        leaf = _jit_leaf(node.args[0])
                        if leaf:
                            targets.append((node, leaf))
            elif isinstance(
                node, (ast.FunctionDef, ast.AsyncFunctionDef)
            ):
                # @jax.jit / @pjit decorators (bare, no call)
                for dec in node.decorator_list:
                    if not isinstance(dec, ast.Call):
                        leaf = _jit_leaf(dec)
                        if leaf:
                            targets.append((dec, leaf))
            for target, leaf in targets:
                findings.append(
                    Finding(
                        rule=RULE,
                        path=unit.path,
                        line=target.lineno,
                        symbol=scope,
                        code="%s()" % leaf,
                        message=(
                            "bare %s in an instrumented scope: use "
                            "observability.device.instrumented_jit "
                            "so recompiles are counted, "
                            "shape-attributed, and visible to the "
                            "recompile_storm detector (identical to "
                            "jax.jit when EDL_DEVICE_OBS=0)" % leaf
                        ),
                    )
                )
    return findings
