"""Fault-tolerance hygiene: swallowed exceptions and deadline-less RPCs.

``ft-swallowed-except`` — a bare ``except:`` or broad
``except Exception/BaseException`` whose body neither re-raises nor
logs hides the failure from the fault-tolerance machinery: the task
isn't reported failed, the pod isn't relaunched, the job wedges
silently. Narrow excepts (``except KeyError``) are a handled case, not
a swallow, and are not flagged.

``ft-grpc-timeout`` — a gRPC stub call without ``timeout=`` blocks
forever when the peer hangs (a half-dead PS pod holds its socket open
without serving); every stub call must carry a deadline so the retry/
recovery path gets control. Framework-aware heuristic: a call
``<recv>.<method>(...)`` counts as a stub call when the receiver
name chain contains "stub" (``self._stub.get_task``,
``stub.push_gradients``, ``self._stubs[i].pull``) — the naming
convention this repo uses for every generated-client handle.
"""

import ast

from elasticdl_tpu.analysis.core import Finding, attr_chain, walk_with_scope

_BROAD = {"Exception", "BaseException"}
_LOGGING_METHODS = {
    "debug", "info", "warning", "warn", "error", "exception", "critical",
    "log",
}


def _is_broad_handler(handler):
    if handler.type is None:
        return True

    def broad(node):
        chain = attr_chain(node)
        return chain is not None and chain.split(".")[-1] in _BROAD

    if isinstance(handler.type, ast.Tuple):
        return any(broad(elt) for elt in handler.type.elts)
    return broad(handler.type)


def _body_surfaces_error(handler):
    """True if the handler re-raises, logs, or prints."""
    for node in ast.walk(handler):
        if isinstance(node, ast.Raise):
            return True
        if isinstance(node, ast.Call):
            func = node.func
            if isinstance(func, ast.Name) and func.id == "print":
                return True
            if (
                isinstance(func, ast.Attribute)
                and func.attr in _LOGGING_METHODS
            ):
                return True
    return False


def run_swallowed_except(units):
    findings = []
    for unit in units:
        for node, scope in walk_with_scope(unit.tree):
            if not isinstance(node, ast.ExceptHandler):
                continue
            if not _is_broad_handler(node):
                continue
            if _body_surfaces_error(node):
                continue
            caught = (
                "bare except" if node.type is None
                else "except %s" % (attr_chain(node.type) or "Exception")
                if not isinstance(node.type, ast.Tuple)
                else "broad except tuple"
            )
            findings.append(
                Finding(
                    rule="ft-swallowed-except",
                    path=unit.path,
                    line=node.lineno,
                    symbol=scope,
                    code=caught,
                    message=(
                        "%s swallows the error without logging or "
                        "re-raising; fault tolerance never hears about "
                        "it — log-and-degrade or re-raise" % caught
                    ),
                )
            )
    return findings


def run_grpc_timeout(units):
    findings = []
    for unit in units:
        for node, scope in walk_with_scope(unit.tree):
            if not isinstance(node, ast.Call):
                continue
            func = node.func
            if not isinstance(func, ast.Attribute):
                continue
            receiver = attr_chain(func.value)
            if receiver is None or "stub" not in receiver.lower():
                continue
            # constructor / channel plumbing, not an RPC
            if func.attr.startswith("_") or func.attr in ("close",):
                continue
            if any(kw.arg == "timeout" for kw in node.keywords):
                continue
            findings.append(
                Finding(
                    rule="ft-grpc-timeout",
                    path=unit.path,
                    line=node.lineno,
                    symbol=scope,
                    code="%s.%s" % (receiver, func.attr),
                    message=(
                        "gRPC call %s.%s() has no timeout=; a hung peer "
                        "blocks this caller forever — add a deadline"
                        % (receiver, func.attr)
                    ),
                )
            )
    return findings
