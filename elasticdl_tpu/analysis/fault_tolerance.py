"""Fault-tolerance hygiene: swallowed exceptions and deadline-less RPCs.

``ft-swallowed-except`` — a bare ``except:`` or broad
``except Exception/BaseException`` whose body neither re-raises nor
logs hides the failure from the fault-tolerance machinery: the task
isn't reported failed, the pod isn't relaunched, the job wedges
silently. Narrow excepts (``except KeyError``) are a handled case, not
a swallow, and are not flagged.

``ft-grpc-timeout`` — a gRPC stub call without ``timeout=`` blocks
forever when the peer hangs (a half-dead PS pod holds its socket open
without serving); every stub call must carry a deadline so the retry/
recovery path gets control. Framework-aware heuristic: a call
``<recv>.<method>(...)`` counts as a stub call when the receiver
name chain contains "stub" (``self._stub.get_task``,
``stub.push_gradients``, ``self._stubs[i].pull``) — the naming
convention this repo uses for every generated-client handle.

``ft-sigterm-no-chain`` — a ``signal.signal(SIGTERM, handler)``
registration in a scope that never calls ``signal.getsignal`` silently
REPLACES whatever handler was installed before it. SIGTERM hooks in
this codebase compose in a chain (flight-recorder ring dump ->
graceful drain -> exit, observability/events.py + worker/drain.py), so
an overwriting registration severs the links behind it — the drain
hook must capture the previous handler (``getsignal``) and call it.

``ft-deadline-no-propagation`` — a gRPC stub call made FROM a request
path (a method of a ``*Servicer`` class, or a function carrying a
``@thread_context`` contract — the repo's marker for code running on a
server/executor thread) that passes a fresh numeric-literal or
module-default ``timeout=`` instead of the propagated deadline budget.
The caller that fanned out to this code had a deadline; restarting the
clock here lets a nested RPC outlive it, so the client gives up, the
server keeps burning PS capacity on an answer nobody is waiting for,
and under overload that zombie work IS the collapse. Wrap the default
in ``common.overload.rpc_timeout(default)`` (caps by the remaining
caller budget carried in thread-local state / the
``edl-deadline-budget`` header) or pass a value derived from it.
Timeouts already computed in a Name or any call expression are trusted
as derived.

``ft-retry-no-jitter`` — a retry loop that sleeps a deterministically
GROWING backoff (``delay``, then ``delay = min(delay * 2, cap)``)
without any randomness retries in lockstep across a fleet: every
worker that lost the same PS at the same moment re-arrives at the same
instants, re-forming the thundering herd at each interval. Heuristic:
a ``while``/``for`` loop that (a) sleeps a Name, (b) reassigns that
Name multiplicatively inside the same loop, and (c) contains no
randomness (``random``/``uniform``/``jitter``/``retry_call``) — use
``common.grpc_utils.retry_call`` (full jitter) instead.
"""

import ast

from elasticdl_tpu.analysis.core import Finding, attr_chain, walk_with_scope

_BROAD = {"Exception", "BaseException"}
_LOGGING_METHODS = {
    "debug", "info", "warning", "warn", "error", "exception", "critical",
    "log",
}


def _is_broad_handler(handler):
    if handler.type is None:
        return True

    def broad(node):
        chain = attr_chain(node)
        return chain is not None and chain.split(".")[-1] in _BROAD

    if isinstance(handler.type, ast.Tuple):
        return any(broad(elt) for elt in handler.type.elts)
    return broad(handler.type)


def _body_surfaces_error(handler):
    """True if the handler re-raises, logs, or prints."""
    for node in ast.walk(handler):
        if isinstance(node, ast.Raise):
            return True
        if isinstance(node, ast.Call):
            func = node.func
            if isinstance(func, ast.Name) and func.id == "print":
                return True
            if (
                isinstance(func, ast.Attribute)
                and func.attr in _LOGGING_METHODS
            ):
                return True
    return False


def run_swallowed_except(units):
    findings = []
    for unit in units:
        for node, scope in walk_with_scope(unit.tree):
            if not isinstance(node, ast.ExceptHandler):
                continue
            if not _is_broad_handler(node):
                continue
            if _body_surfaces_error(node):
                continue
            caught = (
                "bare except" if node.type is None
                else "except %s" % (attr_chain(node.type) or "Exception")
                if not isinstance(node.type, ast.Tuple)
                else "broad except tuple"
            )
            findings.append(
                Finding(
                    rule="ft-swallowed-except",
                    path=unit.path,
                    line=node.lineno,
                    symbol=scope,
                    code=caught,
                    message=(
                        "%s swallows the error without logging or "
                        "re-raising; fault tolerance never hears about "
                        "it — log-and-degrade or re-raise" % caught
                    ),
                )
            )
    return findings


_JITTER_MARKERS = ("random", "uniform", "jitter", "retry_call", "randint")


def _slept_names(loop):
    """Names passed to time.sleep()/sleep() inside a loop body."""
    names = set()
    for node in ast.walk(loop):
        if not isinstance(node, ast.Call) or not node.args:
            continue
        chain = attr_chain(node.func)
        if chain is None or chain.split(".")[-1] != "sleep":
            continue
        if isinstance(node.args[0], ast.Name):
            names.add(node.args[0].id)
    return names


def _grows_multiplicatively(loop, name):
    """True when ``name`` is reassigned inside the loop via a value
    containing a multiplication (the exponential-backoff shape,
    including ``min(delay * 2, cap)``)."""
    for node in ast.walk(loop):
        value = None
        if isinstance(node, ast.Assign) and any(
            isinstance(t, ast.Name) and t.id == name for t in node.targets
        ):
            value = node.value
        elif (
            isinstance(node, ast.AugAssign)
            and isinstance(node.target, ast.Name)
            and node.target.id == name
        ):
            if isinstance(node.op, ast.Mult):
                return True
            value = node.value
        if value is None:
            continue
        for sub in ast.walk(value):
            if isinstance(sub, ast.BinOp) and isinstance(sub.op, ast.Mult):
                return True
    return False


def _has_jitter(loop):
    for node in ast.walk(loop):
        chain = None
        if isinstance(node, ast.Call):
            chain = attr_chain(node.func)
        elif isinstance(node, ast.Name):
            chain = node.id
        if chain is None:
            continue
        lowered = chain.lower()
        if any(marker in lowered for marker in _JITTER_MARKERS):
            return True
    return False


def run_retry_no_jitter(units):
    findings = []
    for unit in units:
        for node, scope in walk_with_scope(unit.tree):
            if not isinstance(node, (ast.While, ast.For)):
                continue
            for name in sorted(_slept_names(node)):
                if not _grows_multiplicatively(node, name):
                    continue
                if _has_jitter(node):
                    continue
                findings.append(
                    Finding(
                        rule="ft-retry-no-jitter",
                        path=unit.path,
                        line=node.lineno,
                        symbol=scope,
                        code="backoff: %s" % name,
                        message=(
                            "retry loop sleeps a deterministically "
                            "growing backoff (%r) with no jitter; a "
                            "fleet retries in lockstep (thundering "
                            "herd) — use common.grpc_utils.retry_call "
                            "or add a uniform draw" % name
                        ),
                    )
                )
    return findings


def _mentions_sigterm(node):
    for sub in ast.walk(node):
        if isinstance(sub, ast.Attribute) and sub.attr == "SIGTERM":
            return True
        if isinstance(sub, ast.Name) and sub.id == "SIGTERM":
            return True
    return False


def run_sigterm_no_chain(units):
    findings = []
    for unit in units:
        # scopes that capture the previous handler
        chaining_scopes = set()
        for node, scope in walk_with_scope(unit.tree):
            if isinstance(node, ast.Call):
                chain = attr_chain(node.func)
                if chain is not None and (
                    chain.split(".")[-1] == "getsignal"
                ):
                    chaining_scopes.add(scope)
        for node, scope in walk_with_scope(unit.tree):
            if not isinstance(node, ast.Call) or len(node.args) < 2:
                continue
            chain = attr_chain(node.func)
            if chain is None or chain.split(".")[-1] != "signal":
                continue
            if not _mentions_sigterm(node.args[0]):
                continue
            if scope in chaining_scopes:
                continue
            findings.append(
                Finding(
                    rule="ft-sigterm-no-chain",
                    path=unit.path,
                    line=node.lineno,
                    symbol=scope,
                    code="signal.signal(SIGTERM)",
                    message=(
                        "SIGTERM handler registered without capturing "
                        "the previous one (signal.getsignal); this "
                        "severs the crash-hook/drain chain — capture "
                        "and call the prior handler"
                    ),
                )
            )
    return findings


def _budget_scopes(tree):
    """Qualnames of defs that run on a request/executor path: methods
    of a ``*Servicer`` class, plus any def decorated with
    ``@thread_context(...)`` (the repo's thread-contract marker)."""
    scopes = set()
    for node, scope in walk_with_scope(tree):
        if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        qual = scope.split(".")
        if len(qual) >= 2 and "Servicer" in qual[-2]:
            scopes.add(scope)
            continue
        for dec in node.decorator_list:
            target = dec.func if isinstance(dec, ast.Call) else dec
            chain = attr_chain(target)
            if chain is not None and (
                chain.split(".")[-1] == "thread_context"
            ):
                scopes.add(scope)
                break
    return scopes


def _fresh_timeout(value):
    """The timeout shapes that restart the deadline clock: a numeric
    literal, or a bare module-default constant (``GRPC.DEFAULT_*``).
    A Name or any call expression is trusted as a derived deadline."""
    if isinstance(value, ast.Constant) and isinstance(
        value.value, (int, float)
    ):
        return repr(value.value)
    chain = attr_chain(value)
    if chain is not None and "DEFAULT" in chain.split(".")[-1].upper():
        return chain
    return None


def run_deadline_no_propagation(units):
    findings = []
    for unit in units:
        scopes = _budget_scopes(unit.tree)
        if not scopes:
            continue
        for node, scope in walk_with_scope(unit.tree):
            if scope not in scopes or not isinstance(node, ast.Call):
                continue
            func = node.func
            if not isinstance(func, ast.Attribute):
                continue
            receiver = attr_chain(func.value)
            if receiver is None or "stub" not in receiver.lower():
                continue
            if func.attr.startswith("_") or func.attr in ("close",):
                continue
            for kw in node.keywords:
                if kw.arg != "timeout":
                    continue
                fresh = _fresh_timeout(kw.value)
                if fresh is None:
                    continue
                findings.append(
                    Finding(
                        rule="ft-deadline-no-propagation",
                        path=unit.path,
                        line=node.lineno,
                        symbol=scope,
                        code="%s.%s(timeout=%s)"
                        % (receiver, func.attr, fresh),
                        message=(
                            "nested RPC %s.%s() on a request path "
                            "restarts the deadline clock with "
                            "timeout=%s; it can outlive the caller's "
                            "budget and burn capacity on abandoned "
                            "work — wrap the default in "
                            "common.overload.rpc_timeout()"
                            % (receiver, func.attr, fresh)
                        ),
                    )
                )
    return findings


def run_grpc_timeout(units):
    findings = []
    for unit in units:
        for node, scope in walk_with_scope(unit.tree):
            if not isinstance(node, ast.Call):
                continue
            func = node.func
            if not isinstance(func, ast.Attribute):
                continue
            receiver = attr_chain(func.value)
            if receiver is None or "stub" not in receiver.lower():
                continue
            # constructor / channel plumbing, not an RPC
            if func.attr.startswith("_") or func.attr in ("close",):
                continue
            if any(kw.arg == "timeout" for kw in node.keywords):
                continue
            findings.append(
                Finding(
                    rule="ft-grpc-timeout",
                    path=unit.path,
                    line=node.lineno,
                    symbol=scope,
                    code="%s.%s" % (receiver, func.attr),
                    message=(
                        "gRPC call %s.%s() has no timeout=; a hung peer "
                        "blocks this caller forever — add a deadline"
                        % (receiver, func.attr)
                    ),
                )
            )
    return findings
