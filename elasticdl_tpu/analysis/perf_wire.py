"""perf-varint-ids: per-element Python-loop serialization into
repeated proto fields.

The idiom this rule exists for (the pre-ISSUE-5 wire path's per-step
hot cost):

    slices.ids.extend(int(i) for i in ids)

Filling a ``repeated int64`` field from a Python generator/compreension
walks every id through the interpreter AND re-encodes 8-byte ids as
1-10 varint bytes each. The fixes are mechanical: the packed
``ids_blob`` wire field (``tensor_utils.pack_ids`` — one vectorized
``astype().tobytes()``) or, where the repeated field must stay,
``ids.astype(np.int64).tolist()`` so the element conversion happens in
numpy, not a Python loop.

Flagged anywhere (not only in resolved-hot functions): serialization
helpers are rarely decorated ``@hot_path`` themselves but always run
on the step path of whoever calls them, and the construct has no
correct-but-slow use worth keeping.

What fires: ``<expr>.extend(<generator or comprehension>)`` whose
element expression wraps each item in a scalar conversion
(``int(...)``/``float(...)``) — the signature of feeding a proto
repeated scalar field element-by-element. A comprehension that does
real per-element WORK (conditions, arithmetic) is left alone.
"""

import ast

from elasticdl_tpu.analysis.core import Finding, walk_with_scope

RULE = "perf-varint-ids"

_SCALAR_CASTS = {"int", "float"}


def _is_scalar_cast_comprehension(node):
    """True for ``int(i) for i in xs`` / ``[float(v) for v in xs]``:
    a single-generator, condition-free comprehension whose element is
    just a scalar cast of the loop variable."""
    if not isinstance(node, (ast.GeneratorExp, ast.ListComp)):
        return False
    if len(node.generators) != 1 or node.generators[0].ifs:
        return False
    elt = node.elt
    if not (
        isinstance(elt, ast.Call)
        and isinstance(elt.func, ast.Name)
        and elt.func.id in _SCALAR_CASTS
        and len(elt.args) == 1
        and not elt.keywords
    ):
        return False
    return isinstance(elt.args[0], ast.Name)


def run(units):
    findings = []
    for unit in units:
        for node, scope in walk_with_scope(unit.tree):
            if not isinstance(node, ast.Call):
                continue
            func = node.func
            if not (
                isinstance(func, ast.Attribute) and func.attr == "extend"
            ):
                continue
            if len(node.args) != 1:
                continue
            if not _is_scalar_cast_comprehension(node.args[0]):
                continue
            cast = node.args[0].elt.func.id
            findings.append(
                Finding(
                    rule=RULE,
                    path=unit.path,
                    line=node.lineno,
                    symbol=scope,
                    code=".extend(%s(...))" % cast,
                    message=(
                        "per-element Python-loop serialization: "
                        ".extend(%s(x) for x in ...) walks every "
                        "element through the interpreter (and varint-"
                        "encodes repeated proto ints one by one); use "
                        "the packed ids_blob wire field "
                        "(tensor_utils.pack_ids) or "
                        "arr.astype(...).tolist()" % cast
                    ),
                )
            )
    return findings
