"""jax-hot-path: host syncs / host RNG / wall clock inside compiled code.

A function is **hot** when any of:

- it is decorated ``@jax.jit`` / ``@jit`` / ``@pjit`` /
  ``@partial(jax.jit, ...)`` or ``@hot_path``
  (``elasticdl_tpu.common.annotations.hot_path`` — the zero-cost marker
  for functions that run on the step path but are compiled indirectly);
- its NAME is passed to a ``jax.jit(...)``/``pjit(...)`` call;
- it is returned by a factory whose call result is jitted
  (``jax.jit(make_train_step(...))`` marks ``make_train_step``'s
  returned inner function) — resolved across modules through
  ``from x import y`` imports, because trainers jit factories defined
  in train/step_fns.py;
- it is a lambda passed to ``jax.jit`` directly;
- a ``@hot_path``-decorated factory's returned inner functions.

Inside a hot function (nested defs included) these calls are flagged —
each forces a device fence, host transfer, or per-trace host effect:

- ``jax.device_get`` / ``.item()`` / ``float(...)`` /
  ``np.asarray(...)`` — host-device syncs
- ``.block_until_ready()`` — explicit fence
- ``np.random.*`` — host RNG baked in at trace time (use jax.random)
- ``time.time()`` / ``time.perf_counter()`` / ``time.monotonic()`` —
  wall clock frozen at trace time
"""

import ast

from elasticdl_tpu.analysis.core import (
    Finding,
    attr_chain,
    walk_with_scope,
)

RULE = "jax-hot-path"

_JIT_NAMES = {"jit", "pjit", "instrumented_jit"}
_TIME_CALLS = {"time.time", "time.perf_counter", "time.monotonic"}
_SYNC_CALLS = {"jax.device_get", "np.asarray", "numpy.asarray"}
# int() stays legal: hot functions routinely int() static config
# (grad_accum_steps, capacity factors); float() has no such static use
# in step code and is the classic accidental concretization
_CAST_CALLS = {"float"}
_SYNC_METHODS = {"item", "block_until_ready"}


def _is_jit_callee(func):
    """True for jit / pjit / jax.jit / jax.experimental.pjit.pjit."""
    if isinstance(func, ast.Name):
        return func.id in _JIT_NAMES
    chain = attr_chain(func)
    return chain is not None and chain.split(".")[-1] in _JIT_NAMES


def _is_hot_decorator(dec):
    """@jax.jit, @jit, @pjit, @hot_path, @partial(jax.jit, ...)."""
    if isinstance(dec, ast.Call):
        func = dec.func
        callee = attr_chain(func)
        if callee and callee.split(".")[-1] == "partial" and dec.args:
            return _is_jit_callee(dec.args[0])
        return _is_jit_callee(func)
    chain = attr_chain(dec)
    if chain is None:
        return False
    leaf = chain.split(".")[-1]
    return leaf in _JIT_NAMES or leaf == "hot_path"


def _returned_inner_functions(factory):
    """Nested FunctionDefs of ``factory`` that a ``return`` statement
    returns by name, plus returned lambdas."""
    inner = {
        node.name: node
        for node in factory.body
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef))
    }
    returned = []
    for node in ast.walk(factory):
        if not isinstance(node, ast.Return) or node.value is None:
            continue
        if isinstance(node.value, ast.Name) and node.value.id in inner:
            returned.append(inner[node.value.id])
        elif isinstance(node.value, ast.Lambda):
            returned.append(node.value)
    return returned


class _ModuleIndex:
    """Per-unit symbol tables needed for cross-module resolution."""

    def __init__(self, unit):
        self.unit = unit
        # top-level (incl. class-nested) function defs by name; names are
        # unique enough for resolution purposes
        self.functions = {}
        # local name -> (module_dotted, original_name) for ``from m import n``
        self.imports = {}
        for node, scope in walk_with_scope(unit.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self.functions.setdefault(node.name, (node, scope))
            elif isinstance(node, ast.ImportFrom) and node.module:
                for alias in node.names:
                    self.imports[alias.asname or alias.name] = (
                        node.module, alias.name
                    )


def _resolve(index_by_module, index, name):
    """(unit, func_node, scope) for ``name`` in ``index``'s module,
    following one from-import hop; None when unresolvable."""
    if name in index.functions:
        node, scope = index.functions[name]
        return index.unit, node, scope
    if name in index.imports:
        module, original = index.imports[name]
        target = index_by_module.get(module)
        if target and original in target.functions:
            node, scope = target.functions[original]
            return target.unit, node, scope
    return None


def _collect_hot(units):
    """-> list of (unit, func_or_lambda_node, symbol)."""
    indexes = [_ModuleIndex(unit) for unit in units]
    index_by_module = {idx.unit.module: idx for idx in indexes}
    hot = []
    seen = set()

    def mark(unit, node, symbol):
        key = (unit.path, id(node))
        if key not in seen:
            seen.add(key)
            hot.append((unit, node, symbol))

    def mark_factory(unit, node, scope):
        for inner in _returned_inner_functions(node):
            name = getattr(inner, "name", "<lambda>")
            mark(unit, inner, "%s.%s" % (scope, name))

    for idx in indexes:
        for node, scope in walk_with_scope(idx.unit.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                if any(_is_hot_decorator(d) for d in node.decorator_list):
                    # a factory's product is the hot code; the factory
                    # body itself is once-per-program setup and marking
                    # it too would double-report every inner hit and
                    # false-positive on host-side preamble
                    inner = _returned_inner_functions(node)
                    if inner:
                        mark_factory(idx.unit, node, scope)
                    else:
                        mark(idx.unit, node, scope)
            elif isinstance(node, ast.Call) and _is_jit_callee(node.func):
                if not node.args:
                    continue
                arg = node.args[0]
                if isinstance(arg, ast.Lambda):
                    mark(idx.unit, arg, scope + ".<lambda>")
                elif isinstance(arg, ast.Name):
                    resolved = _resolve(index_by_module, idx, arg.id)
                    if resolved:
                        unit, fn, fn_scope = resolved
                        mark(unit, fn, fn_scope)
                elif isinstance(arg, ast.Call):
                    callee = arg.func
                    if isinstance(callee, ast.Name):
                        resolved = _resolve(
                            index_by_module, idx, callee.id
                        )
                        if resolved:
                            unit, fn, fn_scope = resolved
                            mark_factory(unit, fn, fn_scope)
    return hot


def _scan_hot_function(unit, node, symbol, findings):
    body = node.body if isinstance(node.body, list) else [node.body]
    for stmt in body:
        for sub in ast.walk(stmt):
            if not isinstance(sub, ast.Call):
                continue
            func = sub.func
            chain = attr_chain(func)
            code = None
            if isinstance(func, ast.Name) and func.id in _CAST_CALLS:
                code = "%s()" % func.id
                detail = (
                    "%s() on a traced value forces a host sync at run "
                    "time (concretization error or silent device fence)"
                    % func.id
                )
            elif chain in _SYNC_CALLS:
                code = chain
                detail = (
                    "%s inside compiled code pulls the value to host "
                    "every step" % chain
                )
            elif chain in _TIME_CALLS:
                code = chain
                detail = (
                    "%s is evaluated once at trace time, not per step; "
                    "pass times in as arguments" % chain
                )
            elif chain and (
                chain.startswith("np.random.")
                or chain.startswith("numpy.random.")
            ):
                code = "np.random"
                detail = (
                    "host RNG inside compiled code is baked in at trace "
                    "time and differs across hosts; use jax.random"
                )
            elif (
                isinstance(func, ast.Attribute)
                and func.attr in _SYNC_METHODS
                and not sub.args
            ):
                code = ".%s()" % func.attr
                detail = (
                    ".%s() forces a blocking device-to-host transfer"
                    % func.attr
                )
            if code is None:
                continue
            findings.append(
                Finding(
                    rule=RULE,
                    path=unit.path,
                    line=sub.lineno,
                    symbol=symbol,
                    code=code,
                    message="hot path: " + detail,
                )
            )


def run(units):
    findings = []
    for unit, node, symbol in _collect_hot(units):
        _scan_hot_function(unit, node, symbol, findings)
    return findings
