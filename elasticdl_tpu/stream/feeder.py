"""Master-side stream feeder: windows -> watermark tasks -> exports.

Runs as one daemon thread on the master (started in Master.prepare
when EDL_STREAM selects a source). Each tick it:

1. **Mints tasks** from arriving windows, flow-controlled by the
   minted-minus-completed record backlog (``max_backlog_records``): a
   fast source must not materialize an unbounded todo queue — windows
   wait in the source until the fleet catches up. This is also what
   keeps the watermark MEANINGFUL: backlog is bounded, so the
   watermark trails the stream head by a bounded gap.
2. **Mints export tasks** each time the watermark crosses an
   ``export_every``-records boundary (EDL_STREAM_EXPORT_EVERY): one
   worker joins its async pushes, flushes its device tier, and writes
   a fresh export — the serving tier's signature watcher then
   hot-swaps onto it. The streaming replacement for the end-of-job
   export, journaled as ``stream_watermark`` events.
3. **Closes the stream** when the source reports exhaustion (bounded
   replay / total-records cap): ``dispatcher.close_stream()`` flips
   ``finished()`` to the normal drain contract.

Resume: the feeder seeks the source to the dispatcher's journaled
``stream_pos()`` before the first tick, so a relaunched master resumes
minting exactly after the last journaled window — no window delivered
twice (done-exactly-once extended to watermark tasks).
"""

import threading

from elasticdl_tpu.common.env_utils import env_float, env_int, env_str
from elasticdl_tpu.common.log_utils import default_logger as _logger_factory
from elasticdl_tpu.observability import events

logger = _logger_factory("elasticdl_tpu.stream.feeder")

STREAM_ENV = "EDL_STREAM"
WINDOW_RECORDS_ENV = "EDL_STREAM_WINDOW_RECORDS"
TOTAL_RECORDS_ENV = "EDL_STREAM_TOTAL_RECORDS"
CHECKPOINT_EVERY_ENV = "EDL_STREAM_CHECKPOINT_EVERY"
EXPORT_EVERY_ENV = "EDL_STREAM_EXPORT_EVERY"
MAX_BACKLOG_ENV = "EDL_STREAM_MAX_BACKLOG"
PASSES_ENV = "EDL_STREAM_PASSES"
HOT_VOCAB_ENV = "EDL_STREAM_HOT_VOCAB"
DRIFT_ENV = "EDL_STREAM_DRIFT"
FEATURES_ENV = "EDL_STREAM_FEATURES"
ZIPF_ENV = "EDL_STREAM_ZIPF_A"
SEED_ENV = "EDL_STREAM_SEED"


def source_from_env(training_data, reader_params=None):
    """Build the stream source EDL_STREAM selects, or None.

    - ``EDL_STREAM=synthetic``: clickstream generator spooling
      recordio windows into ``training_data`` (the workers read the
      spool through their ordinary reader — the dir IS the dataset).
    - ``EDL_STREAM=replay``: bounded replay of whatever reader
      ``training_data`` resolves to, EDL_STREAM_PASSES times.
    """
    mode = env_str(STREAM_ENV, "").strip().lower()
    if not mode or mode == "0":
        return None
    window_records = env_int(WINDOW_RECORDS_ENV, 512)
    if mode == "synthetic":
        from elasticdl_tpu.stream.source import SyntheticClickstreamSource

        if not training_data:
            raise ValueError(
                "EDL_STREAM=synthetic needs --training_data as the "
                "spool directory the workers read"
            )
        return SyntheticClickstreamSource(
            training_data,
            records_per_window=window_records,
            num_features=env_int(FEATURES_ENV, 10),
            hot_vocab=env_int(HOT_VOCAB_ENV, 2000),
            zipf_a=env_float(ZIPF_ENV, 1.3),
            drift_per_window=env_int(DRIFT_ENV, 0),
            total_records=env_int(TOTAL_RECORDS_ENV, 0),
            seed=env_int(SEED_ENV, 0),
        )
    if mode == "replay":
        from elasticdl_tpu.stream.source import replay_source_for

        return replay_source_for(
            training_data,
            records_per_window=window_records,
            passes=env_int(PASSES_ENV, 1),
            reader_params=reader_params,
        )
    raise ValueError(
        "unknown %s=%r (expected 'synthetic' or 'replay')"
        % (STREAM_ENV, mode)
    )


class StreamFeeder:
    def __init__(self, dispatcher, source, saved_model_path="",
                 export_every=None, max_backlog_records=None,
                 poll_secs=0.5, fleet=None):
        self._dispatcher = dispatcher
        self._source = source
        self._saved_model_path = saved_model_path
        # training-health fold (ISSUE 15): windows carrying drift
        # stats (label rate, id-novelty rate) feed the fleet monitor's
        # label_shift detector directly — the feeder runs in the
        # master process, no RPC
        self._fleet = fleet
        self._last_window_stats = None
        self._export_every = (
            export_every
            if export_every is not None
            else env_int(EXPORT_EVERY_ENV, 0)
        )
        self._max_backlog = (
            max_backlog_records
            if max_backlog_records is not None
            else env_int(MAX_BACKLOG_ENV, 8192)
        )
        self._poll_secs = poll_secs
        self._export_mark = None
        self._exports_minted = 0
        self._windows_minted = 0
        self._stop = threading.Event()
        self._thread = None

    # ------------------------------------------------------------------
    def start(self):
        # resume AFTER the dispatcher replayed its journal: the source
        # continues exactly past the last journaled window
        self._source.seek(self._dispatcher.stream_pos())
        self._thread = threading.Thread(
            target=self._run, name="stream-feeder", daemon=True
        )
        self._thread.start()
        logger.info(
            "Stream feeder started at source pos %d (backlog cap %d "
            "records, export every %s records)",
            self._dispatcher.stream_pos(), self._max_backlog,
            self._export_every or "-",
        )

    def stop(self):
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)

    def _run(self):
        try:
            while not self._stop.wait(self._poll_secs):
                self.tick()
                if self._source.exhausted:
                    self._dispatcher.close_stream()
                    events.emit(
                        "stream_watermark",
                        watermark=self._dispatcher.stream_watermark(),
                        minted=self._dispatcher.stream_pos(),
                        kind="closed",
                    )
                    return
        except Exception:
            # a feeder crash must be LOUD but not kill the master: the
            # job degrades to draining what was minted
            logger.exception("stream feeder failed; closing stream")
            try:
                self._dispatcher.close_stream()
            except Exception:
                logger.exception("closing the stream also failed")

    # ------------------------------------------------------------------
    def tick(self):
        """One feeder pass: mint windows up to the backlog cap, then
        check the export cadence. Callable directly (tests drive ticks
        without the thread)."""
        minted = 0
        while not self._stop.is_set():
            state = self._dispatcher.stream_state()
            if not state["open"]:
                return minted
            if state["backlog_records"] >= self._max_backlog:
                break  # fleet is behind; let the watermark catch up
            window = self._source.next_window()
            if window is None:
                break
            self._dispatcher.add_stream_window(
                window.shard_name, window.start, window.end
            )
            self._windows_minted += 1
            minted += 1
            stats = getattr(window, "stats", None)
            if stats is not None:
                # tag drift with the record offset this window lands
                # at (== the watermark once the window completes), so
                # a label_shift alert points at a WINDOW, not a time
                minted_records = self._dispatcher.stream_state()[
                    "minted_records"
                ]
                self._last_window_stats = dict(
                    stats, watermark=minted_records
                )
                if self._fleet is not None:
                    self._fleet.observe_stream_window(
                        minted_records,
                        stats["label_rate"],
                        stats["novelty_rate"],
                    )
        self._maybe_export()
        return minted

    def _maybe_export(self):
        if self._export_every <= 0 or not self._saved_model_path:
            return
        watermark = self._dispatcher.stream_watermark()
        boundary = watermark // self._export_every
        if self._export_mark is None:
            # anchor only: a restarted master must not re-export the
            # boundary its predecessor already covered
            self._export_mark = boundary
            return
        if boundary <= self._export_mark:
            return
        self._export_mark = boundary
        self._dispatcher.add_stream_export_task(
            {"saved_model_path": self._saved_model_path}
        )
        self._exports_minted += 1
        events.emit(
            "stream_watermark", watermark=watermark,
            minted=self._dispatcher.stream_pos(), kind="export",
        )
        logger.info(
            "Stream export task minted at watermark %d", watermark
        )

    def state(self):
        """/statusz section."""
        body = dict(self._dispatcher.stream_state())
        body.update({
            "exports_minted": self._exports_minted,
            "export_every": self._export_every,
            "max_backlog_records": self._max_backlog,
            "source_exhausted": bool(self._source.exhausted),
            "last_window_stats": self._last_window_stats,
        })
        return body
