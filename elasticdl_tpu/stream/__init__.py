"""Continual streaming training (ISSUE 12 / ROADMAP item 4).

Three pieces, spanning the data layer, master, and PS:

- ``source``    — unbounded/bounded stream sources minting record
  windows: replay of an existing reader's shards, and a synthetic
  clickstream generator with Zipfian drift + vocab churn.
- ``feeder``    — master-side thread turning arriving windows into
  dispatcher tasks (watermark mode) and minting export tasks on
  watermark cadence so the serving tier picks up fresh versions
  continuously.
- ``lifecycle`` — PS-side embedding lifecycle manager: frequency-based
  admission behind a counting sketch, TTL + LFU eviction sweeps with
  journaled tombstones, bounded-memory contract for unbounded vocab.
"""
