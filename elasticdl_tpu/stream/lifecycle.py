"""PS-side embedding lifecycle: admission, TTL/LFU eviction, tombstones.

Production CTR vocabularies are unbounded — every novel id that touches
a lazily-initialized table materializes a row (weights + optimizer
slots) forever. Under a clickstream with vocab churn that is a slow
memory leak by design. This manager bounds it with two policies, both
run at the PS (ps/servicer.py routes push/pull ids through here when
lifecycle is enabled):

- **Frequency-based admission**: a novel id is only *tracked* — in a
  bounded count-min sketch, not a table row — until it has been sighted
  ``admit_k`` times (appearances in pull/push traffic). Until then its
  gradients are dropped and its pulls are served from the initializer's
  cold row without materializing anything. One-shot ids (crawlers,
  cookie churn, abuse traffic) therefore cost sketch bytes, not rows.
- **TTL + LFU eviction**: sweeps on the PS poll loop evict admitted
  rows untouched for ``ttl_secs`` (reason ``ttl``) and, when the
  resident-row count exceeds ``max_rows``, the least-frequently-used
  rows down to the bound (reason ``lfu``; the current sweep's survivors
  keep their frequency, optionally decayed so drift ages old hot sets
  out). Evictions delete the row outright on the store —
  ``drop_rows`` removes weights, slots, and Adam step counts, so a
  re-admitted id restarts from the initializer exactly like a
  never-seen id — and are journaled as schema'd ``row_evicted``
  tombstone events so a postmortem can explain a cold row.

Consistency with client caches (the "existing invalidation hooks"
contract, docs/STREAMING.md): an eviction never races a client into
wrongness. The HotRowCache bounds row age by its staleness/TTL clock,
so a cached copy of an evicted row expires within the window the async
PS already tolerates; the device tier holds its resident rows
*authoritatively* and re-asserts them via ``push_embedding_rows``
writebacks — ``note_import`` re-admits such rows, refreshing their
TTL, so the tier's hot set can never be starved by PS-side eviction.

Crash recovery: lifecycle state is deliberately NOT checkpointed.
After a PS restore, ``adopt_store`` re-anchors conservatively — every
restored row is admitted (no lost admitted rows) with a fresh TTL
stamp and seed frequency, the sketch restarts empty (no phantom
admissions: a novel id must earn its ``admit_k`` sightings again).

Everything is guarded by one lock; sweeps and RPC handlers may race.
"""

import heapq
import threading
import time

import numpy as np

from elasticdl_tpu.common.env_utils import env_float, env_int
from elasticdl_tpu.common.log_utils import default_logger as _logger_factory
from elasticdl_tpu.observability import events
from elasticdl_tpu.observability import metrics as obs_metrics

logger = _logger_factory("elasticdl_tpu.stream.lifecycle")

ADMIT_K_ENV = "EDL_EMB_ADMIT_K"
MAX_ROWS_ENV = "EDL_EMB_MAX_ROWS"
TTL_SECS_ENV = "EDL_EMB_TTL_SECS"
SWEEP_SECS_ENV = "EDL_EMB_SWEEP_SECS"
SKETCH_WIDTH_ENV = "EDL_EMB_SKETCH_WIDTH"
LFU_DECAY_ENV = "EDL_EMB_LFU_DECAY"
LFU_PROTECT_ENV = "EDL_EMB_LFU_PROTECT_SECS"

# ids listed verbatim per tombstone event before truncation: enough to
# answer "why is id X cold" for the ids a postmortem actually asks
# about, without letting one churny sweep write megabyte journal lines
_EVENT_ID_CAP = 128

# bound on the per-window novel-id set behind the tracked-ids gauge
# (cleared every sweep; the gauge saturates here rather than growing)
_TRACKED_CAP = 1 << 17


class CountMinSketch:
    """Conservative-update count-min sketch over int64 ids.

    Bounded memory (depth x width uint32 cells) is the point: this is
    the only structure pre-admission ids ever touch. Estimates
    overcount (never undercount), so admission can fire a sighting or
    two early under collisions — acceptable for a frequency-gate
    heuristic, and the bench's bounded-rows gate holds regardless.
    ``halve()`` ages all cells (sweep-time), so dead one-shot ids stop
    polluting buckets under drift.
    """

    # fixed odd multipliers (splitmix-ish constants): one hash family
    # per row, deterministic across processes
    _MULTS = (0x9E3779B97F4A7C15, 0xC2B2AE3D27D4EB4F,
              0x165667B19E3779F9, 0x27D4EB2F165667C5)

    def __init__(self, width=1 << 15, depth=4):
        self.width = int(width)
        self.depth = min(int(depth), len(self._MULTS))
        self._cells = np.zeros((self.depth, self.width), dtype=np.uint32)

    def _rows(self, ids):
        """[depth, n] bucket indices for ``ids`` (int64 array)."""
        u = ids.astype(np.uint64, copy=False)
        out = np.empty((self.depth, u.size), dtype=np.int64)
        for j in range(self.depth):
            with np.errstate(over="ignore"):
                h = u * np.uint64(self._MULTS[j])
            out[j] = (h >> np.uint64(33)).astype(np.int64) % self.width
        return out

    def add(self, ids, counts):
        """Add ``counts[i]`` sightings of ``ids[i]`` (unique ids);
        returns the post-add estimates. Conservative update: every
        cell rises only to min + count, never beyond — roughly halving
        collision inflation versus the plain per-cell increment."""
        rows = self._rows(ids)
        est = np.empty(ids.size, dtype=np.int64)
        cells = self._cells
        depth_idx = np.arange(self.depth)
        for i in range(ids.size):
            idx = rows[:, i]
            vals = cells[depth_idx, idx]
            new = min(int(vals.min()) + int(counts[i]), 0xFFFFFFFF)
            cells[depth_idx, idx] = np.maximum(vals, np.uint32(new))
            est[i] = new
        return est

    def halve(self):
        self._cells >>= 1

    def clear(self):
        self._cells[:] = 0


class _TableState:
    __slots__ = ("dim", "cold_value", "admitted")

    def __init__(self, dim, cold_value):
        self.dim = dim
        self.cold_value = cold_value
        # id -> [frequency, last_seen] (plain lists: mutated in place)
        self.admitted = {}


class EmbeddingLifecycle:
    """Admission + eviction policy over one PS shard's store.

    ``store`` needs ``drop_rows(name, ids)`` and ``table_size(name)``
    (both embedding-store backends implement them). The servicer calls
    ``filter_pull``/``filter_push``/``note_import`` on the RPC paths
    and ``sweep`` from the PS poll loop.
    """

    def __init__(self, store, admit_k=2, max_rows=0, ttl_secs=0.0,
                 sketch_width=None, lfu_decay=1.0, lfu_protect_secs=1.0,
                 clock=time.time):
        self._store = store
        self.admit_k = max(1, int(admit_k))
        self.max_rows = max(0, int(max_rows))  # 0 = no LFU bound
        self.ttl_secs = float(ttl_secs)        # <=0 = no TTL
        self.lfu_decay = float(lfu_decay)
        # In-flight protection: the admission filter refreshes an id's
        # last_seen (under this lock) BEFORE the RPC's store apply
        # runs, so an LFU sweep racing that window could evict the row
        # mid-apply — the lazy init would then re-materialize it with
        # fresh slots OUTSIDE the lifecycle's books (a resident row no
        # sweep ever sees again). Excluding just-touched ids from LFU
        # victims closes the race with orders of magnitude of margin
        # over an RPC's filter->apply gap; TTL is safe by construction
        # (its horizon is far behind a just-refreshed stamp).
        self.lfu_protect_secs = float(lfu_protect_secs)
        self._clock = clock
        self._lock = threading.RLock()
        self._tables = {}
        self._sketch = CountMinSketch(
            width=sketch_width or env_int(SKETCH_WIDTH_ENV, 1 << 15)
        )
        # bounded novel-id window behind the tracked-ids gauge
        self._tracked = set()
        # cumulative tallies (telemetry + /statusz)
        self.admitted_total = 0
        self.evicted_ttl_total = 0
        self.evicted_lfu_total = 0
        self.dropped_grad_rows_total = 0
        self._m_admitted = obs_metrics.counter(
            "edl_ps_rows_admitted_total",
            "Embedding rows materialized after passing frequency "
            "admission", ("table",),
        )
        self._m_evicted = obs_metrics.counter(
            "edl_ps_rows_evicted_total",
            "Embedding rows evicted by lifecycle sweeps",
            ("table", "reason"),
        )
        self._m_dropped = obs_metrics.counter(
            "edl_ps_preadmission_grads_dropped_total",
            "Gradient rows dropped because their id had not passed "
            "admission", ("table",),
        )
        obs_metrics.gauge(
            "edl_ps_tracked_ids",
            "Distinct pre-admission ids sighted since the last sweep "
            "(saturates at the tracking cap)",
        ).set_function(lambda: len(self._tracked))
        obs_metrics.gauge(
            "edl_ps_resident_rows",
            "Materialized embedding rows across all tables (the "
            "bounded-memory contract's number)",
        ).set_function(self.resident_rows)

    @classmethod
    def maybe_create(cls, store):
        """Build from the EDL_EMB_* env knobs; None when no policy is
        enabled (the servicer then runs the pre-lifecycle fast paths
        untouched)."""
        admit_k = env_int(ADMIT_K_ENV, 0)
        max_rows = env_int(MAX_ROWS_ENV, 0)
        ttl_secs = env_float(TTL_SECS_ENV, 0.0)
        if admit_k <= 0 and max_rows <= 0 and ttl_secs <= 0:
            return None
        return cls(
            store,
            admit_k=max(1, admit_k),
            max_rows=max_rows,
            ttl_secs=ttl_secs,
            lfu_decay=env_float(LFU_DECAY_ENV, 1.0),
            lfu_protect_secs=env_float(LFU_PROTECT_ENV, 1.0),
        )

    # ------------------------------------------------------------------
    def register_table(self, name, dim, init_kind="uniform",
                       init_param=0.05):
        """Called by the servicer at table creation. The cold row
        served for pre-admission pulls is the initializer's
        deterministic value: the constant for constant/zeros
        initializers, zeros for stochastic kinds (drawing from the
        real RNG stream without materializing would desync the lazy
        init draws of rows that DO admit)."""
        cold = float(init_param) if init_kind == "constant" else 0.0
        with self._lock:
            state = self._tables.get(name)
            if state is None:
                self._tables[name] = _TableState(int(dim), cold)
            else:
                # re-register (restore-then-register-infos): adopt the
                # model's configured initializer, like the store does
                state.dim = int(dim)
                state.cold_value = cold

    def tables(self):
        with self._lock:
            return list(self._tables)

    def cold_rows(self, name, n):
        state = self._tables[name]
        return np.full((int(n), state.dim), state.cold_value,
                       dtype=np.float32)

    def resident_rows(self):
        with self._lock:
            return sum(
                len(s.admitted) for s in self._tables.values()
            )

    # ------------------------------------------------------------------
    def _observe_locked(self, state, name, ids, now):
        """Fold one request's ids into the frequency state; returns
        (admitted mask, newly-admitted ids). Ids crossing ``admit_k``
        on this request admit NOW — their mask is True, so the very
        push/pull that tipped them materializes the row through the
        store's normal lazy init. Caller journals the admissions AFTER
        releasing the lock (journal I/O never runs under a lock RPC
        handlers contend on — the task_dispatcher discipline)."""
        ids = np.asarray(ids, dtype=np.int64).reshape(-1)
        mask = np.empty(ids.size, dtype=bool)
        admitted = state.admitted
        unknown = []
        for pos, i in enumerate(ids):
            entry = admitted.get(int(i))
            if entry is not None:
                entry[0] += 1
                entry[1] = now
                mask[pos] = True
            else:
                mask[pos] = False
                unknown.append(pos)
        if not unknown:
            return mask, ()
        unk_ids = ids[unknown]
        unique, counts = np.unique(unk_ids, return_counts=True)
        est = self._sketch.add(unique, counts)
        if len(self._tracked) < _TRACKED_CAP:
            self._tracked.update(int(i) for i in unique)
        newly = unique[est >= self.admit_k]
        if newly.size:
            for i in newly:
                admitted[int(i)] = [float(self.admit_k), now]
            newly_set = set(int(i) for i in newly)
            for pos in unknown:
                if int(ids[pos]) in newly_set:
                    mask[pos] = True
            self.admitted_total += newly.size
            self._m_admitted.labels(table=name).inc(int(newly.size))
        return mask, [int(i) for i in newly]

    def _journal_admissions(self, name, newly, journal):
        """Record newly-admitted ids. ``journal`` (a list of (event,
        fields) the caller emits after releasing ITS lock) is for
        callers already holding a contended lock — the sync push path
        runs under the PS push lock, where journal I/O is forbidden."""
        if not newly:
            return
        entry = ("row_admitted", dict(
            table=name, count=len(newly),
            ids=list(newly[:_EVENT_ID_CAP]),
        ))
        if journal is not None:
            journal.append(entry)
        else:
            events.emit(entry[0], **entry[1])

    def filter_pull(self, name, ids, journal=None):
        """Admission gate for a pull: returns the boolean admitted
        mask. Non-admitted positions must be served the table's cold
        row (``cold_rows``) WITHOUT touching the store — a pull is a
        sighting, never a materialization."""
        if name not in self._tables:
            return np.ones(np.asarray(ids).size, dtype=bool)
        now = self._clock()
        with self._lock:
            mask, newly = self._observe_locked(
                self._tables[name], name, ids, now
            )
        self._journal_admissions(name, newly, journal)
        return mask

    def filter_push(self, name, ids, journal=None):
        """Admission gate for pushed gradients: non-admitted rows'
        gradients are dropped by the caller (counted here)."""
        if name not in self._tables:
            return np.ones(np.asarray(ids).size, dtype=bool)
        now = self._clock()
        with self._lock:
            mask, newly = self._observe_locked(
                self._tables[name], name, ids, now
            )
        self._journal_admissions(name, newly, journal)
        dropped = int(mask.size - mask.sum())
        if dropped:
            self.dropped_grad_rows_total += dropped
            self._m_dropped.labels(table=name).inc(dropped)
        return mask

    def note_import(self, name, ids):
        """Imports are authoritative writes (device-tier writebacks,
        checkpoint restores re-sharding rows in): the rows EXIST after
        the import, so they must be admitted — an unadmitted resident
        row would be invisible to the eviction bound and never age
        out."""
        if name not in self._tables:
            return
        now = self._clock()
        with self._lock:
            admitted = self._tables[name].admitted
            fresh = 0
            for i in np.asarray(ids, dtype=np.int64).reshape(-1):
                i = int(i)
                entry = admitted.get(i)
                if entry is None:
                    admitted[i] = [float(self.admit_k), now]
                    fresh += 1
                else:
                    entry[1] = now
            if fresh:
                self.admitted_total += fresh
                self._m_admitted.labels(table=name).inc(fresh)

    def adopt_store(self):
        """Post-restore re-anchor (conservative): every row the store
        actually holds is admitted with a fresh TTL stamp and seed
        frequency — no lost admitted rows; everything else (sketch,
        tracked window) restarts empty — no phantom rows."""
        now = self._clock()
        with self._lock:
            self._sketch.clear()
            self._tracked.clear()
            for name, state in self._tables.items():
                state.admitted = {}
                try:
                    ids, _values = self._store.export_table(name)
                except KeyError:
                    continue
                for i in ids:
                    state.admitted[int(i)] = [float(self.admit_k), now]
        logger.info(
            "lifecycle re-anchored on restored store: %d resident rows "
            "admitted, sketch cleared", self.resident_rows(),
        )

    # ------------------------------------------------------------------
    def sweep(self):
        """One eviction pass (PS poll loop): TTL first, then the LFU
        bound over the survivors. Returns {"ttl": n, "lfu": n}.
        Evicted rows are dropped from the store and journaled as
        tombstones (after the lock releases); the sketch ages (halve)
        so one-shot ids stop polluting buckets under drift."""
        now = self._clock()
        totals = {"ttl": 0, "lfu": 0}
        journal = []
        with self._lock:
            self._sketch.halve()
            self._tracked.clear()
            for name, state in self._tables.items():
                evict = {}
                admitted = state.admitted
                if self.ttl_secs > 0:
                    horizon = now - self.ttl_secs
                    for i, (freq, last) in admitted.items():
                        if last < horizon:
                            evict[i] = "ttl"
                if self.max_rows > 0:
                    over = (len(admitted) - len(evict)) - self.max_rows
                    if over > 0:
                        # in-flight protection: a just-touched id may
                        # have an apply between its admission filter
                        # and the store — never an LFU victim (see
                        # __init__). heapq.nsmallest: the cut is
                        # O(n log over), not a full sort under the lock
                        protect = now - self.lfu_protect_secs
                        by_freq = heapq.nsmallest(
                            over,
                            (
                                (freq, last, i)
                                for i, (freq, last) in admitted.items()
                                if i not in evict and last < protect
                            ),
                        )
                        for _freq, _last, i in by_freq:
                            evict[i] = "lfu"
                if evict:
                    self._evict_locked(name, state, evict, journal)
                    for reason in ("ttl", "lfu"):
                        totals[reason] += sum(
                            1 for r in evict.values() if r == reason
                        )
                if self.lfu_decay < 1.0:
                    for entry in admitted.values():
                        entry[0] *= self.lfu_decay
        for event, fields in journal:
            events.emit(event, **fields)
        return totals

    def _evict_locked(self, name, state, evict, journal):
        by_reason = {"ttl": [], "lfu": []}
        for i, reason in evict.items():
            by_reason[reason].append(i)
            state.admitted.pop(i, None)
        for reason, id_list in by_reason.items():
            if not id_list:
                continue
            try:
                self._store.drop_rows(name, np.asarray(id_list,
                                                       dtype=np.int64))
            except KeyError:
                pass
            if reason == "ttl":
                self.evicted_ttl_total += len(id_list)
            else:
                self.evicted_lfu_total += len(id_list)
            self._m_evicted.labels(table=name, reason=reason).inc(
                len(id_list)
            )
            journal.append((
                "row_evicted",
                dict(table=name, reason=reason, count=len(id_list),
                     ids=[int(i) for i in id_list[:_EVENT_ID_CAP]]),
            ))

    # ------------------------------------------------------------------
    def stats(self):
        with self._lock:
            return {
                "admit_k": self.admit_k,
                "max_rows": self.max_rows,
                "ttl_secs": self.ttl_secs,
                "tracked_ids": len(self._tracked),
                "resident_rows": sum(
                    len(s.admitted) for s in self._tables.values()
                ),
                "rows_admitted": self.admitted_total,
                "rows_evicted_ttl": self.evicted_ttl_total,
                "rows_evicted_lfu": self.evicted_lfu_total,
                "grad_rows_dropped": self.dropped_grad_rows_total,
            }
