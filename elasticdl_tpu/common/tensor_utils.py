"""numpy <-> TensorBlob conversion and IndexedSlices helpers.

Reference parity: elasticdl/python/common/tensor_utils.py:31-122 (which
converts to tensorflow.TensorProto). Here the wire type is our own
TensorBlob (dtype string + dims + raw bytes), chosen so host code never
needs TF and device code can go bytes -> numpy -> jax with one copy.
"""

import numpy as np

from elasticdl_tpu.proto import elasticdl_tpu_pb2 as pb


def ndarray_to_blob(array, blob=None) -> pb.TensorBlob:
    array = np.ascontiguousarray(array)
    if array.dtype == object:
        # object arrays of python strings (categorical features):
        # materialize as fixed-width unicode so they have a raw layout
        array = array.astype(str)
    if blob is None:
        blob = pb.TensorBlob()
    # unicode/bytes need dtype.str ("<U7"/"|S7"; dtype.name is the
    # unparseable "str224"), while extension types like bfloat16 need
    # dtype.name (their dtype.str is an opaque "<V2")
    if array.dtype.kind in ("U", "S"):
        blob.dtype = array.dtype.str
    else:
        blob.dtype = array.dtype.name
    del blob.dims[:]
    blob.dims.extend(array.shape)
    blob.content = array.tobytes()
    return blob


def blob_to_ndarray(blob: pb.TensorBlob) -> np.ndarray:
    dtype = np.dtype(blob.dtype)
    array = np.frombuffer(blob.content, dtype=dtype)
    return array.reshape(tuple(blob.dims))


def serialize_indexed_slices(values, ids, slices=None) -> pb.IndexedSlicesProto:
    """values: (n, dim) ndarray of rows; ids: iterable of int64 row ids."""
    if slices is None:
        slices = pb.IndexedSlicesProto()
    ndarray_to_blob(values, slices.concat_tensors)
    del slices.ids[:]
    slices.ids.extend(int(i) for i in ids)
    return slices


def deserialize_indexed_slices(slices: pb.IndexedSlicesProto):
    values = blob_to_ndarray(slices.concat_tensors)
    ids = np.asarray(slices.ids, dtype=np.int64)
    return values, ids


def merge_indexed_slices(values_a, ids_a, values_b, ids_b):
    """Concatenate two IndexedSlices (no dedup)."""
    return (
        np.concatenate([values_a, values_b], axis=0),
        np.concatenate([ids_a, ids_b], axis=0),
    )


def deduplicate_indexed_slices(values, ids):
    """Sum rows with duplicate ids.

    Returns (summed_values, unique_ids). Mirrors the client-side dedup the
    reference does before pushing embedding gradients
    (worker/ps_client.py:135-232).
    """
    ids = np.asarray(ids, dtype=np.int64)
    unique_ids, index = np.unique(ids, return_inverse=True)
    summed = np.zeros((unique_ids.size, values.shape[1]), dtype=values.dtype)
    np.add.at(summed, index, values)
    return summed, unique_ids
