"""numpy <-> TensorBlob conversion and IndexedSlices helpers.

Reference parity: elasticdl/python/common/tensor_utils.py:31-122 (which
converts to tensorflow.TensorProto). Here the wire type is our own
TensorBlob (dtype string + dims + raw bytes), chosen so host code never
needs TF and device code can go bytes -> numpy -> jax with one copy.

Wire-path hot spots live here (ISSUE 5):

- ids travel as a packed little-endian int64 blob
  (``IndexedSlicesProto.ids_blob``) written straight from the numpy
  buffer; the legacy ``repeated int64 ids`` field walked every id
  through a Python generator and a varint codec. Readers accept either
  encoding, writers prefer packed.
- ``EDL_WIRE_DTYPE`` down-casts float32 *payloads* (embedding-gradient
  pushes, pulled rows) to bfloat16/float16 on the wire. TensorBlob is
  self-describing (the dtype string rides with the bytes), so this is
  a payload change, not a protocol fork: either end may opt in
  independently and the other decodes what it is sent. The PS keeps
  fp32 master copies either way. Unset / ``float32`` is bit-exact with
  the pre-knob wire format.
- ``deduplicate_indexed_slices`` segment-sums via sort + ``reduceat``
  instead of ``np.add.at`` scatter-add — ~1.7-1.9x faster at the
  narrow row dims CTR embeddings use (8-16) on duplicate-heavy
  Zipfian id streams, and a pure permutation (no scatter at all) when
  the ids are already unique. (numpy 2 vectorized ``add.at``; the
  classic 10x folklore no longer holds, and very wide rows favor
  scatter-add again — measured in scripts/bench_wire_micro.py.)
"""


import numpy as np

from elasticdl_tpu.common.env_utils import env_str
from elasticdl_tpu.proto import elasticdl_tpu_pb2 as pb

WIRE_DTYPE_ENV = "EDL_WIRE_DTYPE"

# little-endian int64: the one id encoding ids_blob ever carries,
# regardless of host byte order
_IDS_WIRE_DTYPE = np.dtype("<i8")

# EDL_WIRE_DTYPE values -> numpy dtype to downcast float32 payloads to;
# None = leave payloads alone (bit-exact with the pre-knob wire)
_WIRE_DTYPES = {
    "": None,
    "float32": None,
    "fp32": None,
    "bfloat16": "bfloat16",
    "bf16": "bfloat16",
    "float16": np.float16,
    "fp16": np.float16,
}


def wire_dtype():
    """The configured wire payload dtype, or None for bit-exact fp32.

    Read from the environment on every call so tests (and long-lived
    processes restarted with new knobs) see changes; the lookup is two
    dict probes, far below wire-serialization cost.
    """
    value = env_str(WIRE_DTYPE_ENV, "")
    key = value.strip().lower()
    if key not in _WIRE_DTYPES:
        raise ValueError(
            "%s=%r is not a supported wire dtype (float32, bfloat16, "
            "float16)" % (WIRE_DTYPE_ENV, value)
        )
    resolved = _WIRE_DTYPES[key]
    if resolved is None:
        return None
    if resolved == "bfloat16":
        # bfloat16 is an extension type: resolving the name requires
        # its defining module imported (ml_dtypes ships with jax).
        # Resolve here so a missing registration fails loudly at the
        # knob, not deep in a serialize call.
        import ml_dtypes

        return np.dtype(ml_dtypes.bfloat16)
    return np.dtype(resolved)


def _downcast_for_wire(array, dtype):
    """Down-cast float32 payloads to the wire dtype; anything else
    (ids, int features, already-reduced payloads) passes through."""
    if dtype is not None and array.dtype == np.float32:
        return array.astype(dtype)
    return array


def ndarray_to_blob(array, blob=None, wire_dtype=None) -> pb.TensorBlob:
    """``wire_dtype``: optional reduced-precision dtype for float32
    payloads (callers pass ``wire_dtype()`` on the paths that opt in —
    gradient pushes and pulled rows; dense init/checkpoint payloads
    never downcast)."""
    # asarray, not ascontiguousarray: tobytes() below already emits
    # C-order bytes for any layout, and ascontiguousarray silently
    # promoted 0-d tensors to shape (1,)
    array = np.asarray(array)
    if array.dtype == object:
        # object arrays of python strings (categorical features):
        # materialize as fixed-width unicode so they have a raw layout
        array = array.astype(str)
    array = _downcast_for_wire(array, wire_dtype)
    if blob is None:
        blob = pb.TensorBlob()
    # unicode/bytes need dtype.str ("<U7"/"|S7"; dtype.name is the
    # unparseable "str224"), while extension types like bfloat16 need
    # dtype.name (their dtype.str is an opaque "<V2")
    if array.dtype.kind in ("U", "S"):
        blob.dtype = array.dtype.str
    else:
        blob.dtype = array.dtype.name
    del blob.dims[:]
    blob.dims.extend(array.shape)
    blob.content = array.tobytes()
    return blob


def _resolve_np_dtype(name):
    """np.dtype by wire name; extension names (bfloat16) resolve only
    once their defining module is imported — a receiver must decode
    whatever dtype the sender opted into."""
    try:
        return np.dtype(name)
    except TypeError:
        import ml_dtypes

        return np.dtype(getattr(ml_dtypes, name))


def blob_to_ndarray(blob: pb.TensorBlob) -> np.ndarray:
    dtype = _resolve_np_dtype(blob.dtype)
    array = np.frombuffer(blob.content, dtype=dtype)  # zero-copy view
    return array.reshape(tuple(blob.dims))


def pack_ids(ids) -> bytes:
    """int64 ids -> packed little-endian bytes (the ids_blob wire
    encoding); one vectorized astype+tobytes, no per-id Python."""
    return np.ascontiguousarray(ids, dtype=_IDS_WIRE_DTYPE).tobytes()


def normalize_id_tables(ids_by_table):
    """``{table: ids}`` -> ``{table: contiguous int64 ndarray}`` with
    empty tables dropped — ONE conversion per table (the
    convert-inside-a-filter idiom built a second throwaway array per
    table per step). Shared by every batch-pull front door
    (PSClient / EmbeddingClient / LocalPSClient)."""
    converted = {}
    for name, ids in ids_by_table.items():
        ids = np.asarray(ids, dtype=np.int64)
        if ids.size:
            converted[name] = ids
    return converted


def unpack_ids(message) -> np.ndarray:
    """ids from any message carrying the ids/ids_blob field pair
    (IndexedSlicesProto, PullEmbeddingVectorsRequest). Packed wins when
    present; legacy repeated ids from an old peer still decode."""
    if message.ids_blob:
        ids = np.frombuffer(message.ids_blob, dtype=_IDS_WIRE_DTYPE)
        return ids.astype(np.int64, copy=False)
    return np.asarray(message.ids, dtype=np.int64)


def serialize_indexed_slices(values, ids, slices=None, wire_dtype=None,
                             packed=True) -> pb.IndexedSlicesProto:
    """values: (n, dim) ndarray of rows; ids: iterable of int64 row ids.

    ``packed=False`` writes the legacy repeated field instead of
    ids_blob — for peers from before the packed encoding existed (a
    packed-only push against one silently applies nothing). Vectorized
    either way: tolist() converts in numpy, not a Python loop.
    """
    if slices is None:
        slices = pb.IndexedSlicesProto()
    ndarray_to_blob(values, slices.concat_tensors, wire_dtype=wire_dtype)
    del slices.ids[:]
    if packed:
        slices.ids_blob = pack_ids(ids)
    else:
        slices.ids_blob = b""
        slices.ids.extend(
            np.asarray(ids, dtype=np.int64).tolist()
        )
    return slices


def deserialize_indexed_slices(slices: pb.IndexedSlicesProto):
    values = blob_to_ndarray(slices.concat_tensors)
    return values, unpack_ids(slices)


def merge_indexed_slices(values_a, ids_a, values_b, ids_b):
    """Concatenate two IndexedSlices (no dedup)."""
    return (
        np.concatenate([values_a, values_b], axis=0),
        np.concatenate([ids_a, ids_b], axis=0),
    )


def deduplicate_indexed_slices(values, ids):
    """Sum rows with duplicate ids.

    Returns (summed_values, unique_ids). Mirrors the client-side dedup the
    reference does before pushing embedding gradients
    (worker/ps_client.py:135-232).

    Segment-sum via sort + ``np.add.reduceat`` instead of ``np.add.at``
    scatter-add: ~1.7-1.9x faster at CTR-typical row dims (8-16) on
    duplicate-heavy Zipfian streams, and the no-duplicate case is a
    pure permutation (see module docstring; numbers from
    scripts/bench_wire_micro.py on numpy 2).
    """
    ids = np.asarray(ids, dtype=np.int64)
    values = np.asarray(values)
    unique_ids, index = np.unique(ids, return_inverse=True)
    if unique_ids.size == ids.size:
        # no duplicates: unique() already computed the sort; index is a
        # permutation, so invert it instead of summing 1-row segments
        order = np.argsort(index)
        return values[order], unique_ids
    order = np.argsort(index, kind="stable")
    sorted_values = values[order]
    counts = np.bincount(index, minlength=unique_ids.size)
    # every unique id has >= 1 occurrence, so starts is strictly
    # increasing and reduceat's segments are exactly the id groups
    starts = np.zeros(unique_ids.size, dtype=np.int64)
    np.cumsum(counts[:-1], out=starts[1:])
    summed = np.add.reduceat(sorted_values, starts, axis=0)
    return summed, unique_ids
