"""Version bridge for the JAX surface the parallel/ops stack sits on.

The SPMD trainers, pipeline schedules, and attention collectives were
written against the current JAX API (``jax.shard_map`` with
``check_vma``, ``pltpu.CompilerParams``); the pinned runtime in this
image is jax 0.4.x, where the same machinery lives under
``jax.experimental.shard_map.shard_map`` (with ``check_rep``) and
``pltpu.TPUCompilerParams``. Every call site routes through this
module so the version probe happens exactly once, at import — not
per-trace — and upgrading the pin later is a no-op here (the new-API
branch is preferred whenever it exists).

Nothing in here changes semantics: ``shard_map`` forwards to
whichever implementation the installed JAX ships, and ``check_vma``
(the new name for per-output replication checking) maps onto
``check_rep`` (the old one).
"""

import jax

__all__ = [
    "anchor_replicated", "shard_map", "pvary", "tpu_compiler_params",
]

_NEW_SHARD_MAP = getattr(jax, "shard_map", None)

if _NEW_SHARD_MAP is None:
    from jax.experimental.shard_map import shard_map as _OLD_SHARD_MAP

    def _register_pallas_rep_rule():
        """0.4.x ``check_rep`` has no replication rule for pallas_call,
        so a checked manual region containing a flash kernel dies with
        "No replication rule". A pallas kernel never communicates
        across devices, so the standard elementwise-style rule (output
        replication = the shared input replication) is sound — register
        it once so the checked path keeps working, because the
        UNchecked path is worse: check_rep=False lowers axis_index
        through PartitionId, which XLA rejects on CPU."""
        try:
            from jax._src.pallas.pallas_call import pallas_call_p
            from jax.experimental import shard_map as _sm_mod

            from functools import partial

            _sm_mod.register_standard_check(pallas_call_p)
            # the STANDARD rewrite (not norewrite): it pbroadcasts
            # mismatched input replications down to their meet, so a
            # kernel fed both device-varying blocks and literal-init
            # (fully replicated) carries still traces under the check
            _sm_mod.register_rewrite(pallas_call_p)(
                partial(_sm_mod._standard_rewrite_rule, pallas_call_p)
            )
        except (ImportError, AttributeError, TypeError):
            pass  # internal layout moved; unchecked fallback still works

    _register_pallas_rep_rule()
else:
    _OLD_SHARD_MAP = None


def shard_map(f, *, mesh, in_specs, out_specs, check_vma=None):
    """``jax.shard_map`` on new JAX; the ``jax.experimental`` spelling
    (with ``check_vma`` mapped to its old name ``check_rep``) on the
    pinned 0.4.x runtime."""
    if _NEW_SHARD_MAP is not None:
        kwargs = {}
        if check_vma is not None:
            kwargs["check_vma"] = check_vma
        return _NEW_SHARD_MAP(
            f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
            **kwargs
        )
    # 0.4.x: always TRY the checked path, even when the caller asked
    # for check_vma=False — the old ``check_rep`` inference accepts
    # programs the new VMA annotation checker rejects (e.g. pallas_call
    # outputs carrying no vma), and its False mode lowers axis_index
    # through PartitionId, which XLA SPMD rejects on CPU. Where the old
    # inference is instead too WEAK (it cannot see replication through
    # a scanned custom_vjp the way VMA typing can), it raises a
    # "can't be statically inferred" ValueError at trace time — only
    # then retrace unchecked.
    checked = _OLD_SHARD_MAP(
        f, mesh, in_specs=in_specs, out_specs=out_specs, check_rep=True
    )
    unchecked = _OLD_SHARD_MAP(
        f, mesh, in_specs=in_specs, out_specs=out_specs,
        check_rep=False,
    )

    def _apply(*args, **kw):
        try:
            return checked(*args, **kw)
        except Exception as e:
            message = str(e)
            if (
                "statically inferred" not in message
                and "check_rep=False" not in message
                and "No replication rule" not in message
            ):
                raise
            return unchecked(*args, **kw)

    return _apply


def pvary(x, axes):
    """Cast ``x`` to device-varying over ``axes`` inside a manual
    region. New JAX's VMA typing requires the explicit cast to mix
    literal (unvarying) inits with per-device scan state; 0.4.x
    shard_map has no VMA lattice — its ``check_rep`` tracks
    replication without demanding casts — so this is the identity
    there."""
    axes = tuple(axes)
    if not axes:
        return x
    pcast = getattr(jax.lax, "pcast", None)
    if pcast is not None:
        return pcast(x, axes, to="varying")
    lax_pvary = getattr(jax.lax, "pvary", None)
    if lax_pvary is not None:
        return lax_pvary(x, axes)
    # 0.4.x: the same operation is spelled pbroadcast in shard_map's
    # internal replication lattice (it marks a replicated value as
    # device-varying over ``axes``; its transpose is psum — which is
    # what keeps vjp transposes of invarying params from psumming a
    # cotangent once per scan tick). Identity as a last resort.
    try:
        from jax.experimental.shard_map import pbroadcast
    except ImportError:
        return x
    return pbroadcast(x, axes)


def cotangent_psum(x, axes):
    """Sum per-shard partial cotangents over ``axes``. A ``jax.vjp``
    taken INSIDE a shard_map body on 0.4.x never materializes the
    transpose of the implicit vary-cast that promotes a replicated
    input into an axis-varying computation (on new JAX that transpose
    is a psum over the axis), so the input cotangent comes back as
    this shard's partial; summing the partials reconstitutes it.
    Identity on new JAX, where the vjp already contains the psum."""
    axes = tuple(axes)
    if not axes or _NEW_SHARD_MAP is not None:
        return x
    # AD-repair substrate; mesh_psum is itself built on this module
    # edlint: disable=perf-bare-collective
    return jax.lax.psum(x, axes)


def anchor_replicated(x, axes):
    """Assert-by-construction that ``x`` is replicated over ``axes``
    inside a manual region. New JAX's VMA typing proves this from the
    program; 0.4.x ``check_rep`` inference gives up inside a scanned
    custom_vjp, and its unchecked fallback mis-transposes in-body
    psums — so on old JAX anchor the fact with a pmean, which is the
    identity on a value that is already replicated (what the out_spec
    demands) and gives the checker a reduction it understands."""
    axes = tuple(axes)
    if not axes or _NEW_SHARD_MAP is not None:
        return x
    # replication anchor for the 0.4.x rep-checker, identity by contract
    # edlint: disable=perf-bare-collective
    return jax.lax.pmean(x, axes)


def tpu_compiler_params(**kwargs):
    """``pltpu.CompilerParams`` (new spelling) or ``TPUCompilerParams``
    (0.4.x) — the Mosaic kwargs are identical across the rename."""
    from jax.experimental.pallas import tpu as pltpu

    cls = getattr(pltpu, "CompilerParams", None)
    if cls is None:
        cls = pltpu.TPUCompilerParams
    return cls(**kwargs)
