"""Logging factory (reference parity: elasticdl/python/common/log_utils.py)."""

import logging

_DEFAULT_FMT = "%(asctime)s %(levelname)s %(name)s: %(message)s"

_initialized = False
# set by configure(); wins over the per-call default so loggers created
# AFTER --log_level is applied still honor it
_configured_level = None
# the FileHandler installed by configure(); re-configure replaces it
# instead of stacking a second one (LocalExecutor and tests call
# configure() more than once per process)
_file_handler = None


def default_logger(name: str = "elasticdl_tpu", level: int = logging.INFO):
    global _initialized
    if not _initialized:
        logging.basicConfig(format=_DEFAULT_FMT)
        _initialized = True
    logger = logging.getLogger(name)
    logger.setLevel(
        _configured_level if _configured_level is not None else level
    )
    return logger


def get_logger(name: str, level: int = logging.INFO):
    return default_logger(name, level)


def configure(log_level: str = "", log_file_path: str = ""):
    """Apply the --log_level / --log_file_path flags (reference:
    elasticdl_client/common/args.py:369,392) to every elasticdl_tpu
    logger: the package root's level, plus an optional file handler."""
    global _configured_level
    if log_level:
        level = getattr(logging, log_level.upper(), None)
        if not isinstance(level, int):
            raise ValueError("unknown --log_level %r" % (log_level,))
        _configured_level = level
        # re-level every already-created elasticdl_tpu logger (they get
        # explicit levels from default_logger)
        for name, logger in logging.root.manager.loggerDict.items():
            if name.startswith("elasticdl_tpu") and isinstance(
                logger, logging.Logger
            ):
                logger.setLevel(level)
        logging.getLogger("elasticdl_tpu").setLevel(level)
    if log_file_path:
        global _file_handler
        root = logging.getLogger()
        if _file_handler is not None:
            root.removeHandler(_file_handler)
            _file_handler.close()
        _file_handler = logging.FileHandler(log_file_path)
        _file_handler.setFormatter(logging.Formatter(_DEFAULT_FMT))
        root.addHandler(_file_handler)
