"""Logging factory (reference parity: elasticdl/python/common/log_utils.py)."""

import logging

_DEFAULT_FMT = "%(asctime)s %(levelname)s %(name)s: %(message)s"

_initialized = False


def default_logger(name: str = "elasticdl_tpu", level: int = logging.INFO):
    global _initialized
    if not _initialized:
        logging.basicConfig(format=_DEFAULT_FMT)
        _initialized = True
    logger = logging.getLogger(name)
    logger.setLevel(level)
    return logger


def get_logger(name: str, level: int = logging.INFO):
    return default_logger(name, level)
