"""Zero-cost source annotations consumed by static analysis (edlint).

``@hot_path`` marks a function as part of the per-step compiled/driving
path even when edlint cannot prove it from jit plumbing alone: apply it
to step-function factories (whose returned closure is what gets
jitted) and to host-side functions that run once per training step.
edlint's ``jax-hot-path`` rule then flags host-device syncs, host RNG,
and wall-clock reads inside them. Runtime cost: nothing — it returns
the function unchanged.
"""


def hot_path(fn):
    """Identity decorator: marks ``fn`` (or the closures a factory
    returns) as step-path code for edlint's jax-hot-path rule."""
    return fn


def thread_context(name, reentrant=False):
    """Identity decorator declaring an execution-context contract for
    edlint's ``conc-thread-context`` rule: the function must only run
    on the named thread/context ("ps-poll", "tier-dispatch", ...).
    Call edges reaching it from any other inferred context are flagged;
    handing the function off as a value (Thread target, executor
    submit, queue) is the sanctioned way to enter its context.

    ``reentrant=True`` additionally asserts the function is safe to run
    re-entrantly (signal-handler discipline): it must transitively take
    no locks and never block. Runtime cost: nothing.

    The comment form ``# edlint: thread=<name>`` on/above a ``def`` is
    equivalent for code that must not import this module.
    """
    del name, reentrant  # consumed statically by edlint, not at runtime

    def deco(fn):
        return fn

    return deco
