"""Zero-cost source annotations consumed by static analysis (edlint).

``@hot_path`` marks a function as part of the per-step compiled/driving
path even when edlint cannot prove it from jit plumbing alone: apply it
to step-function factories (whose returned closure is what gets
jitted) and to host-side functions that run once per training step.
edlint's ``jax-hot-path`` rule then flags host-device syncs, host RNG,
and wall-clock reads inside them. Runtime cost: nothing — it returns
the function unchanged.
"""


def hot_path(fn):
    """Identity decorator: marks ``fn`` (or the closures a factory
    returns) as step-path code for edlint's jax-hot-path rule."""
    return fn
