"""Platform selection for framework processes.

TPU containers in this environment register the accelerator backend from
sitecustomize at interpreter start, which overrides JAX_PLATFORMS from
the environment. EDL_PLATFORM provides a reliable escape hatch (used by
tests and CPU-mesh dry runs): it is applied through jax.config after
import, which wins over the sitecustomize registration.
"""

from elasticdl_tpu.common.env_utils import env_str


def apply_platform_overrides():
    platform = env_str("EDL_PLATFORM", "")
    if platform:
        import jax

        jax.config.update("jax_platforms", platform)
