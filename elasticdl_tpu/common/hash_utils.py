"""Stable hashing used for parameter/row placement.

Reference parity: elasticdl/python/common/hash_utils.py:17-62 and the Go
twins `StringToID`/`IntToID` (go/pkg/ps/checkpoint.go:31-44). Dense
parameters route to a shard by sha256(name) mod N; embedding rows by
id mod N. These functions must stay stable across processes and languages
because checkpoint re-sharding on resume depends on them.
"""

import hashlib


def string_to_id(name: str, bucket_num: int) -> int:
    if bucket_num <= 0:
        raise ValueError("bucket_num must be positive, got %s" % bucket_num)
    digest = hashlib.sha256(name.encode("utf-8")).hexdigest()
    return int(digest, 16) % bucket_num


def int_to_id(value: int, bucket_num: int) -> int:
    if bucket_num <= 0:
        raise ValueError("bucket_num must be positive, got %s" % bucket_num)
    return int(value) % bucket_num


def stable_u64(token: str) -> int:
    """Process-stable 64-bit hash of a string token.

    The serving router's consistent-hash ring (ISSUE 17) places replica
    vnodes and affinity keys on a shared u64 circle. Python's builtin
    ``hash`` is salted per process, so ring positions would differ between
    the router and any offline tooling replaying a journal; sha256 keeps
    placement reproducible the same way shard routing above does.
    """
    digest = hashlib.sha256(token.encode("utf-8")).digest()
    return int.from_bytes(digest[:8], "big")


def scatter_ids(ids, bucket_num: int):
    """Group embedding ids by destination shard.

    Returns {shard_id: [positions...]} so callers can both route ids and
    re-assemble the pulled rows in input order.
    """
    buckets = {}
    for pos, i in enumerate(ids):
        buckets.setdefault(int(i) % bucket_num, []).append(pos)
    return buckets
