"""Overload discipline for the training plane (ISSUE 19): deadline
budgets, retry budgets, circuit breakers, and server pushback.

Four cooperating pieces, all inert until their knob is set or the
caller opts in:

- **Deadline-budget propagation.** A caller opens ``with budget(secs):``
  and every nested RPC inherits the REMAINING wall-clock budget instead
  of minting a fresh default timeout at each hop: the channel
  interceptor (installed by ``build_channel``) caps each attempt's
  ``timeout=`` by the remainder and carries it across the wire in an
  ``edl-deadline-budget`` metadata header (the ``edl-traceparent``
  pattern), and the server interceptor (installed by ``build_server``)
  re-opens the budget around the handler so the server's own fan-outs
  inherit it too. Budgets carry REMAINING SECONDS, never absolute
  deadlines — peer wall clocks are not trusted (the incarnation-epoch
  lesson). ``bind_budget`` re-homes the thread-local budget into
  executor threads, the ``trace.bind_context`` twin.

- **Retry budgets.** A per-target token bucket: successes earn
  ``EDL_RETRY_BUDGET_RATIO`` tokens (default 0.1 — ~10% of successful
  traffic may be retries), each retry attempt spends one. An exhausted
  bucket fails fast (counted + journaled) instead of amplifying an
  overloaded peer's load: with every client retrying each failure N
  times, the peer sees N× its capacity exactly when it can least
  afford it.

- **Circuit breakers.** A closed/open/half-open breaker per
  (target, method class). ``EDL_CIRCUIT_FAILURES`` consecutive
  connection-shaped failures open it; after ``EDL_CIRCUIT_RESET_SECS``
  ONE probe attempt is admitted (half-open) and its outcome closes or
  re-opens the breaker. ``retry_call`` PACES on an open breaker —
  waits out the probe window within its budget rather than hammering —
  and only fails fast when the caller opted in (pulls with a brownout
  fallback). Transitions are journaled (``circuit_open`` /
  ``circuit_half_open`` / ``circuit_closed``) and gauged.

- **Server pushback.** An overloaded server answers RESOURCE_EXHAUSTED
  with an ``edl-retry-after-ms`` trailer (see ps/servicer.py admission
  control); ``retry_after_hint`` reads it back and ``retry_call``
  paces by the SERVER's hint instead of its own backoff schedule —
  the server knows its backlog, the client doesn't.

Everything here must stay cheap enough for the per-RPC path: state
lookups are one dict get under a short lock, and the disabled paths
(``EDL_DEADLINE_BUDGET=0``, breaker/budget never engaged because no
``target=`` was passed) add nothing to the call.
"""

import collections
import threading
import time

import grpc

from elasticdl_tpu.common.env_utils import env_float, env_int, env_str
from elasticdl_tpu.common.log_utils import default_logger as _logger_factory
from elasticdl_tpu.observability import events
from elasticdl_tpu.observability import metrics as obs_metrics

logger = _logger_factory("elasticdl_tpu.common.overload")

# remaining-seconds metadata header (metadata keys must be lowercase)
METADATA_KEY = "edl-deadline-budget"
# server pushback trailer: how long the client should wait before the
# retry, in milliseconds (a trailer because it rides the error status)
RETRY_AFTER_KEY = "edl-retry-after-ms"

DEADLINE_BUDGET_ENV = "EDL_DEADLINE_BUDGET"
RETRY_BUDGET_TOKENS_ENV = "EDL_RETRY_BUDGET_TOKENS"
RETRY_BUDGET_RATIO_ENV = "EDL_RETRY_BUDGET_RATIO"
CIRCUIT_FAILURES_ENV = "EDL_CIRCUIT_FAILURES"
CIRCUIT_RESET_SECS_ENV = "EDL_CIRCUIT_RESET_SECS"
# Brownout (ISSUE 19): consecutive overload-class push failures after
# which the trainer skips the batch's push bit-exactly (PR 15's skip
# machinery) instead of wedging the step loop. 0 (default) disables
# the whole degraded mode — pulls then never fail fast into the stale-
# cache path either, preserving pre-ISSUE-19 retry semantics exactly.
BROWNOUT_SKIP_AFTER_ENV = "EDL_BROWNOUT_SKIP_AFTER"


def brownout_skip_after():
    return env_int(BROWNOUT_SKIP_AFTER_ENV, 0)


def brownout_enabled():
    return brownout_skip_after() > 0


def circuit_reset_secs():
    """The breaker's open->half-open window — also the deadline budget
    a browned-out trainer grants each probe push (train/sparse.py)."""
    return env_float(CIRCUIT_RESET_SECS_ENV, 5.0)

_state = threading.local()
_lock = threading.Lock()


class OverloadError(grpc.RpcError):
    """A locally-decided overload failure (no wire attempt was made).

    Subclasses grpc.RpcError and answers code()/details() so every
    existing ``except grpc.RpcError`` / ``e.code()`` handler treats it
    exactly like the transport error it stands in for.
    """

    def __init__(self, code, details):
        super().__init__(details)
        self._code = code
        self._details = details

    def code(self):
        return self._code

    def details(self):
        return self._details


class CircuitOpenError(OverloadError):
    def __init__(self, target, kind):
        super().__init__(
            grpc.StatusCode.UNAVAILABLE,
            "circuit open for %s/%s" % (target, kind),
        )
        self.target = target
        self.kind = kind


class RetryBudgetExhausted(OverloadError):
    def __init__(self, target, code):
        super().__init__(
            code, "retry budget exhausted for %s" % target
        )
        self.target = target


# overload-class status codes a brownout may absorb: the transport is
# down (UNAVAILABLE, incl. CircuitOpenError), the budget ran out
# mid-storm (DEADLINE_EXCEEDED, incl. RetryBudgetExhausted), or the PS
# pushed back and stayed overloaded (RESOURCE_EXHAUSTED). Anything
# else (bad request, server logic error) must still raise.
BROWNOUT_CODES = (
    grpc.StatusCode.UNAVAILABLE,
    grpc.StatusCode.DEADLINE_EXCEEDED,
    grpc.StatusCode.RESOURCE_EXHAUSTED,
)


def is_overload_failure(exc):
    """True when a brownout path may absorb ``exc``: any OverloadError,
    or a transport error carrying an overload-class status. The second
    arm matters because a retry loop that exhausts its deadline budget
    re-raises the last RAW RpcError — when the breaker's reset window
    is shorter than the retry backoff, every retry lands in a half-open
    probe window and no CircuitOpenError is ever minted."""
    if isinstance(exc, OverloadError):
        return True
    code = getattr(exc, "code", None)
    return callable(code) and code() in BROWNOUT_CODES


# ---------------------------------------------------------------------------
# deadline budget (thread-local remaining wall clock)


class budget:
    """``with budget(secs):`` — cap every nested RPC in this thread by
    the remaining wall clock. Nested budgets tighten, never loosen: the
    inner scope's deadline is min(outer remainder, secs). Re-entrant
    and exception-safe; ``secs=None`` is a no-op scope (callers can
    pass an optional knob straight through)."""

    def __init__(self, secs):
        self._secs = secs
        self._prev = None

    def __enter__(self):
        self._prev = getattr(_state, "deadline", None)
        if self._secs is not None:
            deadline = time.monotonic() + float(self._secs)
            if self._prev is not None:
                deadline = min(deadline, self._prev)
            _state.deadline = deadline
        return self

    def __exit__(self, *exc):
        _state.deadline = self._prev
        return False


def remaining():
    """Seconds left in this thread's active budget, or None when no
    budget is open. Floored at 0.0 — an expired budget reads as zero,
    and the caller (retry_call, the interceptor) decides what zero
    means (fail, not block-forever)."""
    deadline = getattr(_state, "deadline", None)
    if deadline is None:
        return None
    return max(0.0, deadline - time.monotonic())


def rpc_timeout(default):
    """The timeout an RPC attempt should carry: the caller's default
    capped by the thread's remaining budget. THE budget helper the
    ``ft-deadline-no-propagation`` lint rule expects at stub call sites
    on propagated paths — a fresh literal there silently forgets the
    caller's remaining time."""
    rem = remaining()
    if rem is None:
        return default
    return min(float(default), rem) if default is not None else rem


def bind_budget(fn):
    """Capture this thread's budget (if any) and reinstate it around
    ``fn`` in whatever thread runs it — for executor fan-outs, which
    lose thread-locals (the ``trace.bind_context`` twin). Without this
    a worker's per-shard push pool would mint fresh default deadlines
    while the caller's budget is nearly gone."""
    deadline = getattr(_state, "deadline", None)
    if deadline is None:
        return fn

    def bound(*args, **kwargs):
        prev = getattr(_state, "deadline", None)
        _state.deadline = (
            deadline if prev is None else min(deadline, prev)
        )
        try:
            return fn(*args, **kwargs)
        finally:
            _state.deadline = prev

    return bound


def propagation_enabled():
    return env_str(DEADLINE_BUDGET_ENV, "") != "0"


class _CallDetails(
    collections.namedtuple(
        "_CallDetails",
        ("method", "timeout", "metadata", "credentials",
         "wait_for_ready", "compression"),
    ),
    grpc.ClientCallDetails,
):
    pass


class DeadlineBudgetClientInterceptor(
    grpc.UnaryUnaryClientInterceptor
):
    """Cap each outgoing attempt's timeout by the thread's remaining
    budget and carry the remainder to the peer as metadata. No active
    budget = untouched call details (zero added work beyond one
    thread-local read)."""

    def intercept_unary_unary(self, continuation, client_call_details,
                              request):
        rem = remaining()
        if rem is None:
            return continuation(client_call_details, request)
        timeout = client_call_details.timeout
        timeout = rem if timeout is None else min(float(timeout), rem)
        metadata = list(client_call_details.metadata or ())
        metadata.append((METADATA_KEY, "%.3f" % rem))
        details = _CallDetails(
            method=client_call_details.method,
            timeout=timeout,
            metadata=metadata,
            credentials=getattr(client_call_details, "credentials", None),
            wait_for_ready=getattr(
                client_call_details, "wait_for_ready", None
            ),
            compression=getattr(client_call_details, "compression", None),
        )
        return continuation(details, request)


def intercept_budget_channel(channel):
    """``build_channel`` seam: wrap with the budget interceptor unless
    EDL_DEADLINE_BUDGET=0 — then the exact input channel is returned
    (identity, test-asserted)."""
    if not propagation_enabled():
        return channel
    return grpc.intercept_channel(
        channel, DeadlineBudgetClientInterceptor()
    )


class _BudgetServerInterceptor(grpc.ServerInterceptor):
    """Adopt an incoming ``edl-deadline-budget`` header as the handler
    thread's budget, so the server's own nested RPCs (PS fan-outs,
    router forwards) inherit the CALLER's remaining time."""

    def intercept_service(self, continuation, handler_call_details):
        handler = continuation(handler_call_details)
        if handler is None or handler.unary_unary is None:
            return handler
        secs = None
        for key, value in handler_call_details.invocation_metadata or ():
            if key == METADATA_KEY:
                try:
                    secs = float(value)
                except ValueError:
                    secs = None
                break
        if secs is None:
            return handler
        inner = handler.unary_unary

        def budgeted(request, context):
            with budget(secs):
                return inner(request, context)

        return grpc.unary_unary_rpc_method_handler(
            budgeted,
            request_deserializer=handler.request_deserializer,
            response_serializer=handler.response_serializer,
        )


def server_budget_interceptors():
    """``build_server`` seam: () unless propagation is on."""
    if not propagation_enabled():
        return ()
    return (_BudgetServerInterceptor(),)


# ---------------------------------------------------------------------------
# server pushback


def retry_after_hint(rpc_error):
    """Seconds the server asked this client to wait before retrying
    (the ``edl-retry-after-ms`` trailer on a RESOURCE_EXHAUSTED
    pushback), or None when the error carries no hint."""
    trailing = getattr(rpc_error, "trailing_metadata", None)
    if trailing is None:
        return None
    try:
        metadata = trailing() or ()
    except Exception:  # edlint: disable=ft-swallowed-except
        # a half-constructed RpcError (test doubles, client-side
        # aborts) has no trailers — no hint, not an error
        return None
    for entry in metadata:
        key = getattr(entry, "key", None) or entry[0]
        value = getattr(entry, "value", None) or entry[1]
        if key == RETRY_AFTER_KEY:
            try:
                return max(0.0, float(value) / 1000.0)
            except (TypeError, ValueError):
                return None
    return None


# ---------------------------------------------------------------------------
# retry budget (per-target token bucket)


class RetryBudget:
    """Token bucket bounding retry amplification toward one target.

    Starts full (``max_tokens``); every retry attempt spends 1.0, every
    success earns ``ratio`` (capped at full). ``spend`` at zero returns
    False — the caller fails fast instead of joining the storm. The
    ~ratio asymptotics are the point: in steady overload the bucket
    drains and at most ``ratio`` retries ride per unit of successful
    traffic, so client-side amplification is bounded at 1+ratio no
    matter how long the brownout lasts.
    """

    def __init__(self, max_tokens=None, ratio=None):
        self.max_tokens = float(
            max_tokens if max_tokens is not None
            else env_int(RETRY_BUDGET_TOKENS_ENV, 100)
        )
        self.ratio = float(
            ratio if ratio is not None
            else env_float(RETRY_BUDGET_RATIO_ENV, 0.1)
        )
        self._tokens = self.max_tokens
        self._lock = threading.Lock()
        self.exhausted = 0  # cumulative fail-fast decisions

    def record_success(self):
        with self._lock:
            self._tokens = min(
                self.max_tokens, self._tokens + self.ratio
            )

    def spend(self):
        """Take one retry token; False = exhausted, fail fast."""
        with self._lock:
            if self._tokens < 1.0:
                self.exhausted += 1
                return False
            self._tokens -= 1.0
            return True

    def tokens(self):
        with self._lock:
            return self._tokens


# ---------------------------------------------------------------------------
# circuit breaker (per target+method-class)

CLOSED, OPEN, HALF_OPEN = "closed", "open", "half_open"
_STATE_VALUE = {CLOSED: 0.0, OPEN: 1.0, HALF_OPEN: 2.0}


class CircuitBreaker:
    """Closed/open/half-open breaker for one (target, method class).

    ``admit_delay()`` is the client-side gate: 0.0 = attempt now
    (closed, or this caller won the half-open probe slot), else seconds
    until the next probe window. Connection-shaped failures
    (UNAVAILABLE / DEADLINE_EXCEEDED) count toward opening; server
    pushback (RESOURCE_EXHAUSTED) deliberately does NOT — a pushing-
    back server is alive and managing load, and opening on it would
    turn graceful degradation into an outage.
    """

    def __init__(self, target, kind, failures=None, reset_secs=None):
        self.target = target
        self.kind = kind
        self.failure_threshold = (
            failures if failures is not None
            else env_int(CIRCUIT_FAILURES_ENV, 5)
        )
        self.reset_secs = (
            reset_secs if reset_secs is not None
            else env_float(CIRCUIT_RESET_SECS_ENV, 5.0)
        )
        self._lock = threading.Lock()
        self._state = CLOSED
        self._consecutive_failures = 0
        self._opened_at = 0.0
        self._probe_inflight = False
        self.open_count = 0  # cumulative closed/half_open -> open

    def state(self):
        with self._lock:
            return self._state

    def admit_delay(self, now=None):
        """0.0 = go; > 0 = seconds until this caller may probe."""
        now = time.monotonic() if now is None else now
        transition = None
        with self._lock:
            if self._state == CLOSED:
                return 0.0
            wait = self._opened_at + self.reset_secs - now
            if wait > 0:
                return wait
            # probe window: admit exactly one caller; the rest keep
            # pacing on the window so a closed->open flap never
            # releases a thundering herd
            if self._probe_inflight:
                delay = self.reset_secs
            else:
                transition = self._transition_locked(HALF_OPEN)
                self._probe_inflight = True
                delay = 0.0
        self._journal(transition)
        return delay

    def record_success(self, now=None):
        transition = None
        with self._lock:
            self._consecutive_failures = 0
            self._probe_inflight = False
            if self._state != CLOSED:
                transition = self._transition_locked(CLOSED)
        self._journal(transition)

    def record_failure(self, now=None):
        now = time.monotonic() if now is None else now
        transition = None
        with self._lock:
            self._consecutive_failures += 1
            if self._state == HALF_OPEN:
                # failed probe: back to open, restart the window
                self._probe_inflight = False
                self._opened_at = now
                transition = self._transition_locked(OPEN)
            elif (
                self._state == CLOSED
                and self._consecutive_failures >= self.failure_threshold
            ):
                self._opened_at = now
                transition = self._transition_locked(OPEN)
        self._journal(transition)

    def _transition_locked(self, state):
        """Flip the state under the lock; returns the (prev, new,
        failures) tuple the caller journals AFTER releasing it (the
        journal write is file IO — never under a lock the RPC path
        contends on)."""
        prev, self._state = self._state, state
        if state == OPEN:
            self.open_count += 1
        return (prev, state, self._consecutive_failures)

    def _journal(self, transition):
        if transition is None:
            return
        prev, state, failures = transition
        _m_circuit_transitions.labels(state=state).inc()
        _m_circuit_state.labels(
            target=self.target, kind=self.kind
        ).set(_STATE_VALUE[state])
        logger.warning(
            "circuit %s -> %s for %s/%s (failures=%d)",
            prev, state, self.target, self.kind, failures,
        )
        if events.enabled():
            events.emit(
                "circuit_%s" % state, target=self.target,
                method_class=self.kind, previous=prev,
                consecutive_failures=failures,
                reset_secs=self.reset_secs,
            )


# hoisted instruments (obs-hot-path: construction is init-scope work).
# LAZY, not eager: this module reaches every role via common.grpc_utils
# at import time, before main() publishes EDL_METRICS_PORT — an eager
# counter() here would freeze the process registry disabled and blank
# /metrics for the whole role.
_m_circuit_transitions = obs_metrics.lazy_counter(
    "edl_circuit_transitions_total",
    "Circuit-breaker state transitions", ("state",),
)
_m_circuit_state = obs_metrics.lazy_gauge(
    "edl_circuit_state",
    "Breaker state per target/method-class "
    "(0 closed, 1 open, 2 half-open)",
    ("target", "kind"),
)
_m_retry_budget_exhausted = obs_metrics.lazy_counter(
    "edl_retry_budget_exhausted_total",
    "Retries refused because the per-target token bucket ran dry",
    ("target",),
)
_m_pushback_waits = obs_metrics.lazy_counter(
    "edl_retry_pushback_waits_total",
    "Retries paced by a server edl-retry-after-ms hint", ("target",),
)

# process-wide registries: breakers/budgets are per-TARGET state shared
# by every stub talking to that target, so they live here, not on the
# client object (two PSClient instances to the same shard must share
# one breaker)
_breakers = {}
_retry_budgets = {}

# process-wide degraded-mode tallies (worker telemetry reads these)
_counters = {
    "degraded_pulls": 0,
    "brownout_skipped_pushes": 0,
    "pushback_waits": 0,
}


def breaker_for(target, kind):
    key = (target, kind)
    with _lock:
        breaker = _breakers.get(key)
        if breaker is None:
            breaker = _breakers[key] = CircuitBreaker(target, kind)
        return breaker


def retry_budget_for(target):
    with _lock:
        bucket = _retry_budgets.get(target)
        if bucket is None:
            bucket = _retry_budgets[target] = RetryBudget()
        return bucket


def method_class(what):
    """Breakers are per method CLASS, not per method: every pull
    variant shares one read-path breaker (they fail together) while
    the non-idempotent push path gets its own."""
    lowered = (what or "").lower()
    if "pull" in lowered or "get" in lowered or "info" in lowered:
        return "read"
    return "write"


def note_degraded_pull(count=1):
    with _lock:
        _counters["degraded_pulls"] += int(count)


def note_brownout_skip():
    with _lock:
        _counters["brownout_skipped_pushes"] += 1


def note_pushback_wait(target):
    _m_pushback_waits.labels(target=target).inc()
    with _lock:
        _counters["pushback_waits"] += 1


def note_budget_exhausted(target):
    _m_retry_budget_exhausted.labels(target=target).inc()


def client_stats():
    """Cumulative overload tallies for this process's client side —
    the worker's telemetry blob and /statusz read these."""
    with _lock:
        stats = dict(_counters)
        breakers = list(_breakers.items())
        budgets = list(_retry_budgets.values())
    stats["circuit_open_count"] = sum(
        b.open_count for _, b in breakers
    )
    stats["retry_budget_exhausted"] = sum(
        b.exhausted for b in budgets
    )
    stats["circuits_not_closed"] = sorted(
        "%s/%s" % key for key, b in breakers if b.state() != CLOSED
    )
    return stats


def _reset_for_tests():
    with _lock:
        _breakers.clear()
        _retry_budgets.clear()
        for key in _counters:
            _counters[key] = 0
