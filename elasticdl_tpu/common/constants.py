"""Shared constants.

Reference parity: elasticdl/python/common/constants.py:15-96.
"""


class GRPC:
    # Whole dense models can ride in single messages (reference raises the
    # limit to 256 MB on both sides: common/constants.py:15-19).
    MAX_SEND_MESSAGE_LENGTH = 256 * 1024 * 1024
    MAX_RECEIVE_MESSAGE_LENGTH = 256 * 1024 * 1024
    # Per-RPC deadline. No unary call in this system legitimately runs
    # longer: get_task answers WAIT instead of blocking, and the big
    # pull/push payloads (256 MB cap) clear in seconds on pod networks.
    # A hung half-dead peer then surfaces as DEADLINE_EXCEEDED — which
    # the PS client's retry loop treats as retryable — instead of
    # blocking the caller forever (edlint: ft-grpc-timeout).
    DEFAULT_RPC_TIMEOUT_SECS = 60.0


class WorkerEnv:
    WORKER_ID = "EDL_WORKER_ID"
    MASTER_ADDR = "EDL_MASTER_ADDR"
    WORKER_NUM = "EDL_WORKER_NUM"


class JobType:
    TRAINING_ONLY = "training"
    EVALUATION_ONLY = "evaluation"
    PREDICTION_ONLY = "prediction"
    TRAINING_WITH_EVALUATION = "training_with_evaluation"


class Mode:
    TRAINING = "training"
    EVALUATION = "evaluation"
    PREDICTION = "prediction"


class DistributionStrategy:
    LOCAL = "Local"
    # Dense gradients allreduced by XLA collectives inside the jitted step.
    ALLREDUCE = "AllreduceStrategy"
    # Sparse embeddings on a host-side PS; dense path still allreduce.
    PARAMETER_SERVER = "ParameterServerStrategy"


class PodStatus:
    PENDING = "Pending"
    RUNNING = "Running"
    SUCCEEDED = "Succeeded"
    FAILED = "Failed"
    UNKNOWN = "Unknown"
    DELETED = "Deleted"


class InstanceManagerStatus:
    PENDING = "Pending"
    RUNNING = "Running"
    FINISHED = "Finished"


class TaskExecCounterKey:
    FAIL_COUNT = "fail_count"


class DefaultPort:
    MASTER = 50001
    PS = 50002
    WORKER = 50003


class SaveModelConfig:
    SAVED_MODEL_PATH = "saved_model_path"


# Per-task retry budget before the job is declared failed
# (reference: master/task_dispatcher.py:27).
MAX_TASK_RETRIES = 3
# Per-minibatch retry budget against PS rejection (reference: worker/worker.py:49).
MAX_MINIBATCH_RETRY_NUM = 64
