"""gRPC channel/server helpers.

Reference parity: elasticdl/python/common/grpc_utils.py:22-40.
"""

import socket
from concurrent import futures

import grpc

from elasticdl_tpu.common.constants import GRPC

_CHANNEL_OPTIONS = [
    ("grpc.max_send_message_length", GRPC.MAX_SEND_MESSAGE_LENGTH),
    ("grpc.max_receive_message_length", GRPC.MAX_RECEIVE_MESSAGE_LENGTH),
]


def build_channel(addr: str) -> grpc.Channel:
    return grpc.insecure_channel(addr, options=_CHANNEL_OPTIONS)


def build_server(max_workers: int = 64, instrument: bool = True) -> grpc.Server:
    """gRPC server with the metrics interceptor installed when metrics
    collection is enabled (observability/grpc_metrics.py); with the
    knobs unset ``server_interceptors()`` is empty and the call path is
    identical to an uninstrumented server."""
    interceptors = ()
    if instrument:
        from elasticdl_tpu.observability.grpc_metrics import (
            server_interceptors,
        )

        interceptors = server_interceptors()
    return grpc.server(
        futures.ThreadPoolExecutor(max_workers=max_workers),
        options=_CHANNEL_OPTIONS,
        interceptors=interceptors,
    )


def find_free_port() -> int:
    with socket.socket(socket.AF_INET, socket.SOCK_STREAM) as sock:
        sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        sock.bind(("localhost", 0))
        return sock.getsockname()[1]
