"""gRPC channel/server helpers + the shared jittered retry policy.

Reference parity: elasticdl/python/common/grpc_utils.py:22-40.
"""

import os
import random
import socket
import time
from concurrent import futures

import grpc

from elasticdl_tpu.common import overload
from elasticdl_tpu.common.constants import GRPC
from elasticdl_tpu.common.env_utils import env_str
from elasticdl_tpu.common.log_utils import default_logger as _logger_factory
from elasticdl_tpu.observability import trace as _trace

logger = _logger_factory("elasticdl_tpu.common.grpc_utils")

_CHANNEL_OPTIONS = [
    ("grpc.max_send_message_length", GRPC.MAX_SEND_MESSAGE_LENGTH),
    ("grpc.max_receive_message_length", GRPC.MAX_RECEIVE_MESSAGE_LENGTH),
    # grpc's default reconnect backoff grows to 120 s — longer than the
    # whole master/PS relaunch retry budget, so a client whose channel
    # went TRANSIENT_FAILURE during the outage could sit out a backoff
    # gap and fail-fast UNAVAILABLE long after the relaunched peer is
    # serving (observed: the master-SIGKILL chaos test). Cap the gap so
    # recovery latency is bounded by OUR jittered retry policy, not the
    # transport's.
    ("grpc.max_reconnect_backoff_ms", 10000),
]

# connection-shaped failures worth retrying: the peer pod is
# relaunching (UNAVAILABLE) or wedged past its deadline; anything else
# (bad request, server logic error) surfaces immediately
RETRYABLE_CODES = (
    grpc.StatusCode.UNAVAILABLE,
    grpc.StatusCode.DEADLINE_EXCEEDED,
)


def _await_reconnect(channel, timeout_secs):
    """Actively drive the channel's reconnection for up to
    ``timeout_secs``; returns True when it went READY.

    This is load-bearing, not an optimization: a fail-fast RPC against
    a TRANSIENT_FAILURE channel fails immediately WITHOUT scheduling a
    fresh connection attempt, so a retry loop that only sleeps can
    burn its whole budget returning UNAVAILABLE while the relaunched
    peer is long since serving (observed in the master-SIGKILL chaos
    test). ``channel_ready_future`` subscribes a connectivity watcher
    (which does schedule attempts, paced by the channel's
    max_reconnect_backoff_ms) and unsubscribes on completion/cancel —
    unlike a standing ``channel.subscribe``, it leaves nothing behind
    to wedge interpreter shutdown on never-closed channels.
    """
    future = grpc.channel_ready_future(channel)
    try:
        future.result(timeout=timeout_secs)
        return True
    except grpc.FutureTimeoutError:
        return False
    finally:
        future.cancel()


def retry_call(fn, what, budget_secs, retryable=RETRYABLE_CODES,
               base_delay=0.5, max_delay=10.0, rng=None, channel=None,
               target=None, fail_fast_when_open=False):
    """Call ``fn`` with FULL-JITTER exponential backoff on retryable
    gRPC errors, up to ``budget_secs`` of wall clock.

    Each backoff is uniform in [0, ceiling) with the ceiling doubling
    per attempt (capped at ``max_delay``) — AWS-style full jitter. The
    jitter is the point, not a nicety: a sync-strategy fleet whose
    every worker hits the same relaunching PS retries in LOCKSTEP under
    deterministic backoff, re-forming the same thundering herd at every
    interval; uniform draws decorrelate the fleet so the relaunched pod
    sees a trickle instead of a wall. ``rng`` (tests) overrides the
    module RNG for deterministic schedules.

    Pass the call's ``channel`` whenever available: the backoff then
    actively drives the channel's reconnection (see _await_reconnect)
    instead of just sleeping, and when the peer comes back early the
    retry fires after only a small residual jitter draw rather than
    the full backoff.

    Overload discipline (ISSUE 19, common/overload.py), engaged only
    when ``target`` names the peer:

    - ``budget_secs`` is first capped by the thread's propagated
      deadline budget, and the whole loop runs inside that budget so
      every attempt's channel-interceptor timeout shrinks with the
      remainder — a nested fan-out can never outlive its caller.
    - a RESOURCE_EXHAUSTED carrying the server's ``edl-retry-after-ms``
      pushback trailer is retried at the SERVER's pace: the hint seeds
      the wait, full jitter rides on top, and consecutive pushbacks
      double it (capped 8x) — all separate from the connection-failure
      jitter ceiling, which pushback never grows.
    - each retry spends a per-target retry-budget token; an empty
      bucket raises ``RetryBudgetExhausted`` (fail fast — bounded
      amplification) instead of sleeping.
    - the per-(target, method-class) circuit breaker paces attempts:
      open = wait out the probe window (still inside the budget), or
      raise ``CircuitOpenError`` immediately when the caller set
      ``fail_fast_when_open`` because it has a degraded fallback
      (brownout pulls). Connection-shaped failures feed the breaker;
      pushback does not.
    """
    jitter = (rng or random).uniform
    budget_secs = overload.rpc_timeout(budget_secs)
    deadline = time.monotonic() + budget_secs
    breaker = (
        overload.breaker_for(target, overload.method_class(what))
        if target is not None else None
    )
    retry_budget = (
        overload.retry_budget_for(target) if target is not None else None
    )
    ceiling = base_delay
    attempt = 0
    pushback_streak = 0
    with overload.budget(budget_secs):
        while True:
            if breaker is not None:
                wait = breaker.admit_delay()
                if wait > 0:
                    if fail_fast_when_open or (
                        time.monotonic() + wait > deadline
                    ):
                        raise overload.CircuitOpenError(
                            breaker.target, breaker.kind
                        )
                    time.sleep(wait)
                    continue
            attempt += 1
            try:
                # each attempt is its OWN child span (ISSUE 9): a
                # retried RPC shows as N sibling spans — the failed
                # attempts carry error/code args — never one span
                # double-ended, and the propagated parent the server
                # sees is the attempt that actually reached it
                if _trace.enabled():
                    with _trace.span("rpc_attempt", what=what,
                                     attempt=attempt):
                        result = fn()
                else:
                    result = fn()
            except grpc.RpcError as e:
                code = e.code() if hasattr(e, "code") else None
                pushback = overload.retry_after_hint(e)
                if breaker is not None and code in RETRYABLE_CODES:
                    breaker.record_failure()
                if pushback is not None and (
                    code == grpc.StatusCode.RESOURCE_EXHAUSTED
                ):
                    # server pushback: the hint SEEDS the pacing (the
                    # connection-failure jitter ceiling is untouched),
                    # with full jitter on top and doubling on
                    # consecutive pushbacks — waiters polling at the
                    # bare hint in lockstep race each freed slot and
                    # mostly miss, re-amplifying the very load the
                    # server is shedding
                    delay = (
                        pushback * (1.0 + jitter(0.0, 1.0))
                        * (1 << min(pushback_streak, 3))
                    )
                    pushback_streak += 1
                else:
                    pushback = None
                    pushback_streak = 0
                    delay = jitter(0.0, ceiling)
                    if code not in retryable:
                        raise
                if time.monotonic() + delay > deadline:
                    raise
                if retry_budget is not None and not retry_budget.spend():
                    overload.note_budget_exhausted(target)
                    logger.warning(
                        "%s: retry budget for %s exhausted; failing "
                        "fast", what, target,
                    )
                    raise overload.RetryBudgetExhausted(
                        target, code
                    ) from e
                if pushback is not None:
                    overload.note_pushback_wait(target)
                    logger.warning(
                        "%s pushed back by %s; retrying in %.2fs",
                        what, target or "peer", delay,
                    )
                    time.sleep(delay)
                    continue
                logger.warning(
                    "%s unavailable (%s); retrying in %.2fs", what,
                    code, delay,
                )
                if channel is not None:
                    if _await_reconnect(channel, delay):
                        # peer is back: keep a small residual jitter
                        # so a fleet whose ready-futures all completed
                        # at the same instant doesn't slam it in
                        # unison
                        time.sleep(jitter(0.0, min(0.25, delay)))
                else:
                    time.sleep(delay)
                ceiling = min(ceiling * 2, max_delay)
            else:
                if breaker is not None:
                    breaker.record_success()
                if retry_budget is not None:
                    retry_budget.record_success()
                return result


# Zero-copy local transport (ISSUE 11): on a TPU-VM host the PS is
# co-located with its workers/serve pods, and the localhost TCP hop is
# pure overhead (checksums, nagle, loopback copies). When
# EDL_PS_UDS_DIR is set, a PS binds gRPC on a unix-domain socket named
# by its TCP port next to the TCP listener, and clients building a
# channel to a LOCAL host:port transparently prefer the socket when it
# exists — TCP stays the fallback (env unset, socket absent, or a
# remote host). The port-derived name is the advertisement: both ends
# already know the port, so co-location needs no extra wiring beyond
# sharing the env var (docs/PERFORMANCE.md "Native data plane").
UDS_DIR_ENV = "EDL_PS_UDS_DIR"


def uds_socket_path(port, uds_dir=None):
    """The socket path a PS serving on ``port`` binds under
    EDL_PS_UDS_DIR, or None when the knob is unset."""
    directory = uds_dir or env_str(UDS_DIR_ENV, "")
    if not directory:
        return None
    return os.path.join(
        os.path.abspath(directory), "edl-ps-%d.sock" % int(port)
    )


def _is_local_host(host):
    host = host.strip("[]")
    if host in ("localhost", "127.0.0.1", "::1", ""):
        return True
    try:
        return host == socket.gethostname()
    except OSError:
        return False


def maybe_uds_addr(addr):
    """``host:port`` -> ``unix:<path>`` when EDL_PS_UDS_DIR names a
    live socket for that port AND the host is this machine; None
    otherwise (caller keeps the TCP address). Existence is checked at
    channel-build time only — after that the channel owns the path, so
    a PS SIGKILL + relaunch on the same socket reconnects without the
    client rebuilding anything."""
    host, _, port = addr.rpartition(":")
    if not port.isdigit() or not _is_local_host(host):
        return None
    path = uds_socket_path(int(port))
    if path and os.path.exists(path):
        return "unix:" + path
    return None


def build_channel(addr: str) -> grpc.Channel:
    uds = maybe_uds_addr(addr)
    if uds is not None:
        logger.info("channel to %s riding the local socket %s", addr, uds)
        addr = uds
    channel = grpc.insecure_channel(addr, options=_CHANNEL_OPTIONS)
    # deadline-budget propagation (ISSUE 19, common/overload.py):
    # innermost — caps each attempt's timeout by the thread's
    # remaining budget and carries the remainder to the peer as
    # edl-deadline-budget metadata. Identity pass-through under
    # EDL_DEADLINE_BUDGET=0, and zero-cost per call when no budget
    # scope is open.
    channel = overload.intercept_budget_channel(channel)
    # trace-context propagation (observability/trace_propagation.py):
    # identity pass-through unless EDL_TRACE_DIR is set with a nonzero
    # sample rate. Inner of the fault interceptor on purpose: a
    # client-side injected fault fails before "sending", so it must
    # not reach the wire-facing layers.
    from elasticdl_tpu.observability.trace_propagation import (
        intercept_trace_channel,
    )

    channel = intercept_trace_channel(channel)
    # deterministic fault injection (testing/faults.py): identity
    # pass-through unless EDL_FAULT_SPEC names this role's client calls
    from elasticdl_tpu.testing.faults import intercept_client_channel

    return intercept_client_channel(channel)


def build_server(max_workers: int = 64, instrument: bool = True) -> grpc.Server:
    """gRPC server with the metrics interceptor installed when metrics
    collection is enabled (observability/grpc_metrics.py); with the
    knobs unset ``server_interceptors()`` is empty and the call path is
    identical to an uninstrumented server."""
    interceptors = ()
    if instrument:
        from elasticdl_tpu.observability.grpc_metrics import (
            server_interceptors,
        )

        interceptors = server_interceptors()
    # deterministic fault injection (testing/faults.py): empty tuple —
    # an unchanged call path — unless EDL_FAULT_SPEC is set
    from elasticdl_tpu.testing.faults import (
        server_interceptors as fault_interceptors,
    )

    interceptors = tuple(interceptors) + fault_interceptors()
    # deadline-budget adoption (ISSUE 19): a handler whose caller sent
    # edl-deadline-budget metadata runs inside that remaining budget,
    # so the server's own nested RPCs inherit the caller's clock
    interceptors = interceptors + overload.server_budget_interceptors()
    return grpc.server(
        futures.ThreadPoolExecutor(max_workers=max_workers),
        # so_reuseport=0: every role here is one-process-per-port, and
        # with SO_REUSEPORT a SIGKILLed predecessor's lingering socket
        # can keep receiving (and black-holing) a share of incoming
        # connections after the same-port relaunch binds — observed as
        # minutes of UNAVAILABLE against a healthy relaunched master
        # in the crash-recovery chaos tests
        options=_CHANNEL_OPTIONS + [("grpc.so_reuseport", 0)],
        interceptors=interceptors,
    )


def find_free_port() -> int:
    with socket.socket(socket.AF_INET, socket.SOCK_STREAM) as sock:
        sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        sock.bind(("localhost", 0))
        return sock.getsockname()[1]
