"""Env-knob parsing, once. Every role reads numeric EDL_* knobs; the
repo had grown five near-identical try/int(os.environ...) copies with
diverging behavior on a typo'd value. One pair, log-and-default."""

import os

from elasticdl_tpu.common.log_utils import default_logger as _logger_factory

logger = _logger_factory("elasticdl_tpu.common.env_utils")


def env_int(name, default):
    """int(os.environ[name]) with ``default`` for unset/empty; a
    non-numeric value logs a warning (a typo'd knob must be loud, not
    silently the default) and falls back."""
    raw = os.environ.get(name, "")
    if not raw:
        return int(default)
    try:
        return int(raw)
    except ValueError:
        logger.warning("ignoring non-numeric %s=%r", name, raw)
        return int(default)


def env_float(name, default):
    raw = os.environ.get(name, "")
    if not raw:
        return float(default)
    try:
        return float(raw)
    except ValueError:
        logger.warning("ignoring non-numeric %s=%r", name, raw)
        return float(default)


def env_str(name, default=""):
    """os.environ[name] with ``default`` for unset (empty counts as
    set: an operator exporting FOO= means "explicitly blank")."""
    return os.environ.get(name, default)


_TRUTHY = frozenset({"1", "true", "yes", "on"})
_FALSY = frozenset({"0", "false", "no", "off", ""})


def env_bool(name, default=False):
    """Boolean knob: 1/true/yes/on and 0/false/no/off (case-blind).
    Unset returns ``default``; an unrecognized value logs a warning and
    falls back — same loud-typo contract as env_int."""
    raw = os.environ.get(name)
    if raw is None:
        return bool(default)
    low = raw.strip().lower()
    if low in _TRUTHY:
        return True
    if low in _FALSY:
        return False
    logger.warning("ignoring non-boolean %s=%r", name, raw)
    return bool(default)
