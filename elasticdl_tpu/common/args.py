"""Argument parsing for master and worker processes.

Reference parity: elasticdl/python/common/args.py:110-228 (the master/
worker argparse surface) — trimmed to the flags that exist in the TPU
design; the client CLI (client/) re-serializes these for pod commands the
same way the reference does (args.py:543-565).
"""

import argparse

from elasticdl_tpu.common.env_utils import env_int


def _add_common(parser):
    parser.add_argument(
        "--model_zoo",
        required=True,
        help="Model module: dotted import path or file path",
    )
    parser.add_argument("--training_data", default="")
    parser.add_argument("--validation_data", default="")
    parser.add_argument("--prediction_data", default="")
    parser.add_argument("--minibatch_size", type=int, default=32)
    parser.add_argument(
        "--data_reader_params",
        default="",
        help="k=v;k=v parameters for the data reader",
    )
    parser.add_argument(
        "--compute_dtype",
        default="",
        help="Computation dtype for the jitted step (e.g. bfloat16); "
        "params stay float32",
    )
    # reference: --model_def picks the module (and optionally the model
    # factory) inside a model-zoo DIRECTORY; --model_params is k=v;k=v
    # kwargs for custom_model (model_utils.py:79-94,139-198)
    parser.add_argument("--model_def", default="")
    parser.add_argument("--model_params", default="")
    # contract symbol-name overrides (reference model_utils.py:139-150:
    # every contract part is addressable by name); empty = default name
    add_symbol_override_arguments(parser)
    # logging controls (reference elasticdl_client args :369,392)
    add_logging_arguments(parser)


def parse_master_args(argv=None):
    parser = argparse.ArgumentParser("elasticdl_tpu master")
    _add_common(parser)
    parser.add_argument("--port", type=int, default=50001)
    parser.add_argument("--records_per_task", type=int, default=1024)
    # reference alternative task sizing: records_per_task =
    # minibatch_size * num_minibatches_per_task (master.py:152)
    parser.add_argument(
        "--num_minibatches_per_task", type=int, default=0
    )
    # accepted on the master so the client can forward it; consumed by
    # the workers the master launches
    parser.add_argument(
        "--log_loss_steps", type=int, default=LOG_LOSS_STEPS_DEFAULT
    )
    parser.add_argument("--num_epochs", type=int, default=1)
    parser.add_argument("--evaluation_steps", type=int, default=0)
    parser.add_argument("--evaluation_throttle_secs", type=int, default=0)
    parser.add_argument("--evaluation_start_delay_secs", type=int, default=0)
    parser.add_argument("--task_timeout_secs", type=float, default=30.0)
    parser.add_argument(
        "--output", default="", help="saved-model export path"
    )
    parser.add_argument("--num_workers", type=int, default=1)
    parser.add_argument("--checkpoint_dir", default="")
    parser.add_argument("--checkpoint_steps", type=int, default=0)
    parser.add_argument("--async_checkpoint", type=int, default=0)
    parser.add_argument("--grad_accum_steps", type=int, default=1)
    parser.add_argument("--keep_checkpoint_max", type=int, default=3)
    parser.add_argument("--checkpoint_dir_for_init", default="")
    parser.add_argument("--consensus_interval", type=int, default=1)
    # sparse host-PS mode, marshalled into PS pod command lines by the
    # pod manager (reference: client flags forwarded Go-PS style,
    # /root/reference/elasticdl/python/master/master.py:392-539)
    add_bool_argument(parser, "--use_async", default=0)
    parser.add_argument("--grads_to_wait", type=int, default=1)
    parser.add_argument("--sync_version_tolerance", type=int, default=0)
    add_bool_argument(parser, "--lr_staleness_modulation", default=0)
    # flags the client CLI forwards (client/args.py); consumed when the
    # master provisions pods via the instance manager
    parser.add_argument("--job_name", default="")
    # pod-spec flags for the worker/PS pods the master creates
    # (reference: the master re-emits these into pod specs,
    # master.py:392-539; k8s_resource/k8s_volume string formats)
    parser.add_argument("--image_name", default="")
    parser.add_argument("--image_pull_policy", default="")
    parser.add_argument("--restart_policy", default="Never")
    parser.add_argument("--worker_resource_request", default="")
    parser.add_argument("--worker_resource_limit", default="")
    parser.add_argument("--ps_resource_request", default="")
    parser.add_argument("--ps_resource_limit", default="")
    parser.add_argument("--worker_pod_priority", default="")
    parser.add_argument("--ps_pod_priority", default="")
    parser.add_argument("--volume", default="")
    parser.add_argument(
        "--tpu_resource",
        default="",
        help='TPU chips per worker pod, e.g. "google.com/tpu=8"',
    )
    parser.add_argument("--cluster_spec", default="")
    parser.add_argument(
        "--distribution_strategy", default="AllreduceStrategy"
    )
    parser.add_argument("--num_ps_pods", type=int, default=0)
    parser.add_argument(
        "--mesh", default="", help='axis sizes, e.g. "dp=4,fsdp=2"'
    )
    parser.add_argument("--envs", default="")
    parser.add_argument("--tensorboard_log_dir", default="")
    # observability: /metrics + /healthz + /readyz on this port
    # (0/unset = disabled; falls back to EDL_METRICS_PORT)
    parser.add_argument("--metrics_port", type=int, default=0)
    return parser.parse_args(argv)


def parse_worker_args(argv=None):
    parser = argparse.ArgumentParser("elasticdl_tpu worker")
    _add_common(parser)
    parser.add_argument("--master_addr", required=True)
    parser.add_argument("--worker_id", type=int, required=True)
    parser.add_argument(
        "--ps_addrs",
        default="",
        help="comma-separated PS addresses for sparse embedding models",
    )
    parser.add_argument(
        "--mode",
        default="training",
        choices=["training", "evaluation", "prediction"],
    )
    parser.add_argument("--report_version_steps", type=int, default=10)
    # log the training loss every N batches (reference --log_loss_steps)
    parser.add_argument(
        "--log_loss_steps", type=int, default=LOG_LOSS_STEPS_DEFAULT
    )
    # async dense checkpointing: the save's file writes ride orbax's
    # background machinery instead of blocking the training loop
    # (single-process workers only; lockstep multi-host stays sync)
    parser.add_argument("--async_checkpoint", type=int, default=0)
    # split each batch into k microbatches with one optimizer update
    # (exact large-batch semantics, activation memory / k)
    parser.add_argument("--grad_accum_steps", type=int, default=1)
    parser.add_argument("--checkpoint_dir", default="")
    parser.add_argument("--checkpoint_steps", type=int, default=0)
    parser.add_argument("--keep_checkpoint_max", type=int, default=3)
    parser.add_argument("--checkpoint_dir_for_init", default="")
    # multi-host elastic SPMD: join the master's mesh rendezvous and
    # (re)initialize jax.distributed; restart on mesh-epoch change
    from elasticdl_tpu.parallel.multihost import COORDINATOR_PORT

    parser.add_argument("--multihost", type=int, default=0)
    parser.add_argument(
        "--coordinator_port", type=int, default=COORDINATOR_PORT
    )
    # mesh axis sizes for the SPMD/lockstep trainers; dp=-1 absorbs the
    # remaining devices so the flag survives elastic world-size changes
    parser.add_argument(
        "--mesh", default="", help='axis sizes, e.g. "dp=4,fsdp=2"'
    )
    # identity in the master's mesh rendezvous; defaults to the pod
    # hostname — override for several workers on one machine
    parser.add_argument("--worker_host", default="")
    # pipelined sparse training (async PS only): overlap batch N+1's PS
    # pull with batch N's device step; optional hot-row reuse and push
    # accumulation (the reference's get_model_steps analogue)
    parser.add_argument("--sparse_pipeline", type=int, default=0)
    parser.add_argument("--sparse_cache_staleness", type=int, default=0)
    parser.add_argument("--sparse_push_interval", type=int, default=1)
    # lockstep consensus cadence (worker.py _train_batches_lockstep);
    # EDL_CONSENSUS_INTERVAL overrides for A/B harnesses
    parser.add_argument(
        "--consensus_interval",
        type=int,
        default=env_int("EDL_CONSENSUS_INTERVAL", 1),
    )
    # observability: /metrics + /healthz + /readyz on this port
    # (0/unset = disabled; falls back to EDL_METRICS_PORT)
    parser.add_argument("--metrics_port", type=int, default=0)
    return parser.parse_args(argv)


# the contract symbol-name override flags (reference
# model_utils.py:139-150) — ONE list consumed by every parser that
# defines them, symbol_overrides_from_args, and the pod manager's
# forwarded-flags set, so a new override cannot be added to one surface
# and silently dropped by another
SYMBOL_OVERRIDE_KEYS = (
    "loss",
    "optimizer",
    "dataset_fn",
    "eval_metrics_fn",
    "callbacks",
    "prediction_outputs_processor",
)


def add_symbol_override_arguments(parser):
    for key in SYMBOL_OVERRIDE_KEYS:
        parser.add_argument("--%s" % key, default="")


LOG_LOSS_STEPS_DEFAULT = 100


def bool_flag(value):
    """Accept the reference's bool spellings (--use_async=True,
    scripts/client_test.sh:46) alongside 0/1."""
    lowered = str(value).strip().lower()
    if lowered in ("true", "yes", "1"):
        return 1
    if lowered in ("false", "no", "0"):
        return 0
    raise argparse.ArgumentTypeError(
        "expected a boolean (true/false/1/0), got %r" % (value,)
    )


def add_bool_argument(parser, name, default=0, help=None):
    """Register a bool flag the way the reference's ``add_bool_param``
    does (/root/reference/elasticdl_client/common/args.py:532-540):
    ``nargs="?"`` with ``const=not default`` so the bare spelling
    (``--use_async`` with no value) flips the default, while the
    explicit spellings (``--use_async=True``, ``--use_async 1``) still
    parse via ``bool_flag``."""
    parser.add_argument(
        name,
        nargs="?",
        const=0 if default else 1,
        type=bool_flag,
        default=default,
        help=help,
    )


def add_logging_arguments(parser):
    """--log_level / --log_file_path, shared by every parser that
    exposes them (client train/evaluate/predict, master, worker) so a
    default or validation change cannot drift between surfaces."""
    parser.add_argument("--log_level", default="")
    parser.add_argument("--log_file_path", default="")


def symbol_overrides_from_args(args):
    """Collect the non-empty contract symbol-name flags into the
    ``symbol_overrides`` dict ``get_model_spec`` takes (None if all
    default)."""
    overrides = {
        k: getattr(args, k)
        for k in SYMBOL_OVERRIDE_KEYS
        if getattr(args, k, "")
    }
    return overrides or None


def parse_params_string(params: str) -> dict:
    """Parse 'k=v;k=v' strings (reference: model_utils.py:79-94). Values
    are eval'd as Python literals when possible."""
    import ast

    result = {}
    for part in (params or "").split(";"):
        part = part.strip()
        if not part:
            continue
        if "=" not in part:
            raise ValueError("Bad params segment %r" % part)
        key, value = part.split("=", 1)
        try:
            result[key.strip()] = ast.literal_eval(value.strip())
        except (ValueError, SyntaxError):
            result[key.strip()] = value.strip()
    return result
