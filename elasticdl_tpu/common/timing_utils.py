"""Opt-in wall-clock accounting per phase + JAX profiler hooks.

Reference parity: common/timing_utils.py:17-48 — `Timing` accumulates
seconds per named phase (task_process, batch_process, get_model,
report_gradient) and dumps totals at DEBUG when a task completes.

TPU additions the reference lacks (SURVEY.md §5 "tracing: minimal"):
- a context-manager surface (`with timing.timeit("batch_process")`)
- `device_sync` blocks on the last JAX output so a phase that launched
  async device work is charged its real duration, not dispatch time
- `trace()` wraps a region in jax.profiler for TensorBoard's trace
  viewer when EDL_PROFILE_DIR is set.
"""

import contextlib
import os
import time

from elasticdl_tpu.common.log_utils import default_logger as _logger_factory

logger = _logger_factory("elasticdl_tpu.common.timing_utils")

PROFILE_DIR_ENV = "EDL_PROFILE_DIR"


class Timing:
    def __init__(self, enabled=None):
        if enabled is None:
            enabled = os.environ.get("EDL_TIMING", "") not in ("", "0")
        self._enabled = enabled
        self._totals = {}
        self._counts = {}

    @property
    def enabled(self):
        return self._enabled

    def start(self):
        return time.time() if self._enabled else 0.0

    def end_record(self, phase, start):
        if not self._enabled:
            return
        self._totals[phase] = self._totals.get(phase, 0.0) + (
            time.time() - start
        )
        self._counts[phase] = self._counts.get(phase, 0) + 1

    def end_record_sync(self, phase, start, result=None):
        """Block on a JAX array (if given) before recording, so async
        dispatch doesn't make device phases look free."""
        if not self._enabled:
            return
        if result is not None:
            try:
                import jax

                jax.block_until_ready(result)
            except Exception:
                pass
        self.end_record(phase, start)

    @contextlib.contextmanager
    def timeit(self, phase, sync_result=None):
        """Time a block; pass sync_result=lambda: x to block on a JAX
        array before stopping the clock (async dispatch otherwise makes
        device phases look free)."""
        start = self.start()
        try:
            yield
        finally:
            if self._enabled and sync_result is not None:
                result = sync_result()
                if result is not None:
                    try:
                        import jax

                        jax.block_until_ready(result)
                    except Exception:
                        pass
            self.end_record(phase, start)

    def summary(self):
        return {
            phase: {
                "seconds": round(self._totals[phase], 4),
                "count": self._counts[phase],
            }
            for phase in sorted(self._totals)
        }

    def report(self, context=""):
        """DEBUG dump + reset, as the reference does per finished task
        (worker.py:810-812)."""
        if not self._enabled or not self._totals:
            return
        logger.info("Timing%s: %s",
                    " (%s)" % context if context else "", self.summary())
        self._totals.clear()
        self._counts.clear()


@contextlib.contextmanager
def trace(name="edl_train"):
    """jax.profiler trace region -> EDL_PROFILE_DIR (view in
    TensorBoard's trace viewer). No-op when the env var is unset."""
    profile_dir = os.environ.get(PROFILE_DIR_ENV, "")
    if not profile_dir:
        yield
        return
    import jax

    with jax.profiler.trace(os.path.join(profile_dir, name)):
        yield


@contextlib.contextmanager
def step_annotation(name, step):
    """Named sub-region inside a trace (StepTraceAnnotation)."""
    if not os.environ.get(PROFILE_DIR_ENV, ""):
        yield
        return
    import jax

    with jax.profiler.StepTraceAnnotation(name, step_num=step):
        yield
