"""Opt-in wall-clock accounting per phase + JAX profiler hooks.

Reference parity: common/timing_utils.py:17-48 — `Timing` accumulates
seconds per named phase (task_process, batch_process, get_model,
report_gradient) and dumps totals at DEBUG when a task completes.

TPU additions the reference lacks (SURVEY.md §5 "tracing: minimal"):
- a context-manager surface (`with timing.timeit("batch_process")`)
- `device_sync` blocks on the last JAX output so a phase that launched
  async device work is charged its real duration, not dispatch time
- `trace()` wraps a region in jax.profiler for TensorBoard's trace
  viewer when EDL_PROFILE_DIR is set
- a metrics bridge: every recorded phase also feeds the observability
  registry (``edl_phase_seconds`` histogram + ``edl_step_time_seconds``
  gauge for the step phase), so live dashboards see the SAME clock the
  DEBUG dump uses — no second timing source. The bridge measures
  whenever either EDL_TIMING or metrics collection is on, and costs
  nothing when both are off.
"""

import contextlib
import os
import time

from elasticdl_tpu.common.env_utils import env_str
from elasticdl_tpu.common.log_utils import default_logger as _logger_factory
from elasticdl_tpu.observability import metrics as obs_metrics

logger = _logger_factory("elasticdl_tpu.common.timing_utils")

PROFILE_DIR_ENV = "EDL_PROFILE_DIR"

# the phase whose duration is "the step" for the step-time gauge and
# derived rates (examples/sec, MFU)
STEP_PHASE = "batch_process"


class Timing:
    def __init__(self, enabled=None):
        if enabled is None:
            enabled = env_str("EDL_TIMING", "") not in ("", "0")
        self._enabled = enabled
        self._totals = {}
        self._counts = {}
        # phase -> duration of the most recent record; consumers derive
        # rates (worker examples/sec) without running a second clock
        self.last_seconds = {}
        self._metrics_on = obs_metrics.metrics_enabled()
        if self._metrics_on:
            self._phase_hist = obs_metrics.histogram(
                "edl_phase_seconds",
                "Wall-clock per training-loop phase (timing_utils bridge)",
                ("phase",),
            )
            self._step_gauge = obs_metrics.gauge(
                "edl_step_time_seconds",
                "Duration of the most recent train step",
            )
        self._measure = self._enabled or self._metrics_on

    @property
    def enabled(self):
        return self._enabled

    def start(self):
        return time.time() if self._measure else 0.0

    def end_record(self, phase, start):
        if not self._measure:
            return
        elapsed = time.time() - start
        self.last_seconds[phase] = elapsed
        if self._metrics_on:
            self._phase_hist.labels(phase).observe(elapsed)
            if phase == STEP_PHASE:
                self._step_gauge.set(elapsed)
        if not self._enabled:
            return
        self._totals[phase] = self._totals.get(phase, 0.0) + elapsed
        self._counts[phase] = self._counts.get(phase, 0) + 1

    def end_record_sync(self, phase, start, result=None):
        """Block on a JAX array (if given) before recording, so async
        dispatch doesn't make device phases look free."""
        if not self._measure:
            return
        if result is not None:
            try:
                import jax

                jax.block_until_ready(result)
            except Exception:
                pass
        self.end_record(phase, start)

    @contextlib.contextmanager
    def timeit(self, phase, sync_result=None):
        """Time a block; pass sync_result=lambda: x to block on a JAX
        array before stopping the clock (async dispatch otherwise makes
        device phases look free)."""
        start = self.start()
        try:
            yield
        finally:
            if self._measure and sync_result is not None:
                result = sync_result()
                if result is not None:
                    try:
                        import jax

                        jax.block_until_ready(result)
                    except Exception:
                        pass
            self.end_record(phase, start)

    def summary(self):
        return {
            phase: {
                "seconds": round(self._totals[phase], 4),
                "count": self._counts[phase],
            }
            for phase in sorted(self._totals)
        }

    def report(self, context=""):
        """DEBUG dump + reset, as the reference does per finished task
        (worker.py:810-812)."""
        if not self._enabled or not self._totals:
            return
        logger.info("Timing%s: %s",
                    " (%s)" % context if context else "", self.summary())
        self._totals.clear()
        self._counts.clear()


@contextlib.contextmanager
def trace(name="edl_train"):
    """jax.profiler trace region -> EDL_PROFILE_DIR (view in
    TensorBoard's trace viewer). No-op when the env var is unset."""
    profile_dir = env_str(PROFILE_DIR_ENV, "")
    if not profile_dir:
        yield
        return
    import jax

    with jax.profiler.trace(os.path.join(profile_dir, name)):
        yield


@contextlib.contextmanager
def step_annotation(name, step):
    """Named sub-region inside a trace (StepTraceAnnotation)."""
    if not env_str(PROFILE_DIR_ENV, ""):
        yield
        return
    import jax

    with jax.profiler.StepTraceAnnotation(name, step_num=step):
        yield
