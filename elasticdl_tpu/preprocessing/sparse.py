"""PaddedSparse: the fixed-shape stand-in for tf.SparseTensor/RaggedTensor.

The reference's preprocessing layers pass tf.SparseTensor / tf.RaggedTensor
between layers (elasticdl_preprocessing/layers/to_sparse.py, to_ragged.py).
XLA requires static shapes, so the TPU-native representation is a dense
``[batch, max_len]`` id matrix plus a boolean validity mask — every op on
it is jit-compatible and maps onto vectorized TPU compute instead of
per-row dynamic shapes.

Conversions at the pipeline boundary (python lists of variable length ->
padded matrices) happen host-side in numpy; everything downstream
(combiners, embedding lookups, offsets) runs on device.
"""

from typing import NamedTuple, Optional

import jax.numpy as jnp
import numpy as np

PAD_ID = -1


class PaddedSparse(NamedTuple):
    """``values``: [batch, max_len] ids (or numerics), pad slots hold
    ``PAD_ID`` (ids) / 0 (numerics); ``mask``: [batch, max_len] bool,
    True on real entries; ``weights``: optional [batch, max_len] float."""

    values: object
    mask: object
    weights: Optional[object] = None

    @property
    def batch_size(self):
        return self.values.shape[0]

    @property
    def max_len(self):
        return self.values.shape[1]

    def with_values(self, values):
        """Same sparsity pattern, new values (the map_flat_values of the
        ragged/sparse world: layers transform values, keep the mask)."""
        return PaddedSparse(values, self.mask, self.weights)

    def row_lengths(self):
        return jnp.sum(self.mask.astype(jnp.int32), axis=1)


def from_row_lists(rows, max_len=None, dtype=np.int64, weights=None):
    """Python lists of variable length -> PaddedSparse (host-side)."""
    max_len = max_len or max((len(r) for r in rows), default=1) or 1
    n = len(rows)
    values = np.zeros((n, max_len), dtype=dtype)
    mask = np.zeros((n, max_len), dtype=bool)
    w = None
    if weights is not None:
        w = np.zeros((n, max_len), dtype=np.float32)
    if np.issubdtype(np.dtype(dtype), np.integer):
        values[:] = PAD_ID
    for i, row in enumerate(rows):
        row = list(row)[:max_len]
        values[i, : len(row)] = row
        mask[i, : len(row)] = True
        if w is not None:
            wr = list(weights[i])[:max_len]
            w[i, : len(wr)] = wr
    return PaddedSparse(values, mask, w)


def to_padded_sparse(dense, ignore_value=None):
    """Dense [batch, len] -> PaddedSparse, dropping ``ignore_value``
    entries from the mask. The reference's ToSparse/ToRagged layers
    (to_sparse.py:34-63) do this with default ignore "" for strings and
    -1 for numerics; same defaults here."""
    dense = np.asarray(dense) if not hasattr(dense, "dtype") else dense
    if ignore_value is None:
        if hasattr(dense, "dtype") and dense.dtype.kind in ("U", "S", "O"):
            ignore_value = ""
        else:
            ignore_value = -1
    if hasattr(dense, "dtype") and dense.dtype.kind in ("U", "S", "O"):
        mask = np.asarray(dense) != ignore_value
        return PaddedSparse(np.asarray(dense), mask)
    mask = dense != ignore_value
    return PaddedSparse(dense, mask)


def dense_rows(sp: PaddedSparse):
    """PaddedSparse -> list of python lists (host-side, for tests/IO)."""
    values = np.asarray(sp.values)
    mask = np.asarray(sp.mask)
    return [list(values[i][mask[i]]) for i in range(values.shape[0])]
