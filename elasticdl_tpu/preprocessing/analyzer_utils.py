"""Feature-statistics lookup from the environment.

Reference parity: elasticdl_preprocessing/utils/analyzer_utils.py:22-60 —
a SQLFlow analysis job plants per-feature min/max/mean/stddev/vocab
statistics into environment variables; model code reads them with a
default fallback so it also runs without the analysis step.
"""

import os

_MIN_ENV = "_edl_analysis_min_{}"
_MAX_ENV = "_edl_analysis_max_{}"
_MEAN_ENV = "_edl_analysis_mean_{}"
_STDDEV_ENV = "_edl_analysis_stddev_{}"
_COUNT_ENV = "_edl_analysis_distinct_count_{}"
_VOCAB_ENV = "_edl_analysis_vocab_{}"


def _get_float(template, feature_name, default_value):
    value = os.getenv(template.format(feature_name))
    return default_value if value is None else float(value)


def get_min(feature_name, default_value):
    return _get_float(_MIN_ENV, feature_name, default_value)


def get_max(feature_name, default_value):
    return _get_float(_MAX_ENV, feature_name, default_value)


def get_mean(feature_name, default_value):
    return _get_float(_MEAN_ENV, feature_name, default_value)


def get_stddev(feature_name, default_value):
    return _get_float(_STDDEV_ENV, feature_name, default_value)


def get_distinct_count(feature_name, default_value):
    value = os.getenv(_COUNT_ENV.format(feature_name))
    return default_value if value is None else int(value)


def get_vocabulary(feature_name, default_value=None):
    """Comma-separated vocabulary planted by the analysis job."""
    value = os.getenv(_VOCAB_ENV.format(feature_name))
    if value is None:
        return default_value
    return [term for term in value.split(",") if term]
