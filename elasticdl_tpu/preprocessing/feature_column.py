"""Feature columns: declarative feature -> dense-input mapping.

Reference parity:
- elasticdl_preprocessing/feature_column/feature_column.py
  (concatenated_categorical_column merging many categorical id spaces by
  offsetting into one, :22-230)
- elasticdl/python/elasticdl/feature_column/feature_column.py
  (embedding_column with sum/mean/sqrtn combiner, :25-221)
- the stock TF columns the model zoo uses (numeric, bucketized,
  identity/vocab/hash categorical, indicator).

TPU redesign: a column is a small object with ``output_dim`` and
``__call__(features) -> [batch, output_dim] array`` (dense) or a
PaddedSparse (categorical). String-consuming columns run host-side;
numeric ones are jit-safe. ``DenseFeatures`` is the flax module that
owns embedding weights and concatenates all column outputs — the
replacement for tf.keras.layers.DenseFeatures.

Embedding tables bigger than the PS routing threshold are rewritten to
the host-PS path by train/model_handler.py, not here: the column layer
stays storage-agnostic.
"""

import jax.numpy as jnp
import numpy as np
import flax.linen as nn

from elasticdl_tpu.preprocessing import layers as pp
from elasticdl_tpu.preprocessing.sparse import (
    PaddedSparse,
    to_padded_sparse,
)


class NumericColumn:
    def __init__(self, key, shape=(1,), normalizer_fn=None):
        self.key = key
        self.shape = tuple(shape)
        self.normalizer_fn = normalizer_fn
        self.output_dim = int(np.prod(self.shape))

    @property
    def name(self):
        return self.key

    def __call__(self, features):
        x = jnp.asarray(features[self.key], jnp.float32)
        if x.ndim == 1:
            x = x[:, None]
        x = x.reshape((x.shape[0], self.output_dim))
        if self.normalizer_fn is not None:
            x = self.normalizer_fn(x)
        return x


class BucketizedColumn:
    """numeric -> bucket ids (categorical with len(boundaries)+1 buckets)."""

    def __init__(self, source: NumericColumn, boundaries):
        self.source = source
        self.boundaries = list(boundaries)
        self._disc = pp.Discretization(self.boundaries)
        self.num_buckets = len(self.boundaries) + 1

    @property
    def name(self):
        return self.source.name + "_bucketized"

    def ids(self, features):
        ids = self._disc(self.source(features)).astype(jnp.int64)
        return PaddedSparse(ids, jnp.ones_like(ids, dtype=bool))


class IdentityCategoricalColumn:
    def __init__(self, key, num_buckets, default_value=None):
        self.key = key
        self.num_buckets = num_buckets
        self.default_value = default_value

    @property
    def name(self):
        return self.key

    def ids(self, features):
        raw = features[self.key]
        if isinstance(raw, PaddedSparse):
            sp = raw
        else:
            # entries outside [0, num_buckets) drop out of the mask
            # unless a default_value re-routes them (TF identity column
            # semantics).
            sp = to_padded_sparse(jnp.asarray(raw), ignore_value=-1)
        values = jnp.asarray(sp.values)
        in_range = (values >= 0) & (values < self.num_buckets)
        if self.default_value is not None:
            values = jnp.where(
                in_range, values, jnp.int64(self.default_value)
            )
            mask = jnp.asarray(sp.mask)
        else:
            mask = jnp.asarray(sp.mask) & in_range
            values = jnp.where(in_range, values, 0)
        return PaddedSparse(values, mask, sp.weights)


class VocabularyCategoricalColumn:
    def __init__(self, key, vocabulary_list, num_oov_buckets=0):
        self.key = key
        self.vocabulary_list = list(vocabulary_list)
        self._lookup = pp.IndexLookup(
            self.vocabulary_list, num_oov_tokens=max(1, num_oov_buckets)
        )
        self._keep_oov = num_oov_buckets > 0
        self.num_buckets = len(self.vocabulary_list) + max(
            0, num_oov_buckets
        )

    @property
    def name(self):
        return self.key

    def ids(self, features):
        raw = features[self.key]
        sp = raw if isinstance(raw, PaddedSparse) else to_padded_sparse(
            np.asarray(raw)
        )
        ids = self._lookup(np.asarray(sp.values))
        mask = np.asarray(sp.mask)
        if not self._keep_oov:
            mask = mask & (ids < len(self.vocabulary_list))
            ids = np.where(mask, ids, 0)
        return PaddedSparse(ids, mask, sp.weights)


class HashCategoricalColumn:
    def __init__(self, key, hash_bucket_size):
        self.key = key
        self.num_buckets = hash_bucket_size
        self._hashing = pp.Hashing(hash_bucket_size)

    @property
    def name(self):
        return self.key

    def ids(self, features):
        raw = features[self.key]
        sp = raw if isinstance(raw, PaddedSparse) else to_padded_sparse(
            np.asarray(raw) if _host_array(raw) else jnp.asarray(raw)
        )
        return sp.with_values(self._hashing(sp.values))


def _host_array(x):
    return isinstance(x, np.ndarray) or isinstance(x, (list, tuple))


class ConcatenatedCategoricalColumn:
    """Merge N categorical columns into one id space by offsetting —
    one big embedding table instead of N small ones.

    Reference: elasticdl_preprocessing/feature_column/feature_column.py:
    22-178 (offsets are exclusive prefix sums of num_buckets).
    """

    def __init__(self, categorical_columns):
        self.columns = list(categorical_columns)
        self.offsets = list(
            np.cumsum([0] + [c.num_buckets for c in self.columns])[:-1]
        )
        self.num_buckets = int(
            sum(c.num_buckets for c in self.columns)
        )

    @property
    def name(self):
        return "_C_".join(c.name for c in self.columns)

    def ids(self, features):
        parts = [c.ids(features) for c in self.columns]
        return pp.ConcatenateWithOffset(self.offsets, axis=1)(parts)


class EmbeddingColumn:
    """categorical ids -> combined embedding vector.

    Reference: elasticdl/python/elasticdl/feature_column/feature_column.py
    :25-221. The weight lives in DenseFeatures (flax); this object only
    describes the mapping.
    """

    def __init__(self, categorical, dimension, combiner="mean"):
        self.categorical = categorical
        self.dimension = dimension
        self.combiner = combiner
        self.output_dim = dimension

    @property
    def name(self):
        return self.categorical.name + "_embedding"

    @property
    def table_shape(self):
        return (self.categorical.num_buckets, self.dimension)


class IndicatorColumn:
    """categorical ids -> multi-hot counts (the wide half of wide&deep)."""

    def __init__(self, categorical):
        self.categorical = categorical
        self.output_dim = categorical.num_buckets

    @property
    def name(self):
        return self.categorical.name + "_indicator"

    def __call__(self, features):
        sp = self.categorical.ids(features)
        return _multi_hot(sp, self.output_dim)


def _multi_hot(sp: PaddedSparse, num_buckets):
    """Scatter-add of the mask: multi-hot with counts."""
    ids = jnp.asarray(sp.values).astype(jnp.int32)
    mask = jnp.asarray(sp.mask)
    safe = jnp.where(mask, ids, 0)
    return jnp.zeros((ids.shape[0], num_buckets), jnp.float32).at[
        jnp.arange(ids.shape[0])[:, None], safe
    ].add(mask.astype(jnp.float32))


# Factory functions mirroring the tf.feature_column API names used by the
# reference model zoo (model_zoo/census_wide_deep_model/...).
def numeric_column(key, shape=(1,), normalizer_fn=None):
    return NumericColumn(key, shape, normalizer_fn)


def bucketized_column(source, boundaries):
    return BucketizedColumn(source, boundaries)


def categorical_column_with_identity(key, num_buckets, default_value=None):
    return IdentityCategoricalColumn(key, num_buckets, default_value)


def categorical_column_with_vocabulary_list(
    key, vocabulary_list, num_oov_buckets=0
):
    return VocabularyCategoricalColumn(key, vocabulary_list, num_oov_buckets)


def categorical_column_with_hash_bucket(key, hash_bucket_size):
    return HashCategoricalColumn(key, hash_bucket_size)


def concatenated_categorical_column(categorical_columns):
    return ConcatenatedCategoricalColumn(categorical_columns)


def embedding_column(categorical, dimension, combiner="mean"):
    return EmbeddingColumn(categorical, dimension, combiner)


def indicator_column(categorical):
    return IndicatorColumn(categorical)


class DenseFeatures(nn.Module):
    """Apply a list of columns to a features dict and concatenate —
    the flax replacement for tf.keras.layers.DenseFeatures. Owns one
    embedding table per EmbeddingColumn.

    String-consuming columns (vocab/hash over numpy arrays) run on host
    BEFORE jit; call ``preprocess(features)`` from the dataset_fn to
    materialize their ids, then the module's __call__ is fully jit-safe.
    """

    columns: tuple

    def preprocess(self, features):
        """Host-side stage: resolve string-consuming categorical columns
        to PaddedSparse ids and DROP the raw string keys, so the jitted
        step sees only numeric arrays."""
        out = dict(features)
        consumed = set()
        for col in self.columns:
            cat = getattr(col, "categorical", None)
            if cat is not None and _consumes_strings(cat):
                out[_ids_key(cat)] = cat.ids(features)
                consumed.update(_feature_keys(cat))
        for key in consumed:
            out.pop(key, None)
        return out

    @nn.compact
    def __call__(self, features):
        outputs = []
        for col in self.columns:
            if isinstance(col, EmbeddingColumn):
                table = self.param(
                    col.name,
                    nn.initializers.variance_scaling(
                        1.0, "fan_out", "uniform"
                    ),
                    col.table_shape,
                )
                sp = _resolve_ids(col.categorical, features)
                outputs.append(
                    _combine(table, sp, col.combiner)
                )
            elif isinstance(col, IndicatorColumn):
                sp = _resolve_ids(col.categorical, features)
                outputs.append(_multi_hot(sp, col.output_dim))
            else:
                outputs.append(col(features))
        return jnp.concatenate(outputs, axis=-1)


def _ids_key(categorical):
    return "__ids__" + categorical.name


def _consumes_strings(categorical):
    return isinstance(
        categorical,
        (VocabularyCategoricalColumn, HashCategoricalColumn),
    ) or (
        isinstance(categorical, ConcatenatedCategoricalColumn)
        and any(_consumes_strings(c) for c in categorical.columns)
    )


def _feature_keys(categorical):
    """Raw feature keys consumed by STRING-consuming leaves only — a
    numeric key (e.g. a bucketized column's source) may be shared with
    dense columns and must survive preprocess()."""
    if isinstance(categorical, ConcatenatedCategoricalColumn):
        keys = set()
        for c in categorical.columns:
            keys.update(_feature_keys(c))
        return keys
    if isinstance(
        categorical, (VocabularyCategoricalColumn, HashCategoricalColumn)
    ):
        return {categorical.key}
    return set()


def _resolve_ids(categorical, features):
    key = _ids_key(categorical)
    if key in features:
        return features[key]
    return categorical.ids(features)


def combine_gathered(gathered, w, combiner):
    """Weighted sum/mean/sqrtn over the sparse-slot axis: gathered is
    [batch, len, dim], w is [batch, len] (0 on padded slots). Shared by
    the on-device table path (_combine) and the host-PS pre-gathered
    path (train/model_handler.PSEmbeddingColumn)."""
    summed = jnp.einsum("blh,bl->bh", gathered, w)
    if combiner == "sum":
        return summed
    denom = jnp.sum(w, axis=1, keepdims=True)
    if combiner == "sqrtn":
        denom = jnp.sqrt(jnp.sum(w * w, axis=1, keepdims=True))
    return summed / jnp.maximum(denom, 1e-12)


def _combine(table, sp: PaddedSparse, combiner):
    ids = jnp.asarray(sp.values)
    mask = jnp.asarray(sp.mask)
    safe = jnp.where(mask, ids, 0).astype(jnp.int32)
    rows = jnp.take(table, safe, axis=0)
    w = mask.astype(rows.dtype)
    if sp.weights is not None:
        w = w * jnp.asarray(sp.weights, rows.dtype)
    return combine_gathered(rows, w, combiner)
