"""Feature-preprocessing transforms.

Reference parity: elasticdl_preprocessing/layers/* (Hashing, IndexLookup,
Discretization, LogRound, RoundIdentity, Normalizer, ToNumber, ToSparse/
ToRagged, ConcatenateWithOffset, SparseEmbedding). The TPU redesign
splits each transform by where it must run:

- **String handling is host-side** (numpy object arrays): XLA has no
  string type. Hashing/IndexLookup/ToNumber accept numpy string arrays
  and return integer/float numpy arrays the jitted step consumes.
- **Numeric transforms are jit-safe** (pure jnp): Discretization,
  LogRound, RoundIdentity, Normalizer, ConcatenateWithOffset and integer
  Hashing/IndexLookup trace into the compiled step, so XLA fuses them
  into the surrounding program instead of running per-batch python.
- **Ragged/sparse inputs** ride the fixed-shape PaddedSparse (see
  sparse.py); every layer maps over ``values`` and preserves the mask,
  the moral equivalent of the reference's ``tf.ragged.map_flat_values``.

Every layer is a plain callable; SparseEmbedding (the only one with
trainable weight) is a flax Module.
"""

import hashlib

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np

from elasticdl_tpu.preprocessing.sparse import (
    PaddedSparse,
    to_padded_sparse,
)


def _is_string_array(x):
    return isinstance(x, np.ndarray) and x.dtype.kind in ("U", "S", "O")


def _map_values(inputs, fn):
    """Apply fn to the value tensor, preserving PaddedSparse structure."""
    if isinstance(inputs, PaddedSparse):
        return inputs.with_values(fn(inputs.values))
    return fn(inputs)


def _string_hash(s, num_bins):
    digest = hashlib.md5(str(s).encode("utf-8")).digest()
    return int.from_bytes(digest[:8], "little") % num_bins


def _int_mix_hash(x, num_bins):
    """splitmix64-style mixer, jit-safe (device path for int features)."""
    x = x.astype(jnp.uint32)
    x = (x ^ (x >> 16)) * jnp.uint32(0x7FEB352D)
    x = (x ^ (x >> 15)) * jnp.uint32(0x846CA68B)
    x = x ^ (x >> 16)
    return (x % jnp.uint32(num_bins)).astype(jnp.int32)


class Hashing:
    """value -> hash(value) % num_bins.

    Reference: elasticdl_preprocessing/layers/hashing.py:19-100. Strings
    (and host numpy ints, for cross-path consistency) use a stable md5
    bucket; traced integer arrays use a jit-safe integer mixer. Both are
    deterministic across processes — the property the reference needs
    when many elastic workers must agree on feature buckets.
    """

    def __init__(self, num_bins):
        if not num_bins or num_bins <= 0:
            raise ValueError("num_bins must be a positive integer")
        self.num_bins = num_bins

    def __call__(self, inputs):
        return _map_values(inputs, self._hash)

    def _hash(self, values):
        if _is_string_array(values):
            flat = [
                _string_hash(v, self.num_bins) for v in values.reshape(-1)
            ]
            return np.array(flat, dtype=np.int64).reshape(values.shape)
        if isinstance(values, np.ndarray):
            flat = [
                _string_hash(int(v), self.num_bins)
                for v in values.reshape(-1)
            ]
            return np.array(flat, dtype=np.int64).reshape(values.shape)
        return _int_mix_hash(values, self.num_bins)


class IndexLookup:
    """vocabulary term -> zero-based index; OOV -> len(vocab) +
    hash(term) % num_oov_tokens.

    Reference: elasticdl_preprocessing/layers/index_lookup.py:22-120
    (vocabulary list or one-token-per-line file; OOV bucketing).
    Host-side (strings live here); emits int64 numpy arrays.
    """

    def __init__(self, vocabulary=None, num_oov_tokens=1):
        if isinstance(vocabulary, str):
            with open(vocabulary) as f:
                vocabulary = [line.rstrip("\n") for line in f if line.strip()]
        vocabulary = list(vocabulary or [])
        if len(set(vocabulary)) != len(vocabulary):
            raise ValueError("vocabulary contains repeated terms")
        self.vocabulary = vocabulary
        self.num_oov_tokens = max(1, num_oov_tokens)
        self._table = {term: i for i, term in enumerate(vocabulary)}

    def vocab_size(self):
        return len(self.vocabulary) + self.num_oov_tokens

    def __call__(self, inputs):
        return _map_values(inputs, self._lookup)

    def _lookup(self, values):
        values = np.asarray(values)
        flat = []
        for v in values.reshape(-1):
            key = v if isinstance(v, str) else str(v)
            idx = self._table.get(key)
            if idx is None:
                idx = len(self.vocabulary) + _string_hash(
                    key, self.num_oov_tokens
                )
            flat.append(idx)
        return np.array(flat, dtype=np.int64).reshape(values.shape)


class Discretization:
    """x -> bucket index over sorted boundaries; bins include the left
    boundary and exclude the right (bins=[0,1,2] -> 4 buckets).

    Reference: elasticdl_preprocessing/layers/discretization.py:20-77.
    jit-safe (jnp.searchsorted compiles to a vectorized compare tree).
    """

    def __init__(self, bins):
        self.bins = jnp.asarray(list(bins), jnp.float32)

    def num_bins(self):
        return len(self.bins) + 1

    def __call__(self, inputs):
        return _map_values(
            inputs,
            lambda v: jnp.searchsorted(
                self.bins, jnp.asarray(v, jnp.float32), side="right"
            ).astype(jnp.int32),
        )


class LogRound:
    """x -> round(log_base(x)), clipped to [0, num_bins); non-positive
    inputs map to default_value.

    Reference: elasticdl_preprocessing/layers/log_round.py:20-90.
    """

    def __init__(self, num_bins, default_value=0, base=None):
        self.num_bins = num_bins
        self.default_value = default_value
        self.base = base

    def __call__(self, inputs):
        return _map_values(inputs, self._log_round)

    def _log_round(self, values):
        x = jnp.asarray(values, jnp.float32)
        logs = jnp.log(jnp.maximum(x, 1e-30))
        if self.base is not None:
            logs = logs / jnp.log(jnp.float32(self.base))
        out = jnp.round(logs).astype(jnp.int32)
        out = jnp.where(x <= 0, jnp.int32(self.default_value), out)
        return jnp.clip(out, 0, self.num_bins - 1)


class RoundIdentity:
    """x -> round(x) clipped to [0, num_buckets]; a degenerate bucketize
    where the value is its own bucket.

    Reference: elasticdl_preprocessing/layers/round_identity.py:20-80.
    """

    def __init__(self, num_buckets, default_value=0):
        self.num_buckets = num_buckets
        self.default_value = default_value

    def __call__(self, inputs):
        return _map_values(
            inputs,
            lambda v: jnp.clip(
                jnp.round(jnp.asarray(v, jnp.float32)), 0, self.num_buckets
            ).astype(jnp.int64),
        )


class Normalizer:
    """x -> (x - subtractor) / divisor.

    Reference: elasticdl_preprocessing/layers/normalizer.py:17-80.
    """

    def __init__(self, subtractor, divisor):
        if divisor == 0:
            raise ValueError("The divisor cannot be 0")
        self.subtractor = subtractor
        self.divisor = divisor

    def __call__(self, inputs):
        return _map_values(
            inputs,
            lambda v: (jnp.asarray(v, jnp.float32) - self.subtractor)
            / self.divisor,
        )


class ToNumber:
    """Parse strings to numbers ("" -> default_value); numeric inputs are
    cast. Host-side for strings, jit-safe for numerics.

    Reference: elasticdl_preprocessing/layers/to_number.py:33-90.
    """

    def __init__(self, out_dtype, default_value=0):
        self.out_dtype = np.dtype(out_dtype)
        self.default_value = default_value

    def __call__(self, inputs):
        return _map_values(inputs, self._convert)

    def _convert(self, values):
        if _is_string_array(values):
            flat = []
            for v in np.asarray(values).reshape(-1):
                if v == "":
                    flat.append(self.default_value)
                elif np.issubdtype(self.out_dtype, np.integer):
                    flat.append(int(float(v)))
                else:
                    flat.append(float(v))
            return np.array(flat, dtype=self.out_dtype).reshape(
                np.asarray(values).shape
            )
        return jnp.asarray(values).astype(self.out_dtype)


class ToSparse:
    """Dense matrix -> PaddedSparse, dropping ignore_value entries.

    Reference: to_sparse.py / to_ragged.py both produce a
    variable-length view of a dense batch; PaddedSparse is the
    fixed-shape equivalent of either (the mask carries the raggedness).
    """

    def __init__(self, ignore_value=None):
        self.ignore_value = ignore_value

    def __call__(self, inputs):
        if isinstance(inputs, PaddedSparse):
            return inputs
        return to_padded_sparse(inputs, self.ignore_value)


ToRagged = ToSparse  # one fixed-shape representation serves both


class ConcatenateWithOffset:
    """Add offsets[i] to inputs[i], then concatenate along axis.

    Reference: concatenate_with_offset.py:17-90 — the id-space merging
    primitive behind concatenated_categorical_column. PaddedSparse
    inputs concatenate values AND masks (axis=1).
    """

    def __init__(self, offsets, axis=-1):
        self.offsets = offsets
        self.axis = axis

    def __call__(self, inputs):
        if not isinstance(inputs, (list, tuple)):
            return inputs
        if self.offsets is not None and len(self.offsets) != len(inputs):
            raise ValueError(
                "offsets length %d != inputs length %d"
                % (len(self.offsets), len(inputs))
            )
        offsets = self.offsets or [0] * len(inputs)
        if isinstance(inputs[0], PaddedSparse):
            values = jnp.concatenate(
                [
                    jnp.asarray(sp.values) + off
                    for sp, off in zip(inputs, offsets)
                ],
                axis=1,
            )
            mask = jnp.concatenate(
                [jnp.asarray(sp.mask) for sp in inputs], axis=1
            )
            weights = None
            if all(sp.weights is not None for sp in inputs):
                weights = jnp.concatenate(
                    [jnp.asarray(sp.weights) for sp in inputs], axis=1
                )
            return PaddedSparse(values, mask, weights)
        return jnp.concatenate(
            [
                jnp.asarray(x) + off
                for x, off in zip(inputs, offsets)
            ],
            axis=self.axis,
        )


class SparseEmbedding(nn.Module):
    """Embedding with a combiner over variable-length ids — the
    device-resident counterpart of the host-PS sparse path.

    Reference: elasticdl_preprocessing/layers/sparse_embedding.py:20-88
    (safe_embedding_lookup_sparse with sum/mean/sqrtn). The TPU-native
    lookup is a masked gather + segment combine, fully jit-fused; rows
    for pad slots are zeroed by the mask so they never contribute.
    """

    input_dim: int
    output_dim: int
    combiner: str = "mean"
    embeddings_initializer: object = nn.initializers.uniform(scale=0.05)

    @nn.compact
    def __call__(self, inputs):
        if self.combiner not in ("sum", "mean", "sqrtn"):
            raise ValueError("combiner must be sum, mean or sqrtn")
        table = self.param(
            "embeddings",
            self.embeddings_initializer,
            (self.input_dim, self.output_dim),
        )
        if not isinstance(inputs, PaddedSparse):
            inputs = to_padded_sparse(inputs, ignore_value=0)
        ids = jnp.asarray(inputs.values)
        mask = jnp.asarray(inputs.mask)
        safe_ids = jnp.where(mask, ids, 0).astype(jnp.int32)
        if self.input_dim:
            safe_ids = jnp.clip(safe_ids, 0, self.input_dim - 1)
        rows = jnp.take(table, safe_ids, axis=0)  # [b, L, dim]
        w = mask.astype(rows.dtype)
        if inputs.weights is not None:
            w = w * jnp.asarray(inputs.weights, rows.dtype)
        summed = jnp.einsum("blh,bl->bh", rows, w)
        if self.combiner == "sum":
            return summed
        denom = jnp.sum(w, axis=1, keepdims=True)
        if self.combiner == "sqrtn":
            denom = jnp.sqrt(jnp.sum(w * w, axis=1, keepdims=True))
        return summed / jnp.maximum(denom, 1e-12)
