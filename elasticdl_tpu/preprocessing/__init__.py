"""Preprocessing library: feature transforms + feature columns.

Reference parity: elasticdl_preprocessing/ (layers, feature_column,
analyzer_utils). See layers.py for the host/device split rationale.
"""

from elasticdl_tpu.preprocessing.layers import (  # noqa: F401
    ConcatenateWithOffset,
    Discretization,
    Hashing,
    IndexLookup,
    LogRound,
    Normalizer,
    RoundIdentity,
    SparseEmbedding,
    ToNumber,
    ToRagged,
    ToSparse,
)
from elasticdl_tpu.preprocessing.sparse import (  # noqa: F401
    PAD_ID,
    PaddedSparse,
    dense_rows,
    from_row_lists,
    to_padded_sparse,
)
