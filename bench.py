"""Benchmark: ResNet50 img/s on one TPU chip + DeepFM CTR steps/sec.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline",
"extra"}. The headline stays ResNet50 (the reference's published
single-accelerator number exists for it); "extra" carries the second
metric family BASELINE.json names — DeepFM CTR global-steps/sec through
a live gRPC PS — for which the reference published no absolute number,
so the comparison there is pipelined-vs-sequential within this
framework.

Baseline context (BASELINE.md): the reference's best published ResNet50
number is 364 images/s on a 4x P100 cluster via Horovod, 145 images/s on
one P100 (ImageNet-shaped inputs, batch 64). vs_baseline is computed
against the single-accelerator number (145 img/s) since this benchmark
runs one chip.
"""

import json
import sys
import time

import numpy as np


def _wait_port(port, timeout=90):
    import socket

    deadline = time.time() + timeout
    while time.time() < deadline:
        s = socket.socket()
        try:
            s.connect(("127.0.0.1", port))
            return
        except OSError:
            time.sleep(0.3)
        finally:
            s.close()
    raise TimeoutError("PS on port %d never came up" % port)


def deepfm_run(pipelined, inject_rpc_delay_ms=0.0, batch_size=512,
               warmup=10, steps=100, device_tier=False):
    """One DeepFM CTR measurement: device step + live gRPC PS pulls and
    pushes against 2 PS shards as separate OS processes (an in-process
    PS shares the worker's GIL and inverts the pipelined/sequential
    comparison). ``inject_rpc_delay_ms`` adds emulated network RTT at
    the PS (scripts/bench_sparse_latency.py). ``device_tier`` promotes
    the Zipfian hot set into device-resident tables (ISSUE 6) so hit
    rows skip the PS round trip entirely. Returns (steps/sec,
    tier stats dict or None)."""
    import os
    import socket
    import subprocess

    from elasticdl_tpu.models import deepfm
    from elasticdl_tpu.train.device_tier import DeviceTierConfig
    from elasticdl_tpu.train.sparse import SparseTrainer
    from elasticdl_tpu.worker.ps_client import PSClient

    # criteo-dac shape from the zoo module; the bench is the DEPLOYMENT
    # config, so it opts into the measured Zipfian id-buffer cap
    # (deepfm.MAX_ID_CAPACITY, +22% steps/s on chip) that the library
    # default — the always-safe batch*fields worst case — leaves off.
    # See docs/PERF_SPARSE.md.
    fields, vocab = deepfm.NUM_FIELDS, 1_000_000
    rng = np.random.RandomState(0)
    batches = []
    for _ in range(warmup + steps):
        # Zipfian ids: CTR id frequencies are heavy-tailed, which is
        # exactly what the hot-row cache exploits
        ids = (rng.zipf(1.2, size=(batch_size, fields)) % vocab).astype(
            np.int64
        )
        batches.append({
            "features": {"ids": ids},
            "labels": rng.randint(0, 2, batch_size).astype(np.float32),
            "_mask": np.ones(batch_size, np.float32),
        })

    def free_port():
        s = socket.socket()
        s.bind(("", 0))
        port = s.getsockname()[1]
        s.close()
        return port

    procs, addrs = [], []
    env = dict(os.environ, JAX_PLATFORMS="cpu")  # PS needs no TPU
    ports = [free_port() for _ in range(2)]
    for ps_id, port in enumerate(ports):
        procs.append(subprocess.Popen(
            [sys.executable, "-m", "elasticdl_tpu.ps.server",
             "--ps_id", str(ps_id), "--num_ps_pods", "2",
             "--port", str(port),
             "--opt_type", "adam", "--opt_args", "lr=0.001",
             "--inject_rpc_delay_ms", str(inject_rpc_delay_ms)],
            env=env,
            stdout=subprocess.DEVNULL,
            stderr=subprocess.DEVNULL,
        ))
        addrs.append("localhost:%d" % port)
    try:
        for port in ports:
            _wait_port(port)
        tier_config = None
        if device_tier:
            # tier optimizer mirrors the PS config above (adam
            # lr=0.001); 64k rows/table covers the Zipf(1.2) hot set
            tier_config = DeviceTierConfig(
                capacity=65536, promote_hits=2, ttl=4096,
                stage_budget=2048, opt_type="adam",
                opt_args={"lr": 0.001}, writeback_steps=256,
            )
        trainer = SparseTrainer(
            model=deepfm.custom_model(),
            loss_fn=deepfm.loss,
            optimizer=deepfm.optimizer(),
            specs=deepfm.sparse_embedding_specs(
                batch_size=batch_size,
                capacity=min(
                    batch_size * deepfm.NUM_FIELDS,
                    deepfm.MAX_ID_CAPACITY,
                ),
            ),
            ps_client=PSClient(addrs),
            seed=0,
            cache_staleness=8 if pipelined else 0,
            device_tier=tier_config,
        )
        if pipelined:
            stream = trainer.train_stream(
                None, batches, push_interval=2
            )
            start = None
            for i, (_, loss, _) in enumerate(stream):
                if i + 1 == warmup:
                    float(loss)
                    start = time.perf_counter()
            elapsed = time.perf_counter() - start
        else:
            state = None
            for i, batch in enumerate(batches):
                state, loss = trainer.train_step(state, batch)
                if i + 1 == warmup:
                    float(loss)
                    start = time.perf_counter()
            elapsed = time.perf_counter() - start
        tier_stats = None
        if trainer.device_tier is not None:
            tier_stats = trainer.device_tier.stats()
            trainer.close()  # flush writebacks before the PS dies
        return steps / elapsed, tier_stats
    finally:
        for proc in procs:
            proc.terminate()
        for proc in procs:
            try:
                proc.wait(timeout=10)
            except Exception:
                proc.kill()


def bench_deepfm():
    """DeepFM CTR global-steps/sec for the bench headline's "extra"
    field: sequential + pipelined at zero injected latency, plus the
    ISSUE-6 device-tier on/off A-B of the pipelined mode (hit rows
    skip the PS round trip entirely; Zipf(1.2) streams sit >0.9
    hit-rate once warm)."""
    from elasticdl_tpu.models import deepfm

    batch_size = 512
    sequential, _ = deepfm_run(pipelined=False, batch_size=batch_size)
    pipelined, _ = deepfm_run(pipelined=True, batch_size=batch_size)
    tiered, tier_stats = deepfm_run(
        pipelined=True, batch_size=batch_size, device_tier=True
    )
    # Headline = the recommended deployment config (pipelined stream +
    # device tier); the explicit _tier_off key keeps the PR 5 series
    # comparable. The controlled-latency experiment
    # (scripts/bench_sparse_latency.py, docs/PERF_SPARSE.md) measured
    # pipelining worth ~1.2x once worker<->PS RTT matters; the tier
    # removes the PS RTT for the hit set outright. If either stage of
    # the ladder inverts (tier slower than plain pipelined, pipelined
    # slower than sequential), say so loudly — the headline would
    # silently under-report relative to max(modes).
    if sequential > pipelined * 1.1:
        print(
            "bench: WARNING deepfm sequential (%.2f steps/s) beats the "
            "pipelined mode (%.2f) by >10%% — pipelined-path "
            "regression?" % (sequential, pipelined),
            file=sys.stderr,
        )
    if pipelined > tiered * 1.1:
        print(
            "bench: WARNING deepfm tier-off pipelined (%.2f steps/s) "
            "beats the device-tier headline (%.2f) by >10%% — "
            "device-tier-path regression?" % (pipelined, tiered),
            file=sys.stderr,
        )
    headline = max(tiered, pipelined)
    return {
        "deepfm_ctr_steps_per_sec": round(headline, 2),
        "deepfm_ctr_examples_per_sec": round(headline * batch_size, 1),
        "deepfm_ctr_steps_per_sec_device_tier": round(tiered, 2),
        "deepfm_ctr_steps_per_sec_tier_off": round(pipelined, 2),
        "deepfm_device_tier_hit_rate": round(
            tier_stats["hit_rate"], 4
        ) if tier_stats else 0.0,
        "deepfm_device_tier_evictions": (
            tier_stats["evictions"] if tier_stats else 0
        ),
        "deepfm_ctr_steps_per_sec_pipelined": round(pipelined, 2),
        "deepfm_ctr_steps_per_sec_sequential": round(sequential, 2),
        "deepfm_batch": batch_size,
        "deepfm_fields": deepfm.NUM_FIELDS,
    }


def bench_deepfm_latency_ab(delay_ms=50.0, steps=60):
    """The injected-PS-latency A/B that shows WHY the pipelined stream
    is the deployment default (docs/PERF_SPARSE.md: on this tunneled
    box the ~230 ms device leg hides the win at 0 ms RTT; at 50-100 ms
    emulated worker<->PS RTT the pipeline's pull-hiding is worth
    ~1.2x). Captured so the claim has a driver artifact."""
    sequential, _ = deepfm_run(
        pipelined=False, inject_rpc_delay_ms=delay_ms, steps=steps
    )
    pipelined, _ = deepfm_run(
        pipelined=True, inject_rpc_delay_ms=delay_ms, steps=steps
    )
    return {
        "deepfm_pipelined_latency_speedup": round(
            pipelined / sequential, 3
        ),
        "deepfm_latency_ab_delay_ms": delay_ms,
        "deepfm_latency_ab_steps_per_sec_sequential": round(
            sequential, 2
        ),
        "deepfm_latency_ab_steps_per_sec_pipelined": round(
            pipelined, 2
        ),
    }


def _run_json_script(argv, timeout=900):
    """Run a bench script in a subprocess (the chip is exclusive on
    single-process libtpu runtimes — the parent must not have touched
    JAX-on-TPU yet) and return its one JSON line."""
    import os
    import subprocess

    out = subprocess.run(
        [sys.executable] + argv,
        capture_output=True, text=True, timeout=timeout,
        cwd=os.path.dirname(os.path.abspath(__file__)) or ".",
    )
    for line in out.stdout.splitlines():
        if line.startswith("{"):
            return json.loads(line)
    raise RuntimeError(
        "no JSON line from %s: %s" % (argv[0], out.stderr[-500:])
    )


def bench_transformer_mfu():
    """TransformerLM training MFU, best measured single-chip config
    (docs/PERF_TRANSFORMER.md). Runs in a subprocess so its ~10 GB of
    device state never coexists with the ResNet bench's."""
    r = _run_json_script(
        ["scripts/bench_transformer_mfu.py",
         "--d", "2048", "--layers", "10", "--heads", "8",
         "--seq", "1024", "--batch", "12", "--remat", "none"],
    )
    return {
        "transformer_mfu": r["mfu"],
        "transformer_tokens_per_sec": r["tokens_per_sec"],
        "transformer_params_m": r["params_m"],
        "transformer_step_ms": r["step_ms"],
    }


def bench_gradaccum_mfu():
    """The 735M L=12 model past the HBM ceiling via grad accumulation
    k=4 (docs/PERF_TRANSFORMER.md "Past the HBM ceiling": 63% MFU; k<4
    documented infeasible by XLA's own buffer assignment)."""
    r = _run_json_script(
        ["scripts/bench_transformer_mfu.py",
         "--d", "2048", "--layers", "12", "--heads", "16",
         "--seq", "2048", "--batch", "8", "--remat", "dots",
         "--grad_accum_steps", "4"],
    )
    return {
        "l12_gradaccum_mfu": r["mfu"],
        "l12_gradaccum_params_m": r["params_m"],
        "l12_gradaccum_step_ms": r["step_ms"],
    }


def bench_s16k_flash_mfu():
    """16k-token context on ONE chip under the "flash" remat policy
    (docs/PERF_TRANSFORMER.md S=16384 row: 53.9% MFU — saves only the
    flash kernel's (o, lse) outputs so the O(S²) forward never
    re-runs)."""
    r = _run_json_script(
        ["scripts/bench_transformer_mfu.py",
         "--d", "2048", "--layers", "10", "--heads", "8",
         "--seq", "16384", "--batch", "1", "--remat", "flash"],
    )
    return {
        "s16k_flash_mfu": r["mfu"],
        "s16k_tokens_per_sec": r["tokens_per_sec"],
        "s16k_step_ms": r["step_ms"],
    }


def bench_moe_mfu():
    """MoE vs dense-at-matched-active-FLOPs single-chip MFUs
    (docs/PERF_MOE.md config: d=1024 L=8 E=8 k=2 cf=1.25, S=1024 B=16
    — the measured batch sweet spot, one-hot einsum dispatch; full
    AdamW step, bf16, pallas attention)."""
    r = _run_json_script(
        ["scripts/bench_moe.py",
         "--d", "1024", "--layers", "8", "--seq", "1024",
         "--batch", "16", "--experts", "8"],
        timeout=1200,
    )
    return {
        "moe_mfu": r["moe"]["mfu"],
        "moe_dense_matched_mfu": r["dense_matched_active"]["mfu"],
        "moe_step_overhead_vs_dense": r["moe_step_overhead_vs_dense"],
        "moe_step_ms": r["moe"]["step_ms"],
        "moe_dispatch_impl": r["config"].get("dispatch", "auto"),
    }


def _probe_once(timeout):
    """One probe attempt in a THROWAWAY subprocess; returns None on
    success or (error string, retryable) — only hangs are retryable."""
    import subprocess

    try:
        out = subprocess.run(
            [sys.executable, "-c",
             "import jax; print(jax.devices()[0].device_kind)"],
            capture_output=True, text=True, timeout=timeout,
        )
        if out.returncode == 0:
            return None
        # deterministic failure (bad env, plugin missing): retrying a
        # doomed probe only delays the diagnostic
        return ("device probe failed: %s" % out.stderr[-300:], False)
    except subprocess.TimeoutExpired:
        # hang = the transient-wedge signature; worth a retry
        return (
            "device probe hung >%ds (wedged tunnel/plugin?)" % timeout,
            True,
        )


def _probe_device(timeout=180, retries=2, backoff_secs=45.0):
    """Touch the accelerator from a THROWAWAY subprocess first: a
    wedged tunnel/plugin makes jax.devices() hang forever (observed on
    the axon tunnel after a client was SIGKILLed mid-transfer), and a
    hang inside this process would lose the whole bench. A subprocess
    hang is killable.

    A single wedged probe must not cost the whole round's perf evidence
    (round 3 lost its BENCH artifact exactly this way): transient
    tunnel wedges have been observed to clear, and each attempt runs in
    a FRESH subprocess — a fresh PJRT client re-dials the tunnel, which
    is the only re-init available from this side of the relay. So:
    bounded retry with backoff between attempts, and only after every
    attempt fails does the bench fail fast with the diagnostic JSON
    line (the terminal state is unchanged)."""
    errors = []
    for attempt in range(retries + 1):
        if attempt:
            time.sleep(backoff_secs)
        result = _probe_once(timeout)
        if result is None:
            return None
        error, retryable = result
        errors.append("attempt %d: %s" % (attempt + 1, error))
        print("bench: %s" % errors[-1], file=sys.stderr)
        if not retryable:
            break
    return "; ".join(errors)


def main():
    probe_error = _probe_device()
    if probe_error:
        print(json.dumps({
            "metric": "resnet50_imagenet_train_throughput_per_chip",
            "value": 0.0,
            "unit": "images/sec",
            "vs_baseline": 0.0,
            "extra": {"error": probe_error},
        }))
        sys.exit(1)

    import jax
    import jax.numpy as jnp

    # Persistent compile cache: first ResNet50 compile is slow; repeat
    # bench runs should time steps, not XLA.
    jax.config.update("jax_compilation_cache_dir", "/tmp/jax_bench_cache")
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)

    sys.path.insert(0, ".")

    # Transformer bench first: it runs in a subprocess that needs the
    # TPU, and on single-process libtpu runtimes the chip is exclusive —
    # the parent must not have initialized JAX-on-TPU yet. Then the CTR
    # bench: it is latency-sensitive (live PS round trips) and measures
    # noticeably slower after the ResNet bench's large device state.
    extra = {}
    for name, fn in (
        ("transformer", bench_transformer_mfu),
        ("l12_gradaccum", bench_gradaccum_mfu),
        ("s16k_flash", bench_s16k_flash_mfu),
        ("moe", bench_moe_mfu),
    ):
        try:  # the headline metric must survive any sub-bench failure
            extra.update(fn())
        except Exception as e:
            extra["%s_error" % name] = repr(e)
    try:
        extra.update(bench_deepfm())
    except Exception as e:
        extra["deepfm_error"] = repr(e)
    try:
        extra.update(bench_deepfm_latency_ab())
    except Exception as e:
        extra["deepfm_latency_ab_error"] = repr(e)
    from elasticdl_tpu.models import resnet
    from elasticdl_tpu.train.optimizers import create_optimizer
    from elasticdl_tpu.train.step_fns import make_train_step
    from elasticdl_tpu.train.train_state import create_train_state

    batch_size = 256
    image_size = 224
    # 100 steps/window: the tunnel charges ~135 ms of fixed
    # dispatch+fetch per window (measured by the round-5 window-length
    # sweep, docs/PERF_RESNET.md "Window-length decomposition") — at 20
    # steps that inflated the step by ~6.8 ms and under-reported the
    # device's sustained img/s by ~5%
    bench_steps = 100

    # MLPerf-style space_to_depth stem (models/resnet.py): the 7x7/2
    # conv over 3 channels is the one MXU-hostile conv in the model;
    # packing 2x2 spatial blocks into channels feeds the MXU a 4x4/1
    # conv over 12 channels instead. Everything else — including exact
    # full-batch BatchNorm — is the stock model. See docs/PERF_RESNET.md
    # for the on-chip profile and the bandwidth-roofline analysis.
    model = resnet.resnet50(num_classes=1000, stem="space_to_depth")
    tx = create_optimizer(
        "Momentum", learning_rate=0.1, momentum=0.9, nesterov=True
    )
    train_step = make_train_step(
        model, resnet.loss, tx, compute_dtype=jnp.bfloat16
    )

    # The whole bench loop is one lax.scan under one jit: a single device
    # execution covers all steps, so the wall-clock between dispatch and
    # the fetched loss is pure device time — immune to async-dispatch
    # artifacts where per-step block_until_ready fences host handles
    # without fencing remote execution, and to per-call host latency on
    # tunneled backends.
    def run_steps(state, batch, n):
        def body(state, _):
            state, loss = train_step(state, batch)
            return state, loss
        return jax.lax.scan(body, state, None, length=n)

    run = jax.jit(run_steps, static_argnums=(2,), donate_argnums=(0,))

    rng = np.random.RandomState(0)
    batch = {
        "features": jnp.asarray(
            rng.rand(batch_size, image_size, image_size, 3), jnp.float32
        ),
        "labels": jnp.asarray(
            rng.randint(0, 1000, size=batch_size), jnp.int32
        ),
        "_mask": jnp.ones((batch_size,), jnp.float32),
    }
    state = create_train_state(
        model, tx, jax.random.PRNGKey(0), batch["features"]
    )

    # Warmup at the SAME scan length as the timed run: scan length is a
    # static shape, so a different length would recompile inside the
    # timed region.
    state, losses = run(state, batch, bench_steps)
    float(losses[-1])

    # Best of 5 timed windows: each window is pure device time (one
    # scan, fenced by the loss fetch), so between-window spread is
    # transient noise (tunnel scheduling, co-tenancy) — the best window
    # is the device's actual throughput. Observed spread on this
    # box: ~2%.
    elapsed = float("inf")
    for _ in range(5):
        start = time.perf_counter()
        state, losses = run(state, batch, bench_steps)
        final_loss = float(losses[-1])  # fetch fences execution
        elapsed = min(elapsed, time.perf_counter() - start)
        assert np.isfinite(final_loss)

    images_per_sec = batch_size * bench_steps / elapsed

    # Reference single-accelerator ResNet50/ImageNet: 145 images/s (P100,
    # ftlib_benchmark.md:115-123).
    baseline = 145.0
    print(
        json.dumps(
            {
                "metric": "resnet50_imagenet_train_throughput_per_chip",
                "value": round(images_per_sec, 2),
                "unit": "images/sec",
                "vs_baseline": round(images_per_sec / baseline, 2),
                "extra": extra,
            }
        )
    )


if __name__ == "__main__":
    main()
