"""Benchmark: ResNet50 training throughput (images/sec) on one TPU chip.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.

Baseline context (BASELINE.md): the reference's best published ResNet50
number is 364 images/s on a 4x P100 cluster via Horovod, 145 images/s on
one P100 (ImageNet-shaped inputs, batch 64). vs_baseline is computed
against the single-accelerator number (145 img/s) since this benchmark
runs one chip.
"""

import json
import sys
import time

import numpy as np


def main():
    import jax
    import jax.numpy as jnp

    # Persistent compile cache: first ResNet50 compile is slow; repeat
    # bench runs should time steps, not XLA.
    jax.config.update("jax_compilation_cache_dir", "/tmp/jax_bench_cache")
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)

    sys.path.insert(0, ".")
    from elasticdl_tpu.models import resnet
    from elasticdl_tpu.train.optimizers import create_optimizer
    from elasticdl_tpu.train.step_fns import make_train_step
    from elasticdl_tpu.train.train_state import create_train_state

    batch_size = 256
    image_size = 224
    bench_steps = 20

    # MLPerf-style space_to_depth stem (models/resnet.py): the 7x7/2
    # conv over 3 channels is the one MXU-hostile conv in the model;
    # packing 2x2 spatial blocks into channels feeds the MXU a 4x4/1
    # conv over 12 channels instead. Everything else — including exact
    # full-batch BatchNorm — is the stock model. See docs/PERF_RESNET.md
    # for the on-chip profile and the bandwidth-roofline analysis.
    model = resnet.resnet50(num_classes=1000, stem="space_to_depth")
    tx = create_optimizer(
        "Momentum", learning_rate=0.1, momentum=0.9, nesterov=True
    )
    train_step = make_train_step(
        model, resnet.loss, tx, compute_dtype=jnp.bfloat16
    )

    # The whole bench loop is one lax.scan under one jit: a single device
    # execution covers all steps, so the wall-clock between dispatch and
    # the fetched loss is pure device time — immune to async-dispatch
    # artifacts where per-step block_until_ready fences host handles
    # without fencing remote execution, and to per-call host latency on
    # tunneled backends.
    def run_steps(state, batch, n):
        def body(state, _):
            state, loss = train_step(state, batch)
            return state, loss
        return jax.lax.scan(body, state, None, length=n)

    run = jax.jit(run_steps, static_argnums=(2,), donate_argnums=(0,))

    rng = np.random.RandomState(0)
    batch = {
        "features": jnp.asarray(
            rng.rand(batch_size, image_size, image_size, 3), jnp.float32
        ),
        "labels": jnp.asarray(
            rng.randint(0, 1000, size=batch_size), jnp.int32
        ),
        "_mask": jnp.ones((batch_size,), jnp.float32),
    }
    state = create_train_state(
        model, tx, jax.random.PRNGKey(0), batch["features"]
    )

    # Warmup at the SAME scan length as the timed run: scan length is a
    # static shape, so a different length would recompile inside the
    # timed region.
    state, losses = run(state, batch, bench_steps)
    float(losses[-1])

    start = time.perf_counter()
    state, losses = run(state, batch, bench_steps)
    final_loss = float(losses[-1])  # device->host fetch fences execution
    elapsed = time.perf_counter() - start
    assert np.isfinite(final_loss)

    images_per_sec = batch_size * bench_steps / elapsed
    # Reference single-accelerator ResNet50/ImageNet: 145 images/s (P100,
    # ftlib_benchmark.md:115-123).
    baseline = 145.0
    print(
        json.dumps(
            {
                "metric": "resnet50_imagenet_train_throughput_per_chip",
                "value": round(images_per_sec, 2),
                "unit": "images/sec",
                "vs_baseline": round(images_per_sec / baseline, 2),
            }
        )
    )


if __name__ == "__main__":
    main()
