import os

import numpy as np

from elasticdl_tpu.ps.checkpoint import SparseCheckpointSaver
from elasticdl_tpu.ps.embedding_store import NumpyEmbeddingStore


def make_store(seed=0):
    store = NumpyEmbeddingStore(seed=seed)
    store.set_optimizer("sgd", lr=0.1)
    store.create_table("t", 4, init_scale=0.5)
    return store


def test_save_restore_and_gc(tmp_path):
    ckpt_dir = str(tmp_path / "ckpt")
    store = make_store()
    ids = np.arange(20, dtype=np.int64)
    values = np.random.RandomState(0).rand(20, 4).astype(np.float32)
    store.import_table("t", ids, values)
    # compact_every=0: every save is a full base (the pre-ISSUE-13
    # behavior — chain GC is covered by test_chain_gc below)
    saver = SparseCheckpointSaver(ckpt_dir, shard_id=0, shard_num=1,
                                  keep_max=2, compact_every=0)
    for version in (5, 10, 15):
        assert saver.save(version, store).kind == "full"
    # GC keeps only the last two complete versions
    remaining = sorted(os.listdir(ckpt_dir))
    assert remaining == ["version-10", "version-15"]

    # restore latest into a 4-shard store: shard 2 keeps ids 2,6,10,14,18
    shard_store = make_store(seed=1)
    shard = SparseCheckpointSaver(ckpt_dir, shard_id=2, shard_num=4)
    version = shard.restore(shard_store)
    assert version == 15
    assert shard_store.table_size("t") == 5
    np.testing.assert_array_equal(
        shard_store.lookup("t", np.array([6], np.int64))[0], values[6]
    )
    # init_scale survives re-registration after restore (tables adopt
    # the registered scale)
    shard_store.create_table("t", 4, init_scale=0.3)
    row = shard_store.lookup("t", np.array([999], np.int64))[0]
    assert (np.abs(row) <= 0.3).all()


def test_full_state_resume_is_bit_identical():
    """Checkpoint -> restore into a fresh store -> further training must
    match an uninterrupted run exactly (slots + per-row Adam steps are
    saved; the reference dropped slots, ps/parameters.py:194-199)."""
    import numpy as np

    from elasticdl_tpu.ps.checkpoint import SparseCheckpointSaver
    from elasticdl_tpu.ps.embedding_store import create_store

    def fresh(tmp, tag):
        store = create_store(seed=0)
        store.set_optimizer("adam", lr=0.05)
        store.create_table("t", 4, init_scale=0.1)
        return store

    import tempfile

    with tempfile.TemporaryDirectory() as tmp:
        rng = np.random.RandomState(0)
        ids = np.arange(6, dtype=np.int64)
        grads = [rng.randn(6, 4).astype(np.float32) for _ in range(8)]

        baseline = fresh(tmp, "a")
        for g in grads:
            baseline.push_gradients("t", ids, g)

        resumed = fresh(tmp, "b")
        for g in grads[:4]:
            resumed.push_gradients("t", ids, g)
        saver = SparseCheckpointSaver(tmp + "/ckpt", shard_id=0, shard_num=1)
        saver.save(4, resumed)

        restored = fresh(tmp, "c")
        assert saver.restore(restored) == 4
        for g in grads[4:]:
            restored.push_gradients("t", ids, g)

        np.testing.assert_allclose(
            restored.lookup("t", ids),
            baseline.lookup("t", ids),
            rtol=1e-6, atol=1e-7,
        )


def test_full_state_reshard_preserves_slots():
    """Re-shard 1 -> 2 shards: each new shard holds only its ids, with
    slot state intact (continued updates match unsharded baseline)."""
    import tempfile

    import numpy as np

    from elasticdl_tpu.ps.checkpoint import SparseCheckpointSaver
    from elasticdl_tpu.ps.embedding_store import create_store

    def fresh():
        store = create_store(seed=0)
        store.set_optimizer("amsgrad", lr=0.05)
        store.create_table("t", 4, init_scale=0.1)
        return store

    with tempfile.TemporaryDirectory() as tmp:
        rng = np.random.RandomState(1)
        ids = np.arange(8, dtype=np.int64)
        pre = [rng.randn(8, 4).astype(np.float32) for _ in range(4)]
        post = [rng.randn(8, 4).astype(np.float32) for _ in range(4)]

        baseline = fresh()
        for g in pre + post:
            baseline.push_gradients("t", ids, g)

        writer = fresh()
        for g in pre:
            writer.push_gradients("t", ids, g)
        SparseCheckpointSaver(tmp, shard_id=0, shard_num=1).save(4, writer)

        for shard_id in range(2):
            shard_store = fresh()
            SparseCheckpointSaver(
                tmp, shard_id=shard_id, shard_num=2
            ).restore(shard_store)
            my_ids = ids[ids % 2 == shard_id]
            for g in post:
                pos = np.nonzero(ids % 2 == shard_id)[0]
                shard_store.push_gradients("t", my_ids, g[pos])
            np.testing.assert_allclose(
                shard_store.lookup("t", my_ids),
                baseline.lookup("t", my_ids),
                rtol=1e-6, atol=1e-7,
            )


def test_optimizer_swap_restores_weights_only():
    """momentum -> adagrad (same slot width): foreign slot state must
    NOT be imported (it would put negative velocities into the adagrad
    accumulator -> sqrt(negative) NaNs)."""
    import tempfile

    import numpy as np

    from elasticdl_tpu.ps.checkpoint import SparseCheckpointSaver
    from elasticdl_tpu.ps.embedding_store import create_store

    with tempfile.TemporaryDirectory() as tmp:
        writer = create_store(seed=0)
        writer.set_optimizer("momentum", lr=0.1, momentum=0.9)
        writer.create_table("t", 4)
        ids = np.arange(4, dtype=np.int64)
        # drive velocities negative
        for _ in range(3):
            writer.push_gradients("t", ids, -np.ones((4, 4), np.float32))
        saver = SparseCheckpointSaver(tmp, shard_id=0, shard_num=1)
        saver.save(3, writer)
        weights = writer.lookup("t", ids)

        restored = create_store(seed=0)
        restored.set_optimizer("adagrad", lr=0.1)
        restored.create_table("t", 4)
        saver.restore(restored)
        np.testing.assert_allclose(restored.lookup("t", ids), weights)
        # further training must stay finite (fresh adagrad accumulator)
        restored.push_gradients("t", ids, np.ones((4, 4), np.float32))
        assert np.isfinite(restored.lookup("t", ids)).all()


def test_graceful_stop_flushes_round_and_rejects_late_pushes(tmp_path):
    """ISSUE 7 PS SIGTERM satellite: graceful_stop applies the
    buffered partial round and saves a final COMPLETE checkpoint —
    and a push handler that loses the lock race against it (gRPC
    keeps running handlers admitted before server.stop()) must be
    REJECTED: buffering after the flush would ACK an update into a
    round buffer nobody will ever apply again, silently missing from
    the state the successor restores."""
    from elasticdl_tpu.common.tensor_utils import ndarray_to_blob
    from elasticdl_tpu.proto import elasticdl_tpu_pb2 as pb
    from elasticdl_tpu.ps.servicer import PserverServicer

    def push(version, worker_id):
        request = pb.PushGradientsRequest()
        request.gradients.version = version
        slices = request.gradients.embedding_tables["t"]
        ndarray_to_blob(np.ones((2, 4), np.float32), slices.concat_tensors)
        slices.ids.extend([0, 1])
        request.worker_id = worker_id
        return request

    store = make_store()
    before = store.lookup("t", np.array([0, 1], np.int64)).copy()
    saver = SparseCheckpointSaver(
        str(tmp_path / "ckpt"), shard_id=0, shard_num=1
    )
    servicer = PserverServicer(
        store, use_async=False, grads_to_wait=2, checkpoint_saver=saver,
    )
    # one buffered push: an under-filled round when SIGTERM arrives
    assert servicer.push_gradients(push(0, worker_id=0)).accepted
    servicer.graceful_stop()
    # the partial round was applied (not lost) and checkpointed
    after = store.lookup("t", np.array([0, 1], np.int64))
    assert not np.allclose(before, after)
    restored = make_store(seed=1)
    assert saver.restore(restored) == store.version
    np.testing.assert_array_equal(
        restored.lookup("t", np.array([0, 1], np.int64)), after
    )
    # late pushes — sync buffering path and a second stop — are inert
    late = servicer.push_gradients(push(store.version, worker_id=1))
    assert not late.accepted
    np.testing.assert_array_equal(
        store.lookup("t", np.array([0, 1], np.int64)), after
    )
    # device-tier writebacks reject too: importing rows now would ACK
    # a flush the final checkpoint never saw (the client raises on the
    # rejection, so a draining worker reports tier_flushed=False)
    rows = pb.Model()
    slices = rows.embedding_tables["t"]
    ndarray_to_blob(np.full((2, 4), 9.0, np.float32), slices.concat_tensors)
    slices.ids.extend([0, 1])
    assert not servicer.push_embedding_rows(rows).accepted
    np.testing.assert_array_equal(
        store.lookup("t", np.array([0, 1], np.int64)), after
    )
    servicer.graceful_stop()  # idempotent

    # the lock-free async path rejects too
    async_store = make_store()
    async_servicer = PserverServicer(async_store, use_async=True)
    async_servicer.graceful_stop()
    resp = async_servicer.push_gradients(push(0, worker_id=0))
    assert not resp.accepted
    np.testing.assert_array_equal(
        async_store.lookup("t", np.array([0, 1], np.int64)),
        make_store().lookup("t", np.array([0, 1], np.int64)),
    )
