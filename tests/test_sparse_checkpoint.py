import os

import numpy as np

from elasticdl_tpu.ps.checkpoint import SparseCheckpointSaver
from elasticdl_tpu.ps.embedding_store import NumpyEmbeddingStore


def make_store(seed=0):
    store = NumpyEmbeddingStore(seed=seed)
    store.set_optimizer("sgd", lr=0.1)
    store.create_table("t", 4, init_scale=0.5)
    return store


def test_save_restore_and_gc(tmp_path):
    ckpt_dir = str(tmp_path / "ckpt")
    store = make_store()
    ids = np.arange(20, dtype=np.int64)
    values = np.random.RandomState(0).rand(20, 4).astype(np.float32)
    store.import_table("t", ids, values)
    saver = SparseCheckpointSaver(ckpt_dir, shard_id=0, shard_num=1, keep_max=2)
    for version in (5, 10, 15):
        saver.save(version, store)
    # GC keeps only the last two complete versions
    remaining = sorted(os.listdir(ckpt_dir))
    assert remaining == ["version-10", "version-15"]

    # restore latest into a 4-shard store: shard 2 keeps ids 2,6,10,14,18
    shard_store = make_store(seed=1)
    shard = SparseCheckpointSaver(ckpt_dir, shard_id=2, shard_num=4)
    version = shard.restore(shard_store)
    assert version == 15
    assert shard_store.table_size("t") == 5
    np.testing.assert_array_equal(
        shard_store.lookup("t", np.array([6], np.int64))[0], values[6]
    )
    # init_scale survives re-registration after restore (tables adopt
    # the registered scale)
    shard_store.create_table("t", 4, init_scale=0.3)
    row = shard_store.lookup("t", np.array([999], np.int64))[0]
    assert (np.abs(row) <= 0.3).all()
