"""MoE routing + expert-parallel training.

Correctness ladder mirroring the transformer SPMD tests: (1) routing
invariants, (2) dispatch/combine against a brute-force per-token loop,
(3) the MoE LM trained GSPMD-sharded over a dp x tp x ep mesh matches
single-device losses.
"""

import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

from elasticdl_tpu.models import moe_transformer
from elasticdl_tpu.ops.moe import (
    expert_capacity,
    moe_combine,
    moe_dispatch,
    top_k_routing,
)
from elasticdl_tpu.parallel.mesh import MeshConfig, build_mesh
from elasticdl_tpu.parallel.spmd_trainer import SpmdTrainer
from elasticdl_tpu.train.optimizers import create_optimizer
from elasticdl_tpu.train.step_fns import make_train_step
from elasticdl_tpu.train.train_state import create_train_state


def test_top1_routing_matches_argmax():
    rng = np.random.RandomState(0)
    logits = jnp.asarray(rng.randn(2, 16, 4).astype(np.float32))
    capacity = 16  # ample: nothing dropped
    combine, dispatch, aux = top_k_routing(logits, k=1, capacity=capacity)
    chosen = np.asarray(dispatch.sum(axis=-1).argmax(axis=-1))
    np.testing.assert_array_equal(
        chosen, np.asarray(logits.argmax(axis=-1))
    )
    # every token dispatched exactly once, with weight 1 after renorm
    np.testing.assert_allclose(
        np.asarray(combine.sum(axis=(2, 3))), 1.0, atol=1e-6
    )
    assert float(aux) > 0


def test_capacity_drops_overflow_tokens():
    # All 8 tokens pick expert 0; capacity 3 keeps only the first 3.
    logits = jnp.tile(
        jnp.asarray([[10.0, 0.0, 0.0, 0.0]]), (1, 8, 1)
    ).reshape(1, 8, 4)
    combine, dispatch, _ = top_k_routing(logits, k=1, capacity=3)
    per_token = np.asarray(dispatch.sum(axis=(2, 3)))
    assert per_token[0, :3].sum() == 3
    assert per_token[0, 3:].sum() == 0
    # each (expert, slot) holds at most one token
    per_slot = np.asarray(dispatch.sum(axis=1))
    assert per_slot.max() == 1


def test_dispatch_combine_matches_bruteforce():
    rng = np.random.RandomState(1)
    g, s, e, m, k = 2, 8, 4, 6, 2
    x = jnp.asarray(rng.randn(g, s, m).astype(np.float32))
    logits = jnp.asarray(rng.randn(g, s, e).astype(np.float32))
    capacity = s * k  # nothing dropped
    combine, dispatch, _ = top_k_routing(logits, k=k, capacity=capacity)

    # "experts" are simple per-expert linear maps
    w = jnp.asarray(rng.randn(e, m, m).astype(np.float32))
    expert_in = moe_dispatch(x, dispatch)  # (E, G, C, M)
    expert_out = jnp.einsum("egcm,emn->egcn", expert_in, w)
    y = moe_combine(expert_out, combine)

    # brute force: per token, weighted sum of its top-k experts' outputs
    probs = jax.nn.softmax(logits, axis=-1)
    gates, indices = jax.lax.top_k(probs, k)
    gates = gates / gates.sum(axis=-1, keepdims=True)
    expected = np.zeros((g, s, m), np.float32)
    for gi in range(g):
        for si in range(s):
            for ki in range(k):
                ei = int(indices[gi, si, ki])
                expected[gi, si] += float(gates[gi, si, ki]) * np.asarray(
                    x[gi, si] @ w[ei]
                )
    np.testing.assert_allclose(np.asarray(y), expected, atol=1e-4)


def _small_moe(**kwargs):
    return moe_transformer.MoeTransformerLM(
        vocab_size=128,
        num_layers=2,
        num_heads=4,
        embed_dim=32,
        num_experts=4,
        top_k=2,
        # ample capacity: deterministic routing regardless of sharding
        capacity_factor=2.0,
        **kwargs,
    )


def _batch(batch=4, seq=32, vocab=128, seed=0):
    rng = np.random.RandomState(seed)
    tokens = rng.randint(0, vocab, size=(batch, seq)).astype(np.int32)
    return {
        "features": tokens,
        "labels": tokens,
        "_mask": np.ones((batch,), np.float32),
    }


def _single_device_losses(batch, steps=3):
    model = _small_moe(attention_impl="xla")
    tx = create_optimizer("Adam", learning_rate=0.01)
    init_rng, _ = jax.random.split(jax.random.PRNGKey(0))
    state = create_train_state(model, tx, init_rng, batch["features"])
    step = jax.jit(make_train_step(model, moe_transformer.loss, tx))
    losses = []
    for _ in range(steps):
        state, loss = step(state, batch)
        losses.append(float(loss))
    return losses


def test_expert_parallel_matches_single_device():
    batch = _batch()
    expected = _single_device_losses(batch)

    mesh = build_mesh(MeshConfig(dp=2, tp=2, ep=2))
    model = _small_moe(attention_impl="xla", mesh=mesh)
    trainer = SpmdTrainer(
        model=model,
        loss_fn=moe_transformer.loss,
        optimizer=create_optimizer("Adam", learning_rate=0.01),
        mesh=mesh,
        seed=0,
        sharding_rules=moe_transformer.sharding_rules(),
        batch_spec=moe_transformer.batch_spec(),
    )
    state = trainer.create_state(batch["features"])
    losses = []
    for _ in range(3):
        state, loss = trainer.train_step(state, batch)
        losses.append(float(loss))
    np.testing.assert_allclose(losses, expected, atol=1e-4, rtol=1e-4)


def test_moe_eval_returns_bare_logits():
    batch = _batch()
    model = _small_moe(attention_impl="xla")
    variables = model.init(
        jax.random.PRNGKey(0), batch["features"], training=False
    )
    out = model.apply(variables, batch["features"], training=False)
    assert out.shape == (4, 32, 128)
    out = model.apply(
        variables,
        batch["features"],
        training=True,
        rngs={"dropout": jax.random.PRNGKey(1)},
    )
    assert set(out.keys()) == {"logits", "aux_loss"}


def test_model_contract_loads():
    from elasticdl_tpu.models.registry import get_model_spec

    spec = get_model_spec("elasticdl_tpu.models.moe_transformer")
    assert spec.sharding_rules is not None
    assert spec.batch_spec is not None


def test_expert_capacity_static():
    assert expert_capacity(64, 8, k=2, capacity_factor=1.0) == 16
    assert expert_capacity(4, 8, k=1, capacity_factor=1.25) == 1


def test_aux_loss_gradient_pushes_toward_uniform():
    """Deterministic property behind the balance claim: at a collapsed
    router (every token's first choice = expert 0), d(aux)/d(logits)
    is negative-toward-expert-0 — following it redistributes load."""
    import jax

    from elasticdl_tpu.ops.moe import top_k_routing

    G, S, E, C = 2, 16, 4, 8
    logits = jnp.zeros((G, S, E)).at[..., 0].set(3.0)

    def aux_of(logits):
        _, _, aux = top_k_routing(logits, k=2, capacity=C)
        return aux

    grad = jax.grad(aux_of)(logits)
    # the dominant expert's logit gradient is positive (aux rises with
    # more concentration), every other expert's is negative — gradient
    # DESCENT therefore moves logits away from expert 0
    assert float(grad[..., 0].mean()) > 0
    assert float(grad[..., 1:].mean()) < 0


@pytest.mark.slow
def test_expert_balance_holds_over_a_real_run():
    """The aux loss keeps dispatch balanced while the model LEARNS —
    trained from a deliberately COLLAPSED router (expert 0 hoards >55%
    of first choices), the run must both fit the task and return to
    near-uniform routing. Full experiment (incl. the no-aux arm):
    scripts/convergence_moe.py, docs/PERF_MOE.md."""
    import sys

    sys.path.insert(0, os.path.join(REPO, "scripts"))
    import convergence_moe

    result = convergence_moe.run_arm(
        aux_weight=0.01, steps=120, collapsed_init=True
    )
    # learned the task
    assert result["ce_last"] < 1.0 < result["ce_first"]
    # started collapsed...
    assert result["max_expert_share_init"] > 0.5
    # ...and recovered to near-uniform dispatch (uniform = 0.25 for
    # E=4; balance 1.0 = perfectly uniform f·p)
    assert result["balance"] < 1.1
    assert result["max_expert_share"] < 0.4


def test_compact_dispatch_matches_onehot():
    """The slot-index (gather) dispatch must be semantically identical
    to the one-hot einsum dispatch — outputs AND gradients — including
    when capacity drops tokens."""
    from elasticdl_tpu.ops.moe import (
        moe_combine_compact,
        moe_dispatch_compact,
        top_k_routing_compact,
    )

    rng = np.random.RandomState(7)
    g, s, e, m, k = 2, 16, 4, 6, 2
    w = jnp.asarray(rng.randn(e, m, m).astype(np.float32))

    def onehot_path(x, logits, capacity):
        combine, dispatch, aux = top_k_routing(logits, k, capacity)
        expert_out = jnp.einsum(
            "egcm,emn->egcn", moe_dispatch(x, dispatch), w
        )
        return moe_combine(expert_out, combine), aux

    def compact_path(x, logits, capacity):
        gates, slot, aux = top_k_routing_compact(logits, k, capacity)
        expert_in = moe_dispatch_compact(x, slot, e, capacity)
        expert_out = jnp.einsum("egcm,emn->egcn", expert_in, w)
        return moe_combine_compact(expert_out, slot, gates), aux

    # capacity=3 forces drops; capacity=s*k drops nothing
    for capacity in (3, s * k):
        x = jnp.asarray(rng.randn(g, s, m).astype(np.float32))
        logits = jnp.asarray(rng.randn(g, s, e).astype(np.float32))
        y1, aux1 = onehot_path(x, logits, capacity)
        y2, aux2 = compact_path(x, logits, capacity)
        np.testing.assert_allclose(
            np.asarray(y1), np.asarray(y2), atol=1e-5
        )
        np.testing.assert_allclose(float(aux1), float(aux2), rtol=1e-6)

        # gradients through both x and the router logits must agree
        def loss1(x, lg):
            y, aux = onehot_path(x, lg, capacity)
            return (y ** 2).sum() + aux

        def loss2(x, lg):
            y, aux = compact_path(x, lg, capacity)
            return (y ** 2).sum() + aux

        gx1, gl1 = jax.grad(loss1, argnums=(0, 1))(x, logits)
        gx2, gl2 = jax.grad(loss2, argnums=(0, 1))(x, logits)
        np.testing.assert_allclose(
            np.asarray(gx1), np.asarray(gx2), atol=1e-4
        )
        np.testing.assert_allclose(
            np.asarray(gl1), np.asarray(gl2), atol=1e-4
        )


def test_moe_lm_compact_matches_onehot_losses():
    """Full MoeTransformerLM trained with dispatch_impl="compact" vs
    "onehot" produces the same loss curve on one device."""
    batch = _batch()
    losses = {}
    for impl in ("onehot", "compact"):
        model = _small_moe(attention_impl="xla", dispatch_impl=impl)
        tx = create_optimizer("Adam", learning_rate=0.01)
        init_rng, _ = jax.random.split(jax.random.PRNGKey(0))
        state = create_train_state(
            model, tx, init_rng, batch["features"]
        )
        step = jax.jit(make_train_step(model, moe_transformer.loss, tx))
        arm = []
        for _ in range(3):
            state, loss = step(state, batch)
            arm.append(float(loss))
        losses[impl] = arm
    np.testing.assert_allclose(
        losses["compact"], losses["onehot"], rtol=1e-4
    )


def test_compact_dispatch_under_dp_mesh_matches_single_device():
    """The compact (gather) path must also compile and stay correct
    when tokens are dp-sharded over a mesh with ep=1 (the gather and
    its custom gather-only backward are per-group, so GSPMD keeps
    them local to each dp shard)."""
    batch = _batch(batch=8)
    # the onehot single-device baseline is a valid reference: the two
    # impls agree to float tolerance (test_compact_dispatch_matches_onehot)
    expected = _single_device_losses(batch)
    mesh = build_mesh(MeshConfig(dp=8))
    model = _small_moe(
        attention_impl="xla", mesh=mesh, dispatch_impl="compact"
    )
    trainer = SpmdTrainer(
        model=model,
        loss_fn=moe_transformer.loss,
        optimizer=create_optimizer("Adam", learning_rate=0.01),
        mesh=mesh,
        seed=0,
        sharding_rules=moe_transformer.sharding_rules(),
        batch_spec=moe_transformer.batch_spec(),
    )
    state = trainer.create_state(batch["features"])
    got = []
    for _ in range(3):
        state, loss = trainer.train_step(state, batch)
        got.append(float(loss))
    np.testing.assert_allclose(got, expected, atol=1e-4, rtol=1e-4)
