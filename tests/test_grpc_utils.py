"""Direct retry_call edge coverage (common/grpc_utils + common/overload,
ISSUE 19): deadline-budget arithmetic, budget exhaustion mid-backoff,
the channel-ready reconnect path, circuit-breaker cycles, retry-budget
exhaustion, and server-pushback pacing."""

import threading
import time

import grpc
import pytest

from elasticdl_tpu.common import overload
from elasticdl_tpu.common.grpc_utils import (
    _await_reconnect,
    build_server,
    find_free_port,
    retry_call,
)


class FakeRpcError(grpc.RpcError):
    """A transport-shaped error with just the surface retry_call reads."""

    def __init__(self, code, retry_after_ms=None):
        super().__init__("fake %s" % code)
        self._code = code
        self._retry_after_ms = retry_after_ms

    def code(self):
        return self._code

    def trailing_metadata(self):
        if self._retry_after_ms is None:
            return ()
        return ((overload.RETRY_AFTER_KEY, str(self._retry_after_ms)),)


class WorstCaseRng:
    """uniform(a, b) -> b: every jitter draw is the full ceiling."""

    def uniform(self, low, high):
        return high


class ZeroRng:
    """uniform(a, b) -> a: every jitter draw is instant."""

    def uniform(self, low, high):
        return low


def _failing(times, code=grpc.StatusCode.UNAVAILABLE, result="ok",
             **error_kwargs):
    """A callable failing ``times`` times, then returning ``result``;
    .calls counts invocations."""
    state = {"calls": 0}

    def fn():
        state["calls"] += 1
        if state["calls"] <= times:
            raise FakeRpcError(code, **error_kwargs)
        return result

    fn.state = state
    return fn


@pytest.fixture(autouse=True)
def _clean_overload(monkeypatch):
    for env in (
        overload.DEADLINE_BUDGET_ENV,
        overload.RETRY_BUDGET_TOKENS_ENV,
        overload.RETRY_BUDGET_RATIO_ENV,
        overload.CIRCUIT_FAILURES_ENV,
        overload.CIRCUIT_RESET_SECS_ENV,
        overload.BROWNOUT_SKIP_AFTER_ENV,
    ):
        monkeypatch.delenv(env, raising=False)
    overload._reset_for_tests()
    yield
    overload._reset_for_tests()


# ---------------------------------------------------------------------------
# deadline-budget arithmetic


def test_nested_budgets_tighten_never_loosen():
    assert overload.remaining() is None
    with overload.budget(5.0):
        outer = overload.remaining()
        assert 4.5 < outer <= 5.0
        # a LOOSER inner scope is clamped to the outer remainder
        with overload.budget(60.0):
            assert overload.remaining() <= outer
        # a TIGHTER inner scope binds ...
        with overload.budget(0.5):
            assert overload.remaining() <= 0.5
        # ... and pops back to the outer remainder on exit
        assert overload.remaining() > 0.5
    assert overload.remaining() is None


def test_budget_none_is_a_noop_scope():
    with overload.budget(None):
        assert overload.remaining() is None


def test_rpc_timeout_caps_by_remainder():
    assert overload.rpc_timeout(60.0) == 60.0  # no budget: the default
    with overload.budget(1.0):
        assert overload.rpc_timeout(60.0) <= 1.0
        assert overload.rpc_timeout(0.2) <= 0.2  # tighter default wins
        assert overload.rpc_timeout(None) <= 1.0  # no default: remainder


def test_expired_budget_reads_zero_not_negative():
    with overload.budget(0.0):
        assert overload.remaining() == 0.0
        assert overload.rpc_timeout(60.0) == 0.0


def test_bind_budget_rehomes_into_another_thread():
    seen = {}

    def probe():
        seen["remaining"] = overload.remaining()

    with overload.budget(5.0):
        bound = overload.bind_budget(probe)
    thread = threading.Thread(target=bound)
    thread.start()
    thread.join()
    assert seen["remaining"] is not None and seen["remaining"] <= 5.0
    # without a budget open, bind_budget is the identity
    assert overload.bind_budget(probe) is probe


# ---------------------------------------------------------------------------
# retry_call core paths


def test_retry_call_returns_result_and_retries_unavailable():
    fn = _failing(times=2)
    result = retry_call(fn, "x", budget_secs=30, rng=ZeroRng())
    assert result == "ok"
    assert fn.state["calls"] == 3


def test_retry_call_non_retryable_raises_immediately():
    fn = _failing(times=5, code=grpc.StatusCode.INTERNAL)
    with pytest.raises(FakeRpcError):
        retry_call(fn, "x", budget_secs=30, rng=ZeroRng())
    assert fn.state["calls"] == 1


def test_retry_call_budget_exhaustion_mid_backoff_raises_original():
    # the drawn backoff (worst case = the full 0.5 s ceiling) would
    # cross the 0.2 s deadline: retry_call must raise the ORIGINAL
    # error right away instead of sleeping through the budget
    fn = _failing(times=10)
    started = time.monotonic()
    with pytest.raises(FakeRpcError):
        retry_call(fn, "x", budget_secs=0.2, rng=WorstCaseRng())
    assert time.monotonic() - started < 0.15
    assert fn.state["calls"] == 1


def test_retry_call_honors_callers_thread_budget():
    # a generous budget_secs is capped by the thread's tighter budget
    fn = _failing(times=10)
    with overload.budget(0.2):
        with pytest.raises(FakeRpcError):
            retry_call(fn, "x", budget_secs=60, rng=WorstCaseRng())
    assert fn.state["calls"] == 1


# ---------------------------------------------------------------------------
# channel-ready reconnect path


def test_await_reconnect_false_when_peer_never_comes_up():
    channel = grpc.insecure_channel("localhost:1")
    try:
        started = time.monotonic()
        assert _await_reconnect(channel, 0.2) is False
        assert time.monotonic() - started < 2.0
    finally:
        channel.close()


def test_retry_call_with_channel_beats_the_drawn_backoff():
    # the peer is up, so channel_ready_future completes in ~ms and the
    # retry fires after only the bounded residual jitter — NOT the full
    # worst-case 5 s draw a sleep-only loop would burn
    server = build_server(max_workers=2, instrument=False)
    port = find_free_port()
    server.add_insecure_port("localhost:%d" % port)
    server.start()
    channel = grpc.insecure_channel("localhost:%d" % port)
    try:
        fn = _failing(times=1)
        started = time.monotonic()
        result = retry_call(
            fn, "x", budget_secs=30, base_delay=5.0,
            rng=WorstCaseRng(), channel=channel,
        )
        elapsed = time.monotonic() - started
        assert result == "ok"
        assert fn.state["calls"] == 2
        assert elapsed < 2.0, elapsed
    finally:
        channel.close()
        server.stop(0)


# ---------------------------------------------------------------------------
# circuit breaker via retry_call


def test_breaker_opens_then_fail_fast_then_probe_recloses(monkeypatch):
    monkeypatch.setenv(overload.CIRCUIT_FAILURES_ENV, "2")
    monkeypatch.setenv(overload.CIRCUIT_RESET_SECS_ENV, "0.2")
    fn = _failing(times=100)
    with pytest.raises(grpc.RpcError):
        retry_call(
            fn, "push", budget_secs=0.25, rng=ZeroRng(), target="ps-0",
        )
    breaker = overload.breaker_for("ps-0", "write")
    assert breaker.state() == overload.OPEN
    assert breaker.open_count >= 1

    # open circuit + fail_fast_when_open: no wire attempt at all
    probe = _failing(times=0)
    with pytest.raises(overload.CircuitOpenError) as excinfo:
        retry_call(
            probe, "push", budget_secs=5, rng=ZeroRng(), target="ps-0",
            fail_fast_when_open=True,
        )
    assert probe.state["calls"] == 0
    assert excinfo.value.code() == grpc.StatusCode.UNAVAILABLE

    # after the reset window one probe is admitted; success re-closes
    time.sleep(0.25)
    healthy = _failing(times=0)
    assert retry_call(
        healthy, "push", budget_secs=5, rng=ZeroRng(), target="ps-0",
    ) == "ok"
    assert breaker.state() == overload.CLOSED
    assert overload.client_stats()["circuits_not_closed"] == []


def test_breaker_paces_within_budget_without_fail_fast(monkeypatch):
    monkeypatch.setenv(overload.CIRCUIT_FAILURES_ENV, "1")
    monkeypatch.setenv(overload.CIRCUIT_RESET_SECS_ENV, "0.1")
    # trip the breaker
    with pytest.raises(grpc.RpcError):
        retry_call(
            _failing(times=100), "push", budget_secs=0.05,
            rng=ZeroRng(), target="ps-1",
        )
    assert overload.breaker_for("ps-1", "write").state() == overload.OPEN
    # a patient caller (no fail-fast) waits out the probe window inside
    # its budget and lands the probe
    healthy = _failing(times=0)
    started = time.monotonic()
    assert retry_call(
        healthy, "push", budget_secs=5, rng=ZeroRng(), target="ps-1",
    ) == "ok"
    assert healthy.state["calls"] == 1
    assert time.monotonic() - started < 2.0


# ---------------------------------------------------------------------------
# retry budget


def test_retry_budget_exhaustion_fails_fast(monkeypatch):
    monkeypatch.setenv(overload.RETRY_BUDGET_TOKENS_ENV, "1")
    fn = _failing(times=100)
    with pytest.raises(overload.RetryBudgetExhausted) as excinfo:
        retry_call(
            fn, "push", budget_secs=30, rng=ZeroRng(), target="ps-2",
        )
    # one token = one funded retry: attempt 1 fails, retry (attempt 2)
    # fails, the second retry finds the bucket dry
    assert fn.state["calls"] == 2
    assert excinfo.value.target == "ps-2"
    assert overload.client_stats()["retry_budget_exhausted"] == 1


def test_successes_refill_the_retry_budget(monkeypatch):
    monkeypatch.setenv(overload.RETRY_BUDGET_TOKENS_ENV, "2")
    monkeypatch.setenv(overload.RETRY_BUDGET_RATIO_ENV, "0.5")
    bucket = overload.retry_budget_for("ps-3")
    assert bucket.spend() and bucket.spend()
    assert not bucket.spend()  # dry
    for _ in range(2):
        bucket.record_success()
    assert bucket.spend()  # 2 successes x 0.5 = one funded retry


# ---------------------------------------------------------------------------
# server pushback


def test_pushback_paces_at_hint_without_penalizing_breaker():
    fn = _failing(
        times=1, code=grpc.StatusCode.RESOURCE_EXHAUSTED,
        retry_after_ms=50,
    )
    started = time.monotonic()
    result = retry_call(
        fn, "push", budget_secs=30, rng=WorstCaseRng(), target="ps-4",
    )
    elapsed = time.monotonic() - started
    assert result == "ok"
    # paced at the server's 50 ms hint, not the worst-case jitter draw
    assert 0.05 <= elapsed < 1.0, elapsed
    assert overload.client_stats()["pushback_waits"] == 1
    # pushback is an ALIVE server managing load: never a breaker strike
    assert overload.breaker_for("ps-4", "write").state() == overload.CLOSED


def test_pushback_without_hint_is_not_retried():
    # plain RESOURCE_EXHAUSTED (no hint trailer) is not retryable
    fn = _failing(times=5, code=grpc.StatusCode.RESOURCE_EXHAUSTED)
    with pytest.raises(FakeRpcError):
        retry_call(fn, "push", budget_secs=30, rng=ZeroRng())
    assert fn.state["calls"] == 1


def test_retry_after_hint_parsing():
    assert overload.retry_after_hint(
        FakeRpcError(grpc.StatusCode.RESOURCE_EXHAUSTED,
                     retry_after_ms=250)
    ) == 0.25
    assert overload.retry_after_hint(
        FakeRpcError(grpc.StatusCode.RESOURCE_EXHAUSTED)
    ) is None
    assert overload.retry_after_hint(grpc.RpcError()) is None
    junk = FakeRpcError(grpc.StatusCode.RESOURCE_EXHAUSTED)
    junk._retry_after_ms = "not-a-number"
    assert overload.retry_after_hint(junk) is None


# ---------------------------------------------------------------------------
# interceptor inertness + error surface


def test_budget_interceptor_identity_when_disabled(monkeypatch):
    monkeypatch.setenv(overload.DEADLINE_BUDGET_ENV, "0")
    channel = grpc.insecure_channel("localhost:1")
    try:
        assert overload.intercept_budget_channel(channel) is channel
    finally:
        channel.close()
    assert overload.server_budget_interceptors() == ()


def test_overload_errors_walk_like_rpc_errors():
    err = overload.CircuitOpenError("ps-0", "write")
    assert isinstance(err, grpc.RpcError)
    assert err.code() == grpc.StatusCode.UNAVAILABLE
    assert "ps-0" in err.details()
    budget_err = overload.RetryBudgetExhausted(
        "ps-1", grpc.StatusCode.UNAVAILABLE
    )
    assert budget_err.code() == grpc.StatusCode.UNAVAILABLE
    assert "ps-1" in budget_err.details()
