"""Cross-role distributed tracing (ISSUE 9).

Covers the acceptance criteria directly:

- a context propagated over REAL gRPC: the server handler's span is a
  child of the exact client-side RPC attempt (trace_id + parent_id
  linkage, not task-id heuristics);
- retry_call attempts are distinct child spans — a fault-injected
  UNAVAILABLE burst shows as failed attempt spans, with no duplicate
  span-ends;
- head sampling: ``EDL_TRACE_SAMPLE=0`` is provably inert (no context,
  no gRPC metadata, an uninstrumented channel), and an UNSAMPLED
  trace's ``sampled=0`` flag propagates so remote roles record
  nothing; tail-keep retains slow unsampled traces locally;
- histogram exemplars: the slowest recent sampled observation's
  trace_id rides /metrics only on the content-negotiated OpenMetrics
  (or env-gated) path — the default 0.0.4 exposition is byte-identical
  to the pre-exemplar format;
- a deepfm local-executor run yields ONE trace per step whose worker
  root span has PS-side child spans, and a serve predict through real
  gRPC reaches a real PS server inside the request's trace;
- scripts: merge_trace threads flows by trace context,
  critical_path.py attributes per-segment self time, trace_summary.py
  groups by trace_id.
"""

import json
import os
import sys
import tempfile
import time

import grpc
import numpy as np
import pytest

from elasticdl_tpu.common.grpc_utils import (
    build_channel,
    build_server,
    find_free_port,
    retry_call,
)
from elasticdl_tpu.common import overload
from elasticdl_tpu.observability import metrics as obs_metrics
from elasticdl_tpu.observability import trace
from elasticdl_tpu.observability.trace_propagation import (
    TraceContextClientInterceptor,
    intercept_trace_channel,
)
from elasticdl_tpu.testing import faults


def _scripts():
    path = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "scripts")
    if path not in sys.path:
        sys.path.insert(0, path)


@pytest.fixture
def traced(tmp_path, monkeypatch):
    """EDL_TRACE_DIR armed + a configured writer; resets module state
    (writer, env caches, thread-locals) afterwards."""
    monkeypatch.setenv(trace.TRACE_DIR_ENV, str(tmp_path))
    monkeypatch.delenv(trace.SAMPLE_ENV, raising=False)
    monkeypatch.delenv(trace.TAIL_KEEP_ENV, raising=False)
    trace.configure("tracetest")
    yield tmp_path
    trace._reset_for_tests()


def _spans(trace_dir):
    _scripts()
    import merge_trace

    trace.flush()
    merged, _names = merge_trace.merge(str(trace_dir))
    return [e for e in merged["traceEvents"] if e.get("ph") == "X"]


# ---------------------------------------------------------------------------
# context format


def test_traceparent_round_trip():
    ctx = trace.SpanContext("ab" * 16, "cd" * 8, True)
    parsed = trace.parse_traceparent(ctx.to_traceparent())
    assert parsed.trace_id == ctx.trace_id
    assert parsed.span_id == ctx.span_id
    assert parsed.sampled
    unsampled = trace.SpanContext("ab" * 16, "cd" * 8, False)
    assert unsampled.to_traceparent().endswith("-00")
    assert not trace.parse_traceparent(
        unsampled.to_traceparent()
    ).sampled


@pytest.mark.parametrize("garbage", [
    "", "banana", "00-zz-cd-01", "00-" + "a" * 31 + "-" + "c" * 16 + "-01",
    "00-%s-%s" % ("a" * 32, "c" * 16), None,
])
def test_traceparent_garbage_is_none(garbage):
    assert trace.parse_traceparent(garbage) is None


def test_extract_context_reads_metadata():
    ctx = trace.SpanContext("ab" * 16, "cd" * 8, True)
    metadata = (
        ("other", "x"), (trace.METADATA_KEY, ctx.to_traceparent()),
    )
    assert trace.extract_context(metadata).trace_id == ctx.trace_id
    assert trace.extract_context((("other", "x"),)) is None
    assert trace.extract_context(None) is None


# ---------------------------------------------------------------------------
# sampling: 0 is provably inert; fractional propagates sampled=0


def test_sample_zero_yields_no_context_and_no_events(
    traced, monkeypatch
):
    monkeypatch.setenv(trace.SAMPLE_ENV, "0")
    with trace.root_span("train_batch") as ctx:
        assert ctx is None
        assert trace.current_context() is None
        with trace.span("ps_pull"):  # legacy span still records
            pass
    spans = _spans(traced)
    assert [e["name"] for e in spans] == ["ps_pull"]
    assert "trace_id" not in spans[0]["args"]


def test_sample_zero_builds_uninstrumented_channel(
    traced, monkeypatch
):
    monkeypatch.setenv(trace.SAMPLE_ENV, "0")
    # deadline-budget propagation (ISSUE 19) rides build_channel too
    # and is on by default; with BOTH kill switches thrown the call
    # path is byte-identical to a bare build (the ISSUE 9 overhead
    # acceptance, extended to every propagation layer)
    monkeypatch.setenv(overload.DEADLINE_BUDGET_ENV, "0")
    channel = build_channel("localhost:1")
    assert "_interceptor" not in type(channel).__module__
    channel.close()


def test_trace_disabled_builds_uninstrumented_channel(monkeypatch):
    monkeypatch.delenv(trace.TRACE_DIR_ENV, raising=False)
    monkeypatch.setenv(overload.DEADLINE_BUDGET_ENV, "0")
    channel = build_channel("localhost:1")
    assert "_interceptor" not in type(channel).__module__
    channel.close()


def test_client_interceptor_injects_traceparent(traced):
    captured = {}

    def continuation(details, request):
        captured["metadata"] = details.metadata
        return "outcome"

    class Details:
        method = "/elasticdl_tpu.Master/get_task"
        timeout = 1.0
        metadata = None
        credentials = None
        wait_for_ready = None
        compression = None

    interceptor = TraceContextClientInterceptor()
    # outside any trace: metadata untouched
    assert interceptor.intercept_unary_unary(
        continuation, Details(), None
    ) == "outcome"
    assert captured["metadata"] is None
    with trace.root_span("step") as ctx:
        interceptor.intercept_unary_unary(continuation, Details(), None)
    sent = trace.extract_context(captured["metadata"])
    assert sent.trace_id == ctx.trace_id
    assert sent.sampled


def test_unsampled_context_propagates_flag_without_recording(
    traced, monkeypatch
):
    monkeypatch.setenv(trace.SAMPLE_ENV, "0.5")
    monkeypatch.setattr(trace, "_rng", _FixedRng(0.9))  # draw > rate
    captured = {}

    def continuation(details, request):
        captured["metadata"] = details.metadata
        return "outcome"

    class Details:
        method = "/m"
        timeout = None
        metadata = None
        credentials = None
        wait_for_ready = None
        compression = None

    interceptor = TraceContextClientInterceptor()
    with trace.root_span("step") as ctx:
        assert ctx is not None and not ctx.sampled
        with trace.span("ps_pull"):
            pass
        interceptor.intercept_unary_unary(continuation, Details(), None)
    sent = trace.extract_context(captured["metadata"])
    assert sent.trace_id == ctx.trace_id
    assert not sent.sampled  # the flag crosses the wire
    assert _spans(traced) == []  # ...and nothing recorded locally


class _FixedRng:
    def __init__(self, value):
        self._value = value

    def random(self):
        return self._value


def test_tail_keep_retains_slow_unsampled_trace(traced, monkeypatch):
    monkeypatch.setenv(trace.SAMPLE_ENV, "0.01")
    monkeypatch.setenv(trace.TAIL_KEEP_ENV, "20")
    monkeypatch.setattr(trace, "_rng", _FixedRng(0.9))
    # fast unsampled root: buffered spans are DROPPED
    with trace.root_span("train_batch") as fast:
        with trace.span("ps_pull"):
            pass
    # slow unsampled root: the buffer flushes, marked tail_kept
    with trace.root_span("train_batch") as slow:
        with trace.span("ps_pull"):
            time.sleep(0.05)
    spans = _spans(traced)
    trace_ids = {e["args"].get("trace_id") for e in spans}
    assert slow.trace_id in trace_ids
    assert fast.trace_id not in trace_ids
    root = next(e for e in spans if e["name"] == "train_batch")
    assert root["args"]["tail_kept"] is True
    child = next(e for e in spans if e["name"] == "ps_pull")
    assert child["args"]["parent_id"] == root["args"]["span_id"]


def test_tail_kept_trace_keeps_late_bound_spans(traced, monkeypatch):
    """A bound callable finishing AFTER its tail-kept root closed (the
    async-push shape) must still land in the trace file — and after a
    DROPPED root, late spans are discarded, not leaked into a dead
    buffer."""
    monkeypatch.setenv(trace.SAMPLE_ENV, "0.01")
    monkeypatch.setenv(trace.TAIL_KEEP_ENV, "20")
    monkeypatch.setattr(trace, "_rng", _FixedRng(0.9))

    def push():
        with trace.span("ps_push"):
            pass

    with trace.root_span("train_batch") as kept:
        late_push = trace.bind_context(push)
        time.sleep(0.05)
    late_push()  # the root already flushed its tail buffer
    with trace.root_span("train_batch") as dropped:
        dropped_push = trace.bind_context(push)
    dropped_push()
    spans = _spans(traced)
    late = [e for e in spans if e["name"] == "ps_push"]
    assert [e["args"]["trace_id"] for e in late] == [kept.trace_id]
    assert not any(
        e["args"].get("trace_id") == dropped.trace_id for e in spans
    )


def test_sampled_zero_metadata_suppresses_server_handler(traced):
    """The server side of sampled=0: a handler receiving an unsampled
    traceparent records neither its own span nor any span the handler
    body opens (child roles don't record)."""
    calls = []

    def handler(request, context):
        with trace.span("ps_apply_push"):
            calls.append(1)
        return "resp"

    wrapped = trace.traced_handler(handler, "Pserver", "push_gradients")

    class Ctx:
        def __init__(self, sampled):
            self._sampled = sampled

        def invocation_metadata(self):
            parent = trace.SpanContext("ef" * 16, "12" * 8, self._sampled)
            return ((trace.METADATA_KEY, parent.to_traceparent()),)

    assert wrapped("req", Ctx(sampled=False)) == "resp"
    assert _spans(traced) == []
    assert wrapped("req", Ctx(sampled=True)) == "resp"
    spans = _spans(traced)
    assert {e["name"] for e in spans} == {
        "Pserver/push_gradients", "ps_apply_push"
    }
    server = next(
        e for e in spans if e["name"] == "Pserver/push_gradients"
    )
    assert server["args"]["trace_id"] == "ef" * 16
    assert server["args"]["parent_id"] == "12" * 8
    apply = next(e for e in spans if e["name"] == "ps_apply_push")
    assert apply["args"]["parent_id"] == server["args"]["span_id"]
    assert calls == [1, 1]


def test_annotate_merges_into_open_span(traced):
    """Mid-block facts (the serve abort path's status code) land on
    the innermost open span even when the exception that ends the
    block carries no code of its own."""
    with pytest.raises(RuntimeError):
        with trace.root_span("serve_predict") as outer:
            ctx = outer
            trace.annotate(code="RESOURCE_EXHAUSTED", rows=4)
            raise RuntimeError("bare abort")
    spans = _spans(traced)
    root = next(e for e in spans if e["name"] == "serve_predict")
    assert root["args"]["trace_id"] == ctx.trace_id
    assert root["args"]["code"] == "RESOURCE_EXHAUSTED"
    assert root["args"]["rows"] == 4
    assert root["args"]["error"] == "RuntimeError"
    # inert outside any span
    trace.annotate(code="X")


def test_serve_shed_root_span_records_status_code(traced):
    """A shed predict's root span carries the abort's status code (the
    critical_path 'shed' classifier) even though grpc's context.abort
    raises a code-less exception."""
    import grpc as grpc_mod

    from elasticdl_tpu.serve import batcher as batcher_mod
    from elasticdl_tpu.serve.servicer import ServeServicer

    class Engine:
        loaded = True

        class batcher:
            max_batch = 32
            default_deadline_secs = 1.0

        @staticmethod
        def predict(features, rows, deadline_secs):
            raise batcher_mod.QueueFull("at depth")

    class Ctx:
        code = None

        def invocation_metadata(self):
            return ()

        def time_remaining(self):
            return 5.0

        def abort(self, code, detail):
            self.code = code
            raise Exception(detail)  # grpc's abort: bare, code-less

    from elasticdl_tpu.proto import elasticdl_tpu_pb2 as pb
    from elasticdl_tpu.common.tensor_utils import ndarray_to_blob

    request = pb.PredictRequest()
    ndarray_to_blob(np.ones((2, 4), np.float32),
                    request.features["ids"])
    servicer = ServeServicer(Engine())
    context = Ctx()
    with pytest.raises(Exception):
        servicer.predict(request, context)
    assert context.code == grpc_mod.StatusCode.RESOURCE_EXHAUSTED
    spans = _spans(traced)
    root = next(e for e in spans if e["name"] == "serve_predict")
    assert root["args"]["code"] == "RESOURCE_EXHAUSTED"
    _scripts()
    import critical_path

    report = critical_path.build_report(
        critical_path.load_events(str(traced))
    )
    assert "shed" in report["predict"]["segments"]


# ---------------------------------------------------------------------------
# propagation over real gRPC + retry_call attempt spans


def _master_server():
    from elasticdl_tpu.master.servicer import MasterServicer
    from elasticdl_tpu.master.task_dispatcher import TaskDispatcher
    from elasticdl_tpu.proto.services import add_master_servicer_to_server

    dispatcher = TaskDispatcher({"s": (0, 64)}, records_per_task=32)
    server = build_server()
    add_master_servicer_to_server(MasterServicer(dispatcher), server)
    port = find_free_port()
    server.add_insecure_port("localhost:%d" % port)
    server.start()
    return server, port


def test_context_propagates_through_real_grpc(traced):
    from elasticdl_tpu.worker.master_client import MasterClient

    server, port = _master_server()
    try:
        mc = MasterClient("localhost:%d" % port, worker_id=0)
        with trace.root_span("train_batch", role="worker") as ctx:
            task = mc.get_task()
        assert task is not None
    finally:
        server.stop(0)
    spans = _spans(traced)
    ours = [e for e in spans if e["args"].get("trace_id") == ctx.trace_id]
    by_name = {e["name"]: e for e in ours}
    # one trace spans the client root, the RPC attempt, and the SERVER
    # handler — linked by explicit parent ids through the metadata hop
    assert {"train_batch", "rpc_attempt", "Master/get_task"} <= set(
        by_name
    )
    root = by_name["train_batch"]
    attempt = by_name["rpc_attempt"]
    handler = by_name["Master/get_task"]
    assert "parent_id" not in root["args"]
    assert attempt["args"]["parent_id"] == root["args"]["span_id"]
    assert handler["args"]["parent_id"] == attempt["args"]["span_id"]
    assert handler["args"]["kind"] == "grpc_server"


def test_retry_attempts_are_distinct_failed_child_spans(
    traced, monkeypatch
):
    """A fault-injected UNAVAILABLE burst: each retry_call attempt is
    its own child span — the failed ones carry error/code args — and
    the enclosing span ends exactly once."""
    monkeypatch.setenv(
        faults.FAULT_SPEC_ENV, "tracer:get_task:unavailable:2"
    )
    faults._reset_for_tests()
    faults.set_role("tracer")
    server, port = _master_server()
    try:
        from elasticdl_tpu.proto import elasticdl_tpu_pb2 as pb
        from elasticdl_tpu.proto.services import MasterStub

        stub = MasterStub(build_channel("localhost:%d" % port))
        with trace.root_span("train_batch") as ctx:
            retry_call(
                lambda: stub.get_task(
                    pb.GetTaskRequest(worker_id=0), timeout=5
                ),
                "get_task", budget_secs=30.0, base_delay=0.01,
            )
    finally:
        server.stop(0)
        faults._reset_for_tests()
        monkeypatch.delenv(faults.FAULT_SPEC_ENV, raising=False)
    spans = [
        e for e in _spans(traced)
        if e["args"].get("trace_id") == ctx.trace_id
    ]
    attempts = sorted(
        (e for e in spans if e["name"] == "rpc_attempt"),
        key=lambda e: e["args"]["attempt"],
    )
    assert [a["args"]["attempt"] for a in attempts] == [1, 2, 3]
    assert [a["args"].get("code") for a in attempts] == [
        "UNAVAILABLE", "UNAVAILABLE", None,
    ]
    # every attempt is a child of the SAME root, which ended once
    roots = [e for e in spans if e["name"] == "train_batch"]
    assert len(roots) == 1
    assert all(
        a["args"]["parent_id"] == roots[0]["args"]["span_id"]
        for a in attempts
    )
    # distinct span ids: no span was double-ended into two events
    span_ids = [e["args"]["span_id"] for e in spans]
    assert len(span_ids) == len(set(span_ids))


# ---------------------------------------------------------------------------
# histogram exemplars + exposition content negotiation


def test_histogram_exemplar_tracks_slowest_sampled_observation(traced):
    reg = obs_metrics.Registry(enabled=True)
    hist = reg.histogram("lat_seconds", "l", buckets=(0.1, 1.0))
    hist.observe(0.9)  # outside any trace: no exemplar
    assert "# {" not in reg.render(exemplars=True)
    with trace.root_span("step") as slow_ctx:
        hist.observe(0.5)
    with trace.root_span("step"):
        hist.observe(0.05)  # faster: must NOT displace the exemplar
    plain = reg.render()
    assert "# {" not in plain  # default 0.0.4 path: no exemplars
    text = reg.render(exemplars=True)
    assert '# {trace_id="%s"} 0.5' % slow_ctx.trace_id in text
    # the exemplar rides the first bucket containing its value
    line = next(l for l in text.splitlines() if "# {" in l)
    assert line.startswith('lat_seconds_bucket{le="1"}')


def test_exemplar_window_admits_fresh_trace(traced, monkeypatch):
    reg = obs_metrics.Registry(enabled=True)
    hist = reg.histogram("lat_seconds", "l", buckets=(10.0,))
    with trace.root_span("step"):
        hist.observe(5.0)
    monkeypatch.setattr(obs_metrics, "EXEMPLAR_WINDOW_SECS", 0.0)
    with trace.root_span("step") as fresh:
        hist.observe(0.5)  # faster but RECENT: replaces the stale one
    assert 'trace_id="%s"' % fresh.trace_id in reg.render(exemplars=True)


def test_metrics_endpoint_content_negotiation(traced, monkeypatch):
    import urllib.request

    from elasticdl_tpu.observability.http_server import (
        ObservabilityServer,
    )

    monkeypatch.delenv(obs_metrics.EXEMPLARS_ENV, raising=False)
    reg = obs_metrics.Registry(enabled=True)
    hist = reg.histogram("edl_lat_seconds", "l", buckets=(1.0,))
    with trace.root_span("step"):
        hist.observe(0.5)
    server = ObservabilityServer("w", 0, registry=reg).start()
    try:
        base = "http://localhost:%d/metrics" % server.port
        plain = urllib.request.urlopen(base, timeout=5)
        body = plain.read().decode()
        # default path: plain 0.0.4 — parseable by existing consumers
        # (no exemplar markers, no EOF terminator, 0.0.4 content type)
        assert "# {" not in body and "# EOF" not in body
        assert "version=0.0.4" in plain.headers["Content-Type"]
        for line in body.splitlines():
            assert line.startswith("#") or " # " not in line
        request = urllib.request.Request(
            base, headers={"Accept": "application/openmetrics-text"}
        )
        negotiated = urllib.request.urlopen(request, timeout=5)
        om_body = negotiated.read().decode()
        assert "# {trace_id=" in om_body
        assert om_body.endswith("# EOF\n")
        assert "openmetrics-text" in negotiated.headers["Content-Type"]
        # a STOCK Prometheus advertises openmetrics WITH a text/plain
        # fallback — it must keep getting the plain 0.0.4 body it
        # parsed yesterday, not this pragmatic exposition
        stock = urllib.request.Request(base, headers={
            "Accept": "application/openmetrics-text;version=1.0.0,"
            "text/plain;version=0.0.4;q=0.5,*/*;q=0.1"
        })
        stock_reply = urllib.request.urlopen(stock, timeout=5)
        stock_body = stock_reply.read().decode()
        assert "# {" not in stock_body and "# EOF" not in stock_body
        assert "version=0.0.4" in stock_reply.headers["Content-Type"]
        # env gate: exemplars on the plain path, still 0.0.4 framed
        monkeypatch.setenv(obs_metrics.EXEMPLARS_ENV, "1")
        gated = urllib.request.urlopen(base, timeout=5).read().decode()
        assert "# {trace_id=" in gated and "# EOF" not in gated
    finally:
        server.stop()


# ---------------------------------------------------------------------------
# scripts: merge threading, critical path, trace summary


def _write_trace_file(trace_dir, role, pid, events):
    path = os.path.join(str(trace_dir), "%s-%d.trace.json" % (role, pid))
    meta = {"name": "process_name", "ph": "M", "pid": pid, "tid": 0,
            "args": {"name": role}}
    with open(path, "w", encoding="utf-8") as f:
        f.write("[\n")
        for event in [meta] + events:
            f.write(json.dumps(event) + ",\n")


def _span_event(name, ts, dur, pid, trace_id=None, span_id=None,
                parent_id=None, **args):
    if trace_id:
        args["trace_id"] = trace_id
        args["span_id"] = span_id
        if parent_id:
            args["parent_id"] = parent_id
    return {"name": name, "ph": "X", "ts": ts, "dur": dur, "pid": pid,
            "tid": 1, "args": args}


def test_merge_threads_flows_by_trace_context(tmp_path):
    _scripts()
    import merge_trace

    tid = "aa" * 16
    _write_trace_file(tmp_path, "worker-0", 1, [
        _span_event("train_batch", 0, 100, 1, tid, "01" * 8,
                    role="worker"),
        _span_event("legacy_a", 500, 10, 1, task_id=9),
    ])
    _write_trace_file(tmp_path, "ps-0", 2, [
        _span_event("Pserver/push_gradients", 10, 20, 2, tid, "02" * 8,
                    parent_id="01" * 8),
        _span_event("legacy_b", 520, 10, 2, task_id=9),
    ])
    merged, _names = merge_trace.merge(str(tmp_path))
    flows = [e for e in merged["traceEvents"]
             if e.get("ph") in ("s", "t", "f")]
    trace_flows = [f for f in flows if f["cat"] == "trace"]
    task_flows = [f for f in flows if f["cat"] == "task"]
    # the context-carrying spans thread by trace_id...
    assert [f["ph"] for f in trace_flows] == ["s", "f"]
    assert all(f["id"] == tid[:16] for f in trace_flows)
    # ...and do NOT double-thread through the task heuristic, which
    # still serves the legacy spans
    assert [f["ph"] for f in task_flows] == ["s", "f"]
    assert {f["ts"] for f in task_flows} == {500, 520}


def test_merge_task_flows_survive_mixed_groups(tmp_path):
    """The master's dispatch span has a task_id but NO trace context
    (get_task runs outside the worker's root span); the worker's
    train span carries both. The task flow must still thread the two —
    only groups FULLY covered by context threading are skipped."""
    _scripts()
    import merge_trace

    tid = "ff" * 16
    _write_trace_file(tmp_path, "master", 5, [
        _span_event("dispatch", 0, 50, 5, task_id=7),
    ])
    _write_trace_file(tmp_path, "worker-0", 6, [
        _span_event("train_batch", 100, 900, 6, tid, "01" * 8,
                    task_id=7, role="worker"),
        _span_event("ps_push", 500, 100, 6, tid, "02" * 8,
                    parent_id="01" * 8, task_id=7),
    ])
    merged, _names = merge_trace.merge(str(tmp_path))
    task_flows = [e for e in merged["traceEvents"]
                  if e.get("ph") in ("s", "t", "f")
                  and e.get("cat") == "task"]
    # dispatch threads into the context-carrying worker spans
    assert [f["ph"] for f in task_flows] == ["s", "t", "f"]
    assert {f["ts"] for f in task_flows} == {0, 100, 500}


def test_critical_path_attribution_math(tmp_path):
    _scripts()
    import critical_path

    tid = "bb" * 16
    # root 10ms; pull child 2ms; push child 3ms containing a 2ms
    # server-side apply -> compute self = 5ms, push self = 1ms
    _write_trace_file(tmp_path, "worker-0", 1, [
        _span_event("train_batch", 0, 10000, 1, tid, "01" * 8,
                    role="worker"),
        _span_event("ps_pull_batch", 1000, 2000, 1, tid, "02" * 8,
                    parent_id="01" * 8),
        _span_event("ps_push", 5000, 3000, 1, tid, "03" * 8,
                    parent_id="01" * 8),
    ])
    _write_trace_file(tmp_path, "ps-0", 2, [
        _span_event("Pserver/push_gradients", 5500, 2000, 2, tid,
                    "04" * 8, parent_id="03" * 8),
    ])
    report = critical_path.build_report(
        critical_path.load_events(str(tmp_path))
    )
    assert report["traces"] == 1
    step = report["step"]
    assert step["count"] == 1
    assert step["roles"] == ["ps", "worker"]
    assert step["multi_role_traces"] == 1
    segments = step["segments"]
    assert segments["compute"]["p50_ms"] == pytest.approx(5.0)
    assert segments["pull"]["p50_ms"] == pytest.approx(2.0)
    assert segments["push"]["p50_ms"] == pytest.approx(1.0)
    assert segments["apply"]["p50_ms"] == pytest.approx(2.0)
    shares = sum(s["share"] for s in segments.values())
    assert shares == pytest.approx(1.0, abs=1e-3)


def test_critical_path_classifies_shed_predicts(tmp_path):
    _scripts()
    import critical_path

    tid = "cc" * 16
    _write_trace_file(tmp_path, "serve-0", 3, [
        _span_event("serve_predict", 0, 2000, 3, tid, "01" * 8,
                    role="serve", error="DeadlineExpired",
                    code="DEADLINE_EXCEEDED"),
    ])
    report = critical_path.build_report(
        critical_path.load_events(str(tmp_path))
    )
    predict = report["predict"]
    assert predict["segments"]["shed"]["p50_ms"] == pytest.approx(2.0)
    assert report["slowest"][0]["shed"] is True


def test_trace_summary_groups_by_trace(tmp_path):
    _scripts()
    import trace_summary

    for i, tid in enumerate(("dd" * 16, "ee" * 16)):
        _write_trace_file(tmp_path, "worker-%d" % i, 10 + i, [
            _span_event("train_batch", 0, 1000 * (i + 1), 10 + i, tid,
                        "01" * 8, role="worker"),
            _span_event("ps_pull", 100, 200, 10 + i, tid, "02" * 8,
                        parent_id="01" * 8, role="ps"),
        ])
    summary = trace_summary.summarize_edl_traces(str(tmp_path))
    assert summary["traces"] == 2
    assert summary["names"]["train_batch"]["count"] == 2
    assert summary["names"]["ps_pull"]["p50_ms"] == pytest.approx(0.2)
    slowest = summary["slowest"]
    assert slowest[0]["duration_ms"] >= slowest[-1]["duration_ms"]
    assert slowest[0]["roles"] == ["ps", "worker"]
    assert slowest[0]["spans"] == 2
    trace_summary.print_edl_summary(summary)  # smoke the table


# ---------------------------------------------------------------------------
# acceptance: deepfm local-executor end to end


@pytest.fixture(scope="module")
def deepfm_traced_run():
    """One traced deepfm local-executor run shared by the e2e tests."""
    tmp = tempfile.mkdtemp(prefix="edl-tracing-")
    trace_dir = os.path.join(tmp, "traces")
    from tests.test_utils import create_ctr_recordio

    create_ctr_recordio(tmp + "/f0.rec", num_records=96, seed=0)
    previous = {
        key: os.environ.get(key)
        for key in (trace.TRACE_DIR_ENV, trace.SAMPLE_ENV)
    }
    os.environ[trace.TRACE_DIR_ENV] = trace_dir
    os.environ[trace.SAMPLE_ENV] = "1"
    try:
        from elasticdl_tpu.train.local_executor import LocalExecutor

        executor = LocalExecutor(
            "elasticdl_tpu.models.deepfm", training_data=tmp,
            minibatch_size=32, num_epochs=1,
        )
        executor.train()
        trace.flush()
    finally:
        for key, value in previous.items():
            if value is None:
                os.environ.pop(key, None)
            else:
                os.environ[key] = value
        trace._reset_for_tests()
    return executor, trace_dir


def test_deepfm_local_run_yields_one_trace_per_step_with_ps_children(
    deepfm_traced_run,
):
    _executor, trace_dir = deepfm_traced_run
    spans = _spans(trace_dir)
    by_trace = {}
    for event in spans:
        tid = event["args"].get("trace_id")
        if tid:
            by_trace.setdefault(tid, []).append(event)
    roots = [e for e in spans if e["name"] == "train_batch"]
    # ONE trace per step: every root owns a distinct trace_id
    assert len(roots) == 3  # 96 records / 32
    assert len({r["args"]["trace_id"] for r in roots}) == len(roots)
    for trace_spans in by_trace.values():
        root = next(
            e for e in trace_spans if "parent_id" not in e["args"]
        )
        assert root["name"] == "train_batch"
        assert root["args"]["role"] == "worker"
        # PS-side children, linked via the propagated context
        ps_children = [
            e for e in trace_spans if e["args"].get("role") == "ps"
        ]
        assert ps_children, trace_spans
        span_ids = {
            e["args"]["span_id"] for e in trace_spans
        }
        assert all(
            e["args"]["parent_id"] in span_ids for e in ps_children
        )
        assert any(
            e["name"] == "ps_apply_push" for e in ps_children
        )


def test_critical_path_report_on_deepfm_run(deepfm_traced_run):
    _scripts()
    import critical_path

    _executor, trace_dir = deepfm_traced_run
    report = critical_path.build_report(
        critical_path.load_events(trace_dir)
    )
    step = report["step"]
    assert step["count"] == 3
    # the CI tier-1d gate: every step trace spans worker AND ps
    assert step["multi_role_traces"] == step["count"]
    assert {"worker", "ps"} <= set(step["roles"])
    assert {"compute", "pull", "apply"} <= set(step["segments"])
    for stats in step["segments"].values():
        assert stats["p99_ms"] >= stats["p50_ms"] >= 0.0


# ---------------------------------------------------------------------------
# acceptance: serve predict through real gRPC with a real PS


@pytest.mark.slow
def test_serve_predict_trace_reaches_real_ps(tmp_path, monkeypatch):
    """client -> serve batcher -> model -> EmbeddingClient -> PS, one
    trace: the serve root span's descendants include the REAL PS
    server's handler span, linked via propagated context across two
    gRPC hops (client->serve is real gRPC too; the serve root opens at
    admission)."""
    from elasticdl_tpu.models import deepfm
    from elasticdl_tpu.ps.embedding_store import create_store
    from elasticdl_tpu.ps.servicer import PserverServicer
    from elasticdl_tpu.proto.services import (
        add_pserver_servicer_to_server,
        add_serve_servicer_to_server,
    )
    from elasticdl_tpu.serve.client import ServeClient
    from elasticdl_tpu.serve.engine import ServingEngine
    from elasticdl_tpu.serve.servicer import ServeServicer
    from elasticdl_tpu.train.export import export_train_state
    from elasticdl_tpu.train.local_executor import LocalExecutor
    from elasticdl_tpu.worker.ps_client import PSClient
    from tests.test_utils import create_ctr_recordio

    monkeypatch.setenv(trace.TRACE_DIR_ENV, str(tmp_path / "traces"))
    monkeypatch.setenv(trace.SAMPLE_ENV, "1")
    trace.configure("servetest")

    data = tmp_path / "data"
    data.mkdir()
    create_ctr_recordio(str(data / "f0.rec"), num_records=64, seed=0)
    executor = LocalExecutor(
        "elasticdl_tpu.models.deepfm", training_data=str(data),
        minibatch_size=32, num_epochs=1,
    )
    executor.train()
    export_dir = str(tmp_path / "export")
    export_train_state(executor.state, export_dir)

    # a REAL PS server (build_server: traced handlers), seeded with the
    # locally trained rows
    store = create_store(seed=0, prefer_native=False)
    store.set_optimizer("adam", lr=0.001)
    ps_server = build_server()
    add_pserver_servicer_to_server(
        PserverServicer(store, use_async=True), ps_server
    )
    ps_port = find_free_port()
    ps_server.add_insecure_port("localhost:%d" % ps_port)
    ps_server.start()
    engine = None
    serve_server = None
    client = None
    try:
        ps_client = PSClient(["localhost:%d" % ps_port])
        specs = deepfm.sparse_embedding_specs(batch_size=32)
        ps_client.push_embedding_table_infos(
            [(s.name, s.dim, str(float(s.init_scale))) for s in specs]
        )
        local_store = executor.trainer.preparer._ps.store
        ps_client.push_embedding_rows({
            s.name: local_store.export_table(s.name) for s in specs
        })
        engine = ServingEngine(
            "elasticdl_tpu.models.deepfm", export_dir,
            ps_client=ps_client, max_batch=32, max_delay_ms=2.0,
            deadline_ms=60000.0,
        ).start(block=True)
        serve_server = build_server()
        add_serve_servicer_to_server(ServeServicer(engine), serve_server)
        serve_port = find_free_port()
        serve_server.add_insecure_port("localhost:%d" % serve_port)
        serve_server.start()
        client = ServeClient("localhost:%d" % serve_port)
        ids = np.random.RandomState(3).randint(
            0, 1000, size=(4, 10)
        ).astype(np.int64)
        outputs, _step, _stamp = client.predict(
            {"ids": ids}, deadline_secs=120
        )
        assert np.isfinite(outputs["output"]).all()
    finally:
        if client is not None:
            client.close()
        if serve_server is not None:
            serve_server.stop(0)
        if engine is not None:
            engine.drain(timeout=5)
        ps_server.stop(0)
        trace.flush()
        trace._reset_for_tests()
    spans = _spans(tmp_path / "traces")
    roots = [e for e in spans if e["name"] == "serve_predict"]
    assert len(roots) == 1
    root = roots[0]
    tid = root["args"]["trace_id"]
    ours = {
        e["args"]["span_id"]: e
        for e in spans
        if e["args"].get("trace_id") == tid
    }
    ps_handler = next(
        (e for e in ours.values()
         if e["name"].startswith("Pserver/pull")), None
    )
    assert ps_handler is not None, sorted(
        e["name"] for e in ours.values()
    )
    assert ps_handler["args"]["kind"] == "grpc_server"
    # walk parents from the PS handler back to the serve root: the
    # chain crosses the batcher thread hand-off AND the gRPC hop
    node = ps_handler
    hops = []
    while "parent_id" in node["args"]:
        hops.append(node["name"])
        node = ours[node["args"]["parent_id"]]
    assert node is root, hops
    assert "serve_batch_run" in (hops + [node["name"]])


# ---------------------------------------------------------------------------
# serve drain satellite: trace flush + trace_flushed event


def test_serve_drain_flushes_trace_and_journals_event(
    tmp_path, monkeypatch, deepfm_traced_run
):
    from elasticdl_tpu.observability import events
    from elasticdl_tpu.serve.main import ServeRole, parse_serve_args
    from elasticdl_tpu.train.export import export_train_state

    executor, _ = deepfm_traced_run
    export_dir = str(tmp_path / "export")
    export_train_state(executor.state, export_dir)
    monkeypatch.setenv(trace.TRACE_DIR_ENV, str(tmp_path / "traces"))
    monkeypatch.setenv(events.EVENTS_DIR_ENV, str(tmp_path / "events"))
    trace.configure("serve-0")
    journal = events.configure("serve-0")
    try:
        role = ServeRole(parse_serve_args([
            "--model_zoo", "elasticdl_tpu.models.deepfm",
            "--export_dir", export_dir,
        ]))
        with trace.span("serve_smoke"):
            pass
        role.drain(reason="test")
        with open(journal.path, encoding="utf-8") as f:
            names = [json.loads(line)["event"] for line in f
                     if line.strip()]
        assert "trace_flushed" in names
        assert names.index("trace_flushed") < names.index("serve_drained")
        # the flush is real: the span above is on disk
        spans = _spans(tmp_path / "traces")
        assert any(e["name"] == "serve_smoke" for e in spans)
        role.drain(reason="test")  # idempotent: no second event
        with open(journal.path, encoding="utf-8") as f:
            again = [json.loads(line)["event"] for line in f
                     if line.strip()]
        assert again.count("trace_flushed") == 1
    finally:
        events._reset_for_tests()
        trace._reset_for_tests()


# ---------------------------------------------------------------------------
# buffered span-id entropy (ISSUE 15 satellite)


def test_entropy_pool_id_shapes_and_uniqueness():
    """Pooled ids keep the W3C wire shape (16-hex span / 32-hex trace)
    and never repeat across refills (10k ids spans ~20 refills of the
    4 KiB buffer at 8 bytes/id... it spans at least 19 boundaries)."""
    from elasticdl_tpu.observability.trace import (
        _new_span_id,
        _new_trace_id,
    )

    span_ids = {_new_span_id() for _ in range(10_000)}
    assert len(span_ids) == 10_000
    assert all(len(s) == 16 for s in span_ids)
    trace_ids = {_new_trace_id() for _ in range(1_000)}
    assert len(trace_ids) == 1_000
    assert all(len(t) == 32 for t in trace_ids)
    int(next(iter(span_ids)), 16)  # hex


def test_entropy_pool_refills_and_resets():
    from elasticdl_tpu.observability.trace import _EntropyPool

    pool = _EntropyPool(size=32)  # tiny: force refills every 4 takes
    taken = [pool.take(8) for _ in range(20)]
    assert all(len(t) == 8 for t in taken)
    assert len(set(taken)) == 20  # refills never re-deal bytes
    # fork-safety hook: reset() empties the buffer so a child draws
    # fresh entropy instead of replaying the parent's remainder
    pool.reset()
    assert pool._buf == b"" and pool._pos == 0
    assert len(pool.take(8)) == 8  # next take refills cleanly


def test_entropy_pool_concurrent_takes_are_distinct():
    import threading

    from elasticdl_tpu.observability.trace import _new_span_id

    out = [None] * 8

    def draw(i):
        out[i] = [_new_span_id() for _ in range(2_000)]

    threads = [
        threading.Thread(target=draw, args=(i,)) for i in range(8)
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    merged = [s for chunk in out for s in chunk]
    assert len(set(merged)) == len(merged)
