"""Native C++ embedding store vs numpy twin: exact semantic parity.

Models the reference's Go kernel tests (go/pkg/kernel/kernel_test.go,
optimizer_test.go): table-driven checks of each sparse optimizer.
"""

import numpy as np
import pytest

from elasticdl_tpu.ps.embedding_store import (
    NativeEmbeddingStore,
    NumpyEmbeddingStore,
    native_lib,
)

needs_native = pytest.mark.skipif(
    native_lib() is None, reason="native store unavailable"
)


@needs_native
def test_native_builds_and_lazy_inits():
    store = NativeEmbeddingStore(seed=7)
    store.set_optimizer("sgd", lr=0.1)
    store.create_table("emb", 8, init_scale=0.05)
    ids = np.array([5, 9, 5], dtype=np.int64)
    rows = store.lookup("emb", ids)
    assert rows.shape == (3, 8)
    # same id -> same lazily-created row
    np.testing.assert_array_equal(rows[0], rows[2])
    assert (np.abs(rows) <= 0.05).all()
    assert store.table_size("emb") == 2


@needs_native
@pytest.mark.parametrize(
    "opt", ["sgd", "momentum", "nesterov", "adagrad", "adam", "amsgrad"]
)
def test_native_matches_numpy_optimizers(opt):
    native = NativeEmbeddingStore(seed=3)
    ref = NumpyEmbeddingStore(seed=3)
    for store in (native, ref):
        store.set_optimizer(opt, lr=0.05)
        store.create_table("t", 4, init_scale=0.1)
    ids = np.array([1, 2, 3], dtype=np.int64)
    # align initial rows (different RNGs): import the same weights
    init = np.random.RandomState(0).rand(3, 4).astype(np.float32)
    native.import_table("t", ids, init)
    ref.import_table("t", ids, init)
    rng = np.random.RandomState(1)
    for step in range(5):
        upd_ids = ids[: 2 + step % 2]
        grads = rng.randn(upd_ids.size, 4).astype(np.float32)
        native.push_gradients("t", upd_ids, grads)
        ref.push_gradients("t", upd_ids, grads)
    np.testing.assert_allclose(
        native.lookup("t", ids), ref.lookup("t", ids), rtol=1e-5, atol=1e-6
    )


@needs_native
def test_export_import_reshard():
    store = NativeEmbeddingStore(seed=0)
    store.set_optimizer("sgd")
    store.create_table("t", 2)
    ids = np.arange(10, dtype=np.int64)
    values = np.arange(20, dtype=np.float32).reshape(10, 2)
    store.import_table("t", ids, values)
    out_ids, out_values = store.export_table("t")
    order = np.argsort(out_ids)
    np.testing.assert_array_equal(out_ids[order], ids)
    np.testing.assert_array_equal(out_values[order], values)
    # re-shard: shard 1 of 3 keeps ids 1,4,7
    shard = NativeEmbeddingStore(seed=0)
    shard.set_optimizer("sgd")
    shard.create_table("t", 2)
    shard.import_table("t", out_ids, out_values, shard_id=1, shard_num=3)
    assert shard.table_size("t") == 3
    np.testing.assert_array_equal(
        shard.lookup("t", np.array([4], dtype=np.int64))[0], values[4]
    )


def test_numpy_store_staleness_lr_scale():
    store = NumpyEmbeddingStore(seed=0)
    store.set_optimizer("sgd", lr=1.0)
    store.create_table("t", 2)
    ids = np.array([1], dtype=np.int64)
    store.import_table("t", ids, np.zeros((1, 2), np.float32))
    store.push_gradients("t", ids, np.ones((1, 2), np.float32), lr_scale=0.5)
    np.testing.assert_allclose(
        store.lookup("t", ids)[0], [-0.5, -0.5]
    )


@needs_native
def test_variant_flags_normalize():
    """nesterov/amsgrad booleans fold into the variant kernels; wrong
    base optimizer is rejected."""
    store = NativeEmbeddingStore(seed=0)
    store.set_optimizer("momentum", lr=0.1, nesterov=True)
    store.create_table("t", 2)
    ref = NumpyEmbeddingStore(seed=0)
    ref.set_optimizer("adam", amsgrad=True)
    with pytest.raises(ValueError, match="nesterov requires"):
        NumpyEmbeddingStore(seed=0).set_optimizer("sgd", nesterov=True)
    with pytest.raises(ValueError, match="amsgrad requires"):
        NumpyEmbeddingStore(seed=0).set_optimizer("sgd", amsgrad=True)


def test_nesterov_differs_from_momentum():
    ids = np.array([0], dtype=np.int64)
    init = np.zeros((1, 2), np.float32)
    results = {}
    for opt in ("momentum", "nesterov"):
        store = NumpyEmbeddingStore(seed=0)
        store.set_optimizer(opt, lr=0.1, momentum=0.9)
        store.create_table("t", 2)
        store.import_table("t", ids, init)
        for _ in range(3):
            store.push_gradients("t", ids, np.ones((1, 2), np.float32))
        results[opt] = store.lookup("t", ids)
    assert not np.allclose(results["momentum"], results["nesterov"])
    # nesterov's lookahead steps further along a constant gradient
    assert (results["nesterov"] < results["momentum"]).all()


@pytest.mark.parametrize("cls", [NumpyEmbeddingStore, NativeEmbeddingStore])
@pytest.mark.parametrize(
    "initializer,param,check",
    [
        ("constant", 1.5, lambda r: np.testing.assert_array_equal(
            r, np.full_like(r, 1.5))),
        ("zeros", 0.0, lambda r: np.testing.assert_array_equal(
            r, np.zeros_like(r))),
        ("uniform", 0.2, lambda r: (
            (np.abs(r) <= 0.2).all() and r.std() > 0.05
        ) or pytest.fail("uniform out of range")),
        ("normal", 0.1, lambda r: (
            abs(float(r.mean())) < 0.02 and 0.05 < float(r.std()) < 0.2
        ) or pytest.fail("normal stats off")),
        ("truncated_normal", 0.1, lambda r: (
            (np.abs(r) <= 0.2 + 1e-6).all() and float(r.std()) > 0.03
        ) or pytest.fail("truncated_normal out of bound")),
    ],
)
def test_initializer_kinds(cls, initializer, param, check):
    """Row initializers match reference initializer.go:25-155 semantics:
    Zero/Constant exact, Normal/TruncatedNormal by moments, truncation
    bounded by 2*stddev."""
    if cls is NativeEmbeddingStore and native_lib() is None:
        pytest.skip("native store unavailable")
    store = cls(seed=11)
    store.set_optimizer("sgd", lr=0.1)
    store.create_table("t", 64, init_scale=param, initializer=initializer)
    rows = store.lookup("t", np.arange(32, dtype=np.int64))
    check(rows)


def test_parse_initializer_wire_formats():
    from elasticdl_tpu.ps.embedding_store import parse_initializer

    assert parse_initializer("0.07") == ("uniform", 0.07)
    assert parse_initializer("") == ("uniform", 0.05)
    assert parse_initializer("normal:0.01") == ("normal", 0.01)
    assert parse_initializer("constant:2.0") == ("constant", 2.0)
    assert parse_initializer("zeros") == ("constant", 0.0)
    assert parse_initializer("truncated_normal") == (
        "truncated_normal", 0.05)
    with pytest.raises(ValueError):
        parse_initializer("glorot:1.0")
