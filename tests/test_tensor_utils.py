"""Round-trip property tests for the wire serialization layer
(common/tensor_utils.py): every dtype the protocol carries, the packed
vs legacy id encodings, and the EDL_WIRE_DTYPE payload knob's
bit-exactness contract (ISSUE 5)."""

import numpy as np
import pytest

from elasticdl_tpu.common import tensor_utils
from elasticdl_tpu.proto import elasticdl_tpu_pb2 as pb


def _roundtrip(array):
    return tensor_utils.blob_to_ndarray(
        tensor_utils.ndarray_to_blob(array)
    )


# ---------------------------------------------------------------------------
# TensorBlob round trips

@pytest.mark.parametrize("dtype", [
    "float32", "float64", "float16", "int8", "uint8", "int32", "int64",
    "bool",
])
def test_blob_roundtrip_numeric_dtypes(dtype):
    rng = np.random.RandomState(0)
    array = (rng.rand(3, 5) * 100).astype(dtype)
    out = _roundtrip(array)
    assert out.dtype == array.dtype
    np.testing.assert_array_equal(out, array)


def test_blob_roundtrip_bfloat16():
    import ml_dtypes

    array = np.arange(12, dtype=np.float32).reshape(4, 3)
    array = array.astype(ml_dtypes.bfloat16)
    out = _roundtrip(array)
    assert out.dtype == array.dtype
    np.testing.assert_array_equal(
        np.asarray(out, np.float32), np.asarray(array, np.float32)
    )


def test_blob_roundtrip_unicode_and_bytes():
    unicode_arr = np.array([["alpha", "β"], ["γγγ", ""]])
    out = _roundtrip(unicode_arr)
    assert out.dtype.kind == "U"
    np.testing.assert_array_equal(out, unicode_arr)

    bytes_arr = np.array([b"ab", b"c", b""], dtype="|S2")
    out = _roundtrip(bytes_arr)
    assert out.dtype == bytes_arr.dtype
    np.testing.assert_array_equal(out, bytes_arr)


def test_blob_roundtrip_object_strings_materialize_as_unicode():
    arr = np.array(["x", "longer"], dtype=object)
    out = _roundtrip(arr)
    assert out.dtype.kind == "U"
    np.testing.assert_array_equal(out, arr.astype(str))


def test_blob_roundtrip_zero_d_and_empty():
    scalar = np.float32(3.5)
    out = _roundtrip(np.asarray(scalar))
    assert out.shape == ()
    assert out == scalar

    empty = np.empty((0, 7), dtype=np.float32)
    out = _roundtrip(empty)
    assert out.shape == (0, 7)
    assert out.dtype == np.float32


# ---------------------------------------------------------------------------
# IndexedSlices: packed ids_blob vs legacy repeated ids

def test_serialize_prefers_packed_ids():
    values = np.arange(6, dtype=np.float32).reshape(3, 2)
    ids = np.array([5, 1, 9], dtype=np.int64)
    slices = tensor_utils.serialize_indexed_slices(values, ids)
    assert slices.ids_blob == ids.astype("<i8").tobytes()
    assert len(slices.ids) == 0
    out_values, out_ids = tensor_utils.deserialize_indexed_slices(slices)
    np.testing.assert_array_equal(out_values, values)
    np.testing.assert_array_equal(out_ids, ids)
    assert out_ids.dtype == np.int64


def test_legacy_repeated_ids_still_deserialize():
    """An old peer writes only the repeated field; a new reader must
    decode it identically (wire-compat acceptance, ISSUE 5)."""
    values = np.ones((2, 3), dtype=np.float32)
    legacy = pb.IndexedSlicesProto()
    tensor_utils.ndarray_to_blob(values, legacy.concat_tensors)
    legacy.ids.extend([7, 2])
    # the wire bytes an old writer would produce
    legacy = pb.IndexedSlicesProto.FromString(legacy.SerializeToString())
    out_values, out_ids = tensor_utils.deserialize_indexed_slices(legacy)
    np.testing.assert_array_equal(out_ids, [7, 2])
    np.testing.assert_array_equal(out_values, values)


def test_packed_wins_when_both_encodings_present():
    slices = pb.IndexedSlicesProto()
    tensor_utils.ndarray_to_blob(
        np.zeros((2, 1), np.float32), slices.concat_tensors
    )
    slices.ids.extend([1, 2])
    slices.ids_blob = tensor_utils.pack_ids(np.array([3, 4], np.int64))
    _, ids = tensor_utils.deserialize_indexed_slices(slices)
    np.testing.assert_array_equal(ids, [3, 4])


def test_pack_unpack_ids_roundtrip_and_empty():
    ids = np.array([0, -1, 2**62], dtype=np.int64)
    request = pb.PullEmbeddingVectorsRequest(
        ids_blob=tensor_utils.pack_ids(ids)
    )
    np.testing.assert_array_equal(tensor_utils.unpack_ids(request), ids)

    empty = pb.PullEmbeddingVectorsRequest()
    out = tensor_utils.unpack_ids(empty)
    assert out.size == 0 and out.dtype == np.int64


# ---------------------------------------------------------------------------
# EDL_WIRE_DTYPE

def test_wire_dtype_unset_and_float32_are_bit_exact(monkeypatch):
    values = np.random.RandomState(3).randn(4, 8).astype(np.float32)
    ids = np.arange(4, dtype=np.int64)

    monkeypatch.delenv(tensor_utils.WIRE_DTYPE_ENV, raising=False)
    assert tensor_utils.wire_dtype() is None
    unset = tensor_utils.serialize_indexed_slices(
        values, ids, wire_dtype=tensor_utils.wire_dtype()
    ).SerializeToString()

    monkeypatch.setenv(tensor_utils.WIRE_DTYPE_ENV, "float32")
    assert tensor_utils.wire_dtype() is None
    explicit = tensor_utils.serialize_indexed_slices(
        values, ids, wire_dtype=tensor_utils.wire_dtype()
    ).SerializeToString()

    assert unset == explicit
    out_values, _ = tensor_utils.deserialize_indexed_slices(
        pb.IndexedSlicesProto.FromString(unset)
    )
    assert out_values.dtype == np.float32
    assert out_values.tobytes() == values.tobytes()  # bit-exact


@pytest.mark.parametrize("knob,expected", [
    ("bfloat16", "bfloat16"), ("bf16", "bfloat16"),
    ("float16", "float16"), ("fp16", "float16"),
])
def test_wire_dtype_downcasts_float32_payloads(monkeypatch, knob, expected):
    monkeypatch.setenv(tensor_utils.WIRE_DTYPE_ENV, knob)
    dtype = tensor_utils.wire_dtype()
    assert dtype is not None and dtype.name == expected
    values = np.random.RandomState(0).randn(5, 4).astype(np.float32)
    slices = tensor_utils.serialize_indexed_slices(
        values, np.arange(5, dtype=np.int64), wire_dtype=dtype
    )
    assert slices.concat_tensors.dtype == expected
    # half the payload bytes of fp32
    assert len(slices.concat_tensors.content) == values.size * 2
    out, _ = tensor_utils.deserialize_indexed_slices(slices)
    np.testing.assert_allclose(
        np.asarray(out, np.float32), values, rtol=2e-2, atol=2e-2
    )


def test_wire_dtype_never_touches_non_float32(monkeypatch):
    monkeypatch.setenv(tensor_utils.WIRE_DTYPE_ENV, "bfloat16")
    dtype = tensor_utils.wire_dtype()
    ints = np.arange(6, dtype=np.int64).reshape(2, 3)
    blob = tensor_utils.ndarray_to_blob(ints, wire_dtype=dtype)
    assert blob.dtype == "int64"
    doubles = np.arange(4, dtype=np.float64)
    blob = tensor_utils.ndarray_to_blob(doubles, wire_dtype=dtype)
    assert blob.dtype == "float64"


def test_wire_dtype_rejects_unknown_value(monkeypatch):
    monkeypatch.setenv(tensor_utils.WIRE_DTYPE_ENV, "int4")
    with pytest.raises(ValueError, match="EDL_WIRE_DTYPE"):
        tensor_utils.wire_dtype()


# ---------------------------------------------------------------------------
# dedup

def test_dedup_matches_scatter_add_on_zipfian_stream():
    rng = np.random.RandomState(0)
    ids = (rng.zipf(1.2, size=4000) % 500).astype(np.int64)
    values = rng.randn(ids.size, 6).astype(np.float32)
    unique, index = np.unique(ids, return_inverse=True)
    ref = np.zeros((unique.size, 6), np.float32)
    np.add.at(ref, index, values)
    summed, out_ids = tensor_utils.deduplicate_indexed_slices(values, ids)
    np.testing.assert_array_equal(out_ids, unique)
    np.testing.assert_allclose(summed, ref, rtol=1e-4, atol=1e-4)


def test_dedup_no_duplicates_returns_sorted_rows():
    ids = np.array([30, 10, 20], dtype=np.int64)
    values = np.array([[3.0], [1.0], [2.0]], dtype=np.float32)
    summed, out_ids = tensor_utils.deduplicate_indexed_slices(values, ids)
    np.testing.assert_array_equal(out_ids, [10, 20, 30])
    np.testing.assert_array_equal(summed, [[1.0], [2.0], [3.0]])


def test_dedup_empty():
    summed, ids = tensor_utils.deduplicate_indexed_slices(
        np.empty((0, 4), np.float32), np.empty((0,), np.int64)
    )
    assert summed.shape[0] == 0 and ids.size == 0
