"""Test model-zoo module: deepfm + a per-worker dense-state dumper.

The N-worker lockstep sparse test asserts dense params are
BIT-IDENTICAL across workers at job end (the shared-model property the
reference bought with per-step get_model RPCs,
/root/reference/elasticdl/python/worker/worker.py:297-336). Each worker
snapshots its dense params after every batch (overwriting), so the last
file per worker reflects its final state; lockstep ends all workers at
the same version, making the files directly comparable.
"""

import os

import numpy as np

from elasticdl_tpu.models.deepfm import (  # noqa: F401
    custom_model,
    dataset_fn,
    eval_metrics_fn,
    loss,
    optimizer,
    sparse_embedding_specs,
)
from elasticdl_tpu.train.callbacks import Callback


class DenseDumper(Callback):
    def on_batch_end(self, step, loss):
        directory = os.environ.get("EDL_DENSE_DUMP_DIR")
        if not directory or self.worker is None:
            return
        state = self.worker.state
        if state is None:
            return
        trainer = self.worker.trainer
        if hasattr(trainer, "local_state"):
            state = trainer.local_state(state)
        import jax

        flat = {}
        for path, leaf in jax.tree_util.tree_flatten_with_path(
            state.params
        )[0]:
            flat[jax.tree_util.keystr(path)] = np.asarray(leaf)
        flat["__step"] = np.asarray(int(state.step))
        # sync-PS retry pressure: the chaos test asserts a relaunched
        # worker doesn't enter a version-rejection storm
        flat["__push_rejections"] = np.asarray(
            int(getattr(trainer, "push_rejections", 0))
        )
        out = os.path.join(
            directory, "worker%s.npz" % self.worker._mc.worker_id
        )
        tmp = out + ".tmp.npz"
        with open(tmp, "wb") as f:
            np.savez(f, **flat)
        os.replace(tmp, out)


def callbacks():
    return [DenseDumper()]
