"""Test model-zoo module: mnist + a table-landing prediction processor
(the reference's ODPS prediction flow, driven by the PREDICTION_ONLY
job e2e in tests/test_eval_predict_jobs.py)."""

from elasticdl_tpu.data.table_writer import (
    InMemoryWritableTable,
    TablePredictionOutputsProcessor,
)
from elasticdl_tpu.models.mnist import (  # noqa: F401
    custom_model,
    dataset_fn,
    eval_metrics_fn,
    loss,
    optimizer,
)

# module-level sink: the in-process e2e reads it back after the job
SINK = InMemoryWritableTable()


class PredictionOutputsProcessor(TablePredictionOutputsProcessor):
    def make_sink(self):
        return SINK
