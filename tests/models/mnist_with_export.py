"""Test model-zoo module: mnist + SavedModelExporter callback."""

from elasticdl_tpu.models.mnist import (  # noqa: F401
    custom_model,
    dataset_fn,
    eval_metrics_fn,
    loss,
    optimizer,
)
from elasticdl_tpu.train.callbacks import SavedModelExporter


def callbacks():
    return [SavedModelExporter()]
