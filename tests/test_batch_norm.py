"""TpuBatchNorm (ops/batch_norm.py) must match flax nn.BatchNorm
numerics exactly in f32: forward (train + eval), gradients, and the
running-statistics update — it is a compiler-friendly reformulation,
not a different normalization."""

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from elasticdl_tpu.ops.batch_norm import TpuBatchNorm


def _flax_bn(training):
    return nn.BatchNorm(
        use_running_average=not training, momentum=0.9, epsilon=1e-5,
        dtype=None,
    )


def _tpu_bn(training, **kw):
    return TpuBatchNorm(
        use_running_average=not training, momentum=0.9, epsilon=1e-5, **kw
    )


@pytest.fixture
def x():
    rng = np.random.default_rng(0)
    return jnp.asarray(
        rng.normal(loc=0.7, scale=2.0, size=(8, 5, 5, 6)), jnp.float32
    )


def test_train_forward_and_stats_match_flax(x):
    ref, ours = _flax_bn(True), _tpu_bn(True)
    vref = ref.init(jax.random.PRNGKey(0), x)
    vours = ours.init(jax.random.PRNGKey(0), x)
    yref, mref = ref.apply(vref, x, mutable=["batch_stats"])
    yours, mours = ours.apply(vours, x, mutable=["batch_stats"])
    np.testing.assert_allclose(yours, yref, rtol=2e-5, atol=2e-5)
    for key in ("mean", "var"):
        np.testing.assert_allclose(
            jax.tree_util.tree_leaves(mours["batch_stats"])[
                0 if key == "mean" else 1
            ],
            jax.tree_util.tree_leaves(mref["batch_stats"])[
                0 if key == "mean" else 1
            ],
            rtol=2e-5, atol=2e-6,
        )


def test_eval_forward_matches_flax(x):
    ref, ours = _flax_bn(False), _tpu_bn(False)
    variables = ref.init(jax.random.PRNGKey(0), x)
    # push non-trivial running stats + affine params into both
    stats = {
        "mean": jnp.linspace(-1.0, 1.0, 6),
        "var": jnp.linspace(0.5, 2.0, 6),
    }
    params = {
        "scale": jnp.linspace(0.5, 1.5, 6),
        "bias": jnp.linspace(-0.2, 0.2, 6),
    }
    variables = {"params": params, "batch_stats": stats}
    yref = ref.apply(variables, x)
    yours = ours.apply(variables, x)
    np.testing.assert_allclose(yours, yref, rtol=2e-5, atol=2e-5)


def test_gradients_match_flax(x):
    ref, ours = _flax_bn(True), _tpu_bn(True)
    variables = ref.init(jax.random.PRNGKey(0), x)

    def loss(mod):
        def fn(params, x):
            y, _ = mod.apply(
                {"params": params,
                 "batch_stats": variables["batch_stats"]},
                x, mutable=["batch_stats"],
            )
            return jnp.sum(jnp.tanh(y))
        return fn

    gref_p, gref_x = jax.grad(loss(ref), argnums=(0, 1))(
        variables["params"], x
    )
    gours_p, gours_x = jax.grad(loss(ours), argnums=(0, 1))(
        variables["params"], x
    )
    np.testing.assert_allclose(gours_x, gref_x, rtol=1e-4, atol=1e-4)
    for k in gref_p:
        np.testing.assert_allclose(
            gours_p[k], gref_p[k], rtol=1e-4, atol=1e-4
        )


def test_scale_init_passthrough(x):
    bn = _tpu_bn(True, scale_init=nn.initializers.zeros_init())
    variables = bn.init(jax.random.PRNGKey(0), x)
    np.testing.assert_array_equal(
        variables["params"]["scale"], np.zeros(6)
    )
    y, _ = bn.apply(x=x, variables=variables, mutable=["batch_stats"])
    # zero scale -> output is just the bias (zeros)
    np.testing.assert_allclose(y, np.zeros_like(x), atol=1e-6)


def test_stats_samples_subsampling(x):
    """stats_samples=k: statistics come from the first k rows only,
    every row is normalized, and running stats track the k-row stats."""
    bn = _tpu_bn(True, stats_samples=4)
    variables = bn.init(jax.random.PRNGKey(0), x)
    y, mutated = bn.apply(x=x, variables=variables, mutable=["batch_stats"])
    xs = np.asarray(x[:4], np.float64)
    mean = xs.mean(axis=(0, 1, 2))
    var = (xs ** 2).mean(axis=(0, 1, 2)) - mean ** 2
    expect = (np.asarray(x) - mean) / np.sqrt(var + 1e-5)
    np.testing.assert_allclose(y, expect, rtol=2e-4, atol=2e-4)
    np.testing.assert_allclose(
        mutated["batch_stats"]["mean"], 0.1 * mean, rtol=2e-5, atol=1e-6
    )


def test_bf16_stream_keeps_dtype(x):
    bn = _tpu_bn(True)
    xb = x.astype(jnp.bfloat16)
    variables = bn.init(jax.random.PRNGKey(0), xb)
    y, _ = bn.apply(x=xb, variables=variables, mutable=["batch_stats"])
    assert y.dtype == jnp.bfloat16
    # params/stats stay f32
    assert variables["params"]["scale"].dtype == jnp.float32
    assert variables["batch_stats"]["mean"].dtype == jnp.float32
