"""Timing accumulation + profiler hooks (reference timing_utils.py:17-48)."""

import time

from elasticdl_tpu.common.timing_utils import Timing, trace


def test_disabled_by_default_records_nothing(monkeypatch):
    monkeypatch.delenv("EDL_TIMING", raising=False)
    timing = Timing()
    with timing.timeit("phase"):
        pass
    assert timing.summary() == {}


def test_accumulates_per_phase():
    timing = Timing(enabled=True)
    for _ in range(3):
        with timing.timeit("a"):
            time.sleep(0.01)
    with timing.timeit("b"):
        pass
    summary = timing.summary()
    assert summary["a"]["count"] == 3
    assert summary["a"]["seconds"] >= 0.03
    assert summary["b"]["count"] == 1


def test_report_resets():
    timing = Timing(enabled=True)
    with timing.timeit("x"):
        pass
    timing.report("task done")
    assert timing.summary() == {}


def test_sync_on_jax_result():
    import jax.numpy as jnp

    timing = Timing(enabled=True)
    start = timing.start()
    result = jnp.ones((8, 8)) @ jnp.ones((8, 8))
    timing.end_record_sync("matmul", start, result)
    assert timing.summary()["matmul"]["count"] == 1


def test_trace_noop_without_env(monkeypatch):
    monkeypatch.delenv("EDL_PROFILE_DIR", raising=False)
    with trace("region"):
        pass  # must not require jax.profiler setup


def test_trace_writes_profile(tmp_path, monkeypatch):
    import glob

    import jax.numpy as jnp

    monkeypatch.setenv("EDL_PROFILE_DIR", str(tmp_path))
    with trace("region"):
        (jnp.ones((4, 4)) @ jnp.ones((4, 4))).block_until_ready()
    assert glob.glob(str(tmp_path / "region" / "**" / "*.xplane.pb"),
                     recursive=True)


def test_sparse_trainer_phases_recorded(monkeypatch, tmp_path):
    """SparseTrainer records sparse_pull / batch_process / sparse_push
    (the reference's get_model / batch / report_gradient phases)."""
    monkeypatch.setenv("EDL_TIMING", "1")
    import flax.linen as nn
    import jax.numpy as jnp
    import numpy as np

    from elasticdl_tpu.data.pipeline import MASK_KEY
    from elasticdl_tpu.ps.local_client import LocalPSClient
    from elasticdl_tpu.train.optimizers import create_optimizer
    from elasticdl_tpu.train.sparse import (
        SparseEmbeddingSpec,
        SparseTrainer,
        embedding_lookup,
    )

    class _Model(nn.Module):
        @nn.compact
        def __call__(self, features, training: bool = False):
            return nn.Dense(1)(
                embedding_lookup(features, "e", combiner="sum")
            )[:, 0]

    trainer = SparseTrainer(
        _Model(),
        lambda labels, logits: (logits - labels) ** 2,
        create_optimizer("SGD", learning_rate=0.1),
        [SparseEmbeddingSpec("e", 4, feature_key="ids")],
        LocalPSClient(opt_type="sgd", lr=0.1),
        compute_dtype="float32",
    )
    batch = {
        "features": {"ids": np.arange(8).reshape(8, 1)},
        "labels": np.ones(8, np.float32),
        MASK_KEY: np.ones(8, dtype=bool),
    }
    trainer.train_step(None, batch)
    summary = trainer.timing.summary()
    assert {"sparse_pull", "batch_process", "sparse_push"} <= set(summary)

