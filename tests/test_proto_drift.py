"""proto <-> pb2 drift check (PR 16 satellite).

The repo regenerates ``elasticdl_tpu_pb2.py`` without protoc by
patching the serialized FileDescriptorProto programmatically (see the
header of the generated file), which means the human-edited
``elasticdl_tpu.proto`` text and the descriptors Python actually loads
can silently diverge: a field renumbered in one but not the other is a
wire-corruption bug that no unit test of either side catches.

This test parses the .proto text directly (messages, fields, numbers,
labels, scalar/message/enum types, map entries, enum values) and
compares it, exhaustively in both directions, against the descriptors
``elasticdl_tpu_pb2`` registered in the default pool.
"""

import os
import re

from google.protobuf import descriptor as _descriptor

from elasticdl_tpu.proto import elasticdl_tpu_pb2 as pb

PROTO_PATH = os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    "elasticdl_tpu", "proto", "elasticdl_tpu.proto",
)

_FIELD_RE = re.compile(
    r"^(?:(repeated|optional)\s+)?"
    r"(map\s*<\s*(\w+)\s*,\s*([\w.]+)\s*>|[\w.]+)\s+"
    r"(\w+)\s*=\s*(\d+)\s*;"
)

_SCALAR_TYPES = {
    "double": _descriptor.FieldDescriptor.TYPE_DOUBLE,
    "float": _descriptor.FieldDescriptor.TYPE_FLOAT,
    "int32": _descriptor.FieldDescriptor.TYPE_INT32,
    "int64": _descriptor.FieldDescriptor.TYPE_INT64,
    "uint32": _descriptor.FieldDescriptor.TYPE_UINT32,
    "uint64": _descriptor.FieldDescriptor.TYPE_UINT64,
    "sint32": _descriptor.FieldDescriptor.TYPE_SINT32,
    "sint64": _descriptor.FieldDescriptor.TYPE_SINT64,
    "fixed32": _descriptor.FieldDescriptor.TYPE_FIXED32,
    "fixed64": _descriptor.FieldDescriptor.TYPE_FIXED64,
    "bool": _descriptor.FieldDescriptor.TYPE_BOOL,
    "string": _descriptor.FieldDescriptor.TYPE_STRING,
    "bytes": _descriptor.FieldDescriptor.TYPE_BYTES,
}


def _strip_comments(text):
    return re.sub(r"//[^\n]*", "", text)


def parse_proto(path):
    """Minimal proto3 parser for this file's feature set: top-level and
    nested messages, one enum, scalar/message fields, repeated,
    proto3 optional, and map<k, v>. Returns (messages, enums) where
    messages maps dotted message name -> {field name: spec dict}."""
    with open(path, "r", encoding="utf-8") as f:
        text = _strip_comments(f.read())
    messages, enums = {}, {}
    stack = []  # (kind, name) of open message/enum blocks

    for raw in text.splitlines():
        line = raw.strip()
        if not line:
            continue
        m = re.match(r"^message\s+(\w+)\s*\{(\s*\})?", line)
        if m:
            name = ".".join(
                [n for k, n in stack if k == "message"] + [m.group(1)]
            )
            messages[name] = {}
            if m.group(2) is None:  # "message Empty {}" opens and closes
                stack.append(("message", m.group(1)))
            continue
        m = re.match(r"^enum\s+(\w+)\s*\{", line)
        if m:
            stack.append(("enum", m.group(1)))
            enums[m.group(1)] = {}
            continue
        if line.startswith("}"):
            if stack:
                stack.pop()
            continue
        if not stack:
            continue
        if stack[-1][0] == "enum":
            m = re.match(r"^(\w+)\s*=\s*(\d+)\s*;", line)
            if m:
                enums[stack[-1][1]][m.group(1)] = int(m.group(2))
            continue
        m = _FIELD_RE.match(line)
        if not m:
            continue
        label, type_text, map_key, map_value, fname, number = m.groups()
        current = ".".join(n for k, n in stack if k == "message")
        messages[current][fname] = {
            "number": int(number),
            "label": label,
            "type": type_text if map_key is None else "map",
            "map_key": map_key,
            "map_value": map_value,
        }
    assert not stack, "unbalanced braces parsing %s" % path
    return messages, enums


def _descriptor_messages():
    """dotted name -> Descriptor for every non-map-entry message."""
    out = {}

    def rec(desc, prefix):
        name = prefix + desc.name
        out[name] = desc
        for nested in desc.nested_types:
            if nested.GetOptions().map_entry:
                continue
            rec(nested, name + ".")

    for desc in pb.DESCRIPTOR.message_types_by_name.values():
        rec(desc, "")
    return out


def _check_field(msg_name, fname, spec, field):
    where = "%s.%s" % (msg_name, fname)
    assert field.number == spec["number"], (
        "%s: .proto says field number %d, pb2 descriptor says %d — "
        "renumbering only one side corrupts the wire" % (
            where, spec["number"], field.number
        )
    )
    if spec["type"] == "map":
        assert field.message_type is not None and (
            field.message_type.GetOptions().map_entry
        ), "%s: .proto declares a map, pb2 field is not a map entry" % where
        key_f = field.message_type.fields_by_name["key"]
        value_f = field.message_type.fields_by_name["value"]
        assert key_f.type == _SCALAR_TYPES[spec["map_key"]], (
            "%s: map key type drift" % where
        )
        if spec["map_value"] in _SCALAR_TYPES:
            assert value_f.type == _SCALAR_TYPES[spec["map_value"]], (
                "%s: map value type drift" % where
            )
        else:
            assert value_f.message_type is not None, (
                "%s: map value should be message %s"
                % (where, spec["map_value"])
            )
            assert value_f.message_type.name == spec["map_value"].split(
                "."
            )[-1], "%s: map value message drift" % where
        return
    expected_repeated = spec["label"] == "repeated"
    if hasattr(field, "is_repeated"):  # protobuf >= 5 spelling
        attr = field.is_repeated
        is_repeated = attr() if callable(attr) else attr
    else:
        is_repeated = field.label == field.LABEL_REPEATED
    assert is_repeated == expected_repeated, (
        "%s: repeated/singular drift" % where
    )
    if spec["label"] == "optional":
        assert field.has_presence, (
            "%s: .proto says proto3 optional but pb2 field has no "
            "presence tracking" % where
        )
    if spec["type"] in _SCALAR_TYPES:
        assert field.type == _SCALAR_TYPES[spec["type"]], (
            "%s: scalar type drift (.proto %s, pb2 type enum %d)"
            % (where, spec["type"], field.type)
        )
    elif field.type == field.TYPE_ENUM:
        assert field.enum_type.name == spec["type"].split(".")[-1], (
            "%s: enum type drift" % where
        )
    else:
        assert field.type == field.TYPE_MESSAGE, (
            "%s: .proto says message %s, pb2 disagrees"
            % (where, spec["type"])
        )
        assert field.message_type.name == spec["type"].split(".")[-1], (
            "%s: message type drift (.proto %s, pb2 %s)"
            % (where, spec["type"], field.message_type.name)
        )


def test_pb2_descriptors_match_proto_text():
    messages, enums = parse_proto(PROTO_PATH)
    assert messages, "parsed no messages from %s" % PROTO_PATH
    desc_messages = _descriptor_messages()

    assert set(messages) == set(desc_messages), (
        "message set drift:\n  only in .proto: %s\n  only in pb2: %s" % (
            sorted(set(messages) - set(desc_messages)),
            sorted(set(desc_messages) - set(messages)),
        )
    )
    for msg_name, fields in sorted(messages.items()):
        desc = desc_messages[msg_name]
        desc_fields = dict(desc.fields_by_name)
        assert set(fields) == set(desc_fields), (
            "%s field-set drift:\n  only in .proto: %s\n  only in pb2: %s"
            % (
                msg_name,
                sorted(set(fields) - set(desc_fields)),
                sorted(set(desc_fields) - set(fields)),
            )
        )
        numbers = [s["number"] for s in fields.values()]
        assert len(numbers) == len(set(numbers)), (
            "%s reuses a field number in the .proto text" % msg_name
        )
        for fname, spec in sorted(fields.items()):
            _check_field(msg_name, fname, spec, desc_fields[fname])


def test_pb2_enums_match_proto_text():
    _messages, enums = parse_proto(PROTO_PATH)
    desc_enums = dict(pb.DESCRIPTOR.enum_types_by_name)
    assert set(enums) == set(desc_enums), "enum set drift"
    for name, values in enums.items():
        desc_values = {
            v.name: v.number for v in desc_enums[name].values
        }
        assert values == desc_values, (
            "enum %s drift: .proto %s, pb2 %s" % (name, values, desc_values)
        )


def test_pb2_file_metadata_matches():
    assert pb.DESCRIPTOR.name == "elasticdl_tpu/proto/elasticdl_tpu.proto"
    assert pb.DESCRIPTOR.package == "elasticdl_tpu"
