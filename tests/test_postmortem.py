"""Postmortem tooling (ISSUE 3): journal/dump merge + ordering +
correlation threading in scripts/postmortem.py, and first-ever coverage
for scripts/trace_summary.py (the per-HLO-category breakdown the perf
docs are generated from)."""

import gzip
import json
import os
import sys

import pytest

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", "scripts"))
import postmortem  # noqa: E402
import trace_summary  # noqa: E402


# ---------------------------------------------------------------------------
# helpers


def write_journal(events_dir, name, records):
    path = os.path.join(str(events_dir), name)
    with open(path, "w", encoding="utf-8") as f:
        for record in records:
            f.write(json.dumps(record) + "\n")
    return path


def ev(ts, role, event, seq=None, **fields):
    record = {"ts": ts, "role": role, "pid": 1, "event": event}
    if seq is not None:
        record["seq"] = seq
    record.update(fields)
    return record


# ---------------------------------------------------------------------------
# postmortem: parsing, merge, ordering


def test_torn_tail_line_is_skipped_not_fatal(tmp_path):
    path = write_journal(
        tmp_path, "worker-1-10.events.ndjson",
        [ev(1.0, "worker-1", "role_start")],
    )
    with open(path, "a", encoding="utf-8") as f:
        f.write('{"ts": 2.0, "role": "worker-1", "eve')  # SIGKILL tear
    events = postmortem.load_journals(str(tmp_path))
    assert len(events) == 1
    assert events[0]["event"] == "role_start"
    assert events[0]["source"] == "worker-1-10.events.ndjson"


def test_timeline_is_time_ordered_across_roles(tmp_path):
    write_journal(
        tmp_path, "master-1.events.ndjson",
        [ev(5.0, "master", "task_report", task=1),
         ev(1.0, "master", "task_dispatch", task=1, worker=0)],
    )
    write_journal(
        tmp_path, "worker-0-2.events.ndjson",
        [ev(3.0, "worker-0", "checkpoint_saved", version=4)],
    )
    report = postmortem.postmortem(str(tmp_path))
    kinds = [e["event"] for e in report["timeline"]]
    assert kinds == ["task_dispatch", "checkpoint_saved", "task_report"]


def test_dump_events_dedupe_against_journal_by_seq(tmp_path):
    """A crash dump re-records the journaled tail; the merged timeline
    must hold one copy of each (role, pid, seq)."""
    journaled = [
        ev(1.0, "worker-3", "role_start", seq=1, worker=3),
        ev(2.0, "worker-3", "task_dispatch", seq=2, task=7, worker=3),
    ]
    write_journal(tmp_path, "worker-3-9.events.ndjson", journaled)
    dump = {
        "role": "worker-3", "pid": 1, "reason": "sigterm",
        "dumped_at": 2.5,
        # the dump holds the same two events PLUS one that never made
        # the journal (emitted after the last flush... write-through
        # normally prevents this, but a dump must still contribute it)
        "events": journaled + [
            ev(2.4, "worker-3", "crash_dump", seq=3, worker=3)
        ],
    }
    with open(
        os.path.join(str(tmp_path), "worker-3-9.dump.json"), "w"
    ) as f:
        json.dump(dump, f)
    report = postmortem.postmortem(str(tmp_path))
    assert len(report["timeline"]) == 3
    assert [e["seq"] for e in report["timeline"]] == [1, 2, 3]
    assert report["dumps"][0]["reason"] == "sigterm"


def test_summary_threads_by_correlation_ids(tmp_path):
    """The acceptance story: worker-3 relaunched, its requeued task,
    the master's alert — one threaded summary."""
    write_journal(
        tmp_path, "master-1.events.ndjson",
        [
            ev(1.0, "master", "worker_register", worker=3, epoch=101),
            ev(2.0, "master", "task_dispatch", task=41, worker=3),
            ev(9.0, "master", "worker_register", worker=3, epoch=102,
               relaunch=True),
            ev(9.1, "master", "task_requeue", task=41, worker=3,
               retries=0, counted=False),
            ev(12.0, "master", "alert_raised", alert="dead_air",
               target="3"),
            ev(15.0, "master", "worker_presumed_dead", worker=3),
        ],
    )
    report = postmortem.postmortem(str(tmp_path))
    worker3 = report["summary"]["workers"]["3"]
    assert worker3["registrations"] == [101, 102]
    assert worker3["requeued_tasks"] == [41]
    assert worker3["alerts"] == ["dead_air"]
    assert worker3["presumed_dead"] == 1
    text = postmortem.render_text(
        report["timeline"], report["summary"], report["dumps"],
        report["alert_counters"],
    )
    assert "worker_register" in text and "dead_air" in text


def test_metrics_snapshot_alert_counters_fold_in(tmp_path):
    write_journal(
        tmp_path, "master-1.events.ndjson",
        [ev(1.0, "master", "role_start")],
    )
    with open(
        os.path.join(str(tmp_path), "master.metrics.txt"), "w"
    ) as f:
        f.write(
            "# TYPE edl_master_alerts_total counter\n"
            'edl_master_alerts_total{alert="dead_air"} 2\n'
            "edl_up 1\n"
        )
    report = postmortem.postmortem(str(tmp_path))
    assert report["alert_counters"] == {
        'edl_master_alerts_total{alert="dead_air"}': 2.0
    }


def test_cli_writes_json_and_exits_by_content(tmp_path):
    write_journal(
        tmp_path, "master-1.events.ndjson",
        [ev(1.0, "master", "role_start")],
    )
    out = str(tmp_path / "incident.json")
    assert postmortem.main([str(tmp_path), "-o", out]) == 0
    with open(out) as f:
        report = json.load(f)
    assert report["timeline"][0]["event"] == "role_start"
    empty = tmp_path / "empty"
    empty.mkdir()
    assert postmortem.main([str(empty)]) == 1


# ---------------------------------------------------------------------------
# trace_summary (previously zero coverage)


def _write_profiler_trace(trace_dir, stamp, events):
    profile_dir = os.path.join(
        str(trace_dir), "plugins", "profile", stamp
    )
    os.makedirs(profile_dir, exist_ok=True)
    path = os.path.join(profile_dir, "host.trace.json.gz")
    with gzip.open(path, "wt", encoding="utf-8") as f:
        json.dump({"traceEvents": events}, f)
    return path


_TPU_META = {
    "ph": "M", "name": "process_name", "pid": 7,
    "args": {"name": "/device:TPU:0"},
}


def _hlo(name, dur, category, bytes_accessed=0, flops=0):
    return {
        "ph": "X", "pid": 7, "tid": 1, "ts": 0, "dur": dur,
        "name": name,
        "args": {
            "hlo_category": category,
            "bytes_accessed": str(bytes_accessed),
            "flops": str(flops),
        },
    }


def test_latest_trace_path_picks_newest_stamp(tmp_path):
    _write_profiler_trace(tmp_path, "2020_01_01", [_TPU_META])
    newest = _write_profiler_trace(tmp_path, "2024_12_31", [_TPU_META])
    assert trace_summary.latest_trace_path(str(tmp_path)) == newest


def test_summarize_trace_breaks_down_by_hlo_category(tmp_path, capsys):
    events = [
        _TPU_META,
        # a host process that must be ignored
        {"ph": "M", "name": "process_name", "pid": 1,
         "args": {"name": "python"}},
        {"ph": "X", "pid": 1, "tid": 1, "ts": 0, "dur": 999,
         "name": "host_op", "args": {"hlo_category": "host"}},
        # while-wrapped ops are excluded (double counting)
        _hlo("while_loop_body", 500, "loop"),
        _hlo("fusion.1", 3000, "convolution",
             bytes_accessed=3_000_000, flops=9_000_000),
        _hlo("fusion.2", 1000, "all-reduce", bytes_accessed=1_000_000),
    ]
    path = _write_profiler_trace(tmp_path, "2024_01_01", events)
    returned = trace_summary.summarize_trace(str(tmp_path), steps=2)
    assert returned == path
    out = capsys.readouterr().out
    assert "convolution" in out and "all-reduce" in out
    assert "host" not in out.split("trace at:")[0].splitlines()[0]
    # device total = 3000+1000 us -> 4.0 ms over 2 steps
    assert "device time: 4.0 ms / 2 steps" in out
    # convolution is 75% of device time
    assert " 75.0%" in out


def test_summarize_trace_while_prefixed_ops_excluded(tmp_path, capsys):
    events = [
        _TPU_META,
        _hlo("while", 10_000, "loop"),
        _hlo("dot.3", 1000, "matmul"),
    ]
    _write_profiler_trace(tmp_path, "2024_02_02", events)
    trace_summary.summarize_trace(str(tmp_path), steps=1)
    out = capsys.readouterr().out
    # the while wrapper's 10ms must not inflate the total
    assert "device time: 1.0 ms / 1 steps" in out


def test_capture_trace_drives_profiler_and_summarizes(tmp_path):
    """capture_trace must start/stop the JAX profiler around run_once
    and summarize what landed. Exercised on CPU: the trace still
    contains XLA ops with hlo_category args."""
    pytest.importorskip("jax")
    import jax
    import jax.numpy as jnp

    @jax.jit
    def step(x):
        return (x @ x).sum()

    x = jnp.ones((64, 64))

    def run_once():
        float(step(x))  # fence so device work lands inside the trace

    try:
        trace_summary.capture_trace(run_once, str(tmp_path), steps=1)
    except IndexError:
        # some CPU builds emit no device track at all — the capture
        # protocol itself (start/stop/summarize path) still ran; the
        # category math is covered by the synthetic-trace tests above
        pytest.skip("jax CPU profiler emitted no categorized trace")
