"""LocalExecutor with a sparse model: in-process store, no gRPC."""

from elasticdl_tpu.train.local_executor import LocalExecutor
from tests.test_utils import create_ctr_recordio


def test_deepfm_local_executor(tmp_path):
    train_dir = tmp_path / "train"
    valid_dir = tmp_path / "valid"
    train_dir.mkdir()
    valid_dir.mkdir()
    create_ctr_recordio(str(train_dir / "f0.rec"), num_records=512, seed=0)
    create_ctr_recordio(str(valid_dir / "f0.rec"), num_records=128, seed=1)
    executor = LocalExecutor(
        "elasticdl_tpu.models.deepfm",
        training_data=str(train_dir),
        validation_data=str(valid_dir),
        minibatch_size=64,
        num_epochs=3,
    )
    losses = executor.train()
    assert losses[-1] < losses[0]
    summary = executor.evaluate()
    assert summary["auc"] > 0.8
