"""Racing sync-PS worker driver (subprocess side of
tests/test_sync_ps.py::test_two_live_workers_race_the_sync_ps).

Two modes, both free-running against ONE live sync-mode PS
(grads_to_wait=2, tolerance 0) — the reference's multi-worker sync
scenario (/root/reference/elasticdl/python/ps/servicer.py:166-236) with
REAL racing processes:

- ``constant``: pushes grad 1.0 for id 0 every step through PSClient,
  retrying version rejections by re-tagging — exact-arithmetic probe
  (the test asserts the final row value accounts for EVERY push: no
  lost updates).
- ``trainer``: a full single-device SparseTrainer on DeepFM — the
  worker-path rejection/retry loop (train/sparse.py train_step) under
  real concurrency.

Prints ONE JSON line: {"accepted": N, "rejections": N, "version": N}.
"""

import argparse
import json
import os
import sys

# CPU backend, forced both ways (sitecustomize pins the axon platform)
os.environ["JAX_PLATFORMS"] = "cpu"


def run_constant(ps_addr, steps):
    import numpy as np

    from elasticdl_tpu.worker.ps_client import PSClient

    client = PSClient([ps_addr])
    client.push_embedding_table_infos([("race", 4, "0.0")])
    version = 0
    rejections = 0
    accepted = 0
    grad = np.ones((1, 4), dtype=np.float32)
    ids = np.array([0], dtype=np.int64)
    for _ in range(steps):
        while True:
            ok, response_version, _ = client.push_gradients(
                {"race": (grad, ids)}, model_version=version
            )
            if ok:
                accepted += 1
                version = response_version
                break
            rejections += 1
            version = response_version
    return accepted, rejections, version


def run_trainer(ps_addr, steps, seed):
    import numpy as np

    from elasticdl_tpu.models import deepfm
    from elasticdl_tpu.train.sparse import SparseTrainer
    from elasticdl_tpu.worker.ps_client import PSClient

    trainer = SparseTrainer(
        model=deepfm.custom_model(),
        loss_fn=deepfm.loss,
        optimizer=deepfm.optimizer(),
        specs=deepfm.sparse_embedding_specs(batch_size=32),
        ps_client=PSClient([ps_addr]),
        seed=0,
    )
    rng = np.random.RandomState(seed)
    state = None
    for _ in range(steps):
        batch = {
            "features": {
                "ids": (
                    rng.zipf(1.3, size=(32, deepfm.NUM_FIELDS)) % 1000
                ).astype(np.int64)
            },
            "labels": rng.randint(0, 2, 32).astype(np.float32),
            "_mask": np.ones(32, np.float32),
        }
        state, loss = trainer.train_step(state, batch)
    assert np.isfinite(float(loss))
    return steps, trainer.push_rejections, trainer._version


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("--mode", choices=["constant", "trainer"],
                        required=True)
    parser.add_argument("--ps_addr", required=True)
    parser.add_argument("--steps", type=int, default=30)
    parser.add_argument("--seed", type=int, default=0)
    args = parser.parse_args()
    import jax

    jax.config.update("jax_platforms", "cpu")
    if args.mode == "constant":
        accepted, rejections, version = run_constant(
            args.ps_addr, args.steps
        )
    else:
        accepted, rejections, version = run_trainer(
            args.ps_addr, args.steps, args.seed
        )
    print(json.dumps({
        "accepted": int(accepted),
        "rejections": int(rejections),
        "version": int(version),
    }))
    return 0


if __name__ == "__main__":
    sys.exit(main())
