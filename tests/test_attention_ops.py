"""Flash kernel vs XLA oracle; ring/ulysses SP vs full attention.

Kernel runs in Pallas interpret mode on CPU (compiled on real TPU); the
SP schedules run on the 8-virtual-device mesh from conftest.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from elasticdl_tpu.ops.attention import xla_attention
from elasticdl_tpu.ops.flash_attention import flash_attention
from elasticdl_tpu.ops.ring_attention import (
    ring_attention,
    ulysses_attention,
)
from elasticdl_tpu.parallel.mesh import MeshConfig, build_mesh


def _inputs(batch=2, heads=2, seq=256, dim=64, seed=0):
    rng = np.random.RandomState(seed)
    shape = (batch, heads, seq, dim)
    mk = lambda s: jnp.asarray(rng.normal(size=shape, scale=0.5), jnp.float32)
    return mk(0), mk(1), mk(2)


@pytest.mark.parametrize("causal", [False, True])
def test_flash_forward_matches_xla(causal):
    q, k, v = _inputs()
    expected = xla_attention(q, k, v, causal=causal)
    got = flash_attention(q, k, v, causal=causal, interpret=True)
    np.testing.assert_allclose(got, expected, atol=2e-5, rtol=2e-5)


@pytest.mark.parametrize("causal", [False, True])
def test_flash_grads_match_xla(causal):
    q, k, v = _inputs(seq=128)

    def loss_ref(q, k, v):
        out = xla_attention(q, k, v, causal=causal)
        return jnp.sum(out * jnp.cos(out))

    def loss_flash(q, k, v):
        out = flash_attention(
            q, k, v, causal=causal, block_q=64, block_k=64, interpret=True
        )
        return jnp.sum(out * jnp.cos(out))

    g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    g_flash = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g_flash, g_ref):
        np.testing.assert_allclose(a, b, atol=3e-4, rtol=3e-4)


@pytest.mark.parametrize("causal", [False, True])
def test_ring_attention_matches_full(causal):
    mesh = build_mesh(MeshConfig(dp=2, sp=4))
    q, k, v = _inputs(seq=64, dim=16)
    expected = xla_attention(q, k, v, causal=causal)

    ring = jax.jit(
        lambda q, k, v: ring_attention(q, k, v, mesh, causal=causal)
    )
    np.testing.assert_allclose(
        ring(q, k, v), expected, atol=2e-5, rtol=2e-5
    )


def test_ring_attention_grads_match_full():
    mesh = build_mesh(MeshConfig(dp=2, sp=4))
    q, k, v = _inputs(seq=32, dim=8)

    def loss_full(q, k, v):
        return jnp.sum(jnp.square(xla_attention(q, k, v, causal=True)))

    def loss_ring(q, k, v):
        return jnp.sum(
            jnp.square(ring_attention(q, k, v, mesh, causal=True))
        )

    g_full = jax.grad(loss_full, argnums=(0, 1, 2))(q, k, v)
    g_ring = jax.jit(jax.grad(loss_ring, argnums=(0, 1, 2)))(q, k, v)
    for a, b in zip(g_ring, g_full):
        np.testing.assert_allclose(a, b, atol=2e-5, rtol=2e-5)


@pytest.mark.parametrize("causal", [False, True])
def test_ulysses_attention_matches_full(causal):
    mesh = build_mesh(MeshConfig(dp=2, sp=4))
    q, k, v = _inputs(heads=4, seq=64, dim=16)
    expected = xla_attention(q, k, v, causal=causal)

    uly = jax.jit(
        lambda q, k, v: ulysses_attention(q, k, v, mesh, causal=causal)
    )
    np.testing.assert_allclose(
        uly(q, k, v), expected, atol=2e-5, rtol=2e-5
    )


def test_ring_attention_sp1_falls_back():
    mesh = build_mesh(MeshConfig(dp=8, sp=1))
    q, k, v = _inputs(seq=32, dim=8)
    expected = xla_attention(q, k, v, causal=True)
    got = ring_attention(q, k, v, mesh, causal=True)
    np.testing.assert_allclose(got, expected, atol=2e-5, rtol=2e-5)


@pytest.mark.parametrize("causal", [False, True])
def test_flash_ring_matches_full(causal):
    """Ring fold with the Pallas kernel as block compute (VERDICT.md
    round-1 item #6): per-device work is true flash attention, output
    matches full single-device attention."""
    mesh = build_mesh(MeshConfig(dp=2, sp=4))
    q, k, v = _inputs(seq=256, dim=32)
    expected = xla_attention(q, k, v, causal=causal)
    got = jax.jit(
        lambda q, k, v: ring_attention(
            q, k, v, mesh, causal=causal,
            block_impl="flash", interpret=True,
        )
    )(q, k, v)
    np.testing.assert_allclose(got, expected, atol=3e-5, rtol=3e-5)


@pytest.mark.parametrize("causal", [False, True])
def test_flash_ring_grads_match_full(causal):
    mesh = build_mesh(MeshConfig(dp=2, sp=4))
    q, k, v = _inputs(seq=256, dim=32, seed=5)

    def loss_ref(q, k, v):
        out = xla_attention(q, k, v, causal=causal)
        return jnp.sum(out * jnp.cos(out))

    def loss_ring(q, k, v):
        out = ring_attention(
            q, k, v, mesh, causal=causal,
            block_impl="flash", interpret=True,
        )
        return jnp.sum(out * jnp.cos(out))

    g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    g_ring = jax.jit(jax.grad(loss_ring, argnums=(0, 1, 2)))(q, k, v)
    for a, b in zip(g_ring, g_ref):
        np.testing.assert_allclose(a, b, atol=5e-4, rtol=5e-4)


def test_flash_ring_agrees_with_einsum_ring():
    """The two block computes are different executions of the same
    math: outputs must agree tightly."""
    mesh = build_mesh(MeshConfig(dp=2, sp=4))
    q, k, v = _inputs(seq=256, dim=32, seed=9)
    a = jax.jit(
        lambda q, k, v: ring_attention(
            q, k, v, mesh, causal=True, block_impl="flash", interpret=True
        )
    )(q, k, v)
    b = jax.jit(
        lambda q, k, v: ring_attention(
            q, k, v, mesh, causal=True, block_impl="einsum"
        )
    )(q, k, v)
    np.testing.assert_allclose(a, b, atol=3e-5, rtol=3e-5)


@pytest.mark.parametrize("causal", [False, True])
def test_flash_bshd_layout_matches_bhsd(causal):
    """The fused-head BSHD layout (no transposes) must agree with the
    BHSD kernel and the XLA oracle, forward and backward."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from elasticdl_tpu.ops.attention import dot_product_attention

    rng = np.random.RandomState(0)
    B, H, S, D = 2, 2, 256, 128  # D lane-aligned: the bshd requirement
    q_bshd, k_bshd, v_bshd = [
        jnp.asarray(rng.randn(B, S, H, D), jnp.float32) for _ in range(3)
    ]
    to_bhsd = lambda t: t.transpose(0, 2, 1, 3)

    def loss(q, k, v, impl, layout):
        out = dot_product_attention(
            q, k, v, causal=causal, impl=impl, layout=layout,
            interpret=True,
        )
        return (out.astype(jnp.float32) ** 2).sum()

    val_ref, grads_ref = jax.value_and_grad(
        lambda q, k, v: loss(q, k, v, "xla", "bhsd"), argnums=(0, 1, 2)
    )(to_bhsd(q_bshd), to_bhsd(k_bshd), to_bhsd(v_bshd))
    val_bshd, grads_bshd = jax.value_and_grad(
        lambda q, k, v: loss(q, k, v, "pallas", "bshd"),
        argnums=(0, 1, 2),
    )(q_bshd, k_bshd, v_bshd)

    np.testing.assert_allclose(
        float(val_ref), float(val_bshd), rtol=1e-5
    )
    for g_ref, g_bshd in zip(grads_ref, grads_bshd):
        np.testing.assert_allclose(
            np.asarray(to_bhsd(g_bshd)),
            np.asarray(g_ref),
            atol=2e-2, rtol=1e-3,
        )


def test_flash_bshd_small_heads_fall_back():
    """head_dim not lane-aligned: auto must not pick the fused path,
    and an explicit pallas request goes through the transpose adapter
    and still matches the oracle."""
    import jax.numpy as jnp
    import numpy as np

    from elasticdl_tpu.ops.attention import (
        _pallas_ok,
        dot_product_attention,
    )

    rng = np.random.RandomState(1)
    q, k, v = [
        jnp.asarray(rng.randn(2, 256, 2, 16), jnp.float32)
        for _ in range(3)
    ]
    assert not _pallas_ok(q, k, None, None, "bshd")
    out = dot_product_attention(
        q, k, v, causal=True, impl="pallas", layout="bshd",
        interpret=True,
    )
    ref = dot_product_attention(
        q, k, v, causal=True, impl="xla", layout="bshd"
    )
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(ref), atol=1e-5
    )


def test_rotary_seq_axis_variants_agree():
    """rotary_embedding(seq_axis=1) on (B, S, H, d) must equal the
    transposed seq_axis=2 result on (B, H, S, d)."""
    import jax.numpy as jnp
    import numpy as np

    from elasticdl_tpu.models.transformer import rotary_embedding

    x_bshd = jnp.asarray(
        np.random.RandomState(3).randn(2, 32, 4, 16), jnp.float32
    )
    via_bshd = rotary_embedding(x_bshd, seq_axis=1)
    via_bhsd = rotary_embedding(
        x_bshd.transpose(0, 2, 1, 3), seq_axis=2
    ).transpose(0, 2, 1, 3)
    np.testing.assert_allclose(
        np.asarray(via_bshd), np.asarray(via_bhsd), atol=1e-6
    )


def test_attention_rejects_unknown_layout():
    import jax.numpy as jnp
    import numpy as np

    from elasticdl_tpu.ops.attention import dot_product_attention

    q = jnp.asarray(np.zeros((1, 2, 16, 8)), jnp.float32)
    with pytest.raises(ValueError, match="layout"):
        dot_product_attention(q, q, q, layout="BHSD")
