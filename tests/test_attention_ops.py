"""Flash kernel vs XLA oracle; ring/ulysses SP vs full attention.

Kernel runs in Pallas interpret mode on CPU (compiled on real TPU); the
SP schedules run on the 8-virtual-device mesh from conftest.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from elasticdl_tpu.ops.attention import xla_attention
from elasticdl_tpu.ops.flash_attention import flash_attention
from elasticdl_tpu.ops.ring_attention import (
    ring_attention,
    ulysses_attention,
)
from elasticdl_tpu.parallel.mesh import MeshConfig, build_mesh


def _inputs(batch=2, heads=2, seq=256, dim=64, seed=0):
    rng = np.random.RandomState(seed)
    shape = (batch, heads, seq, dim)
    mk = lambda s: jnp.asarray(rng.normal(size=shape, scale=0.5), jnp.float32)
    return mk(0), mk(1), mk(2)


@pytest.mark.parametrize("causal", [False, True])
def test_flash_forward_matches_xla(causal):
    q, k, v = _inputs()
    expected = xla_attention(q, k, v, causal=causal)
    got = flash_attention(q, k, v, causal=causal, interpret=True)
    np.testing.assert_allclose(got, expected, atol=2e-5, rtol=2e-5)


@pytest.mark.parametrize("causal", [False, True])
def test_flash_grads_match_xla(causal):
    q, k, v = _inputs(seq=128)

    def loss_ref(q, k, v):
        out = xla_attention(q, k, v, causal=causal)
        return jnp.sum(out * jnp.cos(out))

    def loss_flash(q, k, v):
        out = flash_attention(
            q, k, v, causal=causal, block_q=64, block_k=64, interpret=True
        )
        return jnp.sum(out * jnp.cos(out))

    g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    g_flash = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g_flash, g_ref):
        np.testing.assert_allclose(a, b, atol=3e-4, rtol=3e-4)


@pytest.mark.parametrize("causal", [False, True])
def test_ring_attention_matches_full(causal):
    mesh = build_mesh(MeshConfig(dp=2, sp=4))
    q, k, v = _inputs(seq=64, dim=16)
    expected = xla_attention(q, k, v, causal=causal)

    ring = jax.jit(
        lambda q, k, v: ring_attention(q, k, v, mesh, causal=causal)
    )
    np.testing.assert_allclose(
        ring(q, k, v), expected, atol=2e-5, rtol=2e-5
    )


def test_ring_attention_grads_match_full():
    mesh = build_mesh(MeshConfig(dp=2, sp=4))
    q, k, v = _inputs(seq=32, dim=8)

    def loss_full(q, k, v):
        return jnp.sum(jnp.square(xla_attention(q, k, v, causal=True)))

    def loss_ring(q, k, v):
        return jnp.sum(
            jnp.square(ring_attention(q, k, v, mesh, causal=True))
        )

    g_full = jax.grad(loss_full, argnums=(0, 1, 2))(q, k, v)
    g_ring = jax.jit(jax.grad(loss_ring, argnums=(0, 1, 2)))(q, k, v)
    for a, b in zip(g_ring, g_full):
        np.testing.assert_allclose(a, b, atol=2e-5, rtol=2e-5)


@pytest.mark.parametrize("causal", [False, True])
def test_ulysses_attention_matches_full(causal):
    mesh = build_mesh(MeshConfig(dp=2, sp=4))
    q, k, v = _inputs(heads=4, seq=64, dim=16)
    expected = xla_attention(q, k, v, causal=causal)

    uly = jax.jit(
        lambda q, k, v: ulysses_attention(q, k, v, mesh, causal=causal)
    )
    np.testing.assert_allclose(
        uly(q, k, v), expected, atol=2e-5, rtol=2e-5
    )


def test_ring_attention_sp1_falls_back():
    mesh = build_mesh(MeshConfig(dp=8, sp=1))
    q, k, v = _inputs(seq=32, dim=8)
    expected = xla_attention(q, k, v, causal=True)
    got = ring_attention(q, k, v, mesh, causal=True)
    np.testing.assert_allclose(got, expected, atol=2e-5, rtol=2e-5)


@pytest.mark.parametrize("causal", [False, True])
def test_flash_ring_matches_full(causal):
    """Ring fold with the Pallas kernel as block compute (VERDICT.md
    round-1 item #6): per-device work is true flash attention, output
    matches full single-device attention."""
    mesh = build_mesh(MeshConfig(dp=2, sp=4))
    q, k, v = _inputs(seq=256, dim=32)
    expected = xla_attention(q, k, v, causal=causal)
    got = jax.jit(
        lambda q, k, v: ring_attention(
            q, k, v, mesh, causal=causal,
            block_impl="flash", interpret=True,
        )
    )(q, k, v)
    np.testing.assert_allclose(got, expected, atol=3e-5, rtol=3e-5)


@pytest.mark.parametrize("causal", [False, True])
def test_flash_ring_grads_match_full(causal):
    mesh = build_mesh(MeshConfig(dp=2, sp=4))
    q, k, v = _inputs(seq=256, dim=32, seed=5)

    def loss_ref(q, k, v):
        out = xla_attention(q, k, v, causal=causal)
        return jnp.sum(out * jnp.cos(out))

    def loss_ring(q, k, v):
        out = ring_attention(
            q, k, v, mesh, causal=causal,
            block_impl="flash", interpret=True,
        )
        return jnp.sum(out * jnp.cos(out))

    g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    g_ring = jax.jit(jax.grad(loss_ring, argnums=(0, 1, 2)))(q, k, v)
    for a, b in zip(g_ring, g_ref):
        np.testing.assert_allclose(a, b, atol=5e-4, rtol=5e-4)


def test_flash_ring_agrees_with_einsum_ring():
    """The two block computes are different executions of the same
    math: outputs must agree tightly."""
    mesh = build_mesh(MeshConfig(dp=2, sp=4))
    q, k, v = _inputs(seq=256, dim=32, seed=9)
    a = jax.jit(
        lambda q, k, v: ring_attention(
            q, k, v, mesh, causal=True, block_impl="flash", interpret=True
        )
    )(q, k, v)
    b = jax.jit(
        lambda q, k, v: ring_attention(
            q, k, v, mesh, causal=True, block_impl="einsum"
        )
    )(q, k, v)
    np.testing.assert_allclose(a, b, atol=3e-5, rtol=3e-5)
