"""Online serving tier (ISSUE 8).

Covers the acceptance criteria directly:

- export/serve parity: served predictions BIT-EXACT with the trainer's
  eval forward on the same batch, for a sparse model (deepfm, rows
  resolved through the shared embedding client) and a dense one
  (iris_dnn) — through the real gRPC wire;
- admission control: bounded-queue shedding (RESOURCE_EXHAUSTED),
  past-deadline requests shed rather than served late
  (DEADLINE_EXCEEDED), batch formation by max-size-or-max-delay;
- zero-downtime version swap: a new export picked up mid-traffic with
  ZERO failed requests, in-flight requests finishing on the version
  that admitted them;
- SIGTERM drain: admissions stop, the flushed queue still answers.
"""

import os
import tempfile
import threading
import time

import grpc
import numpy as np
import pytest

from elasticdl_tpu.common.grpc_utils import build_server, find_free_port
from elasticdl_tpu.data.pipeline import MASK_KEY, pad_batch
from elasticdl_tpu.proto.services import add_serve_servicer_to_server
from elasticdl_tpu.serve.batcher import (
    DeadlineExpired,
    Draining,
    MicroBatcher,
    QueueFull,
)
from elasticdl_tpu.serve.client import ServeClient
from elasticdl_tpu.serve.engine import ServingEngine
from elasticdl_tpu.serve.servicer import ServeServicer
from elasticdl_tpu.train.export import export_train_state
from elasticdl_tpu.train.local_executor import LocalExecutor
from tests.test_utils import create_ctr_recordio

BATCH = 32


def _serve(engine):
    server = build_server()
    add_serve_servicer_to_server(ServeServicer(engine), server)
    port = find_free_port()
    server.add_insecure_port("[::]:%d" % port)
    server.start()
    return server, ServeClient("localhost:%d" % port)


@pytest.fixture(scope="module")
def deepfm_run():
    """One trained deepfm + export, shared by the module's tests."""
    tmp = tempfile.mkdtemp(prefix="edl-serving-")
    create_ctr_recordio(tmp + "/f0.rec", num_records=128, seed=0)
    executor = LocalExecutor(
        "elasticdl_tpu.models.deepfm", training_data=tmp,
        minibatch_size=BATCH, num_epochs=1,
    )
    executor.train()
    export_dir = os.path.join(tmp, "export")
    export_train_state(executor.state, export_dir)
    return executor, export_dir


def _deepfm_engine(deepfm_run, **kw):
    executor, export_dir = deepfm_run
    kw.setdefault("max_batch", BATCH)
    kw.setdefault("max_delay_ms", 2.0)
    kw.setdefault("deadline_ms", 5000.0)
    return ServingEngine(
        "elasticdl_tpu.models.deepfm", export_dir,
        ps_client=executor.trainer.preparer._ps, **kw
    ).start(block=True)


# ---------------------------------------------------------------------------
# export/serve parity


def test_export_serve_parity_deepfm(deepfm_run):
    executor, _ = deepfm_run
    engine = _deepfm_engine(deepfm_run)
    server, client = _serve(engine)
    try:
        ids = np.random.RandomState(7).randint(
            0, 1000, size=(BATCH, 10)
        ).astype(np.int64)
        outputs, step, stamp = client.predict({"ids": ids})
        assert step == int(executor.state.step)
        batch = {
            "features": {"ids": ids},
            MASK_KEY: np.ones(BATCH, np.float32),
        }
        trainer_out = np.asarray(
            executor.trainer.eval_step(executor.state, batch)
        )
        # BIT-exact, not allclose: same eval step fn, same fp32 rows,
        # any drift means export flatten/restore corrupted something
        np.testing.assert_array_equal(outputs["output"], trainer_out)
    finally:
        server.stop(0)
        client.close()
        engine.drain(timeout=5)


def test_export_serve_parity_iris_dnn(tmp_path):
    rng = np.random.RandomState(0)
    lines = []
    for _ in range(96):
        x = rng.rand(4) * 2
        label = int(x.sum() > 4)
        lines.append(",".join("%.6f" % v for v in x) + ",%d" % label)
    (tmp_path / "iris.csv").write_text("\n".join(lines) + "\n")
    executor = LocalExecutor(
        "elasticdl_tpu.models.iris_dnn", training_data=str(tmp_path),
        minibatch_size=32, num_epochs=1,
    )
    executor.train()
    export_dir = str(tmp_path / "export")
    export_train_state(executor.state, export_dir)
    engine = ServingEngine(
        "elasticdl_tpu.models.iris_dnn", export_dir,
        max_batch=32, max_delay_ms=2.0, deadline_ms=5000.0,
    ).start(block=True)
    server, client = _serve(engine)
    try:
        x = rng.rand(32, 4).astype(np.float32)
        outputs, step, _ = client.predict(x)  # single-input: bare array
        batch = {
            "features": x,
            MASK_KEY: np.ones(32, np.float32),
        }
        trainer_out = np.asarray(
            executor.trainer.eval_step(executor.state, batch)
        )
        np.testing.assert_array_equal(outputs["output"], trainer_out)
        assert outputs["output"].shape == (32, 3)
    finally:
        server.stop(0)
        client.close()
        engine.drain(timeout=5)


def test_partial_batch_is_padded_not_recompiled(deepfm_run):
    """Requests smaller than max_batch serve off the one compiled
    shape; outputs slice back to the request's rows."""
    engine = _deepfm_engine(deepfm_run)
    try:
        ids = np.random.RandomState(1).randint(
            0, 1000, size=(3, 10)
        ).astype(np.int64)
        (outputs, _, _) = engine.predict({"ids": ids}, 3)
        assert outputs["output"].shape == (3,)
    finally:
        engine.drain(timeout=5)


# ---------------------------------------------------------------------------
# micro-batcher admission control


def test_batcher_sheds_at_queue_depth():
    release = threading.Event()

    def runner(features, rows):
        release.wait(timeout=10)
        return {"output": np.zeros(rows, np.float32)}, 1, "s"

    batcher = MicroBatcher(
        runner, max_batch=4, max_delay_ms=1.0, queue_depth=2,
        default_deadline_ms=5000.0,
    )
    x = np.zeros((1, 2), np.float32)
    threads = [
        threading.Thread(
            target=lambda: _swallow(batcher, x), daemon=True
        )
        for _ in range(8)
    ]
    for t in threads:
        t.start()
    deadline = time.monotonic() + 5
    while batcher.shed_total == 0 and time.monotonic() < deadline:
        time.sleep(0.01)
    assert batcher.shed_total > 0  # queue_full sheds fired
    release.set()
    for t in threads:
        t.join(timeout=10)
    batcher.stop()


def _swallow(batcher, x):
    try:
        batcher.submit(x, 1)
    except (QueueFull, DeadlineExpired, Draining):
        pass


def test_batcher_sheds_past_deadline_not_late():
    served = []

    def runner(features, rows):
        return {"output": np.zeros(rows, np.float32)}, 1, "s"

    # formation window 80 ms >> request budget 5 ms: by the time the
    # batch forms, the request is past its deadline and MUST be shed
    batcher = MicroBatcher(
        runner, max_batch=8, max_delay_ms=80.0, queue_depth=8,
        default_deadline_ms=1000.0,
    )
    with pytest.raises(DeadlineExpired):
        batcher.submit(np.zeros((1, 2), np.float32), 1,
                       deadline_secs=0.005)
    assert not served
    assert batcher.shed_total == 1
    batcher.stop()


def test_batcher_forms_one_batch_from_concurrent_requests():
    sizes = []

    def runner(features, rows):
        sizes.append(rows)
        return {"output": np.zeros(rows, np.float32)}, 1, "s"

    batcher = MicroBatcher(
        runner, max_batch=16, max_delay_ms=60.0, queue_depth=32,
        default_deadline_ms=5000.0,
    )
    results = []

    def one():
        results.append(batcher.submit(np.zeros((2, 3), np.float32), 2))

    threads = [threading.Thread(target=one) for _ in range(4)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=10)
    assert len(results) == 4
    for outputs, _, _ in results:
        assert outputs["output"].shape == (2,)
    # the 60 ms window gathered the concurrent requests into one (or
    # at most two, under scheduler jitter) formed batches
    assert sum(sizes) == 8 and len(sizes) <= 2, sizes
    batcher.stop()


def test_mixed_schema_requests_never_cobatch():
    """Requests whose features disagree on trailing shape/dtype must
    form separate batches — otherwise one malformed request's
    concatenate error poisons every co-batched request."""
    shapes = []

    def runner(features, rows):
        shapes.append(np.asarray(features).shape)
        return {"output": np.zeros(rows, np.float32)}, 1, "s"

    batcher = MicroBatcher(
        runner, max_batch=16, max_delay_ms=60.0, queue_depth=32,
        default_deadline_ms=5000.0,
    )
    results = []

    def one(width):
        results.append(
            batcher.submit(np.zeros((2, width), np.float32), 2)
        )

    threads = [
        threading.Thread(target=one, args=(w,)) for w in (3, 5, 3, 5)
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=10)
    assert len(results) == 4  # nobody failed
    # every formed batch was schema-homogeneous
    assert all(shape[1] in (3, 5) for shape in shapes), shapes
    batcher.stop()


def test_drain_rejects_new_admissions_and_flushes():
    def runner(features, rows):
        return {"output": np.zeros(rows, np.float32)}, 1, "s"

    batcher = MicroBatcher(
        runner, max_batch=4, max_delay_ms=1.0, queue_depth=8,
        default_deadline_ms=5000.0,
    )
    batcher.submit(np.zeros((1, 2), np.float32), 1)
    batcher.drain(timeout=5)
    with pytest.raises(Draining):
        batcher.submit(np.zeros((1, 2), np.float32), 1)


def test_in_message_deadline_honored_under_loose_rpc_timeout(deepfm_run):
    """deadline_ms must shed even when the transport carries a loose
    default RPC deadline — the TIGHTER of the two budgets governs."""
    engine = _deepfm_engine(deepfm_run, max_delay_ms=200.0)
    server, client = _serve(engine)
    try:
        ids = np.random.RandomState(3).randint(
            0, 1000, size=(2, 10)
        ).astype(np.int64)
        # ServeClient sets its 60 s default gRPC timeout; the 20 ms
        # in-message budget is inside the 200 ms formation window, so
        # the request must be SHED server-side, not served late
        with pytest.raises(grpc.RpcError) as err:
            client.predict({"ids": ids}, deadline_ms=20)
        assert err.value.code() == grpc.StatusCode.DEADLINE_EXCEEDED
        assert engine.batcher.shed_total == 1
    finally:
        server.stop(0)
        client.close()
        engine.drain(timeout=5)


def test_server_default_budget_caps_loose_rpc_timeout(deepfm_run):
    """With no in-message deadline_ms, the server's --deadline_ms must
    still cap the queueing budget — a loose transport timeout is not a
    request to queue for that long."""
    engine = _deepfm_engine(deepfm_run, max_delay_ms=200.0,
                            deadline_ms=20.0)
    server, client = _serve(engine)
    try:
        ids = np.random.RandomState(5).randint(
            0, 1000, size=(2, 10)
        ).astype(np.int64)
        # 10 s RPC deadline, no deadline_ms: the 20 ms server default
        # is inside the 200 ms formation window -> shed, never late
        with pytest.raises(grpc.RpcError) as err:
            client.predict({"ids": ids}, deadline_secs=10)
        assert err.value.code() == grpc.StatusCode.DEADLINE_EXCEEDED
    finally:
        server.stop(0)
        client.close()
        engine.drain(timeout=5)


def test_ps_restart_invalidation_survives_discarded_rebuild(deepfm_run):
    """The hook slot on the PS client is single-owner: read-only
    (serving) preparers must never take it, or every sparse
    ServingModel build — including builds the stamp check discards —
    would clobber the engine's shared-cache invalidation chain and a
    PS relaunch would stop clearing the serving cache."""
    executor, _ = deepfm_run
    ps = executor.trainer.preparer._ps
    if not hasattr(ps, "resync_hook"):
        ps.resync_hook = None  # LocalPSClient: give it the gRPC
        # client's hook surface so the chain machinery engages
    engine = _deepfm_engine(deepfm_run)
    try:
        # a rebuild whose stamp matches is discarded, but its preparer
        # still took over the hook mid-build
        assert engine._load_and_swap() is False
        engine.cache.put(
            "deepfm_emb", np.array([7], np.int64),
            np.ones((1, 8), np.float32),
        )
        ps.resync_hook(0)  # PS relaunch detected on any thread
        assert engine.cache._tables == {}, (
            "shared serving cache not dropped on PS restart"
        )
    finally:
        engine.drain(timeout=5)


# ---------------------------------------------------------------------------
# zero-downtime version swap


@pytest.mark.slow
def test_version_swap_zero_failed_requests(deepfm_run):
    executor, export_dir = deepfm_run
    engine = _deepfm_engine(deepfm_run, watch_secs=0.1)
    server, client = _serve(engine)
    first_step = engine.model.step
    errors = []
    steps_seen = set()
    stop = threading.Event()

    def load():
        rng = np.random.RandomState(threading.get_ident() % 2**31)
        while not stop.is_set():
            ids = rng.randint(0, 1000, size=(4, 10)).astype(np.int64)
            try:
                _, step, _ = client.predict({"ids": ids},
                                            deadline_secs=10)
                steps_seen.add(step)
            except grpc.RpcError as e:  # pragma: no cover - the gate
                errors.append(e)
            time.sleep(0.002)

    threads = [threading.Thread(target=load) for _ in range(4)]
    for t in threads:
        t.start()
    time.sleep(0.4)
    # a newer export lands mid-traffic (train a little further so the
    # step really moves)
    for batch in _few_batches(executor, 2):
        executor.state, _ = executor.trainer.train_step(
            executor.state, batch
        )
    export_train_state(executor.state, export_dir)
    deadline = time.monotonic() + 20
    while engine.swaps == 0 and time.monotonic() < deadline:
        time.sleep(0.05)
    time.sleep(0.3)  # traffic on the new version
    stop.set()
    for t in threads:
        t.join(timeout=30)
    server.stop(0)
    client.close()
    engine.drain(timeout=5)
    assert engine.swaps >= 1, "watcher never swapped"
    assert errors == [], "requests failed across the swap: %s" % errors
    new_step = engine.model.step
    assert new_step > first_step
    assert {first_step, new_step} <= steps_seen


def _few_batches(executor, n):
    batches = []
    for batch in executor._batches(executor._train_reader, "training"):
        batches.append(batch)
        if len(batches) >= n:
            break
    return batches


def test_serve_role_telemetry_blob(deepfm_run):
    """The fleet-telemetry provider must build a blob without raising —
    its exceptions are swallowed by MasterClient's telemetry attach, so
    a broken provider silently blanks the inference side of /statusz
    (regression: batcher.queue_depth the int shadowed the method)."""
    _, export_dir = deepfm_run
    from elasticdl_tpu.serve import main as serve_main

    args = serve_main.parse_serve_args([
        "--model_zoo", "elasticdl_tpu.models.deepfm",
        "--export_dir", export_dir,
    ])
    role = serve_main.ServeRole(args)
    try:
        blob = role.telemetry_blob()
        assert blob.role == "serve-0"
        assert blob.serve_queue_depth == 0
        assert blob.serve_shed_total == 0
    finally:
        role.engine.drain(timeout=5)


# ---------------------------------------------------------------------------
# servicer status mapping


def test_unloaded_model_answers_failed_precondition(tmp_path):
    engine = ServingEngine(
        "elasticdl_tpu.models.iris_dnn", str(tmp_path / "nothing"),
        max_batch=4, watch_secs=30.0,
    ).start()
    server, client = _serve(engine)
    try:
        assert client.model_info()["loaded"] is False
        with pytest.raises(grpc.RpcError) as err:
            client.predict(np.zeros((1, 4), np.float32))
        assert err.value.code() == grpc.StatusCode.FAILED_PRECONDITION
    finally:
        server.stop(0)
        client.close()
        engine.drain(timeout=5)
