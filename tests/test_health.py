"""Training-health sentinels (ISSUE 15): tracker semantics, in-graph
scalars, the sentinel-action contract (alert/skip/halt), NaN-batch
fault injection, PS table-health scan, stream drift stats, and the
end-to-end /alerts + postmortem thread."""

import json
import os
import threading
import urllib.request

import numpy as np
import pytest

from elasticdl_tpu.observability import events
from elasticdl_tpu.observability import metrics as obs_metrics
from elasticdl_tpu.testing import faults
from elasticdl_tpu.train.health import (
    HealthSentinelError,
    HealthTracker,
    health_enabled,
    maybe_tracker,
    nonfinite_action,
)


@pytest.fixture(autouse=True)
def _clean_fault_state():
    faults._reset_for_tests()
    yield
    faults._reset_for_tests()


# ---------------------------------------------------------------------------
# HealthTracker semantics


def test_tracker_loss_spike_robust_z():
    t = HealthTracker(action="alert", spike_z=4.0, warmup_steps=5,
                      grad_factor=0.0)
    for _ in range(30):
        t.observe(1.0 + np.random.RandomState(0).uniform(-0.01, 0.01),
                  0.5, False)
    assert t.loss_spikes == 0
    t.observe(50.0, 0.5, False)  # way past 4 sigma of the dev EWMA
    assert t.loss_spikes == 1
    # the spike folded into the EWMAs AFTER the check: the next normal
    # loss is not itself flagged as a (downward) spike storm
    spikes = t.loss_spikes
    t.observe(1.0, 0.5, False)
    t.observe(1.0, 0.5, False)
    assert t.loss_spikes <= spikes + 1


def test_tracker_grad_explosion_absolute_and_relative():
    t = HealthTracker(action="alert", spike_z=0.0, warmup_steps=2,
                      grad_norm_max=100.0, grad_factor=10.0)
    for _ in range(5):
        t.observe(1.0, 1.0, False)
    t.observe(1.0, 200.0, False)  # absolute ceiling
    assert t.grad_explosions == 1
    t2 = HealthTracker(action="alert", spike_z=0.0, warmup_steps=2,
                       grad_norm_max=0.0, grad_factor=10.0)
    for _ in range(5):
        t2.observe(1.0, 1.0, False)
    t2.observe(1.0, 50.0, False)  # 50x the EWMA
    assert t2.grad_explosions == 1


def test_tracker_nonfinite_streak_and_actions():
    t = HealthTracker(action="alert")
    assert t.observe(float("nan"), 1.0, True) is None
    assert t.nonfinite_streak == 1
    assert t.observe(float("nan"), 1.0, True) is None
    assert t.nonfinite_streak == 2
    t.observe(1.0, 1.0, False)
    assert t.nonfinite_streak == 0
    assert t.nonfinite_total == 2

    t_skip = HealthTracker(action="skip")
    assert t_skip.observe(float("nan"), 1.0, True) == "skip"
    assert t_skip.skipped_batches == 1

    t_halt = HealthTracker(action="halt")
    with pytest.raises(HealthSentinelError):
        t_halt.observe(float("nan"), 1.0, True)


def test_tracker_warmup_suppresses_detection():
    t = HealthTracker(action="alert", spike_z=2.0, warmup_steps=50,
                      grad_norm_max=1.0)
    for i in range(20):
        t.observe(float(i * 100), 50.0, False)  # wild, but in warmup
    assert t.loss_spikes == 0 and t.grad_explosions == 0


def test_env_knobs(monkeypatch):
    monkeypatch.delenv("EDL_HEALTH", raising=False)
    monkeypatch.delenv("EDL_HEALTH_ON_NONFINITE", raising=False)
    assert health_enabled()
    assert nonfinite_action() == "alert"
    assert maybe_tracker() is not None
    monkeypatch.setenv("EDL_HEALTH", "0")
    assert not health_enabled()
    assert maybe_tracker() is None
    monkeypatch.setenv("EDL_HEALTH_ON_NONFINITE", "skip")
    assert nonfinite_action() == "skip"
    monkeypatch.setenv("EDL_HEALTH_ON_NONFINITE", "explode")
    with pytest.raises(ValueError):
        nonfinite_action()


# ---------------------------------------------------------------------------
# in-graph scalars + EDL_HEALTH=0 inertness


def _dense_pieces():
    from elasticdl_tpu.models import mnist

    return mnist.custom_model(), mnist.loss, mnist.optimizer()


def _mnist_batch(n=8, seed=0):
    rng = np.random.RandomState(seed)
    return {
        "features": rng.uniform(size=(n, 28, 28, 1)).astype(np.float32),
        "labels": rng.randint(0, 10, n).astype(np.int64),
        "_mask": np.ones(n, np.float32),
    }


def test_health_off_emits_no_extra_outputs():
    """EDL_HEALTH=0 inertness, the acceptance contract: the factory
    default compiles the exact pre-health program — a 2-tuple from
    the dense step, no health dict anywhere."""
    import jax

    from elasticdl_tpu.train.step_fns import make_train_step
    from elasticdl_tpu.train.train_state import create_train_state

    model, loss_fn, tx = _dense_pieces()
    batch = _mnist_batch()
    step = jax.jit(make_train_step(model, loss_fn, tx))
    state = create_train_state(
        model, tx, jax.random.PRNGKey(0), batch["features"]
    )
    out = step(state, batch)
    assert len(out) == 2  # (state, loss) — nothing else


def test_health_on_returns_scalars_and_matches_off_state():
    """With health on (alert mode), the extra outputs exist AND the
    state math is bit-identical to the health-off program."""
    import jax

    from elasticdl_tpu.train.step_fns import make_train_step
    from elasticdl_tpu.train.train_state import create_train_state

    model, loss_fn, tx = _dense_pieces()
    batch = _mnist_batch()
    state_a = create_train_state(
        model, tx, jax.random.PRNGKey(0), batch["features"]
    )
    state_b = jax.tree_util.tree_map(lambda x: x.copy(), state_a)
    plain = jax.jit(make_train_step(model, loss_fn, tx))
    healthy = jax.jit(make_train_step(model, loss_fn, tx, health=True))
    new_a, loss_a = plain(state_a, batch)
    new_b, loss_b, scalars = healthy(state_b, batch)
    assert float(loss_a) == float(loss_b)
    for la, lb in zip(
        jax.tree_util.tree_leaves(new_a), jax.tree_util.tree_leaves(new_b)
    ):
        np.testing.assert_array_equal(np.asarray(la), np.asarray(lb))
    assert np.isfinite(float(scalars["grad_norm"]))
    assert not bool(scalars["nonfinite"])


def test_guard_nonfinite_keeps_previous_state():
    import jax

    from elasticdl_tpu.train.step_fns import make_train_step
    from elasticdl_tpu.train.train_state import create_train_state

    model, loss_fn, tx = _dense_pieces()
    batch = _mnist_batch()
    poisoned = dict(batch)
    poisoned["features"] = np.full_like(batch["features"], np.nan)
    state = create_train_state(
        model, tx, jax.random.PRNGKey(0), batch["features"]
    )
    before = jax.tree_util.tree_map(
        lambda x: np.asarray(x).copy(), state
    )
    step = jax.jit(make_train_step(
        model, loss_fn, tx, health=True, guard_nonfinite=True
    ))
    new_state, loss, scalars = step(state, poisoned)
    assert bool(scalars["nonfinite"])
    assert not np.isfinite(float(loss))
    # every leaf — params, slots, step counter — kept its old value
    for old, new in zip(
        jax.tree_util.tree_leaves(before),
        jax.tree_util.tree_leaves(new_state),
    ):
        np.testing.assert_array_equal(np.asarray(old), np.asarray(new))


# ---------------------------------------------------------------------------
# nan-batch fault injection (testing/faults.py)


def test_nan_batch_spec_fires_once_on_nth_call(monkeypatch):
    monkeypatch.setenv(faults.FAULT_SPEC_ENV,
                       "worker-0:train_step:nan-batch:3")
    faults._reset_for_tests()
    faults.set_role("worker-0")
    batch = {"features": {"x": np.ones((4, 2), np.float32),
                          "ids": np.ones((4, 2), np.int64)},
             "labels": np.ones(4, np.int64)}
    out1 = faults.maybe_poison_batch(batch)
    out2 = faults.maybe_poison_batch(batch)
    assert out1 is batch and out2 is batch  # calls 1-2: untouched
    out3 = faults.maybe_poison_batch(batch)
    assert np.isnan(out3["features"]["x"]).all()  # call 3: poisoned
    # int features and labels untouched (shapes/dtypes stable)
    assert out3["features"]["ids"].dtype == np.int64
    assert np.array_equal(out3["labels"], batch["labels"])
    assert not np.isnan(batch["features"]["x"]).any()  # input not mutated
    out4 = faults.maybe_poison_batch(batch)
    assert out4 is batch  # once per process


def test_nan_batch_inert_when_unset(monkeypatch):
    monkeypatch.delenv(faults.FAULT_SPEC_ENV, raising=False)
    faults._reset_for_tests()
    batch = {"features": {"x": np.ones((2, 2), np.float32)}}
    assert faults.maybe_poison_batch(batch) is batch


def test_nan_batch_respects_role_and_method(monkeypatch):
    monkeypatch.setenv(faults.FAULT_SPEC_ENV,
                       "worker-7:train_step:nan-batch:1")
    faults._reset_for_tests()
    faults.set_role("worker-0")  # different role: never fires
    batch = {"features": {"x": np.ones((2, 2), np.float32)}}
    for _ in range(3):
        assert faults.maybe_poison_batch(batch) is batch


# ---------------------------------------------------------------------------
# the sentinel-action contract through a real SparseTrainer


def _ctr_batches(n, batch=16, fields=10, vocab=100, seed=0):
    rng = np.random.RandomState(seed)
    out = []
    # batch 0 carries the FULL vocab so every later batch's ids are
    # already materialized: the skipped run and the never-saw-it run
    # then materialize identical row sets in identical order
    warm = np.arange(vocab, dtype=np.int64)
    warm = np.resize(warm, (batch, fields))
    out.append({"features": {"ids": warm},
                "labels": rng.randint(0, 2, batch).astype(np.float32),
                "_mask": np.ones(batch, np.float32)})
    for _ in range(n - 1):
        ids = rng.randint(0, vocab, size=(batch, fields)).astype(np.int64)
        out.append({"features": {"ids": ids},
                    "labels": rng.randint(0, 2, batch).astype(np.float32),
                    "_mask": np.ones(batch, np.float32)})
    return out


def _sparse_trainer(action, **kwargs):
    from elasticdl_tpu.models import deepfm
    from elasticdl_tpu.ps.local_client import LocalPSClient
    from elasticdl_tpu.train.sparse import SparseTrainer

    return SparseTrainer(
        model=deepfm.custom_model(),
        loss_fn=deepfm.loss,
        optimizer=deepfm.optimizer(),
        specs=deepfm.sparse_embedding_specs(
            num_features=10, batch_size=16
        ),
        ps_client=LocalPSClient(seed=0, opt_type="adam", lr=0.01),
        seed=0,
        health=HealthTracker(action=action),
        **kwargs,
    )


def _export_all(store):
    out = {}
    for name in store.table_names():
        ids, values = store.export_table(name)
        order = np.argsort(ids)
        out[name] = (ids[order], values[order])
    return out


@pytest.mark.parametrize("pipelined", [False, True])
def test_skip_sentinel_ps_state_bit_identical(monkeypatch, pipelined):
    """Acceptance: under skip, an injected-NaN run's final PS state is
    bit-identical to a run that never saw the poisoned batch — for
    both the sequential train_step and the pipelined train_stream.
    The pipelined variant accumulates to ONE tail push
    (push_interval > len(batches)): with per-step pushes the lookahead
    pull legitimately races the background push (the async staleness
    envelope), so per-step pulled values aren't run-comparable —
    accumulate-then-push makes the stream's fold/drop semantics
    deterministic, which is exactly the part skip must get right."""
    batches = _ctr_batches(8)
    poison_at = 4  # 1-based batch index the spec poisons

    def run(trainer, run_batches):
        state = None
        if pipelined:
            stream = trainer.train_stream(
                state, run_batches, push_interval=100
            )
            for state, loss, _b in stream:
                pass
            trainer.close()
        else:
            for b in run_batches:
                state, loss = trainer.train_step(state, b)
        return _export_all(trainer.preparer._ps.store)

    monkeypatch.setenv(faults.FAULT_SPEC_ENV,
                       "worker-0:train_step:nan-batch:%d" % poison_at)
    faults._reset_for_tests()
    faults.set_role("worker-0")
    trainer_a = _sparse_trainer("skip")
    state_a = run(trainer_a, batches)
    assert trainer_a.health.skipped_batches == 1

    monkeypatch.delenv(faults.FAULT_SPEC_ENV, raising=False)
    faults._reset_for_tests()
    trainer_b = _sparse_trainer("skip")
    clean = [b for i, b in enumerate(batches) if i != poison_at - 1]
    state_b = run(trainer_b, clean)
    assert trainer_b.health.skipped_batches == 0

    assert state_a.keys() == state_b.keys()
    for name in state_a:
        np.testing.assert_array_equal(state_a[name][0], state_b[name][0])
        np.testing.assert_array_equal(
            state_a[name][1], state_b[name][1],
            err_msg="table %s diverged" % name,
        )


def test_halt_sentinel_raises_and_journals(monkeypatch, tmp_path):
    monkeypatch.setenv("EDL_EVENTS_DIR", str(tmp_path))
    monkeypatch.setenv(faults.FAULT_SPEC_ENV,
                       "worker-0:train_step:nan-batch:2")
    faults._reset_for_tests()
    faults.set_role("worker-0")
    events._reset_for_tests()
    events.configure("worker-0")
    try:
        trainer = _sparse_trainer("halt")
        batches = _ctr_batches(3)
        state = None
        state, _ = trainer.train_step(state, batches[0])
        with pytest.raises(HealthSentinelError):
            trainer.train_step(state, batches[1])
    finally:
        events._reset_for_tests()
    lines = []
    for path in tmp_path.glob("*.events.ndjson"):
        with open(path, encoding="utf-8") as f:
            lines += [json.loads(l) for l in f if l.strip()]
    kinds = [e["event"] for e in lines]
    assert "health_nonfinite" in kinds
    assert "health_halt" in kinds


def test_alert_mode_trains_on_and_counts(monkeypatch):
    """Default action: the NaN propagates exactly as pre-health (the
    batch is counted, nothing skipped, state NOT guarded)."""
    monkeypatch.setenv(faults.FAULT_SPEC_ENV,
                       "worker-0:train_step:nan-batch:2")
    faults._reset_for_tests()
    faults.set_role("worker-0")
    trainer = _sparse_trainer("alert")
    batches = _ctr_batches(3)
    state = None
    state, _ = trainer.train_step(state, batches[0])
    state, loss = trainer.train_step(state, batches[1])
    assert not np.isfinite(float(loss))
    assert trainer.health.nonfinite_total == 1
    assert trainer.health.skipped_batches == 0


def test_halt_fails_task_and_master_requeues_exactly_once(
    monkeypatch, tmp_path
):
    """Acceptance: under halt, the task fails with a journaled
    health_halt and the master requeues it exactly once — through a
    REAL in-process master + worker."""
    import sys
    import tempfile

    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    from test_utils import create_mnist_recordio

    from elasticdl_tpu.common.grpc_utils import find_free_port
    from elasticdl_tpu.data.readers import RecordIODataReader
    from elasticdl_tpu.master.master import Master
    from elasticdl_tpu.worker.master_client import MasterClient
    from elasticdl_tpu.worker.worker import Worker

    monkeypatch.setenv("EDL_EVENTS_DIR", str(tmp_path))
    monkeypatch.setenv("EDL_HEALTH_ON_NONFINITE", "halt")
    monkeypatch.setenv(faults.FAULT_SPEC_ENV,
                       "worker-0:train_step:nan-batch:2")
    faults._reset_for_tests()
    faults.set_role("worker-0")
    events._reset_for_tests()
    events.configure("worker-0")
    train = tempfile.mkdtemp()
    create_mnist_recordio(train + "/f0.rec", num_records=96, seed=0)
    master = Master(
        "elasticdl_tpu.models.mnist", training_data=train,
        records_per_task=32, num_epochs=1, port=find_free_port(),
    )
    master.prepare()
    try:
        mc = MasterClient("localhost:%d" % master._port, worker_id=0)
        mc.reset_worker()
        worker = Worker(
            mc, "elasticdl_tpu.models.mnist",
            RecordIODataReader(data_dir=train),
            minibatch_size=32, wait_sleep_secs=0.1,
        )
        with pytest.raises(HealthSentinelError):
            worker.run()
        assert worker.trainer.health.nonfinite_total == 1
        # every held task (current + prefetched) went back exactly
        # ONCE, as a COUNTED failure — the worker died right after,
        # so nothing can loop the retry counter
        requeues = [
            e for e in _journal_events(tmp_path)
            if e["event"] == "task_requeue"
        ]
        assert requeues, "no task_requeue journaled"
        tasks = [e.get("task") for e in requeues]
        assert len(tasks) == len(set(tasks)), requeues  # once per task
        assert all(e.get("counted") is True for e in requeues)
        assert all(e.get("retries") == 1 for e in requeues)
        kinds = [e["event"] for e in _journal_events(tmp_path)]
        assert "health_halt" in kinds
    finally:
        master.stop()
        events._reset_for_tests()


def _journal_events(events_dir):
    lines = []
    import glob

    for path in glob.glob(str(events_dir) + "/*.events.ndjson"):
        with open(path, encoding="utf-8") as f:
            lines += [json.loads(l) for l in f if l.strip()]
    return lines


# ---------------------------------------------------------------------------
# PS table-health scan


def _scan_servicer(lifecycle=None, **env):
    from elasticdl_tpu.ps.embedding_store import NumpyEmbeddingStore
    from elasticdl_tpu.ps.servicer import PserverServicer

    store = NumpyEmbeddingStore(seed=0)
    store.set_optimizer("sgd", lr=0.1)
    store.create_table("emb", 8, init_scale=0.05)
    store.lookup("emb", np.arange(64, dtype=np.int64))
    return store, PserverServicer(
        store, use_async=True, lifecycle=lifecycle
    )


def test_table_health_scan_percentiles_and_exploding(monkeypatch):
    store, servicer = _scan_servicer()
    store.import_table("emb", np.array([3], np.int64),
                       np.full((1, 8), 5e3, np.float32))
    scan = servicer.table_health_scan(force=True)
    table = scan["tables"]["emb"]
    assert 0 < table["p50"] < 1.0  # init-scale norms
    assert table["exploding"] == 1
    assert scan["exploding_rows"] == 1
    blob = servicer.telemetry_blob()
    assert blob.ps_exploding_rows == 1
    assert blob.ps_row_norm_p99 > blob.ps_row_norm_p50 > 0


def test_table_health_scan_skips_oversized_tables(monkeypatch):
    """The scan samples via export_table (a full copy under the table
    lock): past EDL_HEALTH_SCAN_MAX_ROWS it must SKIP the table, not
    stall the data plane for a 256-row sample."""
    monkeypatch.setenv("EDL_HEALTH_SCAN_MAX_ROWS", "32")
    store, servicer = _scan_servicer()  # 64 resident rows > cap
    scan = servicer.table_health_scan(force=True)
    assert scan["tables"] == {}  # the only table was skipped
    monkeypatch.setenv("EDL_HEALTH_SCAN_MAX_ROWS", "1000")
    store2, servicer2 = _scan_servicer()
    assert "emb" in servicer2.table_health_scan(force=True)["tables"]


def test_table_health_scan_rate_limited_and_gated(monkeypatch):
    store, servicer = _scan_servicer()
    assert servicer.table_health_scan(force=True) is not None
    # second un-forced scan inside the window: skipped
    assert servicer.table_health_scan() is None
    # EDL_HEALTH=0 disables the scan entirely
    monkeypatch.setenv("EDL_HEALTH", "0")
    store2, servicer2 = _scan_servicer()
    assert servicer2.table_health_scan(force=True) is None


def test_table_health_dead_row_fraction_from_lifecycle(monkeypatch):
    monkeypatch.setenv("EDL_EMB_ADMIT_K", "1")
    monkeypatch.setenv("EDL_EMB_MAX_ROWS", "16")
    # just-touched rows are sweep-protected for 1 s by default; the
    # test's rows were admitted milliseconds ago
    monkeypatch.setenv("EDL_EMB_LFU_PROTECT_SECS", "0")
    from elasticdl_tpu.ps.embedding_store import NumpyEmbeddingStore
    from elasticdl_tpu.ps.servicer import PserverServicer
    from elasticdl_tpu.stream.lifecycle import EmbeddingLifecycle

    store = NumpyEmbeddingStore(seed=0)
    store.set_optimizer("sgd", lr=0.1)
    lifecycle = EmbeddingLifecycle.maybe_create(store)
    assert lifecycle is not None
    servicer = PserverServicer(
        store, use_async=True, lifecycle=lifecycle
    )
    from elasticdl_tpu.proto import elasticdl_tpu_pb2 as pb

    servicer._create_tables([pb.EmbeddingTableInfo(
        name="emb", dim=4, initializer="0.05"
    )])
    # admit 32 ids through pushes, then sweep down to the 16-row bound
    ids = np.arange(32, dtype=np.int64)
    grads = np.full((32, 4), 0.1, np.float32)
    mask = lifecycle.filter_push("emb", ids)
    store.push_gradients("emb", ids[mask], grads[mask])
    evicted = lifecycle.sweep()
    assert evicted["lfu"] > 0
    scan = servicer.table_health_scan(force=True)
    assert scan["dead_row_fraction"] > 0
    stats = lifecycle.stats()
    expect = (
        (stats["rows_evicted_ttl"] + stats["rows_evicted_lfu"])
        / float(stats["rows_evicted_ttl"] + stats["rows_evicted_lfu"]
                + stats["resident_rows"])
    )
    assert scan["dead_row_fraction"] == pytest.approx(expect)


# ---------------------------------------------------------------------------
# stream drift stats -> feeder -> fleet


def test_feeder_folds_window_stats_into_fleet(tmp_path):
    from elasticdl_tpu.master.fleet import FleetMonitor
    from elasticdl_tpu.master.task_dispatcher import TaskDispatcher
    from elasticdl_tpu.stream.feeder import StreamFeeder
    from elasticdl_tpu.stream.source import SyntheticClickstreamSource

    source = SyntheticClickstreamSource(
        str(tmp_path / "spool"), records_per_window=64,
        hot_vocab=50, drift_per_window=10, total_records=512, seed=3,
    )
    dispatcher = TaskDispatcher(
        {}, records_per_task=64, num_epochs=0, stream=True
    )
    fleet = FleetMonitor()
    feeder = StreamFeeder(dispatcher, source, fleet=fleet)
    minted = feeder.tick()
    assert minted == 8
    books = fleet.snapshot()["health"]["stream"]
    assert books["windows"] == 8
    assert books["watermark"] > 0
    assert 0 <= books["last_label_rate"] <= 1
    state = feeder.state()
    assert state["last_window_stats"]["watermark"] == 512


# ---------------------------------------------------------------------------
# worker telemetry carries the tracker


def test_worker_telemetry_blob_health_fields():
    """The piggyback path: a trainer-shaped object with a tracker ->
    TelemetryBlob fields 28-35, without standing up a Worker."""
    from elasticdl_tpu.proto import elasticdl_tpu_pb2 as pb

    tracker = HealthTracker(action="skip")
    tracker.observe(0.7, 0.5, False)
    tracker.observe(float("nan"), float("nan"), True)
    blob = pb.TelemetryBlob()
    stats = tracker.stats()
    blob.health_loss_ewma = stats["loss_ewma"]
    blob.health_nonfinite_batches = stats["nonfinite_batches"]
    blob.health_nonfinite_streak = stats["nonfinite_streak"]
    blob.health_skipped_batches = stats["skipped_batches"]
    wire = pb.TelemetryBlob.FromString(blob.SerializeToString())
    assert wire.health_nonfinite_batches == 1
    assert wire.health_nonfinite_streak == 1
    assert wire.health_skipped_batches == 1
    assert wire.health_loss_ewma == pytest.approx(0.7)


# ---------------------------------------------------------------------------
# acceptance: all four detectors end-to-end — raise AND clear on a
# live FleetMonitor, visible on /alerts over HTTP, threaded by
# postmortem


def test_four_detectors_end_to_end_alerts_and_postmortem(
    monkeypatch, tmp_path
):
    import sys
    import time

    sys.path.insert(0, os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "scripts",
    ))
    import postmortem as pm

    monkeypatch.setenv("EDL_EVENTS_DIR", str(tmp_path))
    events._reset_for_tests()
    events.configure("master")
    from elasticdl_tpu.master.fleet import FleetMonitor
    from elasticdl_tpu.observability.http_server import (
        ObservabilityServer,
    )
    from elasticdl_tpu.proto import elasticdl_tpu_pb2 as pb

    try:
        fleet = FleetMonitor(
            dead_air_secs=600, health_alert_secs=0.25,
            label_shift_delta=0.1, id_novelty_max=0.8,
        )
        # synthetic traces drive each detector
        fleet.observe(0, pb.TelemetryBlob(
            role="worker-0", health_nonfinite_batches=1,
            health_nonfinite_streak=2, health_skipped_batches=0,
        ))
        fleet.observe(1, pb.TelemetryBlob(
            role="worker-1", health_loss_spikes=3,
            health_loss_last=9.0, health_loss_ewma=0.7,
        ))
        fleet.observe(2, pb.TelemetryBlob(
            role="worker-2", health_grad_explosions=1,
            health_grad_norm=4200.0,
        ))
        for i in range(6):
            fleet.observe_stream_window(64 * (i + 1), 0.5, 0.1)
        fleet.observe_stream_window(448, 0.9, 0.1)
        from elasticdl_tpu.common.grpc_utils import find_free_port

        server = ObservabilityServer(
            "master", find_free_port()
        ).start()
        server.add_json_handler("/alerts", fleet.alerts)
        try:
            body = json.loads(urllib.request.urlopen(
                "http://localhost:%d/alerts" % server.port, timeout=5
            ).read())
            kinds = {a["alert"] for a in body}
            assert kinds == {
                "nonfinite_loss", "loss_spike", "grad_explosion",
                "label_shift",
            }, kinds
            # ... and every one CLEARS: recovery blobs + window decay
            fleet.observe(0, pb.TelemetryBlob(
                role="worker-0", health_nonfinite_batches=1,
            ))
            time.sleep(0.4)
            body = json.loads(urllib.request.urlopen(
                "http://localhost:%d/alerts" % server.port, timeout=5
            ).read())
            assert body == [], body
        finally:
            server.stop()
    finally:
        events._reset_for_tests()
    report = pm.postmortem(str(tmp_path))
    raised = [e for e in report["timeline"]
              if e.get("event") == "alert_raised"]
    cleared = [e for e in report["timeline"]
               if e.get("event") == "alert_cleared"]
    assert {e["alert"] for e in raised} == {
        "nonfinite_loss", "loss_spike", "grad_explosion", "label_shift"
    }
    assert {e["alert"] for e in cleared} == {
        "nonfinite_loss", "loss_spike", "grad_explosion", "label_shift"
    }
    # the health alerts thread into the per-worker summary
    text = pm.render_text(
        report["timeline"], report["summary"], report["dumps"], {}
    )
    assert "nonfinite_loss" in text
