"""Observability subsystem (ISSUE 2): registry semantics, Prometheus
exposition, health/readiness endpoints, RPC interceptors on a live
in-process master<->worker channel, and the trace-merge round trip."""

import json
import sys
import urllib.request

import numpy as np
import pytest

from elasticdl_tpu.observability import metrics as obs_metrics
from elasticdl_tpu.observability import trace
from elasticdl_tpu.observability.http_server import ObservabilityServer
from elasticdl_tpu.observability.metrics import Registry


def _get(url):
    try:
        response = urllib.request.urlopen(url, timeout=5)
        return response.status, response.read().decode()
    except urllib.error.HTTPError as e:
        return e.code, e.read().decode()


# ---------------------------------------------------------------------------
# registry semantics


def test_counter_labels_accumulate_independently():
    reg = Registry(enabled=True)
    c = reg.counter("reqs_total", "requests", ("method", "code"))
    c.labels(method="get_task", code="OK").inc()
    c.labels(method="get_task", code="OK").inc(2)
    c.labels(method="get_task", code="UNAVAILABLE").inc()
    assert c.get("get_task", "OK") == 3
    assert c.get("get_task", "UNAVAILABLE") == 1
    with pytest.raises(ValueError):
        c.labels(method="only-one-label")


def test_counter_rejects_decrement():
    reg = Registry(enabled=True)
    c = reg.counter("ups_total", "u")
    with pytest.raises((TypeError, ValueError)):
        c.dec()


def test_gauge_set_function_reads_live_state():
    reg = Registry(enabled=True)
    state = {"depth": 0}
    g = reg.gauge("queue_depth", "d")
    g.set_function(lambda: state["depth"])
    state["depth"] = 7
    assert "queue_depth 7" in reg.render()


def test_histogram_buckets_are_cumulative():
    reg = Registry(enabled=True)
    h = reg.histogram("lat", "latency", ("m",), buckets=(0.1, 1.0, 10.0))
    child = h.labels(m="push")
    for value in (0.05, 0.5, 0.5, 5.0, 50.0):
        child.observe(value)
    text = reg.render()
    assert 'lat_bucket{m="push",le="0.1"} 1' in text
    assert 'lat_bucket{m="push",le="1"} 3' in text
    assert 'lat_bucket{m="push",le="10"} 4' in text
    assert 'lat_bucket{m="push",le="+Inf"} 5' in text
    assert 'lat_count{m="push"} 5' in text
    assert h.get_count("push") == 5


def test_registry_get_or_create_is_idempotent():
    reg = Registry(enabled=True)
    a = reg.counter("same", "x", ("l",))
    b = reg.counter("same", "x", ("l",))
    assert a is b
    with pytest.raises(ValueError):
        reg.counter("same", "x", ("other",))


def test_disabled_registry_is_noop():
    reg = Registry(enabled=False)
    c = reg.counter("nope_total", "n", ("l",))
    c.labels(l="x").inc()
    c.inc(5)
    g = reg.gauge("g", "g")
    g.set(3)
    h = reg.histogram("h", "h")
    h.observe(1.0)
    assert c is obs_metrics.NOOP and g is obs_metrics.NOOP
    assert reg.render() == ""


def test_metrics_disabled_without_knobs(monkeypatch):
    monkeypatch.delenv("EDL_METRICS", raising=False)
    monkeypatch.delenv("EDL_METRICS_PORT", raising=False)
    assert not obs_metrics.metrics_enabled()
    monkeypatch.setenv("EDL_METRICS_PORT", "9090")
    assert obs_metrics.metrics_enabled()
    monkeypatch.setenv("EDL_METRICS", "0")  # explicit off wins
    assert not obs_metrics.metrics_enabled()


def test_exposition_format_golden():
    reg = Registry(enabled=True)
    c = reg.counter("edl_reqs_total", "Requests served", ("code",))
    c.labels(code="OK").inc(2)
    g = reg.gauge("edl_depth", "Queue depth")
    g.set(3)
    h = reg.histogram("edl_lat_seconds", "Latency", buckets=(0.5,))
    h.observe(0.25)
    assert reg.render() == (
        "# HELP edl_depth Queue depth\n"
        "# TYPE edl_depth gauge\n"
        "edl_depth 3\n"
        "# HELP edl_lat_seconds Latency\n"
        "# TYPE edl_lat_seconds histogram\n"
        'edl_lat_seconds_bucket{le="0.5"} 1\n'
        'edl_lat_seconds_bucket{le="+Inf"} 1\n'
        "edl_lat_seconds_sum 0.25\n"
        "edl_lat_seconds_count 1\n"
        "# HELP edl_reqs_total Requests served\n"
        "# TYPE edl_reqs_total counter\n"
        'edl_reqs_total{code="OK"} 2\n'
    )


def test_render_survives_failing_and_nonfinite_callback_gauges():
    """A broken callback gauge must not take /metrics down: its value
    renders as NaN (and explicit non-finite sets render, not raise)."""
    reg = Registry(enabled=True)
    reg.gauge("broken", "b").set_function(lambda: 1 / 0)
    reg.gauge("neg_inf", "n").set(float("-inf"))
    text = reg.render()
    assert "broken NaN" in text
    assert "neg_inf -Inf" in text


def test_label_values_are_escaped():
    reg = Registry(enabled=True)
    c = reg.counter("esc_total", "e", ("path",))
    c.labels(path='a"b\\c\nd').inc()
    assert 'esc_total{path="a\\"b\\\\c\\nd"} 1' in reg.render()


# ---------------------------------------------------------------------------
# health endpoints


def test_healthz_readyz_role_transitions():
    reg = Registry(enabled=True)
    server = ObservabilityServer("ps-0", 0, registry=reg).start()
    try:
        ready = {"model": False}
        server.add_readiness_check("model_initialized",
                                   lambda: ready["model"])
        base = "http://localhost:%d" % server.port
        assert _get(base + "/healthz")[0] == 200
        status, body = _get(base + "/readyz")
        assert status == 503 and "model_initialized" in body
        ready["model"] = True  # the role milestone flips
        assert _get(base + "/readyz")[0] == 200
        status, body = _get(base + "/metrics")
        assert status == 200
        assert 'edl_up{role="ps-0"} 1' in body
        assert _get(base + "/nope")[0] == 404
    finally:
        server.stop()


def test_raising_readiness_check_is_unready():
    reg = Registry(enabled=True)
    server = ObservabilityServer("w", 0, registry=reg)
    server.add_readiness_check("boom", lambda: 1 / 0)
    ok, failing = server.readiness()
    assert not ok and failing == ["boom"]


# ---------------------------------------------------------------------------
# RPC interceptors on a live in-process master<->worker channel


@pytest.fixture
def live_metrics(monkeypatch):
    """Flip the process-global registry to enabled for the duration of
    the test, restoring the disabled default afterwards."""
    from elasticdl_tpu.observability import grpc_metrics

    monkeypatch.setenv("EDL_METRICS", "1")
    obs_metrics.reset_default_registry()
    monkeypatch.setattr(grpc_metrics, "_client_cache", (None, None))
    yield obs_metrics.default_registry()
    obs_metrics.reset_default_registry()


def test_interceptors_count_live_master_rpcs(live_metrics):
    from elasticdl_tpu.common.grpc_utils import build_server, find_free_port
    from elasticdl_tpu.master.servicer import MasterServicer
    from elasticdl_tpu.master.task_dispatcher import TaskDispatcher
    from elasticdl_tpu.proto.services import add_master_servicer_to_server
    from elasticdl_tpu.worker.master_client import MasterClient

    dispatcher = TaskDispatcher({"s": (0, 64)}, records_per_task=32)
    server = build_server()
    add_master_servicer_to_server(MasterServicer(dispatcher), server)
    port = find_free_port()
    server.add_insecure_port("localhost:%d" % port)
    server.start()
    try:
        mc = MasterClient("localhost:%d" % port, worker_id=0)
        assert mc.reset_worker() == mc.incarnation > 0
        task = mc.get_task()
        assert task.task_id != 0
        mc.report_task_result(task.task_id)

        text = live_metrics.render()
        for series in (
            'edl_grpc_server_handled_total{service="Master",'
            'method="get_task",code="OK"} 1',
            'edl_grpc_client_handled_total{service="Master",'
            'method="get_task",code="OK"} 1',
            'edl_grpc_server_latency_seconds_count{service="Master",'
            'method="get_task"} 1',
            'edl_grpc_client_latency_seconds_count{service="Master",'
            'method="get_task"} 1',
        ):
            assert series in text, series
        # every Master AND Pserver method's latency histogram is
        # pre-registered (zero-count series are part of the contract)
        from elasticdl_tpu.proto import services

        for method in list(services._MASTER_METHODS) + list(
            services._PSERVER_METHODS
        ):
            assert (
                'edl_grpc_client_latency_seconds_count' in text
                and 'method="%s"' % method in text
            ), method
    finally:
        server.stop(0)


def test_client_interceptor_counts_deadline_exceeded(live_metrics):
    """DEADLINE_EXCEEDED is a visible counter, not just a log line:
    point a client at a port nobody answers quickly enough."""
    import grpc

    from elasticdl_tpu.observability.grpc_metrics import (
        instrument_channel,
    )
    from elasticdl_tpu.proto import elasticdl_tpu_pb2 as pb
    from elasticdl_tpu.proto.services import MasterStub

    channel = instrument_channel(
        grpc.insecure_channel("localhost:1")  # nothing listens
    )
    stub = MasterStub(channel)
    with pytest.raises(grpc.RpcError):
        stub.get_task(pb.GetTaskRequest(worker_id=0), timeout=0.2)
    counter = live_metrics.get("edl_grpc_client_handled_total")
    assert (
        counter.get("Master", "get_task", "UNAVAILABLE")
        + counter.get("Master", "get_task", "DEADLINE_EXCEEDED")
    ) >= 1


def test_uninstrumented_channel_when_disabled(monkeypatch):
    import grpc

    from elasticdl_tpu.observability.grpc_metrics import (
        instrument_channel, server_interceptors,
    )

    monkeypatch.delenv("EDL_METRICS", raising=False)
    monkeypatch.delenv("EDL_METRICS_PORT", raising=False)
    channel = grpc.insecure_channel("localhost:1")
    assert instrument_channel(channel) is channel
    assert server_interceptors() == ()


# ---------------------------------------------------------------------------
# cross-role trace + merge round trip


def test_trace_merge_round_trip(tmp_path, monkeypatch):
    monkeypatch.setenv(trace.TRACE_DIR_ENV, str(tmp_path))
    # emulate the three roles of a run in one process (real roles are
    # separate processes; distinct pids keep their tracks apart)
    master = trace.TraceWriter("master", str(tmp_path), pid=1001)
    worker = trace.TraceWriter("worker-0", str(tmp_path), pid=1002)

    monkeypatch.setattr(trace, "_writer", master)
    trace.complete("dispatch", __import__("time").time() - 0.01,
                   task_id=7, worker_id=0)
    master.flush()

    monkeypatch.setattr(trace, "_writer", worker)
    with trace.task_context(7):
        with trace.span("train_batch", version=1):
            with trace.span("ps_push", version=1):
                pass
    worker.flush()
    monkeypatch.setattr(trace, "_writer", None)

    sys.path.insert(0, "scripts")
    try:
        import merge_trace
    finally:
        sys.path.pop(0)
    merged, names = merge_trace.merge(str(tmp_path))
    assert len(names) == 2
    events = merged["traceEvents"]
    # Perfetto-loadable: valid JSON with the traceEvents array shape
    json.loads(json.dumps(merged))
    spans = [e for e in events if e.get("ph") == "X"]
    task7 = [e for e in spans if e["args"].get("task_id") == 7]
    assert {e["name"] for e in task7} == {
        "dispatch", "train_batch", "ps_push"
    }
    # dispatch (master pid) and train/push (worker pid) line up on one
    # timeline, correlated by task_id through flow events
    assert {e["pid"] for e in task7} == {1001, 1002}
    flows = [e for e in events if e.get("ph") in ("s", "t", "f")]
    assert [f["ph"] for f in flows] == ["s", "t", "f"]
    assert all(f["id"] == "7" for f in flows)
    # the span thread-local context propagated into the nested ps_push
    push = next(e for e in spans if e["name"] == "ps_push")
    assert push["args"]["task_id"] == 7


def test_span_is_inert_without_trace_dir(monkeypatch):
    monkeypatch.setattr(trace, "_writer", None)
    with trace.span("nothing", task_id=1):
        pass
    trace.instant("nope")
    trace.complete("nope", 0.0)
    assert not trace.enabled()


# ---------------------------------------------------------------------------
# role wiring: PS readiness milestone + master dispatcher gauges


def _ps_servicer():
    from elasticdl_tpu.ps.embedding_store import create_store
    from elasticdl_tpu.ps.servicer import PserverServicer

    store = create_store(seed=0, prefer_native=False)
    store.set_optimizer("sgd", lr=1.0)
    return PserverServicer(store, use_async=True)


def test_ps_model_initialized_transitions():
    from elasticdl_tpu.proto import elasticdl_tpu_pb2 as pb

    servicer = _ps_servicer()
    assert not servicer.model_initialized()
    infos = pb.Model()
    infos.embedding_table_infos.add(name="emb", dim=4, initializer="0.05")
    servicer.push_embedding_table_infos(infos)
    assert servicer.model_initialized()


def test_ps_dense_init_also_flips_ready():
    from elasticdl_tpu.common.tensor_utils import ndarray_to_blob
    from elasticdl_tpu.proto import elasticdl_tpu_pb2 as pb

    servicer = _ps_servicer()
    assert not servicer.model_initialized()
    request = pb.Model(version=0)
    ndarray_to_blob(np.ones((2, 2), np.float32),
                    request.dense_parameters["w"])
    servicer.push_model(request)
    assert servicer.model_initialized()


def test_dispatcher_stats_track_lifecycle():
    from elasticdl_tpu.master.task_dispatcher import TaskDispatcher

    dispatcher = TaskDispatcher({"s": (0, 64)}, records_per_task=32)
    stats = dispatcher.stats()
    assert stats["pending"] == {"training": 2}
    assert stats["queue_depth"] == {"training": 2, "evaluation": 0}

    task = dispatcher.get(worker_id=0)
    stats = dispatcher.stats()
    assert stats["pending"] == {"training": 1}
    assert stats["doing"] == {"training": 1}

    dispatcher.report(task.task_id, success=True, worker_id=0)
    stats = dispatcher.stats()
    assert stats["done"] == {"training": 1}
    assert stats["doing"] == {}


def test_timing_bridge_feeds_phase_metrics(monkeypatch):
    monkeypatch.setenv("EDL_METRICS", "1")
    monkeypatch.delenv("EDL_TIMING", raising=False)
    obs_metrics.reset_default_registry()
    try:
        from elasticdl_tpu.common.timing_utils import Timing

        timing = Timing()
        assert not timing.enabled  # EDL_TIMING logging stays off
        t0 = timing.start()
        timing.end_record("batch_process", t0)
        assert timing.last_seconds["batch_process"] >= 0
        text = obs_metrics.default_registry().render()
        assert (
            'edl_phase_seconds_count{phase="batch_process"} 1' in text
        )
        assert "edl_step_time_seconds" in text
    finally:
        obs_metrics.reset_default_registry()
