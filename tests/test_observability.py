"""Observability subsystem (ISSUE 2 + the ISSUE 3 flight recorder):
registry semantics, Prometheus exposition, health/readiness endpoints,
RPC interceptors on a live in-process master<->worker channel, the
trace-merge round trip, the structured event journal, and the master's
fleet telemetry + anomaly detectors behind /statusz and /alerts."""

import json
import sys
import urllib.request

import numpy as np
import pytest

from elasticdl_tpu.observability import events
from elasticdl_tpu.observability import metrics as obs_metrics
from elasticdl_tpu.observability import trace
from elasticdl_tpu.observability.http_server import ObservabilityServer
from elasticdl_tpu.observability.metrics import Registry


def _get(url):
    try:
        response = urllib.request.urlopen(url, timeout=5)
        return response.status, response.read().decode()
    except urllib.error.HTTPError as e:
        return e.code, e.read().decode()


# ---------------------------------------------------------------------------
# registry semantics


def test_counter_labels_accumulate_independently():
    reg = Registry(enabled=True)
    c = reg.counter("reqs_total", "requests", ("method", "code"))
    c.labels(method="get_task", code="OK").inc()
    c.labels(method="get_task", code="OK").inc(2)
    c.labels(method="get_task", code="UNAVAILABLE").inc()
    assert c.get("get_task", "OK") == 3
    assert c.get("get_task", "UNAVAILABLE") == 1
    with pytest.raises(ValueError):
        c.labels(method="only-one-label")


def test_counter_rejects_decrement():
    reg = Registry(enabled=True)
    c = reg.counter("ups_total", "u")
    with pytest.raises((TypeError, ValueError)):
        c.dec()


def test_gauge_set_function_reads_live_state():
    reg = Registry(enabled=True)
    state = {"depth": 0}
    g = reg.gauge("queue_depth", "d")
    g.set_function(lambda: state["depth"])
    state["depth"] = 7
    assert "queue_depth 7" in reg.render()


def test_histogram_buckets_are_cumulative():
    reg = Registry(enabled=True)
    h = reg.histogram("lat", "latency", ("m",), buckets=(0.1, 1.0, 10.0))
    child = h.labels(m="push")
    for value in (0.05, 0.5, 0.5, 5.0, 50.0):
        child.observe(value)
    text = reg.render()
    assert 'lat_bucket{m="push",le="0.1"} 1' in text
    assert 'lat_bucket{m="push",le="1"} 3' in text
    assert 'lat_bucket{m="push",le="10"} 4' in text
    assert 'lat_bucket{m="push",le="+Inf"} 5' in text
    assert 'lat_count{m="push"} 5' in text
    assert h.get_count("push") == 5


def test_registry_get_or_create_is_idempotent():
    reg = Registry(enabled=True)
    a = reg.counter("same", "x", ("l",))
    b = reg.counter("same", "x", ("l",))
    assert a is b
    with pytest.raises(ValueError):
        reg.counter("same", "x", ("other",))


def test_disabled_registry_is_noop():
    reg = Registry(enabled=False)
    c = reg.counter("nope_total", "n", ("l",))
    c.labels(l="x").inc()
    c.inc(5)
    g = reg.gauge("g", "g")
    g.set(3)
    h = reg.histogram("h", "h")
    h.observe(1.0)
    assert c is obs_metrics.NOOP and g is obs_metrics.NOOP
    assert reg.render() == ""


def test_metrics_disabled_without_knobs(monkeypatch):
    monkeypatch.delenv("EDL_METRICS", raising=False)
    monkeypatch.delenv("EDL_METRICS_PORT", raising=False)
    assert not obs_metrics.metrics_enabled()
    monkeypatch.setenv("EDL_METRICS_PORT", "9090")
    assert obs_metrics.metrics_enabled()
    monkeypatch.setenv("EDL_METRICS", "0")  # explicit off wins
    assert not obs_metrics.metrics_enabled()


def test_exposition_format_golden():
    reg = Registry(enabled=True)
    c = reg.counter("edl_reqs_total", "Requests served", ("code",))
    c.labels(code="OK").inc(2)
    g = reg.gauge("edl_depth", "Queue depth")
    g.set(3)
    h = reg.histogram("edl_lat_seconds", "Latency", buckets=(0.5,))
    h.observe(0.25)
    assert reg.render() == (
        "# HELP edl_depth Queue depth\n"
        "# TYPE edl_depth gauge\n"
        "edl_depth 3\n"
        "# HELP edl_lat_seconds Latency\n"
        "# TYPE edl_lat_seconds histogram\n"
        'edl_lat_seconds_bucket{le="0.5"} 1\n'
        'edl_lat_seconds_bucket{le="+Inf"} 1\n'
        "edl_lat_seconds_sum 0.25\n"
        "edl_lat_seconds_count 1\n"
        "# HELP edl_reqs_total Requests served\n"
        "# TYPE edl_reqs_total counter\n"
        'edl_reqs_total{code="OK"} 2\n'
    )


def test_render_survives_failing_and_nonfinite_callback_gauges():
    """A broken callback gauge must not take /metrics down: its value
    renders as NaN (and explicit non-finite sets render, not raise)."""
    reg = Registry(enabled=True)
    reg.gauge("broken", "b").set_function(lambda: 1 / 0)
    reg.gauge("neg_inf", "n").set(float("-inf"))
    text = reg.render()
    assert "broken NaN" in text
    assert "neg_inf -Inf" in text


def test_label_values_are_escaped():
    reg = Registry(enabled=True)
    c = reg.counter("esc_total", "e", ("path",))
    c.labels(path='a"b\\c\nd').inc()
    assert 'esc_total{path="a\\"b\\\\c\\nd"} 1' in reg.render()


# ---------------------------------------------------------------------------
# health endpoints


def test_healthz_readyz_role_transitions():
    reg = Registry(enabled=True)
    server = ObservabilityServer("ps-0", 0, registry=reg).start()
    try:
        ready = {"model": False}
        server.add_readiness_check("model_initialized",
                                   lambda: ready["model"])
        base = "http://localhost:%d" % server.port
        assert _get(base + "/healthz")[0] == 200
        status, body = _get(base + "/readyz")
        assert status == 503 and "model_initialized" in body
        ready["model"] = True  # the role milestone flips
        assert _get(base + "/readyz")[0] == 200
        status, body = _get(base + "/metrics")
        assert status == 200
        assert 'edl_up{role="ps-0"} 1' in body
        assert _get(base + "/nope")[0] == 404
    finally:
        server.stop()


def test_raising_readiness_check_is_unready():
    reg = Registry(enabled=True)
    server = ObservabilityServer("w", 0, registry=reg)
    server.add_readiness_check("boom", lambda: 1 / 0)
    ok, failing = server.readiness()
    assert not ok and failing == ["boom"]


# ---------------------------------------------------------------------------
# http daemon error paths (ISSUE 14): previously only exercised
# incidentally through role smokes


def test_unknown_routes_answer_404_and_server_survives():
    reg = Registry(enabled=True)
    server = ObservabilityServer("w", 0, registry=reg).start()
    try:
        base = "http://localhost:%d" % server.port
        for path in ("/nope", "/metricsz", "/profilez/extra", "/"):
            assert _get(base + path)[0] == 404, path
        # 404s never take the daemon down
        assert _get(base + "/healthz")[0] == 200
    finally:
        server.stop()


def test_busy_port_degrades_to_no_server(caplog):
    """maybe_start on an occupied port returns None instead of raising:
    telemetry is best-effort, a port collision must not kill the job."""
    import socket

    from elasticdl_tpu.observability import http_server

    holder = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
    holder.bind(("0.0.0.0", 0))
    holder.listen(1)
    busy_port = holder.getsockname()[1]
    try:
        assert http_server.maybe_start("w", cli_port=busy_port) is None
    finally:
        holder.close()


def test_raising_json_handler_answers_500_and_daemon_survives():
    reg = Registry(enabled=True)
    server = ObservabilityServer("master", 0, registry=reg).start()
    calls = {"n": 0}

    def flaky():
        calls["n"] += 1
        if calls["n"] == 1:
            raise RuntimeError("snapshot source broke")
        return {"ok": calls["n"]}

    server.add_json_handler("/statusz", flaky)
    try:
        base = "http://localhost:%d" % server.port
        status, body = _get(base + "/statusz")
        assert status == 500 and "snapshot source broke" in body
        # the handler thread died with the request, not the daemon:
        # probes still answer and the next handler call succeeds
        assert _get(base + "/healthz")[0] == 200
        status, body = _get(base + "/statusz")
        assert status == 200 and json.loads(body) == {"ok": 2}
        assert _get(base + "/metrics")[0] == 200
    finally:
        server.stop()


# ---------------------------------------------------------------------------
# RPC interceptors on a live in-process master<->worker channel


@pytest.fixture
def live_metrics(monkeypatch):
    """Flip the process-global registry to enabled for the duration of
    the test, restoring the disabled default afterwards."""
    from elasticdl_tpu.observability import grpc_metrics

    monkeypatch.setenv("EDL_METRICS", "1")
    obs_metrics.reset_default_registry()
    monkeypatch.setattr(grpc_metrics, "_client_cache", (None, None))
    yield obs_metrics.default_registry()
    obs_metrics.reset_default_registry()


def test_interceptors_count_live_master_rpcs(live_metrics):
    from elasticdl_tpu.common.grpc_utils import build_server, find_free_port
    from elasticdl_tpu.master.servicer import MasterServicer
    from elasticdl_tpu.master.task_dispatcher import TaskDispatcher
    from elasticdl_tpu.proto.services import add_master_servicer_to_server
    from elasticdl_tpu.worker.master_client import MasterClient

    dispatcher = TaskDispatcher({"s": (0, 64)}, records_per_task=32)
    server = build_server()
    add_master_servicer_to_server(MasterServicer(dispatcher), server)
    port = find_free_port()
    server.add_insecure_port("localhost:%d" % port)
    server.start()
    try:
        mc = MasterClient("localhost:%d" % port, worker_id=0)
        assert mc.reset_worker() == mc.incarnation > 0
        task = mc.get_task()
        assert task.task_id != 0
        mc.report_task_result(task.task_id)

        text = live_metrics.render()
        for series in (
            'edl_grpc_server_handled_total{service="Master",'
            'method="get_task",code="OK"} 1',
            'edl_grpc_client_handled_total{service="Master",'
            'method="get_task",code="OK"} 1',
            'edl_grpc_server_latency_seconds_count{service="Master",'
            'method="get_task"} 1',
            'edl_grpc_client_latency_seconds_count{service="Master",'
            'method="get_task"} 1',
        ):
            assert series in text, series
        # every Master AND Pserver method's latency histogram is
        # pre-registered (zero-count series are part of the contract)
        from elasticdl_tpu.proto import services

        for method in list(services._MASTER_METHODS) + list(
            services._PSERVER_METHODS
        ):
            assert (
                'edl_grpc_client_latency_seconds_count' in text
                and 'method="%s"' % method in text
            ), method
    finally:
        server.stop(0)


def test_client_interceptor_counts_deadline_exceeded(live_metrics):
    """DEADLINE_EXCEEDED is a visible counter, not just a log line:
    point a client at a port nobody answers quickly enough."""
    import grpc

    from elasticdl_tpu.observability.grpc_metrics import (
        instrument_channel,
    )
    from elasticdl_tpu.proto import elasticdl_tpu_pb2 as pb
    from elasticdl_tpu.proto.services import MasterStub

    channel = instrument_channel(
        grpc.insecure_channel("localhost:1")  # nothing listens
    )
    stub = MasterStub(channel)
    with pytest.raises(grpc.RpcError):
        stub.get_task(pb.GetTaskRequest(worker_id=0), timeout=0.2)
    counter = live_metrics.get("edl_grpc_client_handled_total")
    assert (
        counter.get("Master", "get_task", "UNAVAILABLE")
        + counter.get("Master", "get_task", "DEADLINE_EXCEEDED")
    ) >= 1


def test_uninstrumented_channel_when_disabled(monkeypatch):
    import grpc

    from elasticdl_tpu.observability.grpc_metrics import (
        instrument_channel, server_interceptors,
    )

    monkeypatch.delenv("EDL_METRICS", raising=False)
    monkeypatch.delenv("EDL_METRICS_PORT", raising=False)
    channel = grpc.insecure_channel("localhost:1")
    assert instrument_channel(channel) is channel
    assert server_interceptors() == ()


# ---------------------------------------------------------------------------
# cross-role trace + merge round trip


def test_trace_merge_round_trip(tmp_path, monkeypatch):
    monkeypatch.setenv(trace.TRACE_DIR_ENV, str(tmp_path))
    # emulate the three roles of a run in one process (real roles are
    # separate processes; distinct pids keep their tracks apart)
    master = trace.TraceWriter("master", str(tmp_path), pid=1001)
    worker = trace.TraceWriter("worker-0", str(tmp_path), pid=1002)

    monkeypatch.setattr(trace, "_writer", master)
    trace.complete("dispatch", __import__("time").time() - 0.01,
                   task_id=7, worker_id=0)
    master.flush()

    monkeypatch.setattr(trace, "_writer", worker)
    with trace.task_context(7):
        with trace.span("train_batch", version=1):
            with trace.span("ps_push", version=1):
                pass
    worker.flush()
    monkeypatch.setattr(trace, "_writer", None)

    sys.path.insert(0, "scripts")
    try:
        import merge_trace
    finally:
        sys.path.pop(0)
    merged, names = merge_trace.merge(str(tmp_path))
    assert len(names) == 2
    events = merged["traceEvents"]
    # Perfetto-loadable: valid JSON with the traceEvents array shape
    json.loads(json.dumps(merged))
    spans = [e for e in events if e.get("ph") == "X"]
    task7 = [e for e in spans if e["args"].get("task_id") == 7]
    assert {e["name"] for e in task7} == {
        "dispatch", "train_batch", "ps_push"
    }
    # dispatch (master pid) and train/push (worker pid) line up on one
    # timeline, correlated by task_id through flow events
    assert {e["pid"] for e in task7} == {1001, 1002}
    flows = [e for e in events if e.get("ph") in ("s", "t", "f")]
    assert [f["ph"] for f in flows] == ["s", "t", "f"]
    assert all(f["id"] == "7" for f in flows)
    # the span thread-local context propagated into the nested ps_push
    push = next(e for e in spans if e["name"] == "ps_push")
    assert push["args"]["task_id"] == 7


def test_span_is_inert_without_trace_dir(monkeypatch):
    monkeypatch.setattr(trace, "_writer", None)
    with trace.span("nothing", task_id=1):
        pass
    trace.instant("nope")
    trace.complete("nope", 0.0)
    assert not trace.enabled()


# ---------------------------------------------------------------------------
# structured event journal (ISSUE 3 flight recorder)


@pytest.fixture
def journal_dir(tmp_path, monkeypatch):
    monkeypatch.setenv(events.EVENTS_DIR_ENV, str(tmp_path))
    monkeypatch.setenv(events.JOB_NAME_ENV, "test-job")
    yield tmp_path
    events._reset_for_tests()


def _read_journal(path):
    with open(path, encoding="utf-8") as f:
        return [json.loads(line) for line in f if line.strip()]


def test_journal_is_write_through_ndjson(journal_dir):
    """Every emit is on disk before the call returns — the SIGKILL
    guarantee: no flush() needed to observe the lines."""
    journal = events.configure("worker-0")
    events.emit("role_start", worker=0, epoch=7)
    events.emit("task_dispatch", task=41, worker=0)
    records = _read_journal(journal.path)
    assert [r["event"] for r in records] == ["role_start",
                                             "task_dispatch"]
    first = records[0]
    assert first["role"] == "worker-0" and first["job"] == "test-job"
    assert first["seq"] == 1 and first["ts"] > 0
    assert records[1]["task"] == 41


def test_emit_unknown_event_type_raises(journal_dir):
    events.configure("worker-0")
    with pytest.raises(ValueError):
        events.emit("not_a_real_event")


def test_emit_survives_reentrant_write(journal_dir):
    """The SIGTERM drain hook emits while the interrupted thread may be
    inside this journal's own file.write(); Python raises RuntimeError
    ('reentrant call') on the nested write. emit() must swallow it —
    losing one line beats crashing the drain, and the record is still
    in the ring for the crash dump."""
    journal = events.configure("worker-0")
    events.emit("role_start", worker=0)  # opens the file

    class ReentrantFile:
        def write(self, line):
            raise RuntimeError("reentrant call inside TextIOWrapper")

        def flush(self):
            raise RuntimeError("reentrant call inside TextIOWrapper")

        def close(self):
            pass

    journal._file = ReentrantFile()
    events.emit("worker_draining", worker=0, reason="sigterm")
    assert journal._ring[-1]["event"] == "worker_draining"


def test_journal_inert_without_events_dir(monkeypatch, tmp_path):
    monkeypatch.delenv(events.EVENTS_DIR_ENV, raising=False)
    assert events.configure("worker-0") is None
    assert not events.enabled()
    events.emit("role_start")  # no-op, no crash, nothing written
    events.flush()
    assert events.dump("whatever") is None
    assert not list(tmp_path.iterdir())


def test_ring_dump_is_bounded_and_first_reason_wins(journal_dir):
    journal = events.configure("ps-0")
    for i in range(events._RING_SIZE + 50):
        events.emit("round_fill", version=i, fill=1, worker=0)
    path = events.dump("sigterm")
    assert path == journal.dump_path
    # a later crash path must not overwrite the original cause
    assert events.dump("uncaught:RuntimeError") is None
    with open(path, encoding="utf-8") as f:
        payload = json.load(f)
    assert payload["reason"] == "sigterm"
    assert payload["role"] == "ps-0"
    assert len(payload["events"]) == events._RING_SIZE
    # the ring holds the LAST K events
    assert payload["events"][-1]["version"] == events._RING_SIZE + 49


def test_excepthook_dumps_ring(journal_dir, monkeypatch):
    journal = events.configure("worker-2")
    events.emit("role_start", worker=2)
    monkeypatch.setattr(events, "_hooks_installed", False)
    calls = []
    monkeypatch.setattr(sys, "excepthook",
                        lambda *a: calls.append(a))
    events.install_crash_hooks()
    try:
        raise RuntimeError("boom")
    except RuntimeError:
        sys.excepthook(*sys.exc_info())
    assert calls, "original excepthook must still run"
    with open(journal.dump_path, encoding="utf-8") as f:
        assert json.load(f)["reason"] == "uncaught:RuntimeError"


# ---------------------------------------------------------------------------
# fleet telemetry + anomaly detectors (master/fleet.py)


def _blob(role="", **kw):
    from elasticdl_tpu.proto import elasticdl_tpu_pb2 as pb

    return pb.TelemetryBlob(role=role, **kw)


def _fleet(**kw):
    from elasticdl_tpu.master.fleet import FleetMonitor

    defaults = dict(
        straggler_factor=3.0, dead_air_secs=60.0,
        stuck_round_secs=60.0, version_lag_max=100,
    )
    defaults.update(kw)
    return FleetMonitor(**defaults)


def test_straggler_fires_only_against_a_fleet():
    fleet = _fleet()
    fleet.observe(0, _blob(step_time_ewma=0.1))
    fleet.observe(1, _blob(step_time_ewma=0.9))
    assert fleet.evaluate() == []  # two workers: no median to trust
    fleet.observe(2, _blob(step_time_ewma=0.1))
    firing = fleet.evaluate()
    assert [a["alert"] for a in firing] == ["straggler"]
    assert firing[0]["worker_id"] == 1
    # the straggler recovers -> the alert clears
    fleet.observe(1, _blob(step_time_ewma=0.12))
    assert fleet.evaluate() == []


def test_dead_air_fires_after_window_and_clears_on_forget():
    fleet = _fleet(dead_air_secs=0.05)
    fleet.observe(0, _blob(role="worker-0"))
    import time

    time.sleep(0.1)
    firing = fleet.evaluate()
    assert [a["alert"] for a in firing] == ["dead_air"]
    assert firing[0]["role"] == "worker-0"
    fleet.forget(0)
    assert fleet.evaluate() == []


def test_eviction_forces_dead_air_tombstone(monkeypatch):
    """A fast-task job's 3x-average task timeout can evict a dead
    worker BEFORE the dead-air window elapses (observed live: avg task
    0.25 s -> eviction at 0.75 s vs a 3 s window). The eviction must
    force the transition — counter + journal + a tombstone on /alerts
    — never silently erase the story."""
    monkeypatch.setenv("EDL_METRICS", "1")
    obs_metrics.reset_default_registry()
    try:
        fleet = _fleet(dead_air_secs=60.0)  # window far in the future
        fleet.observe(1, _blob(role="worker-1"))
        assert fleet.evaluate() == []
        fleet.mark_dead(1)  # task monitor eviction beat the window
        firing = fleet.evaluate()
        assert [a["alert"] for a in firing] == ["dead_air"]
        assert firing[0]["evicted"] is True
        assert firing[0]["role"] == "worker-1"
        counter = obs_metrics.default_registry().get(
            "edl_master_alerts_total"
        )
        assert counter.get("dead_air") == 1
        # the tombstone persists while the worker stays gone...
        assert fleet.evaluate(), "tombstone must not self-clear"
        # ...and clears when a relaunch re-registers the worker_id
        fleet.observe(1, _blob(role="worker-1"))
        assert fleet.evaluate() == []
    finally:
        obs_metrics.reset_default_registry()


def test_stuck_round_fires_when_fill_stalls():
    fleet = _fleet(stuck_round_secs=0.05)
    fleet.observe(-1, _blob(role="ps-0", round_buffer_fill=2,
                            model_version=5))
    import time

    time.sleep(0.1)
    assert [a["alert"] for a in fleet.evaluate()] == ["stuck_round"]
    # the round completes (fill empties, version advances): clears
    fleet.observe(-1, _blob(role="ps-0", round_buffer_fill=0,
                            model_version=6))
    assert fleet.evaluate() == []


def test_version_lag_runaway_fires():
    fleet = _fleet(version_lag_max=10)
    fleet.observe(-1, _blob(role="ps-0", version_lag=50))
    assert [a["alert"] for a in fleet.evaluate()] == ["version_lag"]


def test_alert_transitions_bump_counter_once(monkeypatch):
    monkeypatch.setenv("EDL_METRICS", "1")
    obs_metrics.reset_default_registry()
    try:
        fleet = _fleet(version_lag_max=10)
        fleet.observe(-1, _blob(role="ps-0", version_lag=50))
        fleet.evaluate()
        fleet.evaluate()  # still firing: edge-triggered, no re-count
        counter = obs_metrics.default_registry().get(
            "edl_master_alerts_total"
        )
        assert counter.get("version_lag") == 1
        text = obs_metrics.default_registry().render()
        assert "edl_master_alerts_firing 1" in text
    finally:
        obs_metrics.reset_default_registry()


def test_alert_clear_and_reraise_cycle_counts_and_journals(
    monkeypatch, tmp_path
):
    """Satellite (ISSUE 15): raise→clear→re-raise cycles. Only raise
    paths were asserted before; this pins the full cycle — the counter
    bumps once per RAISE transition (twice across the cycle), never on
    clear, and BOTH edges land in the journal."""
    monkeypatch.setenv("EDL_METRICS", "1")
    monkeypatch.setenv("EDL_EVENTS_DIR", str(tmp_path))
    obs_metrics.reset_default_registry()
    events._reset_for_tests()
    events.configure("master")
    try:
        fleet = _fleet(version_lag_max=10)
        # raise
        fleet.observe(-1, _blob(role="ps-0", version_lag=50))
        assert [a["alert"] for a in fleet.evaluate()] == ["version_lag"]
        # clear (lag recovers)
        fleet.observe(-1, _blob(role="ps-0", version_lag=0))
        assert fleet.evaluate() == []
        # re-raise
        fleet.observe(-1, _blob(role="ps-0", version_lag=80))
        assert [a["alert"] for a in fleet.evaluate()] == ["version_lag"]
        counter = obs_metrics.default_registry().get(
            "edl_master_alerts_total"
        )
        assert counter.get("version_lag") == 2  # one per raise, none per clear
        lines = []
        for path in tmp_path.glob("*.events.ndjson"):
            with open(path, encoding="utf-8") as f:
                lines += [json.loads(l) for l in f if l.strip()]
        edges = [
            (e["event"], e["alert"]) for e in lines
            if e["event"] in ("alert_raised", "alert_cleared")
        ]
        assert edges == [
            ("alert_raised", "version_lag"),
            ("alert_cleared", "version_lag"),
            ("alert_raised", "version_lag"),
        ], edges
    finally:
        obs_metrics.reset_default_registry()
        events._reset_for_tests()


def test_straggler_clear_and_reraise_cycle(monkeypatch):
    """The straggler detector's clear edge (recovery) and re-raise
    both transition correctly — cycle coverage for a second detector
    family (fleet-relative, not threshold-absolute)."""
    monkeypatch.setenv("EDL_METRICS", "1")
    obs_metrics.reset_default_registry()
    try:
        fleet = _fleet(straggler_factor=2.0)
        for wid, ewma in ((0, 0.1), (1, 0.1), (2, 0.9)):
            fleet.observe(wid, _blob(step_time_ewma=ewma))
        assert [a["alert"] for a in fleet.evaluate()] == ["straggler"]
        fleet.observe(2, _blob(step_time_ewma=0.11))  # recovers
        assert fleet.evaluate() == []
        fleet.observe(2, _blob(step_time_ewma=0.95))  # degrades again
        assert [a["alert"] for a in fleet.evaluate()] == ["straggler"]
        counter = obs_metrics.default_registry().get(
            "edl_master_alerts_total"
        )
        assert counter.get("straggler") == 2
    finally:
        obs_metrics.reset_default_registry()


# ---------------------------------------------------------------------------
# training-health detectors (ISSUE 15)


def test_health_detectors_raise_and_clear(monkeypatch):
    """nonfinite_loss / loss_spike / grad_explosion: raise on recent
    counter movement (or a live streak), clear after the recency
    window, re-raise on the next movement."""
    import time

    monkeypatch.setenv("EDL_METRICS", "1")
    obs_metrics.reset_default_registry()
    try:
        fleet = _fleet(health_alert_secs=0.2)
        fleet.observe(0, _blob(
            role="worker-0", health_nonfinite_batches=1,
            health_nonfinite_streak=1,
        ))
        fleet.observe(1, _blob(
            role="worker-1", health_loss_spikes=1,
            health_grad_explosions=1,
        ))
        kinds = {a["alert"] for a in fleet.evaluate()}
        assert kinds == {
            "nonfinite_loss", "loss_spike", "grad_explosion"
        }, kinds
        # a LIVE streak keeps nonfinite_loss firing past the window
        time.sleep(0.3)
        fleet.observe(0, _blob(
            role="worker-0", health_nonfinite_batches=1,
            health_nonfinite_streak=1,
        ))
        kinds = {a["alert"] for a in fleet.evaluate()}
        assert kinds == {"nonfinite_loss"}, kinds
        # streak ends, counters stop moving: everything clears
        fleet.observe(0, _blob(
            role="worker-0", health_nonfinite_batches=1,
        ))
        time.sleep(0.3)
        assert fleet.evaluate() == []
        # re-raise on the next increment
        fleet.observe(1, _blob(
            role="worker-1", health_loss_spikes=2,
            health_grad_explosions=1,
        ))
        assert [a["alert"] for a in fleet.evaluate()] == ["loss_spike"]
        counter = obs_metrics.default_registry().get(
            "edl_master_alerts_total"
        )
        assert counter.get("loss_spike") == 2
        assert counter.get("nonfinite_loss") == 1
        assert counter.get("grad_explosion") == 1
    finally:
        obs_metrics.reset_default_registry()


def test_label_shift_detector_tags_the_window():
    import time

    fleet = _fleet(health_alert_secs=0.2, label_shift_delta=0.1,
                   id_novelty_max=0.8)
    for i in range(6):  # warm the label-rate EWMA
        fleet.observe_stream_window(128 * (i + 1), 0.5, 0.2)
    assert fleet.evaluate() == []
    fleet.observe_stream_window(896, 0.85, 0.2)  # label rate jumps
    firing = fleet.evaluate()
    assert [a["alert"] for a in firing] == ["label_shift"]
    assert firing[0]["watermark"] == 896  # drift attributable to a window
    assert firing[0]["reason"] == "label_rate"
    time.sleep(0.3)  # back in band: clears after the window
    assert fleet.evaluate() == []
    # novelty-rate ceiling is the other trigger
    fleet.observe_stream_window(1024, 0.5, 0.95)
    firing = fleet.evaluate()
    assert firing and firing[0]["reason"] == "id_novelty"


def test_statusz_health_section():
    fleet = _fleet()
    fleet.observe(0, _blob(
        role="worker-0", health_loss_ewma=0.69,
        health_nonfinite_batches=2, health_skipped_batches=1,
    ))
    fleet.observe(-1, _blob(
        role="ps-0", ps_row_norm_p50=0.07, ps_row_norm_p99=1.2,
        ps_dead_row_fraction=0.25, ps_exploding_rows=3,
    ))
    fleet.observe_stream_window(512, 0.4, 0.1)
    body = fleet.snapshot()
    json.dumps(body)  # JSON-ready
    health = body["health"]
    assert health["workers"]["worker-0"]["health_nonfinite_batches"] == 2
    assert health["workers"]["worker-0"]["health_skipped_batches"] == 1
    assert health["ps"]["ps-0"]["ps_exploding_rows"] == 3
    assert health["ps"]["ps-0"]["ps_dead_row_fraction"] == 0.25
    assert health["stream"]["windows"] == 1
    assert body["thresholds"]["health_alert_secs"] == 30.0


def test_snapshot_carries_fleet_and_extras():
    fleet = _fleet()
    fleet.observe(0, _blob(role="worker-0", step_time_ewma=0.25,
                           model_version=12))
    body = fleet.snapshot(extra={"tasks": {"pending": 3}})
    json.dumps(body)  # must be JSON-ready
    entry = body["fleet"]["worker-0"]
    assert entry["step_time_ewma"] == pytest.approx(0.25)
    assert entry["model_version"] == 12
    assert body["tasks"] == {"pending": 3}
    assert body["thresholds"]["straggler_factor"] == 3.0


def test_statusz_and_alerts_served_over_http():
    reg = Registry(enabled=True)
    server = ObservabilityServer("master", 0, registry=reg).start()
    try:
        fleet = _fleet(dead_air_secs=0.01)
        fleet.observe(3, _blob(role="worker-3"))
        server.add_json_handler("/statusz", fleet.snapshot)
        server.add_json_handler("/alerts", fleet.alerts)
        import time

        time.sleep(0.05)
        base = "http://localhost:%d" % server.port
        status, body = _get(base + "/statusz")
        assert status == 200
        snap = json.loads(body)
        assert "worker-3" in snap["fleet"]
        status, body = _get(base + "/alerts")
        assert status == 200
        alerts = json.loads(body)
        assert [a["alert"] for a in alerts] == ["dead_air"]
        # a broken handler degrades to 500, never kills the server
        server.add_json_handler("/boom", lambda: 1 / 0)
        assert _get(base + "/boom")[0] == 500
        assert _get(base + "/healthz")[0] == 200
    finally:
        server.stop()


# ---------------------------------------------------------------------------
# telemetry piggyback: servicer ingestion + worker/PS production


def test_servicer_feeds_fleet_from_piggybacked_blobs():
    from elasticdl_tpu.master.servicer import MasterServicer
    from elasticdl_tpu.master.task_dispatcher import TaskDispatcher
    from elasticdl_tpu.proto import elasticdl_tpu_pb2 as pb

    fleet = _fleet()
    dispatcher = TaskDispatcher({"s": (0, 64)}, records_per_task=32)
    servicer = MasterServicer(dispatcher, fleet_monitor=fleet)
    request = pb.GetTaskRequest(
        worker_id=0,
        telemetry=pb.TelemetryBlob(role="worker-0",
                                   step_time_ewma=0.5),
    )
    servicer.get_task(request)
    # a blob-less RPC is still a liveness sighting
    servicer.report_task_result(
        pb.ReportTaskResultRequest(task_id=1, worker_id=5)
    )
    snap = fleet.snapshot()
    assert snap["fleet"]["worker-0"]["step_time_ewma"] == pytest.approx(
        0.5
    )
    assert "worker-5" in snap["fleet"]


def test_worker_telemetry_blob_reflects_training(tmp_path):
    from elasticdl_tpu.data.readers import RecordIODataReader
    from elasticdl_tpu.master.servicer import MasterServicer
    from elasticdl_tpu.master.task_dispatcher import TaskDispatcher
    from elasticdl_tpu.worker.worker import Worker
    from tests.test_utils import create_mnist_recordio

    class LoopbackClient:
        """In-process MasterClient twin with the telemetry surface."""

        def __init__(self, servicer):
            from elasticdl_tpu.proto import elasticdl_tpu_pb2 as pb

            self._pb = pb
            self._servicer = servicer
            self.worker_id = 0
            self.incarnation = None
            self.telemetry_provider = None

        def _req(self, cls, **kw):
            request = cls(**kw)
            if self.telemetry_provider is not None:
                blob = self.telemetry_provider()
                if blob is not None:
                    request.telemetry.CopyFrom(blob)
            return request

        def get_task(self, task_type=None):
            request = self._req(
                self._pb.GetTaskRequest, worker_id=self.worker_id
            )
            if task_type is not None:
                request.task_type = task_type
            return self._servicer.get_task(request)

        def report_task_result(self, task_id, err_message="",
                               exec_counters=None):
            self._servicer.report_task_result(
                self._req(
                    self._pb.ReportTaskResultRequest,
                    task_id=task_id, err_message=err_message,
                    worker_id=self.worker_id,
                )
            )

        def report_version(self, version):
            pass

        def report_evaluation_metrics(self, *a, **kw):
            pass

        def get_comm_info(self):
            return self._pb.CommInfo(rank=0, world_size=1,
                                     mesh_epoch=0)

    train_dir = tmp_path / "train"
    train_dir.mkdir()
    create_mnist_recordio(str(train_dir / "f0.rec"), num_records=96,
                          seed=0)
    reader = RecordIODataReader(data_dir=str(train_dir))
    fleet = _fleet()
    dispatcher = TaskDispatcher(
        training_shards=reader.create_shards(), records_per_task=32,
    )
    servicer = MasterServicer(dispatcher, fleet_monitor=fleet)
    worker = Worker(
        LoopbackClient(servicer),
        "tests.models.mnist_with_export",
        reader,
        minibatch_size=32,
        wait_sleep_secs=0.05,
    )
    worker.run()
    assert dispatcher.finished()
    snap = fleet.snapshot()
    entry = snap["fleet"]["worker-0"]
    # the piggybacked blobs carried real training telemetry
    assert entry["step_time_ewma"] > 0
    assert entry["examples_per_sec"] > 0
    assert entry["last_task_seconds"] > 0
    assert entry["model_version"] >= 3


def test_ps_telemetry_blob_reports_rates_and_fill():
    servicer = _sync_ps_servicer(grads_to_wait=2)
    first = servicer.telemetry_blob()
    assert first.role == "ps-0" and first.push_rate == 0.0
    # one buffered push: fill=1, rates computed over the window
    servicer.push_gradients(_push_request(version=0, worker_id=1))
    blob = servicer.telemetry_blob()
    assert blob.round_buffer_fill == 1
    assert blob.push_rate > 0
    assert blob.model_version == 0


def _sync_ps_servicer(grads_to_wait=2):
    from elasticdl_tpu.ps.embedding_store import create_store
    from elasticdl_tpu.ps.servicer import PserverServicer

    store = create_store(seed=0, prefer_native=False)
    store.set_optimizer("sgd", lr=0.1)
    store.create_table("emb", 4, init_scale=0.05)
    return PserverServicer(
        store, use_async=False, grads_to_wait=grads_to_wait,
    )


def _push_request(version, worker_id=None):
    from elasticdl_tpu.common.tensor_utils import ndarray_to_blob
    from elasticdl_tpu.proto import elasticdl_tpu_pb2 as pb

    request = pb.PushGradientsRequest()
    request.gradients.version = version
    slices = request.gradients.embedding_tables["emb"]
    ndarray_to_blob(
        np.ones((2, 4), np.float32), slices.concat_tensors
    )
    slices.ids.extend([0, 1])
    if worker_id is not None:
        request.worker_id = worker_id
    return request


def test_sync_round_lifecycle_is_journaled(tmp_path, monkeypatch):
    monkeypatch.setenv(events.EVENTS_DIR_ENV, str(tmp_path))
    journal = events.configure("ps-0")
    try:
        servicer = _sync_ps_servicer(grads_to_wait=2)
        servicer.push_gradients(_push_request(version=0, worker_id=0))
        servicer.push_gradients(_push_request(version=0, worker_id=1))
        # now store version is 1: a version-0 push is stale
        servicer.push_gradients(_push_request(version=0, worker_id=0))
        kinds = [r["event"] for r in _read_journal(journal.path)]
        assert kinds == [
            "round_open", "round_fill", "round_fill", "round_close",
            "stale_push_rejected",
        ]
    finally:
        events._reset_for_tests()


# ---------------------------------------------------------------------------
# role wiring: PS readiness milestone + master dispatcher gauges


def _ps_servicer():
    from elasticdl_tpu.ps.embedding_store import create_store
    from elasticdl_tpu.ps.servicer import PserverServicer

    store = create_store(seed=0, prefer_native=False)
    store.set_optimizer("sgd", lr=1.0)
    return PserverServicer(store, use_async=True)


def test_ps_model_initialized_transitions():
    from elasticdl_tpu.proto import elasticdl_tpu_pb2 as pb

    servicer = _ps_servicer()
    assert not servicer.model_initialized()
    infos = pb.Model()
    infos.embedding_table_infos.add(name="emb", dim=4, initializer="0.05")
    servicer.push_embedding_table_infos(infos)
    assert servicer.model_initialized()


def test_ps_dense_init_also_flips_ready():
    from elasticdl_tpu.common.tensor_utils import ndarray_to_blob
    from elasticdl_tpu.proto import elasticdl_tpu_pb2 as pb

    servicer = _ps_servicer()
    assert not servicer.model_initialized()
    request = pb.Model(version=0)
    ndarray_to_blob(np.ones((2, 2), np.float32),
                    request.dense_parameters["w"])
    servicer.push_model(request)
    assert servicer.model_initialized()


def test_dispatcher_stats_track_lifecycle():
    from elasticdl_tpu.master.task_dispatcher import TaskDispatcher

    dispatcher = TaskDispatcher({"s": (0, 64)}, records_per_task=32)
    stats = dispatcher.stats()
    assert stats["pending"] == {"training": 2}
    assert stats["queue_depth"] == {"training": 2, "evaluation": 0}

    task = dispatcher.get(worker_id=0)
    stats = dispatcher.stats()
    assert stats["pending"] == {"training": 1}
    assert stats["doing"] == {"training": 1}

    dispatcher.report(task.task_id, success=True, worker_id=0)
    stats = dispatcher.stats()
    assert stats["done"] == {"training": 1}
    assert stats["doing"] == {}


def test_timing_bridge_feeds_phase_metrics(monkeypatch):
    monkeypatch.setenv("EDL_METRICS", "1")
    monkeypatch.delenv("EDL_TIMING", raising=False)
    obs_metrics.reset_default_registry()
    try:
        from elasticdl_tpu.common.timing_utils import Timing

        timing = Timing()
        assert not timing.enabled  # EDL_TIMING logging stays off
        t0 = timing.start()
        timing.end_record("batch_process", t0)
        assert timing.last_seconds["batch_process"] >= 0
        text = obs_metrics.default_registry().render()
        assert (
            'edl_phase_seconds_count{phase="batch_process"} 1' in text
        )
        assert "edl_step_time_seconds" in text
    finally:
        obs_metrics.reset_default_registry()
