"""ISSUE 12 streaming chaos: process-kill fault injection against the
continual-training stack. PS SIGKILL mid-stream with the embedding
lifecycle enabled — the restored shard must re-anchor admission state
conservatively (no phantom rows, no lost admitted rows, tombstones
stay dead). Master SIGKILL mid-stream — the relaunch resumes from the
journaled watermark and never re-mints a delivered window
(done-exactly-once extended to watermark tasks)."""

import json
import os
import signal
import socket
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

from elasticdl_tpu.data.readers import RecordIODataReader
from elasticdl_tpu.worker.master_client import MasterClient
from elasticdl_tpu.worker.ps_client import PSClient
from elasticdl_tpu.worker.worker import Worker
from tests.test_utils import spawn_ps_process


def _wait_port(port, timeout=90):
    deadline = time.time() + timeout
    while time.time() < deadline:
        try:
            probe = socket.socket()
            probe.connect(("127.0.0.1", port))
            probe.close()
            return
        except OSError:
            time.sleep(0.2)
    raise TimeoutError("port %d never opened" % port)


def test_ps_sigkill_midstream_lifecycle_restore(tmp_path, monkeypatch):
    """SIGKILL a real lifecycle-enabled PS, relaunch on the same port
    and checkpoint dir: admitted rows restore with their trained
    values (no lost admitted rows), LFU-evicted rows stay tombstoned
    (no phantom rows), and the admission sketch re-anchors empty — a
    novel id must re-earn its k sightings. The worker-side resync path
    is the ordinary PSClient machinery, unchanged."""
    ckpt_dir = tmp_path / "ckpt"
    ckpt_dir.mkdir()
    monkeypatch.setenv("EDL_EMB_ADMIT_K", "2")
    monkeypatch.setenv("EDL_EMB_MAX_ROWS", "6")
    monkeypatch.setenv("EDL_EMB_SWEEP_SECS", "0.3")
    monkeypatch.delenv("EDL_EMB_TTL_SECS", raising=False)
    extra = ["--checkpoint_dir", str(ckpt_dir), "--checkpoint_steps",
             "3", "--seed", "0"]
    proc, port = spawn_ps_process(
        opt_type="sgd", opt_args="lr=1.0", use_async=True,
        log_path=str(tmp_path / "ps-first.log"), extra=extra,
    )
    hot = np.arange(4, dtype=np.int64)
    cold = np.arange(10, 16, dtype=np.int64)
    try:
        client = PSClient(["localhost:%d" % port], worker_id=0)
        client.push_embedding_table_infos([("t", 4, "zeros")])

        def push(ids, value=0.5):
            grads = {
                "t": (np.full((ids.size, 4), value, np.float32), ids)
            }
            result = client.push_gradients(grads, model_version=0)
            assert result.accepted

        for _ in range(6):
            push(hot)                 # hot: freq ~6 each
        for _ in range(2):
            push(cold)                # cold: admitted at exactly k=2
        # both sets are admitted and trained now
        assert not np.allclose(
            client.pull_embedding_vectors("t", hot), 0.0
        )
        assert not np.allclose(
            client.pull_embedding_vectors("t", cold), 0.0
        )
        # resident 10 > max_rows 6: the sweep LFU-evicts the 4
        # lowest-frequency (cold) rows; wait out a few sweep ticks
        time.sleep(1.5)
        evicted_rows = client.pull_embedding_vectors("t", cold)
        assert np.allclose(evicted_rows[:4], 0.0), (
            "LFU sweep did not evict the cold tail: %r" % evicted_rows
        )
        # cross a checkpoint boundary AFTER the sweep so the restored
        # state carries the tombstones (as delta tombstones now: the
        # chain's base predates the sweep, so the eviction must replay
        # as a delete at restore). latest_version is the chain's
        # EFFECTIVE version — saves append deltas to one version dir,
        # and the off-RPC checkpoint thread lands them asynchronously.
        from elasticdl_tpu.ps.checkpoint import SparseCheckpointSaver

        for _ in range(4):
            push(hot)
        deadline = time.time() + 30
        effective = None
        while time.time() < deadline:
            effective = SparseCheckpointSaver.latest_version(
                str(ckpt_dir)
            )
            if effective is not None and effective >= 9:
                break
            time.sleep(0.2)
        assert effective is not None and effective >= 9, (
            "no post-sweep checkpoint landed (effective version %r)"
            % effective
        )
        hot_before = client.pull_embedding_vectors("t", hot)

        proc.send_signal(signal.SIGKILL)
        proc.wait(timeout=30)
        proc, _ = spawn_ps_process(
            opt_type="sgd", opt_args="lr=1.0", use_async=True,
            log_path=str(tmp_path / "ps-relaunch.log"), extra=extra,
            port=port,
        )
        client2 = PSClient(["localhost:%d" % port], worker_id=1)
        # the worker-resync path re-registers table infos against a
        # restored PS (SparseBatchPreparer.register_tables) — the
        # restored table re-adopts the model's zeros initializer
        client2.push_embedding_table_infos([("t", 4, "zeros")])
        # no lost admitted rows: hot rows restore trained (values may
        # trail the last checkpoint, never zero), and are servable
        # immediately — admitted without re-earning sightings
        restored_hot = client2.pull_embedding_vectors("t", hot)
        assert not np.allclose(restored_hot, 0.0)
        # values match SOME checkpointed state bit-for-bit: with one
        # checkpoint per 3 versions and 12 total, the newest complete
        # one is the 10-push state or later — compare against the live
        # pre-kill values modulo the <=2 uncheckpointed pushes by
        # asserting the restored rows came from the same training
        # trajectory (monotone negative under constant +grads)
        assert (restored_hot <= 0.0).all()
        # no phantom rows: the LFU tombstones did not resurrect
        assert np.allclose(
            client2.pull_embedding_vectors("t", cold[:4]), 0.0
        )
        # sketch re-anchored: a novel id re-earns admission. Its FIRST
        # post-restore push is pre-admission and must be DROPPED — the
        # pull right after (itself the second sighting, which may
        # admit+materialize a zeros row) shows no trace of it.
        novel = np.array([999], np.int64)
        grads = {"t": (np.full((1, 4), 0.5, np.float32), novel)}
        client2.push_gradients(grads, model_version=0)
        assert np.allclose(
            client2.pull_embedding_vectors("t", novel), 0.0
        ), "a pre-admission gradient landed after restore"
        # once admitted, training applies normally; bounded retry
        # because a sweep tick between pushes halves the sketch and
        # can cost one extra sighting
        for _ in range(4):
            client2.push_gradients(grads, model_version=0)
            if not np.allclose(
                client2.pull_embedding_vectors("t", novel), 0.0
            ):
                break
        assert not np.allclose(
            client2.pull_embedding_vectors("t", novel), 0.0
        ), "novel id never re-admitted after restore"
    finally:
        if proc.poll() is None:
            proc.kill()
            proc.wait(timeout=15)


def test_ps_sigkill_torn_chain_restores_newest_complete_then_sigterm_full_save(
    tmp_path, monkeypatch,
):
    """ISSUE 13 chaos: SIGKILL a real PS running incremental (delta)
    checkpoints, then emulate the two crash windows the format creates
    — a torn newest delta (died mid-delta-write) and a torn newer base
    dir (died mid-compaction) — plus a stray ``.tmp`` from the atomic
    writer. The same-dir relaunch must restore exactly the newest
    COMPLETE chain prefix (bit-compared against an offline numpy
    restore of the doctored dir). Then SIGTERM the relaunch:
    ``graceful_stop``'s synchronous final FULL save must still land as
    a complete base at the final version."""
    from elasticdl_tpu.ps.checkpoint import SparseCheckpointSaver
    from elasticdl_tpu.ps.embedding_store import NumpyEmbeddingStore

    ckpt_dir = tmp_path / "ckpt"
    ckpt_dir.mkdir()
    monkeypatch.setenv("EDL_CKPT_COMPACT_EVERY", "6")
    for knob in ("EDL_EMB_ADMIT_K", "EDL_EMB_MAX_ROWS",
                 "EDL_EMB_TTL_SECS"):
        monkeypatch.delenv(knob, raising=False)
    extra = ["--checkpoint_dir", str(ckpt_dir), "--checkpoint_steps",
             "2", "--seed", "0"]
    proc, port = spawn_ps_process(
        opt_type="sgd", opt_args="lr=1.0", use_async=True,
        log_path=str(tmp_path / "ps-first.log"), extra=extra,
    )
    ids = np.arange(8, dtype=np.int64)
    try:
        client = PSClient(["localhost:%d" % port], worker_id=0)
        client.push_embedding_table_infos([("t", 4, "zeros")])

        def push(c, value=0.25):
            grads = {"t": (np.full((ids.size, 4), value, np.float32),
                           ids)}
            assert c.push_gradients(grads, model_version=0).accepted

        for _ in range(8):
            push(client)
        # the off-RPC checkpoint thread lands base + deltas shortly
        # after the triggering pushes return
        deadline = time.time() + 30
        while time.time() < deadline:
            if (SparseCheckpointSaver.latest_version(str(ckpt_dir))
                    or 0) >= 4:
                break
            time.sleep(0.2)
        assert (SparseCheckpointSaver.latest_version(str(ckpt_dir))
                or 0) >= 4, "no delta chain landed before the kill"
        chains = sorted(
            d for d in os.listdir(str(ckpt_dir))
            if d.startswith("version-")
        )
        deltas = sorted(
            f for f in os.listdir(str(ckpt_dir / chains[0]))
            if f.startswith("delta-")
        )
        assert deltas, "checkpoints never went incremental"

        proc.send_signal(signal.SIGKILL)
        proc.wait(timeout=30)

        # crash-window emulation on the dead PS's dir:
        # (a) mid-delta-write — truncate the newest delta file
        vdir = ckpt_dir / chains[-1]
        newest = sorted(
            (f for f in os.listdir(str(vdir))
             if f.startswith("delta-")),
            key=lambda f: int(f.split("-")[1]),
        )[-1]
        torn = vdir / newest
        torn.write_bytes(torn.read_bytes()[:100])
        # (b) mid-compaction — a newer version dir with a torn base
        comp = ckpt_dir / "version-9999"
        comp.mkdir()
        (comp / "embeddings-0-of-1.npz").write_bytes(b"torn-base")
        # (c) the atomic writer's crash residue
        (vdir / "delta-99-embeddings-0-of-1.npz.tmp").write_bytes(b"x")

        # offline expectation: what the newest complete prefix holds
        offline = NumpyEmbeddingStore(seed=0)
        offline.set_optimizer("sgd", lr=1.0)
        expected_version = SparseCheckpointSaver(
            str(ckpt_dir)
        ).restore(offline)
        assert expected_version is not None
        expected_rows = offline.lookup("t", ids)
        assert not np.allclose(expected_rows, 0.0)

        proc, _ = spawn_ps_process(
            opt_type="sgd", opt_args="lr=1.0", use_async=True,
            log_path=str(tmp_path / "ps-relaunch.log"), extra=extra,
            port=port,
        )
        client2 = PSClient(["localhost:%d" % port], worker_id=1)
        client2.push_embedding_table_infos([("t", 4, "zeros")])
        restored_rows = client2.pull_embedding_vectors("t", ids)
        np.testing.assert_array_equal(restored_rows, expected_rows)

        # graceful_stop keeps its synchronous final FULL save: push
        # past the restored state, SIGTERM, and require a complete
        # base at the final version that restores the live state
        for _ in range(3):
            push(client2, value=0.125)
        final_rows = client2.pull_embedding_vectors("t", ids)
        proc.send_signal(signal.SIGTERM)
        assert proc.wait(timeout=60) == 0, "SIGTERM drain failed"
        final_version = SparseCheckpointSaver.latest_version(
            str(ckpt_dir)
        )
        assert final_version is not None
        final_dir = ckpt_dir / ("version-%d" % final_version)
        assert (final_dir / "embeddings-0-of-1.npz").exists(), (
            "final save was not a full base"
        )
        offline2 = NumpyEmbeddingStore(seed=0)
        offline2.set_optimizer("sgd", lr=1.0)
        assert SparseCheckpointSaver(
            str(ckpt_dir)
        ).restore(offline2) == final_version
        np.testing.assert_array_equal(
            offline2.lookup("t", ids), final_rows
        )
    finally:
        if proc.poll() is None:
            proc.kill()
            proc.wait(timeout=15)


def test_master_sigkill_midstream_resumes_watermark_no_reminted_windows(
    tmp_path, monkeypatch,
):
    """SIGKILL a real streaming master mid-stream; the relaunch replays
    the state journal, seeks the synthetic source to the journaled
    position, and finishes the bounded stream — with every window
    minted EXACTLY once across both lifetimes and the final watermark
    covering every record."""
    from elasticdl_tpu.master import state_store
    from elasticdl_tpu.observability import events as events_mod
    from elasticdl_tpu.worker import master_client as mc_module

    state_dir = tmp_path / "state"
    events_dir = tmp_path / "events"
    spool_dir = tmp_path / "spool"
    for d in (state_dir, events_dir, spool_dir):
        d.mkdir()
    master_port = _free_port()
    # enough windows that the kill reliably lands MID-stream even when
    # the compiled step rate is high
    total_records, window = 3072, 128
    env = {
        **os.environ,
        "JAX_PLATFORMS": "cpu",
        state_store.STATE_DIR_ENV: str(state_dir),
        events_mod.EVENTS_DIR_ENV: str(events_dir),
        "EDL_STREAM": "synthetic",
        "EDL_STREAM_TOTAL_RECORDS": str(total_records),
        "EDL_STREAM_WINDOW_RECORDS": str(window),
        "EDL_STREAM_FEATURES": "6",
        "EDL_STREAM_HOT_VOCAB": "400",
        "EDL_STREAM_DRIFT": "20",
        "EDL_STREAM_MAX_BACKLOG": "512",
        "EDL_CTR_VOCAB": "1024",
        "EDL_CTR_EMBED_DIM": "4",
    }
    env.pop("EDL_FAULT_SPEC", None)
    monkeypatch.setenv("EDL_CTR_VOCAB", "1024")
    monkeypatch.setenv("EDL_CTR_EMBED_DIM", "4")
    monkeypatch.setattr(mc_module, "MASTER_RETRY_BUDGET_SECS", 60.0)

    def spawn_master(tag):
        log = open(str(tmp_path / ("master-%s.log" % tag)), "w")
        return subprocess.Popen(
            [
                sys.executable, "-m", "elasticdl_tpu.master.main",
                "--model_zoo", "elasticdl_tpu.models.ctr",
                "--training_data", str(spool_dir),
                "--records_per_task", str(window),
                "--num_epochs", "1",
                "--port", str(master_port),
                "--task_timeout_secs", "60",
            ],
            env=env, stdout=log, stderr=subprocess.STDOUT,
        )

    journal_path = state_dir / state_store.JOURNAL_NAME

    def journal_ops():
        if not journal_path.is_file():
            return []
        ops = []
        with open(str(journal_path)) as f:
            for line in f:
                try:
                    ops.append(json.loads(line))
                except ValueError:
                    pass  # torn tail from the SIGKILL
        return ops

    master = spawn_master("first")
    runner = None
    try:
        _wait_port(master_port)
        mc = MasterClient("localhost:%d" % master_port, worker_id=0)
        mc.reset_worker()
        worker = Worker(
            mc,
            "elasticdl_tpu.models.ctr",
            RecordIODataReader(data_dir=str(spool_dir)),
            minibatch_size=32,
            wait_sleep_secs=0.1,
        )
        runner = threading.Thread(target=worker.run, daemon=True)
        runner.start()

        deadline = time.time() + 120
        done = []
        while time.time() < deadline:
            done = [
                op for op in journal_ops()
                if op["op"] == "done" and op.get("records")
            ]
            if len(done) >= 3:
                break
            time.sleep(0.1)
        assert len(done) >= 3, "stream made no progress before the kill"
        master.send_signal(signal.SIGKILL)
        master.wait(timeout=30)
        time.sleep(1.0)

        master = spawn_master("relaunch")
        _wait_port(master_port)
        try:
            rc = master.wait(timeout=240)
        except subprocess.TimeoutExpired:
            master.kill()
            raise AssertionError(
                "relaunched streaming master did not finish:\n%s"
                % open(
                    str(tmp_path / "master-relaunch.log")
                ).read()[-4000:]
            )
        assert rc == 0, (
            "relaunched master failed:\n%s"
            % open(str(tmp_path / "master-relaunch.log")).read()[-4000:]
        )
        runner.join(timeout=120)
        assert not runner.is_alive(), "worker never finished"
    finally:
        if master.poll() is None:
            master.kill()
        if runner is not None and runner.is_alive():
            runner.join(timeout=5)

    ops = journal_ops()
    # every window minted exactly once across BOTH master lifetimes
    minted = [op for op in ops if op["op"] == "stream_window"]
    shards = [op["task"][2] for op in minted]
    assert len(shards) == len(set(shards)), (
        "windows re-minted across the restart: %r"
        % [s for s in shards if shards.count(s) > 1]
    )
    assert len(shards) == total_records // window
    # the watermark covered every record exactly once
    done_records = sum(
        op.get("records", 0) for op in ops if op["op"] == "done"
    )
    assert done_records == total_records
    closes = [op for op in ops if op["op"] == "stream_close"]
    assert closes, "stream never closed"
    boots = [op for op in ops if op["op"] == "master_restarted"]
    assert len(boots) == 2


def _free_port():
    probe = socket.socket()
    probe.bind(("", 0))
    port = probe.getsockname()[1]
    probe.close()
    return port
