"""ISSUE 6 device-resident embedding tier, end to end: fused
gather/scatter-apply kernel parity (jnp vs Pallas-interpret),
promotion-after-k-hits, LFU/TTL demotion with eviction writeback,
miss-path pull parity (never-promote config bit-exact vs tier-off),
flush-before-checkpoint ordering, PS-restart flush-then-invalidate,
the push_embedding_rows writeback RPC over live gRPC, and the
Zipfian hit-rate acceptance bound."""

import numpy as np
import pytest

from elasticdl_tpu.models import deepfm
from elasticdl_tpu.ops import embedding_tier as tier_ops
from elasticdl_tpu.ps.local_client import LocalPSClient
from elasticdl_tpu.train.device_tier import (
    DeviceEmbeddingTier,
    DeviceTierConfig,
    resolve_tier_config,
)
from elasticdl_tpu.train.sparse import SparseTrainer

FIELDS = 4
BATCH = 32
VOCAB = 1000


def make_batches(n, seed=0, zipf=1.6, vocab=VOCAB, offset=0):
    rng = np.random.RandomState(seed)
    out = []
    for _ in range(n):
        ids = (rng.zipf(zipf, size=(BATCH, FIELDS)) % vocab + offset)
        out.append({
            "features": {"ids": ids.astype(np.int64)},
            "labels": (ids.sum(1) % 2).astype(np.float32),
            "_mask": np.ones(BATCH, np.float32),
        })
    return out


def build_trainer(device_tier, seed=0, **kwargs):
    return SparseTrainer(
        model=deepfm.custom_model(),
        loss_fn=deepfm.loss,
        optimizer=deepfm.optimizer(),
        specs=deepfm.sparse_embedding_specs(
            num_features=FIELDS, batch_size=BATCH
        ),
        ps_client=LocalPSClient(seed=seed, opt_type="adam", lr=0.01),
        seed=seed,
        device_tier=device_tier,
        **kwargs,
    )


def small_config(**overrides):
    base = dict(
        capacity=256, promote_hits=2, ttl=100, stage_budget=64,
        opt_type="adam", opt_args={"lr": 0.01}, writeback_steps=8,
    )
    base.update(overrides)
    return DeviceTierConfig(**base)


# ---------------------------------------------------------------------
# fused kernels


def _rand_state(rng, alloc, dim, opt_type):
    state = tier_ops.init_table_state(alloc, dim, opt_type)
    import jax.numpy as jnp

    state["rows"] = jnp.asarray(rng.rand(alloc, dim).astype(np.float32))
    for key in list(state):
        if key.startswith("slot"):
            state[key] = jnp.asarray(
                rng.rand(alloc, dim).astype(np.float32) * 0.1
            )
    return state


def test_jnp_insert_gather_semantics():
    import jax.numpy as jnp

    rng = np.random.RandomState(0)
    state = _rand_state(rng, 9, 8, "adam")
    rows0 = np.asarray(state["rows"])
    slots = jnp.asarray(np.array([0, 3, -1, 5, -1], np.int32))
    miss = rng.rand(5, 8).astype(np.float32)
    ins_slots = jnp.asarray(np.array([7, 8], np.int32))  # 8 = scratch
    ins_rows = rng.rand(2, 8).astype(np.float32)
    ev = jnp.asarray(np.array([1, 8], np.int32))
    new_state, combined, evicted = tier_ops.fused_insert_gather(
        state, ins_slots, jnp.asarray(ins_rows), ev, slots,
        jnp.asarray(miss), kernel="jnp",
    )
    # victims read out BEFORE inserts land
    assert np.allclose(np.asarray(evicted)[0], rows0[1])
    # staged insert landed (and its opt state reset)
    assert np.allclose(np.asarray(new_state["rows"])[7], ins_rows[0])
    assert np.allclose(np.asarray(new_state["slot0"])[7], 0.0)
    # combined: hits from the table, misses from the pulled buffer
    out = np.asarray(combined)
    assert np.allclose(out[0], rows0[0])
    assert np.allclose(out[1], rows0[3])
    assert np.allclose(out[2], miss[2])
    assert np.allclose(out[3], rows0[5])


@pytest.mark.parametrize("opt_type", ["sgd", "momentum", "adagrad", "adam"])
def test_jnp_scatter_apply_matches_store_math(opt_type):
    """The in-device optimizer step must track the PS store's update
    math — a row trains the same whichever tier holds it."""
    import jax.numpy as jnp

    from elasticdl_tpu.ps.embedding_store import NumpyEmbeddingStore

    rng = np.random.RandomState(1)
    dim, n = 6, 4
    store = NumpyEmbeddingStore(seed=0)
    store.set_optimizer(opt_type, lr=0.05)
    store.create_table("t", dim, init_scale=0.1)
    ids = np.arange(n, dtype=np.int64)
    init_rows = store.lookup("t", ids)  # materialize

    state = tier_ops.init_table_state(n + 1, dim, opt_type)
    state["rows"] = jnp.asarray(
        np.concatenate([init_rows, np.zeros((1, dim), np.float32)])
    )
    slots = jnp.asarray(np.arange(n, dtype=np.int32))
    for _ in range(3):  # multi-step: exercises slot state + step counts
        grads = rng.rand(n, dim).astype(np.float32)
        store.push_gradients("t", ids, grads)
        state = tier_ops.fused_scatter_apply(
            state, slots, jnp.asarray(grads), opt_type=opt_type,
            lr=0.05, kernel="jnp",
        )
    np.testing.assert_allclose(
        np.asarray(state["rows"])[:n], store.lookup("t", ids),
        rtol=1e-5, atol=1e-6,
    )


def test_pallas_interpret_matches_jnp():
    """The Pallas kernels (interpret mode on CPU — same code path as
    TPU minus the Mosaic lowering) agree with the jnp fallback on
    everything but the scratch row (whose contents are garbage by
    contract)."""
    import jax.numpy as jnp

    old = tier_ops.INTERPRET
    tier_ops.INTERPRET = True
    try:
        rng = np.random.RandomState(2)
        state = _rand_state(rng, 9, 8, "adam")
        slots = jnp.asarray(np.array([0, 3, -1, 5, -1], np.int32))
        miss = jnp.asarray(rng.rand(5, 8).astype(np.float32))
        ins_slots = jnp.asarray(np.array([7, 8], np.int32))
        ins_rows = jnp.asarray(rng.rand(2, 8).astype(np.float32))
        ev = jnp.asarray(np.array([1, 8], np.int32))
        a = tier_ops.fused_insert_gather(
            dict(state), ins_slots, ins_rows, ev, slots, miss,
            kernel="jnp",
        )
        b = tier_ops.fused_insert_gather(
            dict(state), ins_slots, ins_rows, ev, slots, miss,
            kernel="pallas",
        )
        assert np.allclose(np.asarray(a[1]), np.asarray(b[1]))
        assert np.allclose(np.asarray(a[2]), np.asarray(b[2]))
        for key in a[0]:
            assert np.allclose(
                np.asarray(a[0][key])[:8], np.asarray(b[0][key])[:8]
            ), key
        grads = jnp.asarray(rng.rand(5, 8).astype(np.float32))
        sa = tier_ops.fused_scatter_apply(
            dict(state), slots, grads, opt_type="adam", lr=0.01,
            kernel="jnp",
        )
        sb = tier_ops.fused_scatter_apply(
            dict(state), slots, grads, opt_type="adam", lr=0.01,
            kernel="pallas",
        )
        for key in sa:
            assert np.allclose(
                np.asarray(sa[key])[:8], np.asarray(sb[key])[:8],
                atol=1e-6,
            ), key
    finally:
        tier_ops.INTERPRET = old


# ---------------------------------------------------------------------
# tier policy


def test_promotion_after_k_hits():
    """An id is promoted only after ``promote_hits`` sightings, and is
    a hit from its promotion step on."""
    client = LocalPSClient(seed=0)
    client.push_embedding_table_infos([("t", 4, "0.05")])
    spec = type("S", (), {"name": "t", "dim": 4})()
    tier = DeviceEmbeddingTier(
        [spec], client, small_config(promote_hits=3, writeback_steps=0)
    )
    ids = np.array([5, 9], np.int64)
    rows = np.zeros((2, 4), np.float32)
    for sighting in range(1, 4):
        tier.advance()
        slots = tier.lookup("t", ids)
        assert (slots < 0).all() or sighting > 3
        promoted, _ = tier.admit("t", ids, rows)
        if sighting < 3:
            assert not promoted.any(), sighting
        else:
            assert promoted.all()
    tier.advance()
    assert (tier.lookup("t", ids) >= 0).all()
    stats = tier.stats()
    assert stats["hits"] == 2 and stats["misses"] == 6
    tier.close()


def test_lfu_pressure_evicts_coldest():
    """Promotion into a full tier evicts the least-frequently-used
    idle slot; the victim's id misses afterwards."""
    client = LocalPSClient(seed=0)
    client.push_embedding_table_infos([("t", 4, "0.05")])
    spec = type("S", (), {"name": "t", "dim": 4})()
    tier = DeviceEmbeddingTier(
        [spec], client,
        small_config(capacity=2, promote_hits=1, writeback_steps=0,
                     ttl=0),
    )
    rows1 = np.ones((2, 4), np.float32)

    tier.advance()
    tier.lookup("t", np.array([1, 2], np.int64))
    tier.admit("t", np.array([1, 2], np.int64), rows1)  # fills the tier
    # heat up id 1 (two more hits); id 2 stays cold
    for _ in range(2):
        tier.advance()
        assert (tier.lookup("t", np.array([1], np.int64)) >= 0).all()
    tier.advance()
    tier.lookup("t", np.array([7], np.int64))
    promoted, _ = tier.admit(
        "t", np.array([7], np.int64), rows1[:1]
    )
    assert promoted.all()
    tier.advance()
    slots = tier.lookup("t", np.array([1, 2, 7], np.int64))
    assert slots[0] >= 0, "hot id 1 must survive LFU pressure"
    assert slots[1] < 0, "cold id 2 must be the LFU victim"
    assert slots[2] >= 0
    assert tier.stats()["evictions"] == 1
    tier.close()


def test_ttl_demotion_writes_back():
    """Rows idle past the TTL are demoted, and a dirty victim's device
    value reaches the PS store (the eviction writeback)."""
    batches = make_batches(3, seed=1)
    trainer = build_trainer(
        small_config(capacity=32, promote_hits=1, ttl=10,
                     writeback_steps=0, stage_budget=16)
    )
    state = None
    for batch in batches:
        state, _ = trainer.train_step(state, batch)
    tier = trainer.device_tier
    hot_ids, hot_rows = tier.table_rows("deepfm_emb")
    assert hot_ids.size > 0
    # disjoint id range: the hot set idles past the TTL (sweep cadence
    # is every 64 clocks)
    for batch in make_batches(80, seed=9, offset=VOCAB + 10):
        state, _ = trainer.train_step(state, batch)
    tier.drain_writebacks()
    assert tier.stats()["evictions"] > 0
    remaining = set(tier.table_rows("deepfm_emb")[0].tolist())
    evicted = [
        (i, row) for i, row in zip(hot_ids, hot_rows)
        if int(i) not in remaining
    ]
    assert evicted, "TTL sweep demoted nothing"
    store = trainer.preparer._ps.store
    for id_, row in evicted[:8]:
        np.testing.assert_allclose(
            store.lookup("deepfm_emb", np.array([id_]))[0], row,
            rtol=1e-6,
        )
    trainer.close()


# ---------------------------------------------------------------------
# trainer integration


def test_ttl_sweep_evicts_clean_flushes_dirty_first():
    """TTL demotion policy after the ordering-barrier review: idle
    CLEAN slots evict directly (their PS copy is exact); idle DIRTY
    slots first force a flush (becoming clean), then a later sweep
    evicts them — a dirty idle slot is never evicted with its
    writeback invisible to the miss-path barrier."""
    client = LocalPSClient(seed=0)
    client.push_embedding_table_infos([("t", 4, "0.05")])
    spec = type("S", (), {"name": "t", "dim": 4})()
    tier = DeviceEmbeddingTier(
        [spec], client,
        small_config(capacity=8, promote_hits=1, ttl=16,
                     writeback_steps=0),
    )
    ids = np.array([5], np.int64)
    rows = client.pull_embedding_vectors("t", ids)
    tier.advance()
    tier.lookup("t", ids)
    tier.admit("t", ids, rows)
    tier.combine("t", np.full((1,), -1, np.int32),
                 np.zeros((1, 4), np.float32))  # land the insert
    # slot is dirty (dirty-from-birth): the first sweep past the TTL
    # must NOT evict it, only force a flush
    for _ in range(70):
        tier.advance()
    assert tier.stats()["evictions"] == 0
    assert tier._force_flush
    tier.maybe_periodic_writeback()  # forced despite writeback_steps=0
    tier.drain_writebacks()
    # now clean: the next sweep (clock multiple of 64) evicts it
    for _ in range(70):
        tier.advance()
    assert tier.stats()["evictions"] == 1
    tier.advance()
    assert (tier.lookup("t", ids) < 0).all()
    tier.close()


def test_never_promote_bit_exact_vs_tier_off():
    """Miss-path parity: with the tier engaged but promotion
    unreachable every id takes the pull/push path — losses must be
    BIT-EXACT vs the tier-off trainer (and by extension vs the
    pre-tier code, which is the same code path)."""
    never = DeviceTierConfig(
        capacity=64, promote_hits=10 ** 9, ttl=0, stage_budget=16,
        writeback_steps=0,
    )
    t_off, t_on = build_trainer(False), build_trainer(never)
    s_off = s_on = None
    for batch in make_batches(8, seed=3):
        s_off, loss_off = t_off.train_step(s_off, batch)
        s_on, loss_on = t_on.train_step(s_on, batch)
        assert float(loss_off) == float(loss_on)
    t_off.close()
    t_on.close()


def test_env_tier_disabled_is_none(monkeypatch):
    monkeypatch.delenv("EDL_DEVICE_TIER", raising=False)
    assert resolve_tier_config(None) is None
    monkeypatch.setenv("EDL_DEVICE_TIER", "0")
    assert resolve_tier_config(None) is None
    monkeypatch.setenv("EDL_DEVICE_TIER", "1")
    monkeypatch.setenv("EDL_DEVICE_TIER_ROWS", "123")
    config = resolve_tier_config(None)
    assert config is not None and config.capacity == 123


def test_flush_before_checkpoint_parity():
    """flush() (the worker checkpoint/export boundary) lands every
    tier-held update in the PS store: resident rows == store rows."""
    trainer = build_trainer(small_config())
    state = None
    for batch in make_batches(25, seed=4):
        state, _ = trainer.train_step(state, batch)
    trainer.flush_device_tier()
    store = trainer.preparer._ps.store
    for table in ("deepfm_emb", "deepfm_linear"):
        ids, rows = trainer.device_tier.table_rows(table)
        assert ids.size > 0
        np.testing.assert_allclose(
            rows, store.lookup(table, ids), rtol=1e-6, atol=1e-7
        )
    trainer.close()


def test_stream_flush_parity_and_hit_rate():
    """The pipelined train_stream path (lookahead prepare thread +
    fold-time applies): flush parity holds, and a Zipfian stream's
    hit rate clears the acceptance bound (>= 0.9) once warm."""
    trainer = build_trainer(
        small_config(capacity=512, promote_hits=2),
        cache_staleness=4,
    )
    batches = make_batches(40, seed=5, zipf=2.0)
    for _ in trainer.train_stream(None, batches, push_interval=2):
        pass
    trainer.flush_device_tier()
    store = trainer.preparer._ps.store
    for table in ("deepfm_emb", "deepfm_linear"):
        ids, rows = trainer.device_tier.table_rows(table)
        np.testing.assert_allclose(
            rows, store.lookup(table, ids), rtol=1e-6, atol=1e-7
        )
    # warm-phase hit rate: measure the tail (cold-start misses
    # excluded by construction — reset tallies, then stream more)
    tier = trainer.device_tier
    tier.hits = tier.misses = 0
    for _ in trainer.train_stream(
        None, make_batches(20, seed=6, zipf=2.0), push_interval=2
    ):
        pass
    assert tier.stats()["hit_rate"] >= 0.9, tier.stats()
    trainer.close()


def test_ps_restart_flush_then_invalidate():
    """Restored-stamp change: the tier's rows (newer than anything the
    PS restored) are written back, then the map invalidates and
    repopulates — the PR 4 chaos contract's no-lost-updates order."""
    trainer = build_trainer(
        small_config(capacity=256, promote_hits=1, writeback_steps=0)
    )
    state = None
    batches = make_batches(16, seed=7)
    for batch in batches[:8]:
        state, _ = trainer.train_step(state, batch)
    tier = trainer.device_tier
    pre_ids, pre_rows = tier.table_rows("deepfm_emb")
    assert pre_ids.size > 0
    store = trainer.preparer._ps.store
    # the store is stale for resident rows before the flush
    stale = store.lookup("deepfm_emb", pre_ids)
    assert not np.allclose(stale, pre_rows)
    epoch0 = tier.epoch
    trainer.preparer._on_ps_restart(0)  # restored-stamp change path
    assert tier.epoch == epoch0 + 1
    # resident map must already be invalid (host half, immediate)
    assert (tier.lookup("deepfm_emb", pre_ids) < 0).all()
    # next step processes the device half: writeback then reset
    for batch in batches[8:]:
        state, _ = trainer.train_step(state, batch)
    tier.drain_writebacks()
    post = store.lookup("deepfm_emb", pre_ids)
    # every pre-restart resident row's latest value reached the store
    # (later training may have updated some again via the normal path;
    # assert none regressed to the stale pre-flush value)
    for k in range(pre_ids.size):
        assert not np.allclose(post[k], stale[k]) or np.allclose(
            pre_rows[k], stale[k]
        ), int(pre_ids[k])
    trainer.close()


def test_restart_with_staged_promotions_writes_host_values():
    """A PS relaunch marked between admit (promotion staged, slot
    dirty-from-birth) and combine (insert lands) must write the staged
    HOST row back — a device read of the never-landed slot would push
    zeros over the restored PS row (review finding)."""
    client = LocalPSClient(seed=0)
    client.push_embedding_table_infos([("t", 4, "0.05")])
    spec = type("S", (), {"name": "t", "dim": 4})()
    tier = DeviceEmbeddingTier(
        [spec], client,
        small_config(capacity=8, promote_hits=1, writeback_steps=0),
    )
    ids = np.array([3, 9], np.int64)
    rows = client.pull_embedding_vectors("t", ids)  # materialize
    staged_rows = rows + 1.0  # pretend the tier's values moved on
    tier.advance()
    tier.lookup("t", ids)
    promoted, _ = tier.admit("t", ids, staged_rows)
    assert promoted.all()
    # relaunch strikes BEFORE any combine lands the staged insert
    tier.mark_restart()
    tier._process_restart()
    tier.drain_writebacks()
    np.testing.assert_allclose(
        client.pull_embedding_vectors("t", ids), staged_rows, rtol=1e-6
    )
    tier.close()


def test_stale_step_context_reprepares():
    """A batch prepared before a PS relaunch must not combine with its
    stale slot context — the trainer re-prepares it (tier epoch
    guard)."""
    trainer = build_trainer(
        small_config(capacity=128, promote_hits=1, writeback_steps=0)
    )
    state = None
    batches = make_batches(6, seed=8)
    for batch in batches[:4]:
        state, _ = trainer.train_step(state, batch)
    # prepare the next batch, THEN signal the relaunch (the async-push
    # thread can interleave exactly like this)
    prepared, pull_info = trainer.preparer.prepare(batches[4])
    trainer.preparer._on_ps_restart(0)
    assert pull_info.tier_epoch != trainer.device_tier.epoch
    # train_step re-prepares internally; the step must still succeed
    state, loss = trainer.train_step(state, batches[5])
    assert np.isfinite(float(loss))
    trainer.close()


# ---------------------------------------------------------------------
# writeback RPC over live gRPC


def test_push_embedding_rows_grpc_roundtrip():
    from elasticdl_tpu.common.grpc_utils import (
        build_server,
        find_free_port,
    )
    from elasticdl_tpu.proto.services import (
        add_pserver_servicer_to_server,
    )
    from elasticdl_tpu.ps.embedding_store import NumpyEmbeddingStore
    from elasticdl_tpu.ps.servicer import PserverServicer
    from elasticdl_tpu.worker.ps_client import PSClient

    servers, addrs = [], []
    for ps_id in range(2):
        store = NumpyEmbeddingStore(seed=ps_id)
        store.set_optimizer("adam", lr=0.01)
        server = build_server()
        add_pserver_servicer_to_server(
            PserverServicer(store, ps_id=ps_id), server
        )
        port = find_free_port()
        server.add_insecure_port("localhost:%d" % port)
        server.start()
        servers.append(server)
        addrs.append("localhost:%d" % port)
    try:
        client = PSClient(addrs)
        client.push_embedding_table_infos([("t", 4, "0.05")])
        ids = np.arange(10, dtype=np.int64)
        client.pull_embedding_vectors("t", ids)  # materialize
        values = np.arange(40, dtype=np.float32).reshape(10, 4)
        client.push_embedding_rows({"t": (ids, values)})
        np.testing.assert_array_equal(
            client.pull_embedding_vectors("t", ids), values
        )
        # id-mod sharding: each shard holds only its slice
        assert servers  # both shards served the overwrite above
    finally:
        for server in servers:
            server.stop(0)


def test_telemetry_blob_tier_fields_reach_statusz():
    from elasticdl_tpu.master.fleet import FleetMonitor
    from elasticdl_tpu.proto import elasticdl_tpu_pb2 as pb

    monitor = FleetMonitor()
    monitor.observe(0, pb.TelemetryBlob(
        role="worker-0", tier_hit_rate=0.93, tier_occupancy=0.5,
        tier_hits=930, tier_misses=70, tier_evictions=3,
    ))
    snapshot = monitor.snapshot()
    entry = snapshot["fleet"]["worker-0"]
    assert entry["tier_hit_rate"] == pytest.approx(0.93, abs=1e-4)
    assert entry["tier_hits"] == 930
    assert entry["tier_evictions"] == 3
