"""SPMD training over a virtual 8-device CPU mesh.

Mirrors the reference's strategy of testing distributed behavior without
real hardware (SURVEY.md §4), with the fake devices standing in for a TPU
slice.
"""

import jax
import numpy as np
import pytest

from elasticdl_tpu.data.pipeline import Dataset
from elasticdl_tpu.models import mnist
from elasticdl_tpu.parallel.mesh import MeshConfig, build_mesh
from elasticdl_tpu.parallel.spmd_trainer import SpmdTrainer
from elasticdl_tpu.worker.trainer import JaxTrainer


def _batch(seed=0, batch=32):
    rng = np.random.RandomState(seed)
    images = rng.rand(batch, 8, 8).astype(np.float32)
    labels = rng.randint(0, 4, size=batch)
    return {
        "features": images,
        "labels": labels,
        "_mask": np.ones(batch, np.float32),
    }


def make_trainer(**kwargs):
    return SpmdTrainer(
        model=mnist.custom_model(),
        loss_fn=mnist.loss,
        optimizer=mnist.optimizer(),
        seed=0,
        **kwargs,
    )


def test_requires_8_devices():
    assert jax.device_count() >= 8, "conftest must provide 8 CPU devices"


def test_dp8_matches_single_device_semantics():
    batch = _batch()
    spmd = make_trainer(mesh_config=MeshConfig(dp=8))
    state_spmd = spmd.create_state(batch["features"])
    single = JaxTrainer(
        model=mnist.custom_model(),
        loss_fn=mnist.loss,
        optimizer=mnist.optimizer(),
        seed=0,
    )
    state_single = single.create_state(batch["features"])
    # Same init (same seed) -> identical first-step loss and params.
    for _ in range(3):
        state_spmd, loss_spmd = spmd.train_step(state_spmd, batch)
        state_single, loss_single = single.train_step(state_single, batch)
        assert abs(float(loss_spmd) - float(loss_single)) < 1e-4
    p_spmd = jax.tree_util.tree_leaves(jax.device_get(state_spmd.params))
    p_single = jax.tree_util.tree_leaves(jax.device_get(state_single.params))
    for a, b in zip(p_spmd, p_single):
        np.testing.assert_allclose(a, b, rtol=2e-3, atol=2e-4)


def test_fsdp_shards_params_and_opt_state():
    mesh = build_mesh(MeshConfig(dp=4, fsdp=2))
    spmd = make_trainer(mesh=mesh)
    batch = _batch()
    state = spmd.create_state(batch["features"])
    # at least one large parameter must actually be sharded over fsdp
    sharded = [
        leaf
        for leaf in jax.tree_util.tree_leaves(state.params)
        if any(
            "fsdp" in str(s) for s in [leaf.sharding.spec]
        )
    ]
    assert sharded, "no parameter picked up an fsdp sharding"
    # optimizer slot state follows its parameter's sharding (ZeRO)
    opt_sharded = [
        leaf
        for leaf in jax.tree_util.tree_leaves(state.opt_state)
        if hasattr(leaf, "sharding") and "fsdp" in str(leaf.sharding.spec)
    ]
    assert opt_sharded, "optimizer state not sharded with params"
    # and the step still runs + loss decreases over a few steps
    losses = []
    for i in range(5):
        state, loss = spmd.train_step(state, _batch(seed=i))
        losses.append(float(loss))
    assert np.isfinite(losses).all()


def test_batch_not_divisible_raises():
    spmd = make_trainer(mesh_config=MeshConfig(dp=8))
    state = spmd.create_state(_batch()["features"])
    with pytest.raises(ValueError):
        spmd.train_step(state, _batch(batch=30))
