"""SPMD training over a virtual 8-device CPU mesh.

Mirrors the reference's strategy of testing distributed behavior without
real hardware (SURVEY.md §4), with the fake devices standing in for a TPU
slice.
"""

import jax
import numpy as np
import pytest

from elasticdl_tpu.data.pipeline import Dataset
from elasticdl_tpu.models import mnist
from elasticdl_tpu.parallel.mesh import MeshConfig, build_mesh
from elasticdl_tpu.parallel.spmd_trainer import SpmdTrainer
from elasticdl_tpu.worker.trainer import JaxTrainer


def _batch(seed=0, batch=32):
    rng = np.random.RandomState(seed)
    images = rng.rand(batch, 8, 8).astype(np.float32)
    labels = rng.randint(0, 4, size=batch)
    return {
        "features": images,
        "labels": labels,
        "_mask": np.ones(batch, np.float32),
    }


def make_trainer(**kwargs):
    return SpmdTrainer(
        model=mnist.custom_model(),
        loss_fn=mnist.loss,
        optimizer=mnist.optimizer(),
        seed=0,
        **kwargs,
    )


def test_requires_8_devices():
    assert jax.device_count() >= 8, "conftest must provide 8 CPU devices"


def test_dp8_matches_single_device_semantics():
    batch = _batch()
    spmd = make_trainer(mesh_config=MeshConfig(dp=8))
    state_spmd = spmd.create_state(batch["features"])
    single = JaxTrainer(
        model=mnist.custom_model(),
        loss_fn=mnist.loss,
        optimizer=mnist.optimizer(),
        seed=0,
    )
    state_single = single.create_state(batch["features"])
    # Same init (same seed) -> identical first-step loss and params.
    for _ in range(3):
        state_spmd, loss_spmd = spmd.train_step(state_spmd, batch)
        state_single, loss_single = single.train_step(state_single, batch)
        assert abs(float(loss_spmd) - float(loss_single)) < 1e-4
    p_spmd = jax.tree_util.tree_leaves(jax.device_get(state_spmd.params))
    p_single = jax.tree_util.tree_leaves(jax.device_get(state_single.params))
    for a, b in zip(p_spmd, p_single):
        np.testing.assert_allclose(a, b, rtol=2e-3, atol=2e-4)


def test_fsdp_shards_params_and_opt_state():
    mesh = build_mesh(MeshConfig(dp=4, fsdp=2))
    spmd = make_trainer(mesh=mesh)
    batch = _batch()
    state = spmd.create_state(batch["features"])
    # at least one large parameter must actually be sharded over fsdp
    sharded = [
        leaf
        for leaf in jax.tree_util.tree_leaves(state.params)
        if any(
            "fsdp" in str(s) for s in [leaf.sharding.spec]
        )
    ]
    assert sharded, "no parameter picked up an fsdp sharding"
    # optimizer slot state follows its parameter's sharding (ZeRO)
    opt_sharded = [
        leaf
        for leaf in jax.tree_util.tree_leaves(state.opt_state)
        if hasattr(leaf, "sharding") and "fsdp" in str(leaf.sharding.spec)
    ]
    assert opt_sharded, "optimizer state not sharded with params"
    # and the step still runs + loss decreases over a few steps
    losses = []
    for i in range(5):
        state, loss = spmd.train_step(state, _batch(seed=i))
        losses.append(float(loss))
    assert np.isfinite(losses).all()


def test_batch_not_divisible_raises():
    spmd = make_trainer(mesh_config=MeshConfig(dp=8))
    state = spmd.create_state(_batch()["features"])
    with pytest.raises(ValueError):
        spmd.train_step(state, _batch(batch=30))


def test_sharded_init_never_materializes_full_state_per_device():
    """VERDICT r2 item 5: fresh init must run as one jit with
    out_shardings so a ZeRO-sharded state larger than a single device's
    HBM can be created, not just restored. Asserts the layout: every
    device holds ~1/fsdp of the big leaves, and no device holds more
    than a fraction of the full state."""
    import flax.linen as nn
    import jax.numpy as jnp

    from elasticdl_tpu.train.losses import sparse_softmax_cross_entropy
    from elasticdl_tpu.train.optimizers import create_optimizer

    class BigMLP(nn.Module):
        @nn.compact
        def __call__(self, x, training=False):
            x = nn.Dense(2048)(x)
            x = nn.relu(x)
            x = nn.Dense(2048)(x)
            x = nn.relu(x)
            return nn.Dense(16)(x)

    def loss_fn(labels, predictions):
        return sparse_softmax_cross_entropy(labels, predictions)

    trainer = SpmdTrainer(
        model=BigMLP(),
        loss_fn=loss_fn,
        optimizer=create_optimizer("Adam", learning_rate=1e-3),
        seed=0,
        mesh_config=MeshConfig(dp=1, fsdp=8),
    )
    rng = np.random.RandomState(0)
    batch = {
        "features": rng.rand(16, 256).astype(np.float32),
        "labels": rng.randint(0, 16, size=16),
        "_mask": np.ones(16, np.float32),
    }
    state = trainer.create_state(batch["features"])

    # Account state bytes per device from the actual shard layout.
    per_device = {}
    total = 0
    for leaf in jax.tree_util.tree_leaves(state):
        if not isinstance(leaf, jax.Array):
            continue
        total += leaf.size * leaf.dtype.itemsize
        for shard in leaf.addressable_shards:
            nbytes = shard.data.size * leaf.dtype.itemsize
            per_device[shard.device] = (
                per_device.get(shard.device, 0) + nbytes
            )
    # The three big kernels (+ their Adam mu/nu) dominate total bytes;
    # with fsdp=8 every device must hold well under half the state.
    assert len(per_device) == 8
    assert max(per_device.values()) < total / 3, (
        "init materialized too much on one device: max %d of %d bytes"
        % (max(per_device.values()), total)
    )
    # and the 2048x2048 kernels really are 8-way sharded
    kernel = state.params["Dense_1"]["kernel"]
    assert kernel.addressable_shards[0].data.size == kernel.size // 8

    # the sharded-init state trains and improves
    first = last = None
    for i in range(5):
        state, loss = trainer.train_step(state, batch)
        first = first if first is not None else float(loss)
        last = float(loss)
    assert last < first
