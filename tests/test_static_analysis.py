"""edlint (elasticdl_tpu.analysis) rule tests + the zero-findings gate.

Every rule gets a positive fixture (a small snippet containing the bug
— the rule must fire) and a clean twin (the rule must stay quiet), plus
suppression/baseline mechanics and the tier-1 gate: the whole package
analyzes clean against the checked-in baseline.
"""

import json
import os
import subprocess
import sys
import textwrap

import pytest

from elasticdl_tpu.analysis import (
    analyze_paths,
    analyze_sources,
    load_baseline,
    split_baselined,
)

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
BASELINE_PATH = os.path.join(REPO_ROOT, ".edlint-baseline.json")


def findings_for(source, path="fixture.py", rules=None):
    return analyze_sources(
        [(path, textwrap.dedent(source))], rules=rules
    )


def rules_of(findings):
    return {f.rule for f in findings}


# ---------------------------------------------------------------------------
# lock-discipline

LOCKED_CLASS = """
    import threading

    class Dispatcher:
        def __init__(self):
            self._lock = threading.Lock()
            self._todo = []

        def add(self, task):
            with self._lock:
                self._todo.append(task)

        def drain(self):
            self._todo.clear()   # BUG: no lock
"""


def test_lock_discipline_flags_unlocked_mutation():
    findings = findings_for(LOCKED_CLASS)
    assert any(
        f.rule == "lock-discipline" and "_todo" in f.code
        and f.symbol == "Dispatcher.drain"
        for f in findings
    ), findings


def test_lock_discipline_quiet_on_clean_twin():
    clean = LOCKED_CLASS.replace(
        "            self._todo.clear()   # BUG: no lock",
        "            with self._lock:\n"
        "                self._todo.clear()",
    )
    assert not findings_for(clean)


def test_lock_discipline_locked_suffix_is_caller_holds_lock():
    source = LOCKED_CLASS.replace("def drain(self):", "def drain_locked(self):")
    assert not findings_for(source)


def test_lock_discipline_subscript_chain_counts_as_mutation():
    findings = findings_for("""
        import threading

        class Store:
            def __init__(self):
                self._lock = threading.Lock()
                self._slots = {}

            def put(self, k, v):
                with self._lock:
                    self._slots[k] = v

            def put_racy(self, k, i, v):
                self._slots[k][i] = v   # BUG
    """)
    assert any(
        f.rule == "lock-discipline" and f.symbol == "Store.put_racy"
        for f in findings
    )


def test_lock_discipline_same_named_methods_checked_independently():
    # property getter/setter share a name: the racy getter must still
    # be flagged (and the clean setter must not mask it)
    findings = findings_for("""
        import threading

        class Box:
            def __init__(self):
                self._lock = threading.Lock()
                self._items = []

            def push(self, x):
                with self._lock:
                    self._items.append(x)

            @property
            def items(self):
                self._items.append(None)   # BUG: off-lock
                return list(self._items)

            @items.setter
            def items(self, value):
                with self._lock:
                    self._items.clear()
                    self._items.extend(value)
    """)
    flagged = [f for f in findings if f.rule == "lock-discipline"]
    assert len(flagged) == 1 and flagged[0].symbol == "Box.items", findings


def test_lock_discipline_nested_def_does_not_inherit_lock():
    findings = findings_for("""
        import threading

        class Q:
            def __init__(self):
                self._lock = threading.Lock()
                self._items = []

            def push(self, x):
                with self._lock:
                    self._items.append(x)

            def deferred(self, x):
                with self._lock:
                    def later():
                        self._items.append(x)   # deferred: lock is gone
                    return later
    """)
    assert any(
        f.rule == "lock-discipline" and "deferred" in f.symbol
        for f in findings
    )


# ---------------------------------------------------------------------------
# jax-hot-path

def test_hot_path_flags_decorated_jit():
    findings = findings_for("""
        import time
        import jax
        import numpy as np

        @jax.jit
        def step(x):
            t = time.time()           # BUG: frozen at trace time
            r = np.random.uniform()   # BUG: host RNG
            return float(x) + t + r   # BUG: host sync
    """)
    codes = {f.code for f in findings if f.rule == "jax-hot-path"}
    assert {"time.time", "np.random", "float()"} <= codes, findings


def test_hot_path_flags_jitted_factory_product_cross_module():
    factory = """
        def make_step(cfg):
            def step(x):
                return x.item()    # BUG: device fence every step
            return step
    """
    user = """
        import jax
        from elasticdl_tpu.fake.steps import make_step

        train = jax.jit(make_step(None))
    """
    findings = analyze_sources([
        ("elasticdl_tpu/fake/steps.py", textwrap.dedent(factory)),
        ("elasticdl_tpu/fake/user.py", textwrap.dedent(user)),
    ])
    assert any(
        f.rule == "jax-hot-path" and f.code == ".item()"
        and f.path == "elasticdl_tpu/fake/steps.py"
        for f in findings
    ), findings


def test_hot_path_annotation_marks_function_and_factory():
    findings = findings_for("""
        import numpy as np
        from elasticdl_tpu.common.annotations import hot_path

        @hot_path
        def make_step():
            def step(x):
                return np.asarray(x)   # BUG
            return step

        @hot_path
        def consensus(flags):
            return float(flags)        # BUG
    """)
    assert {"np.asarray", "float()"} <= {
        f.code for f in findings if f.rule == "jax-hot-path"
    }


def test_hot_path_quiet_on_host_code_and_clean_jit():
    assert not findings_for("""
        import time
        import jax
        import jax.numpy as jnp
        import numpy as np

        def host_loop(batches):
            start = time.time()            # host code: fine
            return np.asarray(batches[0])  # host code: fine

        @jax.jit
        def step(x):
            return jnp.sum(x) + int(3)     # int() on static: fine
    """)


# ---------------------------------------------------------------------------
# obs-hot-path

def test_obs_hot_path_flags_logging_and_instrument_lookup():
    findings = findings_for("""
        import jax
        from elasticdl_tpu.observability import metrics as obs_metrics
        from elasticdl_tpu.common.log_utils import default_logger
        logger = default_logger(__name__)

        @jax.jit
        def step(x):
            logger.info("step %s", x)                      # BUG
            obs_metrics.counter("steps_total", "n").inc()  # BUG: lookup
            print(x)                                       # BUG
            return x
    """, rules=["obs-hot-path"])
    assert {f.code for f in findings} == {
        "logger.info", "obs_metrics.counter", "print"
    }, findings
    assert all(f.rule == "obs-hot-path" for f in findings)


def test_obs_hot_path_covers_hot_annotated_factory_products():
    findings = findings_for("""
        from elasticdl_tpu.common.annotations import hot_path
        from elasticdl_tpu.observability import metrics

        @hot_path
        def make_step(logger):
            def step(x):
                logger.warning("x=%s", x)          # BUG
                h = metrics.histogram("lat", "l")  # BUG
                return x
            return step
    """, rules=["obs-hot-path"])
    assert {f.code for f in findings} == {
        "logger.warning", "metrics.histogram"
    }, findings


def test_obs_hot_path_quiet_on_host_code_and_instrument_methods():
    assert not findings_for("""
        import jax
        from elasticdl_tpu.observability import metrics as obs_metrics
        from elasticdl_tpu.common.log_utils import default_logger
        logger = default_logger(__name__)

        # module scope: the supported place to construct instruments
        STEPS = obs_metrics.counter("steps_total", "n")

        def host_loop(batches):
            logger.info("starting")     # host code: fine
            for batch in batches:
                STEPS.inc()

        @jax.jit
        def step(x):
            STEPS.inc()                 # method on a hoisted
            STEPS.labels()              # instrument: the supported
            return x                    # hot surface
    """, rules=["obs-hot-path"])


# ---------------------------------------------------------------------------
# ft-swallowed-except

def test_swallowed_except_flags_silent_broad_handler():
    findings = findings_for("""
        def poll(client):
            try:
                client.ping()
            except Exception:
                pass   # BUG: swallowed
    """)
    assert rules_of(findings) == {"ft-swallowed-except"}


def test_swallowed_except_quiet_when_logged_raised_or_narrow():
    assert not findings_for("""
        import logging
        logger = logging.getLogger(__name__)

        def a(client):
            try:
                client.ping()
            except Exception:
                logger.exception("ping failed")

        def b(client):
            try:
                client.ping()
            except Exception as e:
                raise RuntimeError("ping") from e

        def c(client):
            try:
                client.ping()
            except ConnectionError:
                pass   # narrow: a handled case, not a swallow
    """)


# ---------------------------------------------------------------------------
# ft-grpc-timeout

def test_grpc_timeout_flags_deadline_less_stub_call():
    findings = findings_for("""
        class Client:
            def __init__(self, stub):
                self._stub = stub

            def get(self, request):
                return self._stub.get_task(request)   # BUG: no deadline
    """)
    assert rules_of(findings) == {"ft-grpc-timeout"}


def test_grpc_timeout_quiet_with_deadline_or_non_stub():
    assert not findings_for("""
        class Client:
            def __init__(self, stub, helper):
                self._stub = stub
                self._helper = helper

            def get(self, request):
                return self._stub.get_task(request, timeout=60.0)

            def local(self, request):
                return self._helper.get_task(request)  # not a stub

            def teardown(self):
                self._stub.close()  # channel plumbing, not an RPC
    """)


def test_deadline_no_propagation_flags_literal_timeout_in_handler():
    findings = findings_for("""
        class RouterServicer:
            def __init__(self, stub):
                self._stub = stub

            def model_info(self, request, context):
                return self._stub.model_info(request, timeout=5.0)  # BUG
    """, rules=["ft-deadline-no-propagation"])
    assert rules_of(findings) == {"ft-deadline-no-propagation"}
    assert findings[0].symbol == "RouterServicer.model_info"
    assert "timeout=5.0" in findings[0].code


def test_deadline_no_propagation_flags_default_const_in_thread_context():
    findings = findings_for("""
        from elasticdl_tpu.common.annotations import thread_context

        class GRPC:
            DEFAULT_RPC_TIMEOUT_SECS = 60.0

        @thread_context("apply-pool")
        def fan_out(stub, request):
            return stub.push_model(
                request, timeout=GRPC.DEFAULT_RPC_TIMEOUT_SECS  # BUG
            )
    """, rules=["ft-deadline-no-propagation"])
    assert rules_of(findings) == {"ft-deadline-no-propagation"}


def test_deadline_no_propagation_quiet_on_derived_or_client_paths():
    assert not findings_for("""
        from elasticdl_tpu.common import overload
        from elasticdl_tpu.common.annotations import thread_context

        class RouterServicer:
            def __init__(self, stub):
                self._stub = stub

            def model_info(self, request, context):
                # the budget helper caps by the caller's remainder
                return self._stub.model_info(
                    request, timeout=overload.rpc_timeout(5.0)
                )

            def predict(self, request, context, deadline):
                # a Name is trusted as a derived deadline
                return self._stub.predict(request, timeout=deadline)

        @thread_context("apply-pool")
        def local_fan_out(helper, request):
            return helper.push_model(request, timeout=5.0)  # not a stub

        def plain_client(stub, request):
            # fresh deadline on a top-level client path is fine
            return stub.get_task(request, timeout=60.0)
    """, rules=["ft-deadline-no-propagation"])


def test_retry_no_jitter_flags_deterministic_backoff_loop():
    findings = findings_for("""
        import time

        def call_with_retry(fn):
            delay = 0.5
            while True:
                try:
                    return fn()
                except ConnectionError:
                    time.sleep(delay)            # BUG: lockstep herd
                    delay = min(delay * 2, 10.0)
    """)
    assert rules_of(findings) == {"ft-retry-no-jitter"}


def test_retry_no_jitter_quiet_with_jitter_or_constant_sleep():
    assert not findings_for("""
        import random
        import time

        def jittered(fn):
            ceiling = 0.5
            while True:
                try:
                    return fn()
                except ConnectionError:
                    delay = random.uniform(0, ceiling)
                    time.sleep(delay)
                    ceiling = min(ceiling * 2, 10.0)

        def poller(fn, poll_secs):
            while True:
                fn()
                time.sleep(poll_secs)   # constant cadence, not backoff
    """)


def test_sigterm_no_chain_flags_overwriting_handler():
    findings = findings_for("""
        import signal
        import sys

        def install_stop_hook(server):
            def _on_term(signum, frame):
                server.stop(grace=1.0)
                sys.exit(0)
            # BUG: severs the flight-recorder/drain chain behind it
            signal.signal(signal.SIGTERM, _on_term)
    """)
    assert rules_of(findings) == {"ft-sigterm-no-chain"}


def test_sigterm_no_chain_quiet_when_previous_captured_or_other_signal():
    assert not findings_for("""
        import signal
        import sys

        def install_chained_hook(server):
            previous = signal.getsignal(signal.SIGTERM)

            def _on_term(signum, frame):
                server.stop(grace=1.0)
                if callable(previous):
                    previous(signum, frame)
                else:
                    sys.exit(0)

            signal.signal(signal.SIGTERM, _on_term)

        def install_usr1_dump():
            # non-TERM signals don't participate in the eviction chain
            signal.signal(signal.SIGUSR1, lambda s, f: None)
    """)


# ---------------------------------------------------------------------------
# perf-varint-ids

def test_perf_varint_ids_flags_scalar_cast_extend():
    findings = findings_for("""
        def serialize_indexed_slices(values, ids, slices):
            del slices.ids[:]
            slices.ids.extend(int(i) for i in ids)   # BUG: per-id loop
            return slices

        def also_bad(request, ids):
            request.ids.extend([float(v) for v in ids])
    """, rules=["perf-varint-ids"])
    assert len(findings) == 2
    assert all(f.rule == "perf-varint-ids" for f in findings)
    assert findings[0].code == ".extend(int(...))"


def test_perf_varint_ids_quiet_on_vectorized_and_working_comprehensions():
    assert not findings_for("""
        import numpy as np

        def packed(slices, ids):
            slices.ids_blob = np.ascontiguousarray(
                ids, dtype="<i8"
            ).tobytes()

        def legacy_but_vectorized(slices, ids):
            slices.ids.extend(ids.astype(np.int64).tolist())

        def real_per_element_work(out, pairs):
            # arithmetic / filtering per element: not the serialization
            # anti-pattern
            out.extend(int(a) * 2 for a in pairs)
            out.extend(int(a) for a in pairs if a > 0)
            out.extend(str(x) for x in pairs)
    """, rules=["perf-varint-ids"])


# ---------------------------------------------------------------------------
# perf-gil-held-apply

GIL_HELD_APPLY = """
    class Servicer:
        def push(self, request):
            with self._push_lock:
                values, ids = _deserialize_gradients(slices)  # BUG
                self._store.push_gradients(name, ids, values)
"""


def test_perf_gil_held_apply_flags_parse_and_apply_under_lock():
    findings = findings_for(
        GIL_HELD_APPLY, path="elasticdl_tpu/ps/servicer.py",
        rules=["perf-gil-held-apply"],
    )
    assert len(findings) == 1
    assert findings[0].rule == "perf-gil-held-apply"
    assert "_deserialize_gradients" in findings[0].message


def test_perf_gil_held_apply_quiet_when_parse_hoisted():
    assert not findings_for("""
        class Servicer:
            def push(self, request):
                tables = {
                    name: _deserialize_gradients(slices)
                    for name, slices in request.tables.items()
                }
                with self._push_lock:
                    for name, (values, ids) in tables.items():
                        self._store.push_gradients(name, ids, values)

            def non_lock_context(self, slices):
                with trace.span("apply"):
                    values, ids = _deserialize_gradients(slices)
                    self._store.push_gradients("t", ids, values)
    """, path="elasticdl_tpu/ps/servicer.py",
        rules=["perf-gil-held-apply"])


def test_perf_gil_held_apply_scoped_to_servicer_modules():
    # same construct outside ps/servicer scope: a deliberate atomicity
    # choice elsewhere is not this rule's business
    assert not findings_for(
        GIL_HELD_APPLY, path="elasticdl_tpu/train/device_tier.py",
        rules=["perf-gil-held-apply"],
    )


# ---------------------------------------------------------------------------
# perf-io-under-lock (ISSUE 13)

IO_UNDER_LOCK = """
    import numpy as np

    class Servicer:
        def push(self, request, version):
            with self._push_lock:
                self._apply(request)
                self._checkpoint_saver.save(version, self._store)  # BUG

        def snapshot(self, path, arrays):
            with self._store_lock:
                np.savez(path, **arrays)  # BUG
"""


def test_perf_io_under_lock_flags_savez_and_saver_call():
    findings = findings_for(
        IO_UNDER_LOCK, path="elasticdl_tpu/ps/servicer.py",
        rules=["perf-io-under-lock"],
    )
    assert len(findings) == 2
    assert all(f.rule == "perf-io-under-lock" for f in findings)
    assert any("savez" in f.message for f in findings)
    assert any("save" in f.message for f in findings)


def test_perf_io_under_lock_quiet_when_io_hoisted():
    # the ISSUE-13 shape: snapshot under the lock (export_table_dirty
    # inside save() takes it internally), serialize+write outside —
    # and non-lock with-blocks (spans, np.load file handles) are not
    # this rule's business
    assert not findings_for("""
        import numpy as np

        class Saver:
            def save(self, version, store):
                with self._cond:
                    version, kind = self._pending
                    self._pending = None
                arrays = self._export(store)
                np.savez(self._path(version), **arrays)

            def read(self, path):
                with np.load(path) as data:
                    return dict(data)
    """, path="elasticdl_tpu/ps/checkpoint.py",
        rules=["perf-io-under-lock"])


def test_perf_io_under_lock_scoped_to_ps_modules():
    # a write-through journal holding its lock across the append is a
    # deliberate durability choice outside ps/ (observability/events)
    assert not findings_for(
        IO_UNDER_LOCK, path="elasticdl_tpu/observability/events.py",
        rules=["perf-io-under-lock"],
    )


# ---------------------------------------------------------------------------
# xhost-determinism

def test_determinism_flags_set_iteration_in_checkpoint_path():
    findings = findings_for("""
        def restore(data):
            tables = {k.split("/")[1] for k in data}
            out = []
            for name in tables:        # BUG: hash order
                out.append(name)
            return out
    """, path="fake_checkpoint.py")
    assert any(
        f.rule == "xhost-determinism" and f.code == "set-iteration"
        for f in findings
    )


def test_determinism_flags_unsorted_listdir():
    findings = findings_for("""
        import os

        def shards(d):
            return [f for f in os.listdir(d)]   # BUG: fs order
    """, path="fake_export.py")
    assert any(f.code == "os.listdir" for f in findings)


def test_determinism_quiet_when_sorted_or_out_of_scope():
    clean = """
        import os

        def shards(d):
            extra = {1, 2}
            return sorted(os.listdir(d)) + [x for x in sorted(extra)]
    """
    assert not findings_for(clean, path="fake_checkpoint.py")
    # same set iteration outside checkpoint/export scope: not this
    # rule's business
    racy = """
        def f():
            s = {1, 2}
            return [x for x in s]
    """
    assert not findings_for(racy, path="ordinary_module.py")


# ---------------------------------------------------------------------------
# suppression + baseline mechanics

def test_inline_suppression_silences_one_rule_on_one_line():
    findings = findings_for("""
        def poll(client):
            try:
                client.ping()
            except Exception:  # edlint: disable=ft-swallowed-except
                pass
    """)
    assert not findings


def test_def_line_suppression_covers_the_whole_function():
    findings = findings_for("""
        import threading

        class Q:
            def __init__(self):
                self._lock = threading.Lock()
                self._items = []

            def push(self, x):
                with self._lock:
                    self._items.append(x)

            # runs under the caller's lock via a path edlint can't see
            def helper(self):  # edlint: disable=lock-discipline
                self._items.clear()
                self._items.append(None)
    """)
    assert not findings


def test_baseline_filters_matching_findings_and_requires_justification(
    tmp_path,
):
    findings = findings_for(LOCKED_CLASS, path="elasticdl_tpu/fake/d.py")
    assert findings
    entry = {
        "rule": findings[0].rule,
        "path": "elasticdl_tpu/fake/d.py",
        "symbol": findings[0].symbol,
        "code": findings[0].code,
        "justification": "test entry",
    }
    baseline_file = tmp_path / "base.json"
    baseline_file.write_text(json.dumps({"findings": [entry]}))
    baseline = load_baseline(str(baseline_file))
    new, matched, unused = split_baselined(findings, baseline)
    assert not new and matched and not unused

    entry.pop("justification")
    baseline_file.write_text(json.dumps({"findings": [entry]}))
    with pytest.raises(ValueError):
        load_baseline(str(baseline_file))


# ---------------------------------------------------------------------------
# CLI

def _run_cli(args, cwd):
    return subprocess.run(
        [sys.executable, "-m", "elasticdl_tpu.analysis"] + args,
        capture_output=True,
        text=True,
        cwd=cwd,
        env=dict(os.environ, PYTHONPATH=REPO_ROOT),
        timeout=120,
    )


_CLI_POSITIVE_FIXTURES = {
    "lock-discipline": ("bad_locks.py", LOCKED_CLASS),
    "jax-hot-path": ("bad_step.py", """
        import time
        import jax

        @jax.jit
        def step(x):
            return x + time.time()
    """),
    "obs-hot-path": ("bad_obs.py", """
        import jax
        import logging

        @jax.jit
        def step(x):
            logging.info("step")
            return x
    """),
    "ft-swallowed-except": ("bad_except.py", """
        def poll(client):
            try:
                client.ping()
            except Exception:
                pass
    """),
    "ft-grpc-timeout": ("bad_rpc.py", """
        def call(stub, request):
            return stub.get_task(request)
    """),
    "ft-deadline-no-propagation": ("bad_deadline.py", """
        class EchoServicer:
            def __init__(self, stub):
                self._stub = stub

            def echo(self, request, context):
                return self._stub.echo(request, timeout=5.0)
    """),
    "ft-retry-no-jitter": ("bad_backoff.py", """
        import time

        def retry(fn):
            delay = 1.0
            while True:
                try:
                    return fn()
                except OSError:
                    time.sleep(delay)
                    delay = delay * 2
    """),
    "xhost-determinism": ("bad_checkpoint.py", """
        def restore(names):
            return [n for n in set(names)]
    """),
    "perf-varint-ids": ("bad_wire.py", """
        def serialize(slices, ids):
            slices.ids.extend(int(i) for i in ids)
    """),
    "obs-deterministic-tracer": ("bad_tracer.py", """
        import sys

        def arm(callback):
            sys.settrace(callback)
    """),
    "conc-lock-order": ("bad_order.py", """
        import threading

        class Pipeline:
            def __init__(self):
                self._a = threading.Lock()
                self._b = threading.Lock()

            def forward(self):
                with self._a:
                    with self._b:
                        pass

            def backward(self):
                with self._b:
                    with self._a:
                        pass
    """),
    "conc-blocking-under-lock": ("bad_blocking.py", """
        import threading

        class Saver:
            def __init__(self):
                self._lock = threading.Lock()

            def save(self):
                with self._lock:
                    self._write()

            def _write(self):
                with open("/tmp/x", "w") as f:
                    f.write("data")
    """),
    "conc-thread-context": ("bad_handler.py", """
        import signal
        import threading

        class Server:
            def __init__(self):
                self._lock = threading.Lock()
                signal.signal(signal.SIGTERM, self._on_term)

            def _on_term(self, signum, frame):
                with self._lock:
                    pass
    """),
    "knob-registry": ("bad_knob.py", """
        import os

        def port():
            return os.getenv("EDL_FAKE_PORT", "0")
    """),
}


@pytest.mark.parametrize("rule", sorted(_CLI_POSITIVE_FIXTURES))
def test_cli_exits_nonzero_on_each_rules_positive_fixture(rule, tmp_path):
    fname, source = _CLI_POSITIVE_FIXTURES[rule]
    bad = tmp_path / fname
    bad.write_text(textwrap.dedent(source))
    result = _run_cli([str(bad), "--no-baseline"], cwd=str(tmp_path))
    assert result.returncode == 1, result.stdout + result.stderr
    assert rule in result.stdout


def test_cli_exits_zero_on_clean_file(tmp_path):
    good = tmp_path / "good.py"
    good.write_text("def f():\n    return 1\n")
    result = _run_cli([str(good), "--no-baseline"], cwd=str(tmp_path))
    assert result.returncode == 0, result.stdout + result.stderr


def test_cli_graph_dumps_call_graph_json(tmp_path):
    bad = tmp_path / "bad_order.py"
    bad.write_text(textwrap.dedent(
        _CLI_POSITIVE_FIXTURES["conc-lock-order"][1]
    ))
    result = _run_cli(["--graph", str(bad)], cwd=str(tmp_path))
    assert result.returncode == 0, result.stdout + result.stderr
    graph = json.loads(result.stdout)
    assert set(graph) == {
        "functions", "entries", "lock_order", "lock_cycles",
        "unknown_callees",
    }
    assert graph["lock_cycles"], "ABBA fixture should produce a cycle"


def test_cli_surfaces_unknown_callee_degradation(tmp_path):
    source = """
        import threading

        def helper():
            pass

        class Box:
            def __init__(self):
                self._lock = threading.Lock()

            def poke(self):
                self.helper()
    """
    bad = tmp_path / "degraded.py"
    bad.write_text(textwrap.dedent(source))
    result = _run_cli([str(bad), "--no-baseline"], cwd=str(tmp_path))
    assert result.returncode == 0, result.stdout + result.stderr
    assert "unresolved possibly-package callee" in result.stderr
    assert "self.helper" in result.stderr


# ---------------------------------------------------------------------------
# obs-span-no-context (ISSUE 9)

def test_obs_span_flags_stub_call_on_raw_channel():
    findings = findings_for("""
        import grpc
        from elasticdl_tpu.observability import trace

        class Client:
            def __init__(self, addr):
                self._stubs = [Stub(grpc.insecure_channel(addr))]

            def pull(self, request, shard):
                with trace.span("ps_pull"):
                    return self._stubs[shard].pull_embedding_vectors(
                        request, timeout=5
                    )                                       # BUG
    """, rules=["obs-span-no-context"])
    assert len(findings) == 1, findings
    assert findings[0].code == "self._stubs.pull_embedding_vectors"
    assert findings[0].symbol == "Client.pull"


def test_obs_span_flags_root_span_blocks_too():
    findings = findings_for("""
        from elasticdl_tpu.observability.trace import root_span

        def predict(stub, request):
            with root_span("serve_predict"):
                return stub.predict(request, timeout=5)  # BUG
    """, rules=["obs-span-no-context"])
    assert len(findings) == 1
    assert findings[0].code == "stub.predict"


def test_obs_span_quiet_with_build_channel_module():
    # the module obtains its channels from build_channel: every stub
    # rides the propagating interceptor, span blocks are fine
    assert not findings_for("""
        from elasticdl_tpu.common.grpc_utils import build_channel
        from elasticdl_tpu.observability import trace

        class Client:
            def __init__(self, addr):
                self._stub = Stub(build_channel(addr))

            def pull(self, request):
                with trace.span("ps_pull"):
                    return self._stub.pull(request, timeout=5)
    """, rules=["obs-span-no-context"])


def test_obs_span_quiet_outside_span_blocks():
    assert not findings_for("""
        import grpc

        class Client:
            def __init__(self, addr):
                self._stub = Stub(grpc.insecure_channel(addr))

            def pull(self, request):
                return self._stub.pull(request, timeout=5)
    """, rules=["obs-span-no-context"])


def test_obs_span_suppression_comment_works():
    assert not findings_for("""
        import grpc
        from elasticdl_tpu.observability import trace

        def probe(stub, request):
            with trace.span("probe"):
                # edlint: disable=obs-span-no-context
                return stub.check(request, timeout=5)
    """, rules=["obs-span-no-context"])


# ---------------------------------------------------------------------------
# obs-deterministic-tracer (ISSUE 14)

def test_deterministic_tracer_flags_sys_and_threading_installers():
    findings = findings_for("""
        import sys
        import threading

        def arm(callback):
            sys.settrace(callback)          # BUG
            threading.setprofile(callback)  # BUG
    """, rules=["obs-deterministic-tracer"])
    assert len(findings) == 2, findings
    assert {f.code for f in findings} == {
        "sys.settrace", "threading.setprofile"
    }
    assert all(f.symbol == "arm" for f in findings)


def test_deterministic_tracer_flags_bare_imported_name():
    findings = findings_for("""
        from sys import settrace as st

        def arm(callback):
            st(callback)  # BUG: aliased import of the installer
    """, rules=["obs-deterministic-tracer"])
    assert len(findings) == 1
    assert findings[0].code == "st"


def test_deterministic_tracer_exempts_profiler_and_tests():
    armed = """
        import sys

        def arm(callback):
            sys.settrace(callback)
    """
    assert not findings_for(
        armed,
        path="elasticdl_tpu/observability/profiler.py",
        rules=["obs-deterministic-tracer"],
    )
    assert not findings_for(
        armed,
        path="tests/test_debugging.py",
        rules=["obs-deterministic-tracer"],
    )


def test_deterministic_tracer_quiet_on_lookalikes():
    # reading gettrace, a same-named method on another object, and the
    # sampling profiler's own frame walk are all fine
    assert not findings_for("""
        import sys

        def sample(tracer):
            frames = sys._current_frames()
            old = sys.gettrace()
            tracer.settrace("not the sys one")
            return frames, old
    """, rules=["obs-deterministic-tracer"])


def test_deterministic_tracer_suppression_comment_works():
    assert not findings_for("""
        import sys

        def arm(callback):
            # edlint: disable=obs-deterministic-tracer
            sys.settrace(callback)
    """, rules=["obs-deterministic-tracer"])


# ---------------------------------------------------------------------------
# num-silent-nonfinite (ISSUE 15)

def test_nonfinite_rule_flags_nan_aggregations_in_scope():
    findings = findings_for("""
        import numpy as np

        def summarize(losses, grads):
            mean = np.nanmean(losses)       # BUG: NaN batch vanishes
            grads = np.nan_to_num(grads)    # BUG: corruption trains on
            return mean, grads
    """, path="elasticdl_tpu/train/fixture.py",
        rules=["num-silent-nonfinite"])
    assert len(findings) == 2, findings
    assert {f.code for f in findings} == {
        "np.nanmean", "np.nan_to_num"
    }


def test_nonfinite_rule_flags_bare_imported_name_and_jnp():
    findings = findings_for("""
        import jax.numpy as jnp
        from numpy import nansum as ns

        def fold(values):
            return ns(values) + jnp.nanmax(values)
    """, path="elasticdl_tpu/ps/fixture.py",
        rules=["num-silent-nonfinite"])
    assert {f.code for f in findings} == {"ns", "jnp.nanmax"}


def test_nonfinite_rule_only_fires_in_hot_scopes():
    source = """
        import numpy as np

        def report(values):
            return np.nanmean(values)
    """
    # scripts/tooling summarizing "absent encoded as NaN" are fine
    assert not findings_for(
        source, path="scripts/bench_report.py",
        rules=["num-silent-nonfinite"],
    )
    assert not findings_for(
        source, path="elasticdl_tpu/analysis/fixture.py",
        rules=["num-silent-nonfinite"],
    )
    # the training data path is not
    assert findings_for(
        source, path="elasticdl_tpu/worker/fixture.py",
        rules=["num-silent-nonfinite"],
    )


def test_nonfinite_rule_quiet_on_finite_math():
    assert not findings_for("""
        import numpy as np

        def fold(values, mask):
            kept = values[mask]
            return np.mean(kept), np.sum(kept), np.isnan(values).any()
    """, path="elasticdl_tpu/train/fixture.py",
        rules=["num-silent-nonfinite"])


def test_nonfinite_rule_suppression_comment_works():
    assert not findings_for("""
        import numpy as np

        def report(values):
            # metrics array encodes "absent" as NaN by design
            # edlint: disable=num-silent-nonfinite
            return np.nanmean(values)
    """, path="elasticdl_tpu/train/fixture.py",
        rules=["num-silent-nonfinite"])


# ---------------------------------------------------------------------------
# ft-unbounded-vocab (ISSUE 12: id-keyed growth with no eviction bound)

UNBOUNDED_VOCAB = """
    class Store:
        def ingest(self, ids, rows):
            for i in ids:
                self._rows[int(i)] = rows[i]
"""


def test_unbounded_vocab_flags_id_keyed_growth_without_eviction():
    findings = findings_for(
        UNBOUNDED_VOCAB, path="elasticdl_tpu/ps/store.py",
        rules=["ft-unbounded-vocab"],
    )
    assert len(findings) == 1
    assert findings[0].rule == "ft-unbounded-vocab"
    assert "drop_rows" in findings[0].message


def test_unbounded_vocab_quiet_with_eviction_entry_point():
    assert not findings_for("""
        class Store:
            def ingest(self, ids, rows):
                for i in ids:
                    self._rows[int(i)] = rows[i]

            def drop_rows(self, name, ids):
                for i in ids:
                    self._rows.pop(int(i), None)
    """, path="elasticdl_tpu/ps/store.py",
        rules=["ft-unbounded-vocab"])


def test_unbounded_vocab_flags_setdefault_and_set_add():
    findings = findings_for("""
        def track(unique_ids):
            seen = set()
            counts = {}
            for i in unique_ids:
                seen.add(i)
                counts.setdefault(i, 0)
    """, path="elasticdl_tpu/stream/tracker.py",
        rules=["ft-unbounded-vocab"])
    assert len(findings) == 2
    assert {f.code for f in findings} == {"seen.add()",
                                          "counts.setdefault()"}


def test_unbounded_vocab_quiet_outside_store_layers():
    # the same growth in a model/bench module is not a PS memory leak
    assert not findings_for(
        UNBOUNDED_VOCAB, path="elasticdl_tpu/models/store.py",
        rules=["ft-unbounded-vocab"],
    )


# ---------------------------------------------------------------------------
# serve-affinity-unbounded-ring (ISSUE 17: replica-keyed growth with no
# cleanup entry point in the serving tier)

UNBOUNDED_RING = """
    class Router:
        def register(self, replica_id, addr):
            self._addrs[replica_id] = addr

        def admit(self, replica_id):
            self._inflight.setdefault(replica_id, 0)
"""


def test_ring_rule_flags_replica_keyed_growth_without_cleanup():
    findings = findings_for(
        UNBOUNDED_RING, path="elasticdl_tpu/serve/fixture.py",
        rules=["serve-affinity-unbounded-ring"],
    )
    assert len(findings) == 2
    assert {f.code for f in findings} == {
        "self._addrs[...] =", "self._inflight.setdefault()",
    }
    assert all("deregister" in f.message for f in findings)


def test_ring_rule_quiet_with_cleanup_entry_point():
    assert not findings_for("""
        class Router:
            def register(self, replica_id, addr):
                self._addrs[replica_id] = addr

            def deregister(self, replica_id):
                self._addrs.pop(replica_id, None)
    """, path="elasticdl_tpu/serve/fixture.py",
        rules=["serve-affinity-unbounded-ring"])


def test_ring_rule_flags_set_add_and_attribute_keys():
    findings = findings_for("""
        class Scaler:
            def spawn(self, proc):
                self._seen.add(proc.pid)
    """, path="elasticdl_tpu/serve/fixture.py",
        rules=["serve-affinity-unbounded-ring"])
    assert len(findings) == 1
    assert findings[0].code == "self._seen.add()"


def test_ring_rule_quiet_for_locals_and_non_identity_keys():
    # a per-call dict dies with the call; a name-keyed config does not
    # track replica churn — neither is the leak class
    assert not findings_for("""
        class Router:
            def tally(self, replica_id):
                votes = {}
                votes[replica_id] = 1
                return votes

            def configure(self, name, value):
                self._options[name] = value
    """, path="elasticdl_tpu/serve/fixture.py",
        rules=["serve-affinity-unbounded-ring"])


def test_ring_rule_quiet_outside_serve_package():
    # the same growth in the master's worker table is the training
    # fleet's lifecycle, owned by other rules
    assert not findings_for(
        UNBOUNDED_RING, path="elasticdl_tpu/master/fixture.py",
        rules=["serve-affinity-unbounded-ring"],
    )


def test_ring_rule_suppression_comment_works():
    assert not findings_for("""
        class Router:
            def register(self, replica_id, addr):
                # bounded by the k8s pod quota, entries reused by id
                # edlint: disable=serve-affinity-unbounded-ring
                self._addrs[replica_id] = addr
    """, path="elasticdl_tpu/serve/fixture.py",
        rules=["serve-affinity-unbounded-ring"])


def test_unbounded_vocab_quiet_for_non_id_iterables():
    assert not findings_for("""
        class Cache:
            def fill(self, batches):
                for b in batches:
                    self._slots[b] = 1
    """, path="elasticdl_tpu/ps/cache.py",
        rules=["ft-unbounded-vocab"])


# ---------------------------------------------------------------------------
# conc-* whole-program rules (PR 16) — engine-level coverage lives in
# tests/test_callgraph.py; here each rule gets its positive fixture, a
# clean twin, and suppression mechanics

_ABBA = """
    import threading

    class Pipeline:
        def __init__(self):
            self._a = threading.Lock()
            self._b = threading.Lock()

        def forward(self):
            with self._a:
                with self._b:
                    pass

        def backward(self):
            with self._b:
                with self._a:
                    pass
"""


def test_conc_lock_order_flags_abba_cycle():
    findings = findings_for(_ABBA, rules=["conc-lock-order"])
    assert len(findings) == 1
    assert "Pipeline._a" in findings[0].code
    assert "Pipeline._b" in findings[0].code


def test_conc_lock_order_quiet_on_consistent_order():
    clean = _ABBA.replace(
        "            with self._b:\n"
        "                with self._a:",
        "            with self._a:\n"
        "                with self._b:",
    )
    assert clean != _ABBA
    assert not findings_for(clean, rules=["conc-lock-order"])


_BLOCKING_HELPER = """
    import threading

    class Saver:
        def __init__(self):
            self._lock = threading.Lock()

        def save(self):
            with self._lock:
                self._write()

        def _write(self):
            with open("/tmp/x", "w") as f:
                f.write("data")
"""


def test_conc_blocking_under_lock_flags_transitive_io():
    findings = findings_for(
        _BLOCKING_HELPER, rules=["conc-blocking-under-lock"]
    )
    assert len(findings) == 1
    assert findings[0].symbol == "Saver.save"
    assert findings[0].code == "open via _write under Saver._lock"


def test_conc_blocking_under_lock_quiet_when_hoisted():
    clean = _BLOCKING_HELPER.replace(
        "            with self._lock:\n"
        "                self._write()",
        "            self._write()\n"
        "            with self._lock:\n"
        "                pass",
    )
    assert clean != _BLOCKING_HELPER
    assert not findings_for(clean, rules=["conc-blocking-under-lock"])


def test_conc_blocking_under_lock_suppression_comment_works():
    suppressed = _BLOCKING_HELPER.replace(
        "            self._write()",
        "            self._write()  "
        "# edlint: disable=conc-blocking-under-lock",
    )
    assert not findings_for(suppressed, rules=["conc-blocking-under-lock"])


_SIGNAL_LOCK = """
    import signal
    import threading

    class Server:
        def __init__(self):
            self._lock = threading.Lock()
            signal.signal(signal.SIGTERM, self._on_term)

        def _on_term(self, signum, frame):
            with self._lock:
                pass
"""


def test_conc_thread_context_flags_lock_in_signal_handler():
    findings = findings_for(_SIGNAL_LOCK, rules=["conc-thread-context"])
    assert len(findings) == 1
    assert findings[0].code == "signal-lock: Server._lock"


def test_conc_thread_context_quiet_on_flag_only_handler():
    clean = _SIGNAL_LOCK.replace(
        "            with self._lock:\n"
        "                pass",
        "            self._term_flag = True",
    )
    assert clean != _SIGNAL_LOCK
    assert not findings_for(clean, rules=["conc-thread-context"])


def test_conc_thread_context_flags_declared_contract_crossing():
    findings = findings_for("""
        import threading

        class Cache:
            # edlint: thread=prepare
            def invalidate(self):
                pass

        class Client:
            def __init__(self):
                self.cache = Cache()

            def _push(self, grads):
                self.cache.invalidate()

        class Trainer:
            def __init__(self):
                self.client = Client()

            def start(self, pool):
                pool.submit(self.client._push, None)
    """, rules=["conc-thread-context"])
    assert len(findings) == 1
    assert findings[0].symbol == "Client._push"
    assert findings[0].code == "invalidate[prepare] from executor:pool"


# ---------------------------------------------------------------------------
# knob-registry (PR 16 satellite)


def test_knob_registry_flags_raw_env_reads():
    findings = findings_for("""
        import os

        PORT_ENV = "EDL_FAKE_PORT"

        def port():
            return int(os.getenv(PORT_ENV, "0"))

        def host():
            return os.environ["EDL_FAKE_HOST"]
    """, rules=["knob-registry"])
    codes = {f.code for f in findings}
    assert "raw-env: EDL_FAKE_PORT" in codes  # const-resolved name
    assert "raw-env: EDL_FAKE_HOST" in codes  # subscript read


def test_knob_registry_quiet_on_env_utils_helpers_and_non_knobs():
    # EDL_CONSENSUS_INTERVAL is a documented knob (docs discovery walks
    # up from the fixture path to the repo's docs/ corpus), so the
    # env_int read passes both the raw-read and the documented check
    assert not findings_for("""
        import os

        from elasticdl_tpu.common.env_utils import env_int

        def interval():
            return env_int("EDL_CONSENSUS_INTERVAL", 1)

        def home():
            return os.getenv("HOME", "")

        def dynamic(name):
            return os.getenv("EDL_FEATURE_%s" % name, "")
    """, rules=["knob-registry"])


def test_knob_registry_flags_undocumented_helper_read():
    findings = findings_for("""
        from elasticdl_tpu.common.env_utils import env_int

        def weird():
            return env_int("EDL_NO_SUCH_KNOB_ANYWHERE", 0)
    """, rules=["knob-registry"])
    assert [f.code for f in findings] == [
        "undocumented: EDL_NO_SUCH_KNOB_ANYWHERE"
    ]


def test_knob_registry_suppression_comment_works():
    assert not findings_for("""
        import os

        def port():
            # edlint: disable=knob-registry
            return os.getenv("EDL_FAKE_PORT", "0")
    """, rules=["knob-registry"])


# ---------------------------------------------------------------------------
# obs-bare-jit (ISSUE 18)

def test_obs_bare_jit_flags_bare_jit_in_train_scope():
    findings = findings_for("""
        import jax

        class Trainer:
            def __init__(self, fn):
                self._step = jax.jit(fn, donate_argnums=(0,))  # BUG
    """, path="elasticdl_tpu/train/fixture.py", rules=["obs-bare-jit"])
    assert len(findings) == 1, findings
    assert findings[0].code == "jit()"
    assert findings[0].symbol == "Trainer.__init__"


def test_obs_bare_jit_flags_pjit_partial_and_decorator():
    findings = findings_for("""
        import jax
        from functools import partial
        from jax.experimental.pjit import pjit

        def build(fn):
            a = pjit(fn)                       # BUG
            b = partial(jax.jit, static_argnums=(1,))  # BUG
            return a, b

        @jax.jit
        def decorated(x):                      # BUG (decorator)
            return x
    """, path="elasticdl_tpu/serve/fixture.py", rules=["obs-bare-jit"])
    assert sorted(f.code for f in findings) == ["jit()", "jit()", "pjit()"]


def test_obs_bare_jit_quiet_on_instrumented_and_out_of_scope():
    # the sanctioned wrapper has a different leaf name
    assert not findings_for("""
        from elasticdl_tpu.observability import device as device_obs

        class Trainer:
            def __init__(self, fn):
                self._step = device_obs.instrumented_jit(
                    fn, name="train_step", donate_argnums=(0,))
    """, path="elasticdl_tpu/train/fixture.py", rules=["obs-bare-jit"])
    # parallel/ research trainers are deliberately out of scope
    assert not findings_for("""
        import jax

        def build(fn):
            return jax.jit(fn)
    """, path="elasticdl_tpu/parallel/fixture.py", rules=["obs-bare-jit"])


def test_obs_bare_jit_suppression_comment_works():
    assert not findings_for("""
        import jax

        def init(model, rng, feats):
            return jax.jit(  # edlint: disable=obs-bare-jit
                lambda r, f: model.init(r, f)
            )(rng, feats)
    """, path="elasticdl_tpu/train/fixture.py", rules=["obs-bare-jit"])


# ---------------------------------------------------------------------------
# perf-bare-collective (ISSUE 20)

def test_perf_bare_collective_flags_raw_lax_in_model_scope():
    findings = findings_for("""
        import jax

        def stage(p, x):
            h = x @ p["W1"]
            return jax.lax.psum(h @ p["W2"], "tp")  # BUG
    """, path="elasticdl_tpu/models/fixture.py",
       rules=["perf-bare-collective"])
    assert len(findings) == 1, findings
    assert findings[0].code == "lax.psum()"
    assert "mesh_psum" in findings[0].message


def test_perf_bare_collective_flags_bare_import_and_lax_prefix():
    findings = findings_for("""
        from jax.lax import psum
        from jax import lax

        def reduce_all(x, v):
            a = psum(x, "dp")           # BUG (bare import)
            b = lax.all_gather(v, "dp")  # BUG (lax prefix)
            return a, b
    """, path="elasticdl_tpu/train/fixture.py",
       rules=["perf-bare-collective"])
    assert sorted(f.code for f in findings) == [
        "lax.all_gather()", "lax.psum()"
    ]


def test_perf_bare_collective_quiet_on_helpers_and_owned_scopes():
    # the sanctioned helpers have different leaf names
    assert not findings_for("""
        from elasticdl_tpu.parallel.collectives import (
            mesh_psum, mesh_reduce_scatter,
        )

        def stage(p, x):
            g = mesh_reduce_scatter(x, "fsdp")
            return mesh_psum(g @ p["W"], "tp")
    """, path="elasticdl_tpu/models/fixture.py",
       rules=["perf-bare-collective"])
    # parallel/ and ops/ OWN communication; raw lax is their job
    for owned in ("parallel", "ops"):
        assert not findings_for("""
            import jax

            def helper(x):
                return jax.lax.psum(x, "tp")
        """, path="elasticdl_tpu/%s/fixture.py" % owned,
           rules=["perf-bare-collective"])
    # non-lax attributes sharing a collective's leaf name are not
    # collectives
    assert not findings_for("""
        def pull(store, ids):
            return store.all_gather(ids)
    """, path="elasticdl_tpu/ps/fixture.py",
       rules=["perf-bare-collective"])


def test_perf_bare_collective_suppression_comment_works():
    assert not findings_for("""
        import jax

        def compat_sum(x, axes):
            # edlint: disable=perf-bare-collective
            return jax.lax.psum(x, axes)
    """, path="elasticdl_tpu/common/fixture.py",
       rules=["perf-bare-collective"])


# ---------------------------------------------------------------------------
# the gate

@pytest.mark.lint
def test_package_has_zero_non_baselined_findings():
    """Tier-1 gate: the whole package analyzes clean against the
    checked-in baseline. A new finding means: fix it, suppress it with
    a justification comment, or baseline it with a justification."""
    findings, errors = analyze_paths(
        [os.path.join(REPO_ROOT, "elasticdl_tpu")]
    )
    assert not errors, errors
    baseline = load_baseline(BASELINE_PATH)
    new, _matched, unused = split_baselined(findings, baseline)
    assert not new, "new edlint findings:\n" + "\n".join(
        f.render() for f in new
    )
    assert not unused, (
        "stale baseline entries (the finding no longer exists — remove "
        "them):\n%s" % json.dumps(unused, indent=2)
    )
