"""Worker driving the SPMD trainer over the virtual 8-device mesh,
against a real gRPC master — the full distributed data plane."""

from elasticdl_tpu.data.readers import RecordIODataReader
from elasticdl_tpu.parallel.spmd_trainer import SpmdTrainer
from elasticdl_tpu.worker.master_client import MasterClient
from elasticdl_tpu.worker.worker import Worker
from tests.test_utils import create_mnist_recordio
from tests.test_worker_distributed import start_master


def test_worker_with_spmd_trainer(tmp_path):
    train_dir = tmp_path / "train"
    valid_dir = tmp_path / "valid"
    train_dir.mkdir()
    valid_dir.mkdir()
    create_mnist_recordio(str(train_dir / "f0.rec"), num_records=256, seed=0)
    create_mnist_recordio(str(valid_dir / "f0.rec"), num_records=64, seed=1)

    server, dispatcher, evals, port = start_master(
        str(train_dir), str(valid_dir), str(tmp_path / "export"), eval_steps=8
    )
    try:
        worker = Worker(
            MasterClient("localhost:%d" % port, worker_id=0),
            "elasticdl_tpu.models.mnist",
            RecordIODataReader(data_dir=str(train_dir)),
            minibatch_size=32,  # 32 % 8 devices == 0
            report_version_steps=4,
            wait_sleep_secs=0.1,
            trainer_factory=SpmdTrainer,
        )
        worker.run()
        assert dispatcher.finished()
        assert evals.completed_summaries
        _, summary = evals.completed_summaries[-1]
        assert summary["accuracy"] > 0.8
    finally:
        server.stop(None)
