"""N workers sharing ONE model on the sparse path — live processes.

The reference's flagship CTR scenario: N workers train one DeepFM
concurrently, dense updates shared through the PS
(/root/reference/elasticdl/python/worker/worker.py:297-336), embedding
grads applied sync (ps/servicer.py:166-236, grads_to_wait=N) or async
(:120-165). The TPU redesign shares dense state through a lockstep
psum over a process-spanning mesh instead of per-step RPCs
(train/sparse_spmd.py MultiHostSparseSpmdTrainer); this test proves the
redesign delivers the same property with REAL worker processes:

- 2 live `worker.main` processes under jax.distributed, one dp slot
  each, against a live master and 2 live PS shards;
- dense params BIT-IDENTICAL across workers at job end;
- final AUC >= the 1-worker run's (same data, same epochs);
- both PS modes: async, and sync with grads_to_wait=2 (each worker's
  round-k push arrives at store version k — no spurious rejections).
"""

import os
import subprocess
import sys
import time

import numpy as np
import pytest

from elasticdl_tpu.common.grpc_utils import build_server, find_free_port
from elasticdl_tpu.data.readers import RecordIODataReader
from elasticdl_tpu.master.evaluation_service import EvaluationService
from elasticdl_tpu.master.rendezvous import MeshRendezvous
from elasticdl_tpu.master.servicer import MasterServicer
from elasticdl_tpu.master.task_dispatcher import TaskDispatcher
from elasticdl_tpu.master.task_monitor import TaskMonitor
from elasticdl_tpu.models import deepfm
from elasticdl_tpu.proto.services import (
    add_master_servicer_to_server,
    add_pserver_servicer_to_server,
)
from elasticdl_tpu.ps.embedding_store import create_store
from elasticdl_tpu.ps.servicer import PserverServicer
from elasticdl_tpu.worker.master_client import MasterClient
from elasticdl_tpu.worker.worker import Worker
from tests.test_utils import create_ctr_recordio, spawn_ps_process

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _spawn_worker(idx, master_port, coordinator_port, train_dir,
                  ps_addrs, dump_dir, ckpt_dir, log_path):
    env = dict(
        os.environ,
        JAX_PLATFORMS="cpu",
        EDL_FAULTHANDLER="1",
        EDL_DENSE_DUMP_DIR=dump_dir,
        PYTHONPATH=REPO,
        XLA_FLAGS="--xla_force_host_platform_device_count=1",
    )
    log = open(log_path, "ab")
    return subprocess.Popen(
        [sys.executable, "-m", "elasticdl_tpu.worker.main",
         "--master_addr", "localhost:%d" % master_port,
         "--worker_id", str(idx),
         "--model_zoo", "tests.models.deepfm_dump",
         "--training_data", train_dir,
         "--minibatch_size", "64",
         "--multihost", "1",
         "--coordinator_port", str(coordinator_port),
         "--worker_host", "localhost:%d" % (62000 + idx),
         "--ps_addrs", ",".join(ps_addrs),
         "--checkpoint_dir", ckpt_dir,
         "--checkpoint_steps", "2",
         "--report_version_steps", "2"],
        env=env,
        stdout=log,
        stderr=subprocess.STDOUT,
        cwd=REPO,
    )


def _single_worker_auc(tmp_path, train_dir, valid_dir):
    """Baseline: the same job drained by ONE in-process worker."""
    train_reader = RecordIODataReader(data_dir=str(train_dir))
    valid_reader = RecordIODataReader(data_dir=str(valid_dir))
    dispatcher = TaskDispatcher(
        training_shards=train_reader.create_shards(),
        evaluation_shards=valid_reader.create_shards(),
        records_per_task=128,
        num_epochs=2,
        seed=0,
    )
    evals = EvaluationService(
        dispatcher, deepfm.eval_metrics_fn, eval_steps=24
    )
    master_server = build_server()
    add_master_servicer_to_server(
        MasterServicer(dispatcher, evals), master_server
    )
    master_port = find_free_port()
    master_server.add_insecure_port("localhost:%d" % master_port)
    master_server.start()
    ps_servers, ps_addrs = [], []
    for ps_id in range(2):
        store = create_store(seed=ps_id)
        store.set_optimizer("adam", lr=0.01)
        server = build_server()
        add_pserver_servicer_to_server(
            PserverServicer(store, ps_id=ps_id), server
        )
        port = find_free_port()
        server.add_insecure_port("localhost:%d" % port)
        server.start()
        ps_servers.append(server)
        ps_addrs.append("localhost:%d" % port)
    try:
        worker = Worker(
            MasterClient("localhost:%d" % master_port, worker_id=0),
            "elasticdl_tpu.models.deepfm",
            RecordIODataReader(data_dir=str(train_dir)),
            minibatch_size=64,
            report_version_steps=4,
            wait_sleep_secs=0.1,
            ps_addrs=ps_addrs,
        )
        worker.run()
        assert dispatcher.finished()
        _, summary = evals.completed_summaries[-1]
        return summary["auc"]
    finally:
        master_server.stop(None)
        for server in ps_servers:
            server.stop(None)


def _read_dump_step(path):
    """Best-effort __step from a worker dump (None if absent/mid-write)."""
    try:
        with np.load(str(path)) as dump:
            return int(dump["__step"])
    except Exception:
        return None


def _run_two_worker_job(tmp_path, use_async, grads_to_wait,
                        kill_worker_after_step=None, deadline_secs=420,
                        kill_ps_after_step=None):
    """Drive the 2-worker lockstep sparse job to completion and return
    (dispatcher, evals, dump_dir, relaunches, logs, auc_single).

    With ``kill_worker_after_step=k``: once worker 1's dense dump shows
    step >= k AND a checkpoint exists, SIGKILL worker 1 mid-round — the
    deliberate-failure arm (reference: the instance manager relaunches
    killed worker pods,
    /root/reference/elasticdl/python/master/k8s_instance_manager.py:282-328).
    The supervisor then relaunches exactly as the pod manager would.

    With ``kill_ps_after_step=k``: once worker 1's dump shows step >= k
    AND PS shard 0 has committed a sparse checkpoint, SIGKILL PS 0 and
    relaunch it on the SAME port with ``--checkpoint_dir_for_init`` —
    the stable-Service PS relaunch (reference: same-id PS pod behind a
    per-pod Service). Both workers' PS clients must bridge the outage
    inside their retry budgets; no worker restart should be needed.
    """
    train_dir = tmp_path / "train"
    valid_dir = tmp_path / "valid"
    dump_dir = tmp_path / "dumps"
    ckpt_dir = tmp_path / "ckpt"
    train_dir.mkdir()
    valid_dir.mkdir()
    dump_dir.mkdir()
    create_ctr_recordio(str(train_dir / "f0.rec"), num_records=1024, seed=0)
    create_ctr_recordio(str(valid_dir / "f0.rec"), num_records=256, seed=1)

    auc_single = _single_worker_auc(tmp_path, train_dir, valid_dir)

    train_reader = RecordIODataReader(data_dir=str(train_dir))
    valid_reader = RecordIODataReader(data_dir=str(valid_dir))
    # 4 epochs (vs the baseline's 2): a lockstep round is ONE update
    # over a 2x-bigger global batch, so matching the baseline's update
    # count needs twice the passes — the v12 eval then compares equal
    # update counts, the 2-worker one with 2x the records per update
    dispatcher = TaskDispatcher(
        training_shards=train_reader.create_shards(),
        evaluation_shards=valid_reader.create_shards(),
        records_per_task=128,
        num_epochs=4,
        seed=0,
    )
    evals = EvaluationService(
        dispatcher, deepfm.eval_metrics_fn, eval_steps=12
    )
    rendezvous = MeshRendezvous()
    servicer = MasterServicer(dispatcher, evals, rendezvous=rendezvous)
    monitor = TaskMonitor(
        dispatcher,
        servicer,
        rendezvous=rendezvous,
        liveness_timeout_secs=60.0,
        scan_interval_secs=0.5,
        mesh_restart_grace_secs=30.0,
    )
    master_server = build_server()
    add_master_servicer_to_server(servicer, master_server)
    master_port = find_free_port()
    master_server.add_insecure_port("localhost:%d" % master_port)
    master_server.start()
    monitor.start()

    ps_ckpt = tmp_path / "ps_ckpt"
    ps_extra = ()
    if kill_ps_after_step is not None:
        # BOTH shards must checkpoint into the shared dir: a version is
        # only restorable once every shard's file exists
        # (SparseCheckpointSaver._complete — no silent partial restore)
        ps_extra = (
            "--checkpoint_dir", str(ps_ckpt), "--checkpoint_steps", "2",
        )
    ps_procs, ps_addrs, ps_ports = [], [], []
    for ps_id in range(2):
        proc, port = spawn_ps_process(
            ps_id=ps_id, num_ps_pods=2, use_async=use_async,
            grads_to_wait=grads_to_wait,
            log_path=str(tmp_path / ("ps%d.log" % ps_id)),
            extra=ps_extra,
        )
        ps_procs.append(proc)
        ps_ports.append(port)
        ps_addrs.append("localhost:%d" % port)
    coordinator_port = find_free_port()
    workers = {}
    relaunches = {0: 0, 1: 0}
    chaos = {"killed": False}
    logs = {i: str(tmp_path / ("worker%d.log" % i)) for i in (0, 1)}
    try:
        for i in (0, 1):
            workers[i] = _spawn_worker(
                i, master_port, coordinator_port, str(train_dir),
                ps_addrs, str(dump_dir), str(ckpt_dir), logs[i],
            )

        def supervise():
            """Pod-manager stand-in: the jax.distributed join is
            inherently racy at different startup times (a late joiner
            against a world-of-1 coordinator aborts fatally), and the
            recovery model is relaunch-and-rejoin at the bumped mesh
            epoch — same as tests/test_multihost_e2e.py."""
            for i, proc in list(workers.items()):
                if proc.poll() is None:
                    continue
                relaunches[i] += 1
                assert relaunches[i] < 12, (
                    "worker %d restart-looped: %s"
                    % (i, open(logs[i]).read()[-2500:])
                )
                workers[i] = _spawn_worker(
                    i, master_port, coordinator_port, str(train_dir),
                    ps_addrs, str(dump_dir), str(ckpt_dir), logs[i],
                )

        def maybe_kill():
            if kill_worker_after_step is None or chaos["killed"]:
                return
            step = _read_dump_step(dump_dir / "worker1.npz")
            if step is None or step < kill_worker_after_step:
                return
            if not ckpt_dir.exists() or not any(ckpt_dir.glob("*")):
                return  # wait for a committed checkpoint first
            if workers[1].poll() is None:
                os.kill(workers[1].pid, 9)
                chaos["killed"] = True

        def maybe_kill_ps():
            if kill_ps_after_step is None or chaos.get("ps_killed"):
                return
            step = _read_dump_step(dump_dir / "worker1.npz")
            if step is None or step < kill_ps_after_step:
                return
            # gate on a COMPLETE (all-shards, fully written) version —
            # a bare directory listing would pass on a mid-write save
            # and the SIGKILL could then corrupt the restore source
            from elasticdl_tpu.ps.checkpoint import SparseCheckpointSaver

            if SparseCheckpointSaver.latest_version(str(ps_ckpt)) is None:
                return
            os.kill(ps_procs[0].pid, 9)
            ps_procs[0].wait(timeout=30)
            time.sleep(1.5)  # let both workers hit the outage window
            ps_procs[0], _ = spawn_ps_process(
                ps_id=0, num_ps_pods=2, use_async=use_async,
                grads_to_wait=grads_to_wait,
                log_path=str(tmp_path / "ps0.log"),
                extra=ps_extra + (
                    "--checkpoint_dir_for_init", str(ps_ckpt),
                ),
                port=ps_ports[0],
            )
            chaos["ps_killed"] = True

        deadline = time.time() + deadline_secs
        while time.time() < deadline and not dispatcher.finished():
            supervise()
            maybe_kill()
            maybe_kill_ps()
            time.sleep(0.5)
        assert dispatcher.finished(), (
            "job never finished; worker0 log tail: %s"
            % open(logs[0]).read()[-2500:]
        )
        for proc in workers.values():
            proc.wait(timeout=60)
        if kill_worker_after_step is not None:
            assert chaos["killed"], (
                "job finished before the chaos kill could fire "
                "(worker1 never reached step %d with a checkpoint)"
                % kill_worker_after_step
            )
        if kill_ps_after_step is not None:
            assert chaos.get("ps_killed"), (
                "job finished before the PS chaos kill could fire "
                "(PS 0 never checkpointed by worker step %d)"
                % kill_ps_after_step
            )
        return dispatcher, evals, dump_dir, relaunches, logs, auc_single
    finally:
        for proc in workers.values():
            if proc.poll() is None:
                proc.kill()
        for proc in ps_procs:
            proc.terminate()
        monitor.stop()
        master_server.stop(0)


def _assert_shared_model(dump_dir, evals, auc_single,
                         max_push_rejections=None, auc_slack=0.03):
    # (a) dense params bit-identical across the two workers
    dump0 = np.load(str(dump_dir / "worker0.npz"))
    dump1 = np.load(str(dump_dir / "worker1.npz"))
    assert int(dump0["__step"]) == int(dump1["__step"]) > 0
    assert set(dump0.files) == set(dump1.files)
    for key in dump0.files:
        if key == "__push_rejections":
            continue  # per-process retry counter, legitimately differs
        np.testing.assert_array_equal(
            dump0[key], dump1[key],
            err_msg="dense param %s diverged across workers" % key,
        )

    # (b) converged comparably to the 1-worker run. Best summary,
    # not last: with this tiny dataset the tail of the run
    # overfits, and per-round PS-apply cadence differs by mode
    # (async applies once per worker push) — both runs are judged
    # by the best model they produced.
    assert evals.completed_summaries
    auc = max(s["auc"] for _, s in evals.completed_summaries)
    assert auc > 0.72
    assert auc >= auc_single - auc_slack, (
        "2-worker best AUC %.4f fell below 1-worker %.4f"
        % (auc, auc_single)
    )

    if max_push_rejections is not None:
        # no version-rejection storm: each worker's final process
        # (for worker 1, the relaunched one exercising the
        # state.step round-recovery from a non-zero step,
        # train/sparse_spmd.py:456-473) resolved its push version in
        # a bounded number of sync-PS retries
        for dump in (dump0, dump1):
            assert int(dump["__push_rejections"]) <= max_push_rejections


@pytest.mark.slow
@pytest.mark.parametrize(
    "use_async,grads_to_wait", [(True, 1), (False, 2)],
    ids=["async_ps", "sync_ps_wait2"],
)
def test_two_workers_share_one_model(tmp_path, use_async, grads_to_wait):
    _, evals, dump_dir, _, _, auc_single = _run_two_worker_job(
        tmp_path, use_async, grads_to_wait
    )
    _assert_shared_model(dump_dir, evals, auc_single)


@pytest.mark.slow
@pytest.mark.parametrize(
    "use_async,grads_to_wait", [(True, 1), (False, 2)],
    ids=["async_ps", "sync_ps_wait2"],
)
def test_sigkill_worker_mid_training_recovers(
    tmp_path, use_async, grads_to_wait
):
    """Deliberate-failure arm of the flagship scenario: SIGKILL worker 1
    once it has trained past its first committed checkpoint, let the
    supervisor relaunch it, and require the job to end with the same
    guarantees as the healthy run — completion, bit-identical dense
    params, AUC floor — plus a bounded sync-PS retry count (the
    relaunched worker's round counter recovers from the restored
    ``state.step``, so its pushes are not version-rejected in a storm).
    Dense-twin precedent: tests/test_multihost_e2e.py SIGKILL e2e."""
    _, evals, dump_dir, relaunches, _, auc_single = _run_two_worker_job(
        tmp_path, use_async, grads_to_wait,
        kill_worker_after_step=3, deadline_secs=600,
    )
    assert relaunches[1] >= 1  # the kill really forced a relaunch
    _assert_shared_model(
        dump_dir, evals, auc_single, max_push_rejections=8,
        # a mid-round kill can cost up to a round of progress on this
        # tiny dataset; the absolute floor above still binds
        auc_slack=0.05,
    )


@pytest.mark.slow
@pytest.mark.parametrize(
    "use_async,grads_to_wait", [(True, 1), (False, 2)],
    ids=["async_ps", "sync_ps_wait2"],
)
def test_sigkill_ps_mid_training_recovers(
    tmp_path, use_async, grads_to_wait
):
    """The other half of the chaos matrix for the flagship scenario:
    SIGKILL a PS SHARD (not a worker) mid-training once it has
    committed a sparse checkpoint, relaunch it on the same port with
    checkpoint restore, and require the 2-worker lockstep job to
    complete with the shared-model guarantees intact — both workers'
    PS clients bridging the outage inside their retry budgets, no
    worker restart required. Single-worker precedent:
    tests/test_chaos.py::test_ps_crash_restart_job_completes."""
    _, evals, dump_dir, relaunches, _, auc_single = _run_two_worker_job(
        tmp_path, use_async, grads_to_wait,
        kill_ps_after_step=3, deadline_secs=600,
    )
    # the relaunched shard really restored (not an empty-store restart:
    # SparseCheckpointSaver.restore logs this only on success)
    assert "Restored sparse checkpoint" in open(
        tmp_path / "ps0.log"
    ).read()
    _assert_shared_model(
        dump_dir, evals, auc_single, max_push_rejections=8,
        # the PS outage + restore-from-checkpoint can replay/lose a
        # couple of sparse applies; the absolute floor still binds
        auc_slack=0.05,
    )
