"""Real-process multi-host elasticity, end to end.

The scenario VERDICT.md round 1 asked for (and the reference exercised
with live Horovod re-init, allreduce_trainer_test.py): two worker OS
processes with LIVE ``jax.distributed.initialize`` training one job in
lockstep over a mesh spanning both; one is SIGKILLed; the master's
liveness scan evicts it and bumps the mesh epoch; the survivor — which
the jax coordination service fatally aborts on peer death (measured
behavior, multihost_trainer.py docstring) — is relaunched by the
pod-manager-style supervisor, re-initializes at the new epoch with
world size 1, restores from the checkpoint, and drains the job.
"""

import os
import signal
import subprocess
import sys
import time

import pytest

from elasticdl_tpu.common.grpc_utils import build_server, find_free_port
from elasticdl_tpu.data.readers import RecordIODataReader
from elasticdl_tpu.master.rendezvous import MeshRendezvous
from elasticdl_tpu.master.servicer import MasterServicer
from elasticdl_tpu.master.task_dispatcher import TaskDispatcher
from elasticdl_tpu.master.task_monitor import TaskMonitor
from elasticdl_tpu.proto.services import add_master_servicer_to_server
from tests.test_utils import create_mnist_recordio

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _spawn_worker(idx, master_port, coordinator_port, train_dir,
                  ckpt_dir, log_path, devices_per_proc=1, mesh=""):
    env = dict(
        os.environ,
        JAX_PLATFORMS="cpu",
        EDL_FAULTHANDLER="1",
        PYTHONPATH=REPO,
        # workers must NOT inherit the test session's 8 virtual devices:
        # devices_per_proc local devices per worker process (1 keeps the
        # global mesh 2 x 1; 4 with --mesh fsdp=4 exercises in-host
        # model parallelism under a process-spanning mesh)
        XLA_FLAGS="--xla_force_host_platform_device_count=%d"
        % devices_per_proc,
    )
    log = open(log_path, "ab")
    log.write(b"\n===== incarnation spawn =====\n")
    log.flush()
    cmd = [
        sys.executable, "-m", "elasticdl_tpu.worker.main",
        "--master_addr", "localhost:%d" % master_port,
        "--worker_id", str(idx),
        "--model_zoo", "elasticdl_tpu.models.mnist",
        "--training_data", train_dir,
        "--minibatch_size", "32",
        "--multihost", "1",
        "--coordinator_port", str(coordinator_port),
        "--worker_host", "localhost:%d" % (61000 + idx),
        "--checkpoint_dir", ckpt_dir,
        "--checkpoint_steps", "2",
    ]
    if mesh:
        cmd += ["--mesh", mesh]
    return subprocess.Popen(
        cmd,
        env=env,
        stdout=log,
        stderr=subprocess.STDOUT,
        cwd=REPO,
    )


@pytest.mark.slow
@pytest.mark.parametrize(
    "devices_per_proc,mesh",
    [
        (1, ""),  # v1 scenario: dp-only 2x1 mesh, one device per host
        # v2 scenario: dp spans the 2 processes, fsdp=4 inside each —
        # state is fsdp-sharded (mnist's big kernels exceed the
        # fsdp_auto_spec threshold), so checkpoint save/restore runs the
        # make_array-aware global-Array path, and the post-kill restart
        # re-shards the 8-device checkpoint onto the survivor's 1x4 mesh
        (4, "fsdp=4"),
    ],
    ids=["dp_only", "fsdp_inhost"],
)
def test_kill_one_host_epoch_bump_reinit_restore_completes(
    tmp_path, devices_per_proc, mesh
):
    train_dir = tmp_path / "train"
    train_dir.mkdir()
    create_mnist_recordio(
        str(train_dir / "f0.rec"), num_records=1024, seed=0
    )
    reader = RecordIODataReader(data_dir=str(train_dir))
    dispatcher = TaskDispatcher(
        training_shards=reader.create_shards(),
        records_per_task=128,
        num_epochs=1,
        seed=0,
    )
    # master-side log trail for post-mortems (the master runs in-process)
    import logging

    master_log = str(tmp_path / "master.log")
    handler = logging.FileHandler(master_log)
    handler.setFormatter(logging.Formatter("%(asctime)s %(message)s"))
    for name in (
        "elasticdl_tpu.master.rendezvous",
        "elasticdl_tpu.master.task_monitor",
    ):
        logging.getLogger(name).addHandler(handler)

    rendezvous = MeshRendezvous()
    servicer = MasterServicer(dispatcher, None, rendezvous=rendezvous)
    monitor = TaskMonitor(
        dispatcher,
        servicer,
        rendezvous=rendezvous,
        # must exceed the joiner crash-loop cycle (python + jax import
        # then the fatal abort against a not-yet-restarted coordinator:
        # ~8 s unloaded, ~15 s under CI load) — each loop iteration
        # touches liveness once
        liveness_timeout_secs=30.0,
        scan_interval_secs=0.3,
        # must exceed a worker's relaunch latency (~12-15 s of python +
        # jax import) or the restart gap itself evicts members and the
        # epoch churns — see TaskMonitor.__init__
        mesh_restart_grace_secs=25.0,
    )
    server = build_server()
    add_master_servicer_to_server(servicer, server)
    master_port = find_free_port()
    server.add_insecure_port("localhost:%d" % master_port)
    server.start()
    monitor.start()

    coordinator_port = find_free_port()
    ckpt_dir = str(tmp_path / "ckpt")
    logs = {i: str(tmp_path / ("worker%d.log" % i)) for i in (0, 1)}
    procs = {}
    relaunches = {0: 0, 1: 0}
    killed = set()
    try:
        for i in (0, 1):
            procs[i] = _spawn_worker(
                i, master_port, coordinator_port, str(train_dir),
                ckpt_dir, logs[i], devices_per_proc, mesh,
            )

        def supervise():
            """Pod-manager stand-in: relaunch any non-killed worker that
            exits while the job is unfinished (epoch restarts AND the
            coordination service's fatal abort on peer death)."""
            for i, proc in list(procs.items()):
                if i in killed or proc.poll() is None:
                    continue
                relaunches[i] += 1
                print(
                    "[supervisor] relaunch worker %d (rc=%s, n=%d)"
                    % (i, proc.returncode, relaunches[i]),
                    flush=True,
                )
                assert relaunches[i] < 12, (
                    "worker %d restart-looped; see %s" % (i, logs[i])
                )
                procs[i] = _spawn_worker(
                    i, master_port, coordinator_port, str(train_dir),
                    ckpt_dir, logs[i], devices_per_proc, mesh,
                )

        def committed_checkpoints():
            """COMMITTED checkpoint steps only: an orbax save interrupted
            by the kill leaves a '<step>.orbax-checkpoint-tmp' dir that
            is not restorable — killing on its existence makes the
            survivor legitimately fresh-init instead of resume."""
            if not os.path.isdir(ckpt_dir):
                return []
            return [
                entry for entry in os.listdir(ckpt_dir)
                if entry.isdigit()
            ]

        # Phase 1: both workers join one mesh and make real progress
        deadline = time.time() + 240
        while time.time() < deadline:
            supervise()
            if len(rendezvous.hosts()) == 2 and committed_checkpoints():
                break
            time.sleep(0.5)
        assert len(rendezvous.hosts()) == 2, "second host never joined"
        assert committed_checkpoints(), "no checkpoint written before kill"
        epoch_before = rendezvous.mesh_epoch

        # Phase 2: kill worker 1 without ceremony
        killed.add(1)
        procs[1].send_signal(signal.SIGKILL)
        procs[1].wait(timeout=30)

        # Phase 3: liveness eviction bumps the epoch; the survivor is
        # relaunched (coordination-service abort or epoch restart) and
        # drains the job at world size 1 from the checkpoint
        deadline = time.time() + 300
        while time.time() < deadline and not dispatcher.finished():
            supervise()
            time.sleep(0.5)
        assert dispatcher.finished(), (
            "job never completed after the kill; worker log tail: %s"
            % open(logs[0]).read()[-2000:]
        )
        assert not dispatcher.job_failed()
        assert rendezvous.mesh_epoch > epoch_before, (
            "mesh epoch never bumped on host death"
        )
        assert rendezvous.hosts() == ["localhost:61000"]

        log0 = open(logs[0]).read()
        # the survivor really crossed the jax.distributed boundary:
        # initialized in a 2-host world, then re-initialized alone
        assert "rank 0/2" in log0 or "rank 1/2" in log0, log0[-2000:]
        assert "rank 0/1" in log0
        assert "Resumed from checkpoint" in log0
        assert relaunches[0] >= 1, "survivor was never relaunched"
        if mesh:
            # the fsdp extent really was in the process-spanning mesh
            # (2-host phase) and in the survivor's post-restart mesh
            assert "'fsdp': 4" in log0, log0[-2000:]
    finally:
        for proc in procs.values():
            if proc.poll() is None:
                proc.kill()
        monitor.stop()
        server.stop(0)
