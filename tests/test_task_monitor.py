import time

from elasticdl_tpu.master.rendezvous import MeshRendezvous
from elasticdl_tpu.master.servicer import MasterServicer
from elasticdl_tpu.master.task_dispatcher import TaskDispatcher
from elasticdl_tpu.master.task_monitor import TaskMonitor
from elasticdl_tpu.proto import elasticdl_tpu_pb2 as pb


def test_silent_worker_recovered_and_mesh_epoch_bumped():
    dispatcher = TaskDispatcher(
        training_shards={"f": (0, 10)}, records_per_task=5, num_epochs=1
    )
    rendezvous = MeshRendezvous()
    servicer = MasterServicer(dispatcher, rendezvous=rendezvous)
    monitor = TaskMonitor(
        dispatcher,
        servicer,
        rendezvous,
        liveness_timeout_secs=0.3,
        scan_interval_secs=0.05,
        # scale the mesh-restart allowances with the test's tiny
        # liveness timeout (production defaults are 30s/90s)
        mesh_restart_grace_secs=0.2,
        mesh_rejoin_timeout_secs=0.6,
    )
    # worker 1 joins the mesh and takes a task
    info = servicer.get_comm_info(
        pb.GetCommInfoRequest(worker_id=1, worker_host="h1:1")
    )
    assert info.rank == 0 and info.world_size == 1
    epoch_before = rendezvous.mesh_epoch
    task = servicer.get_task(pb.GetTaskRequest(worker_id=1))
    assert task.task_id > 0

    monitor.start()
    try:
        deadline = time.time() + 5
        while dispatcher.doing_tasks() and time.time() < deadline:
            time.sleep(0.05)
        # task recovered, host evicted, epoch bumped
        assert not dispatcher.doing_tasks()
        assert rendezvous.mesh_epoch > epoch_before
        assert rendezvous.hosts() == []
        # the task is back in the queue (at the tail) for another worker
        seen = set()
        while True:
            t2 = servicer.get_task(pb.GetTaskRequest(worker_id=2))
            seen.add(t2.task_id)
            if t2.task_id == task.task_id:
                break
        assert task.task_id in seen
        # a stale report from the presumed-dead worker is ignored
        servicer.report_task_result(
            pb.ReportTaskResultRequest(task_id=t2.task_id, worker_id=1)
        )
        assert dispatcher.doing_tasks()  # still held by worker 2
        # worker 1 heartbeats again -> rejoins the mesh cleanly
        servicer.get_comm_info(
            pb.GetCommInfoRequest(worker_id=1, worker_host="h1:1")
        )
        assert rendezvous.hosts() == ["h1:1"]
    finally:
        monitor.stop()


def test_idle_mesh_member_evicted_on_silence():
    """A mesh member holding no tasks must still be evicted when silent
    (a ghost in the rendezvous wedges jax.distributed's world size)."""
    import time

    from elasticdl_tpu.master.rendezvous import MeshRendezvous
    from elasticdl_tpu.master.servicer import MasterServicer
    from elasticdl_tpu.master.task_dispatcher import TaskDispatcher
    from elasticdl_tpu.master.task_monitor import TaskMonitor
    from elasticdl_tpu.proto import elasticdl_tpu_pb2 as pb

    dispatcher = TaskDispatcher(
        training_shards={"t": (0, 4)}, records_per_task=2, num_epochs=1
    )
    rendezvous = MeshRendezvous()
    servicer = MasterServicer(dispatcher, None, rendezvous)
    monitor = TaskMonitor(
        dispatcher, servicer, rendezvous, liveness_timeout_secs=0.05,
        mesh_restart_grace_secs=0.02, mesh_rejoin_timeout_secs=0.08,
    )
    # idle member joins the mesh via get_comm_info, never takes a task
    servicer.get_comm_info(
        pb.GetCommInfoRequest(worker_id=7, worker_host="ghost:3333")
    )
    assert rendezvous.hosts() == ["ghost:3333"]
    time.sleep(0.1)
    # first scan sees the join's epoch bump and credits the restart
    # allowance; eviction happens once that horizon + the liveness
    # timeout pass with no ping
    monitor._scan()
    time.sleep(0.15)
    monitor._scan()
    assert rendezvous.hosts() == []


def test_first_compile_task_survives_fast_fleet_average():
    """The task-timeout threshold is floored at the liveness timeout: a
    fleet of 0.1 s tasks must not drag the threshold so low that a
    heartbeating fresh worker's first task (carrying its jit compile)
    is falsely recovered (observed live in the ISSUE 3 chaos drive)."""
    import time

    from elasticdl_tpu.master.servicer import MasterServicer
    from elasticdl_tpu.master.task_dispatcher import TaskDispatcher
    from elasticdl_tpu.master.task_monitor import TaskMonitor
    from elasticdl_tpu.proto import elasticdl_tpu_pb2 as pb

    dispatcher = TaskDispatcher(
        training_shards={"t": (0, 64)}, records_per_task=2, num_epochs=1
    )
    servicer = MasterServicer(dispatcher, None)
    monitor = TaskMonitor(
        dispatcher, servicer, None, liveness_timeout_secs=0.6,
        scan_interval_secs=0.05,
    )
    # train the rolling average down to "fast" (>= 20 samples)
    for _ in range(24):
        task = servicer.get_task(pb.GetTaskRequest(worker_id=1))
        dispatcher.report(task.task_id, success=True, worker_id=1)
    assert dispatcher.avg_task_duration() < 0.05
    # a fresh worker takes its first task and compiles: slower than
    # 3x the fleet average, but heartbeating the whole time
    task = servicer.get_task(pb.GetTaskRequest(worker_id=2))
    deadline = time.time() + 0.3  # > 3x avg, < the liveness floor
    while time.time() < deadline:
        servicer.get_comm_info(pb.GetCommInfoRequest(worker_id=2))
        monitor._scan()
        time.sleep(0.05)
    assert task.task_id in dispatcher.doing_tasks(), (
        "compile-length first task was falsely recovered"
    )
    # a worker that actually goes silent past the liveness floor is
    # still recovered
    time.sleep(0.7)
    monitor._scan()
    assert task.task_id not in dispatcher.doing_tasks()
