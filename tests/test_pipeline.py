"""Pipeline parallelism: schedule correctness and end-to-end training.

Mirrors the reference's tier-2 strategy (SURVEY.md §4) — distributed
behavior exercised without hardware, here on the 8-virtual-device CPU
mesh — for the pp axis the reference never had (SURVEY.md §2.12).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from elasticdl_tpu.models import pipeline_transformer, transformer
from elasticdl_tpu.parallel.mesh import MeshConfig, build_mesh
from elasticdl_tpu.parallel.pipeline import (
    pipeline_apply,
    stack_stage_params,
    unstack_stage_params,
)
from elasticdl_tpu.parallel.spmd_trainer import SpmdTrainer


def _affine_stages(num_stages, dim=8, seed=0):
    rng = np.random.RandomState(seed)
    return [
        dict(
            W=jnp.asarray(rng.randn(dim, dim) * 0.3, jnp.float32),
            b=jnp.asarray(rng.randn(dim) * 0.1, jnp.float32),
        )
        for _ in range(num_stages)
    ]


def _stage_fn(p, x):
    return jnp.tanh(x @ p["W"] + p["b"])


def _sequential(params_list, x):
    for p in params_list:
        x = _stage_fn(p, x)
    return x


def test_pipeline_forward_matches_sequential():
    mesh = build_mesh(MeshConfig(dp=2, pp=4))
    params = _affine_stages(4)
    stacked = stack_stage_params(params)
    x = jnp.asarray(np.random.RandomState(1).randn(16, 8), jnp.float32)

    out = jax.jit(
        lambda sp, x: pipeline_apply(
            _stage_fn, sp, x, num_microbatches=4, mesh=mesh
        )
    )(stacked, x)
    ref = _sequential(params, x)
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(ref), atol=1e-5
    )


def test_pipeline_gradients_match_sequential():
    mesh = build_mesh(MeshConfig(dp=2, pp=4))
    params = _affine_stages(4, seed=2)
    stacked = stack_stage_params(params)
    x = jnp.asarray(np.random.RandomState(3).randn(8, 8), jnp.float32)

    g_pipe = jax.jit(
        jax.grad(
            lambda sp: jnp.mean(
                pipeline_apply(_stage_fn, sp, x, 2, mesh) ** 2
            )
        )
    )(stacked)
    g_seq = jax.grad(
        lambda ps: jnp.mean(_sequential(ps, x) ** 2)
    )(params)
    for a, b in zip(
        jax.tree_util.tree_leaves(g_pipe),
        jax.tree_util.tree_leaves(stack_stage_params(g_seq)),
    ):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), atol=1e-5
        )


def test_microbatch_count_independence():
    """The schedule must be a pure implementation detail: any M gives
    identical outputs."""
    mesh = build_mesh(MeshConfig(dp=1, pp=4), num_devices=4)
    params = _affine_stages(4, seed=4)
    stacked = stack_stage_params(params)
    x = jnp.asarray(np.random.RandomState(5).randn(12, 8), jnp.float32)
    outs = [
        np.asarray(
            jax.jit(
                lambda sp, x, m=m: pipeline_apply(
                    _stage_fn, sp, x, m, mesh
                )
            )(stacked, x)
        )
        for m in (1, 2, 4, 6)
    ]
    for other in outs[1:]:
        np.testing.assert_allclose(outs[0], other, atol=1e-5)


def test_stack_unstack_roundtrip():
    params = _affine_stages(3, seed=6)
    stacked = stack_stage_params(params)
    unstacked = unstack_stage_params(stacked, 3)
    for orig, back in zip(params, unstacked):
        for a, b in zip(
            jax.tree_util.tree_leaves(orig),
            jax.tree_util.tree_leaves(back),
        ):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def _lm_batch(batch=8, seq=16, vocab=64, seed=0):
    rng = np.random.RandomState(seed)
    tokens = rng.randint(0, vocab, size=(batch, seq)).astype(np.int32)
    return {
        "features": tokens,
        "labels": tokens,
        "_mask": np.ones((batch,), np.float32),
    }


def test_pipelined_lm_matches_sequential_fallback():
    """Same params through the pp=4 pipeline and the meshless sequential
    path must produce identical logits."""
    mesh = build_mesh(MeshConfig(dp=2, pp=4))
    kwargs = dict(
        vocab_size=64,
        num_layers=8,
        num_stages=4,
        num_heads=2,
        embed_dim=16,
        num_microbatches=2,
        attention_impl="xla",
    )
    piped = pipeline_transformer.PipelinedTransformerLM(
        mesh=mesh, **kwargs
    )
    seq_model = pipeline_transformer.PipelinedTransformerLM(
        mesh=None, **kwargs
    )
    batch = _lm_batch()
    variables = piped.init(jax.random.PRNGKey(0), batch["features"])
    out_piped = jax.jit(
        lambda v, t: piped.apply(v, t, training=False)
    )(variables, batch["features"])
    out_seq = jax.jit(
        lambda v, t: seq_model.apply(v, t, training=False)
    )(variables, batch["features"])
    np.testing.assert_allclose(
        np.asarray(out_piped), np.asarray(out_seq), atol=1e-4
    )


def test_zoo_contract_mesh_injection():
    """The model-zoo entry must build a pipeline matching the mesh's pp
    extent when given a mesh (the worker passes its trainer mesh), and a
    sequential model when not."""
    from elasticdl_tpu.models.registry import get_model_spec

    spec = get_model_spec("elasticdl_tpu.models.pipeline_transformer")
    mesh = build_mesh(MeshConfig(dp=2, pp=4))
    model = spec.custom_model(mesh=mesh)
    assert model.num_stages == 4
    assert model.mesh is mesh
    assert spec.custom_model().mesh is None
    config = spec.mesh_config(8)
    assert config.pp == 4 and config.dp == 2


def test_param_layout_is_topology_independent():
    """Checkpoints must restore across pp extents: init() leaf shapes
    cannot depend on num_stages, and a non-divisor pp must raise rather
    than silently change depth."""
    batch = _lm_batch()
    kwargs = dict(
        vocab_size=64, num_layers=8, num_heads=2, embed_dim=16
    )
    v4 = pipeline_transformer.PipelinedTransformerLM(
        num_stages=4, **kwargs
    ).init(jax.random.PRNGKey(0), batch["features"])
    v2 = pipeline_transformer.PipelinedTransformerLM(
        num_stages=2, **kwargs
    ).init(jax.random.PRNGKey(0), batch["features"])
    for a, b in zip(
        jax.tree_util.tree_leaves(v4), jax.tree_util.tree_leaves(v2)
    ):
        assert a.shape == b.shape
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

    with pytest.raises(ValueError, match="not divisible"):
        pipeline_transformer.PipelinedTransformerLM(
            num_stages=3, **kwargs
        )


def test_pipelined_lm_trains_on_pp_mesh():
    mesh = build_mesh(MeshConfig(dp=2, pp=4))
    model = pipeline_transformer.PipelinedTransformerLM(
        vocab_size=64,
        num_layers=4,
        num_stages=4,
        num_heads=2,
        embed_dim=16,
        num_microbatches=2,
        attention_impl="xla",
        mesh=mesh,
    )
    trainer = SpmdTrainer(
        model=model,
        loss_fn=pipeline_transformer.loss,
        optimizer=transformer.optimizer(),
        mesh=mesh,
        seed=0,
        sharding_rules=pipeline_transformer.sharding_rules(),
    )
    batch = _lm_batch(batch=8, seq=16)
    state = trainer.create_state(batch["features"])

    # Stage params (and their optimizer state) must actually shard over pp.
    blocks_sh = trainer.state_shardings.params["blocks"]
    leaf = jax.tree_util.tree_leaves(blocks_sh)[0]
    assert leaf.spec[0] == "pp"

    losses = []
    for _ in range(5):
        state, loss = trainer.train_step(state, batch)
        losses.append(float(loss))
    assert np.isfinite(losses).all()
    assert losses[-1] < losses[0]
