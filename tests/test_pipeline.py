"""Pipeline parallelism: schedule correctness and end-to-end training.

Mirrors the reference's tier-2 strategy (SURVEY.md §4) — distributed
behavior exercised without hardware, here on the 8-virtual-device CPU
mesh — for the pp axis the reference never had (SURVEY.md §2.12).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from elasticdl_tpu.models import pipeline_transformer, transformer
from elasticdl_tpu.parallel.mesh import MeshConfig, build_mesh
from elasticdl_tpu.parallel.pipeline import (
    pipeline_apply,
    stack_stage_params,
    unstack_stage_params,
)
from elasticdl_tpu.parallel.spmd_trainer import SpmdTrainer


def _affine_stages(num_stages, dim=8, seed=0):
    rng = np.random.RandomState(seed)
    return [
        dict(
            W=jnp.asarray(rng.randn(dim, dim) * 0.3, jnp.float32),
            b=jnp.asarray(rng.randn(dim) * 0.1, jnp.float32),
        )
        for _ in range(num_stages)
    ]


def _stage_fn(p, x):
    return jnp.tanh(x @ p["W"] + p["b"])


def _sequential(params_list, x):
    for p in params_list:
        x = _stage_fn(p, x)
    return x


def test_pipeline_forward_matches_sequential():
    mesh = build_mesh(MeshConfig(dp=2, pp=4))
    params = _affine_stages(4)
    stacked = stack_stage_params(params)
    x = jnp.asarray(np.random.RandomState(1).randn(16, 8), jnp.float32)

    out = jax.jit(
        lambda sp, x: pipeline_apply(
            _stage_fn, sp, x, num_microbatches=4, mesh=mesh
        )
    )(stacked, x)
    ref = _sequential(params, x)
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(ref), atol=1e-5
    )


def test_pipeline_gradients_match_sequential():
    mesh = build_mesh(MeshConfig(dp=2, pp=4))
    params = _affine_stages(4, seed=2)
    stacked = stack_stage_params(params)
    x = jnp.asarray(np.random.RandomState(3).randn(8, 8), jnp.float32)

    g_pipe = jax.jit(
        jax.grad(
            lambda sp: jnp.mean(
                pipeline_apply(_stage_fn, sp, x, 2, mesh) ** 2
            )
        )
    )(stacked)
    g_seq = jax.grad(
        lambda ps: jnp.mean(_sequential(ps, x) ** 2)
    )(params)
    for a, b in zip(
        jax.tree_util.tree_leaves(g_pipe),
        jax.tree_util.tree_leaves(stack_stage_params(g_seq)),
    ):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), atol=1e-5
        )


def test_microbatch_count_independence():
    """The schedule must be a pure implementation detail: any M gives
    identical outputs."""
    mesh = build_mesh(MeshConfig(dp=1, pp=4), num_devices=4)
    params = _affine_stages(4, seed=4)
    stacked = stack_stage_params(params)
    x = jnp.asarray(np.random.RandomState(5).randn(12, 8), jnp.float32)
    outs = [
        np.asarray(
            jax.jit(
                lambda sp, x, m=m: pipeline_apply(
                    _stage_fn, sp, x, m, mesh
                )
            )(stacked, x)
        )
        for m in (1, 2, 4, 6)
    ]
    for other in outs[1:]:
        np.testing.assert_allclose(outs[0], other, atol=1e-5)


def test_stack_unstack_roundtrip():
    params = _affine_stages(3, seed=6)
    stacked = stack_stage_params(params)
    unstacked = unstack_stage_params(stacked, 3)
    for orig, back in zip(params, unstacked):
        for a, b in zip(
            jax.tree_util.tree_leaves(orig),
            jax.tree_util.tree_leaves(back),
        ):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def _lm_batch(batch=8, seq=16, vocab=64, seed=0):
    rng = np.random.RandomState(seed)
    tokens = rng.randint(0, vocab, size=(batch, seq)).astype(np.int32)
    return {
        "features": tokens,
        "labels": tokens,
        "_mask": np.ones((batch,), np.float32),
    }


def test_pipelined_lm_matches_sequential_fallback():
    """Same params through the pp=4 pipeline and the meshless sequential
    path must produce identical logits."""
    mesh = build_mesh(MeshConfig(dp=2, pp=4))
    kwargs = dict(
        vocab_size=64,
        num_layers=8,
        num_stages=4,
        num_heads=2,
        embed_dim=16,
        num_microbatches=2,
        attention_impl="xla",
    )
    piped = pipeline_transformer.PipelinedTransformerLM(
        mesh=mesh, **kwargs
    )
    seq_model = pipeline_transformer.PipelinedTransformerLM(
        mesh=None, **kwargs
    )
    batch = _lm_batch()
    variables = piped.init(jax.random.PRNGKey(0), batch["features"])
    out_piped = jax.jit(
        lambda v, t: piped.apply(v, t, training=False)
    )(variables, batch["features"])
    out_seq = jax.jit(
        lambda v, t: seq_model.apply(v, t, training=False)
    )(variables, batch["features"])
    np.testing.assert_allclose(
        np.asarray(out_piped), np.asarray(out_seq), atol=1e-4
    )


def test_zoo_contract_mesh_injection():
    """The model-zoo entry must build a pipeline matching the mesh's pp
    extent when given a mesh (the worker passes its trainer mesh), and a
    sequential model when not."""
    from elasticdl_tpu.models.registry import get_model_spec

    spec = get_model_spec("elasticdl_tpu.models.pipeline_transformer")
    mesh = build_mesh(MeshConfig(dp=2, pp=4))
    model = spec.custom_model(mesh=mesh)
    assert model.num_stages == 4
    assert model.mesh is mesh
    assert spec.custom_model().mesh is None
    config = spec.mesh_config(8)
    assert config.pp == 4 and config.dp == 2


def test_param_layout_is_topology_independent():
    """Checkpoints must restore across pp extents: init() leaf shapes
    cannot depend on num_stages, and a non-divisor pp must raise rather
    than silently change depth."""
    batch = _lm_batch()
    kwargs = dict(
        vocab_size=64, num_layers=8, num_heads=2, embed_dim=16
    )
    v4 = pipeline_transformer.PipelinedTransformerLM(
        num_stages=4, **kwargs
    ).init(jax.random.PRNGKey(0), batch["features"])
    v2 = pipeline_transformer.PipelinedTransformerLM(
        num_stages=2, **kwargs
    ).init(jax.random.PRNGKey(0), batch["features"])
    for a, b in zip(
        jax.tree_util.tree_leaves(v4), jax.tree_util.tree_leaves(v2)
    ):
        assert a.shape == b.shape
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

    with pytest.raises(ValueError, match="not divisible"):
        pipeline_transformer.PipelinedTransformerLM(
            num_stages=3, **kwargs
        )


def test_pipelined_lm_trains_on_pp_mesh():
    mesh = build_mesh(MeshConfig(dp=2, pp=4))
    model = pipeline_transformer.PipelinedTransformerLM(
        vocab_size=64,
        num_layers=4,
        num_stages=4,
        num_heads=2,
        embed_dim=16,
        num_microbatches=2,
        attention_impl="xla",
        mesh=mesh,
    )
    trainer = SpmdTrainer(
        model=model,
        loss_fn=pipeline_transformer.loss,
        optimizer=transformer.optimizer(),
        mesh=mesh,
        seed=0,
        sharding_rules=pipeline_transformer.sharding_rules(),
    )
    batch = _lm_batch(batch=8, seq=16)
    state = trainer.create_state(batch["features"])

    # Stage params (and their optimizer state) must actually shard over pp.
    blocks_sh = trainer.state_shardings.params["blocks"]
    leaf = jax.tree_util.tree_leaves(blocks_sh)[0]
    assert leaf.spec[0] == "pp"

    losses = []
    for _ in range(5):
        state, loss = trainer.train_step(state, batch)
        losses.append(float(loss))
    assert np.isfinite(losses).all()
    assert losses[-1] < losses[0]


def test_schedules_agree():
    """gpipe and 1f1b are different execution schedules of the same
    math: outputs and gradients must match each other exactly."""
    mesh = build_mesh(MeshConfig(dp=2, pp=4))
    params = _affine_stages(4, seed=7)
    stacked = stack_stage_params(params)
    x = jnp.asarray(np.random.RandomState(8).randn(8, 8), jnp.float32)

    outs, grads = [], []
    for schedule in ("gpipe", "1f1b"):
        def loss(sp, schedule=schedule):
            return jnp.mean(
                pipeline_apply(
                    _stage_fn, sp, x, 2, mesh, schedule=schedule
                ) ** 2
            )

        value, grad = jax.jit(jax.value_and_grad(loss))(stacked)
        outs.append(float(value))
        grads.append(grad)
    np.testing.assert_allclose(outs[0], outs[1], rtol=1e-5)
    for a, b in zip(
        jax.tree_util.tree_leaves(grads[0]),
        jax.tree_util.tree_leaves(grads[1]),
    ):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), atol=1e-5
        )


def test_interleaved_chunks_match_sequential():
    """num_chunks=2: 8 virtual chunks over pp=4, microbatches wrap from
    the last device back to the first; forward and gradients must match
    the 8-stage sequential reference."""
    mesh = build_mesh(MeshConfig(dp=2, pp=4))
    params = _affine_stages(8, seed=9)
    stacked = stack_stage_params(params)
    x = jnp.asarray(np.random.RandomState(10).randn(16, 8), jnp.float32)

    out = jax.jit(
        lambda sp, x: pipeline_apply(
            _stage_fn, sp, x, num_microbatches=4, mesh=mesh, num_chunks=2
        )
    )(stacked, x)
    ref = _sequential(params, x)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-5)

    g_pipe = jax.jit(
        jax.grad(
            lambda sp: jnp.mean(
                pipeline_apply(_stage_fn, sp, x, 4, mesh, num_chunks=2)
                ** 2
            )
        )
    )(stacked)
    g_seq = jax.grad(
        lambda ps: jnp.mean(_sequential(ps, x) ** 2)
    )(params)
    for a, b in zip(
        jax.tree_util.tree_leaves(g_pipe),
        jax.tree_util.tree_leaves(stack_stage_params(g_seq)),
    ):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-5)


def test_interleaved_requires_small_m():
    mesh = build_mesh(MeshConfig(dp=2, pp=4))
    params = _affine_stages(8, seed=9)
    stacked = stack_stage_params(params)
    x = jnp.asarray(np.random.RandomState(10).randn(16, 8), jnp.float32)
    with pytest.raises(ValueError, match="conflict-free"):
        pipeline_apply(_stage_fn, stacked, x, 8, mesh, num_chunks=2)


def test_bubble_fraction_interleaving_beats_gpipe():
    """The 'measured bubble' contract: tick counts come straight from
    the scan lengths (M + S*V - 1 per direction); interleaving V=2
    strictly beats the V=1/GPipe bubble at M = S."""
    from elasticdl_tpu.parallel.pipeline import schedule_info

    gpipe = schedule_info(num_stages=4, num_microbatches=4, num_chunks=1)
    inter = schedule_info(num_stages=4, num_microbatches=4, num_chunks=2)
    assert gpipe["ticks_per_direction"] == 4 + 4 - 1
    assert inter["ticks_per_direction"] == 4 + 8 - 1
    assert inter["bubble_fraction"] < gpipe["bubble_fraction"]
    # 1f1b linear memory vs gpipe autodiff's O((M+S)*M) carry saves
    assert inter["activations_per_device"] == 8


def _tp_stage_fn(p, x):
    """Megatron-style column+row parallel MLP: W1 sharded on its output
    dim over tp, W2 on its input dim; one manual all-reduce rejoins the
    activation — tensor parallelism INSIDE a pipeline stage. Routed
    through mesh_psum (not bare lax.psum): the schedule differentiates
    the stage body inside the shard_map region, and mesh_psum is the
    collective whose transpose is correct there on every jax version
    (see parallel/collectives.py)."""
    from elasticdl_tpu.parallel.collectives import mesh_psum

    h = jnp.maximum(x @ p["W1"], 0.0)
    return mesh_psum(h @ p["W2"], "tp") + p["b"]


def test_tp_inside_pp():
    """tp composes within a stage: stage params shard over tp via
    param_specs, the stage body psums over tp, gradients match the
    single-device sequential reference."""
    from jax.sharding import PartitionSpec as P

    mesh = build_mesh(MeshConfig(dp=2, pp=2, tp=2))
    rng = np.random.RandomState(11)
    dim, hidden = 8, 16
    params = [
        dict(
            W1=jnp.asarray(rng.randn(dim, hidden) * 0.3, jnp.float32),
            W2=jnp.asarray(rng.randn(hidden, dim) * 0.3, jnp.float32),
            b=jnp.asarray(rng.randn(dim) * 0.1, jnp.float32),
        )
        for _ in range(2)
    ]
    stacked = stack_stage_params(params)
    param_specs = dict(
        W1=P("pp", None, "tp"), W2=P("pp", "tp", None), b=P("pp")
    )
    x = jnp.asarray(np.random.RandomState(12).randn(8, dim), jnp.float32)

    def seq(ps, x):
        for p in ps:
            x = jnp.maximum(x @ p["W1"], 0.0) @ p["W2"] + p["b"]
        return x

    out = jax.jit(
        lambda sp, x: pipeline_apply(
            _tp_stage_fn, sp, x, 2, mesh, param_specs=param_specs
        )
    )(stacked, x)
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(seq(params, x)), atol=1e-5
    )

    g_pipe = jax.jit(
        jax.grad(
            lambda sp: jnp.mean(
                pipeline_apply(
                    _tp_stage_fn, sp, x, 2, mesh, param_specs=param_specs
                ) ** 2
            )
        )
    )(stacked)
    g_seq = jax.grad(lambda ps: jnp.mean(seq(ps, x) ** 2))(params)
    for a, b in zip(
        jax.tree_util.tree_leaves(g_pipe),
        jax.tree_util.tree_leaves(stack_stage_params(g_seq)),
    ):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-5)


def test_pipeline_mlp_trains_on_pptp_mesh():
    """The pp x tp model family end to end: stage params shard over
    both axes, loss decreases under the SPMD trainer."""
    from elasticdl_tpu.models import pipeline_mlp

    mesh = build_mesh(MeshConfig(dp=2, pp=2, tp=2))
    model = pipeline_mlp.PipelinedMlpNet(
        num_classes=4, dim=16, hidden=32, num_layers=4,
        num_stages=2, num_microbatches=2, mesh=mesh,
    )
    trainer = SpmdTrainer(
        model=model,
        loss_fn=pipeline_mlp.loss,
        optimizer=pipeline_mlp.optimizer(),
        mesh=mesh,
        seed=0,
        sharding_rules=pipeline_mlp.sharding_rules(),
    )
    rng = np.random.RandomState(0)
    features = rng.randn(16, 16).astype(np.float32)
    labels = (features.sum(axis=1) > 0).astype(np.int32)
    batch = {
        "features": features,
        "labels": labels,
        "_mask": np.ones((16,), np.float32),
    }
    state = trainer.create_state(batch["features"])
    # W1 actually sharded over both pp (layer stack) and tp (hidden dim)
    w1_spec = trainer.state_shardings.params["blocks"]["W1"].spec
    assert w1_spec[0] == "pp" and "tp" in tuple(w1_spec)
    losses = []
    for _ in range(30):
        state, loss = trainer.train_step(state, batch)
        losses.append(float(loss))
    assert np.isfinite(losses).all() and losses[-1] < losses[0]


def test_interleaved_transformer_matches_sequential():
    """PipelinedTransformerLM with num_chunks=2: identical logits to
    the meshless sequential path."""
    mesh = build_mesh(MeshConfig(dp=2, pp=4))
    kwargs = dict(
        vocab_size=64,
        num_layers=8,
        num_stages=4,
        num_heads=2,
        embed_dim=16,
        num_microbatches=2,
        attention_impl="xla",
    )
    piped = pipeline_transformer.PipelinedTransformerLM(
        mesh=mesh, num_chunks=2, **kwargs
    )
    seq_model = pipeline_transformer.PipelinedTransformerLM(
        mesh=None, **kwargs
    )
    batch = _lm_batch()
    variables = piped.init(jax.random.PRNGKey(0), batch["features"])
    out_piped = jax.jit(
        lambda v, t: piped.apply(v, t, training=False)
    )(variables, batch["features"])
    out_seq = jax.jit(
        lambda v, t: seq_model.apply(v, t, training=False)
    )(variables, batch["features"])
    np.testing.assert_allclose(
        np.asarray(out_piped), np.asarray(out_seq), atol=1e-4
    )


def test_device_major_layout_matches_chunk_major():
    """params_layout='device' (no per-step cross-shard permutation of
    the stage stack) must be numerically identical to the portable
    chunk-major layout: same logits, same loss, and gradients that map
    onto each other under the model's layout conversion."""
    mesh = build_mesh(MeshConfig(dp=2, pp=4))
    kwargs = dict(
        vocab_size=64,
        num_layers=8,
        num_stages=4,
        num_heads=2,
        embed_dim=16,
        num_microbatches=2,
        attention_impl="xla",
        mesh=mesh,
        num_chunks=2,
    )
    chunk_model = pipeline_transformer.PipelinedTransformerLM(**kwargs)
    dev_model = pipeline_transformer.PipelinedTransformerLM(
        device_major_params=True, **kwargs
    )
    batch = _lm_batch()
    tokens = batch["features"]
    v_chunk = chunk_model.init(jax.random.PRNGKey(0), tokens)
    v_dev = dev_model.init(jax.random.PRNGKey(0), tokens)

    # same seed: the device-major stack is exactly the portable stack
    # under the model's layout conversion
    for a, b in zip(
        jax.tree_util.tree_leaves(
            dev_model.blocks_to_portable(v_dev["params"]["blocks_device_major"])
        ),
        jax.tree_util.tree_leaves(v_chunk["params"]["blocks"]),
    ):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

    def loss_fn(model):
        def fn(variables):
            logits = model.apply(variables, tokens, training=False)
            return jnp.mean(
                transformer.loss(tokens, logits).astype(jnp.float32)
            )
        return fn

    l_chunk, g_chunk = jax.value_and_grad(loss_fn(chunk_model))(v_chunk)
    l_dev, g_dev = jax.value_and_grad(loss_fn(dev_model))(v_dev)
    assert np.isclose(float(l_chunk), float(l_dev), rtol=1e-6)
    g_dev_portable = dict(g_dev["params"])
    g_dev_portable["blocks"] = dev_model.blocks_to_portable(
        g_dev_portable.pop("blocks_device_major")
    )
    for a, b in zip(
        jax.tree_util.tree_leaves(g_dev_portable),
        jax.tree_util.tree_leaves(g_chunk["params"]),
    ):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), atol=1e-5
        )


def test_device_major_requires_interleaving():
    with pytest.raises(ValueError, match="device_major_params"):
        pipeline_transformer.PipelinedTransformerLM(
            num_layers=8, num_stages=4, num_chunks=1,
            device_major_params=True,
            mesh=build_mesh(MeshConfig(dp=2, pp=4)),
        )
