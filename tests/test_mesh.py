"""Mesh construction + the --mesh CLI spec parser."""

import pytest

from elasticdl_tpu.parallel.mesh import (
    MeshConfig,
    build_mesh,
    data_parallel_size,
    parse_mesh_spec,
)


def test_parse_mesh_spec_empty_is_none():
    assert parse_mesh_spec("") is None
    assert parse_mesh_spec("  ") is None


def test_parse_mesh_spec_axes():
    config = parse_mesh_spec("dp=2,fsdp=4")
    assert config.dp == 2 and config.fsdp == 4
    config = parse_mesh_spec("fsdp=4")
    assert config.dp == -1  # absorbs the remaining devices
    config = parse_mesh_spec("pp=2, tp=2")
    assert config.pp == 2 and config.tp == 2


@pytest.mark.parametrize(
    "bad,match",
    [
        ("bogus=2", "unknown mesh axis"),
        ("fsdp", "integer size"),
        ("fsdp=", "integer size"),
        ("dp=2,dp=4", "duplicate"),
        # non-positive sizes must fail HERE with the axis named, not
        # later as a baffling reshape error inside mesh_utils
        ("fsdp=-1", "sizes must be >= 1"),
        ("fsdp=0", "sizes must be >= 1"),
        ("dp=0", "sizes must be >= 1"),
        ("dp=-2", "sizes must be >= 1"),
    ],
)
def test_parse_mesh_spec_rejects(bad, match):
    with pytest.raises(ValueError, match=match):
        parse_mesh_spec(bad)


def test_parse_mesh_spec_dp_absorb_allowed():
    assert parse_mesh_spec("dp=-1,fsdp=2").fsdp == 2


def test_build_mesh_from_parsed_spec():
    mesh = build_mesh(parse_mesh_spec("fsdp=4"), num_devices=8)
    assert dict(mesh.shape)["fsdp"] == 4
    assert dict(mesh.shape)["dp"] == 2  # -1 absorbed 8/4
    assert data_parallel_size(mesh) == 8  # dp * fsdp


def test_build_mesh_rejects_non_divisible():
    with pytest.raises(ValueError, match="not divisible"):
        MeshConfig(fsdp=3).resolve(num_devices=8)
