"""Concurrency stress for the task dispatcher (the elasticity core).

The reference's dispatcher is exercised single-threaded in its tests;
in production it serves many worker RPC threads concurrently while the
liveness monitor calls recover_tasks. This hammers that surface from
real threads and asserts the invariants that make elastic training
correct:

- every record range is completed exactly once per epoch (no loss, no
  double-count) despite churn;
- recover_tasks mid-flight never duplicates completed work;
- the job reaches finished() with empty todo/doing.
"""

import random
import threading
import time

from elasticdl_tpu.master.task_dispatcher import TaskDispatcher
from elasticdl_tpu.proto import elasticdl_tpu_pb2 as pb


def test_concurrent_workers_with_churn_complete_every_record_once():
    records = 64 * 97  # not a multiple of records_per_task
    epochs = 3
    dispatcher = TaskDispatcher(
        training_shards={"shard": (0, records)},
        records_per_task=100,
        num_epochs=epochs,
        seed=0,
    )
    # make the TRAIN_END_CALLBACK surface real: one worker will receive
    # the deferred train-end task after the last epoch drains
    dispatcher.add_deferred_callback_create_train_end_task()
    train_end_seen = []
    completed = []  # (start, end) per completed task, appended under lock
    completed_lock = threading.Lock()
    stop = threading.Event()
    errors = []

    def worker(worker_id, crashy):
        rng = random.Random(worker_id)
        try:
            while not stop.is_set():
                task = dispatcher.get(worker_id)
                if task is None:
                    if dispatcher.finished():
                        return
                    time.sleep(0.001)  # don't starve the task holder
                    continue
                if task.type == pb.TRAIN_END_CALLBACK:
                    train_end_seen.append(worker_id)
                    dispatcher.report(task.task_id, True,
                                      worker_id=worker_id)
                    continue
                if crashy and rng.random() < 0.2:
                    # simulate a crash while holding the task: another
                    # thread's recover_tasks must requeue it
                    dispatcher.recover_tasks(worker_id)
                    continue
                if rng.random() < 0.1:
                    # transient failure; count_failure=False so random
                    # unluck can't trip the 3-strike cap and fail the
                    # whole job mid-stress
                    dispatcher.report(task.task_id, False,
                                      worker_id=worker_id,
                                      count_failure=False)
                    continue
                with completed_lock:
                    completed.append((task.start, task.end))
                dispatcher.report(task.task_id, True, worker_id=worker_id)
        except Exception as e:  # pragma: no cover
            errors.append(e)
            stop.set()

    threads = [
        threading.Thread(target=worker, args=(i, i % 2 == 0))
        for i in range(8)
    ]
    for t in threads:
        t.start()
    wedged = False
    for t in threads:
        t.join(timeout=120)
        wedged = wedged or t.is_alive()
    stop.set()  # release any spinners BEFORE asserting, or pytest hangs
    assert not wedged, "worker thread wedged"
    assert not errors, errors
    assert len(train_end_seen) == 1, train_end_seen

    assert dispatcher.finished()
    assert not dispatcher.doing_tasks()
    # exactly epochs * records records completed, each range once per
    # epoch: count coverage per record offset
    coverage = {}
    for start, end in completed:
        coverage[(start, end)] = coverage.get((start, end), 0) + 1
    total = sum((end - start) * n for (start, end), n in coverage.items())
    assert total == records * epochs, (total, records * epochs)
    # every distinct range seen exactly `epochs` times
    assert all(n == epochs for n in coverage.values()), {
        k: n for k, n in coverage.items() if n != epochs
    }
