"""Full sparse job: master + 2 PS + worker, all live over gRPC.

The reference's heaviest in-process pattern (worker vs N real PS with a
real master; tests/test_utils.py:286-430) applied to the sparse path.
"""

from elasticdl_tpu.common.grpc_utils import (
    build_server,
    find_free_port,
)
from elasticdl_tpu.data.readers import RecordIODataReader
from elasticdl_tpu.master.evaluation_service import EvaluationService
from elasticdl_tpu.master.servicer import MasterServicer
from elasticdl_tpu.master.task_dispatcher import TaskDispatcher
from elasticdl_tpu.models import deepfm
from elasticdl_tpu.proto.services import (
    add_master_servicer_to_server,
    add_pserver_servicer_to_server,
)
from elasticdl_tpu.ps.embedding_store import create_store
from elasticdl_tpu.ps.servicer import PserverServicer
from elasticdl_tpu.worker.master_client import MasterClient
from elasticdl_tpu.worker.worker import Worker
from tests.test_utils import create_ctr_recordio


def test_deepfm_distributed_job(tmp_path):
    train_dir = tmp_path / "train"
    valid_dir = tmp_path / "valid"
    train_dir.mkdir()
    valid_dir.mkdir()
    create_ctr_recordio(str(train_dir / "f0.rec"), num_records=512, seed=0)
    create_ctr_recordio(str(valid_dir / "f0.rec"), num_records=128, seed=1)

    # master
    train_reader = RecordIODataReader(data_dir=str(train_dir))
    valid_reader = RecordIODataReader(data_dir=str(valid_dir))
    dispatcher = TaskDispatcher(
        training_shards=train_reader.create_shards(),
        evaluation_shards=valid_reader.create_shards(),
        records_per_task=128,
        num_epochs=2,
        seed=0,
    )
    evals = EvaluationService(
        dispatcher, deepfm.eval_metrics_fn, eval_steps=12
    )
    master_server = build_server()
    add_master_servicer_to_server(
        MasterServicer(dispatcher, evals), master_server
    )
    master_port = find_free_port()
    master_server.add_insecure_port("localhost:%d" % master_port)
    master_server.start()

    # 2 PS shards
    ps_servers = []
    ps_addrs = []
    for ps_id in range(2):
        store = create_store(seed=ps_id)
        store.set_optimizer("adam", lr=0.01)
        server = build_server()
        add_pserver_servicer_to_server(
            PserverServicer(store, ps_id=ps_id), server
        )
        port = find_free_port()
        server.add_insecure_port("localhost:%d" % port)
        server.start()
        ps_servers.append(server)
        ps_addrs.append("localhost:%d" % port)

    try:
        worker = Worker(
            MasterClient("localhost:%d" % master_port, worker_id=0),
            "elasticdl_tpu.models.deepfm",
            RecordIODataReader(data_dir=str(train_dir)),
            minibatch_size=64,
            report_version_steps=4,
            wait_sleep_secs=0.1,
            ps_addrs=ps_addrs,
        )
        worker.run()
        assert dispatcher.finished()
        assert evals.completed_summaries
        _, summary = evals.completed_summaries[-1]
        assert summary["auc"] > 0.75
    finally:
        master_server.stop(None)
        for server in ps_servers:
            server.stop(None)


def test_deepfm_distributed_job_pipelined(tmp_path):
    """Same job through the pipelined stream (overlapped pulls, hot-row
    cache, background pushes) — converges to the same quality."""
    train_dir = tmp_path / "train"
    valid_dir = tmp_path / "valid"
    train_dir.mkdir()
    valid_dir.mkdir()
    create_ctr_recordio(str(train_dir / "f0.rec"), num_records=512, seed=0)
    create_ctr_recordio(str(valid_dir / "f0.rec"), num_records=128, seed=1)

    train_reader = RecordIODataReader(data_dir=str(train_dir))
    valid_reader = RecordIODataReader(data_dir=str(valid_dir))
    dispatcher = TaskDispatcher(
        training_shards=train_reader.create_shards(),
        evaluation_shards=valid_reader.create_shards(),
        records_per_task=128,
        num_epochs=2,
        seed=0,
    )
    evals = EvaluationService(
        dispatcher, deepfm.eval_metrics_fn, eval_steps=12
    )
    master_server = build_server()
    add_master_servicer_to_server(
        MasterServicer(dispatcher, evals), master_server
    )
    master_port = find_free_port()
    master_server.add_insecure_port("localhost:%d" % master_port)
    master_server.start()

    ps_servers = []
    ps_addrs = []
    for ps_id in range(2):
        store = create_store(seed=ps_id)
        store.set_optimizer("adam", lr=0.01)
        server = build_server()
        add_pserver_servicer_to_server(
            PserverServicer(store, ps_id=ps_id), server
        )
        port = find_free_port()
        server.add_insecure_port("localhost:%d" % port)
        server.start()
        ps_servers.append(server)
        ps_addrs.append("localhost:%d" % port)

    try:
        worker = Worker(
            MasterClient("localhost:%d" % master_port, worker_id=0),
            "elasticdl_tpu.models.deepfm",
            RecordIODataReader(data_dir=str(train_dir)),
            minibatch_size=64,
            report_version_steps=4,
            wait_sleep_secs=0.1,
            ps_addrs=ps_addrs,
            sparse_pipeline=True,
            sparse_cache_staleness=4,
        )
        worker.run()
        assert dispatcher.finished()
        assert evals.completed_summaries
        _, summary = evals.completed_summaries[-1]
        assert summary["auc"] > 0.75
        # the pipelined loop actually ran (and the cache saw traffic)
        assert worker._sparse_pipeline
        cache = worker.trainer.preparer.cache
        assert cache is not None and cache.hits > 0
    finally:
        master_server.stop(None)
        for server in ps_servers:
            server.stop(None)


def test_pipelined_pure_training_epoch_boundary(tmp_path):
    """Regression: a pure-training multi-epoch job (no eval service to
    break the stream) must not deadlock at the epoch boundary — the
    stream's yield must precede its lookahead, or the master waits for
    the record report while the worker waits for the next task."""
    import threading

    train_dir = tmp_path / "train"
    train_dir.mkdir()
    create_ctr_recordio(str(train_dir / "f0.rec"), num_records=256, seed=0)
    train_reader = RecordIODataReader(data_dir=str(train_dir))
    dispatcher = TaskDispatcher(
        training_shards=train_reader.create_shards(),
        records_per_task=128,
        num_epochs=3,
        seed=0,
    )
    master_server = build_server()
    add_master_servicer_to_server(
        MasterServicer(dispatcher, None), master_server
    )
    master_port = find_free_port()
    master_server.add_insecure_port("localhost:%d" % master_port)
    master_server.start()

    store = create_store(seed=0)
    store.set_optimizer("adam", lr=0.01)
    ps_server = build_server()
    add_pserver_servicer_to_server(PserverServicer(store), ps_server)
    ps_port = find_free_port()
    ps_server.add_insecure_port("localhost:%d" % ps_port)
    ps_server.start()
    try:
        worker = Worker(
            MasterClient("localhost:%d" % master_port, worker_id=0),
            "elasticdl_tpu.models.deepfm",
            RecordIODataReader(data_dir=str(train_dir)),
            minibatch_size=64,
            wait_sleep_secs=0.1,
            ps_addrs=["localhost:%d" % ps_port],
            sparse_pipeline=True,
            sparse_push_interval=2,
        )
        runner = threading.Thread(target=worker.run, daemon=True)
        runner.start()
        runner.join(timeout=120)
        assert not runner.is_alive(), (
            "pipelined worker deadlocked at an epoch boundary"
        )
        assert dispatcher.finished()
    finally:
        master_server.stop(None)
        ps_server.stop(None)
