"""edlint v2 call-graph engine tests (PR 16).

The engine (elasticdl_tpu.analysis.callgraph) builds a whole-program
index — per-function lock/blocking summaries, resolved call edges,
thread entry points — that the three conc-* rules consume. These tests
exercise the engine on small synthetic multi-module programs: symbol
resolution (methods, inheritance, import aliases), entry detection,
transitive summaries, the documented unknown-callee degradation, and
the rule-level behaviors the acceptance list names: a cross-module
lock-order cycle, blocking propagated >= 2 call hops under a lock, and
the PR 6 pulling-thread cache-invalidation race in its pre-fix shape.
"""

import textwrap

from elasticdl_tpu.analysis.callgraph import CallGraph
from elasticdl_tpu.analysis.concurrency import (
    BLOCKING_RULE,
    CONTEXT_RULE,
    LOCK_ORDER_RULE,
    run_blocking_under_lock,
    run_lock_order,
    run_thread_context,
)
from elasticdl_tpu.analysis.core import Unit


def _units(*sources):
    """sources: (relative path under elasticdl_tpu/, source)."""
    return [
        Unit("elasticdl_tpu/" + path, textwrap.dedent(src))
        for path, src in sources
    ]


def _graph(*sources):
    return CallGraph.build(_units(*sources))


# ---------------------------------------------------------------------------
# symbol resolution


def test_method_call_edge_and_lock_propagation():
    graph = _graph(("pkg/store.py", """
        import threading

        class Store:
            def __init__(self):
                self._lock = threading.Lock()

            def save(self):
                self._locked_write()

            def _locked_write(self):
                with self._lock:
                    pass
    """))
    save = graph.functions["elasticdl_tpu.pkg.store:Store.save"]
    assert any(
        "elasticdl_tpu.pkg.store:Store._locked_write" in site.callees
        for site in save.calls
    )
    acquired = graph.transitive_acquires(save.key)
    assert "Store._lock" in acquired
    # path is caller-first: save -> _locked_write
    assert [graph.functions[k].name for k in acquired["Store._lock"]] == [
        "save", "_locked_write",
    ]


def test_inherited_method_resolves_through_mro():
    graph = _graph(("pkg/roles.py", """
        import threading

        class Base:
            def __init__(self):
                self._lock = threading.Lock()

            def guard(self):
                with self._lock:
                    pass

        class Worker(Base):
            def step(self):
                self.guard()
    """))
    step = graph.functions["elasticdl_tpu.pkg.roles:Worker.step"]
    assert any(
        "elasticdl_tpu.pkg.roles:Base.guard" in site.callees
        for site in step.calls
    )
    assert "Base._lock" in graph.transitive_acquires(step.key)


def test_aliased_cross_module_import_resolves():
    graph = _graph(
        ("pkg/m1.py", """
            from elasticdl_tpu.pkg import m2 as registry

            def tick():
                registry.record()
        """),
        ("pkg/m2.py", """
            def record():
                pass
        """),
    )
    tick = graph.functions["elasticdl_tpu.pkg.m1:tick"]
    assert any(
        "elasticdl_tpu.pkg.m2:record" in site.callees for site in tick.calls
    )


def test_typed_attribute_receiver_resolves_cross_module():
    graph = _graph(
        ("pkg/owner.py", """
            from elasticdl_tpu.pkg.helper import Helper

            class Owner:
                def __init__(self):
                    self.helper = Helper()

                def go(self):
                    self.helper.work()
        """),
        ("pkg/helper.py", """
            class Helper:
                def work(self):
                    pass
        """),
    )
    go = graph.functions["elasticdl_tpu.pkg.owner:Owner.go"]
    assert any(
        "elasticdl_tpu.pkg.helper:Helper.work" in site.callees
        for site in go.calls
    )


# ---------------------------------------------------------------------------
# entry points


def test_thread_executor_and_signal_entries():
    graph = _graph(("pkg/role.py", """
        import signal
        import threading

        class Role:
            def start(self, pool):
                threading.Thread(
                    target=self._loop, name="edl-push", daemon=True
                ).start()
                pool.submit(self._flush)
                signal.signal(signal.SIGTERM, self._on_term)

            def _loop(self):
                pass

            def _flush(self):
                pass

            def _on_term(self, signum, frame):
                pass
    """))
    entries = {e.key: e for e in graph.entries}
    loop = entries["elasticdl_tpu.pkg.role:Role._loop"]
    assert loop.context == "thread:edl-push" and not loop.reentrant
    flush = entries["elasticdl_tpu.pkg.role:Role._flush"]
    assert flush.context == "executor:pool"
    term = entries["elasticdl_tpu.pkg.role:Role._on_term"]
    assert term.context == "signal" and term.reentrant


def test_grpc_servicer_public_methods_are_entries():
    graph = _graph(("pkg/svc.py", """
        class PserverServicer:
            def push_gradient(self, request, context):
                pass

            def _internal(self):
                pass
    """))
    contexts = {e.key: e.context for e in graph.entries}
    assert contexts.get(
        "elasticdl_tpu.pkg.svc:PserverServicer.push_gradient"
    ) == "grpc"
    assert "elasticdl_tpu.pkg.svc:PserverServicer._internal" not in contexts


def test_registration_of_declared_target_is_the_handoff():
    """Submitting a function whose contract names the context being
    created is the declared handoff: no entry, and the contract seeds
    the context fixpoint instead."""
    graph = _graph(("pkg/prep.py", """
        class Preparer:
            # edlint: thread=prepare
            def prepare(self, batch):
                pass

        class Trainer:
            def start(self, pool, preparer):
                pool.submit(preparer.prepare, None)
    """))
    key = "elasticdl_tpu.pkg.prep:Preparer.prepare"
    assert graph.functions[key].thread_context == "prepare"
    assert key not in {e.key for e in graph.entries}
    assert graph.contexts()[key] == frozenset({"prepare"})


# ---------------------------------------------------------------------------
# unknown-callee degradation


def test_unresolved_package_name_degrades_to_unknown_not_safe():
    graph = _graph(("pkg/dyn.py", """
        def process():
            pass

        class Runner:
            def go(self):
                self.process()
    """))
    count, sample = graph.unknown_summary()
    assert count == 1
    assert "self.process" in sample[0]


def test_external_receivers_are_not_unknown():
    graph = _graph(("pkg/ext.py", """
        import argparse

        def build(items):
            parser = argparse.ArgumentParser()
            parser.add_argument("--x")
            items.append(1)
    """))
    assert graph.unknown_summary()[0] == 0


# ---------------------------------------------------------------------------
# conc-lock-order: cross-module ABBA cycle


_CYCLE_M1 = ("pkg/m1.py", """
    import threading

    from elasticdl_tpu.pkg import m2

    _DISPATCH_LOCK = threading.Lock()

    def dispatch():
        with _DISPATCH_LOCK:
            m2.record()

    def audit():
        with _DISPATCH_LOCK:
            pass
""")

_CYCLE_M2 = ("pkg/m2.py", """
    import threading

    from elasticdl_tpu.pkg import m1

    _REG_LOCK = threading.Lock()

    def record():
        with _REG_LOCK:
            pass

    def flush():
        with _REG_LOCK:
            m1.audit()
""")


def test_lock_order_detects_cross_module_cycle():
    units = _units(_CYCLE_M1, _CYCLE_M2)
    graph = CallGraph.build(units)
    cycles = graph.lock_cycles()
    assert len(cycles) == 1
    assert set(cycles[0]["locks"]) == {"m1._DISPATCH_LOCK", "m2._REG_LOCK"}
    findings = run_lock_order(units)
    assert len(findings) == 1
    assert findings[0].rule == LOCK_ORDER_RULE
    assert "m1._DISPATCH_LOCK" in findings[0].code
    assert "m2._REG_LOCK" in findings[0].code


def test_lock_order_quiet_on_consistent_order():
    # same two modules, but m2.flush no longer calls back into m1:
    # every path acquires DISPATCH before REG
    clean_m2 = (_CYCLE_M2[0], _CYCLE_M2[1].replace(
        "        m1.audit()", "        pass"
    ))
    units = _units(_CYCLE_M1, clean_m2)
    assert CallGraph.build(units).lock_cycles() == []
    assert run_lock_order(units) == []


# ---------------------------------------------------------------------------
# conc-blocking-under-lock: >= 2-hop transitive propagation


_TWO_HOP = ("pkg/ckpt.py", """
    import threading

    class Saver:
        def __init__(self):
            self._lock = threading.Lock()

        def save(self):
            with self._lock:
                self._persist()

        def _persist(self):
            self._write_file()

        def _write_file(self):
            with open("/tmp/x", "w") as f:
                f.write("data")
""")


def test_blocking_propagates_two_hops_under_lock():
    units = _units(_TWO_HOP)
    graph = CallGraph.build(units)
    save_key = "elasticdl_tpu.pkg.ckpt:Saver.save"
    blocking = graph.transitive_blocking(save_key)
    paths = {code: path for (_, code), path in blocking.items()}
    assert "open" in paths
    # save -> _persist -> _write_file: the effect sits 2 call hops deep
    assert [graph.functions[k].name for k in paths["open"]] == [
        "save", "_persist", "_write_file",
    ]
    findings = run_blocking_under_lock(units)
    assert len(findings) == 1
    f = findings[0]
    assert f.rule == BLOCKING_RULE
    assert f.symbol == "Saver.save"
    assert f.code == "open via _persist under Saver._lock"
    assert "2 hops" in f.message


def test_blocking_quiet_when_io_hoisted_out_of_lock():
    hoisted = (_TWO_HOP[0], _TWO_HOP[1].replace(
        "        def save(self):\n"
        "            with self._lock:\n"
        "                self._persist()",
        "        def save(self):\n"
        "            self._persist()\n"
        "            with self._lock:\n"
        "                pass",
    ))
    assert run_blocking_under_lock(_units(hoisted)) == []


def test_condition_wait_on_released_lock_is_exempt():
    units = _units(("pkg/cv.py", """
        import threading

        class Queue:
            def __init__(self):
                self._cond = threading.Condition()

            def get(self):
                with self._cond:
                    self._cond.wait()
    """))
    assert run_blocking_under_lock(units) == []


# ---------------------------------------------------------------------------
# conc-thread-context: the PR 6 pulling-thread race, pre-fix shape


_PR6_RACE = ("pkg/sparse.py", """
    from elasticdl_tpu.pkg.cache import RowCache

    class PSClient:
        def __init__(self):
            self.cache = RowCache()

        def _on_restart(self, shard):
            self.cache.invalidate()

        def _push(self, grads):
            self._on_restart(0)

    class Trainer:
        def __init__(self):
            self.client = PSClient()

        def step(self, pool, grads):
            pool.submit(self.client._push, grads)
""")

_PR6_CACHE = ("pkg/cache.py", """
    class RowCache:
        def __init__(self):
            self._rows = {}

        # edlint: thread=prepare
        def invalidate(self):
            self._rows.clear()
""")


def test_pr6_pulling_thread_invalidation_race_is_caught():
    """PR 6's bug before its fix: the gradient-push path (an executor
    thread) detected a PS relaunch and called the row cache's
    invalidate() directly, racing the prepare thread that owns the
    cache. With invalidate() declared thread=prepare, the engine infers
    the push path's executor context and flags the crossing edge."""
    findings = run_thread_context(_units(_PR6_RACE, _PR6_CACHE))
    assert len(findings) == 1
    f = findings[0]
    assert f.rule == CONTEXT_RULE
    assert f.symbol == "PSClient._on_restart"
    assert f.code == "invalidate[prepare] from executor:pool"


def test_pr6_fix_shape_is_quiet():
    """Post-fix shape: the restart hook runs on the prepare thread
    itself (the preparer polls a flag and invalidates from its own
    context), so the only caller's context matches the contract."""
    fixed = ("pkg/sparse.py", """
        from elasticdl_tpu.pkg.cache import RowCache

        class Preparer:
            def __init__(self):
                self.cache = RowCache()

            # edlint: thread=prepare
            def prepare(self, batch):
                self.cache.invalidate()

        class Trainer:
            def __init__(self):
                self.preparer = Preparer()

            def step(self, pool, batch):
                pool.submit(self.preparer.prepare, batch)
    """)
    assert run_thread_context(_units(fixed, _PR6_CACHE)) == []


# ---------------------------------------------------------------------------
# conc-thread-context: reentrant signal handlers (the PR 16 SIGTERM fix)


def test_signal_handler_taking_locks_is_flagged():
    """The exact pre-fix shape of ps/server.py's SIGTERM handler:
    draining inline acquires the servicer lock from a handler that may
    have interrupted the very thread holding it."""
    units = _units(("pkg/server.py", """
        import signal
        import threading

        class Server:
            def __init__(self):
                self._push_lock = threading.Lock()
                signal.signal(signal.SIGTERM, self._on_term)

            def _on_term(self, signum, frame):
                self.graceful_stop()

            def graceful_stop(self):
                with self._push_lock:
                    pass
    """))
    findings = run_thread_context(units)
    codes = {f.code for f in findings}
    assert "signal-lock: Server._push_lock" in codes
    assert all(f.symbol == "Server._on_term" for f in findings)


def test_flag_only_signal_handler_is_quiet():
    units = _units(("pkg/server.py", """
        import signal
        import threading

        class Server:
            def __init__(self):
                self._push_lock = threading.Lock()
                self._term_flag = False
                signal.signal(signal.SIGTERM, self._on_term)

            def _on_term(self, signum, frame):
                self._term_flag = True

            def run(self):
                if self._term_flag:
                    self.graceful_stop()

            def graceful_stop(self):
                with self._push_lock:
                    pass
    """))
    assert run_thread_context(units) == []
