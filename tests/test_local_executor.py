"""End-to-end local training: the minimum slice (reference PR1 scope)."""

from elasticdl_tpu.train.local_executor import LocalExecutor
from tests.test_utils import create_mnist_recordio


def test_mnist_local_training_converges(tmp_path):
    train_dir = tmp_path / "train"
    valid_dir = tmp_path / "valid"
    train_dir.mkdir()
    valid_dir.mkdir()
    create_mnist_recordio(str(train_dir / "f0.rec"), num_records=256, seed=0)
    create_mnist_recordio(str(valid_dir / "f0.rec"), num_records=64, seed=1)

    executor = LocalExecutor(
        "elasticdl_tpu.models.mnist",
        training_data=str(train_dir),
        validation_data=str(valid_dir),
        minibatch_size=32,
        num_epochs=3,
    )
    losses = executor.train()
    assert losses[-1] < losses[0]
    summary = executor.evaluate()
    # quadrant task is separable; the CNN should nail it
    assert summary["accuracy"] > 0.9

    predictions = executor.predict()
    assert sum(p.shape[0] for p in predictions) == 64
