"""CI smoke for the §B utilization analogue harness
(scripts/bench_utilization.py): a tiny co-located run per policy must
finish the job and produce sane measurements. The real (longer)
measurement is the committed docs/UTILIZATION.md.
"""

import json
import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.mark.slow
def test_utilization_harness_smoke(tmp_path):
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "scripts",
                                      "bench_utilization.py"),
         "--period_secs", "6",
         "--records_per_task", "512",
         "--num_epochs", "1",
         "--baseline_secs", "4",
         "--timeout_secs", "240",
         "--scratch", str(tmp_path)],
        capture_output=True, text=True, cwd=REPO,
        env=dict(os.environ, JAX_PLATFORMS="cpu"),
        timeout=600,
    )
    assert proc.returncode == 0, proc.stderr[-2000:]
    result = json.loads(proc.stdout.strip().splitlines()[-1])
    for arm in ("elastic", "gang"):
        assert result[arm]["finished"], result
        assert result[arm]["makespan_s"] > 0
        assert 0 < result[arm]["box_cpu_util"] <= 1
    assert result["foreground_alone"]["fg_quanta_per_s"] > 0
