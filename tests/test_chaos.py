"""Fault injection: a real worker process dies holding tasks; the
master's liveness detection recovers them and a surviving worker drains
the job. The reference had no fault-injection tests at all (SURVEY.md
§5 "fault injection: none; CI relies on natural preemption")."""

import json
import os
import signal
import subprocess
import sys
import threading
import time

import pytest

from elasticdl_tpu.common.grpc_utils import build_server, find_free_port
from elasticdl_tpu.data.readers import RecordIODataReader
from elasticdl_tpu.master.servicer import MasterServicer
from elasticdl_tpu.master.task_dispatcher import TaskDispatcher
from elasticdl_tpu.master.task_monitor import TaskMonitor
from elasticdl_tpu.proto.services import add_master_servicer_to_server
from elasticdl_tpu.worker.master_client import MasterClient
from elasticdl_tpu.worker.worker import Worker
from tests.test_utils import create_mnist_recordio

CRASHER = r"""
import os, sys
sys.path.insert(0, %(repo)r)
from elasticdl_tpu.worker.master_client import MasterClient
mc = MasterClient(%(addr)r, worker_id=1)
task = mc.get_task()
assert task.task_id != 0, "no task to hold"
os._exit(1)  # die mid-task, nothing reported
"""


def test_worker_crash_recovers_and_job_completes(tmp_path):
    train_dir = tmp_path / "train"
    train_dir.mkdir()
    create_mnist_recordio(str(train_dir / "f0.rec"), num_records=256, seed=0)
    reader = RecordIODataReader(data_dir=str(train_dir))

    dispatcher = TaskDispatcher(
        training_shards=reader.create_shards(),
        records_per_task=64,
        num_epochs=1,
        seed=0,
    )
    servicer = MasterServicer(dispatcher, None)
    monitor = TaskMonitor(
        dispatcher, servicer, None, liveness_timeout_secs=4.0,
        scan_interval_secs=0.2,
    )
    server = build_server()
    add_master_servicer_to_server(servicer, server)
    port = find_free_port()
    server.add_insecure_port("localhost:%d" % port)
    server.start()
    monitor.start()
    try:
        # chaos: a real OS process grabs a task and dies holding it
        script = CRASHER % {
            "repo": os.path.dirname(os.path.dirname(__file__)),
            "addr": "localhost:%d" % port,
        }
        proc = subprocess.run(
            [sys.executable, "-c", script], timeout=60,
            env={**os.environ, "JAX_PLATFORMS": "cpu"},
        )
        assert proc.returncode == 1
        assert dispatcher.doing_tasks(), "crasher held no task"

        # liveness detection must recover the orphaned task
        deadline = time.time() + 15
        while dispatcher.doing_tasks() and time.time() < deadline:
            time.sleep(0.2)
        assert not dispatcher.doing_tasks(), "task never recovered"

        # a surviving worker drains the whole job, crashed task included
        worker = Worker(
            MasterClient("localhost:%d" % port, worker_id=2),
            "tests.models.mnist_with_export",
            reader,
            minibatch_size=32,
            wait_sleep_secs=0.1,
        )
        worker.run()
        assert dispatcher.finished()
        assert not dispatcher.job_failed()
    finally:
        monitor.stop()
        server.stop(0)


VICTIM = r"""
import sys, time
sys.path.insert(0, %(repo)r)
from elasticdl_tpu.observability import events
from elasticdl_tpu.proto import elasticdl_tpu_pb2 as pb
from elasticdl_tpu.worker.master_client import MasterClient

events.configure("worker-1")
events.install_crash_hooks()
mc = MasterClient(%(addr)r, worker_id=1)
mc.telemetry_provider = lambda: pb.TelemetryBlob(
    role="worker-1", step_time_ewma=0.1, model_version=1)
mc.reset_worker()
events.emit("role_start", worker=1, epoch=mc.incarnation or 0)
task = mc.get_task()
assert task.task_id != 0, "no task to hold"
print("READY", flush=True)
while True:  # heartbeat mid-round until killed
    mc.get_comm_info()
    time.sleep(0.2)
"""


@pytest.mark.slow
@pytest.mark.parametrize("kill_signal",
                         [signal.SIGTERM, signal.SIGKILL])
def test_worker_kill_fires_dead_air_and_leaves_flight_record(
    tmp_path, monkeypatch, kill_signal,
):
    """ISSUE 3 chaos acceptance: kill a real worker process mid-round;
    the master's fleet monitor must raise a dead-air alert within the
    detection window (counter incremented), the victim's flight record
    must be on disk (journal always; ring dump for the SIGTERM/eviction
    path — SIGKILL can't run hooks, write-through covers it), and
    scripts/postmortem.py must thread one timeline spanning the
    victim's record, the master's requeue, and the alert."""
    from elasticdl_tpu.master.fleet import FleetMonitor
    from elasticdl_tpu.observability import events
    from elasticdl_tpu.observability import metrics as obs_metrics
    from tests.test_utils import create_mnist_recordio

    events_dir = tmp_path / "events"
    events_dir.mkdir()
    train_dir = tmp_path / "train"
    train_dir.mkdir()
    create_mnist_recordio(str(train_dir / "f0.rec"), num_records=128,
                          seed=0)
    reader = RecordIODataReader(data_dir=str(train_dir))

    monkeypatch.setenv(events.EVENTS_DIR_ENV, str(events_dir))
    monkeypatch.setenv("EDL_METRICS", "1")
    obs_metrics.reset_default_registry()
    events.configure("master")
    dispatcher = TaskDispatcher(
        training_shards=reader.create_shards(), records_per_task=64,
        num_epochs=1, seed=0,
    )
    fleet = FleetMonitor(
        straggler_factor=3.0, dead_air_secs=1.5,
        stuck_round_secs=60.0, version_lag_max=1000,
    )
    servicer = MasterServicer(dispatcher, fleet_monitor=fleet)
    monitor = TaskMonitor(
        dispatcher, servicer, None, liveness_timeout_secs=4.0,
        scan_interval_secs=0.2, fleet_monitor=fleet,
    )
    server = build_server()
    add_master_servicer_to_server(servicer, server)
    port = find_free_port()
    server.add_insecure_port("localhost:%d" % port)
    server.start()
    monitor.start()
    victim = None
    try:
        victim = subprocess.Popen(
            [sys.executable, "-c", VICTIM % {
                "repo": os.path.dirname(os.path.dirname(__file__)),
                "addr": "localhost:%d" % port,
            }],
            env={**os.environ, "JAX_PLATFORMS": "cpu",
                 events.EVENTS_DIR_ENV: str(events_dir)},
            stdout=subprocess.PIPE, text=True,
        )
        assert victim.stdout.readline().strip() == "READY"
        assert dispatcher.doing_tasks(), "victim held no task"

        # chaos: kill the worker process mid-round
        victim.send_signal(kill_signal)
        victim.wait(timeout=30)
        killed_at = time.time()

        # the dead-air detector must fire within its window (the scan
        # thread evaluates every 0.2 s; window is 1.5 s of silence)
        deadline = killed_at + 10
        fired = None
        while time.time() < deadline:
            fired = [
                a for a in fleet.alerts()
                if a["alert"] == "dead_air" and a["worker_id"] == 1
            ]
            if fired:
                break
            time.sleep(0.1)
        assert fired, "dead-air alert never fired for the victim"
        assert time.time() - killed_at < 10, "detection too slow"
        counter = obs_metrics.default_registry().get(
            "edl_master_alerts_total"
        )
        assert counter.get("dead_air") >= 1

        # the victim's flight record survived it
        journals = [
            name for name in os.listdir(str(events_dir))
            if name.startswith("worker-1") and
            name.endswith(".events.ndjson")
        ]
        assert journals, "victim journal missing"
        with open(str(events_dir / journals[0])) as f:
            victim_events = [json.loads(line) for line in f]
        assert any(e["event"] == "role_start" for e in victim_events)
        dumps = [
            name for name in os.listdir(str(events_dir))
            if name.startswith("worker-1") and
            name.endswith(".dump.json")
        ]
        if kill_signal == signal.SIGTERM:
            # the crash hook dumped the ring on the way down
            assert dumps, "victim ring dump missing after SIGTERM"
            with open(str(events_dir / dumps[0])) as f:
                assert json.load(f)["reason"] == "sigterm"

        # liveness recovery requeues the orphaned task -> journaled
        deadline = time.time() + 15
        while dispatcher.doing_tasks() and time.time() < deadline:
            time.sleep(0.1)
        assert not dispatcher.doing_tasks(), "task never recovered"
    finally:
        monitor.stop()
        server.stop(0)
        if victim is not None and victim.poll() is None:
            victim.kill()
        events.flush()

    # postmortem threads one correlation-keyed timeline across the
    # victim's record, the master's requeue, and the alert
    sys.path.insert(0, os.path.join(
        os.path.dirname(os.path.dirname(__file__)), "scripts"
    ))
    try:
        import postmortem
    finally:
        sys.path.pop(0)
    report = postmortem.postmortem(str(events_dir))
    events._reset_for_tests()
    kinds = {e["event"] for e in report["timeline"]}
    assert {"role_start", "worker_register", "task_dispatch",
            "alert_raised", "task_requeue",
            "worker_presumed_dead"} <= kinds, kinds
    timeline_ts = [e.get("ts", 0) for e in report["timeline"]]
    assert timeline_ts == sorted(timeline_ts)
    worker1 = report["summary"]["workers"]["1"]
    assert worker1["registrations"], "victim registration not threaded"
    assert worker1["requeued_tasks"], "requeue not threaded"
    assert "dead_air" in worker1["alerts"]
    if kill_signal == signal.SIGTERM:
        assert worker1["dump"] == "sigterm"


def test_ps_crash_restart_job_completes(tmp_path):
    """A parameter-server shard dies mid-training and is relaunched on
    the same address with checkpoint restore; the worker's PS client
    retries through the outage (ps_client.py PS_RETRY_BUDGET) and the
    job completes — no task-retry budget burned on the restart window.
    (Reference behavior: same-id PS relaunch behind a stable per-pod
    Service, instance_manager; worker main's channel connect retries.)"""
    import signal
    import socket

    from elasticdl_tpu.master.servicer import MasterServicer
    from tests.test_utils import create_ctr_recordio

    def free_port():
        s = socket.socket()
        s.bind(("", 0))
        port = s.getsockname()[1]
        s.close()
        return port

    def wait_port(port, timeout=90):
        deadline = time.time() + timeout
        while time.time() < deadline:
            s = socket.socket()
            try:
                s.connect(("127.0.0.1", port))
                return
            except OSError:
                time.sleep(0.3)
            finally:
                s.close()
        raise TimeoutError(port)

    train_dir = tmp_path / "train"
    train_dir.mkdir()
    create_ctr_recordio(str(train_dir / "f0.rec"), num_records=768, seed=0)
    reader = RecordIODataReader(data_dir=str(train_dir))
    dispatcher = TaskDispatcher(
        training_shards=reader.create_shards(),
        records_per_task=128,
        num_epochs=2,
        seed=0,
    )
    server = build_server()
    add_master_servicer_to_server(MasterServicer(dispatcher, None), server)
    master_port = find_free_port()
    server.add_insecure_port("localhost:%d" % master_port)
    server.start()

    ps_port = free_port()
    ckpt_dir = str(tmp_path / "ps_ckpt")

    def spawn_ps(restore):
        cmd = [
            sys.executable, "-m", "elasticdl_tpu.ps.server",
            "--ps_id", "0", "--num_ps_pods", "1",
            "--port", str(ps_port),
            "--opt_type", "adam", "--opt_args", "lr=0.01",
            "--checkpoint_dir", ckpt_dir,
            "--checkpoint_steps", "2",
        ]
        if restore:
            cmd += ["--checkpoint_dir_for_init", ckpt_dir]
        return subprocess.Popen(
            cmd,
            env={**os.environ, "JAX_PLATFORMS": "cpu"},
            stdout=subprocess.DEVNULL,
            stderr=subprocess.DEVNULL,
        )

    ps_proc = spawn_ps(restore=False)
    wait_port(ps_port)
    try:
        worker = Worker(
            MasterClient("localhost:%d" % master_port, worker_id=0),
            "elasticdl_tpu.models.deepfm",
            RecordIODataReader(data_dir=str(train_dir)),
            minibatch_size=64,
            wait_sleep_secs=0.1,
            ps_addrs=["localhost:%d" % ps_port],
        )
        runner = threading.Thread(target=worker.run, daemon=True)
        runner.start()

        # let training make progress (PS checkpoints every 2 versions)
        deadline = time.time() + 120
        while time.time() < deadline and not (
            os.path.isdir(ckpt_dir) and os.listdir(ckpt_dir)
        ):
            time.sleep(0.2)
        assert os.listdir(ckpt_dir), "PS never checkpointed"

        # chaos: SIGKILL the PS shard mid-job, relaunch with restore
        ps_proc.send_signal(signal.SIGKILL)
        ps_proc.wait(timeout=30)
        time.sleep(2)  # let the worker hit the outage window
        ps_proc = spawn_ps(restore=True)

        runner.join(timeout=180)
        assert not runner.is_alive(), "worker never finished after PS restart"
        assert dispatcher.finished(), "job did not complete"
        assert not dispatcher.job_failed(), (
            "PS restart window burned the task retry budget"
        )
    finally:
        server.stop(0)
        if ps_proc.poll() is None:
            ps_proc.kill()


def _wait_port(port, timeout=90):
    import socket

    deadline = time.time() + timeout
    while time.time() < deadline:
        s = socket.socket()
        try:
            s.connect(("127.0.0.1", port))
            return
        except OSError:
            time.sleep(0.3)
        finally:
            s.close()
    raise TimeoutError(port)


def test_master_sigkill_mid_epoch_replay_no_shard_lost_or_doubled(
    tmp_path, monkeypatch,
):
    """ISSUE 4 tentpole acceptance: SIGKILL a real master process
    mid-epoch; the relaunched master replays its state journal
    (EDL_STATE_DIR), resumes the dispatcher, and the job completes with
    every task reported done EXACTLY once across both master lifetimes.
    The worker survives the outage on its jittered get_task retry
    budget and re-registers when it sees the master_epoch move."""
    from elasticdl_tpu.master import state_store
    from elasticdl_tpu.observability import events

    state_dir = tmp_path / "state"
    events_dir = tmp_path / "events"
    train_dir = tmp_path / "train"
    for d in (state_dir, events_dir, train_dir):
        d.mkdir()
    create_mnist_recordio(str(train_dir / "f0.rec"), num_records=256,
                          seed=0)
    master_port = find_free_port()
    env = {
        **os.environ,
        "JAX_PLATFORMS": "cpu",
        state_store.STATE_DIR_ENV: str(state_dir),
        events.EVENTS_DIR_ENV: str(events_dir),
    }
    env.pop("EDL_FAULT_SPEC", None)

    def spawn_master(tag):
        log = open(str(tmp_path / ("master-%s.log" % tag)), "w")
        return subprocess.Popen(
            [
                sys.executable, "-m", "elasticdl_tpu.master.main",
                "--model_zoo", "elasticdl_tpu.models.mnist",
                "--training_data", str(train_dir),
                "--records_per_task", "32",
                "--num_epochs", "2",
                "--port", str(master_port),
                "--task_timeout_secs", "60",
            ],
            env=env,
            stdout=log,
            stderr=subprocess.STDOUT,
        )

    journal_path = state_dir / state_store.JOURNAL_NAME

    def journal_ops():
        if not journal_path.is_file():
            return []
        ops = []
        with open(str(journal_path)) as f:
            for line in f:
                try:
                    ops.append(json.loads(line))
                except ValueError:
                    pass  # torn tail (SIGKILL mid-write) is expected
        return ops

    # the in-process worker outlives the finished master by its whole
    # get_task retry budget before concluding job-over; trim the
    # default 120 s tail while still covering a cold master relaunch
    # (python + jax imports take tens of seconds on a loaded CI box)
    from elasticdl_tpu.worker import master_client as mc_module

    monkeypatch.setattr(mc_module, "MASTER_RETRY_BUDGET_SECS", 60.0)

    master = spawn_master("first")
    runner = None
    try:
        _wait_port(master_port)
        mc = MasterClient("localhost:%d" % master_port, worker_id=0)
        mc.reset_worker()
        worker = Worker(
            mc,
            "elasticdl_tpu.models.mnist",
            RecordIODataReader(data_dir=str(train_dir)),
            minibatch_size=32,
            wait_sleep_secs=0.1,
        )
        runner = threading.Thread(target=worker.run, daemon=True)
        runner.start()

        # let the job make real progress, then kill the master cold
        # while tasks are still in flight (mid-epoch by construction:
        # 16 tasks over 2 epochs, we kill before 8 are done)
        deadline = time.time() + 120
        while time.time() < deadline:
            done = [op for op in journal_ops() if op["op"] == "done"]
            if len(done) >= 3:
                break
            time.sleep(0.1)
        assert len(done) >= 3, "job made no progress before the kill"
        master.send_signal(signal.SIGKILL)
        master.wait(timeout=30)
        time.sleep(1.0)  # the worker is now inside the outage window

        master = spawn_master("relaunch")
        _wait_port(master_port)  # a bind failure surfaces here, loudly
        # the relaunched master replays the journal, serves the rest of
        # the job, and exits 0 when the dispatcher reports finished
        try:
            rc = master.wait(timeout=240)
        except subprocess.TimeoutExpired:
            master.kill()
            raise AssertionError(
                "relaunched master did not finish the job:\n%s"
                % open(str(tmp_path / "master-relaunch.log")).read()[-4000:]
            )
        assert rc == 0, (
            "relaunched master failed:\n%s"
            % open(str(tmp_path / "master-relaunch.log")).read()[-4000:]
        )
        # the worker exits after its retry budget concludes job-over
        runner.join(timeout=120)
        assert not runner.is_alive(), "worker never finished"
    finally:
        if master.poll() is None:
            master.kill()
        if runner is not None and runner.is_alive():
            runner.join(timeout=5)

    # --- accounting: every task done exactly once, none lost ---
    ops = journal_ops()
    created = {
        task[0]
        for op in ops if op["op"] == "tasks_created"
        for task in op["tasks"]
    }
    done_ids = [op["task"] for op in ops if op["op"] == "done"]
    assert len(created) == 16, created  # 8 tasks/epoch x 2 epochs
    assert sorted(done_ids) == sorted(created), (
        "done ops do not match created tasks exactly once: %r vs %r"
        % (sorted(done_ids), sorted(created))
    )
    boots = [op for op in ops if op["op"] == "master_restarted"]
    assert len(boots) == 2  # original + relaunch

    # --- flight recorder: the restart threads through the postmortem ---
    sys.path.insert(0, os.path.join(
        os.path.dirname(os.path.dirname(__file__)), "scripts"
    ))
    try:
        import postmortem
    finally:
        sys.path.pop(0)
    report = postmortem.postmortem(str(events_dir))
    kinds = {e["event"] for e in report["timeline"]}
    assert {"role_start", "master_restarted", "task_dispatch",
            "worker_register"} <= kinds, kinds
    timeline_ts = [e.get("ts", 0) for e in report["timeline"]]
    assert timeline_ts == sorted(timeline_ts)
    # the worker re-registered with the relaunched master: at least two
    # worker_register events for worker 0 (one per master lifetime)
    registers = [
        e for e in report["timeline"]
        if e["event"] == "worker_register" and e.get("worker") == 0
    ]
    assert len(registers) >= 2, registers


@pytest.mark.parametrize(
    "async_push,device_tier",
    [
        (False, False),
        # ISSUE 5 acceptance: the same SIGKILL/auto-restore/resync
        # protocol must hold with the double-buffered async push on —
        # an in-flight push resolves (retry budget) or surfaces at the
        # depth-1 join, never silently drops. Slow-marked: the fault
        # window alone is ~a minute; the fast lane keeps the sync
        # variant.
        pytest.param(True, False, marks=pytest.mark.slow),
        # ISSUE 6 acceptance: PS SIGKILL mid-job with the DEVICE TIER
        # enabled loses no tier-held updates — the restored-stamp
        # change triggers flush-then-invalidate (the tier's rows,
        # newer than the restored checkpoint, write back before the
        # map drops), and at job end every resident row's value
        # matches the PS store (writebacks all landed).
        pytest.param(True, True, marks=pytest.mark.slow),
    ],
)
def test_ps_sigkill_auto_restore_and_worker_resync(
    tmp_path, monkeypatch, async_push, device_tier
):
    """ISSUE 4 tentpole acceptance: SIGKILL the PS mid-round and
    relaunch it with NO restore flag — the PS auto-restores its newest
    complete checkpoint from its own --checkpoint_dir, stamps
    restored_version on responses, and the worker detects the version
    regression, resyncs (re-pushes table infos), rolls its version back
    to the PS's reality, and the job completes. Version accounting
    stays consistent: the worker's final version equals the PS store
    version (each accepted async push bumps it by one from the restored
    base), exactly as a no-fault run's accounting — no pushes vanished
    into a void."""
    import socket

    from elasticdl_tpu.master.servicer import MasterServicer
    from elasticdl_tpu.observability import events
    from tests.test_utils import create_ctr_recordio

    events_dir = tmp_path / "events"
    events_dir.mkdir()
    monkeypatch.setenv(events.EVENTS_DIR_ENV, str(events_dir))
    if async_push:
        # read by SparseTrainer at construction (inside Worker below)
        monkeypatch.setenv("EDL_ASYNC_PUSH", "1")
    if device_tier:
        monkeypatch.setenv("EDL_DEVICE_TIER", "1")
        # a PARTIAL hot set (256 rows over the ctr fixture's 1000-id
        # uniform vocab): misses keep flowing so the PS still sees
        # pushes (the kill-once trigger counts push_gradients — a
        # full-residency tier absorbs ALL traffic and the fault never
        # fires), and LFU churn keeps eviction writebacks live across
        # the kill window
        monkeypatch.setenv("EDL_DEVICE_TIER_ROWS", "256")
        monkeypatch.setenv("EDL_DEVICE_TIER_PROMOTE", "2")
        # match the PS server's optimizer config (adam lr=0.01)
        monkeypatch.setenv("EDL_DEVICE_TIER_OPT", "adam")
        monkeypatch.setenv("EDL_DEVICE_TIER_OPT_ARGS", "lr=0.01")
    events.configure("worker-0")

    train_dir = tmp_path / "train"
    train_dir.mkdir()
    # enough records that the job still holds real work when the kill
    # fires: the kill-decision poll below can lag many steps under
    # full-suite CPU contention, and a job that drains before the
    # SIGKILL leaves nothing to resync (the flight-recorder asserts at
    # the end would then fail on a technicality, not a recovery bug)
    create_ctr_recordio(str(train_dir / "f0.rec"), num_records=1152, seed=0)
    reader = RecordIODataReader(data_dir=str(train_dir))
    dispatcher = TaskDispatcher(
        training_shards=reader.create_shards(),
        records_per_task=128,
        num_epochs=2,
        seed=0,
    )
    server = build_server()
    add_master_servicer_to_server(MasterServicer(dispatcher, None), server)
    master_port = find_free_port()
    server.add_insecure_port("localhost:%d" % master_port)
    server.start()

    def free_port():
        s = socket.socket()
        s.bind(("", 0))
        port = s.getsockname()[1]
        s.close()
        return port

    ps_port = free_port()
    ckpt_dir = str(tmp_path / "ps_ckpt")

    def spawn_ps(fault_spec=None):
        # note: NO --checkpoint_dir_for_init — restore must be automatic
        env = {**os.environ, "JAX_PLATFORMS": "cpu",
               events.EVENTS_DIR_ENV: str(events_dir)}
        env.pop("EDL_FAULT_SPEC", None)
        if fault_spec:
            env["EDL_FAULT_SPEC"] = fault_spec
        return subprocess.Popen(
            [
                sys.executable, "-m", "elasticdl_tpu.ps.server",
                "--ps_id", "0", "--num_ps_pods", "1",
                "--port", str(ps_port),
                "--opt_type", "adam", "--opt_args", "lr=0.01",
                "--checkpoint_dir", ckpt_dir,
                "--checkpoint_steps", "3",
            ],
            env=env,
            stdout=subprocess.DEVNULL,
            stderr=subprocess.DEVNULL,
        )

    # Deterministic mid-job death (testing/faults.py): the PS SIGKILLs
    # ITSELF on its 12th push_gradients — checkpoints at versions 3/6/9
    # are complete by then and 24 of the job's 36 steps remain, so
    # there is always post-kill work left to resync. (An external
    # kill decided by polling the worker's version raced the worker
    # under full-suite CPU contention: by the time the polling thread
    # got scheduled the job had drained, and the flight-recorder
    # asserts below failed with nothing left to push — a 1-in-N flake
    # once the ISSUE-5 wire path sped the steps up.)
    ps_proc = spawn_ps("ps-0:push_gradients:kill-once:12")
    _wait_port(ps_port)
    try:
        worker = Worker(
            MasterClient("localhost:%d" % master_port, worker_id=0),
            "elasticdl_tpu.models.deepfm",
            RecordIODataReader(data_dir=str(train_dir)),
            minibatch_size=64,
            wait_sleep_secs=0.1,
            ps_addrs=["localhost:%d" % ps_port],
        )
        runner = threading.Thread(target=worker.run, daemon=True)
        runner.start()

        from elasticdl_tpu.ps.checkpoint import SparseCheckpointSaver

        # the injected kill-once takes the PS down mid-job (SIGKILL:
        # rc is nonzero), after versions past complete checkpoints —
        # the relaunch restores an observably older version (the
        # version-REGRESSION detection path this test pins; a kill
        # landing exactly on a checkpoint would be the restored-stamp
        # path instead, which kill-once on push 12 ≠ 0 mod 3 avoids)
        rc = ps_proc.wait(timeout=120)
        assert rc != 0, "PS survived its kill-once fault"
        restored_floor = SparseCheckpointSaver.latest_version(ckpt_dir)
        assert restored_floor is not None, "PS never checkpointed"

        time.sleep(2)  # let the worker hit the outage window
        ps_proc = spawn_ps()
        # the relaunch must reach serving (restore done, ps_restored
        # journaled) before this test can tear it down — a fast job
        # ending right after the relaunch must not kill a booting PS
        _wait_port(ps_port)

        runner.join(timeout=180)
        assert not runner.is_alive(), "worker never finished after PS restart"
        assert dispatcher.finished(), "job did not complete"
        assert not dispatcher.job_failed(), (
            "PS restart window burned the task retry budget"
        )
        # rolled back then advanced: the final version is consistent
        # with the restored base, not the pre-kill high-water mark
        assert worker.trainer._version >= restored_floor
        if device_tier:
            # no lost updates across the SIGKILL: the trainer's
            # end-of-life close() flushed the tier, and every resident
            # row's device value must match what the (restarted) PS
            # now stores — the resync flush + eviction/periodic
            # writebacks all landed
            import numpy as np

            from elasticdl_tpu.worker.ps_client import PSClient

            tier = worker.trainer.device_tier
            assert tier is not None, "EDL_DEVICE_TIER did not engage"
            assert tier.epoch >= 1, (
                "PS relaunch never invalidated the tier"
            )
            probe = PSClient(["localhost:%d" % ps_port])
            for table in ("deepfm_emb", "deepfm_linear"):
                ids, rows = tier.table_rows(table)
                if not ids.size:
                    continue
                np.testing.assert_allclose(
                    probe.pull_embedding_vectors(table, ids), rows,
                    rtol=1e-5, atol=1e-6,
                )
    finally:
        server.stop(0)
        if ps_proc.poll() is None:
            ps_proc.kill()
        events.flush()
        events._reset_for_tests()

    # --- flight recorder: restore + resync are journaled ---
    from tests.test_utils import load_journal

    ps_events = load_journal(events_dir, "ps-0")
    restored = [e for e in ps_events if e["event"] == "ps_restored"]
    assert restored, "relaunched PS journaled no ps_restored event"
    assert restored[0]["version"] >= restored_floor
    worker_events = load_journal(events_dir, "worker-0")
    resynced = [e for e in worker_events if e["event"] == "worker_resynced"]
    assert resynced, "worker journaled no worker_resynced event"
    assert resynced[0]["restored"] == restored[0]["version"]


# ---------------------------------------------------------------------------
# ISSUE 7: graceful drain under preemption


@pytest.mark.slow
def test_worker_drain_under_async_push_and_device_tier_tier_ps_parity(
    tmp_path, monkeypatch,
):
    """ISSUE 7 acceptance (graceful path): drain a worker mid-job under
    EDL_ASYNC_PUSH + EDL_DEVICE_TIER — begin_drain is exactly what the
    SIGTERM hook calls. The drain must (a) finish the current task
    (done-exactly-once: zero task_requeue events end to end), (b) join
    the in-flight push and flush dirty tier rows so every resident
    row's device value matches the PS (tier<->PS parity), and (c)
    deregister so the removal stays alert-silent."""
    import numpy as np

    from elasticdl_tpu.master.autoscaler import DrainManager
    from elasticdl_tpu.master.fleet import FleetMonitor
    from elasticdl_tpu.observability import events
    from elasticdl_tpu.worker.ps_client import PSClient
    from tests.test_utils import create_ctr_recordio

    events_dir = tmp_path / "events"
    events_dir.mkdir()
    monkeypatch.setenv(events.EVENTS_DIR_ENV, str(events_dir))
    monkeypatch.setenv("EDL_ASYNC_PUSH", "1")
    monkeypatch.setenv("EDL_DEVICE_TIER", "1")
    monkeypatch.setenv("EDL_DEVICE_TIER_ROWS", "256")
    monkeypatch.setenv("EDL_DEVICE_TIER_PROMOTE", "2")
    monkeypatch.setenv("EDL_DEVICE_TIER_OPT", "adam")
    monkeypatch.setenv("EDL_DEVICE_TIER_OPT_ARGS", "lr=0.01")
    # the drain watchdog must not fire under full-suite CPU contention
    monkeypatch.setenv("EDL_DRAIN_DEADLINE_SECS", "300")
    events.configure("master")

    train_dir = tmp_path / "train"
    train_dir.mkdir()
    create_ctr_recordio(str(train_dir / "f0.rec"), num_records=1152,
                        seed=0)
    reader = RecordIODataReader(data_dir=str(train_dir))
    dispatcher = TaskDispatcher(
        training_shards=reader.create_shards(),
        records_per_task=128, num_epochs=2, seed=0,
    )
    fleet = FleetMonitor(dead_air_secs=60.0)
    servicer = MasterServicer(dispatcher, None, fleet_monitor=fleet)
    drain = DrainManager(dispatcher, servicer=servicer, fleet=fleet,
                         deadline_secs=240.0)
    servicer.drain_manager = drain
    monitor = TaskMonitor(
        dispatcher, servicer, liveness_timeout_secs=60.0,
        scan_interval_secs=0.5, fleet_monitor=fleet,
        drain_manager=drain,
    )
    server = build_server()
    add_master_servicer_to_server(servicer, server)
    master_port = find_free_port()
    server.add_insecure_port("localhost:%d" % master_port)
    server.start()
    monitor.start()

    import socket

    def free_port():
        s = socket.socket()
        s.bind(("", 0))
        port = s.getsockname()[1]
        s.close()
        return port

    ps_port = free_port()
    ps_proc = subprocess.Popen(
        [
            sys.executable, "-m", "elasticdl_tpu.ps.server",
            "--ps_id", "0", "--num_ps_pods", "1",
            "--port", str(ps_port),
            # async PS: EDL_ASYNC_PUSH's supported mode (a sync PS
            # rejects the second worker's post-drain pushes as stale)
            "--use_async", "1",
            "--opt_type", "adam", "--opt_args", "lr=0.01",
        ],
        env={**os.environ, "JAX_PLATFORMS": "cpu",
             events.EVENTS_DIR_ENV: str(events_dir)},
        stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL,
    )
    _wait_port(ps_port)
    try:
        worker = Worker(
            MasterClient("localhost:%d" % master_port, worker_id=0),
            "elasticdl_tpu.models.deepfm",
            RecordIODataReader(data_dir=str(train_dir)),
            minibatch_size=64, wait_sleep_secs=0.1,
            ps_addrs=["localhost:%d" % ps_port],
        )
        runner = threading.Thread(target=worker.run, daemon=True)
        runner.start()
        # drain once real progress exists: tasks done AND tier traffic
        deadline = time.time() + 120
        while time.time() < deadline and (
            dispatcher.stats()["done"].get("training", 0) < 2
        ):
            time.sleep(0.2)
        assert dispatcher.stats()["done"].get("training", 0) >= 2, (
            "worker made no progress"
        )
        drain.begin_drain(0, reason="scale_down")
        runner.join(timeout=180)
        assert not runner.is_alive(), "draining worker never exited"
        assert worker._drain_done

        # (b) tier<->PS parity: every resident row's device value must
        # equal what the PS stores — the drain's flush landed
        tier = worker.trainer.device_tier
        assert tier is not None, "EDL_DEVICE_TIER did not engage"
        probe = PSClient(["localhost:%d" % ps_port])
        compared = 0
        for table in ("deepfm_emb", "deepfm_linear"):
            ids, rows = tier.table_rows(table)
            if not ids.size:
                continue
            np.testing.assert_allclose(
                probe.pull_embedding_vectors(table, ids), rows,
                rtol=1e-5, atol=1e-6,
            )
            compared += ids.size
        assert compared > 0, "tier held no rows to compare"

        # (c) alert-silent removal, and work remains for a peer
        assert fleet.evaluate() == []
        assert 0 not in servicer.worker_liveness()
        assert not dispatcher.finished()

        # a second worker finishes the job (fresh id: the drained id's
        # tombstone must not block a replacement either)
        worker2 = Worker(
            MasterClient("localhost:%d" % master_port, worker_id=1),
            "elasticdl_tpu.models.deepfm",
            RecordIODataReader(data_dir=str(train_dir)),
            minibatch_size=64, wait_sleep_secs=0.1,
            ps_addrs=["localhost:%d" % ps_port],
        )
        worker2.run()
        assert dispatcher.finished()
        assert not dispatcher.job_failed()
    finally:
        monitor.stop()
        server.stop(0)
        if ps_proc.poll() is None:
            ps_proc.kill()
        events.flush()
        events._reset_for_tests()

    from tests.test_utils import load_journal

    merged = load_journal(events_dir)
    acks = [e for e in merged if e["event"] == "drain_ack"]
    assert acks and acks[0]["worker"] == 0
    assert acks[0]["pushes_joined"] and acks[0]["tier_flushed"]
    assert acks[0]["handed_back"] == 0
    # (a) done-exactly-once: nothing was ever requeued
    assert [e for e in merged if e["event"] == "task_requeue"] == []
    assert [e for e in merged if e["event"] == "drain_expired"] == []


STUCK_WORKER = r"""
import signal, sys, time
sys.path.insert(0, %(repo)r)
signal.signal(signal.SIGTERM, signal.SIG_IGN)  # a wedged victim
from elasticdl_tpu.worker.master_client import MasterClient
mc = MasterClient(%(addr)r, worker_id=0)
mc.reset_worker()
task = mc.get_task()
assert task.task_id != 0, "no task to hold"
print("HOLDING", flush=True)
time.sleep(600)  # never reports, never drains
"""


@pytest.mark.slow
def test_drain_deadline_expiry_falls_back_to_requeue_on_death(
    tmp_path, monkeypatch,
):
    """ISSUE 7 acceptance (fallback path): a scale-down victim that
    ignores SIGTERM and never acks. The master's drain deadline expires
    -> requeue-on-death (drain_expired journaled, the held task
    requeues UNCOUNTED, the tombstone says drained: true), the SIGKILL
    fallback reaps the pod, and a surviving worker completes the job —
    done-exactly-once still holds."""
    from elasticdl_tpu.master.autoscaler import DrainManager
    from elasticdl_tpu.master.fleet import FleetMonitor
    from elasticdl_tpu.observability import events

    events_dir = tmp_path / "events"
    events_dir.mkdir()
    monkeypatch.setenv(events.EVENTS_DIR_ENV, str(events_dir))
    events.configure("master")

    train_dir = tmp_path / "train"
    train_dir.mkdir()
    create_mnist_recordio(str(train_dir / "f0.rec"), num_records=256,
                          seed=0)
    reader = RecordIODataReader(data_dir=str(train_dir))
    dispatcher = TaskDispatcher(
        training_shards=reader.create_shards(), records_per_task=64,
        num_epochs=1, seed=0,
    )
    fleet = FleetMonitor(dead_air_secs=120.0)
    servicer = MasterServicer(dispatcher, None, fleet_monitor=fleet)
    drain = DrainManager(dispatcher, servicer=servicer, fleet=fleet,
                         deadline_secs=3.0)
    servicer.drain_manager = drain
    monitor = TaskMonitor(
        dispatcher, servicer, liveness_timeout_secs=120.0,
        scan_interval_secs=0.2, fleet_monitor=fleet,
        drain_manager=drain,
    )
    server = build_server()
    add_master_servicer_to_server(servicer, server)
    port = find_free_port()
    server.add_insecure_port("localhost:%d" % port)
    server.start()
    monitor.start()
    proc = None
    try:
        script = STUCK_WORKER % {
            "repo": os.path.dirname(os.path.dirname(__file__)),
            "addr": "localhost:%d" % port,
        }
        proc = subprocess.Popen(
            [sys.executable, "-c", script],
            env={**os.environ, "JAX_PLATFORMS": "cpu"},
            stdout=subprocess.PIPE,
        )
        deadline = time.time() + 60
        while time.time() < deadline and not dispatcher.doing_tasks():
            time.sleep(0.1)
        held = dispatcher.doing_tasks()
        assert held, "stuck worker never took a task"
        (held_task,) = held

        # scale-down decision: drain, deliver SIGTERM (ignored)
        drain.begin_drain(0, reason="scale_down")
        proc.send_signal(signal.SIGTERM)
        # the deadline expires on the monitor scan -> requeue fallback
        deadline = time.time() + 30
        while time.time() < deadline and dispatcher.doing_tasks():
            time.sleep(0.2)
        assert not dispatcher.doing_tasks(), "task never recovered"
        assert not drain.is_draining(0)
        # SIGKILL fallback (kubelet's grace-period kill)
        proc.kill()
        proc.wait(timeout=30)

        # the eviction alerted, flagged as a LATE intentional removal
        alerts = fleet.alerts()
        assert any(
            a["alert"] == "dead_air" and a.get("drained") is True
            for a in alerts
        ), alerts

        # a surviving worker drains the job; the held task runs exactly
        # once more (its original holder never trained it)
        worker = Worker(
            MasterClient("localhost:%d" % port, worker_id=2),
            "elasticdl_tpu.models.mnist", reader,
            minibatch_size=32, wait_sleep_secs=0.1,
        )
        worker.run()
        assert dispatcher.finished()
        assert not dispatcher.job_failed(), (
            "the drain fallback burned the retry cap"
        )
    finally:
        monitor.stop()
        server.stop(0)
        if proc is not None and proc.poll() is None:
            proc.kill()
        events.flush()
        events._reset_for_tests()

    from tests.test_utils import load_journal

    merged = load_journal(events_dir)
    expired = [e for e in merged if e["event"] == "drain_expired"]
    assert expired and expired[0]["worker"] == 0
    requeues = [e for e in merged if e["event"] == "task_requeue"]
    assert [e["task"] for e in requeues] == [held_task]
    assert all(e["counted"] is False for e in requeues)
