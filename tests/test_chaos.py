"""Fault injection: a real worker process dies holding tasks; the
master's liveness detection recovers them and a surviving worker drains
the job. The reference had no fault-injection tests at all (SURVEY.md
§5 "fault injection: none; CI relies on natural preemption")."""

import json
import os
import signal
import subprocess
import sys
import threading
import time

import pytest

from elasticdl_tpu.common.grpc_utils import build_server, find_free_port
from elasticdl_tpu.data.readers import RecordIODataReader
from elasticdl_tpu.master.servicer import MasterServicer
from elasticdl_tpu.master.task_dispatcher import TaskDispatcher
from elasticdl_tpu.master.task_monitor import TaskMonitor
from elasticdl_tpu.proto.services import add_master_servicer_to_server
from elasticdl_tpu.worker.master_client import MasterClient
from elasticdl_tpu.worker.worker import Worker
from tests.test_utils import create_mnist_recordio

CRASHER = r"""
import os, sys
sys.path.insert(0, %(repo)r)
from elasticdl_tpu.worker.master_client import MasterClient
mc = MasterClient(%(addr)r, worker_id=1)
task = mc.get_task()
assert task.task_id != 0, "no task to hold"
os._exit(1)  # die mid-task, nothing reported
"""


def test_worker_crash_recovers_and_job_completes(tmp_path):
    train_dir = tmp_path / "train"
    train_dir.mkdir()
    create_mnist_recordio(str(train_dir / "f0.rec"), num_records=256, seed=0)
    reader = RecordIODataReader(data_dir=str(train_dir))

    dispatcher = TaskDispatcher(
        training_shards=reader.create_shards(),
        records_per_task=64,
        num_epochs=1,
        seed=0,
    )
    servicer = MasterServicer(dispatcher, None)
    monitor = TaskMonitor(
        dispatcher, servicer, None, liveness_timeout_secs=4.0,
        scan_interval_secs=0.2,
    )
    server = build_server()
    add_master_servicer_to_server(servicer, server)
    port = find_free_port()
    server.add_insecure_port("localhost:%d" % port)
    server.start()
    monitor.start()
    try:
        # chaos: a real OS process grabs a task and dies holding it
        script = CRASHER % {
            "repo": os.path.dirname(os.path.dirname(__file__)),
            "addr": "localhost:%d" % port,
        }
        proc = subprocess.run(
            [sys.executable, "-c", script], timeout=60,
            env={**os.environ, "JAX_PLATFORMS": "cpu"},
        )
        assert proc.returncode == 1
        assert dispatcher.doing_tasks(), "crasher held no task"

        # liveness detection must recover the orphaned task
        deadline = time.time() + 15
        while dispatcher.doing_tasks() and time.time() < deadline:
            time.sleep(0.2)
        assert not dispatcher.doing_tasks(), "task never recovered"

        # a surviving worker drains the whole job, crashed task included
        worker = Worker(
            MasterClient("localhost:%d" % port, worker_id=2),
            "tests.models.mnist_with_export",
            reader,
            minibatch_size=32,
            wait_sleep_secs=0.1,
        )
        worker.run()
        assert dispatcher.finished()
        assert not dispatcher.job_failed()
    finally:
        monitor.stop()
        server.stop(0)


VICTIM = r"""
import sys, time
sys.path.insert(0, %(repo)r)
from elasticdl_tpu.observability import events
from elasticdl_tpu.proto import elasticdl_tpu_pb2 as pb
from elasticdl_tpu.worker.master_client import MasterClient

events.configure("worker-1")
events.install_crash_hooks()
mc = MasterClient(%(addr)r, worker_id=1)
mc.telemetry_provider = lambda: pb.TelemetryBlob(
    role="worker-1", step_time_ewma=0.1, model_version=1)
mc.reset_worker()
events.emit("role_start", worker=1, epoch=mc.incarnation or 0)
task = mc.get_task()
assert task.task_id != 0, "no task to hold"
print("READY", flush=True)
while True:  # heartbeat mid-round until killed
    mc.get_comm_info()
    time.sleep(0.2)
"""


@pytest.mark.slow
@pytest.mark.parametrize("kill_signal",
                         [signal.SIGTERM, signal.SIGKILL])
def test_worker_kill_fires_dead_air_and_leaves_flight_record(
    tmp_path, monkeypatch, kill_signal,
):
    """ISSUE 3 chaos acceptance: kill a real worker process mid-round;
    the master's fleet monitor must raise a dead-air alert within the
    detection window (counter incremented), the victim's flight record
    must be on disk (journal always; ring dump for the SIGTERM/eviction
    path — SIGKILL can't run hooks, write-through covers it), and
    scripts/postmortem.py must thread one timeline spanning the
    victim's record, the master's requeue, and the alert."""
    from elasticdl_tpu.master.fleet import FleetMonitor
    from elasticdl_tpu.observability import events
    from elasticdl_tpu.observability import metrics as obs_metrics
    from tests.test_utils import create_mnist_recordio

    events_dir = tmp_path / "events"
    events_dir.mkdir()
    train_dir = tmp_path / "train"
    train_dir.mkdir()
    create_mnist_recordio(str(train_dir / "f0.rec"), num_records=128,
                          seed=0)
    reader = RecordIODataReader(data_dir=str(train_dir))

    monkeypatch.setenv(events.EVENTS_DIR_ENV, str(events_dir))
    monkeypatch.setenv("EDL_METRICS", "1")
    obs_metrics.reset_default_registry()
    events.configure("master")
    dispatcher = TaskDispatcher(
        training_shards=reader.create_shards(), records_per_task=64,
        num_epochs=1, seed=0,
    )
    fleet = FleetMonitor(
        straggler_factor=3.0, dead_air_secs=1.5,
        stuck_round_secs=60.0, version_lag_max=1000,
    )
    servicer = MasterServicer(dispatcher, fleet_monitor=fleet)
    monitor = TaskMonitor(
        dispatcher, servicer, None, liveness_timeout_secs=4.0,
        scan_interval_secs=0.2, fleet_monitor=fleet,
    )
    server = build_server()
    add_master_servicer_to_server(servicer, server)
    port = find_free_port()
    server.add_insecure_port("localhost:%d" % port)
    server.start()
    monitor.start()
    victim = None
    try:
        victim = subprocess.Popen(
            [sys.executable, "-c", VICTIM % {
                "repo": os.path.dirname(os.path.dirname(__file__)),
                "addr": "localhost:%d" % port,
            }],
            env={**os.environ, "JAX_PLATFORMS": "cpu",
                 events.EVENTS_DIR_ENV: str(events_dir)},
            stdout=subprocess.PIPE, text=True,
        )
        assert victim.stdout.readline().strip() == "READY"
        assert dispatcher.doing_tasks(), "victim held no task"

        # chaos: kill the worker process mid-round
        victim.send_signal(kill_signal)
        victim.wait(timeout=30)
        killed_at = time.time()

        # the dead-air detector must fire within its window (the scan
        # thread evaluates every 0.2 s; window is 1.5 s of silence)
        deadline = killed_at + 10
        fired = None
        while time.time() < deadline:
            fired = [
                a for a in fleet.alerts()
                if a["alert"] == "dead_air" and a["worker_id"] == 1
            ]
            if fired:
                break
            time.sleep(0.1)
        assert fired, "dead-air alert never fired for the victim"
        assert time.time() - killed_at < 10, "detection too slow"
        counter = obs_metrics.default_registry().get(
            "edl_master_alerts_total"
        )
        assert counter.get("dead_air") >= 1

        # the victim's flight record survived it
        journals = [
            name for name in os.listdir(str(events_dir))
            if name.startswith("worker-1") and
            name.endswith(".events.ndjson")
        ]
        assert journals, "victim journal missing"
        with open(str(events_dir / journals[0])) as f:
            victim_events = [json.loads(line) for line in f]
        assert any(e["event"] == "role_start" for e in victim_events)
        dumps = [
            name for name in os.listdir(str(events_dir))
            if name.startswith("worker-1") and
            name.endswith(".dump.json")
        ]
        if kill_signal == signal.SIGTERM:
            # the crash hook dumped the ring on the way down
            assert dumps, "victim ring dump missing after SIGTERM"
            with open(str(events_dir / dumps[0])) as f:
                assert json.load(f)["reason"] == "sigterm"

        # liveness recovery requeues the orphaned task -> journaled
        deadline = time.time() + 15
        while dispatcher.doing_tasks() and time.time() < deadline:
            time.sleep(0.1)
        assert not dispatcher.doing_tasks(), "task never recovered"
    finally:
        monitor.stop()
        server.stop(0)
        if victim is not None and victim.poll() is None:
            victim.kill()
        events.flush()

    # postmortem threads one correlation-keyed timeline across the
    # victim's record, the master's requeue, and the alert
    sys.path.insert(0, os.path.join(
        os.path.dirname(os.path.dirname(__file__)), "scripts"
    ))
    try:
        import postmortem
    finally:
        sys.path.pop(0)
    report = postmortem.postmortem(str(events_dir))
    events._reset_for_tests()
    kinds = {e["event"] for e in report["timeline"]}
    assert {"role_start", "worker_register", "task_dispatch",
            "alert_raised", "task_requeue",
            "worker_presumed_dead"} <= kinds, kinds
    timeline_ts = [e.get("ts", 0) for e in report["timeline"]]
    assert timeline_ts == sorted(timeline_ts)
    worker1 = report["summary"]["workers"]["1"]
    assert worker1["registrations"], "victim registration not threaded"
    assert worker1["requeued_tasks"], "requeue not threaded"
    assert "dead_air" in worker1["alerts"]
    if kill_signal == signal.SIGTERM:
        assert worker1["dump"] == "sigterm"


def test_ps_crash_restart_job_completes(tmp_path):
    """A parameter-server shard dies mid-training and is relaunched on
    the same address with checkpoint restore; the worker's PS client
    retries through the outage (ps_client.py PS_RETRY_BUDGET) and the
    job completes — no task-retry budget burned on the restart window.
    (Reference behavior: same-id PS relaunch behind a stable per-pod
    Service, instance_manager; worker main's channel connect retries.)"""
    import signal
    import socket

    from elasticdl_tpu.master.servicer import MasterServicer
    from tests.test_utils import create_ctr_recordio

    def free_port():
        s = socket.socket()
        s.bind(("", 0))
        port = s.getsockname()[1]
        s.close()
        return port

    def wait_port(port, timeout=90):
        deadline = time.time() + timeout
        while time.time() < deadline:
            s = socket.socket()
            try:
                s.connect(("127.0.0.1", port))
                return
            except OSError:
                time.sleep(0.3)
            finally:
                s.close()
        raise TimeoutError(port)

    train_dir = tmp_path / "train"
    train_dir.mkdir()
    create_ctr_recordio(str(train_dir / "f0.rec"), num_records=768, seed=0)
    reader = RecordIODataReader(data_dir=str(train_dir))
    dispatcher = TaskDispatcher(
        training_shards=reader.create_shards(),
        records_per_task=128,
        num_epochs=2,
        seed=0,
    )
    server = build_server()
    add_master_servicer_to_server(MasterServicer(dispatcher, None), server)
    master_port = find_free_port()
    server.add_insecure_port("localhost:%d" % master_port)
    server.start()

    ps_port = free_port()
    ckpt_dir = str(tmp_path / "ps_ckpt")

    def spawn_ps(restore):
        cmd = [
            sys.executable, "-m", "elasticdl_tpu.ps.server",
            "--ps_id", "0", "--num_ps_pods", "1",
            "--port", str(ps_port),
            "--opt_type", "adam", "--opt_args", "lr=0.01",
            "--checkpoint_dir", ckpt_dir,
            "--checkpoint_steps", "2",
        ]
        if restore:
            cmd += ["--checkpoint_dir_for_init", ckpt_dir]
        return subprocess.Popen(
            cmd,
            env={**os.environ, "JAX_PLATFORMS": "cpu"},
            stdout=subprocess.DEVNULL,
            stderr=subprocess.DEVNULL,
        )

    ps_proc = spawn_ps(restore=False)
    wait_port(ps_port)
    try:
        worker = Worker(
            MasterClient("localhost:%d" % master_port, worker_id=0),
            "elasticdl_tpu.models.deepfm",
            RecordIODataReader(data_dir=str(train_dir)),
            minibatch_size=64,
            wait_sleep_secs=0.1,
            ps_addrs=["localhost:%d" % ps_port],
        )
        runner = threading.Thread(target=worker.run, daemon=True)
        runner.start()

        # let training make progress (PS checkpoints every 2 versions)
        deadline = time.time() + 120
        while time.time() < deadline and not (
            os.path.isdir(ckpt_dir) and os.listdir(ckpt_dir)
        ):
            time.sleep(0.2)
        assert os.listdir(ckpt_dir), "PS never checkpointed"

        # chaos: SIGKILL the PS shard mid-job, relaunch with restore
        ps_proc.send_signal(signal.SIGKILL)
        ps_proc.wait(timeout=30)
        time.sleep(2)  # let the worker hit the outage window
        ps_proc = spawn_ps(restore=True)

        runner.join(timeout=180)
        assert not runner.is_alive(), "worker never finished after PS restart"
        assert dispatcher.finished(), "job did not complete"
        assert not dispatcher.job_failed(), (
            "PS restart window burned the task retry budget"
        )
    finally:
        server.stop(0)
        if ps_proc.poll() is None:
            ps_proc.kill()
