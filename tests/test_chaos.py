"""Fault injection: a real worker process dies holding tasks; the
master's liveness detection recovers them and a surviving worker drains
the job. The reference had no fault-injection tests at all (SURVEY.md
§5 "fault injection: none; CI relies on natural preemption")."""

import os
import subprocess
import sys
import threading
import time

from elasticdl_tpu.common.grpc_utils import build_server, find_free_port
from elasticdl_tpu.data.readers import RecordIODataReader
from elasticdl_tpu.master.servicer import MasterServicer
from elasticdl_tpu.master.task_dispatcher import TaskDispatcher
from elasticdl_tpu.master.task_monitor import TaskMonitor
from elasticdl_tpu.proto.services import add_master_servicer_to_server
from elasticdl_tpu.worker.master_client import MasterClient
from elasticdl_tpu.worker.worker import Worker
from tests.test_utils import create_mnist_recordio

CRASHER = r"""
import os, sys
sys.path.insert(0, %(repo)r)
from elasticdl_tpu.worker.master_client import MasterClient
mc = MasterClient(%(addr)r, worker_id=1)
task = mc.get_task()
assert task.task_id != 0, "no task to hold"
os._exit(1)  # die mid-task, nothing reported
"""


def test_worker_crash_recovers_and_job_completes(tmp_path):
    train_dir = tmp_path / "train"
    train_dir.mkdir()
    create_mnist_recordio(str(train_dir / "f0.rec"), num_records=256, seed=0)
    reader = RecordIODataReader(data_dir=str(train_dir))

    dispatcher = TaskDispatcher(
        training_shards=reader.create_shards(),
        records_per_task=64,
        num_epochs=1,
        seed=0,
    )
    servicer = MasterServicer(dispatcher, None)
    monitor = TaskMonitor(
        dispatcher, servicer, None, liveness_timeout_secs=4.0,
        scan_interval_secs=0.2,
    )
    server = build_server()
    add_master_servicer_to_server(servicer, server)
    port = find_free_port()
    server.add_insecure_port("localhost:%d" % port)
    server.start()
    monitor.start()
    try:
        # chaos: a real OS process grabs a task and dies holding it
        script = CRASHER % {
            "repo": os.path.dirname(os.path.dirname(__file__)),
            "addr": "localhost:%d" % port,
        }
        proc = subprocess.run(
            [sys.executable, "-c", script], timeout=60,
            env={**os.environ, "JAX_PLATFORMS": "cpu"},
        )
        assert proc.returncode == 1
        assert dispatcher.doing_tasks(), "crasher held no task"

        # liveness detection must recover the orphaned task
        deadline = time.time() + 15
        while dispatcher.doing_tasks() and time.time() < deadline:
            time.sleep(0.2)
        assert not dispatcher.doing_tasks(), "task never recovered"

        # a surviving worker drains the whole job, crashed task included
        worker = Worker(
            MasterClient("localhost:%d" % port, worker_id=2),
            "tests.models.mnist_with_export",
            reader,
            minibatch_size=32,
            wait_sleep_secs=0.1,
        )
        worker.run()
        assert dispatcher.finished()
        assert not dispatcher.job_failed()
    finally:
        monitor.stop()
        server.stop(0)


def test_ps_crash_restart_job_completes(tmp_path):
    """A parameter-server shard dies mid-training and is relaunched on
    the same address with checkpoint restore; the worker's PS client
    retries through the outage (ps_client.py PS_RETRY_BUDGET) and the
    job completes — no task-retry budget burned on the restart window.
    (Reference behavior: same-id PS relaunch behind a stable per-pod
    Service, instance_manager; worker main's channel connect retries.)"""
    import signal
    import socket

    from elasticdl_tpu.master.servicer import MasterServicer
    from tests.test_utils import create_ctr_recordio

    def free_port():
        s = socket.socket()
        s.bind(("", 0))
        port = s.getsockname()[1]
        s.close()
        return port

    def wait_port(port, timeout=90):
        deadline = time.time() + timeout
        while time.time() < deadline:
            s = socket.socket()
            try:
                s.connect(("127.0.0.1", port))
                return
            except OSError:
                time.sleep(0.3)
            finally:
                s.close()
        raise TimeoutError(port)

    train_dir = tmp_path / "train"
    train_dir.mkdir()
    create_ctr_recordio(str(train_dir / "f0.rec"), num_records=768, seed=0)
    reader = RecordIODataReader(data_dir=str(train_dir))
    dispatcher = TaskDispatcher(
        training_shards=reader.create_shards(),
        records_per_task=128,
        num_epochs=2,
        seed=0,
    )
    server = build_server()
    add_master_servicer_to_server(MasterServicer(dispatcher, None), server)
    master_port = find_free_port()
    server.add_insecure_port("localhost:%d" % master_port)
    server.start()

    ps_port = free_port()
    ckpt_dir = str(tmp_path / "ps_ckpt")

    def spawn_ps(restore):
        cmd = [
            sys.executable, "-m", "elasticdl_tpu.ps.server",
            "--ps_id", "0", "--num_ps_pods", "1",
            "--port", str(ps_port),
            "--opt_type", "adam", "--opt_args", "lr=0.01",
            "--checkpoint_dir", ckpt_dir,
            "--checkpoint_steps", "2",
        ]
        if restore:
            cmd += ["--checkpoint_dir_for_init", ckpt_dir]
        return subprocess.Popen(
            cmd,
            env={**os.environ, "JAX_PLATFORMS": "cpu"},
            stdout=subprocess.DEVNULL,
            stderr=subprocess.DEVNULL,
        )

    ps_proc = spawn_ps(restore=False)
    wait_port(ps_port)
    try:
        worker = Worker(
            MasterClient("localhost:%d" % master_port, worker_id=0),
            "elasticdl_tpu.models.deepfm",
            RecordIODataReader(data_dir=str(train_dir)),
            minibatch_size=64,
            wait_sleep_secs=0.1,
            ps_addrs=["localhost:%d" % ps_port],
        )
        runner = threading.Thread(target=worker.run, daemon=True)
        runner.start()

        # let training make progress (PS checkpoints every 2 versions)
        deadline = time.time() + 120
        while time.time() < deadline and not (
            os.path.isdir(ckpt_dir) and os.listdir(ckpt_dir)
        ):
            time.sleep(0.2)
        assert os.listdir(ckpt_dir), "PS never checkpointed"

        # chaos: SIGKILL the PS shard mid-job, relaunch with restore
        ps_proc.send_signal(signal.SIGKILL)
        ps_proc.wait(timeout=30)
        time.sleep(2)  # let the worker hit the outage window
        ps_proc = spawn_ps(restore=True)

        runner.join(timeout=180)
        assert not runner.is_alive(), "worker never finished after PS restart"
        assert dispatcher.finished(), "job did not complete"
        assert not dispatcher.job_failed(), (
            "PS restart window burned the task retry budget"
        )
    finally:
        server.stop(0)
        if ps_proc.poll() is None:
            ps_proc.kill()
