"""Fault injection: a real worker process dies holding tasks; the
master's liveness detection recovers them and a surviving worker drains
the job. The reference had no fault-injection tests at all (SURVEY.md
§5 "fault injection: none; CI relies on natural preemption")."""

import os
import subprocess
import sys
import time

from elasticdl_tpu.common.grpc_utils import build_server, find_free_port
from elasticdl_tpu.data.readers import RecordIODataReader
from elasticdl_tpu.master.servicer import MasterServicer
from elasticdl_tpu.master.task_dispatcher import TaskDispatcher
from elasticdl_tpu.master.task_monitor import TaskMonitor
from elasticdl_tpu.proto.services import add_master_servicer_to_server
from elasticdl_tpu.worker.master_client import MasterClient
from elasticdl_tpu.worker.worker import Worker
from tests.test_utils import create_mnist_recordio

CRASHER = r"""
import os, sys
sys.path.insert(0, %(repo)r)
from elasticdl_tpu.worker.master_client import MasterClient
mc = MasterClient(%(addr)r, worker_id=1)
task = mc.get_task()
assert task.task_id != 0, "no task to hold"
os._exit(1)  # die mid-task, nothing reported
"""


def test_worker_crash_recovers_and_job_completes(tmp_path):
    train_dir = tmp_path / "train"
    train_dir.mkdir()
    create_mnist_recordio(str(train_dir / "f0.rec"), num_records=256, seed=0)
    reader = RecordIODataReader(data_dir=str(train_dir))

    dispatcher = TaskDispatcher(
        training_shards=reader.create_shards(),
        records_per_task=64,
        num_epochs=1,
        seed=0,
    )
    servicer = MasterServicer(dispatcher, None)
    monitor = TaskMonitor(
        dispatcher, servicer, None, liveness_timeout_secs=4.0,
        scan_interval_secs=0.2,
    )
    server = build_server()
    add_master_servicer_to_server(servicer, server)
    port = find_free_port()
    server.add_insecure_port("localhost:%d" % port)
    server.start()
    monitor.start()
    try:
        # chaos: a real OS process grabs a task and dies holding it
        script = CRASHER % {
            "repo": os.path.dirname(os.path.dirname(__file__)),
            "addr": "localhost:%d" % port,
        }
        proc = subprocess.run(
            [sys.executable, "-c", script], timeout=60,
            env={**os.environ, "JAX_PLATFORMS": "cpu"},
        )
        assert proc.returncode == 1
        assert dispatcher.doing_tasks(), "crasher held no task"

        # liveness detection must recover the orphaned task
        deadline = time.time() + 15
        while dispatcher.doing_tasks() and time.time() < deadline:
            time.sleep(0.2)
        assert not dispatcher.doing_tasks(), "task never recovered"

        # a surviving worker drains the whole job, crashed task included
        worker = Worker(
            MasterClient("localhost:%d" % port, worker_id=2),
            "tests.models.mnist_with_export",
            reader,
            minibatch_size=32,
            wait_sleep_secs=0.1,
        )
        worker.run()
        assert dispatcher.finished()
        assert not dispatcher.job_failed()
    finally:
        monitor.stop()
        server.stop(0)
