"""Test configuration.

Tests run on the JAX CPU backend with 8 virtual devices standing in for a
TPU slice, mirroring the reference's strategy of exercising distributed
behavior without a real cluster (SURVEY.md §4: in-process gRPC
multi-servicer tests + fake devices).

Environment must be set before jax is imported anywhere.
"""

import os
import sys

# Force-set (not setdefault): the TPU container exports JAX_PLATFORMS=axon
# and its sitecustomize imports jax at interpreter start, so both the env
# var and the already-imported config must be overridden before any
# backend initializes.
os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
