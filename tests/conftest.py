"""Test configuration.

Tests run on the JAX CPU backend with 8 virtual devices standing in for a
TPU slice, mirroring the reference's strategy of exercising distributed
behavior without a real cluster (SURVEY.md §4: in-process gRPC
multi-servicer tests + fake devices).

Environment must be set before jax is imported anywhere.
"""

import os
import sys

# Force-set (not setdefault): the TPU container exports JAX_PLATFORMS=axon
# and its sitecustomize imports jax at interpreter start, so both the env
# var and the already-imported config must be overridden before any
# backend initializes.
os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax  # noqa: E402
import pytest  # noqa: E402

jax.config.update("jax_platforms", "cpu")

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


# ---------------------------------------------------------------------------
# Suite tiering (reference parity: the two-tier travis split,
# /root/reference/.travis.yml:30-98). Multi-minute live-process e2es carry
# @pytest.mark.slow in their files; the list below additionally demotes the
# heaviest convergence/SPMD tests (measured full-suite --durations, round 5)
# so `pytest -m "not slow"` — the scripts/ci.sh fast lane — stays under
# 5 minutes as the suite grows. Criterion: >=8 s/test on the round-5 box.
# ---------------------------------------------------------------------------

SLOW_BY_DURATION = {
    "test_model_zoo.py": (
        "test_vision_family_learns",        # 97 s + 42 s params
        "test_ctr_family_learns",
        "test_census_wide_deep_learns",
        "test_census_sqlflow_wide_deep_learns",
        "test_census_dnn_learns",
    ),
    "test_pipeline.py": (
        "test_device_major_layout_matches_chunk_major",  # 67 s
        "test_pipelined_lm_matches_sequential_fallback",
        "test_pipelined_lm_trains_on_pp_mesh",
    ),
    "test_dense_checkpoint.py": (
        "test_resume_onto_different_mesh",
        "test_roundtrip_includes_optimizer_state",
        "test_spmd_checkpoint_restores_on_single_chip",
    ),
    "test_transformer_spmd.py": (
        "test_remat_policies_match_no_remat",
        "test_spmd_tp_sp_matches_single_device",
        "test_spmd_fsdp_transformer_runs",
    ),
    "test_resnet_dtypes.py": ("test_bf16_stream_f32_stats",),
    "test_moe.py": (
        "test_expert_parallel_matches_single_device",
        "test_expert_balance_holds_over_a_real_run",
        "test_moe_eval_returns_bare_logits",
        "test_moe_lm_compact_matches_onehot_losses",
        "test_compact_dispatch_under_dp_mesh_matches_single_device",
    ),
    "test_sparse_spmd.py": (
        "test_sparse_spmd_matches_single_device",
        "test_sparse_spmd_pads_ragged_batches",
    ),
    "test_sync_ps.py": ("test_two_live_sparse_trainers_race_sync_ps",),
    "test_eval_predict_jobs.py": (
        "test_evaluation_only_job_end_to_end",
        "test_prediction_only_job_end_to_end",
    ),
    "test_local_executor.py": ("test_mnist_local_training_converges",),
    "test_chaos.py": (
        "test_ps_crash_restart_job_completes",
        "test_worker_crash_recovers_and_job_completes",
    ),
    "test_grad_accum.py": ("test_accum_with_dropout_still_trains",),
    "test_worker_distributed.py": (
        "test_two_workers_share_the_queue",
        "test_worker_checkpoint_resume_and_fatal_restore",
    ),
    "test_spmd_trainer.py": (
        "test_dp8_matches_single_device_semantics",
    ),
    "test_sparse_pipeline.py": (
        "test_train_stream_matches_sequential_on_disjoint_ids",
    ),
    "test_data_gen.py": ("test_generated_census_is_learnable",),
    "test_tensorboard_service.py": (
        "test_event_roundtrip_via_tensorboard_reader",
    ),
    "test_tutorials.py": (
        "test_local_quickstart_runs",
        "test_model_contract_example_satisfies_loader",
    ),
}


def _test_names_defined_in(path):
    """Every test function name defined in a test file, including
    methods inside Test* classes (AST walk — so the staleness guard
    below sees what EXISTS, independent of how many items this
    particular invocation collected; a single-node-ID rerun must not
    trip it)."""
    import ast

    return {
        node.name
        for node in ast.walk(ast.parse(open(path).read()))
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef))
        and node.name.startswith("test")
    }


@pytest.hookimpl(tryfirst=True)  # before -k/-m deselection filters
def pytest_collection_modifyitems(items):
    checked_files = {}
    for item in items:
        fname = os.path.basename(str(item.fspath))
        names = SLOW_BY_DURATION.get(fname)
        if not names:
            continue
        if fname not in checked_files:
            checked_files[fname] = str(item.fspath)
        for name in names:
            if item.name == name or item.name.startswith(name + "["):
                item.add_marker(pytest.mark.slow)
    # staleness guard: a renamed/removed slow test must not silently
    # re-enter the fast lane — fail collection loudly instead
    for fname, path in checked_files.items():
        missing = set(SLOW_BY_DURATION[fname]) - _test_names_defined_in(
            path
        )
        assert not missing, (
            "conftest SLOW_BY_DURATION lists tests that no longer exist "
            "in %s: %s — update the list" % (fname, sorted(missing))
        )
