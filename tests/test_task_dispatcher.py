"""TaskDispatcher semantics tests.

Models the reference's task_dispatcher_test.py coverage: slicing, epochs,
re-queue on failure, retry cap, recover_tasks, train-end callback task.
"""

from elasticdl_tpu.master.task_dispatcher import TaskDispatcher
from elasticdl_tpu.proto import elasticdl_tpu_pb2 as pb


def make_dispatcher(**kwargs):
    defaults = dict(
        training_shards={"f1": (0, 10), "f2": (0, 10)},
        evaluation_shards={"e1": (0, 10)},
        records_per_task=3,
        num_epochs=2,
        shuffle=False,
    )
    defaults.update(kwargs)
    return TaskDispatcher(**defaults)


def drain(dispatcher, worker_id=0):
    tasks = []
    while True:
        task = dispatcher.get(worker_id)
        if task is None:
            break
        tasks.append(task)
    return tasks


def test_task_slicing_covers_all_records():
    d = make_dispatcher(num_epochs=1)
    tasks = drain(d)
    # 10 records / 3 per task = 4 tasks per shard, 2 shards
    assert len(tasks) == 8
    covered = {}
    for t in tasks:
        covered.setdefault(t.shard_name, []).append((t.start, t.end))
    for name in ("f1", "f2"):
        ranges = sorted(covered[name])
        assert ranges[0][0] == 0
        assert ranges[-1][1] == 10
        # contiguity
        for (s0, e0), (s1, e1) in zip(ranges, ranges[1:]):
            assert e0 == s1


def test_lazy_epoch_creation():
    # get() creates the next epoch's tasks lazily when the queue drains,
    # so a persistent worker sees all epochs as one continuous stream.
    d = make_dispatcher(num_epochs=3)
    tasks = drain(d)
    assert len(tasks) == 24  # 8 tasks/epoch x 3 epochs
    for t in tasks:
        d.report(t.task_id, True)
    assert d.get(0) is None
    assert d.finished()


def test_failed_task_requeued_then_capped():
    d = make_dispatcher(num_epochs=1, max_task_retries=3)
    task = d.get(0)
    for _ in range(3):
        d.report(task.task_id, False)
        again = None
        # the failed task goes to the back of the queue
        while True:
            t = d.get(0)
            if t is None:
                break
            if t.task_id == task.task_id:
                again = t
                break
            d.report(t.task_id, True)
        assert again is not None
    d.report(task.task_id, False)  # 4th failure exceeds cap
    assert d.job_failed()


def test_recover_tasks_requeues_worker_inflight():
    d = make_dispatcher(num_epochs=1)
    t1 = d.get(worker_id=1)
    t2 = d.get(worker_id=1)
    t3 = d.get(worker_id=2)
    d.recover_tasks(1)
    remaining = drain(d, worker_id=3)
    ids = {t.task_id for t in remaining}
    assert t1.task_id in ids and t2.task_id in ids
    assert t3.task_id not in ids  # still held by worker 2


def test_train_end_callback_task_created_after_last_epoch():
    d = make_dispatcher(num_epochs=1)
    d.add_deferred_callback_create_train_end_task({"saved_model_path": "/tmp/m"})
    tasks = drain(d)
    for t in tasks:
        d.report(t.task_id, True)
    end_task = d.get(0)
    assert end_task is not None
    assert end_task.type == pb.TRAIN_END_CALLBACK
    assert end_task.extended_config["saved_model_path"] == "/tmp/m"
    assert not d.finished()
    d.report(end_task.task_id, True)
    assert d.finished()


def test_evaluation_tasks_take_priority():
    d = make_dispatcher(num_epochs=1)
    n = d.create_evaluation_tasks(model_version=5)
    assert n == 4  # 10 records / 3 per task, 1 eval shard
    t = d.get(0)
    assert t.type == pb.EVALUATION
    assert t.model_version == 5


def test_prediction_only_job():
    d = TaskDispatcher(
        training_shards={},
        prediction_shards={"p": (0, 7)},
        records_per_task=3,
        num_epochs=1,
    )
    tasks = drain(d)
    assert len(tasks) == 3
    assert all(t.type == pb.PREDICTION for t in tasks)
