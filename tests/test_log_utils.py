"""Logging-control tests (common/log_utils.py: the --log_level /
--log_file_path surface, reference client args :369,392)."""

import logging

import pytest

from elasticdl_tpu.common import log_utils


def test_log_configure_level_and_file(tmp_path):
    """configure() re-levels existing AND future package loggers and
    adds a file handler; bad levels error loudly."""
    existing = log_utils.default_logger("elasticdl_tpu.test_existing")
    assert existing.level == logging.INFO
    log_file = tmp_path / "edl.log"
    log_utils.configure("DEBUG", str(log_file))
    try:
        assert existing.level == logging.DEBUG
        created_after = log_utils.default_logger(
            "elasticdl_tpu.test_after"
        )
        assert created_after.level == logging.DEBUG
        created_after.debug("hello-from-configure-test")
        for h in logging.getLogger().handlers:
            h.flush()
        assert "hello-from-configure-test" in log_file.read_text()
        with pytest.raises(ValueError, match="log_level"):
            log_utils.configure("NOISY")
    finally:
        # configure() re-leveled EVERY existing elasticdl_tpu logger —
        # restore them all, or the rest of the session runs at DEBUG
        log_utils._configured_level = None
        for name, logger in logging.root.manager.loggerDict.items():
            if name.startswith("elasticdl_tpu") and isinstance(
                logger, logging.Logger
            ):
                logger.setLevel(logging.INFO)
        logging.getLogger("elasticdl_tpu").setLevel(logging.INFO)
        for h in list(logging.getLogger().handlers):
            if isinstance(h, logging.FileHandler):
                h.close()
                logging.getLogger().removeHandler(h)
