"""Incremental sparse checkpointing (ISSUE 13): dirty-row tracking in
both store backends, the delta chain format (base + dirty-row deltas +
lifecycle tombstones, EDL_CKPT_COMPACT_EVERY compaction), atomic shard
writes, chain-aware restore/latest_version under torn files, the
off-RPC AsyncCheckpointer (coalescing contract), and the
maybe_stream_checkpoint boundary anchoring that was untested edge
logic before this PR."""

import os
import shutil
import threading
import time

import numpy as np
import pytest

from elasticdl_tpu.ps.checkpoint import (
    AsyncCheckpointer,
    SparseCheckpointSaver,
)
from elasticdl_tpu.ps.embedding_store import (
    NumpyEmbeddingStore,
    native_lib,
)

BACKENDS = ["numpy"] + (["native"] if native_lib() is not None else [])


def make_store(backend, opt_type="adam", seed=0, **opt_args):
    if backend == "native":
        from elasticdl_tpu.ps.embedding_store import NativeEmbeddingStore

        store = NativeEmbeddingStore(seed=seed)
    else:
        store = NumpyEmbeddingStore(seed=seed)
    store.set_optimizer(opt_type, **opt_args)
    store.create_table("t", 4, init_scale=0.0, initializer="zeros")
    return store


def full_state(store, name="t"):
    """(ids, rows, steps) sorted by id — order-free comparison key."""
    ids, rows, steps = store.export_table_full(name)
    order = np.argsort(ids)
    return ids[order], rows[order], steps[order]


def assert_state_equal(a, b):
    sa, sb = full_state(a), full_state(b)
    assert sa[0].shape == sb[0].shape
    np.testing.assert_array_equal(sa[0], sb[0])
    np.testing.assert_array_equal(sa[1], sb[1])
    np.testing.assert_array_equal(sa[2], sb[2])


# ---------------------------------------------------------------------------
# dirty-row tracking


@pytest.mark.parametrize("backend", BACKENDS)
def test_dirty_tracking_snapshot_and_clear(backend):
    store = make_store(backend)
    ids = np.arange(8, dtype=np.int64)
    store.push_gradients("t", ids, np.ones((8, 4), np.float32))
    # a lookup that MATERIALIZES a row is a state change; re-reading a
    # resident row is not
    store.lookup("t", np.array([99, 3], np.int64))
    assert store.dirty_count("t") == 9
    d_ids, d_rows, d_steps, dead = store.export_table_dirty("t")
    # ids ascending (deterministic files), full train-state width
    np.testing.assert_array_equal(
        d_ids, np.array([0, 1, 2, 3, 4, 5, 6, 7, 99])
    )
    assert d_rows.shape == (9, 4 * (1 + store.table_slots("t")))
    assert dead.size == 0
    # snapshot CLEARED: nothing dirty until the next mutation
    assert store.dirty_count("t") == 0
    assert store.export_table_dirty("t")[0].size == 0
    store.push_gradients(
        "t", np.array([3], np.int64), np.ones((1, 4), np.float32)
    )
    assert store.dirty_count("t") == 1


@pytest.mark.parametrize("backend", BACKENDS)
def test_drop_rows_moves_dirty_to_dead(backend):
    store = make_store(backend)
    ids = np.arange(4, dtype=np.int64)
    store.push_gradients("t", ids, np.ones((4, 4), np.float32))
    assert store.drop_rows("t", np.array([1, 2, 77], np.int64)) == 2
    d_ids, _, _, dead = store.export_table_dirty("t")
    np.testing.assert_array_equal(d_ids, np.array([0, 3]))
    # only rows that EXISTED are tombstoned (77 never materialized)
    np.testing.assert_array_equal(dead, np.array([1, 2]))
    # a re-materialized id leaves the dead set again
    store.push_gradients(
        "t", np.array([1], np.int64), np.ones((1, 4), np.float32)
    )
    d_ids, _, _, dead = store.export_table_dirty("t")
    np.testing.assert_array_equal(d_ids, np.array([1]))
    assert dead.size == 0


def test_dirty_export_parity_numpy_native():
    if native_lib() is None:
        pytest.skip("no native lib")
    stores = [make_store(b) for b in ("numpy", "native")]
    rng = np.random.RandomState(7)
    for step in range(5):
        ids = rng.randint(0, 40, size=12).astype(np.int64)
        ids = np.unique(ids)
        grads = rng.randn(ids.size, 4).astype(np.float32)
        for store in stores:
            store.push_gradients("t", ids, grads)
        if step == 2:
            for store in stores:
                store.drop_rows("t", np.array([5, 6], np.int64))
    exports = [s.export_table_dirty("t") for s in stores]
    for a, b in zip(*exports):
        np.testing.assert_array_equal(a, b)


# ---------------------------------------------------------------------------
# delta chain format


@pytest.mark.parametrize("backend", BACKENDS)
def test_chain_restore_bit_identical_to_full(backend, tmp_path):
    """The acceptance shape: base + deltas (with tombstones) restores
    bit-identically to a full save of the same live store, and
    tombstoned ids stay dead."""
    live = make_store(backend)
    rng = np.random.RandomState(0)
    saver = SparseCheckpointSaver(str(tmp_path / "chain"),
                                  compact_every=10)
    ids = np.arange(50, dtype=np.int64)
    live.push_gradients("t", ids, rng.randn(50, 4).astype(np.float32))
    assert saver.save(1, live).kind == "full"
    for v in range(2, 6):
        sub = np.unique(rng.randint(0, 60, size=8)).astype(np.int64)
        live.push_gradients(
            "t", sub, rng.randn(sub.size, 4).astype(np.float32)
        )
        live.drop_rows("t", np.array([40 + v], np.int64))
        result = saver.save(v, live)
        assert result.kind == "delta"
        assert result.chain_len == v - 1
    # reference: an independent FULL save of the same live state
    SparseCheckpointSaver(str(tmp_path / "full")).save(5, live)

    from_chain = make_store(backend, seed=1)
    from_full = make_store(backend, seed=2)
    assert SparseCheckpointSaver(
        str(tmp_path / "chain")
    ).restore(from_chain) == 5
    assert SparseCheckpointSaver(
        str(tmp_path / "full")
    ).restore(from_full) == 5
    assert_state_equal(from_chain, from_full)
    assert_state_equal(from_chain, live)
    resident = set(from_chain.export_table_full("t")[0].tolist())
    for v in range(2, 6):
        assert 40 + v not in resident, "tombstoned id resurrected"


def test_chain_interop_numpy_native_bit_exact(tmp_path):
    """A chain written from the numpy store restores into the native
    store bit-exactly, and vice versa (the checkpoint is the interop
    boundary between backends)."""
    if native_lib() is None:
        pytest.skip("no native lib")
    for writer_backend, reader_backend in (
        ("numpy", "native"), ("native", "numpy"),
    ):
        ckpt = tmp_path / ("chain-" + writer_backend)
        writer = make_store(writer_backend)
        rng = np.random.RandomState(3)
        saver = SparseCheckpointSaver(str(ckpt), compact_every=8)
        ids = np.arange(20, dtype=np.int64)
        writer.push_gradients(
            "t", ids, rng.randn(20, 4).astype(np.float32)
        )
        saver.save(1, writer)
        writer.drop_rows("t", np.array([4], np.int64))
        writer.push_gradients(
            "t", ids[:6], rng.randn(6, 4).astype(np.float32)
        )
        saver.save(2, writer)
        reader = make_store(reader_backend, seed=9)
        assert SparseCheckpointSaver(str(ckpt)).restore(reader) == 2
        assert_state_equal(reader, writer)


def test_old_full_format_still_restores(tmp_path):
    """A pre-ISSUE-13 checkpoint dir (full base only, written by the
    old non-atomic saver) is a chain of length zero."""
    store = make_store("numpy")
    ids = np.arange(6, dtype=np.int64)
    store.push_gradients("t", ids, np.ones((6, 4), np.float32))
    arrays = {}
    full_ids, rows, steps = store.export_table_full("t")
    arrays["ids/t"] = full_ids
    arrays["fullrows/t"] = rows
    arrays["steps/t"] = steps
    arrays["dim/t"] = np.int64(4)
    arrays["opt/t"] = np.str_(store.opt_type)
    vdir = tmp_path / "version-7"
    vdir.mkdir(parents=True)
    np.savez(str(vdir / "embeddings-0-of-1.npz"), **arrays)
    restored = make_store("numpy", seed=1)
    assert SparseCheckpointSaver(str(tmp_path)).restore(restored) == 7
    assert_state_equal(restored, store)


def test_compaction_bounds_chain_and_gc_retires_old_chains(tmp_path):
    store = make_store("numpy")
    saver = SparseCheckpointSaver(str(tmp_path), keep_max=2,
                                  compact_every=2)
    ids = np.arange(4, dtype=np.int64)
    version = 0
    for round_ in range(4):
        for _ in range(3):
            version += 1
            store.push_gradients(
                "t", ids, np.ones((4, 4), np.float32)
            )
            saver.save(version, store)
    # every 3rd save compacts (base + 2 deltas per chain); keep_max=2
    chains = sorted(os.listdir(str(tmp_path)))
    assert len(chains) == 2, chains
    for chain in chains:
        names = sorted(os.listdir(str(tmp_path / chain)))
        assert names == [
            "delta-1-embeddings-0-of-1.npz",
            "delta-2-embeddings-0-of-1.npz",
            "embeddings-0-of-1.npz",
        ], names
    restored = make_store("numpy", seed=1)
    assert SparseCheckpointSaver(str(tmp_path)).restore(restored) == 12
    assert_state_equal(restored, store)


# ---------------------------------------------------------------------------
# torn files / crash windows


def _build_chain(tmp_path, deltas=3):
    store = make_store("numpy")
    saver = SparseCheckpointSaver(str(tmp_path), compact_every=10)
    rng = np.random.RandomState(1)
    states = []
    ids = np.arange(10, dtype=np.int64)
    store.push_gradients("t", ids, rng.randn(10, 4).astype(np.float32))
    saver.save(1, store)
    states.append(full_state(store))
    for v in range(2, 2 + deltas):
        store.push_gradients(
            "t", ids[:3], rng.randn(3, 4).astype(np.float32)
        )
        saver.save(v, store)
        states.append(full_state(store))
    return store, states


def test_torn_delta_truncates_chain_to_newest_complete_prefix(tmp_path):
    _, states = _build_chain(tmp_path, deltas=3)
    vdir = tmp_path / "version-1"
    # SIGKILL mid-delta-write: the newest delta is a truncated npz
    path = vdir / "delta-3-embeddings-0-of-1.npz"
    raw = path.read_bytes()
    path.write_bytes(raw[: len(raw) // 2])
    assert SparseCheckpointSaver.latest_version(str(tmp_path)) == 3
    restored = make_store("numpy", seed=1)
    assert SparseCheckpointSaver(str(tmp_path)).restore(restored) == 3
    ids, rows, steps = full_state(restored)
    np.testing.assert_array_equal(rows, states[2][1])
    # a gap poisons everything past it: drop delta-2 entirely, the
    # intact delta-3 copy must NOT be replayed over delta-1 state
    path.write_bytes(raw)
    os.unlink(str(vdir / "delta-2-embeddings-0-of-1.npz"))
    restored2 = make_store("numpy", seed=2)
    assert SparseCheckpointSaver(str(tmp_path)).restore(restored2) == 2
    np.testing.assert_array_equal(full_state(restored2)[1], states[1][1])


def test_tmp_files_are_invisible_to_restore_and_completeness(tmp_path):
    _, states = _build_chain(tmp_path, deltas=1)
    vdir = tmp_path / "version-1"
    # crash mid-write leaves only .tmp siblings — never counted as
    # shards, never opened by restore
    (vdir / "delta-2-embeddings-0-of-1.npz.tmp").write_bytes(b"torn")
    (vdir / "embeddings-9-of-9.npz.tmp").write_bytes(b"torn")
    assert SparseCheckpointSaver.latest_version(str(tmp_path)) == 2
    restored = make_store("numpy", seed=1)
    assert SparseCheckpointSaver(str(tmp_path)).restore(restored) == 2
    np.testing.assert_array_equal(full_state(restored)[1], states[1][1])


def test_drop_table_replays_as_table_tombstone(tmp_path):
    """A table dropped after the chain's base must NOT resurrect at
    restore: every delta records the live table set, so a table absent
    from the newest delta replays as drop_table — the table-level twin
    of the row tombstones."""
    store = make_store("numpy")
    store.create_table("t2", 4, init_scale=0.0, initializer="zeros")
    ids = np.arange(4, dtype=np.int64)
    store.push_gradients("t", ids, np.ones((4, 4), np.float32))
    store.push_gradients("t2", ids, np.ones((4, 4), np.float32))
    saver = SparseCheckpointSaver(str(tmp_path), compact_every=8)
    saver.save(2, store)
    store.drop_table("t2")
    store.push_gradients("t", ids[:2], np.ones((2, 4), np.float32))
    assert saver.save(3, store).kind == "delta"
    restored = make_store("numpy", seed=1)
    assert SparseCheckpointSaver(str(tmp_path)).restore(restored) == 3
    assert restored.table_names() == ["t"]
    assert_state_equal(restored, store)


def test_middle_delta_corruption_latest_version_matches_restore(
    tmp_path,
):
    """latest_version and restore walk the chain the same way: a bad
    MIDDLE delta truncates both at the same point, so a poller that
    waits on latest_version never observes a restore anchored below
    what it promised."""
    _, states = _build_chain(tmp_path, deltas=3)
    path = tmp_path / "version-1" / "delta-2-embeddings-0-of-1.npz"
    raw = path.read_bytes()
    path.write_bytes(raw[: len(raw) // 2])  # delta-3 stays intact
    assert SparseCheckpointSaver.latest_version(str(tmp_path)) == 2
    restored = make_store("numpy", seed=1)
    assert SparseCheckpointSaver(str(tmp_path)).restore(restored) == 2
    np.testing.assert_array_equal(full_state(restored)[1], states[1][1])


def test_concurrent_inline_saves_are_serialized(tmp_path):
    """EDL_CKPT_ASYNC=0 runs saves in the push handlers, and two
    handlers can trip the cadence concurrently — the saver must
    serialize them (unserialized, both write the same delta-<k>
    through the same .tmp path and corrupt the chain)."""
    store = make_store("numpy", opt_type="sgd", lr=0.1)
    ids = np.arange(8, dtype=np.int64)
    store.push_gradients("t", ids, np.ones((8, 4), np.float32))
    saver = SparseCheckpointSaver(str(tmp_path), compact_every=100)
    saver.save(1, store)
    errors = []
    barrier = threading.Barrier(4)

    def hammer(tid):
        try:
            barrier.wait(5)
            for i in range(10):
                store.push_gradients(
                    "t", ids[:2], np.ones((2, 4), np.float32)
                )
                saver.save(2 + tid * 10 + i, store)
        except Exception as e:  # pragma: no cover
            errors.append(e)

    threads = [
        threading.Thread(target=hammer, args=(t,)) for t in range(4)
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join(30)
    assert not errors, errors
    # quiesced final save: the chain must be intact (no torn/dup
    # delta indices) and restore to exactly the live state
    saver.save(999, store)
    restored = make_store("numpy", opt_type="sgd", lr=0.1, seed=1)
    assert SparseCheckpointSaver(str(tmp_path)).restore(restored) == 999
    assert_state_equal(restored, store)


def test_stale_delta_from_old_generation_never_replays(tmp_path):
    """A full base saved into a version dir that still holds another
    generation's delta files (colliding version across process lives)
    must NOT have those deltas replayed over it — the chain token
    pins every delta to the base that minted it."""
    old = make_store("numpy")
    ids = np.arange(5, dtype=np.int64)
    old.push_gradients("t", ids, np.ones((5, 4), np.float32))
    saver1 = SparseCheckpointSaver(str(tmp_path), compact_every=8)
    saver1.save(3, old)
    old.push_gradients("t", ids, np.full((5, 4), 9.0, np.float32))
    assert saver1.save(4, old).kind == "delta"  # gen-1 delta lingers

    new = make_store("numpy", seed=1)
    new.push_gradients("t", ids, np.full((5, 4), 0.5, np.float32))
    saver2 = SparseCheckpointSaver(str(tmp_path), compact_every=8)
    # same version dir, new generation: the gen-1 delta-1 file is
    # still on disk beside the fresh base
    saver2.save(3, new, force_full=True)
    assert os.path.exists(
        str(tmp_path / "version-3" / "delta-1-embeddings-0-of-1.npz")
    )
    assert SparseCheckpointSaver.latest_version(str(tmp_path)) == 3
    restored = make_store("numpy", seed=2)
    assert SparseCheckpointSaver(str(tmp_path)).restore(restored) == 3
    assert_state_equal(restored, new)
    # a delta of the NEW generation appends and replays normally
    new.push_gradients("t", ids[:2], np.ones((2, 4), np.float32))
    assert saver2.save(4, new).kind == "delta"
    restored2 = make_store("numpy", seed=3)
    assert SparseCheckpointSaver(str(tmp_path)).restore(restored2) == 4
    assert_state_equal(restored2, new)


def test_crash_mid_compaction_falls_back_to_previous_chain(tmp_path):
    store, states = _build_chain(tmp_path, deltas=2)
    # a compaction that died mid-base-write: newer version dir whose
    # base shard is truncated
    vdir = tmp_path / "version-9"
    vdir.mkdir()
    good = tmp_path / "version-1" / "embeddings-0-of-1.npz"
    raw = good.read_bytes()
    (vdir / "embeddings-0-of-1.npz").write_bytes(raw[: len(raw) // 3])
    restored = make_store("numpy", seed=1)
    assert SparseCheckpointSaver(str(tmp_path)).restore(restored) == 3
    np.testing.assert_array_equal(full_state(restored)[1], states[2][1])


# ---------------------------------------------------------------------------
# AsyncCheckpointer


def test_async_checkpointer_coalesces_bursts():
    gate = threading.Event()
    saved = []

    def slow_save(version, kind):
        gate.wait(5.0)
        saved.append((version, kind))

    ckpt = AsyncCheckpointer(slow_save)
    assert ckpt.request(1, "sparse")
    # wait for the thread to take request 1 into flight, then burst:
    # 2..5 arrive while 1 is saving — they coalesce to ONE trailing
    # save at the newest version
    deadline = time.time() + 5
    while not ckpt._in_flight and time.time() < deadline:
        time.sleep(0.01)
    assert ckpt._in_flight
    for v in range(2, 6):
        assert ckpt.request(v, "sparse")
    gate.set()
    assert ckpt.drain(timeout=10)
    assert saved == [(1, "sparse"), (5, "sparse")]
    assert ckpt.coalesced == 3
    ckpt.stop()
    assert not ckpt.request(6, "sparse"), "request after stop"


def test_async_checkpointer_survives_save_failure():
    calls = []

    def flaky(version, kind):
        calls.append(version)
        if version == 1:
            raise RuntimeError("disk full")

    ckpt = AsyncCheckpointer(flaky)
    ckpt.request(1)
    assert ckpt.drain(timeout=10)
    ckpt.request(2)
    assert ckpt.drain(timeout=10)
    assert calls == [1, 2]
    ckpt.stop()


# ---------------------------------------------------------------------------
# servicer integration: off-RPC saves + boundary anchoring


def make_servicer(tmp_path, monkeypatch, ckpt_async, checkpoint_steps=0,
                  restored_version=None, compact_every=None):
    from elasticdl_tpu.ps.servicer import PserverServicer

    monkeypatch.setenv("EDL_CKPT_ASYNC", "1" if ckpt_async else "0")
    if compact_every is not None:
        monkeypatch.setenv("EDL_CKPT_COMPACT_EVERY", str(compact_every))
    store = make_store("numpy", opt_type="sgd", lr=1.0)
    saver = SparseCheckpointSaver(str(tmp_path))
    servicer = PserverServicer(
        store, use_async=True, checkpoint_saver=saver,
        checkpoint_steps=checkpoint_steps,
        restored_version=restored_version,
    )
    return servicer, store, saver


def push(servicer, ids, value=1.0):
    from elasticdl_tpu.common.tensor_utils import serialize_indexed_slices
    from elasticdl_tpu.proto import elasticdl_tpu_pb2 as pb

    ids = np.asarray(ids, np.int64)
    request = pb.PushGradientsRequest()
    serialize_indexed_slices(
        np.full((ids.size, 4), value, np.float32), ids,
        request.gradients.embedding_tables["t"],
    )
    return servicer.push_gradients(request)


def test_push_path_only_enqueues_and_save_lands_async(
    tmp_path, monkeypatch,
):
    servicer, store, _ = make_servicer(
        tmp_path, monkeypatch, ckpt_async=True, checkpoint_steps=1,
    )
    assert push(servicer, [0, 1]).accepted
    deadline = time.time() + 20
    while time.time() < deadline:
        if SparseCheckpointSaver.latest_version(str(tmp_path)) == 1:
            break
        time.sleep(0.05)
    assert SparseCheckpointSaver.latest_version(str(tmp_path)) == 1
    assert servicer._ckpt_async is not None
    # the saved state restores what the push applied
    restored = make_store("numpy", opt_type="sgd", lr=1.0, seed=1)
    servicer.finish_checkpoints()
    SparseCheckpointSaver(str(tmp_path)).restore(restored)
    np.testing.assert_array_equal(
        restored.lookup("t", np.array([0], np.int64)),
        store.lookup("t", np.array([0], np.int64)),
    )


def test_graceful_stop_final_full_save_supersedes_pending(
    tmp_path, monkeypatch,
):
    servicer, store, _ = make_servicer(
        tmp_path, monkeypatch, ckpt_async=True, checkpoint_steps=1,
        compact_every=8,
    )
    for i in range(4):
        assert push(servicer, [i]).accepted
    servicer.graceful_stop()
    # the final save is synchronous, FULL, and at the final version —
    # whatever the async thread had pending is superseded
    restored = make_store("numpy", opt_type="sgd", lr=1.0, seed=1)
    assert SparseCheckpointSaver(
        str(tmp_path)
    ).restore(restored) == store.version
    assert_state_equal(restored, store)
    vdir = str(tmp_path / ("version-%d" % store.version))
    assert os.path.exists(
        os.path.join(vdir, "embeddings-0-of-1.npz")
    ), "final save must be a full base"


def test_inline_mode_saves_synchronously(tmp_path, monkeypatch):
    servicer, store, _ = make_servicer(
        tmp_path, monkeypatch, ckpt_async=False, checkpoint_steps=2,
    )
    assert servicer._ckpt_async is None
    push(servicer, [0])
    assert SparseCheckpointSaver.latest_version(str(tmp_path)) is None
    push(servicer, [1])
    # inline: the save completed before the push RPC returned
    assert SparseCheckpointSaver.latest_version(str(tmp_path)) == 2


# maybe_stream_checkpoint boundary anchoring (ps/servicer.py — the
# fresh-boot vs restored-boot `_stream_ckpt_boundary` paths)


def test_stream_boundary_fresh_boot_saves_from_first_crossing(
    tmp_path, monkeypatch,
):
    servicer, store, _ = make_servicer(
        tmp_path, monkeypatch, ckpt_async=False,
    )
    push(servicer, [0])
    # below the first boundary: anchors at 0, nothing saved
    assert not servicer.maybe_stream_checkpoint(50, 100)
    assert servicer._stream_ckpt_boundary == 0
    # first crossing saves; repeated watermarks inside the same
    # boundary do not
    assert servicer.maybe_stream_checkpoint(250, 100)
    assert servicer._stream_ckpt_boundary == 2
    assert not servicer.maybe_stream_checkpoint(260, 100)
    assert servicer.maybe_stream_checkpoint(300, 100)
    assert servicer._stream_ckpt_boundary == 3
    assert SparseCheckpointSaver.latest_version(
        str(tmp_path)
    ) == store.version


def test_stream_boundary_restored_boot_anchors_at_first_watermark(
    tmp_path, monkeypatch,
):
    servicer, store, _ = make_servicer(
        tmp_path, monkeypatch, ckpt_async=False, restored_version=5,
    )
    push(servicer, [0])
    # a restored PS anchors at its first observed watermark WITHOUT
    # saving: the predecessor already covered those boundaries
    assert not servicer.maybe_stream_checkpoint(250, 100)
    assert servicer._stream_ckpt_boundary == 2
    assert SparseCheckpointSaver.latest_version(str(tmp_path)) is None
    assert not servicer.maybe_stream_checkpoint(299, 100)
    # the next boundary after the anchor saves
    assert servicer.maybe_stream_checkpoint(300, 100)
    assert SparseCheckpointSaver.latest_version(
        str(tmp_path)
    ) == store.version


def test_stream_boundary_guards(tmp_path, monkeypatch):
    servicer, _, _ = make_servicer(
        tmp_path, monkeypatch, ckpt_async=False,
    )
    assert not servicer.maybe_stream_checkpoint(0, 100)   # no watermark
    assert not servicer.maybe_stream_checkpoint(100, 0)   # cadence off
    from elasticdl_tpu.ps.servicer import PserverServicer

    saverless = PserverServicer(
        make_store("numpy", opt_type="sgd", lr=1.0), use_async=True,
    )
    assert not saverless.maybe_stream_checkpoint(100, 10)  # no saver
