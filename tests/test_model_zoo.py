"""Model-zoo breadth: every family inits, steps, and learns.

Mirrors the reference's model-zoo CI coverage (model_zoo/ trained per
job type in .travis.yml) at unit scale: synthetic separable datasets,
a few dozen steps, loss must drop (and AUC/accuracy clear a bar where
the fixture plants real signal).
"""

import numpy as np
import pytest

from elasticdl_tpu.data.example import encode_example
from elasticdl_tpu.data.recordio import write_records
from elasticdl_tpu.train.local_executor import LocalExecutor
from tests.test_utils import create_ctr_recordio, create_mnist_recordio


def _make_dirs(tmp_path, maker, **kwargs):
    train_dir = tmp_path / "train"
    valid_dir = tmp_path / "valid"
    train_dir.mkdir()
    valid_dir.mkdir()
    maker(str(train_dir / "f0.rec"), seed=0, **kwargs)
    maker(str(valid_dir / "f0.rec"), seed=1, **kwargs)
    return str(train_dir), str(valid_dir)


@pytest.mark.parametrize(
    "module",
    [
        "elasticdl_tpu.models.wide_deep",
        "elasticdl_tpu.models.dcn",
        "elasticdl_tpu.models.xdeepfm",
    ],
)
def test_ctr_family_learns(tmp_path, module):
    # enough rows that per-id weights see ~20 examples each — these
    # models memorize per-id embeddings, so few-shot ids overfit
    train_dir, valid_dir = _make_dirs(
        tmp_path, create_ctr_recordio, num_records=2048
    )
    executor = LocalExecutor(
        module,
        training_data=train_dir,
        validation_data=valid_dir,
        minibatch_size=64,
        num_epochs=2,
    )
    losses = executor.train()
    assert losses[-1] < losses[0]
    summary = executor.evaluate()
    assert summary["auc"] > 0.7  # planted linear signal is learnable


def create_census_recordio(path, num_records=256, seed=0):
    """Census-shaped records with a planted rule: high hours + married
    + gov job -> label 1."""
    from elasticdl_tpu.models.census_wide_deep import (
        MARITAL_STATUS_VOCABULARY,
        WORK_CLASS_VOCABULARY,
    )

    rng = np.random.RandomState(seed)
    payloads = []
    educations = ["HS", "BA", "MS", "PhD"]
    occupations = ["eng", "sales", "admin", "exec"]
    for _ in range(num_records):
        age = rng.uniform(17, 80)
        hours = rng.uniform(10, 70)
        work = WORK_CLASS_VOCABULARY[
            rng.randint(len(WORK_CLASS_VOCABULARY))
        ]
        marital = MARITAL_STATUS_VOCABULARY[
            rng.randint(len(MARITAL_STATUS_VOCABULARY))
        ]
        score = (
            (hours - 40) / 15.0
            + (1.5 if marital == "Married-civ-spouse" else -0.5)
            + (1.0 if "gov" in work.lower() else 0.0)
        )
        label = 1 if score + rng.randn() * 0.3 > 0 else 0
        payloads.append(
            encode_example(
                {
                    "age": np.float32(age),
                    "hours_per_week": np.float32(hours),
                    "work_class": np.array(work),
                    "marital_status": np.array(marital),
                    "education": np.array(
                        educations[rng.randint(len(educations))]
                    ),
                    "occupation": np.array(
                        occupations[rng.randint(len(occupations))]
                    ),
                    "label": np.int64(label),
                }
            )
        )
    write_records(path, payloads)
    return path


def test_census_wide_deep_learns(tmp_path):
    train_dir, valid_dir = _make_dirs(
        tmp_path, create_census_recordio, num_records=1024
    )
    executor = LocalExecutor(
        "elasticdl_tpu.models.census_wide_deep",
        training_data=train_dir,
        validation_data=valid_dir,
        minibatch_size=64,
        num_epochs=10,
    )
    losses = executor.train()
    assert losses[-1] < losses[0]
    summary = executor.evaluate()
    assert summary["auc"] > 0.75


def create_cifar_recordio(path, num_records=128, seed=0, image_size=16):
    """Tiny separable RGB images: label = dominant color channel +
    bright-half bit."""
    rng = np.random.RandomState(seed)
    payloads = []
    for _ in range(num_records):
        label = rng.randint(0, 6)
        channel, half = label % 3, label // 3
        image = rng.rand(image_size, image_size, 3).astype(np.float32) * 40
        rows = slice(0, image_size // 2) if half == 0 else slice(
            image_size // 2, image_size
        )
        image[rows, :, channel] += 180
        payloads.append(
            encode_example(
                {
                    "image": image.astype(np.uint8),
                    "label": np.int64(label),
                }
            )
        )
    write_records(path, payloads)
    return path


@pytest.mark.parametrize(
    "module",
    ["elasticdl_tpu.models.cifar10", "elasticdl_tpu.models.mobilenet"],
)
def test_vision_family_learns(tmp_path, module):
    train_dir, valid_dir = _make_dirs(
        tmp_path, create_cifar_recordio, num_records=192
    )
    executor = LocalExecutor(
        module,
        training_data=train_dir,
        validation_data=valid_dir,
        minibatch_size=32,
        num_epochs=3,
    )
    losses = executor.train()
    assert losses[-1] < losses[0]


def create_iris_csv(path, num_records=120, seed=0):
    rng = np.random.RandomState(seed)
    with open(path, "w") as f:
        for _ in range(num_records):
            label = rng.randint(0, 3)
            base = np.array([4.5, 3.0, 1.5, 0.2]) + label * np.array(
                [1.0, 0.2, 1.8, 0.9]
            )
            row = base + rng.randn(4) * 0.2
            f.write(
                ",".join("%.3f" % v for v in row) + ",%d\n" % label
            )
    return path


def test_iris_dnn_learns(tmp_path):
    train_dir = tmp_path / "train"
    valid_dir = tmp_path / "valid"
    train_dir.mkdir()
    valid_dir.mkdir()
    create_iris_csv(str(train_dir / "iris.csv"), seed=0)
    create_iris_csv(str(valid_dir / "iris.csv"), seed=1)
    executor = LocalExecutor(
        "elasticdl_tpu.models.iris_dnn",
        training_data=str(train_dir),
        validation_data=str(valid_dir),
        minibatch_size=16,
        num_epochs=10,
    )
    losses = executor.train()
    assert losses[-1] < losses[0]
    summary = executor.evaluate()
    assert summary["accuracy"] > 0.85


def test_lr_scheduler_rewrites_injected_lr(tmp_path):
    """The census module's staged LR schedule must actually land in the
    optimizer state (host-set, no recompile)."""
    import jax.numpy as jnp

    from elasticdl_tpu.train.callbacks import LearningRateScheduler
    from elasticdl_tpu.train.optimizers import (
        create_host_schedulable_optimizer,
        set_learning_rate,
    )

    tx = create_host_schedulable_optimizer("Adam", learning_rate=0.5)
    params = {"w": jnp.ones((3,))}
    opt_state = tx.init(params)
    new_state = set_learning_rate(opt_state, 0.125)
    assert new_state is not None

    grads = {"w": jnp.ones((3,))}
    _, after = tx.update(grads, new_state, params)
    # hyperparams carry the host-set LR through the update
    hp_state = after if hasattr(after, "hyperparams") else after[0]
    assert float(hp_state.hyperparams["learning_rate"]) == 0.125

    class FakeWorker:
        pass

    worker = FakeWorker()
    from elasticdl_tpu.train.train_state import TrainState

    worker.state = TrainState(
        step=jnp.zeros((), jnp.int32),
        params=params,
        model_state={},
        opt_state=opt_state,
    )
    cb = LearningRateScheduler(lambda step: 0.25 if step > 10 else 0.5)
    cb.set_worker(worker)
    cb.on_batch_end(20, 0.0)
    hp = worker.state.opt_state
    hp = hp if hasattr(hp, "hyperparams") else hp[0]
    assert float(hp.hyperparams["learning_rate"]) == 0.25


def test_heart_learns(tmp_path):
    """heart_functional_api parity (reference model_zoo/
    heart_functional_api): bucketized age + hashed thal embedding."""
    from elasticdl_tpu.data.gen import gen_heart_recordio

    train_dir = tmp_path / "train"
    valid_dir = tmp_path / "valid"
    train_dir.mkdir()
    valid_dir.mkdir()
    gen_heart_recordio(str(train_dir), num_records=1024, seed=0)
    gen_heart_recordio(str(valid_dir), num_records=256, seed=1)
    executor = LocalExecutor(
        "elasticdl_tpu.models.heart",
        training_data=str(train_dir),
        validation_data=str(valid_dir),
        minibatch_size=64,
        num_epochs=10,
    )
    losses = executor.train()
    assert losses[-1] < losses[0]
    summary = executor.evaluate()
    assert summary["auc"] > 0.7


def test_census_dnn_learns(tmp_path):
    """census_dnn_model parity (reference model_zoo/census_dnn_model):
    4 numeric + 8 hashed-embedded categorical columns, 16-16-1 tower."""
    from elasticdl_tpu.data.gen import gen_census_recordio

    train_dir = tmp_path / "train"
    valid_dir = tmp_path / "valid"
    train_dir.mkdir()
    valid_dir.mkdir()
    gen_census_recordio(str(train_dir), num_records=2048, seed=0)
    gen_census_recordio(str(valid_dir), num_records=512, seed=1)
    executor = LocalExecutor(
        "elasticdl_tpu.models.census_dnn",
        training_data=str(train_dir),
        validation_data=str(valid_dir),
        minibatch_size=64,
        num_epochs=6,
    )
    losses = executor.train()
    assert losses[-1] < losses[0]
    summary = executor.evaluate()
    assert summary["auc"] > 0.75


def test_census_sqlflow_wide_deep_learns(tmp_path):
    """census_model_sqlflow parity: the declarative transform graph
    (three Concat id groups, wide dim-1 + deep dim-8 embeddings)."""
    from elasticdl_tpu.data.gen import gen_census_recordio
    from elasticdl_tpu.models import census_sqlflow_wide_deep as m

    # group extents match the reference's id-offset math
    wide_cols, deep_cols = m.build_columns()
    assert len(wide_cols) == 2 and len(deep_cols) == 3

    train_dir = tmp_path / "train"
    valid_dir = tmp_path / "valid"
    train_dir.mkdir()
    valid_dir.mkdir()
    gen_census_recordio(str(train_dir), num_records=2048, seed=0)
    gen_census_recordio(str(valid_dir), num_records=512, seed=1)
    executor = LocalExecutor(
        "elasticdl_tpu.models.census_sqlflow_wide_deep",
        training_data=str(train_dir),
        validation_data=str(valid_dir),
        minibatch_size=64,
        num_epochs=6,
    )
    losses = executor.train()
    assert losses[-1] < losses[0]
    summary = executor.evaluate()
    assert summary["auc"] > 0.75


def test_model_def_and_model_params(tmp_path):
    """Reference parity: --model_def picks the module (and optionally
    the factory) inside a model-zoo DIRECTORY; --model_params binds
    k=v;k=v kwargs onto custom_model (model_utils.py:79-94,139-198).
    Round 4 found the flags were parsed but silently ignored."""
    from elasticdl_tpu.models.registry import get_model_spec

    zoo = tmp_path / "zoo" / "toy"
    zoo.mkdir(parents=True)
    (zoo / "toy_model.py").write_text(
        "import flax.linen as nn\n"
        "import optax\n"
        "class _M(nn.Module):\n"
        "    hidden: int = 4\n"
        "    @nn.compact\n"
        "    def __call__(self, x, training=False):\n"
        "        return nn.Dense(self.hidden)(x)\n"
        "def custom_model(hidden=4):\n"
        "    return _M(hidden=hidden)\n"
        "def make_wide(hidden=4):\n"
        "    return _M(hidden=hidden * 2)\n"
        "def loss(labels, predictions):\n"
        "    return ((predictions - labels) ** 2).mean(axis=-1)\n"
        "def optimizer():\n"
        "    return optax.sgd(0.1)\n"
        "def dataset_fn(dataset, mode, metadata):\n"
        "    return dataset\n"
    )

    # module path alone -> default custom_model factory
    spec = get_model_spec(str(tmp_path / "zoo"), model_def="toy.toy_model")
    assert spec.custom_model().hidden == 4

    # trailing segment names the factory; model_params binds kwargs
    spec = get_model_spec(
        str(tmp_path / "zoo"),
        model_def="toy.toy_model.make_wide",
        model_params="hidden=8",
    )
    assert spec.custom_model().hidden == 16

    # model_params works without model_def (dotted module path)
    spec = get_model_spec(
        str(zoo / "toy_model.py"), model_params="hidden=3"
    )
    assert spec.custom_model().hidden == 3

    with pytest.raises(ValueError, match="directory"):
        get_model_spec(str(zoo / "toy_model.py"), model_def="x.y")
    with pytest.raises(ValueError, match="resolves to neither"):
        get_model_spec(str(tmp_path / "zoo"), model_def="toy.nope")


def test_model_def_single_segment_stays_inside_zoo(tmp_path):
    """A one-segment --model_def with no matching file must error inside
    the zoo, not probe '<zoo>.py' outside it."""
    from elasticdl_tpu.models.registry import get_model_spec

    zoo = tmp_path / "models"
    zoo.mkdir()
    # adversarial sibling OUTSIDE the zoo that a naive join would import
    (tmp_path / "models.py").write_text("custom_model = None\n")
    with pytest.raises(ValueError, match="no module file"):
        get_model_spec(str(zoo), model_def="custom_model")


def test_symbol_overrides(tmp_path):
    """Reference parity: every contract part is addressable by name
    (--loss/--optimizer/... , model_utils.py:139-150)."""
    from elasticdl_tpu.models.registry import get_model_spec

    mod = tmp_path / "named.py"
    mod.write_text(
        "import flax.linen as nn\n"
        "import optax\n"
        "def custom_model():\n"
        "    return nn.Dense(2)\n"
        "def loss(labels, predictions):\n"
        "    return ((predictions - labels) ** 2).mean(axis=-1)\n"
        "def my_loss(labels, predictions):\n"
        "    return ((predictions - labels) ** 2).mean(axis=-1) * 2\n"
        "def optimizer():\n"
        "    return optax.sgd(0.1)\n"
        "def dataset_fn(dataset, mode, metadata):\n"
        "    return dataset\n"
    )
    spec = get_model_spec(
        str(mod), symbol_overrides={"loss": "my_loss"}
    )
    assert spec.loss.__name__ == "my_loss"

    # an explicitly named symbol that is missing errors even for
    # otherwise-optional parts
    with pytest.raises(ValueError, match="my_callbacks"):
        get_model_spec(
            str(mod), symbol_overrides={"callbacks": "my_callbacks"}
        )
