"""Dense full-TrainState checkpoints: roundtrip, GC, cross-mesh resume.

The reference drops optimizer slot state from checkpoints
(ps/parameters.py:194-199); these tests pin that the rebuild does not,
and that resume re-shards onto a different mesh topology.
"""

import jax
import numpy as np

from elasticdl_tpu.models import mnist
from elasticdl_tpu.parallel.mesh import MeshConfig, build_mesh
from elasticdl_tpu.parallel.spmd_trainer import SpmdTrainer
from elasticdl_tpu.train.checkpoint import DenseCheckpointManager
from elasticdl_tpu.worker.trainer import JaxTrainer


def _batch(n=8, seed=0):
    rng = np.random.RandomState(seed)
    return {
        "features": rng.rand(n, 28, 28).astype(np.float32),
        "labels": rng.randint(0, 10, size=n).astype(np.int32),
        "_mask": np.ones((n,), np.float32),
    }


def _trainer():
    return JaxTrainer(
        model=mnist.custom_model(),
        loss_fn=mnist.loss,
        optimizer=mnist.optimizer(),
        seed=0,
    )


def _trees_equal(a, b):
    flat_a = jax.tree_util.tree_leaves(a)
    flat_b = jax.tree_util.tree_leaves(b)
    assert len(flat_a) == len(flat_b)
    for x, y in zip(flat_a, flat_b):
        np.testing.assert_allclose(np.asarray(x), np.asarray(y))


def test_roundtrip_includes_optimizer_state(tmp_path):
    trainer = _trainer()
    batch = _batch()
    state = None
    for _ in range(3):
        state, _ = trainer.train_step(state, batch)
    mgr = DenseCheckpointManager(str(tmp_path / "ckpt"), keep_max=3)
    mgr.save(3, state)

    fresh_state, _ = _trainer().train_step(None, batch)
    restored = mgr.restore(template=fresh_state)
    mgr.close()
    assert int(restored.step) == 3
    _trees_equal(restored.params, state.params)
    # Adam slot state (m, v) must survive — the reference loses it.
    _trees_equal(restored.opt_state, state.opt_state)


def test_keep_max_gc(tmp_path):
    trainer = _trainer()
    batch = _batch()
    state, _ = trainer.train_step(None, batch)
    mgr = DenseCheckpointManager(str(tmp_path / "ckpt"), keep_max=2)
    for v in (1, 2, 3, 4, 5):
        mgr.save(v, state)
    assert mgr.latest_version() == 5
    kept = [
        d
        for d in (tmp_path / "ckpt").iterdir()
        if d.is_dir() and d.name.isdigit()
    ]
    mgr.close()
    assert sorted(int(d.name) for d in kept) == [4, 5]


def test_resume_onto_different_mesh(tmp_path):
    batch = _batch(n=16)

    # Uninterrupted 4-step run on a pure-dp mesh = the oracle.
    mesh_a = build_mesh(MeshConfig(dp=8))
    trainer_a = SpmdTrainer(
        model=mnist.custom_model(),
        loss_fn=mnist.loss,
        optimizer=mnist.optimizer(),
        mesh=mesh_a,
        seed=0,
    )
    state = trainer_a.create_state(batch["features"])
    oracle_losses = []
    for _ in range(4):
        state, loss = trainer_a.train_step(state, batch)
        oracle_losses.append(float(loss))

    # Interrupted run: 2 steps on mesh A, checkpoint, resume on a
    # dp2 x fsdp4 mesh (params/slots ZeRO-sharded differently).
    trainer_b = SpmdTrainer(
        model=mnist.custom_model(),
        loss_fn=mnist.loss,
        optimizer=mnist.optimizer(),
        mesh=mesh_a,
        seed=0,
    )
    state_b = trainer_b.create_state(batch["features"])
    for _ in range(2):
        state_b, _ = trainer_b.train_step(state_b, batch)
    mgr = DenseCheckpointManager(str(tmp_path / "ckpt"), keep_max=3)
    mgr.save(2, state_b)

    mesh_c = build_mesh(MeshConfig(dp=2, fsdp=4))
    trainer_c = SpmdTrainer(
        model=mnist.custom_model(),
        loss_fn=mnist.loss,
        optimizer=mnist.optimizer(),
        mesh=mesh_c,
        seed=1,  # different init — must be overwritten by the restore
    )
    template = trainer_c.create_state(batch["features"])
    restored = mgr.restore(
        template=template, shardings=trainer_c.state_shardings
    )
    mgr.close()
    assert int(restored.step) == 2
    resumed_losses = []
    for _ in range(2):
        restored, loss = trainer_c.train_step(restored, batch)
        resumed_losses.append(float(loss))
    np.testing.assert_allclose(
        resumed_losses, oracle_losses[2:], atol=1e-5, rtol=1e-5
    )


def test_spmd_checkpoint_restores_on_single_chip(tmp_path):
    """A checkpoint saved from an 8-device SPMD mesh must restore on a
    plain single-chip JaxTrainer (shardings=None): restore pins leaves to
    the local default device instead of replaying the save-time layout."""
    batch = _batch(16)
    mesh = build_mesh(MeshConfig(dp=2, fsdp=4))
    spmd = SpmdTrainer(
        model=mnist.custom_model(),
        loss_fn=mnist.loss,
        optimizer=mnist.optimizer(),
        mesh=mesh,
        seed=0,
    )
    state = spmd.create_state(batch["features"])
    state, _ = spmd.train_step(state, batch)
    mgr = DenseCheckpointManager(str(tmp_path / "ckpt"), keep_max=1)
    mgr.save(1, state)
    mgr.close()

    single = JaxTrainer(
        model=mnist.custom_model(),
        loss_fn=mnist.loss,
        optimizer=mnist.optimizer(),
        seed=1,
    )
    template = single.abstract_state(batch["features"])
    mgr = DenseCheckpointManager(str(tmp_path / "ckpt"), keep_max=1)
    restored = mgr.restore(template=template, shardings=None)
    mgr.close()
    assert int(restored.step) == 1
    # restored state drives the single-chip step
    new_state, loss = single.train_step(restored, batch)
    assert np.isfinite(float(loss))
    assert int(new_state.step) == 2


def test_async_save_commits_and_restores(tmp_path):
    """async_save=True: save returns before the write is durable, the
    next wait/save joins it, latest_version only reports COMMITTED
    steps, and restore round-trips exactly."""
    import jax
    import numpy as np

    from elasticdl_tpu.models import mnist
    from elasticdl_tpu.train.checkpoint import DenseCheckpointManager
    from elasticdl_tpu.train.optimizers import create_optimizer
    from elasticdl_tpu.train.train_state import create_train_state

    model = mnist.custom_model()
    tx = create_optimizer("Adam", learning_rate=0.01)
    sample = np.zeros((2, 8, 8), np.float32)
    state = create_train_state(model, tx, jax.random.PRNGKey(0), sample)

    mgr = DenseCheckpointManager(str(tmp_path / "ckpt"), async_save=True)
    try:
        mgr.save(1, state)
        mgr.save(2, state)  # joins save 1 internally
        mgr.wait_until_finished()
        assert mgr.latest_version() == 2
        restored = mgr.restore(template=state)
        for a, b in zip(
            jax.tree_util.tree_leaves(state),
            jax.tree_util.tree_leaves(restored),
        ):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    finally:
        mgr.close()
