"""Dense data plane: collective helpers, reduction plan, and the
bit-exactness contract of the SPMD trainer against the single-chip
trainer at mesh=1."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from elasticdl_tpu.parallel.collectives import (
    CollectiveBytes,
    mesh_all_gather,
    mesh_pmean,
    mesh_psum,
    mesh_reduce_scatter,
    track_collective_bytes,
)
from elasticdl_tpu.parallel.dense_plane import plan_dense_plane
from elasticdl_tpu.parallel.mesh import MeshConfig, build_mesh
from elasticdl_tpu.parallel.sharding import ShardingRules

from jax.sharding import PartitionSpec as P

from elasticdl_tpu.common import jax_compat


def test_mesh_psum_values_and_grad_inside_shard_map():
    """mesh_psum reduces like lax.psum AND its vjp taken INSIDE the
    manual region is correct — the transpose of an all-reduce whose
    cotangent is replicated is the identity, not another psum (bare
    lax.psum gets this wrong by a factor of the axis size on the
    pinned jax; see parallel/collectives.py)."""
    mesh = build_mesh(MeshConfig(dp=1, tp=4, devices=jax.devices()[:4]))

    def body(w, x):
        # w varies over tp (a different shard everywhere); the stage
        # reduces the partial products and the loss differentiates
        # through the reduction in-body
        def loss(w_, x_):
            return jnp.sum(mesh_psum(w_ * x_, "tp") ** 2)

        val, grad = jax.value_and_grad(loss)(w, x)
        return val, grad

    wrapped = jax_compat.shard_map(
        body,
        mesh=mesh,
        in_specs=(P("tp"), P()),
        out_specs=(P(), P("tp")),
    )
    w = jnp.arange(4, dtype=jnp.float32) + 1.0  # shards: 1,2,3,4
    x = jnp.ones((), jnp.float32)

    def reference(w_, x_):
        return jnp.sum(jnp.sum(w_ * x_) ** 2)

    val, grad = jax.jit(wrapped)(w, x)
    ref_val, ref_grad = jax.value_and_grad(reference)(w, x)
    np.testing.assert_allclose(np.asarray(val), np.asarray(ref_val))
    np.testing.assert_allclose(np.asarray(grad), np.asarray(ref_grad))


def test_mesh_pmean_and_gather_scatter_roundtrip():
    mesh = build_mesh(MeshConfig(dp=4, devices=jax.devices()[:4]))

    def body(x):
        mean = mesh_pmean(x, "dp", mesh=mesh)
        # a full-size per-device value (like a gradient): each device
        # holds a different scaling of the same vector
        scale = (jax.lax.axis_index("dp") + 1).astype(jnp.float32)
        v = scale * jnp.arange(8, dtype=jnp.float32)
        scattered = mesh_reduce_scatter(v, "dp")
        gathered = mesh_all_gather(scattered, "dp")
        return mean, gathered

    wrapped = jax_compat.shard_map(
        body, mesh=mesh, in_specs=(P("dp"),), out_specs=(P(), P(None))
    )
    x = jnp.arange(8, dtype=jnp.float32).reshape(4, 2)
    mean, gathered = jax.jit(wrapped)(x)
    np.testing.assert_allclose(
        np.asarray(mean), np.asarray(x.sum(0, keepdims=True) / 4.0)
    )
    # reduce-scatter sums the 4 scalings (1+2+3+4 = 10) and leaves each
    # device its slice; the all-gather re-materializes the full sum
    np.testing.assert_allclose(
        np.asarray(gathered), 10.0 * np.arange(8, dtype=np.float32)
    )


def test_track_collective_bytes_ring_costs():
    mesh = build_mesh(MeshConfig(dp=4, devices=jax.devices()[:4]))
    x = jnp.zeros((128,), jnp.float32)  # 512 payload bytes

    with track_collective_bytes() as acc:

        def body(v):
            return (
                mesh_psum(v, "dp", mesh=mesh),
                mesh_reduce_scatter(v, "dp", mesh=mesh),
                mesh_all_gather(v, "dp", mesh=mesh),
            )

        jax.eval_shape(
            jax_compat.shard_map(
                body, mesh=mesh,
                in_specs=(P("dp"),),
                out_specs=(P(), P("dp"), P("dp")),
            ),
            x,
        )
    # per-shard payload is 32 floats = 128 bytes; ring cost B(n-1)/n
    ring = 128 * 3 // 4
    assert acc.all_reduce == 2 * ring
    assert acc.reduce_scatter == ring
    assert acc.all_gather == ring
    assert acc.total == 4 * ring
    assert acc.calls == 3


def test_track_collective_bytes_nested_and_size1_axis():
    mesh = build_mesh(MeshConfig(dp=1, devices=jax.devices()[:1]))
    with track_collective_bytes() as outer:
        with track_collective_bytes() as inner:
            # size-1 axis: no traffic, no call recorded
            mesh_psum(jnp.ones((4,)), "dp", mesh=mesh)
        assert inner.total == 0 and inner.calls == 0
    assert outer.total == 0


def test_plan_reduce_scatter_vs_psum_fallback():
    """fsdp-sharded params reduce-scatter; small/replicated params fall
    back to a psum; tp-sharded params reduce only over the data
    extent."""
    mesh = build_mesh(MeshConfig(dp=2, fsdp=2, tp=2, devices=jax.devices()[:8]))
    params = {
        "big": jax.ShapeDtypeStruct((1024, 64), jnp.float32),
        "tiny": jax.ShapeDtypeStruct((8,), jnp.float32),
        "tpw": jax.ShapeDtypeStruct((64, 64), jnp.float32),
    }
    rules = ShardingRules(
        rules=[
            (r"^big$", P("fsdp", None)),
            (r"^tpw$", P(None, "tp")),
        ],
        default_spec=P(),
    )
    plan = plan_dense_plane(params, mesh, rules)
    modes = {p.path: p for p in plan.params}
    assert modes["big"].mode == "reduce_scatter"
    assert modes["tiny"].mode == "psum"
    assert modes["tpw"].mode == "psum"
    big, tiny, tpw = modes["big"], modes["tiny"], modes["tpw"]
    # big: RS over fsdp=2 then all-reduce of the half over dp=2
    assert big.grad_bytes_per_step == (
        big.nbytes // 2 + 2 * ((big.nbytes // 2) // 2)
    )
    # tiny: plain all-reduce over dp*fsdp=4
    assert tiny.grad_bytes_per_step == 2 * (tiny.nbytes * 3 // 4)
    # tpw: each tp shard all-reduces over dp*fsdp=4 only
    assert tpw.grad_bytes_per_step == 2 * ((tpw.nbytes // 2) * 3 // 4)
    summary = plan.summary()
    assert summary["mesh_shape"] == "dp=2,fsdp=2,tp=2"
    assert summary["reduce_scatter_params"] == 1
    assert summary["psum_params"] == 2
    assert summary["collective_bytes_per_step"] == (
        big.grad_bytes_per_step
        + tiny.grad_bytes_per_step
        + tpw.grad_bytes_per_step
    )


def test_plan_single_chip_is_all_local():
    mesh = build_mesh(MeshConfig(dp=1, devices=jax.devices()[:1]))
    params = {"w": jax.ShapeDtypeStruct((32, 32), jnp.float32)}
    plan = plan_dense_plane(params, mesh)
    assert all(p.mode == "local" for p in plan.params)
    assert plan.collective_bytes_per_step == 0
    assert plan.mesh_shape_str() == "dp=1"


def _mnist_batch(rng, n=16):
    return {
        "features": rng.randn(n, 28, 28, 1).astype(np.float32),
        "labels": rng.randint(0, 10, size=n).astype(np.int32),
        "_mask": np.ones((n,), np.bool_),
    }


@pytest.mark.slow
def test_spmd_trainer_bit_exact_at_mesh1():
    """The dense-plane acceptance contract: at mesh=1 the SPMD trainer
    computes bit-identical step state to the single-chip JaxTrainer —
    the sharding annotations change WHERE tensors live, never what
    they hold."""
    from elasticdl_tpu.models import mnist
    from elasticdl_tpu.parallel.spmd_trainer import SpmdTrainer
    from elasticdl_tpu.worker.trainer import JaxTrainer

    mesh = build_mesh(MeshConfig(dp=1, devices=[jax.devices()[0]]))
    make = lambda: dict(
        model=mnist.custom_model(),
        loss_fn=mnist.loss,
        optimizer=mnist.optimizer(),
        seed=7,
    )
    spmd = SpmdTrainer(mesh=mesh, **make())
    single = JaxTrainer(health=False, **make())

    rng = np.random.RandomState(3)
    batches = [_mnist_batch(rng) for _ in range(3)]
    s_state = d_state = None
    for batch in batches:
        s_state, s_loss = single.train_step(s_state, dict(batch))
        d_state, d_loss = spmd.train_step(d_state, dict(batch))
    assert float(s_loss) == float(d_loss)
    for a, b in zip(
        jax.tree_util.tree_leaves(s_state.params),
        jax.tree_util.tree_leaves(d_state.params),
    ):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    for a, b in zip(
        jax.tree_util.tree_leaves(s_state.opt_state),
        jax.tree_util.tree_leaves(d_state.opt_state),
    ):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    # the plan is derived and exported for telemetry
    assert spmd.dense_plan is not None
    assert spmd.mesh_shape_str == "dp=1"
    assert spmd.collective_bytes_per_step == 0.0


def test_spmd_trainer_dense_plan_on_fsdp_mesh():
    from elasticdl_tpu.models import mnist
    from elasticdl_tpu.parallel.spmd_trainer import SpmdTrainer

    if jax.device_count() < 2:
        pytest.skip("needs >=2 devices")
    mesh = build_mesh(MeshConfig(dp=1, fsdp=2, devices=jax.devices()[:2]))
    trainer = SpmdTrainer(
        model=mnist.custom_model(),
        loss_fn=mnist.loss,
        optimizer=mnist.optimizer(),
        mesh=mesh,
        seed=0,
    )
    batch = _mnist_batch(np.random.RandomState(0))
    state, loss = trainer.train_step(None, batch)
    assert np.isfinite(float(loss))
    plan = trainer.dense_plan
    assert plan is not None
    # the conv/dense kernels are big enough to shard; biases fall back
    modes = {p.path: p.mode for p in plan.params}
    assert "reduce_scatter" in modes.values()
    assert trainer.collective_bytes_per_step > 0
    assert trainer.mesh_shape_str == "fsdp=2"
