"""Extracted embedding client (ISSUE 8): TTL/thread-safe cache modes
and the pull stack shared between training prepare and serving resolve.

The headline regression here is the PR 6 note the extraction surfaced:
``HotRowCache`` invalidation used to be tied to the pulling thread
(train/sparse.py defers a PS-relaunch clear to the next prepare because
the unlocked cache races). Serving has no such thread — its cache is
built ``thread_safe=True`` and invalidation may land from ANY thread
while readers are mid-split; the concurrency test pins that this is
now safe.
"""

import threading
import time

import numpy as np
import pytest

from elasticdl_tpu.embedding import EmbeddingClient, HotRowCache
from elasticdl_tpu.ps.local_client import LocalPSClient


def _rows(n, dim=4, base=0.0):
    return (np.arange(n * dim, dtype=np.float32) + base).reshape(n, dim)


# ---------------------------------------------------------------------------
# HotRowCache: TTL mode


def test_ttl_mode_serves_fresh_and_expires():
    cache = HotRowCache(capacity=100, ttl_secs=0.15, thread_safe=True)
    ids = np.array([3, 7], np.int64)
    cache.put("t", ids, _rows(2))
    mask, cached = cache.split("t", ids)
    assert mask.all()
    np.testing.assert_array_equal(cached, _rows(2))
    time.sleep(0.2)  # past the TTL: every row is stale
    mask, cached = cache.split("t", ids)
    assert not mask.any() and cached is None


def test_ttl_mode_advance_is_a_noop():
    cache = HotRowCache(capacity=100, ttl_secs=60.0)
    ids = np.array([1], np.int64)
    cache.put("t", ids, _rows(1))
    for _ in range(50):
        cache.advance()  # the logical clock must not age TTL entries
    mask, _ = cache.split("t", ids)
    assert mask.all()


def test_ttl_validation():
    with pytest.raises(ValueError):
        HotRowCache(ttl_secs=0)
    with pytest.raises(ValueError):
        HotRowCache(staleness=0)


def test_hit_rate():
    cache = HotRowCache(staleness=2)
    ids = np.array([1, 2], np.int64)
    cache.split("t", ids)  # 2 misses
    cache.put("t", ids, _rows(2))
    cache.advance()
    cache.split("t", ids)  # 2 hits
    assert cache.hit_rate() == pytest.approx(0.5)


# ---------------------------------------------------------------------------
# the satellite regression: concurrent readers during invalidation


@pytest.mark.parametrize("writers", [1, 2])
def test_concurrent_readers_during_invalidation(writers):
    """Readers split() while other threads put() and clear() — the
    serving topology (batcher thread reads, PS-restart hook
    invalidates, warm-up thread fills). Every observed (mask, rows)
    pair must be internally consistent and no operation may raise."""
    cache = HotRowCache(capacity=10_000, ttl_secs=60.0, thread_safe=True)
    ids = np.arange(512, dtype=np.int64)
    cache.put("t", ids, _rows(512))
    stop = time.monotonic() + 1.0
    errors = []

    def reader():
        try:
            while time.monotonic() < stop:
                mask, rows = cache.split("t", ids)
                if rows is None:
                    assert not mask.any()
                else:
                    # a torn read (clear between mask and gather) would
                    # break this pairing
                    assert rows.shape[0] == int(mask.sum())
        except Exception as e:  # pragma: no cover - the regression
            errors.append(e)

    def invalidator():
        try:
            while time.monotonic() < stop:
                cache.clear()
                time.sleep(0.001)
        except Exception as e:  # pragma: no cover
            errors.append(e)

    def writer():
        try:
            while time.monotonic() < stop:
                cache.put("t", ids, _rows(512))
        except Exception as e:  # pragma: no cover
            errors.append(e)

    threads = (
        [threading.Thread(target=reader) for _ in range(3)]
        + [threading.Thread(target=invalidator)]
        + [threading.Thread(target=writer) for _ in range(writers)]
    )
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=30)
    assert not errors, errors


# ---------------------------------------------------------------------------
# EmbeddingClient pull stack


class _CountingClient(LocalPSClient):
    """Counts wire-level pulls; LocalPSClient's batch pull delegates to
    its per-table pull internally, so a flag keeps the inner calls out
    of the single-pull tally."""

    def __init__(self, **kw):
        super().__init__(**kw)
        self.single_pulls = 0
        self.batch_pulls = 0
        self.pulled_ids = 0
        self._in_batch = False

    def pull_embedding_vectors(self, name, ids):
        if not self._in_batch:
            self.single_pulls += 1
            self.pulled_ids += int(np.asarray(ids).size)
        return super().pull_embedding_vectors(name, ids)

    def pull_embedding_batch(self, ids_by_table):
        self.batch_pulls += 1
        self.pulled_ids += int(
            sum(np.asarray(i).size for i in ids_by_table.values())
        )
        self._in_batch = True
        try:
            return super().pull_embedding_batch(ids_by_table)
        finally:
            self._in_batch = False


def _tables(ps):
    ps.push_embedding_table_infos([("a", 4, "0.05"), ("b", 4, "0.05")])


def test_pull_tables_rides_fused_batch_and_fills_cache():
    ps = _CountingClient(seed=0)
    _tables(ps)
    client = EmbeddingClient(
        ps, cache=HotRowCache(ttl_secs=60.0, thread_safe=True),
        read_only=True,
    )
    ids = np.arange(16, dtype=np.int64)
    first = client.pull_tables({"a": ids, "b": ids})
    assert set(first) == {"a", "b"}
    assert ps.batch_pulls == 1 and ps.single_pulls == 0
    again = client.pull_tables({"a": ids, "b": ids})
    # all rows cache-fresh: no new RPC of either kind
    assert ps.batch_pulls == 1 and ps.single_pulls == 0
    for name in ("a", "b"):
        np.testing.assert_array_equal(first[name], again[name])


def test_pull_tables_partial_miss_pulls_only_misses():
    ps = _CountingClient(seed=0)
    _tables(ps)
    client = EmbeddingClient(ps, cache=HotRowCache(ttl_secs=60.0))
    client.pull_tables({"a": np.arange(8, dtype=np.int64)})
    before = ps.pulled_ids
    rows = client.pull_tables({"a": np.arange(12, dtype=np.int64)})
    assert ps.pulled_ids - before == 4  # only ids 8..11 hit the wire
    direct = ps.store.lookup("a", np.arange(12, dtype=np.int64))
    np.testing.assert_array_equal(rows["a"], direct)


def test_fan_out_without_batch_pull_matches():
    class _NoBatch(LocalPSClient):
        pull_embedding_batch = None

        def __getattribute__(self, name):
            if name == "pull_embedding_batch":
                raise AttributeError(name)
            return super().__getattribute__(name)

    ps = _NoBatch(seed=0)
    _tables(ps)
    client = EmbeddingClient(ps)
    ids = np.arange(6, dtype=np.int64)
    rows = client.pull_tables({"a": ids, "b": ids})
    np.testing.assert_array_equal(rows["a"], ps.store.lookup("a", ids))
    np.testing.assert_array_equal(rows["b"], ps.store.lookup("b", ids))


def test_invalidate_drops_rows_from_any_thread():
    ps = _CountingClient(seed=0)
    _tables(ps)
    client = EmbeddingClient(
        ps, cache=HotRowCache(ttl_secs=60.0, thread_safe=True)
    )
    ids = np.arange(4, dtype=np.int64)
    client.pull_tables({"a": ids})
    thread = threading.Thread(target=client.invalidate)
    thread.start()
    thread.join()
    before = ps.pulled_ids
    client.pull_tables({"a": ids})
    assert ps.pulled_ids - before == 4  # cache was really dropped


# ---------------------------------------------------------------------------
# read-only preparer (the serving resolve path)


def test_read_only_preparer_never_writes():
    class _ReadOnlyGuard(LocalPSClient):
        def push_embedding_table_infos(self, infos):
            raise AssertionError("read-only consumer pushed table infos")

        def push_gradients(self, *a, **k):
            raise AssertionError("read-only consumer pushed gradients")

    from elasticdl_tpu.train.sparse import (
        SparseBatchPreparer,
        SparseEmbeddingSpec,
    )

    ps = _ReadOnlyGuard(seed=0)
    # tables exist already (created by "training")
    LocalPSClient.push_embedding_table_infos(ps, [("t", 4, "0.05")])
    preparer = SparseBatchPreparer(
        [SparseEmbeddingSpec("t", 4, feature_key="ids", capacity=32)],
        ps,
        cache=HotRowCache(ttl_secs=60.0, thread_safe=True),
        read_only=True,
    )
    batch = {
        "features": {
            "ids": np.arange(8, dtype=np.int64).reshape(4, 2)
        }
    }
    prepared, _ = preparer.prepare(batch)
    assert prepared["features"]["t__rows"].shape == (32, 4)
    # a PS-relaunch hook must not re-arm registration either
    preparer._on_ps_restart(0)
    preparer.prepare(batch)


# ---------------------------------------------------------------------------
# PS-restart invalidation vs in-flight fill (ISSUE 17 S1)


def test_clear_racing_inflight_fill_drops_the_fill():
    """A PS restored-stamp invalidation (cache.clear, any thread) that
    lands between a fill's PS fetch and its put must WIN: the fetched
    rows came from the dead process and may not be re-inserted behind
    the clear. The caller still gets its rows (the response is what it
    is); only the cache insert is dropped, so the next request
    re-pulls from the live PS."""
    ps = _CountingClient(seed=0)
    _tables(ps)
    cache = HotRowCache(ttl_secs=60.0, thread_safe=True)
    client = EmbeddingClient(ps, cache=cache, read_only=True)
    ids = np.arange(8, dtype=np.int64)

    real_batch = ps.pull_embedding_batch

    def racing_batch(ids_by_table):
        out = real_batch(ids_by_table)
        cache.clear()  # the invalidation lands mid-fill, post-fetch
        return out

    ps.pull_embedding_batch = racing_batch
    rows = client.pull_tables({"a": ids})
    # the racing request is still served its rows
    np.testing.assert_array_equal(
        rows["a"], ps.store.lookup("a", ids)
    )
    ps.pull_embedding_batch = real_batch
    before = ps.pulled_ids
    client.pull_tables({"a": ids})
    # every id hits the wire again: the stale fill never entered
    assert ps.pulled_ids - before == ids.size


def test_clear_racing_single_table_pull_drops_the_fill():
    """Same pin for the per-table pull path (clients without the fused
    batch RPC) — both paths share _assemble, but the generation
    snapshot happens per entry point."""
    ps = _CountingClient(seed=0)
    _tables(ps)
    cache = HotRowCache(ttl_secs=60.0, thread_safe=True)
    client = EmbeddingClient(ps, cache=cache, read_only=True)
    ids = np.arange(6, dtype=np.int64)

    real_pull = ps.pull_embedding_vectors

    def racing_pull(name, pull_ids):
        out = real_pull(name, pull_ids)
        cache.clear()
        return out

    ps.pull_embedding_vectors = racing_pull
    client.pull("a", ids)
    ps.pull_embedding_vectors = real_pull
    mask, _ = cache.split("a", ids)
    assert not mask.any()  # nothing from the raced fill was cached


def test_generation_unraced_fill_still_caches():
    """The conditional put must not break the happy path: with no
    clear in flight, fills cache exactly as before."""
    ps = _CountingClient(seed=0)
    _tables(ps)
    cache = HotRowCache(ttl_secs=60.0, thread_safe=True)
    client = EmbeddingClient(ps, cache=cache, read_only=True)
    ids = np.arange(5, dtype=np.int64)
    client.pull("a", ids)
    mask, _ = cache.split("a", ids)
    assert mask.all()
    assert cache.generation == 0
    cache.clear()
    assert cache.generation == 1
