"""Transformer LM: TP + SP sharded training matches single-device math.

The strongest correctness check for the parallel layer: the same model,
same init, same batch, trained (a) on one device with plain XLA
attention and (b) GSPMD-sharded over a dp x tp x sp mesh with ring (and
ulysses) attention, must produce the same losses.
"""

import jax
import numpy as np
import pytest

from elasticdl_tpu.models import transformer
from elasticdl_tpu.parallel.mesh import MeshConfig, build_mesh
from elasticdl_tpu.parallel.spmd_trainer import SpmdTrainer
from elasticdl_tpu.train.optimizers import create_optimizer
from elasticdl_tpu.train.step_fns import make_train_step
from elasticdl_tpu.train.train_state import create_train_state


def _small_lm(**kwargs):
    return transformer.TransformerLM(
        vocab_size=128,
        num_layers=2,
        num_heads=4,
        embed_dim=32,
        **kwargs,
    )


def _batch(batch=4, seq=32, vocab=128, seed=0):
    rng = np.random.RandomState(seed)
    tokens = rng.randint(0, vocab, size=(batch, seq)).astype(np.int32)
    return {
        "features": tokens,
        "labels": tokens,
        "_mask": np.ones((batch,), np.float32),
    }


def _single_device_losses(batch, steps=3):
    model = _small_lm(attention_impl="xla")
    tx = create_optimizer("Adam", learning_rate=0.01)
    # Same key derivation as SpmdTrainer(seed=0).create_state so both
    # paths start from identical parameters.
    init_rng, _ = jax.random.split(jax.random.PRNGKey(0))
    state = create_train_state(model, tx, init_rng, batch["features"])
    step = jax.jit(make_train_step(model, transformer.loss, tx))
    losses = []
    for _ in range(steps):
        state, loss = step(state, batch)
        losses.append(float(loss))
    return losses


@pytest.mark.parametrize("impl", ["ring", "ulysses"])
def test_spmd_tp_sp_matches_single_device(impl):
    batch = _batch()
    expected = _single_device_losses(batch)

    mesh = build_mesh(MeshConfig(dp=2, tp=2, sp=2))
    model = _small_lm(attention_impl=impl, mesh=mesh)
    trainer = SpmdTrainer(
        model=model,
        loss_fn=transformer.loss,
        optimizer=create_optimizer("Adam", learning_rate=0.01),
        mesh=mesh,
        seed=0,
        sharding_rules=transformer.sharding_rules(),
        batch_spec=transformer.batch_spec(),
    )
    state = trainer.create_state(batch["features"])
    losses = []
    for _ in range(3):
        state, loss = trainer.train_step(state, batch)
        losses.append(float(loss))
    np.testing.assert_allclose(losses, expected, atol=1e-4, rtol=1e-4)


def test_spmd_fsdp_transformer_runs():
    batch = _batch(batch=8)
    mesh = build_mesh(MeshConfig(dp=2, fsdp=4))
    model = _small_lm(attention_impl="xla", mesh=mesh)
    trainer = SpmdTrainer(
        model=model,
        loss_fn=transformer.loss,
        optimizer=create_optimizer("Adam", learning_rate=0.01),
        mesh=mesh,
        seed=0,
        sharding_rules=transformer.sharding_rules(),
        batch_spec=transformer.batch_spec(),
    )
    state = trainer.create_state(batch["features"])
    state, loss1 = trainer.train_step(state, batch)
    state, loss2 = trainer.train_step(state, batch)
    assert np.isfinite(float(loss1)) and float(loss2) < float(loss1)


def test_model_contract_loads():
    from elasticdl_tpu.models.registry import get_model_spec

    spec = get_model_spec("elasticdl_tpu.models.transformer")
    assert spec.sharding_rules is not None
    assert spec.batch_spec is not None


@pytest.mark.parametrize("remat_policy", ["full", "dots", "flash"])
@pytest.mark.parametrize("attention_impl", ["xla", "pallas"])
def test_remat_policies_match_no_remat(remat_policy, attention_impl,
                                       monkeypatch):
    """Every remat policy must leave loss/gradients identical, including
    over the pallas flash kernel (whose o/lse the "dots" policy saves
    via checkpoint_name — the _attach custom_vjp machinery in
    ops/flash_attention.py). Pallas runs in interpret mode on CPU."""
    import functools

    import jax
    import jax.numpy as jnp
    import numpy as np

    from elasticdl_tpu.models import transformer

    if remat_policy == "flash" and attention_impl == "xla":
        pytest.skip(
            'remat_policy="flash" rejects non-pallas attention '
            "(covered by test_remat_policy_validated)"
        )

    if attention_impl == "pallas":
        orig = transformer.dot_product_attention
        monkeypatch.setattr(
            transformer,
            "dot_product_attention",
            functools.partial(orig, interpret=True),
        )

    tokens = jnp.asarray(
        np.random.RandomState(1).randint(0, 64, (2, 16)), jnp.int32
    )

    def loss_and_grads(remat):
        model = transformer.TransformerLM(
            vocab_size=64, num_layers=2, num_heads=2, embed_dim=32,
            attention_impl=attention_impl, remat=remat,
            remat_policy=remat_policy,
        )
        variables = model.init(jax.random.PRNGKey(0), tokens)

        def loss_fn(params):
            logits = model.apply({"params": params}, tokens)
            return jnp.mean(
                transformer.loss(tokens, logits).astype(jnp.float32)
            )

        return jax.value_and_grad(loss_fn)(variables["params"])

    v0, g0 = loss_and_grads(False)
    v1, g1 = loss_and_grads(True)
    assert np.isclose(float(v0), float(v1), rtol=1e-6)
    for a, b in zip(
        jax.tree_util.tree_leaves(g0), jax.tree_util.tree_leaves(g1)
    ):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=1e-5, atol=1e-7
        )


def test_remat_policy_validated():
    import jax
    import jax.numpy as jnp
    import numpy as np
    import pytest as _pytest

    from elasticdl_tpu.models import transformer

    tokens = jnp.asarray(
        np.random.RandomState(0).randint(0, 64, (1, 8)), jnp.int32
    )
    model = transformer.TransformerLM(
        vocab_size=64, num_layers=1, num_heads=2, embed_dim=32,
        attention_impl="xla", remat=True, remat_policy="Dots",
    )
    with _pytest.raises(ValueError, match="remat_policy"):
        model.init(jax.random.PRNGKey(0), tokens)

    # "flash" saves the pallas kernel's named outputs; under xla
    # attention the policy would match nothing and silently run as
    # "full" — the model must reject the contradiction loudly
    model = transformer.TransformerLM(
        vocab_size=64, num_layers=1, num_heads=2, embed_dim=32,
        attention_impl="xla", remat=True, remat_policy="flash",
    )
    with _pytest.raises(ValueError, match="flash"):
        model.init(jax.random.PRNGKey(0), tokens)
