"""Sparse embedding path end-to-end: JAX model + live PS over gRPC.

Models the reference's worker_ps_interaction_test.py: a real Pserver
service on localhost, the worker-side PSClient, and training that
converges through the host tables.
"""

import numpy as np
import pytest

from elasticdl_tpu.common.grpc_utils import (
    build_channel,
    build_server,
    find_free_port,
)
from elasticdl_tpu.models import deepfm
from elasticdl_tpu.proto.services import add_pserver_servicer_to_server
from elasticdl_tpu.ps.embedding_store import NumpyEmbeddingStore, create_store
from elasticdl_tpu.ps.servicer import PserverServicer
from elasticdl_tpu.train.metrics import AUC
from elasticdl_tpu.train.sparse import SparseTrainer
from elasticdl_tpu.worker.ps_client import PSClient


@pytest.fixture
def ps_cluster():
    """Two real PS servers on localhost."""
    servers = []
    addrs = []
    for ps_id in range(2):
        store = create_store(seed=ps_id)
        store.set_optimizer("adam", lr=0.01)
        servicer = PserverServicer(store, ps_id=ps_id)
        server = build_server()
        add_pserver_servicer_to_server(servicer, server)
        port = find_free_port()
        server.add_insecure_port("localhost:%d" % port)
        server.start()
        servers.append((server, store))
        addrs.append("localhost:%d" % port)
    yield addrs, [s for _, s in servers]
    for server, _ in servers:
        server.stop(None)


def _ctr_batch(rng, batch_size=64, num_features=10, vocab=500, weights=None):
    ids = rng.randint(0, vocab, size=(batch_size, num_features)).astype(
        np.int64
    )
    score = weights[ids].sum(axis=1) / np.sqrt(num_features)
    labels = (score + rng.randn(batch_size) * 0.1 > 0).astype(np.float32)
    return {
        "features": {"ids": ids},
        "labels": labels,
        "_mask": np.ones(batch_size, np.float32),
    }


def test_deepfm_trains_through_live_ps(ps_cluster):
    addrs, stores = ps_cluster
    client = PSClient(addrs)
    trainer = SparseTrainer(
        model=deepfm.custom_model(),
        loss_fn=deepfm.loss,
        optimizer=deepfm.optimizer(),
        specs=deepfm.sparse_embedding_specs(num_features=10, batch_size=64),
        ps_client=client,
        seed=0,
    )
    rng = np.random.RandomState(0)
    weights = np.random.RandomState(42).randn(500) * 2

    state = None
    losses = []
    for _ in range(30):
        batch = _ctr_batch(rng, weights=weights)
        state, loss = trainer.train_step(state, batch)
        losses.append(float(loss))
    assert losses[-1] < losses[0]

    # rows are sharded across both PS stores by id % 2
    sizes = [store.table_size("deepfm_emb") for store in stores]
    assert all(size > 0 for size in sizes)

    # AUC on held-out data clearly better than chance
    auc = AUC(from_logits=True)
    eval_rng = np.random.RandomState(7)
    for _ in range(4):
        batch = _ctr_batch(eval_rng, weights=weights)
        outputs = trainer.eval_step(state, batch)
        auc.update_state(batch["labels"], outputs)
    assert auc.result() > 0.8


def test_ps_client_routing_and_dedup(ps_cluster):
    addrs, stores = ps_cluster
    client = PSClient(addrs)
    client.push_embedding_table_infos([("t", 4, 0.05)])
    ids = np.array([0, 1, 2, 3, 10, 11], dtype=np.int64)
    rows = client.pull_embedding_vectors("t", ids)
    assert rows.shape == (6, 4)
    # same id pulled via different shards stays consistent
    again = client.pull_embedding_vectors("t", ids[::-1])
    np.testing.assert_array_equal(again, rows[::-1])
    # push deduped gradients: id 2 appears twice -> summed once
    values = np.ones((3, 4), np.float32)
    result = client.push_gradients(
        {"t": (values, np.array([2, 2, 3], dtype=np.int64))}
    )
    assert result.accepted and result.version >= 1
    after = client.pull_embedding_vectors("t", np.array([2, 3], np.int64))
    # sgd default lr=0.01: id2 got grad 2.0, id3 got 1.0... but stores
    # use adam here, so just check rows moved and differ
    assert not np.allclose(after[0], rows[2])
    assert not np.allclose(after[1], rows[3])


def test_dense_cold_start_protocol(ps_cluster):
    addrs, _ = ps_cluster
    client = PSClient(addrs)
    initialized, version, params = client.pull_dense_init()
    assert not initialized
    client.push_dense_init({"w": np.ones((2, 2), np.float32)}, version=5)
    # second push is ignored (first writer wins)
    client.push_dense_init({"w": np.zeros((2, 2), np.float32)}, version=9)
    initialized, version, params = client.pull_dense_init()
    assert initialized and version == 5
    np.testing.assert_array_equal(params["w"], np.ones((2, 2)))


def test_delayed_servicer_wraps_and_delays():
    """ps/server.py _DelayedServicer: every public method sleeps the
    injected delay then delegates (the latency-experiment knob)."""
    import time

    from elasticdl_tpu.ps.server import _DelayedServicer

    class Fake:
        attr = 7

        def pull_embedding_vectors(self, request, context=None):
            return ("pulled", request)

    wrapped = _DelayedServicer(Fake(), delay_ms=30.0)
    assert wrapped.attr == 7  # non-callables pass through
    t0 = time.perf_counter()
    out = wrapped.pull_embedding_vectors("req")
    elapsed = time.perf_counter() - t0
    assert out == ("pulled", "req")
    assert elapsed >= 0.025, elapsed


def test_sparse_capacity_env_override(monkeypatch):
    from elasticdl_tpu.models import deepfm

    monkeypatch.delenv("EDL_SPARSE_ID_CAPACITY", raising=False)
    # library default = the always-safe worst case (any id stream fits);
    # the measured Zipfian cap is an explicit deployment opt-in
    specs = deepfm.sparse_embedding_specs(batch_size=512)
    assert specs[0].capacity == 512 * deepfm.NUM_FIELDS
    specs = deepfm.sparse_embedding_specs(
        batch_size=512,
        capacity=min(512 * deepfm.NUM_FIELDS, deepfm.MAX_ID_CAPACITY),
    )
    assert specs[0].capacity == deepfm.MAX_ID_CAPACITY
    monkeypatch.setenv("EDL_SPARSE_ID_CAPACITY", "4096")
    specs = deepfm.sparse_embedding_specs(batch_size=512)
    assert specs[0].capacity == 4096
