"""Table readers (reference odps_reader.py parity) against an
in-memory table client."""

import numpy as np

from elasticdl_tpu.data.pipeline import Dataset
from elasticdl_tpu.data.readers import create_data_reader
from elasticdl_tpu.data.table_reader import (
    InMemoryTableClient,
    ParallelTableDataReader,
    TableDataReader,
)


class _Task:
    def __init__(self, shard_name, start, end):
        self.shard_name = shard_name
        self.start = start
        self.end = end


def _iris_client(n=130):
    rng = np.random.RandomState(0)
    rows = [
        (
            float(rng.rand()),
            float(rng.rand()),
            float(rng.rand()),
            float(rng.rand()),
            int(rng.randint(0, 3)),
        )
        for _ in range(n)
    ]
    columns = ["sepal_l", "sepal_w", "petal_l", "petal_w", "class"]
    return InMemoryTableClient(rows, columns), rows


def test_fixed_range_shards_with_remainder():
    client, _ = _iris_client(130)
    reader = TableDataReader(
        table_client=client, table="iris", records_per_task=50
    )
    shards = reader.create_shards()
    # 50+50+30, names <table>:shard_<i> (odps_reader.py:61-82)
    assert shards == {
        "iris:shard_0": (0, 50),
        "iris:shard_1": (50, 50),
        "iris:shard_2": (100, 30),
    }


def test_read_records_range_and_columns():
    client, rows = _iris_client(20)
    reader = TableDataReader(
        table_client=client,
        table="iris",
        records_per_task=8,
        columns=["petal_l", "class"],
    )
    got = list(reader.read_records(_Task("iris:shard_1", 8, 16)))
    assert got == [(r[2], r[4]) for r in rows[8:16]]
    assert reader.metadata.column_names == ["petal_l", "class"]


def test_parallel_reader_preserves_order():
    client, rows = _iris_client(101)
    reader = ParallelTableDataReader(
        table_client=client,
        table="iris",
        records_per_task=101,
        num_parallel=4,
        page_size=7,
    )
    got = list(reader.read_records(_Task("iris:shard_0", 0, 101)))
    assert got == [tuple(r) for r in rows]


def test_parallel_reader_stops_fetching_when_abandoned():
    """An abandoned generator (worker stopped mid-task) must not keep
    reading the remaining pages from the warehouse."""
    import time

    client, _ = _iris_client(1000)
    calls = []
    original = client.read_rows

    def counting_read_rows(start, end, columns=None):
        calls.append((start, end))
        time.sleep(0.005)
        return original(start, end, columns)

    client.read_rows = counting_read_rows
    reader = ParallelTableDataReader(
        table_client=client,
        table="iris",
        records_per_task=1000,
        num_parallel=2,
        page_size=10,  # 100 pages
    )
    stream = reader.read_records(_Task("iris:shard_0", 0, 1000))
    next(stream)
    stream.close()  # abandons the generator -> cancelled.set()
    time.sleep(0.2)
    fetched = len(calls)
    time.sleep(0.3)
    assert len(calls) == fetched, "fetches continued after abandonment"
    assert fetched < 100


def test_default_dataset_fn_last_column_is_label():
    client, rows = _iris_client(10)
    reader = TableDataReader(table_client=client, table="iris")
    dataset_fn = reader.default_dataset_fn()
    dataset = dataset_fn(
        Dataset(lambda: reader.read_records(_Task("iris:shard_0", 0, 10))),
        None,
        reader.metadata,
    )
    features, label = next(iter(dataset))
    assert set(features) == {"sepal_l", "sepal_w", "petal_l", "petal_w"}
    assert float(label) == float(rows[0][4])


def test_factory_routes_table_client():
    client, _ = _iris_client(10)
    reader = create_data_reader(
        "odps://proj/iris", records_per_task=5, table_client=client
    )
    assert isinstance(reader, TableDataReader)
    assert len(reader.create_shards()) == 2


def test_odps_sdk_gated_import():
    import pytest

    from elasticdl_tpu.data.table_reader import ODPSTableClient

    try:
        import odps  # noqa: F401
        has_sdk = True
    except ImportError:
        has_sdk = False
    if not has_sdk:
        with pytest.raises(ImportError, match="odps"):
            ODPSTableClient("p", "ak", "sk", "t")


# ---------------------------------------------------------------------------
# Write path (reference ODPSWriter, odps_io.py:444-515)
# ---------------------------------------------------------------------------

def test_table_writer_round_trip_through_reader():
    """Prediction outputs written with parallel writers read back
    through the range-sharded reader, order preserved per partition."""
    from elasticdl_tpu.data.table_reader import (
        InMemoryTableClient,
        ParallelTableDataReader,
    )
    from elasticdl_tpu.data.table_writer import (
        InMemoryWritableTable,
        TableWriter,
    )

    sink = InMemoryWritableTable(column_names=["pred", "row_id"])
    writer = TableWriter(
        sink, worker_index=3, buffer_rows=16, num_parallel=3
    )
    rows = [(float(i) / 100.0, i) for i in range(1000)]
    for start in range(0, 1000, 37):  # uneven write batches
        writer.write(rows[start:start + 37])
    writer.close()

    written = sink.rows("worker=3")
    assert sorted(written, key=lambda r: r[1]) == rows
    assert len(written) == 1000

    # read the written partition back through the reader stack
    reader = ParallelTableDataReader(
        table_client=InMemoryTableClient(
            sorted(written, key=lambda r: r[1]), ["pred", "row_id"]
        ),
        table="preds",
        records_per_task=128,
        num_parallel=2,
        page_size=50,
    )
    got = []
    for name, (start, count) in sorted(reader.create_shards().items()):
        class T:
            pass

        task = T()
        task.start, task.end = start, start + count
        got.extend(reader.read_records(task))
    assert got == rows


def test_table_writer_dict_outputs_and_error_surface():
    from elasticdl_tpu.data.table_writer import (
        InMemoryWritableTable,
        TableWriter,
        WritableTable,
    )
    import numpy as np
    import pytest

    sink = InMemoryWritableTable()
    writer = TableWriter(sink, worker_index=0, buffer_rows=4)
    # dict-of-arrays shape (normalize_outputs hands processors this)
    writer.write({"output": np.array([0.1, 0.2]), "id": np.array([7, 8])})
    writer.close()
    assert sink.rows("worker=0") == [(0.1, 7), (0.2, 8)]

    class Failing(WritableTable):
        def write_rows(self, rows, partition=None):
            raise IOError("tunnel down")

    bad = TableWriter(Failing(), buffer_rows=1)
    bad.write([(1,)])
    with pytest.raises(RuntimeError, match="table write failed"):
        bad.close()


def test_prediction_processor_writes_per_worker_partitions():
    """The PredictionOutputsProcessor contract wired to the table
    writer: each worker's outputs land in its own partition (reference
    per-worker ODPS partitions, odps_io.py:508-515)."""
    from elasticdl_tpu.data.table_writer import (
        InMemoryWritableTable,
        TablePredictionOutputsProcessor,
    )
    import numpy as np

    sink = InMemoryWritableTable()

    class Processor(TablePredictionOutputsProcessor):
        pass

    Processor.sink = sink
    processor = Processor()
    processor.process({"output": np.array([1.0, 2.0])}, worker_id=0)
    processor.process({"output": np.array([9.0])}, worker_id=4)
    processor.close()
    assert sink.rows("worker=0") == [(1.0,), (2.0,)]
    assert sink.rows("worker=4") == [(9.0,)]
