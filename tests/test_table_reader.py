"""Table readers (reference odps_reader.py parity) against an
in-memory table client."""

import numpy as np

from elasticdl_tpu.data.pipeline import Dataset
from elasticdl_tpu.data.readers import create_data_reader
from elasticdl_tpu.data.table_reader import (
    InMemoryTableClient,
    ParallelTableDataReader,
    TableDataReader,
)


class _Task:
    def __init__(self, shard_name, start, end):
        self.shard_name = shard_name
        self.start = start
        self.end = end


def _iris_client(n=130):
    rng = np.random.RandomState(0)
    rows = [
        (
            float(rng.rand()),
            float(rng.rand()),
            float(rng.rand()),
            float(rng.rand()),
            int(rng.randint(0, 3)),
        )
        for _ in range(n)
    ]
    columns = ["sepal_l", "sepal_w", "petal_l", "petal_w", "class"]
    return InMemoryTableClient(rows, columns), rows


def test_fixed_range_shards_with_remainder():
    client, _ = _iris_client(130)
    reader = TableDataReader(
        table_client=client, table="iris", records_per_task=50
    )
    shards = reader.create_shards()
    # 50+50+30, names <table>:shard_<i> (odps_reader.py:61-82)
    assert shards == {
        "iris:shard_0": (0, 50),
        "iris:shard_1": (50, 50),
        "iris:shard_2": (100, 30),
    }


def test_read_records_range_and_columns():
    client, rows = _iris_client(20)
    reader = TableDataReader(
        table_client=client,
        table="iris",
        records_per_task=8,
        columns=["petal_l", "class"],
    )
    got = list(reader.read_records(_Task("iris:shard_1", 8, 16)))
    assert got == [(r[2], r[4]) for r in rows[8:16]]
    assert reader.metadata.column_names == ["petal_l", "class"]


def test_parallel_reader_preserves_order():
    client, rows = _iris_client(101)
    reader = ParallelTableDataReader(
        table_client=client,
        table="iris",
        records_per_task=101,
        num_parallel=4,
        page_size=7,
    )
    got = list(reader.read_records(_Task("iris:shard_0", 0, 101)))
    assert got == [tuple(r) for r in rows]


def test_parallel_reader_stops_fetching_when_abandoned():
    """An abandoned generator (worker stopped mid-task) must not keep
    reading the remaining pages from the warehouse."""
    import time

    client, _ = _iris_client(1000)
    calls = []
    original = client.read_rows

    def counting_read_rows(start, end, columns=None):
        calls.append((start, end))
        time.sleep(0.005)
        return original(start, end, columns)

    client.read_rows = counting_read_rows
    reader = ParallelTableDataReader(
        table_client=client,
        table="iris",
        records_per_task=1000,
        num_parallel=2,
        page_size=10,  # 100 pages
    )
    stream = reader.read_records(_Task("iris:shard_0", 0, 1000))
    next(stream)
    stream.close()  # abandons the generator -> cancelled.set()
    time.sleep(0.2)
    fetched = len(calls)
    time.sleep(0.3)
    assert len(calls) == fetched, "fetches continued after abandonment"
    assert fetched < 100


def test_default_dataset_fn_last_column_is_label():
    client, rows = _iris_client(10)
    reader = TableDataReader(table_client=client, table="iris")
    dataset_fn = reader.default_dataset_fn()
    dataset = dataset_fn(
        Dataset(lambda: reader.read_records(_Task("iris:shard_0", 0, 10))),
        None,
        reader.metadata,
    )
    features, label = next(iter(dataset))
    assert set(features) == {"sepal_l", "sepal_w", "petal_l", "petal_w"}
    assert float(label) == float(rows[0][4])


def test_factory_routes_table_client():
    client, _ = _iris_client(10)
    reader = create_data_reader(
        "odps://proj/iris", records_per_task=5, table_client=client
    )
    assert isinstance(reader, TableDataReader)
    assert len(reader.create_shards()) == 2


def test_odps_sdk_gated_import():
    import pytest

    from elasticdl_tpu.data.table_reader import ODPSTableClient

    try:
        import odps  # noqa: F401
        has_sdk = True
    except ImportError:
        has_sdk = False
    if not has_sdk:
        with pytest.raises(ImportError, match="odps"):
            ODPSTableClient("p", "ak", "sk", "t")
