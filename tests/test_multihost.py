"""MultiHostRuntime: mesh-epoch-driven jax.distributed lifecycle
(reference allreduce_trainer.py:94-118 re-init semantics), driven
against the real MeshRendezvous."""

import pytest

from elasticdl_tpu.master.rendezvous import MeshRendezvous
from elasticdl_tpu.parallel.multihost import MultiHostRuntime
from elasticdl_tpu.proto import elasticdl_tpu_pb2 as pb


class FakeDistributed:
    def __init__(self):
        self.calls = []

    def initialize(self, coordinator_address, num_processes, process_id,
                   initialization_timeout=None):
        self.calls.append(
            ("init", coordinator_address, num_processes, process_id)
        )

    def shutdown(self):
        self.calls.append(("shutdown",))


class Client:
    """MasterClient stand-in wired straight to a MeshRendezvous."""

    def __init__(self, rendezvous, host):
        self._r = rendezvous
        self._host = host

    def get_comm_info(self):
        rank, size, epoch, coord = self._r.get_comm_info(self._host)
        return pb.CommInfo(
            rank=rank, world_size=size, mesh_epoch=epoch,
            coordinator_addr=coord,
        )


def test_initialize_once_then_noop():
    rendezvous = MeshRendezvous()
    rendezvous.set_worker_hosts(["hostA:3333", "hostB:3333"])
    fake = FakeDistributed()
    runtime = MultiHostRuntime(
        Client(rendezvous, "hostB:3333"), distributed=fake,
        coordinator_port=5000,
    )
    assert runtime.ensure_runtime() is True
    assert fake.calls == [("init", "hostA:5000", 2, 1)]
    assert runtime.rank == 1 and runtime.world_size == 2
    # same epoch: no-op
    assert runtime.ensure_runtime() is False
    assert len(fake.calls) == 1
    assert not runtime.check_epoch()


def test_membership_change_reinitializes():
    rendezvous = MeshRendezvous()
    rendezvous.set_worker_hosts(["hostA:3333", "hostB:3333"])
    fake = FakeDistributed()
    runtime = MultiHostRuntime(
        Client(rendezvous, "hostA:3333"), distributed=fake,
        coordinator_port=5000,
    )
    runtime.ensure_runtime()
    rendezvous.add_worker_host("hostC:3333")  # epoch bump
    assert runtime.check_epoch()
    assert runtime.ensure_runtime() is True
    assert fake.calls == [
        ("init", "hostA:5000", 2, 0),
        ("shutdown",),
        ("init", "hostA:5000", 3, 0),
    ]


def test_rpc_failure_marker_is_not_an_epoch_change():
    """mesh_epoch=-1 (MasterClient RPC-failure marker) must not trigger
    a restart — a network blip would discard un-checkpointed work."""
    rendezvous = MeshRendezvous()
    rendezvous.set_worker_hosts(["hostA:3333"])
    fake = FakeDistributed()
    runtime = MultiHostRuntime(
        Client(rendezvous, "hostA:3333"), distributed=fake,
        coordinator_port=5000,
    )
    runtime.ensure_runtime()
    assert not runtime.epoch_moved(-1)
    assert not runtime.epoch_moved(None)
    assert runtime.epoch_moved(rendezvous.mesh_epoch + 1)


def test_unadmitted_host_blocks_then_joins():
    rendezvous = MeshRendezvous()
    rendezvous.set_worker_hosts(["hostA:3333"])
    fake = FakeDistributed()
    client = Client(rendezvous, "hostB:3333")
    runtime = MultiHostRuntime(
        client, distributed=fake, coordinator_port=5000
    )
    with pytest.raises(TimeoutError):
        runtime.ensure_runtime(wait_sleep_secs=0.01, max_wait_secs=0.05)
    rendezvous.add_worker_host("hostB:3333")
    assert runtime.ensure_runtime() is True
    assert runtime.rank == 1


def test_coordinator_loss_promotes_next_rank():
    """When the coordinator host dies, the surviving worker re-inits
    with itself as rank 0 / coordinator."""
    rendezvous = MeshRendezvous()
    rendezvous.set_worker_hosts(["hostA:3333", "hostB:3333"])
    fake = FakeDistributed()
    runtime = MultiHostRuntime(
        Client(rendezvous, "hostB:3333"), distributed=fake,
        coordinator_port=5000,
    )
    runtime.ensure_runtime()
    assert runtime.rank == 1
    rendezvous.remove_worker_host("hostA:3333")
    assert runtime.ensure_runtime() is True
    assert runtime.rank == 0
    assert fake.calls[-1] == ("init", "hostB:5000", 1, 0)


def test_failed_init_retries_with_fresh_membership():
    """A join attempt that fails (e.g. the coordinator host died
    between fetching comm info and connecting, or the per-attempt
    initialization_timeout expired) must refresh membership and retry
    inside ensure_runtime — not block for jax's 300 s default or give
    up (the mid-join coordinator-death hang found by the chaos e2e)."""

    class FlakyDistributed(FakeDistributed):
        def __init__(self):
            super().__init__()
            self.fail_next_init = False

        def initialize(self, coordinator_address, num_processes,
                       process_id, initialization_timeout=None):
            if self.fail_next_init:
                self.fail_next_init = False
                self.calls.append(("init-failed",))
                raise RuntimeError("coordinator unreachable")
            super().initialize(
                coordinator_address, num_processes, process_id
            )

    rendezvous = MeshRendezvous()
    rendezvous.set_worker_hosts(["hostA:3333", "hostB:3333"])
    fake = FlakyDistributed()
    runtime = MultiHostRuntime(
        Client(rendezvous, "hostB:3333"), distributed=fake,
        coordinator_port=5000,
    )
    runtime.ensure_runtime()
    rendezvous.add_worker_host("hostC:3333")  # epoch bump
    fake.fail_next_init = True
    # the failed attempt is retried internally against refreshed
    # membership — simulate the coordinator dying mid-join
    rendezvous.remove_worker_host("hostA:3333")
    assert runtime.ensure_runtime() is True
    assert runtime.initialized
    # final successful init targets the POST-change membership
    assert fake.calls[-1] == ("init", "hostB:5000", 2, 0)
    assert ("init-failed",) in fake.calls
