r"""Real-cluster integration tier (env-gated).

Reference parity: the reference's minikube CI tier submitted a train
job and validated the pod lifecycle
(`scripts/travis/run_job.sh:28-51` + `scripts/validate_job_status.py`).
This is the same pair for this framework, against any live cluster
(kind / minikube / GKE). It is OFF by default — this image carries no
cluster or docker daemon — and turns on with:

    K8S_TESTS=True \
    EDL_K8S_API_URL=http://127.0.0.1:8001 \   # e.g. `kubectl proxy`
    EDL_TEST_IMAGE=<image with this repo installed> \
    [EDL_K8S_TOKEN=...] [EDL_K8S_NAMESPACE=default] \
    python -m pytest tests/test_k8s_cluster_e2e.py -v

The image must contain this package plus a copy of the mnist RecordIO
data at /data/train (the manifest mounts nothing), and the namespace's
default ServiceAccount needs pods+services create/watch RBAC — the
master provisions workers in-cluster (the Role/RoleBinding CI applies
in .github/workflows/ci.yml, mirroring the reference run_job.sh RBAC
setup). CI wires this as an optional, non-blocking tier.

The docker zoo-build gate (`edl zoo init/build` against a local
daemon, reference .travis.yml:77-98) is its own env gate:
EDL_DOCKER_TESTS=True.

Execution attempts on record (the tier needs a container runtime to
stand a cluster up): 2026-07-31 (round 4) — probed for docker / kind /
minikube / kubectl binaries and /var/run/docker.sock in the build
container; none exist (and the environment is zero-egress, so none
can be installed), so the tier remains validated against the fake
clientset only. 2026-07-31 (round 5) — re-probed: docker / podman /
nerdctl / k3s / minikube / kind / crictl all absent, no
/var/run/docker.sock or /run/containerd; unchanged. First environment
with a docker daemon: run the command block above and commit the
pod-lifecycle log as an artifact.
"""

import os
import shutil
import subprocess
import sys
import time
import uuid

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

_K8S_ON = os.environ.get("K8S_TESTS") == "True"
_DOCKER_ON = os.environ.get("EDL_DOCKER_TESTS") == "True"


def _env_api():
    from elasticdl_tpu.k8s.api import K8sApi

    url = os.environ.get("EDL_K8S_API_URL")
    if not url:
        pytest.skip("EDL_K8S_API_URL not set")
    return K8sApi(
        base_url=url,
        token=os.environ.get("EDL_K8S_TOKEN", ""),
        namespace=os.environ.get("EDL_K8S_NAMESPACE", "default"),
        verify=not url.startswith("http://"),
    )


@pytest.mark.slow
@pytest.mark.skipif(not _K8S_ON, reason="K8S_TESTS != True")
def test_submit_train_job_completes_on_cluster(monkeypatch):
    """Submit a small mnist train job through the real client path and
    validate the pod lifecycle the way the reference's
    validate_job_status.py did: master pod reaches Running, worker pods
    appear with the job's labels, master reaches Succeeded."""
    image = os.environ.get("EDL_TEST_IMAGE")
    if not image:
        pytest.skip("EDL_TEST_IMAGE not set")

    from elasticdl_tpu.client import api as client_api
    from elasticdl_tpu.client import main as client_main
    from elasticdl_tpu.k8s.client import (
        ELASTICDL_JOB_KEY,
        ELASTICDL_REPLICA_TYPE_KEY,
    )

    api = _env_api()
    monkeypatch.setattr(client_api, "_make_api", lambda parsed: api)

    from elasticdl_tpu.k8s.client import Client

    job_name = "edl-e2e-%s" % uuid.uuid4().hex[:8]
    master_pod = Client(api, job_name).get_master_pod_name()
    argv = [
        "train",
        "--image_name", image,
        "--job_name", job_name,
        "--model_zoo", "elasticdl_tpu.models.mnist",
        "--training_data", "/data/train",
        "--num_workers", "2",
        "--num_epochs", "1",
        "--records_per_task", "128",
        "--minibatch_size", "32",
        "--master_resource_request", "cpu=0.5,memory=1024Mi",
        "--worker_resource_request", "cpu=0.5,memory=1024Mi",
    ]

    def phase():
        try:
            pod = api.get_pod(master_pod)
        except Exception:
            return None
        return pod.get("status", {}).get("phase")

    def pods_with(selector, want, timeout):
        deadline = time.time() + timeout
        seen = set()
        while time.time() < deadline:
            for event in api.watch_pods(
                label_selector=selector, timeout_seconds=10
            ):
                obj = event.get("object", {})
                seen.add(obj.get("metadata", {}).get("name"))
                if len(seen) >= want:
                    return seen
        return seen

    try:
        client_main.main(argv)

        # master schedules and runs
        deadline = time.time() + 300
        while time.time() < deadline and phase() not in (
            "Running", "Succeeded"
        ):
            time.sleep(2)
        assert phase() in ("Running", "Succeeded"), phase()

        # the master provisions the workers (label-selected, as
        # validate_job_status.py selected on the job name)
        selector = "%s=%s,%s=worker" % (
            ELASTICDL_JOB_KEY, job_name, ELASTICDL_REPLICA_TYPE_KEY,
        )
        workers = pods_with(selector, want=2, timeout=300)
        assert len(workers) >= 2, workers

        # the job drains and the master exits cleanly
        deadline = time.time() + 600
        while time.time() < deadline and phase() not in (
            "Succeeded", "Failed"
        ):
            time.sleep(5)
        assert phase() == "Succeeded", phase()
    finally:
        # delete the master AND any worker pods it provisioned (on a
        # shared cluster leaked uuid-named workers accumulate)
        leftovers = {master_pod}
        try:
            for event in api.watch_pods(
                label_selector="%s=%s" % (ELASTICDL_JOB_KEY, job_name),
                timeout_seconds=5,
            ):
                name = (
                    event.get("object", {}).get("metadata", {}).get("name")
                )
                if name:
                    leftovers.add(name)
        except Exception:
            pass
        for name in leftovers:
            try:
                api.delete_pod(name)
            except Exception:
                pass


@pytest.mark.slow
@pytest.mark.skipif(not _DOCKER_ON, reason="EDL_DOCKER_TESTS != True")
def test_zoo_init_build_against_local_daemon(tmp_path):
    """`edl zoo init` + `edl zoo build` really build an image
    (reference .travis.yml:77-98 built and pushed the zoo image)."""
    if shutil.which("docker") is None:
        pytest.skip("no docker CLI")
    zoo_dir = str(tmp_path / "zoo")
    os.makedirs(zoo_dir)
    env = dict(os.environ, PYTHONPATH=REPO)

    def run(argv, cwd):
        return subprocess.run(
            [sys.executable, "-m", "elasticdl_tpu.client.main"] + argv,
            env=env, cwd=cwd, capture_output=True, text=True,
            timeout=600,
        )

    # zoo init writes ./Dockerfile into the zoo directory
    out = run(["zoo", "init"], cwd=zoo_dir)
    assert out.returncode == 0, out.stderr
    dockerfile = os.path.join(zoo_dir, "Dockerfile")
    assert os.path.exists(dockerfile)
    # the rendered template pip-installs the framework package, which
    # is not on public PyPI in CI — what this gate exercises is the
    # docker build invocation path, so swap in an installable package
    content = open(dockerfile).read()
    content = content.replace(
        "pip install elasticdl_tpu", "pip install numpy"
    )
    with open(dockerfile, "w") as f:
        f.write(content)
    tag = "elasticdl-tpu-zoo-test:%s" % uuid.uuid4().hex[:8]
    out = run(["zoo", "build", "--image", tag, zoo_dir], cwd=REPO)
    assert out.returncode == 0, out.stderr
    images = subprocess.run(
        ["docker", "images", "-q", tag], capture_output=True, text=True
    )
    assert images.stdout.strip(), "built image not found in daemon"
    subprocess.run(["docker", "rmi", tag], capture_output=True)
