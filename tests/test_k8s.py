"""K8s layer tests over a fake API (no cluster), mirroring the
reference's minikube-gated tier (SURVEY.md §4 tier 3) as in-process
fakes: instance-manager recovery semantics, pod/service manifests,
job-monitor phases, CLI dry-run round-trip into the master parser.
"""

import queue

import pytest
import yaml

from elasticdl_tpu.client import main as client_main
from elasticdl_tpu.common.args import parse_master_args
from elasticdl_tpu.k8s.client import Client
from elasticdl_tpu.k8s.instance_manager import InstanceManager
from elasticdl_tpu.k8s.job_monitor import PodMonitor
from elasticdl_tpu.master.rendezvous import MeshRendezvous


class FakeApi:
    """In-memory stand-in implementing the K8sApi surface."""

    def __init__(self, namespace="default"):
        self.namespace = namespace
        self.pods = {}
        self.services = {}
        self.events = queue.Queue()

    def create_pod(self, manifest):
        name = manifest["metadata"]["name"]
        if name in self.pods:
            raise RuntimeError("pod %s exists" % name)
        manifest.setdefault("status", {"phase": "Pending"})
        self.pods[name] = manifest
        return manifest

    def delete_pod(self, name, grace_period_seconds=0):
        return self.pods.pop(name, {})

    def get_pod(self, name):
        if name not in self.pods:
            raise RuntimeError("pod %s not found" % name)
        return self.pods[name]

    def patch_pod_labels(self, name, labels):
        self.pods[name]["metadata"].setdefault("labels", {}).update(labels)
        return self.pods[name]

    def create_service(self, manifest):
        self.services[manifest["metadata"]["name"]] = manifest
        return manifest

    def delete_service(self, name):
        return self.services.pop(name, {})

    def watch_pods(self, label_selector=None, timeout_seconds=None):
        while True:
            item = self.events.get()
            if item is None:
                return
            yield item


class FakeDispatcher:
    def __init__(self):
        self.recovered = []

    def recover_tasks(self, worker_id):
        self.recovered.append(worker_id)


def _manager(api, num_workers=2, num_ps=1, rendezvous=None):
    client = Client(api, "job1", image_name="img:latest")
    dispatcher = FakeDispatcher()
    manager = InstanceManager(
        client,
        num_workers=num_workers,
        num_ps=num_ps,
        worker_command=["python", "-m", "worker", "--worker_id={worker_id}"],
        ps_command=["python", "-m", "ps", "--ps_id={ps_id}"],
        task_dispatcher=dispatcher,
        rendezvous=rendezvous,
    )
    return client, dispatcher, manager


def _running(pod, start_time="t0"):
    pod["status"] = {"phase": "Running", "startTime": start_time}
    return pod


def test_manifests_and_services():
    api = FakeApi()
    client, dispatcher, manager = _manager(api)
    manager.start_workers()
    manager.start_parameter_servers()
    assert set(api.pods) == {
        "elasticdl-job1-worker-0",
        "elasticdl-job1-worker-1",
        "elasticdl-job1-ps-0",
    }
    # per-pod services with stable DNS names (reference k8s_client.py:29-31)
    assert set(api.services) == set(api.pods)
    pod = api.pods["elasticdl-job1-worker-0"]
    assert pod["spec"]["containers"][0]["command"][-1] == "--worker_id=0"
    labels = pod["metadata"]["labels"]
    assert labels["elasticdl-tpu-job-name"] == "job1"
    assert labels["elasticdl-tpu-replica-type"] == "worker"
    assert client.get_ps_service_address(0).startswith(
        "elasticdl-job1-ps-0.default.svc:"
    )


def test_worker_failure_recovers_tasks_and_relaunches():
    api = FakeApi()
    client, dispatcher, manager = _manager(api, num_workers=2, num_ps=0)
    manager.start_workers()
    pod = api.pods["elasticdl-job1-worker-0"]
    pod["status"] = {
        "phase": "Failed",
        "containerStatuses": [
            {"state": {"terminated": {"exitCode": 1, "reason": "Error"}}}
        ],
    }
    manager._event_cb("MODIFIED", pod)
    # dead worker's tasks re-queued under its id; replacement has NEW id
    assert dispatcher.recovered == [0]
    assert "elasticdl-job1-worker-2" in api.pods
    assert "elasticdl-job1-worker-0" not in manager.worker_phases()


def test_oom_killed_worker_not_relaunched():
    api = FakeApi()
    client, dispatcher, manager = _manager(api, num_workers=1, num_ps=0)
    manager.start_workers()
    pod = api.pods["elasticdl-job1-worker-0"]
    pod["status"] = {
        "phase": "Failed",
        "containerStatuses": [
            {
                "state": {
                    "terminated": {"exitCode": 137, "reason": "OOMKilled"}
                }
            }
        ],
    }
    manager._event_cb("MODIFIED", pod)
    assert dispatcher.recovered == [0]  # tasks still recovered
    assert len(api.pods) == 1  # no replacement pod
    assert manager.all_workers_failed


def test_ps_relaunch_keeps_id_and_address():
    api = FakeApi()
    client, dispatcher, manager = _manager(api, num_workers=0, num_ps=2)
    manager.start_parameter_servers()
    pod = api.pods["elasticdl-job1-ps-1"]
    pod["status"] = {
        "phase": "Failed",
        "containerStatuses": [
            {"state": {"terminated": {"exitCode": 1, "reason": "Error"}}}
        ],
    }
    manager._event_cb("MODIFIED", pod)
    # same pod name = same service address (k8s_instance_manager.py:349-354)
    assert "elasticdl-job1-ps-1" in api.pods
    assert api.pods["elasticdl-job1-ps-1"]["status"]["phase"] == "Pending"


def test_membership_feeds_rendezvous_sorted_by_start_time():
    api = FakeApi()
    rendezvous = MeshRendezvous()
    client, dispatcher, manager = _manager(
        api, num_workers=2, num_ps=0, rendezvous=rendezvous
    )
    manager.start_workers()
    # worker 1 started earlier than worker 0
    manager._event_cb(
        "MODIFIED", _running(api.pods["elasticdl-job1-worker-1"], "t1")
    )
    manager._event_cb(
        "MODIFIED", _running(api.pods["elasticdl-job1-worker-0"], "t2")
    )
    epoch_before = rendezvous.mesh_epoch
    assert rendezvous.hosts() == [
        client.get_worker_service_address(1),
        client.get_worker_service_address(0),
    ]
    # a death bumps the mesh epoch and shrinks the host list
    pod = api.pods["elasticdl-job1-worker-1"]
    pod["status"]["phase"] = "Failed"
    pod["status"]["containerStatuses"] = [
        {"state": {"terminated": {"exitCode": 1, "reason": "Error"}}}
    ]
    manager._event_cb("MODIFIED", pod)
    assert rendezvous.mesh_epoch > epoch_before
    assert client.get_worker_service_address(0) in rendezvous.hosts()


def test_scale_up_mid_job_recomputes_rendezvous(  # ISSUE 7 satellite
):
    api = FakeApi()
    rendezvous = MeshRendezvous()
    client, dispatcher, manager = _manager(
        api, num_workers=2, num_ps=0, rendezvous=rendezvous
    )
    manager.start_workers()
    manager._event_cb(
        "MODIFIED", _running(api.pods["elasticdl-job1-worker-0"], "t0")
    )
    manager._event_cb(
        "MODIFIED", _running(api.pods["elasticdl-job1-worker-1"], "t1")
    )
    epoch_before = rendezvous.mesh_epoch
    started = manager.scale_up(2)
    assert started == [2, 3]
    assert "elasticdl-job1-worker-2" in api.pods
    assert sorted(manager.worker_ids()) == [0, 1, 2, 3]
    # new pods join the alive-host list as they reach Running, sorted
    # by start time -> stable ranks for the incumbents
    manager._event_cb(
        "MODIFIED", _running(api.pods["elasticdl-job1-worker-2"], "t2")
    )
    manager._event_cb(
        "MODIFIED", _running(api.pods["elasticdl-job1-worker-3"], "t3")
    )
    assert rendezvous.mesh_epoch > epoch_before
    assert rendezvous.hosts() == [
        client.get_worker_service_address(i) for i in range(4)
    ]


def test_scale_down_drained_pod_not_relaunched():
    """ISSUE 7 satellite: an intentionally-removed worker must not be
    relaunched by its own DELETED event, must not trip
    all_workers_failed while peers live, and must leave the rendezvous
    alive-host list."""
    api = FakeApi()
    rendezvous = MeshRendezvous()
    client, dispatcher, manager = _manager(
        api, num_workers=2, num_ps=0, rendezvous=rendezvous
    )
    manager.start_workers()
    for idx in (0, 1):
        manager._event_cb(
            "MODIFIED",
            _running(api.pods["elasticdl-job1-worker-%d" % idx],
                     "t%d" % idx),
        )
    assert manager.remove_worker(1)
    pod = dict(api.pods.get("elasticdl-job1-worker-1") or {})
    assert "elasticdl-job1-worker-1" not in api.pods  # deleted
    # the watch delivers the DELETED event for the removed pod
    pod = {
        "metadata": {
            "name": "elasticdl-job1-worker-1",
            "labels": {"elasticdl-tpu-replica-type": "worker"},
        },
        "status": {"phase": "Running", "startTime": "t1"},
    }
    manager._event_cb("DELETED", pod)
    # no replacement, no recovery sweep (the drain handled the tasks),
    # no all-failed abort, and the host left the mesh
    assert set(api.pods) == {"elasticdl-job1-worker-0"}
    assert dispatcher.recovered == []
    assert not manager.all_workers_failed
    assert rendezvous.hosts() == [client.get_worker_service_address(0)]
    assert manager.worker_ids() == [0]
    # removing an unknown id is a no-op
    assert not manager.remove_worker(99)


def test_scale_down_victim_that_dies_nonzero_is_still_intentional():
    """A wedged drain ends in the watchdog's exit(1) or kubelet's
    SIGKILL, so the watch can deliver MODIFIED phase=Failed BEFORE the
    DELETED event. That is still an intentional removal: no recovery
    sweep, no replacement (which would defeat the scale-down), no
    all_workers_failed — and the later DELETED must stay a no-op."""
    api = FakeApi()
    client, dispatcher, manager = _manager(api, num_workers=2, num_ps=0)
    manager.start_workers()
    for idx in (0, 1):
        manager._event_cb(
            "MODIFIED",
            _running(api.pods["elasticdl-job1-worker-%d" % idx],
                     "t%d" % idx),
        )
    assert manager.remove_worker(1)
    pod = {
        "metadata": {
            "name": "elasticdl-job1-worker-1",
            "labels": {"elasticdl-tpu-replica-type": "worker"},
        },
        "status": {"phase": "Failed", "startTime": "t1"},
    }
    manager._event_cb("MODIFIED", pod)
    assert set(api.pods) == {"elasticdl-job1-worker-0"}  # no relaunch
    assert dispatcher.recovered == []
    assert not manager.all_workers_failed
    assert manager.worker_ids() == [0]
    # the DELETED that follows the Failed phase changes nothing
    manager._event_cb("DELETED", pod)
    assert set(api.pods) == {"elasticdl-job1-worker-0"}
    assert dispatcher.recovered == []


def test_failed_scale_down_delete_keeps_mark_for_fallback_delete():
    """A transient API error on the scale-down delete must KEEP the
    intentional mark: the victim is condemned (its get_task gate
    answers WAIT), and the drain-deadline fallback
    (``on_worker_presumed_dead``) deletes the pod again later. That
    later DELETED event must still read as intentional — relaunching a
    replacement would undo the shrink and loop (fallback delete →
    replacement → over-budget → drain → ...)."""
    api = FakeApi()
    client, dispatcher, manager = _manager(api, num_workers=2, num_ps=0)
    manager.start_workers()
    for idx in (0, 1):
        manager._event_cb(
            "MODIFIED",
            _running(api.pods["elasticdl-job1-worker-%d" % idx],
                     "t%d" % idx),
        )
    real_delete = api.delete_pod

    def flaky_delete(name, grace_period_seconds=0):
        raise RuntimeError("transient apiserver error")

    api.delete_pod = flaky_delete
    assert manager.remove_worker(1)
    assert "elasticdl-job1-worker-1" in api.pods  # delete failed
    api.delete_pod = real_delete
    # drain deadline expires → the presumed-dead fallback deletes the
    # pod via the client (no mark of its own), then the watch delivers
    # DELETED
    client.delete_worker(1)
    pod = {
        "metadata": {
            "name": "elasticdl-job1-worker-1",
            "labels": {"elasticdl-tpu-replica-type": "worker"},
        },
        "status": {"phase": "Running", "startTime": "t1"},
    }
    manager._event_cb("DELETED", pod)
    # intentional path: no replacement, no recovery sweep (the drain
    # deadline already requeued), no all-failed abort
    assert set(api.pods) == {"elasticdl-job1-worker-0"}
    assert dispatcher.recovered == []
    assert not manager.all_workers_failed
    assert manager.worker_ids() == [0]


def test_oom_killed_pod_never_relaunched_after_scale_events():
    """Scale churn must not erode the OOM rule: after a scale_up, an
    OOM-killed pod still gets no replacement (a bigger pod is an
    operator decision) while its tasks recover."""
    api = FakeApi()
    client, dispatcher, manager = _manager(api, num_workers=1, num_ps=0)
    manager.start_workers()
    manager.scale_up(1)
    pods_before = set(api.pods)
    pod = api.pods["elasticdl-job1-worker-1"]
    pod["status"] = {
        "phase": "Failed",
        "containerStatuses": [
            {
                "state": {
                    "terminated": {"exitCode": 137, "reason": "OOMKilled"}
                }
            }
        ],
    }
    manager._event_cb("MODIFIED", pod)
    assert dispatcher.recovered == [1]
    # no replacement pod appeared (the failed pod object itself stays
    # in the fake API; only relaunches create new names)
    assert set(api.pods) == pods_before
    assert manager.worker_ids() == [0]
    assert not manager.all_workers_failed  # worker 0 lives


def test_job_monitor_phases():
    api = FakeApi()
    api.create_pod(
        {"metadata": {"name": "m", "labels": {}}, "status": {"phase": "Running"}}
    )
    monitor = PodMonitor(api, "m", poll_secs=0)
    assert not monitor.pod_finished()
    api.pods["m"]["status"]["phase"] = "Succeeded"
    assert monitor.pod_finished()
    # Finished label counts too (Go PS exit check)
    api.pods["m"]["status"]["phase"] = "Running"
    api.patch_pod_labels("m", {"status": "Finished"})
    assert monitor.pod_finished()
    # vanished pod counts as finished
    api.delete_pod("m")
    assert monitor.pod_finished()


def test_cli_dry_run_roundtrips_master_args(tmp_path, capsys):
    out_yaml = tmp_path / "master.yaml"
    client_main.main(
        [
            "train",
            "--job_name=census1",
            "--image_name=registry/edl:latest",
            "--model_zoo=elasticdl_tpu.models.census_wide_deep",
            "--training_data=/data/train",
            "--validation_data=/data/valid",
            "--num_workers=4",
            "--num_ps_pods=2",
            "--minibatch_size=128",
            "--num_epochs=3",
            "--evaluation_steps=100",
            "--checkpoint_dir=/ckpt",
            "--checkpoint_steps=50",
            "--tpu_resource=google.com/tpu=8",
            "--mesh=dp=4,fsdp=2",
            "--use_async=0",
            "--grads_to_wait=2",
            "--volume=claim_name=data-pvc,mount_path=/data",
            "--envs=A=1,B=x",
            "--yaml=%s" % out_yaml,
        ]
    )
    manifest = yaml.safe_load(out_yaml.read_text())
    assert manifest["kind"] == "Pod"
    assert manifest["metadata"]["name"] == "elasticdl-census1-master"
    command = manifest["spec"]["containers"][0]["command"]
    assert command[:3] == ["python", "-m", "elasticdl_tpu.master.main"]
    # the forwarded command line must parse cleanly master-side with the
    # values intact (reference args.py:543-565 round trip)
    master_parsed = parse_master_args(command[3:])
    assert master_parsed.model_zoo == "elasticdl_tpu.models.census_wide_deep"
    assert master_parsed.num_workers == 4
    assert master_parsed.num_ps_pods == 2
    assert master_parsed.minibatch_size == 128
    assert master_parsed.checkpoint_steps == 50
    assert master_parsed.mesh == "dp=4,fsdp=2"
    # a meaningful zero must survive the round trip: 0 == False in
    # Python, so a naive empty-value filter drops --use_async=0 and the
    # master silently runs the async PS
    assert master_parsed.use_async == 0
    assert master_parsed.grads_to_wait == 2
    # volume landed in the pod spec
    mounts = manifest["spec"]["containers"][0]["volumeMounts"]
    assert mounts[0]["mountPath"] == "/data"


def test_ps_command_forwards_mode_flags():
    from elasticdl_tpu.k8s.pod_manager import build_ps_command
    from elasticdl_tpu.ps.server import parse_ps_args

    master_args = parse_master_args(
        [
            "--model_zoo=elasticdl_tpu.models.deepfm",
            "--use_async=0",
            "--grads_to_wait=3",
            "--sync_version_tolerance=1",
            "--lr_staleness_modulation=0",
        ]
    )
    command = build_ps_command(master_args, "master:50001", num_ps=2)
    rendered = [c.format(ps_id=1) for c in command]
    # the PS binary must parse the marshalled command with values intact
    # (reference marshals these Go-PS style, master.py:392-539)
    ps_parsed = parse_ps_args(rendered[3:])
    assert ps_parsed.use_async == 0
    assert ps_parsed.grads_to_wait == 3
    assert ps_parsed.sync_version_tolerance == 1
    assert ps_parsed.lr_staleness_modulation == 0


def test_cli_zoo_init(tmp_path, monkeypatch):
    monkeypatch.chdir(tmp_path)
    client_main.main(["zoo", "init", "--base_image=python:3.12-slim"])
    dockerfile = (tmp_path / "Dockerfile").read_text()
    assert "FROM python:3.12-slim" in dockerfile
    assert "COPY . /model_zoo" in dockerfile


def test_tensorboard_loadbalancer_service():
    """Reference parity: k8s_tensorboard_client.py:33-66 — a
    LoadBalancer service selecting the master pod on the TB port."""
    from elasticdl_tpu.k8s.client import Client

    api = FakeApi()
    client = Client(api, "job1", image_name="img")
    client.create_tensorboard_service(port=6006)
    service = api.services["tensorboard-job1"]
    assert service["spec"]["type"] == "LoadBalancer"
    assert service["spec"]["ports"][0]["port"] == 6006
    selector = service["spec"]["selector"]
    assert selector["elasticdl-tpu-replica-type"] == "master"


def test_pod_manager_applies_pod_spec_flags_from_args():
    """The full flag path: client train args -> forwarded master args ->
    K8sPodManager -> worker/PS pod specs. Round 4 found the resource /
    tpu / volume / priority flags were parsed client-side but never
    reached the pods the master creates (reference master.py:392-539
    re-emits them)."""
    from elasticdl_tpu.client.args import build_master_arguments
    from elasticdl_tpu.client.main import build_parser
    from elasticdl_tpu.k8s.pod_manager import K8sPodManager

    parsed = build_parser().parse_args([
        "train",
        "--job_name=rs1",
        "--image_name=registry/edl:v1",
        "--model_zoo=elasticdl_tpu.models.mnist",
        "--training_data=/data/train",
        "--num_workers=1",
        "--num_ps_pods=1",
        "--worker_resource_request=cpu=4,memory=8192Mi",
        "--worker_resource_limit=cpu=8,memory=16384Mi",
        "--ps_resource_request=cpu=2,memory=4096Mi",
        "--worker_pod_priority=high-priority",
        "--tpu_resource=google.com/tpu=8",
        "--volume=claim_name=data-pvc,mount_path=/data",
        "--image_pull_policy=IfNotPresent",
    ])
    master_args = parse_master_args(build_master_arguments(parsed))

    api = FakeApi()
    pm = K8sPodManager(
        master_args, FakeDispatcher(), rendezvous=None, api=api
    )
    pm._manager.start_workers()
    pm._manager.start_parameter_servers()

    worker = api.pods["elasticdl-rs1-worker-0"]
    container = worker["spec"]["containers"][0]
    assert container["image"] == "registry/edl:v1"
    assert container["imagePullPolicy"] == "IfNotPresent"
    assert container["resources"]["requests"] == {
        "cpu": "4", "memory": "8192Mi"
    }
    assert container["resources"]["limits"] == {
        "cpu": "8", "memory": "16384Mi", "google.com/tpu": "8"
    }
    assert worker["spec"]["priorityClassName"] == "high-priority"
    assert container["volumeMounts"][0]["mountPath"] == "/data"
    assert worker["spec"]["volumes"][0]["persistentVolumeClaim"] == {
        "claimName": "data-pvc"
    }

    ps = api.pods["elasticdl-rs1-ps-0"]
    ps_container = ps["spec"]["containers"][0]
    assert ps_container["resources"]["requests"] == {
        "cpu": "2", "memory": "4096Mi"
    }
    # TPU chips belong to worker pods only
    assert "google.com/tpu" not in ps_container["resources"]["limits"]
    assert "priorityClassName" not in ps["spec"]


def test_zoo_build_honors_docker_connection_flags(monkeypatch):
    """--docker_base_url / --docker_tlscert/key were parsed but never
    reached the docker invocation (reference drives the docker SDK with
    them, elasticdl_client/api.py:93-113)."""
    from elasticdl_tpu.client import api as client_api

    calls = []
    monkeypatch.setattr(
        client_api.subprocess, "run",
        lambda command, check: calls.append(command),
    )
    client_main.main([
        "zoo", "build", ".", "--image=r/edl:v1",
        "--docker_base_url=tcp://build-host:2376",
        "--docker_tlscert=/certs/cert.pem",
        "--docker_tlskey=/certs/key.pem",
    ])
    (command,) = calls
    assert command[:2] == ["docker", "--host"]
    assert "tcp://build-host:2376" in command
    assert "--tls" in command and "/certs/key.pem" in command
    assert command[-4:] == ["build", "-t", "r/edl:v1", "."]

    # push reaches the same daemon
    calls.clear()
    client_main.main([
        "zoo", "push", "r/edl:v1",
        "--docker_base_url=tcp://build-host:2376",
    ])
    (command,) = calls
    assert command[:3] == ["docker", "--host", "tcp://build-host:2376"]
    assert command[-2:] == ["push", "r/edl:v1"]

    # one-of-two TLS flags is a loud error, not silent plaintext
    with pytest.raises(ValueError, match="both required"):
        client_main.main([
            "zoo", "build", ".", "--image=r/edl:v1",
            "--docker_tlscert=/certs/cert.pem",
        ])


def test_cluster_spec_hooks_apply_to_all_manifests(tmp_path):
    """Reference parity: --cluster_spec names a module exporting
    `cluster` whose with_pod/with_service hooks customize every
    pod/service manifest (elasticdl_client/common/k8s_client.py:98-100,
    :184; elasticdl/python/common/k8s_client.py:293-294). Previously the
    file was only COPY'd into the zoo image and never loaded."""
    from elasticdl_tpu.client.args import build_master_arguments
    from elasticdl_tpu.client.main import build_parser
    from elasticdl_tpu.k8s.pod_manager import K8sPodManager

    spec_py = tmp_path / "my_cluster.py"
    spec_py.write_text(
        "class _C:\n"
        "    def with_pod(self, pod):\n"
        "        pod['spec'].setdefault('tolerations', []).append(\n"
        "            {'key': 'tpu', 'operator': 'Exists'})\n"
        "        return pod\n"
        "    def with_service(self, service):\n"
        "        service['metadata'].setdefault('labels', {})[\n"
        "            'team'] = 'ads'\n"
        "        return service\n"
        "cluster = _C()\n"
    )

    parsed = build_parser().parse_args([
        "train",
        "--job_name=cs1",
        "--image_name=img:1",
        "--model_zoo=elasticdl_tpu.models.mnist",
        "--cluster_spec=%s" % spec_py,
        "--num_workers=1",
    ])
    master_args = parse_master_args(build_master_arguments(parsed))
    api = FakeApi()
    pm = K8sPodManager(
        master_args, FakeDispatcher(), rendezvous=None, api=api
    )
    pm._manager.start_workers()
    worker = api.pods["elasticdl-cs1-worker-0"]
    assert worker["spec"]["tolerations"] == [
        {"key": "tpu", "operator": "Exists"}
    ]
    service = api.services["elasticdl-cs1-worker-0"]
    assert service["metadata"]["labels"]["team"] == "ads"

    # the client-side master pod gets the hook too
    from elasticdl_tpu.client import main as cm

    manifest = cm.main([
        "train", "--job_name=cs2", "--image_name=img:1",
        "--model_zoo=elasticdl_tpu.models.mnist",
        "--cluster_spec=%s" % spec_py, "--dry_run",
    ])
    assert manifest["spec"]["tolerations"] == [
        {"key": "tpu", "operator": "Exists"}
    ]
    # the master command carries the IN-IMAGE path (zoo init COPYs the
    # module to /cluster_spec/), not the client-local one
    command = manifest["spec"]["containers"][0]["command"]
    assert "--cluster_spec=/cluster_spec/my_cluster.py" in command

    # a module without a `cluster` export fails loudly
    bad = tmp_path / "bad_cluster.py"
    bad.write_text("x = 1\n")
    with pytest.raises(ValueError, match="cluster"):
        cm.main([
            "train", "--job_name=cs3", "--image_name=img:1",
            "--model_zoo=elasticdl_tpu.models.mnist",
            "--cluster_spec=%s" % bad, "--dry_run",
        ])


def test_cli_dry_run_exit_code_is_zero():
    """`edl train --dry_run` must exit 0: main() returns the manifest
    for tests, and sys.exit(<dict>) would turn that into exit code 1 —
    the process entry point (cli) discards the return value."""
    import os
    import subprocess
    import sys as _sys

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    proc = subprocess.run(
        [_sys.executable, "-m", "elasticdl_tpu.client.main", "train",
         "--job_name=rc0", "--image_name=i", "--model_zoo=m",
         "--dry_run"],
        capture_output=True, text=True, cwd=repo,
    )
    assert proc.returncode == 0, proc.stderr[-500:]


def test_reference_ci_command_lines_parse():
    """The reference's own CI submissions (scripts/client_test.sh:24-90)
    must parse against this client verbatim — including bool spellings
    (--use_async=True) and the evaluate/predict flag groups."""
    from elasticdl_tpu.client.main import build_parser

    p = build_parser()
    train = p.parse_args([
        "train", "--image_name=elasticdl:ci", "--model_zoo=model_zoo",
        "--model_def=deepfm_functional_api.deepfm_functional_api"
        ".custom_model",
        "--training_data=/data/frappe/train",
        "--validation_data=/data/frappe/test", "--num_epochs=1",
        "--master_resource_request=cpu=0.2,memory=1024Mi",
        "--master_resource_limit=cpu=1,memory=2048Mi",
        "--worker_resource_request=cpu=0.4,memory=2048Mi",
        "--worker_resource_limit=cpu=1,memory=3072Mi",
        "--ps_resource_request=cpu=0.2,memory=1024Mi",
        "--ps_resource_limit=cpu=1,memory=2048Mi",
        "--minibatch_size=64", "--num_minibatches_per_task=2",
        "--num_workers=2", "--num_ps_pods=2", "--checkpoint_steps=500",
        "--evaluation_steps=500",
        "--tensorboard_log_dir=/tmp/tensorboard-log",
        "--grads_to_wait=1", "--use_async=True",
        "--job_name=test-train", "--log_level=INFO",
        "--image_pull_policy=Never",
        "--output=/data/saved_model/model_output",
        "--volume=host_path=/d,mount_path=/data",
    ])
    assert train.use_async == 1  # "True" -> 1

    evaluate = p.parse_args([
        "evaluate", "--image_name=elasticdl:ci",
        "--model_zoo=model_zoo",
        "--model_def=mnist.mnist_functional_api.custom_model",
        "--checkpoint_dir_for_init=/ckpt/version-100",
        "--validation_data=/data/mnist/test", "--num_epochs=1",
        "--minibatch_size=64", "--num_minibatches_per_task=2",
        "--num_workers=2", "--num_ps_pods=2", "--evaluation_steps=15",
        "--tensorboard_log_dir=/tmp/tensorboard-log",
        "--job_name=test-evaluate", "--log_level=INFO",
        "--image_pull_policy=Never",
        "--volume=host_path=/d,mount_path=/data",
    ])
    assert evaluate.num_minibatches_per_task == 2

    predict = p.parse_args([
        "predict", "--image_name=elasticdl:ci",
        "--model_zoo=model_zoo",
        "--model_def=mnist.mnist_functional_api.custom_model",
        "--checkpoint_dir_for_init=/ckpt/version-100",
        "--prediction_data=/data/mnist/test", "--minibatch_size=64",
        "--num_minibatches_per_task=2", "--num_workers=2",
        "--num_ps_pods=2", "--job_name=test-predict",
    ])
    assert predict.prediction_data == "/data/mnist/test"


def test_bool_flag_defaults_and_bare_spelling_match_reference():
    """--use_async / --lr_staleness_modulation default to False like
    the reference (elasticdl_client/common/args.py:151-163), and the
    bare spelling (no value) flips the default the way the reference's
    add_bool_param (nargs="?", const=not default) does."""
    from elasticdl_tpu.client.main import build_parser
    from elasticdl_tpu.common.args import parse_master_args
    from elasticdl_tpu.ps.server import parse_ps_args

    base = [
        "train", "--image_name=i", "--model_zoo=m", "--job_name=j",
    ]
    p = build_parser()
    omitted = p.parse_args(base)
    assert omitted.use_async == 0
    assert omitted.lr_staleness_modulation == 0

    bare = p.parse_args(
        base + ["--use_async", "--lr_staleness_modulation"]
    )
    assert bare.use_async == 1
    assert bare.lr_staleness_modulation == 1

    explicit = p.parse_args(
        base + ["--use_async=False", "--lr_staleness_modulation=0"]
    )
    assert explicit.use_async == 0
    assert explicit.lr_staleness_modulation == 0

    # same semantics on the master and PS surfaces
    m = parse_master_args(["--model_zoo=m"])
    assert m.use_async == 0 and m.lr_staleness_modulation == 0
    ps = parse_ps_args(["--use_async"])
    assert ps.use_async == 1 and ps.lr_staleness_modulation == 0
