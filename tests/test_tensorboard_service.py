"""TensorBoard event files written by the master, verified against
TensorBoard's own reader (no TF in the writer path).

Reference parity: master/tensorboard_service.py:21-63 — one scalar
summary per completed evaluation, keyed by model version.
"""

import glob
import struct

import numpy as np

from elasticdl_tpu.master.tensorboard_service import (
    EventFileWriter,
    TensorboardService,
    _crc32c,
    _masked_crc,
    encode_event,
)


def test_crc32c_known_vectors():
    # RFC 3720 / kernel test vectors for CRC32C (Castagnoli)
    assert _crc32c(b"") == 0x00000000
    assert _crc32c(b"123456789") == 0xE3069283
    assert _crc32c(b"\x00" * 32) == 0x8A9136AA


def test_event_roundtrip_via_tensorboard_reader(tmp_path):
    tb = TensorboardService(str(tmp_path))
    tb.write_eval_summary(5, {"accuracy": 0.75, "auc": 0.9})
    tb.write_eval_summary(10, {"accuracy": 0.875, "note": "skipme"})
    tb.stop()

    from tensorboard.backend.event_processing.event_file_loader import (
        RawEventFileLoader,
    )
    from tensorboard.compat.proto.event_pb2 import Event

    files = glob.glob(str(tmp_path / "events.out.tfevents.*"))
    assert len(files) == 1
    events = [
        Event.FromString(raw)
        for raw in RawEventFileLoader(files[0]).Load()
    ]
    assert events[0].file_version == "brain.Event:2"
    scalars = {}
    for event in events[1:]:
        for value in event.summary.value:
            scalars[(event.step, value.tag)] = value.simple_value
    assert np.isclose(scalars[(5, "accuracy")], 0.75)
    assert np.isclose(scalars[(5, "auc")], 0.9)
    assert np.isclose(scalars[(10, "accuracy")], 0.875)
    assert (10, "note") not in scalars  # non-scalar metrics skipped


def test_tfrecord_framing(tmp_path):
    writer = EventFileWriter(str(tmp_path))
    writer.add_scalars(1, {"loss": 2.5})
    writer.close()
    with open(writer.path, "rb") as f:
        blob = f.read()
    offset = 0
    records = []
    while offset < len(blob):
        (length,) = struct.unpack_from("<Q", blob, offset)
        (len_crc,) = struct.unpack_from("<I", blob, offset + 8)
        assert len_crc == _masked_crc(blob[offset : offset + 8])
        record = blob[offset + 12 : offset + 12 + length]
        (data_crc,) = struct.unpack_from("<I", blob, offset + 12 + length)
        assert data_crc == _masked_crc(record)
        records.append(record)
        offset += 12 + length + 4
    assert len(records) == 2  # file_version + one scalar event


def test_evaluation_service_feeds_tensorboard(tmp_path):
    """A completed evaluation must land in the event file keyed by the
    model version (the reference's eval -> tf.summary flow)."""
    from elasticdl_tpu.common.tensor_utils import ndarray_to_blob
    from elasticdl_tpu.master.evaluation_service import EvaluationService
    from elasticdl_tpu.master.task_dispatcher import TaskDispatcher
    from elasticdl_tpu.proto import elasticdl_tpu_pb2 as pb
    from elasticdl_tpu.train.metrics import Accuracy

    tb = TensorboardService(str(tmp_path))
    dispatcher = TaskDispatcher(
        training_shards={"t": (0, 4)},
        evaluation_shards={"e": (0, 4)},
        records_per_task=2,
        num_epochs=1,
    )
    service = EvaluationService(
        dispatcher,
        lambda: {"accuracy": Accuracy()},
        eval_steps=10,
        summary_writer=tb,
    )
    assert service.add_evaluation_task_if_needed(10)
    outputs = {"output": ndarray_to_blob(np.eye(2)[[0, 1]])}
    labels = ndarray_to_blob(np.array([0, 1]))
    while True:
        task = dispatcher.get(0)
        if task is None:
            break
        if task.type == pb.EVALUATION:
            service.report_evaluation_metrics(outputs, labels)
        dispatcher.report(task.task_id, True)
    tb.stop()

    from tensorboard.backend.event_processing.event_file_loader import (
        RawEventFileLoader,
    )
    from tensorboard.compat.proto.event_pb2 import Event

    events = [
        Event.FromString(raw)
        for raw in RawEventFileLoader(tb.event_file).Load()
    ]
    tagged = {
        (e.step, v.tag): v.simple_value
        for e in events
        for v in e.summary.value
    }
    assert np.isclose(tagged[(10, "accuracy")], 1.0)
