"""ISSUE 5 wire-path overhaul, end to end: fused multi-table pulls
over live gRPC, legacy-peer interop, EDL_WIRE_DTYPE payloads, push
request reuse, bytes accounting, and the async double-buffered push."""

import threading
import time

import numpy as np
import pytest

from elasticdl_tpu.common.grpc_utils import (
    build_server,
    find_free_port,
)
from elasticdl_tpu.common import tensor_utils
from elasticdl_tpu.proto import services
from elasticdl_tpu.proto.services import add_pserver_servicer_to_server
from elasticdl_tpu.ps.embedding_store import NumpyEmbeddingStore
from elasticdl_tpu.ps.servicer import PserverServicer
from elasticdl_tpu.worker.ps_client import PSClient


class _RecordingServicer(PserverServicer):
    """Counts RPCs and remembers each push's table set."""

    def __init__(self, *args, **kwargs):
        super().__init__(*args, **kwargs)
        self.pull_vector_calls = 0
        self.pull_batch_calls = 0
        self.pushed_table_sets = []

    def pull_embedding_vectors(self, request, context=None):
        self.pull_vector_calls += 1
        return super().pull_embedding_vectors(request, context)

    def pull_embedding_batch(self, request, context=None):
        self.pull_batch_calls += 1
        return super().pull_embedding_batch(request, context)

    def push_gradients(self, request, context=None):
        self.pushed_table_sets.append(
            sorted(request.gradients.embedding_tables)
        )
        self.push_id_encodings = getattr(self, "push_id_encodings", [])
        for slices in request.gradients.embedding_tables.values():
            self.push_id_encodings.append(
                "packed" if slices.ids_blob else "legacy"
            )
        return super().push_gradients(request, context)


def _start_ps(n_shards=2, legacy=False):
    """n live PS servers; ``legacy=True`` serves only the pre-ISSUE-5
    method set (no pull_embedding_batch), like an old binary."""
    servers, servicers, addrs = [], [], []
    for ps_id in range(n_shards):
        store = NumpyEmbeddingStore(seed=ps_id)
        store.set_optimizer("adam", lr=0.01)
        servicer = _RecordingServicer(store, ps_id=ps_id)
        server = build_server()
        if legacy:
            methods = {
                name: pair
                for name, pair in services._PSERVER_METHODS.items()
                if name != "pull_embedding_batch"
            }
            services._add_service(
                server, servicer, services._PSERVER_SERVICE, methods
            )
        else:
            add_pserver_servicer_to_server(servicer, server)
        port = find_free_port()
        server.add_insecure_port("localhost:%d" % port)
        server.start()
        servers.append(server)
        servicers.append(servicer)
        addrs.append("localhost:%d" % port)
    return servers, servicers, addrs


@pytest.fixture
def live_ps():
    servers, servicers, addrs = _start_ps()
    yield servicers, addrs
    for server in servers:
        server.stop(None)


@pytest.fixture
def legacy_ps():
    servers, servicers, addrs = _start_ps(legacy=True)
    yield servicers, addrs
    for server in servers:
        server.stop(None)


def _register(client, tables=("t1", "t2", "t3"), dim=4):
    client.push_embedding_table_infos(
        [(name, dim, "0.05") for name in tables]
    )


# ---------------------------------------------------------------------------
# fused multi-table pull

def test_batched_pull_matches_per_table_and_costs_one_rpc_per_shard(
    live_ps,
):
    servicers, addrs = live_ps
    client = PSClient(addrs)
    _register(client)
    rng = np.random.RandomState(0)
    ids_by_table = {
        name: rng.randint(0, 1000, size=n).astype(np.int64)
        for name, n in (("t1", 64), ("t2", 17), ("t3", 1))
    }
    per_table = {
        name: client.pull_embedding_vectors(name, ids)
        for name, ids in ids_by_table.items()
    }
    vector_rpcs = sum(s.pull_vector_calls for s in servicers)
    assert vector_rpcs >= 3  # per-table path: >= one RPC per table
    batched = client.pull_embedding_batch(ids_by_table)
    assert sorted(batched) == ["t1", "t2", "t3"]
    for name, ids in ids_by_table.items():
        assert batched[name].shape == (ids.size, 4)
        np.testing.assert_array_equal(batched[name], per_table[name])
    # the whole 3-table pull cost at most one batch RPC per shard
    assert sum(s.pull_batch_calls for s in servicers) <= len(servicers)
    assert sum(s.pull_vector_calls for s in servicers) == vector_rpcs


def test_batched_pull_empty_and_missing_ids():
    servers, _, addrs = _start_ps(n_shards=1)
    try:
        client = PSClient(addrs)
        _register(client)
        assert client.pull_embedding_batch({}) == {}
        out = client.pull_embedding_batch(
            {"t1": np.empty((0,), np.int64)}
        )
        assert out == {}
    finally:
        for server in servers:
            server.stop(None)


def test_batched_pull_falls_back_against_legacy_server(legacy_ps):
    """An old PS answers pull_embedding_batch with UNIMPLEMENTED; the
    client must remember and serve every pull per-table."""
    servicers, addrs = legacy_ps
    client = PSClient(addrs)
    _register(client)
    ids = np.arange(40, dtype=np.int64)
    out = client.pull_embedding_batch({"t1": ids, "t2": ids[:7]})
    assert client._batch_pull_supported is False
    assert out["t1"].shape == (40, 4)
    assert out["t2"].shape == (7, 4)
    np.testing.assert_array_equal(
        out["t1"], client.pull_embedding_vectors("t1", ids)
    )
    # second pull goes straight per-table (no repeated UNIMPLEMENTED)
    out2 = client.pull_embedding_batch({"t3": ids[:3]})
    assert out2["t3"].shape == (3, 4)
    # and pushes switch to the legacy repeated-id encoding: a
    # pre-ids_blob server reads only `ids`, so a packed push against
    # it would silently apply nothing
    client.push_gradients(
        {"t1": (np.ones((4, 4), np.float32),
                np.arange(4, dtype=np.int64))}
    )
    encodings = [e for s in servicers
                 for e in getattr(s, "push_id_encodings", [])]
    assert encodings and set(encodings) == {"legacy"}, encodings


def test_legacy_fallback_many_tables_does_not_deadlock(legacy_ps):
    """Regression: the per-table fallback must fan out on its own pool.
    Nested on the client's shard pool, >= max_workers simultaneously
    blocked per-table tasks starve their own per-shard sub-tasks and
    the pull hangs forever."""
    servicers, addrs = legacy_ps
    client = PSClient(addrs)
    tables = tuple("t%d" % i for i in range(6))  # > pool max_workers
    _register(client, tables=tables)
    ids = np.arange(20, dtype=np.int64)
    done = {}

    def pull():
        done["out"] = client.pull_embedding_batch(
            {name: ids for name in tables}
        )

    thread = threading.Thread(target=pull, daemon=True)
    thread.start()
    thread.join(timeout=30)
    assert not thread.is_alive(), "legacy per-table fallback deadlocked"
    assert sorted(done["out"]) == sorted(tables)
    for name in tables:
        assert done["out"][name].shape == (20, 4)


def test_legacy_repeated_ids_request_still_served(monkeypatch, live_ps):
    """A legacy CLIENT sending repeated varint ids must keep working
    against the new server (reader-accepts-either contract) — and must
    be served plain fp32 even when the server runs a reduced wire
    dtype, since a pre-knob client cannot resolve extension dtype
    names."""
    import grpc

    from elasticdl_tpu.proto import elasticdl_tpu_pb2 as pb
    from elasticdl_tpu.proto.services import PserverStub

    servicers, addrs = live_ps
    client = PSClient(addrs)
    _register(client)
    monkeypatch.setenv(tensor_utils.WIRE_DTYPE_ENV, "bfloat16")
    stub = PserverStub(grpc.insecure_channel(addrs[0]))
    request = pb.PullEmbeddingVectorsRequest(name="t1", ids=[1, 2, 3])
    blob = stub.pull_embedding_vectors(request, timeout=10)
    assert blob.dtype == "float32"  # legacy peers never get bf16
    rows = tensor_utils.blob_to_ndarray(blob)
    assert rows.shape == (3, 4)


# ---------------------------------------------------------------------------
# push path: request reuse + packed ids on the wire

def test_push_requests_reused_without_cross_step_leftovers(live_ps):
    servicers, addrs = live_ps
    client = PSClient(addrs)
    _register(client)
    rng = np.random.RandomState(1)
    grads = lambda n: (  # noqa: E731
        rng.randn(n, 4).astype(np.float32),
        rng.permutation(1000)[:n].astype(np.int64),
    )
    client.push_gradients({"t1": grads(8), "t2": grads(5)})
    client.push_gradients({"t3": grads(6)})
    pushed = [s for servicer in servicers
              for s in servicer.pushed_table_sets]
    # no request carried t1/t2 leftovers into the second step
    for table_set in pushed:
        assert not ({"t1", "t2"} & set(table_set)) or "t3" not in table_set
    assert any("t3" in s for s in pushed)
    # ids traveled packed: the store applied them (value check) and
    # bytes were tallied
    assert sum(s._t_push_bytes for s in servicers) > 0


def test_push_and_pull_bytes_flow_into_telemetry(live_ps):
    servicers, addrs = live_ps
    client = PSClient(addrs)
    _register(client)
    ids = np.arange(32, dtype=np.int64)
    client.pull_embedding_batch({"t1": ids})
    client.push_gradients(
        {"t1": (np.ones((32, 4), np.float32), ids)}
    )
    blobs = [s.telemetry_blob() for s in servicers]
    assert sum(b.pull_bytes for b in blobs) == 32 * 4 * 4
    # push payload: 32 fp32 rows of dim 4 + 32 packed int64 ids
    assert sum(b.push_bytes for b in blobs) == 32 * 4 * 4 + 32 * 8


# ---------------------------------------------------------------------------
# EDL_WIRE_DTYPE over a real wire

def test_bfloat16_wire_end_to_end(monkeypatch, live_ps):
    servicers, addrs = live_ps
    monkeypatch.setenv(tensor_utils.WIRE_DTYPE_ENV, "bfloat16")
    client = PSClient(addrs)
    _register(client, tables=("t1",))
    ids = np.arange(16, dtype=np.int64)
    rows = client.pull_embedding_batch({"t1": ids})["t1"]
    assert rows.dtype == np.float32  # upcast client-side
    grads = np.full((16, 4), 0.125, np.float32)  # bf16-exact value
    accepted, version, _ = client.push_gradients({"t1": (grads, ids)})
    assert accepted
    # the PS kept fp32 master copies and applied the (exactly
    # representable) payload: rows moved by adam's first step
    total_rows = sum(s._store.table_size("t1") for s in servicers)
    assert total_rows == 16
    # payload bytes were half of fp32 and labeled bfloat16
    pushed = sum(s._t_push_bytes for s in servicers)
    assert pushed == 16 * 4 * 2 + 16 * 8  # bf16 rows + packed ids
    # float32 pull on a fresh client (knob off) still decodes tables
    monkeypatch.delenv(tensor_utils.WIRE_DTYPE_ENV)
    again = client.pull_embedding_batch({"t1": ids})["t1"]
    assert again.dtype == np.float32
    assert not np.array_equal(again, rows)  # the push landed


# ---------------------------------------------------------------------------
# async double-buffered push

class _SlowLocalClient:
    """LocalPSClient wrapper whose pushes block until released —
    deterministic overlap/join probes."""

    def __init__(self, inner):
        self._inner = inner
        self.release = threading.Event()
        self.push_started = threading.Event()
        self.pushes = 0
        self.fail_next = None  # None | "reject" | "raise"

    def __getattr__(self, name):
        return getattr(self._inner, name)

    def push_gradients(self, *args, **kwargs):
        self.push_started.set()
        assert self.release.wait(timeout=30), "push never released"
        self.pushes += 1
        failure, self.fail_next = self.fail_next, None
        if failure == "reject":
            from elasticdl_tpu.worker.ps_client import PushResult

            return PushResult(False, 7, (0,))
        if failure == "raise":
            raise ConnectionError("injected push transport failure")
        return self._inner.push_gradients(*args, **kwargs)


def _async_trainer(client):
    from elasticdl_tpu.models import deepfm
    from elasticdl_tpu.train.sparse import SparseTrainer

    return SparseTrainer(
        model=deepfm.custom_model(),
        loss_fn=deepfm.loss,
        optimizer=deepfm.optimizer(),
        specs=deepfm.sparse_embedding_specs(
            num_features=5, batch_size=8
        ),
        ps_client=client,
        seed=0,
        async_push=True,
    )


def _batches(n, seed=0):
    rng = np.random.RandomState(seed)
    return [{
        "features": {
            "ids": rng.randint(0, 100, size=(8, 5)).astype(np.int64)
        },
        "labels": rng.randint(0, 2, 8).astype(np.float32),
        "_mask": np.ones(8, np.float32),
    } for _ in range(n)]


def test_async_push_overlaps_step_and_joins_depth_one():
    from elasticdl_tpu.ps.local_client import LocalPSClient

    client = _SlowLocalClient(LocalPSClient(seed=0, opt_type="adam"))
    trainer = _async_trainer(client)
    b1, b2 = _batches(2)
    state, _ = trainer.train_step(None, b1)
    # step 1 returned while its push is still blocked: overlap is real
    assert client.push_started.wait(timeout=10)
    assert client.pushes == 0
    client.release.set()
    # depth-1 barrier: step 2 joins step 1's push before submitting
    state, _ = trainer.train_step(state, b2)
    trainer.join_pushes()
    assert client.pushes == 2
    assert trainer._version == 2


def test_async_push_failure_surfaces_on_join():
    from elasticdl_tpu.ps.local_client import LocalPSClient

    client = _SlowLocalClient(LocalPSClient(seed=0, opt_type="adam"))
    client.release.set()
    trainer = _async_trainer(client)
    (batch,) = _batches(1)
    client.fail_next = "raise"
    state, _ = trainer.train_step(None, batch)
    with pytest.raises(ConnectionError, match="injected push"):
        trainer.join_pushes()
    # the failed future is consumed: the barrier is reusable
    trainer.join_pushes()


def test_async_push_rejection_raises_with_shards_on_join():
    from elasticdl_tpu.ps.local_client import LocalPSClient

    client = _SlowLocalClient(LocalPSClient(seed=0, opt_type="adam"))
    client.release.set()
    trainer = _async_trainer(client)
    (batch,) = _batches(1)
    client.fail_next = "reject"
    trainer.train_step(None, batch)
    with pytest.raises(RuntimeError, match=r"rejected.*\[0\]"):
        trainer.join_pushes()
    assert trainer.push_rejections == 1


def test_async_push_trains_through_live_ps(live_ps):
    """Async-push training over a real gRPC PS: every step's push
    lands (version accounting adds up) and losses stay finite."""
    _, addrs = live_ps
    from elasticdl_tpu.models import deepfm
    from elasticdl_tpu.train.sparse import SparseTrainer

    def run(async_push):
        trainer = SparseTrainer(
            model=deepfm.custom_model(),
            loss_fn=deepfm.loss,
            optimizer=deepfm.optimizer(),
            specs=deepfm.sparse_embedding_specs(
                num_features=5, batch_size=8
            ),
            ps_client=PSClient(addrs),
            seed=0,
            async_push=async_push,
        )
        rng = np.random.RandomState(7)
        state = None
        losses = []
        for k in range(4):
            ids = (k * 100 + rng.randint(0, 100, size=(8, 5))).astype(
                np.int64
            )
            batch = {
                "features": {"ids": ids},
                "labels": rng.randint(0, 2, 8).astype(np.float32),
                "_mask": np.ones(8, np.float32),
            }
            state, loss = trainer.train_step(state, batch)
            losses.append(float(loss))
        trainer.join_pushes()
        return losses

    sync_losses = run(False)
    async_losses = run(True)
    assert np.isfinite(sync_losses).all() and np.isfinite(
        async_losses
    ).all()
