import numpy as np

from elasticdl_tpu.train import metrics as M


def test_accuracy_sparse_categorical():
    m = M.Accuracy()
    labels = np.array([0, 1, 2, 1])
    outputs = np.eye(3)[[0, 1, 0, 1]]
    m.update_state(labels, outputs)
    assert m.result() == 0.75


def test_binary_accuracy_logits():
    m = M.BinaryAccuracy(from_logits=True)
    m.update_state(np.array([1, 0, 1]), np.array([2.0, -2.0, -2.0]))
    assert abs(m.result() - 2 / 3) < 1e-9


def test_auc_matches_sklearn_style_rank():
    rng = np.random.RandomState(0)
    labels = rng.randint(0, 2, size=200)
    scores = rng.rand(200) + labels * 0.5
    m = M.AUC()
    # streaming in chunks must equal one-shot
    m.update_state(labels[:100], scores[:100])
    m.update_state(labels[100:], scores[100:])
    # brute-force pairwise AUC
    pos = scores[labels == 1]
    neg = scores[labels == 0]
    pairs = (pos[:, None] > neg[None, :]).sum() + 0.5 * (
        pos[:, None] == neg[None, :]
    ).sum()
    expected = pairs / (pos.size * neg.size)
    assert abs(m.result() - expected) < 1e-9


def test_mse_mae():
    mse = M.MeanSquaredError()
    mae = M.MeanAbsoluteError()
    labels = np.array([1.0, 2.0, 3.0])
    outputs = np.array([1.0, 1.0, 5.0])
    mse.update_state(labels, outputs)
    mae.update_state(labels, outputs)
    assert abs(mse.result() - (0 + 1 + 4) / 3) < 1e-9
    assert abs(mae.result() - (0 + 1 + 2) / 3) < 1e-9


def test_evaluation_metrics_multi_output():
    books = M.EvaluationMetrics(
        {"probs": {"acc": M.Accuracy()}, "aux": {"mse": M.MeanSquaredError()}}
    )
    books.update_evaluation_metrics(
        {"probs": np.eye(2)[[0, 1]], "aux": np.array([1.0, 1.0])},
        np.array([0, 1]),
    )
    summary = books.get_evaluation_summary()
    assert summary["probs"]["acc"] == 1.0
    assert summary["aux"]["mse"] == 0.5
