"""Mixed-precision invariants of the ResNet family.

Locks in the bf16 residual stream: BN must not force f32 outputs (that
would promote every downstream conv to f32 and halve the MXU rate —
measured 1.8x step time on v5e), while BN statistics stay f32."""

import jax
import jax.numpy as jnp
import numpy as np

from elasticdl_tpu.models import resnet
from elasticdl_tpu.train.train_state import cast_floating


def test_bf16_stream_f32_stats():
    model = resnet.resnet18(num_classes=8, small_inputs=True)
    x = jnp.ones((2, 16, 16, 3), jnp.float32)
    variables = model.init(jax.random.PRNGKey(0), x, training=False)
    params = cast_floating(variables["params"], jnp.bfloat16)

    outputs, updated = model.apply(
        {"params": params, "batch_stats": variables["batch_stats"]},
        cast_floating(x, jnp.bfloat16),
        training=True,
        mutable=["batch_stats"],
    )
    # head logits pinned to f32, running stats stay f32
    assert outputs.dtype == jnp.float32
    stats_dtypes = {
        leaf.dtype for leaf in jax.tree_util.tree_leaves(
            updated["batch_stats"]
        )
    }
    assert stats_dtypes == {np.dtype(jnp.float32)}

    # the stream feeding the head must be bf16: capture an intermediate
    _, state = model.apply(
        {"params": params, "batch_stats": variables["batch_stats"]},
        cast_floating(x, jnp.bfloat16),
        training=False,
        capture_intermediates=True,
        mutable=["intermediates"],
    )
    inter = state["intermediates"]
    # every BatchNorm output in the trunk is bf16 (none promote to f32)
    bn_outputs = [
        value[0]
        for path, value in _flatten_intermediates(inter)
        if "BatchNorm" in path
    ]
    assert bn_outputs, "no BatchNorm intermediates captured"
    assert all(o.dtype == jnp.bfloat16 for o in bn_outputs)


def test_space_to_depth_packing():
    """Exact 2x2-block packing semantics."""
    x = jnp.arange(2 * 4 * 4 * 3).reshape(2, 4, 4, 3)
    packed = resnet.space_to_depth(x, 2)
    assert packed.shape == (2, 2, 2, 12)
    # output pixel (0,0) = rows 0-1 x cols 0-1 of the input, channel-major
    np.testing.assert_array_equal(
        np.asarray(packed)[0, 0, 0],
        np.concatenate([
            np.asarray(x)[0, 0, 0], np.asarray(x)[0, 0, 1],
            np.asarray(x)[0, 1, 0], np.asarray(x)[0, 1, 1],
        ]),
    )


def test_space_to_depth_stem_forward():
    model = resnet.resnet18(num_classes=4, stem="space_to_depth")
    x = jnp.ones((2, 32, 32, 3), jnp.float32)
    variables = model.init(jax.random.PRNGKey(0), x, training=False)
    out = model.apply(variables, x, training=False)
    assert out.shape == (2, 4)
    # stem grid is half-res, like conv7
    stem_kernel = variables["params"]["Conv_0"]["kernel"]
    assert stem_kernel.shape == (4, 4, 12, 64)


def _flatten_intermediates(tree, prefix=""):
    items = []
    if isinstance(tree, dict):
        for key, value in tree.items():
            items.extend(_flatten_intermediates(value, prefix + key + "/"))
    else:
        items.append((prefix, tree))
    return items
