"""Optimizer factory coverage (reference optimizer families:
go/pkg/ps/optimizer.go + ps/optimizer_wrapper.py slot table)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from elasticdl_tpu.train.optimizers import SUPPORTED, create_optimizer


@pytest.mark.parametrize("opt_type", SUPPORTED)
def test_all_supported_optimizers_descend_quadratic(opt_type):
    """Every factory product must reduce f(w) = |w - target|^2."""
    # adadelta's effective step starts near sqrt(eps)-scale regardless
    # of lr (Zeiler 2012), so it needs a big lr on a 100-step budget
    lr = 10.0 if opt_type == "Adadelta" else 0.1
    tx = create_optimizer(opt_type, learning_rate=lr)
    target = jnp.asarray([1.0, -2.0, 3.0])
    params = {"w": jnp.zeros(3)}
    opt_state = tx.init(params)

    @jax.jit
    def step(params, opt_state):
        loss, grads = jax.value_and_grad(
            lambda p: jnp.sum((p["w"] - target) ** 2)
        )(params)
        updates, opt_state = tx.update(grads, opt_state, params)
        return optax_apply(params, updates), opt_state, loss

    import optax

    def optax_apply(params, updates):
        return optax.apply_updates(params, updates)

    steps = 300 if opt_type == "Adadelta" else 100
    first = None
    for _ in range(steps):
        params, opt_state, loss = step(params, opt_state)
        if first is None:
            first = float(loss)
    assert float(loss) < first * 0.3, (opt_type, first, float(loss))


def test_ftrl_matches_torch_reference():
    """Cross-check the FTRL update against an independent numpy
    transcription of the published FTRL-proximal rule."""
    from elasticdl_tpu.train.optimizers import ftrl

    lr, l1, l2, power, init_acc = 0.5, 0.1, 0.2, -0.5, 0.1
    tx = ftrl(lr, learning_rate_power=power,
              initial_accumulator_value=init_acc,
              l1_regularization_strength=l1,
              l2_regularization_strength=l2)
    rng = np.random.RandomState(0)
    w = rng.randn(5).astype(np.float32)
    params = {"w": jnp.asarray(w)}
    state = tx.init(params)

    # independent numpy model of the same rule
    n = np.full(5, init_acc, np.float32)
    z = np.zeros(5, np.float32)
    w_ref = w.copy()
    for step_i in range(5):
        g = rng.randn(5).astype(np.float32)
        updates, state = tx.update({"w": jnp.asarray(g)}, state, params)
        params = {"w": params["w"] + updates["w"]}

        new_n = n + g * g
        sigma = (new_n ** -power - n ** -power) / lr
        z = z + g - sigma * w_ref
        n = new_n
        quad = n ** -power / lr + 2 * l2
        w_ref = np.where(
            np.abs(z) > l1, (np.sign(z) * l1 - z) / quad, 0.0
        ).astype(np.float32)
        np.testing.assert_allclose(
            np.asarray(params["w"]), w_ref, rtol=1e-5, atol=1e-6
        )


def test_ftrl_l1_produces_sparsity():
    from elasticdl_tpu.train.optimizers import ftrl

    tx = create_optimizer("Ftrl", learning_rate=0.1,
                          l1_regularization_strength=2.0)
    assert tx  # factory route works with the kwarg spelling
    tx = ftrl(0.1, l1_regularization_strength=2.0)
    params = {"w": jnp.asarray([0.5, -0.5, 0.0])}
    state = tx.init(params)
    # tiny gradients: |z| never exceeds l1 -> weights snap to exactly 0
    for _ in range(3):
        updates, state = tx.update(
            {"w": jnp.asarray([0.01, -0.01, 0.01])}, state, params
        )
        params = {"w": params["w"] + updates["w"]}
    np.testing.assert_array_equal(np.asarray(params["w"]), 0.0)


def test_ftrl_accepts_schedule():
    import optax

    from elasticdl_tpu.train.optimizers import ftrl

    tx = ftrl(optax.constant_schedule(0.1))
    params = {"w": jnp.zeros(3)}
    state = tx.init(params)
    updates, state = tx.update({"w": jnp.ones(3)}, state, params)
    assert int(state.count) == 1
    assert np.isfinite(np.asarray(updates["w"])).all()


def test_unknown_optimizer_rejected():
    with pytest.raises(ValueError, match="Unsupported optimizer"):
        create_optimizer("Lion")


def test_unknown_kwarg_rejected():
    with pytest.raises(Exception):
        create_optimizer("Adam", learning_rate=0.1, blah=3)


def test_ftrl_params_tree_with_tuples():
    """A params tree containing 3-tuples must not confuse the result
    split (structure-driven tree_transpose, not len-3 sniffing)."""
    from elasticdl_tpu.train.optimizers import ftrl

    tx = ftrl(0.1)
    params = (jnp.ones(2), jnp.ones(3), jnp.ones(4))  # a 3-tuple tree
    state = tx.init(params)
    grads = (jnp.ones(2), jnp.ones(3), jnp.ones(4))
    updates, state = tx.update(grads, state, params)
    assert [u.shape for u in updates] == [(2,), (3,), (4,)]
    import optax

    new_params = optax.apply_updates(params, updates)
    assert [p.shape for p in new_params] == [(2,), (3,), (4,)]
